"""Compiled KV-cache generation engine (models/generation.py).

The two load-bearing guarantees:

1. **Equivalence** — token-by-token cached decode produces the same
   logits as the full-sequence forward (GPT positional embeddings and
   Llama RoPE/GQA both thread ``(cache, position_offset)`` correctly);
2. **Compile discipline** — a 64-token batched ``generate()`` compiles
   exactly ``#prefill_buckets + 1`` XLA programs under ``retrace_guard``
   (the O(1)-compile serving claim the README makes).

Plus the sampling knobs (greedy/temperature/top-k/top-p, EOS done-mask)
and the hapi surface. Tier-1 budget discipline: the models are
module-scoped and most tests share ONE engine geometry (GEO below), so
the compiled prefill/decode programs are paid for once per family.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.framework import compile_cache

# the shared engine geometry: tests that use it reuse each other's
# compiled programs (engines are cached per (max_length, buckets))
GEO = dict(max_length=64, prefill_buckets=(16, 32))


@pytest.fixture(scope="module")
def gpt_model():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


@pytest.fixture(scope="module")
def llama_model():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(7)
    cfg = llama_tiny(use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


def _assert_cached_matches_full(model, cfg, prefill_len=3, total_len=9):
    """Prefill ``prefill_len`` tokens, decode the rest one-by-one, and
    compare every position's logits against the full-sequence forward."""
    from paddle_tpu.models.generation import init_cache

    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, total_len)).astype(np.int32)
    full = np.asarray(model(jnp.asarray(ids)))
    cache = init_cache(model, 2, 16)
    logits, cache = model(jnp.asarray(ids[:, :prefill_len]), cache=cache,
                          position_offset=0)
    np.testing.assert_allclose(np.asarray(logits), full[:, :prefill_len],
                               rtol=2e-4, atol=2e-4)
    for t in range(prefill_len, total_len):
        logits, cache = model(jnp.asarray(ids[:, t:t + 1]), cache=cache,
                              position_offset=jnp.int32(t))
        np.testing.assert_allclose(np.asarray(logits)[:, 0], full[:, t],
                                   rtol=2e-4, atol=2e-4)


def test_gpt_cached_decode_matches_full_forward(gpt_model):
    _assert_cached_matches_full(*gpt_model)


def test_llama_gqa_cached_decode_matches_full_forward(llama_model):
    model, cfg = llama_model
    assert cfg.num_kv_heads < cfg.num_heads  # the GQA path, not MHA
    _assert_cached_matches_full(model, cfg)


def test_gpt_model_position_offset_threaded(gpt_model):
    """Satellite: position_offset reaches GPTEmbeddings from the model
    entry point — offset k must select position table rows k..k+L."""
    model, cfg = gpt_model
    ids = np.random.default_rng(1).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    h0 = np.asarray(model.gpt(jnp.asarray(ids)))
    h0b = np.asarray(model.gpt(jnp.asarray(ids), position_offset=0))
    np.testing.assert_allclose(h0, h0b, rtol=1e-6)
    h3 = np.asarray(model.gpt(jnp.asarray(ids), position_offset=3))
    assert not np.allclose(h0, h3)  # different positions, different codes


def test_generate_compiles_buckets_plus_one():
    """The acceptance criterion: 64 tokens, batch 4, under retrace_guard —
    one prefill compile per bucket USED plus exactly one decode compile,
    never one per token. Fresh model: the counters must start at zero."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    buckets = (16, 32)
    rng = np.random.default_rng(0)
    with compile_cache.retrace_guard(max_compiles=len(buckets) + 1,
                                    label="generate"):
        ids = rng.integers(0, cfg.vocab_size, (4, 12)).astype(np.int32)
        out, stats = model.generate(ids, max_new_tokens=64, max_length=128,
                                    prefill_buckets=buckets,
                                    return_stats=True)
        assert out.shape == (4, 64)
        cc = stats["compile_stats"]
        assert cc["prefill"]["compiles"] == 1  # one bucket used so far
        assert cc["decode"]["compiles"] == 1   # O(1), not O(N)
        assert cc["decode"]["calls"] == 64 - 1
        # a second prompt landing in the OTHER bucket adds exactly one
        # prefill program; decode stays fully cached
        ids2 = rng.integers(0, cfg.vocab_size, (4, 20)).astype(np.int32)
        _, stats2 = model.generate(ids2, max_new_tokens=8, max_length=128,
                                   prefill_buckets=buckets,
                                   return_stats=True)
        cc2 = stats2["compile_stats"]
        assert cc2["prefill"]["compiles"] == len(buckets)
        assert cc2["decode"]["compiles"] == 1
    total = cc2["prefill"]["compiles"] + cc2["decode"]["compiles"]
    assert total == len(buckets) + 1


def test_generate_greedy_matches_argmax_rollout(gpt_model):
    model, cfg = gpt_model
    ids = np.random.default_rng(2).integers(
        0, cfg.vocab_size, (2, 10)).astype(np.int32)
    out = model.generate(ids, max_new_tokens=3, **GEO)
    rolled = ids.copy()
    for _ in range(3):
        logits = np.asarray(model(jnp.asarray(rolled)))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        rolled = np.concatenate([rolled, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, rolled[:, 10:])


def test_generate_eos_early_stop_done_mask(gpt_model):
    model, cfg = gpt_model
    ids = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (2, 10)).astype(np.int32)
    probe = model.generate(ids, max_new_tokens=1, **GEO)
    eos = int(probe[0, 0])  # the token greedy emits first for row 0
    out = model.generate(ids, max_new_tokens=32, eos_token_id=eos, **GEO)
    # row 0 finished on its first token: the loop must stop well short of
    # 32 once EVERY row is done, and finished rows keep emitting eos
    assert out.shape[1] < 32 or (out == eos).all(axis=1).any()
    row0 = out[0]
    assert row0[0] == eos
    assert (row0 == eos).all()  # done-mask holds the row on eos


def test_generate_do_sample_seeded_and_in_vocab(gpt_model):
    model, cfg = gpt_model
    ids = np.random.default_rng(4).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32)
    kw = dict(max_new_tokens=4, do_sample=True, temperature=0.7, top_k=8,
              top_p=0.9, seed=11, **GEO)
    a = model.generate(ids, **kw)
    b = model.generate(ids, **kw)
    np.testing.assert_array_equal(a, b)  # same seed, same stream
    assert (a >= 0).all() and (a < cfg.vocab_size).all()


def test_sample_logits_knobs():
    from paddle_tpu.models.generation import sample_logits

    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 10.0]] * 32, jnp.float32)
    # greedy ignores the key
    g = sample_logits(logits, None, greedy=True)
    assert (np.asarray(g) == 4).all()
    key = jax.random.PRNGKey(0)
    # top_k=2 restricts support to the two largest logits
    s = np.asarray(sample_logits(logits, key, temperature=5.0, top_k=2))
    assert set(s.tolist()) <= {3, 4}
    # tiny top_p keeps only the dominant token
    s = np.asarray(sample_logits(logits, key, temperature=1.0, top_p=0.05))
    assert (s == 4).all()
    # near-zero temperature concentrates on the argmax even unmasked
    s = np.asarray(sample_logits(logits, key, temperature=1e-4))
    assert (s == 4).all()


def test_sampled_randomness_fresh_per_step_and_per_row(gpt_model):
    """PRNG regression (PR-4 satellite): under a fixed seed, sampled
    decode must NOT reuse one key — (a) a row's tokens vary across steps
    (position folded into the key), (b) IDENTICAL prompts in one batch
    sample different continuations (row index folded in too), (c) the
    stream stays deterministic for a given seed."""
    model, cfg = gpt_model
    row = np.random.default_rng(8).integers(
        0, cfg.vocab_size, (8,)).astype(np.int32)
    ids = np.stack([row, row])  # two IDENTICAL prompts
    kw = dict(max_new_tokens=10, do_sample=True, temperature=8.0, seed=13,
              **GEO)
    a = model.generate(ids, **kw)
    b = model.generate(ids, **kw)
    np.testing.assert_array_equal(a, b)          # (c) seeded determinism
    assert len(set(a[0].tolist())) > 3           # (a) steps differ
    assert not np.array_equal(a[0], a[1])        # (b) rows differ


def test_done_check_interval_output_equivalence(gpt_model):
    """Satellite: reading the all-done flag every k-th step (fewer host
    syncs) + host-side overshoot trim must produce EXACTLY the per-step
    checked output, for eos stops landing on and off the interval."""
    model, cfg = gpt_model
    ids = np.random.default_rng(9).integers(
        0, cfg.vocab_size, (2, 10)).astype(np.int32)
    probe = model.generate(ids, max_new_tokens=16, **GEO)
    for stop_step in (2, 5, 7):  # off- and on-interval stops
        eos = int(probe[0, stop_step])
        ref = model.generate(ids, max_new_tokens=16, eos_token_id=eos,
                             done_check_interval=1, **GEO)
        for k in (3, 4, 16):
            out = model.generate(ids, max_new_tokens=16, eos_token_id=eos,
                                 done_check_interval=k, **GEO)
            np.testing.assert_array_equal(out, ref)


def test_prompt_len_exactly_at_largest_bucket(gpt_model):
    """Edge: a prompt filling the largest prefill bucket exactly — no
    padding, last_index at the bucket edge — still matches greedy
    rollout."""
    model, cfg = gpt_model
    L = max(GEO["prefill_buckets"])  # == 32
    ids = np.random.default_rng(10).integers(
        0, cfg.vocab_size, (1, L)).astype(np.int32)
    out, stats = model.generate(ids, max_new_tokens=3, return_stats=True,
                                **GEO)
    assert stats["prefill_bucket"] == L
    logits = np.asarray(model(jnp.asarray(ids)))
    assert out[0, 0] == logits[0, -1].argmax()


def test_eos_from_prefill_means_zero_decode_iterations(gpt_model):
    """Edge: when the PREFILL step itself emits eos for every row, the
    loop must run 0 decode iterations — output is exactly one column."""
    model, cfg = gpt_model
    ids = np.random.default_rng(11).integers(
        0, cfg.vocab_size, (2, 6)).astype(np.int32)
    probe = model.generate(ids, max_new_tokens=1, **GEO)
    eos = int(probe[0, 0])
    if int(probe[1, 0]) != eos:  # make BOTH rows hit eos at prefill
        ids = np.stack([ids[0], ids[0]])
    out = model.generate(ids, max_new_tokens=32, eos_token_id=eos, **GEO)
    assert out.shape == (2, 1)
    assert (out == eos).all()


def test_rows_finish_at_different_steps(gpt_model):
    """Edge: B>1 where rows hit eos at different steps — the finished
    row holds at eos while the other keeps decoding unperturbed, and the
    batch only drains when the LAST row finishes (here: at the token
    budget)."""
    model, cfg = gpt_model
    rng = np.random.default_rng(12)
    kw = dict(max_new_tokens=12, do_sample=True, temperature=4.0, seed=21,
              **GEO)
    # seeded sampled streams are diverse: find a token row 1 emits
    # mid-stream that row 0 never emits — row 1 finishes there, row 0
    # runs to the budget (seeded: draw 1 suffices today; the bound caps
    # tier-1 cost if the model init ever shifts)
    for _ in range(6):
        ids = rng.integers(0, cfg.vocab_size, (2, 10)).astype(np.int32)
        probe = model.generate(ids, **kw)
        row0 = set(probe[0].tolist())
        hit = [(int(t), j) for j, t in enumerate(probe[1].tolist())
               if 2 <= j <= 8 and int(t) not in row0]
        if hit:
            break
    assert hit, "could not construct a staggered-finish pair"
    eos, j = hit[0]
    out = model.generate(ids, eos_token_id=eos, done_check_interval=1,
                         **kw)
    assert out.shape[1] == 12              # row 0 never finishes early
    np.testing.assert_array_equal(out[0], probe[0])  # unperturbed
    assert out[1, j] == eos
    assert (out[1, j:] == eos).all()       # done-mask holds the row


def test_generate_rejects_overlong_request(gpt_model):
    model, _ = gpt_model
    ids = np.zeros((1, 8), np.int32)
    with pytest.raises(ValueError, match="max_length"):
        model.generate(ids, max_new_tokens=100, max_length=32)


def test_hapi_model_generate(gpt_model):
    from paddle_tpu.hapi import Model

    net, cfg = gpt_model
    m = Model(net)
    ids = np.random.default_rng(5).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32)
    out = m.generate(ids, max_new_tokens=2, **GEO)
    assert out.shape == (2, 2)
    assert out.dtype == np.int32
    # non-LM networks fail loudly, not confusingly
    import paddle_tpu.nn as nn

    with pytest.raises(TypeError, match="generate"):
        Model(nn.Linear(4, 4)).generate(ids)


def test_llama_generate_smoke(llama_model):
    model, cfg = llama_model
    ids = np.random.default_rng(6).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32)
    out, stats = model.generate(ids, max_new_tokens=4, return_stats=True,
                                **GEO)
    assert out.shape == (2, 4)
    assert stats["compile_stats"]["decode"]["compiles"] == 1
    assert stats["ttft_s"] > 0 and stats["tokens_per_sec"] > 0


def test_vector_position_offset_matches_scalar_decode(llama_model):
    """Continuous-batching substrate: a [B] position_offset VECTOR with
    per-row (staggered) positions reproduces the full-forward logits —
    RoPE tables, the causal mask frontier, and the GQA cache write all
    index per row. Eager (no jit), so tier-1 pays no extra compiles."""
    from paddle_tpu.models.generation import init_cache

    model, cfg = llama_model
    ids = np.random.default_rng(13).integers(
        0, cfg.vocab_size, (2, 8)).astype(np.int32)
    full = np.asarray(model(jnp.asarray(ids)))
    cache = init_cache(model, 2, 16)
    _, cache = model(jnp.asarray(ids[:, :5]), cache=cache,
                     position_offset=0)
    # row 0 advances to position 6 while row 1 replays position 5: the
    # slots sit at DIFFERENT frontiers, like a live serving batch
    tok = jnp.asarray(np.stack([ids[0, 5:6], ids[1, 5:6]]))
    _, cache = model(tok, cache=cache,
                     position_offset=jnp.asarray([5, 5], jnp.int32))
    tok2 = jnp.asarray(np.stack([ids[0, 6:7], ids[1, 5:6]]))
    logits, cache = model(tok2, cache=cache,
                          position_offset=jnp.asarray([6, 5], jnp.int32))
    out = np.asarray(logits)[:, 0]
    np.testing.assert_allclose(out[0], full[0, 6], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(out[1], full[1, 5], rtol=2e-4, atol=2e-4)


def test_cache_sharding_spec_on_mesh():
    """On a dp×mp mesh the cache shards batch over dp and kv heads over
    mp; indivisible kv heads stay replicated rather than erroring."""
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.models.generation import cache_sharding_spec

    init_mesh(dp=2, mp=2)
    spec = cache_sharding_spec(batch=4, n_kv_heads=4)
    assert spec is not None
    parts = tuple(spec.spec)
    assert "mp" in str(parts) and "dp" in str(parts)
    # 3 kv heads don't divide mp=2: head axis replicated, batch still dp
    spec_odd = cache_sharding_spec(batch=4, n_kv_heads=3)
    assert "mp" not in str(tuple(spec_odd.spec))


@pytest.mark.slow
def test_decode_bench_cli_runs():
    """tools/decode_bench.py end-to-end on CPU: emits tokens/s + TTFT
    JSON and exits 0 (no steady-state recompiles)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "decode_bench.py"),
         "--new-tokens", "16"],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(
        [l for l in proc.stdout.splitlines() if l.startswith('{"')][-1])
    assert rec["metric"] == "gpt_decode_tokens_per_sec"
    assert rec["value"] > 0
    assert rec["extra"]["ttft_ms"] > 0
    assert rec["extra"]["decode_compiles"] == 1
    assert rec["extra"]["steady_state_recompiles"] == 0
