"""Pipeline parallelism tests (VERDICT r1 item 3).

Covers: interleaved virtual stages, heterogeneous stages, tied-embedding
GPT loss parity vs single device, and the bounded-activation-memory
property of the remat'd ring schedule.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist
from paddle_tpu.distributed.mesh import init_mesh, mesh_scope, set_mesh
from paddle_tpu.distributed.parallel.pipeline import (
    HeterogeneousPipeline, LayerDesc, PipelineLayer, PipelineStagedModule)
from paddle_tpu.nn import functional_call, param_state


class Block(nn.Layer):
    def __init__(self, width=16):
        super().__init__()
        self.fc = nn.Linear(width, width)

    def forward(self, x):
        return x + 0.1 * F.tanh(self.fc(x))


@pytest.fixture(autouse=True)
def _clean_mesh():
    set_mesh(None)
    yield
    set_mesh(None)


@pytest.mark.slow
def test_virtual_stages_parity():
    """pp=2 x virtual=2 interleaved == sequential, incl. grads.

    Slow-tier: the remat'd grad parity compiles ~22s on the CI box
    (tier-1 slowest-tests report); test_virtual_stages_many_microbatches
    keeps the interleaved path covered inside the budget."""
    pt.seed(5)
    m = init_mesh(pp=2, dp=4)
    set_mesh(None)
    with mesh_scope(m):
        pipe = PipelineStagedModule(Block(), num_layers=8, num_micro=4,
                                    remat=True, num_virtual_stages=2,
                                    block_factory=lambda: Block())
    x = pt.randn([8, 16])

    set_mesh(None)
    ref = pipe(x)  # sequential path (global order)
    with mesh_scope(m):
        out = pipe(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    # grads parity between pipelined and sequential execution
    params = param_state(pipe)

    def loss_pp(p):
        with mesh_scope(m):
            o, _ = functional_call(pipe, p, {}, x)
        return jnp.sum(o ** 2)

    def loss_seq(p):
        set_mesh(None)
        o, _ = functional_call(pipe, p, {}, x)
        return jnp.sum(o ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in g_pp:
        np.testing.assert_allclose(np.asarray(g_pp[k]), np.asarray(g_seq[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)


def test_virtual_stages_many_microbatches():
    """num_micro > pp exercises multiple depth-first bursts."""
    pt.seed(6)
    m = init_mesh(pp=2, dp=4)
    set_mesh(None)
    with mesh_scope(m):
        pipe = PipelineStagedModule(Block(), num_layers=4, num_micro=6,
                                    remat=False, num_virtual_stages=2,
                                    block_factory=lambda: Block())
    x = pt.randn([12, 16])
    set_mesh(None)
    ref = pipe(x)
    with mesh_scope(m):
        out = pipe(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


@pytest.mark.slow   # ~15s multi-stage compile (tier-1 report)
def test_heterogeneous_pipeline_parity():
    """Different layer types per stage (reference PipelineLayer hetero)."""

    class Wide(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(16, 32)
            self.b = nn.Linear(32, 16)

        def forward(self, x):
            return x + 0.1 * F.relu(self.b(F.relu(self.a(x))))

    pt.seed(7)
    stages = [Block(), Wide(), Block(), Wide()]
    pipe = HeterogeneousPipeline(stages, num_micro=4, remat=True)
    x = pt.randn([8, 16])
    ref = pipe(x)  # no mesh -> sequential

    m = init_mesh(pp=4, dp=2)
    with mesh_scope(m):
        out = pipe(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    # grads flow to every stage's params
    with mesh_scope(m):
        params = param_state(pipe)

        def loss(p):
            o, _ = functional_call(pipe, p, {}, x)
            return jnp.sum(o ** 2)

        g = jax.grad(loss)(params)
    for k, v in g.items():
        assert float(jnp.abs(v).sum()) > 0, k


# ---------------------------------------------------- tied-embedding GPT
class TiedGPT(nn.Layer):
    """Tiny GPT arrangement: embed -> pipelined blocks -> tied-logits head.

    The tied weight lives outside the stacked stage params (PipelineLayer
    pre/post), matching the reference's SharedLayerDesc first/last-stage
    tying without a grad-sync group."""

    def __init__(self, vocab=64, width=16, layers=4, num_micro=2):
        super().__init__()
        self.embed = nn.Embedding(vocab, width)
        self.blocks = PipelineStagedModule(Block(width), layers,
                                           num_micro=num_micro, remat=True,
                                           block_factory=lambda: Block(width))
        self.ln = nn.LayerNorm(width)

    def forward(self, ids):
        h = self.embed(ids)
        h = self.blocks(h)
        h = self.ln(h)
        # tied head: logits with the embedding matrix
        return h @ jnp.swapaxes(self.embed.weight, 0, 1)


def test_tied_embedding_gpt_pipeline_loss_parity():
    """pp=4 training-loss trajectory == single-device (TestDistBase pattern),
    with the embedding weight shared by first (embed) and last (head) stage."""
    from paddle_tpu.optimizer import SGD

    def loss_fn(out, batch):
        ids, labels = batch
        return F.cross_entropy(out.reshape(-1, out.shape[-1]),
                               labels.reshape(-1))

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, (8, 12)).astype(np.int32)

    pt.seed(9)
    set_mesh(None)
    model_ref = TiedGPT()
    model_pp = TiedGPT()
    model_pp.set_state_dict(model_ref.state_dict())

    from paddle_tpu.framework.jit import TrainStep

    ref_step = TrainStep(model_ref, SGD(learning_rate=0.1), loss_fn=loss_fn)
    ref_losses = [float(ref_step((ids, ids))) for _ in range(4)]

    m = init_mesh(pp=4, dp=2)
    with mesh_scope(m):
        pp_step = dist.DistributedTrainStep(model_pp, SGD(learning_rate=0.1),
                                            loss_fn=loss_fn, mesh=m,
                                            batch_axes=("dp",))
        pp_losses = [float(pp_step((ids, ids))) for _ in range(4)]

    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4, atol=1e-5)

    # the tied weight received gradient (it moved)
    before = np.asarray(model_ref.embed.weight)
    after = np.asarray(pp_step.params["embed.weight"])
    assert not np.allclose(before, after)


def test_pipeline_memory_bounded():
    """In-flight internal activations don't scale with num_micro: compiled
    temp memory at M=8 stays well under 2x the M=2 program (the stage body
    is remat'd, so only per-microbatch boundary tensors scale)."""
    pt.seed(11)
    m = init_mesh(pp=4, dp=2)
    set_mesh(None)
    # wide blocks so internal activations dominate boundaries
    mems = {}
    for M in (2, 8):
        with mesh_scope(m):
            pipe = PipelineStagedModule(Block(128), num_layers=4, num_micro=M,
                                        remat=True,
                                        block_factory=lambda: Block(128))
            x = pt.randn([8, 128])
            params = param_state(pipe)

            def loss(p):
                o, _ = functional_call(pipe, p, {}, x)
                return jnp.sum(o ** 2)

            compiled = jax.jit(jax.grad(loss)).lower(params).compile()
            analysis = compiled.memory_analysis()
            if analysis is None:
                pytest.skip("backend provides no memory analysis")
            mems[M] = analysis.temp_size_in_bytes
        set_mesh(None)
    assert mems[8] < 2 * mems[2], mems


# ------------------------------------------- round-3 pipeline upgrades
class BNBlock(nn.Layer):
    """A pipelined block WITH buffers (BatchNorm running stats)."""

    def __init__(self, width=16):
        super().__init__()
        self.fc = nn.Linear(width, width)
        self.bn = nn.BatchNorm1D(width)

    def forward(self, x):
        return x + 0.1 * F.tanh(self.bn(self.fc(x)))


def test_pipeline_batchnorm_blocks_parity():
    """BN stages pipeline now: outputs AND updated running stats match the
    sequential path bit-for-bit (num_micro=1 so batch stats agree)."""
    pt.seed(7)
    m = init_mesh(pp=4)
    set_mesh(None)
    pipe = PipelineStagedModule(BNBlock(), num_layers=4, num_micro=1,
                                remat=True, block_factory=lambda: BNBlock())
    x = pt.randn([8, 16])

    from paddle_tpu.nn import buffer_state

    bufs0 = {k: np.asarray(v).copy() for k, v in buffer_state(pipe).items()}
    ref = np.asarray(pipe(x))
    bufs_seq = {k: np.asarray(v).copy() for k, v in buffer_state(pipe).items()}
    # stats moved in the sequential run
    assert any(not np.allclose(bufs0[k], bufs_seq[k]) for k in bufs0)

    # reset buffers, run pipelined, compare output + stats
    for k, v in bufs0.items():
        pipe._set_by_path(k, jnp.asarray(v))
    with mesh_scope(m):
        out = np.asarray(pipe(x))
    bufs_pp = {k: np.asarray(v) for k, v in buffer_state(pipe).items()}
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    for k in bufs_seq:
        np.testing.assert_allclose(bufs_pp[k], bufs_seq[k], rtol=1e-4,
                                   atol=1e-6, err_msg=k)


def test_pipeline_batchnorm_multi_micro_updates_once_per_microbatch():
    """With num_micro>1 each microbatch's BN update lands (momentum applied
    num_micro times), and bubble ticks never pollute the stats."""
    pt.seed(3)
    m = init_mesh(pp=2)
    set_mesh(None)
    pipe = PipelineStagedModule(BNBlock(), num_layers=2, num_micro=4,
                                remat=False, block_factory=lambda: BNBlock())
    x = pt.randn([8, 16])
    from paddle_tpu.nn import buffer_state, functional_call as fc, param_state

    params = param_state(pipe)
    bufs = buffer_state(pipe)
    # reference: run the 4 microbatches sequentially through the pp=1 path
    ref_bufs = dict(bufs)
    for i in range(4):
        _, ref_bufs = fc(pipe, params, ref_bufs, x[i * 2:(i + 1) * 2])
    with mesh_scope(m):
        _, pp_bufs = fc(pipe, params, bufs, x)
    for k in ref_bufs:
        np.testing.assert_allclose(np.asarray(pp_bufs[k]),
                                   np.asarray(ref_bufs[k]), rtol=1e-4,
                                   atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_heterogeneous_pipeline_shards_params_over_pp():
    """Per-stage params live in ONE [pp, maxlen] stack sharded over pp —
    a rank holds its own stage (+padding), not pp replicas of everything.

    Slow-tier (~18s on the CI box); test_heterogeneous_pipeline_parity
    keeps the mixed-stage path in the tier-1 budget."""
    pt.seed(11)
    m = init_mesh(pp=4)
    set_mesh(None)
    stages = [nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16)),
              nn.Sequential(nn.Linear(16, 16)),
              nn.Sequential(nn.Linear(16, 48), nn.ReLU(), nn.Linear(48, 16)),
              nn.Sequential(nn.Linear(16, 16))]
    pipe = HeterogeneousPipeline(stages, num_micro=2, remat=False)
    params = param_state(pipe)
    assert list(params) == ["stages_flat"]
    lens = pipe._stage_lens
    assert params["stages_flat"].shape == (4, max(lens))
    assert dict(pipe.named_param_shardings())["stages_flat"] == ("pp", None)

    x = pt.randn([4, 16])
    ref = np.asarray(pipe(x))  # sequential path
    with mesh_scope(m):
        out = np.asarray(pipe(x))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    # checkpoint interchange: unraveled per-stage pytrees match originals
    sds = pipe.stage_state_dicts()
    np.testing.assert_allclose(np.asarray(sds[0]["0.weight"]),
                               np.asarray(param_state(stages[0])["0.weight"]))

    # grads flow into the single stack
    def loss(p):
        with mesh_scope(m):
            o, _ = functional_call(pipe, p, {}, x)
        return jnp.mean(o * o)

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["stages_flat"]).sum()) > 0


def test_pipeline_layer_shards_pre_post_over_pp():
    """PipelineLayer annotates big embedding/head matrices to shard over
    the pp axis instead of replicating them on every pp rank."""
    pt.seed(2)
    m = init_mesh(pp=4)
    with mesh_scope(m):
        pipe = PipelineLayer([
            LayerDesc(nn.Embedding, 1024, 64),
            LayerDesc(Block, 64), LayerDesc(Block, 64),
            LayerDesc(Block, 64), LayerDesc(Block, 64),
            LayerDesc(nn.Linear, 64, 1024),
        ], num_micro=2)
    shardings = dict(pipe.named_param_shardings())
    emb = [k for k in shardings if k.startswith("pre") and "weight" in k]
    head = [k for k in shardings if k.startswith("post") and "weight" in k]
    assert emb and shardings[emb[0]][0] == "pp"
    assert head and shardings[head[0]][0] == "pp"
    # and it still computes correctly under the mesh
    x = np.random.default_rng(0).integers(0, 1024, (4, 8))
    with mesh_scope(m):
        out = pipe(jnp.asarray(x))
    assert out.shape == (4, 8, 1024)
