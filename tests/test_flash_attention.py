"""Flash-attention Pallas kernel tests (interpret mode on the CPU mesh).

Covers the full Pallas forward+backward (VERDICT r1 weak #3): causal, bias
(incl. dbias), Lq != Lk, block-size tiling. Dropout uses the TPU PRNG which
has no CPU lowering — exercised by tools/flash_check.py on the real chip.
"""
import functools
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import flash_attention as fa


@pytest.fixture()
def interpret_pallas():
    orig = fa.pl.pallas_call

    def interp(*a, **k):
        k["interpret"] = True
        return orig(*a, **k)

    with mock.patch.object(fa.pl, "pallas_call", interp):
        yield


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).standard_normal(shape),
                       jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(interpret_pallas, causal):
    B, H, L, D = 2, 2, 256, 64
    q, k, v = _rand((B, H, L, D), 0), _rand((B, H, L, D), 1), _rand((B, H, L, D), 2)
    o = fa.flash_attention_bhld(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = fa.reference_attention_bhld(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_backward_matches_reference(interpret_pallas):
    B, H, L, D = 1, 2, 256, 64
    q, k, v = _rand((B, H, L, D), 0), _rand((B, H, L, D), 1), _rand((B, H, L, D), 2)

    def loss_flash(q, k, v):
        return jnp.sum(fa.flash_attention_bhld(
            q, k, v, causal=True, block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(fa.reference_attention_bhld(q, k, v, causal=True) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_flash_bias_and_dbias(interpret_pallas):
    B, H, L, D = 2, 2, 256, 64
    q, k, v = _rand((B, H, L, D), 0), _rand((B, H, L, D), 1), _rand((B, H, L, D), 2)
    bias = 0.5 * _rand((1, 1, L, L), 3)  # broadcast over B and H

    o = fa.flash_attention_bhld(q, k, v, causal=True, bias=bias,
                                block_q=128, block_k=128)
    ref = fa.reference_attention_bhld(q, k, v, causal=True, bias=bias)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def loss_flash(q, k, v, b):
        return jnp.sum(fa.flash_attention_bhld(
            q, k, v, causal=True, bias=b, block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v, b):
        return jnp.sum(fa.reference_attention_bhld(q, k, v, causal=True, bias=b) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g, gr):
        # atol 1e-4: flash vs reference disagree by ~1 accumulation ulp on
        # exactly-zero grads under some XLA versions
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=1e-4)


def test_flash_cross_attention_shapes(interpret_pallas):
    """Lq != Lk with non-square blocks."""
    B, H, D = 1, 2, 64
    q, k, v = _rand((B, H, 256, D), 0), _rand((B, H, 512, D), 1), _rand((B, H, 512, D), 2)
    o = fa.flash_attention_bhld(q, k, v, causal=False, block_q=128, block_k=256)
    ref = fa.reference_attention_bhld(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_blhd_layout(interpret_pallas):
    B, L, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, L, H, D), 0), _rand((B, L, H, D), 1), _rand((B, L, H, D), 2)
    o = fa.flash_attention_blhd(q, k, v, causal=True, block_q=128, block_k=128)
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    ref = jnp.swapaxes(fa.reference_attention_bhld(qt, kt, vt, causal=True), 1, 2)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_should_use_flash_gate():
    # CPU backend -> always False
    q = jnp.zeros((2, 1024, 8, 64))
    assert not fa.should_use_flash(q, q, None, 0.0)


def test_gate_logic_shapes(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    mk = lambda L, D=64: jnp.zeros((2, L, 8, D))
    # short sequences stay on the (faster) XLA fused path
    assert not fa.should_use_flash(mk(1024), mk(1024), None, 0.0)
    assert fa.should_use_flash(mk(2048), mk(2048), None, 0.0)
    assert fa.should_use_flash(mk(2048), mk(2048), None, 0.5)  # dropout ok
    assert not fa.should_use_flash(mk(2000), mk(2000), None, 0.0)  # not /128
    assert not fa.should_use_flash(mk(2048, 32), mk(2048, 32), None, 0.0)  # D
    bias = jnp.zeros((1, 1, 2048, 2048))
    assert fa.should_use_flash(mk(2048), mk(2048), bias, 0.0)  # bias ok
    bad = jnp.zeros((3, 1, 2048, 2048))
    assert not fa.should_use_flash(mk(2048), mk(2048), bad, 0.0)  # B mismatch
