"""Crash-safe checkpoint tests: corruption detection + fallback, orphaned
staging sweep, and workers killed mid-save (both a real SIGKILL landed
while shards are being written, and an injected in-process crash).

Tier-1-safe: kills are triggered by observing the staging directory appear
(no sleep-and-hope), every wait is deadline-bounded, and fault plans are
seeded/counted.
"""
import glob
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.distributed.checkpoint import (
    AutoCheckpoint, CheckpointCorruptError, latest_checkpoint, load_state,
    save_state, validate_checkpoint)
from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(step):
    return {"w": np.full((8, 4), float(step), np.float32),
            "b": np.arange(6, dtype=np.float32) + step, "step": step}


def _two_checkpoints(root):
    for step in (1, 2):
        save_state(_state(step), os.path.join(root, f"step_{step}"))
    assert latest_checkpoint(root).endswith("step_2")


def _shard_files(d):
    return sorted(f for f in os.listdir(d) if f.endswith(".npy"))


# ------------------------------------------------------ corruption fallback
def test_truncated_shard_detected_and_skipped(tmp_path):
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    victim = os.path.join(d2, _shard_files(d2)[0])
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    with pytest.raises(CheckpointCorruptError, match="bytes"):
        load_state(d2)
    assert "bytes" in validate_checkpoint(d2)
    # restore falls back to the previous complete checkpoint
    assert latest_checkpoint(root).endswith("step_1")
    out = load_state(latest_checkpoint(root))
    np.testing.assert_array_equal(out["w"], np.full((8, 4), 1.0, np.float32))


def test_flipped_bytes_detected_and_skipped(tmp_path):
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    victim = os.path.join(d2, _shard_files(d2)[-1])
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size - 3)  # flip payload bytes, keep the length intact
        chunk = f.read(3)
        f.seek(size - 3)
        f.write(bytes(b ^ 0xFF for b in chunk))
    assert os.path.getsize(victim) == size
    with pytest.raises(CheckpointCorruptError, match="crc32"):
        load_state(d2)
    assert latest_checkpoint(root).endswith("step_1")
    # verification can be bypassed explicitly (forensics)
    load_state(d2, verify=False)


def test_proactive_verify_rejects_corrupt_shard_up_front(tmp_path):
    # supervisor restores use verify="proactive": EVERY recorded shard is
    # crc-checked before a byte of state is constructed — not just the
    # slices this topology's devices happen to read lazily
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    victim = os.path.join(d2, _shard_files(d2)[0])
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size - 2)
        chunk = f.read(2)
        f.seek(size - 2)
        f.write(bytes(b ^ 0x01 for b in chunk))   # one-bit rot, same length
    with pytest.raises(CheckpointCorruptError, match="crc32"):
        load_state(d2, verify="proactive")
    # the message names the poisoned shard file for the operator
    try:
        load_state(d2, verify="proactive")
    except CheckpointCorruptError as e:
        assert os.path.basename(victim) in str(e)
    # a clean sibling loads identically under both verify modes
    d1 = os.path.join(root, "step_1")
    lazy, proactive = load_state(d1), load_state(d1, verify="proactive")
    np.testing.assert_array_equal(lazy["w"], proactive["w"])
    np.testing.assert_array_equal(lazy["b"], proactive["b"])


def test_supervisor_restore_falls_back_past_corrupt_shard(tmp_path):
    # the end-to-end regression: a FakeStep supervisor restore must skip
    # the bit-rotted newest checkpoint and land on the older valid one
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)

    class Step:
        _count = 0

        def state_dict(self):
            return {"w": np.full(4, float(self._count), np.float32),
                    "count": np.asarray(self._count)}

        def set_state_dict(self, state):
            self._count = int(np.asarray(state["count"]))

    root = str(tmp_path / "ckpt")
    step = Step()
    sup = TrainingSupervisor(step, RecoveryPolicy(
        checkpoint_dir=root, save_interval_steps=1, keep_max=4,
        async_save=False, preemption=False))
    sup.save_now()
    step._count = 1
    sup.save_now()
    d1 = os.path.join(root, "step_1")
    victim = os.path.join(d1, _shard_files(d1)[0])
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size - 1)
        last = f.read(1)
        f.seek(size - 1)
        f.write(bytes([last[0] ^ 0x01]))
    step._count = 99
    sup.restore()
    assert step._count == 0                      # fell back to step_0


def test_missing_metadata_detected_and_skipped(tmp_path):
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    os.remove(os.path.join(d2, "metadata.json"))
    with pytest.raises(CheckpointCorruptError, match="metadata.json"):
        load_state(d2)
    assert latest_checkpoint(root).endswith("step_1")


def test_missing_shard_detected_and_skipped(tmp_path):
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    os.remove(os.path.join(d2, _shard_files(d2)[0]))
    with pytest.raises(CheckpointCorruptError, match="missing"):
        load_state(d2)
    assert latest_checkpoint(root).endswith("step_1")


def test_missing_peer_metadata_detected_and_skipped(tmp_path):
    """A multi-process save whose peer died before committing its
    metadata.N.json must not validate or load (its shards are silently
    absent otherwise)."""
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    mpath = os.path.join(d2, "metadata.json")
    with open(mpath) as f:
        meta = json.load(f)
    meta["process_count"] = 2  # simulate: peer 1 never wrote metadata.1.json
    with open(mpath, "w") as f:
        json.dump(meta, f)
    assert "metadata.1.json" in validate_checkpoint(d2)
    with pytest.raises(CheckpointCorruptError, match="killed before"):
        load_state(d2)
    assert latest_checkpoint(root).endswith("step_1")


def test_stale_peer_metadata_from_larger_world_ignored(tmp_path):
    """Re-saving into a path that once held a larger-world save must not
    merge the stale metadata.N.json (N >= process_count): the restored
    state would silently mix shards from a different trajectory."""
    root = str(tmp_path)
    _two_checkpoints(root)
    d2 = os.path.join(root, "step_2")
    # leftover from a hypothetical earlier 2-process save at this path
    stale = {"format": "paddle_tpu.ckpt.v1", "process_count": 2,
             "leaves": {"ghost": {"kind": "array", "shape": [2],
                                  "dtype": "float32",
                                  "shards": [{"file": "ghost.npy",
                                              "start": [0], "shape": [2]}]}}}
    with open(os.path.join(d2, "metadata.1.json"), "w") as f:
        json.dump(stale, f)
    # current metadata records process_count=1 -> the stale file is ignored
    assert validate_checkpoint(d2) is None
    out = load_state(d2)
    assert "ghost" not in out and out["step"] == 2
    assert latest_checkpoint(root).endswith("step_2")


def test_all_checkpoints_corrupt_returns_none(tmp_path):
    root = str(tmp_path)
    save_state(_state(1), os.path.join(root, "step_1"))
    os.remove(os.path.join(root, "step_1", "metadata.json"))
    assert latest_checkpoint(root) is None


def test_autocheckpoint_restore_skips_torn_snapshot(tmp_path):
    root = str(tmp_path)
    ac = AutoCheckpoint(root, save_interval_steps=1, async_save=False)
    ac.save(1, _state(1))
    ac.save(2, _state(2))
    d2 = os.path.join(root, "step_2")
    victim = os.path.join(d2, _shard_files(d2)[0])
    with open(victim, "r+b") as f:
        f.truncate(1)
    step, restored = AutoCheckpoint(root).restore()
    assert step == 1 and restored["step"] == 1


def test_orphaned_staging_dirs_swept_on_startup(tmp_path):
    root = str(tmp_path)
    save_state(_state(3), os.path.join(root, "step_3"))
    for orphan in ("step_5.tmp-pt1234", "step_4.tmp"):
        os.makedirs(os.path.join(root, orphan))
        with open(os.path.join(root, orphan, "junk.npy"), "wb") as f:
            f.write(b"x")
    AutoCheckpoint(root)
    assert sorted(os.listdir(root)) == ["step_3"]
    assert latest_checkpoint(root).endswith("step_3")


def test_stale_staging_dirs_reaped_by_ttl_mid_run(tmp_path):
    """Regression: a SIGKILLed sibling's staging dir used to leak until the
    next process restart (the sweep only ran at __init__). The periodic
    sweep (_gc, after every save) reaps staging older than
    staging_ttl_seconds while leaving a FRESH dir (a live peer's in-flight
    save) alone."""
    root = str(tmp_path)
    ac = AutoCheckpoint(root, save_interval_steps=1, async_save=False,
                        staging_ttl_seconds=600.0)
    stale = os.path.join(root, "step_9.tmp-pt4242")   # killed sibling
    fresh = os.path.join(root, "step_8.tmp-pt4343")   # live peer, mid-save
    for d in (stale, fresh):
        os.makedirs(d)
        with open(os.path.join(d, "junk.npy"), "wb") as f:
            f.write(b"x")
    hours_ago = time.time() - 7200
    os.utime(stale, (hours_ago, hours_ago))
    ac.save(1, _state(1))                             # triggers _gc + sweep
    names = sorted(os.listdir(root))
    assert os.path.basename(stale) not in names       # reaped (past TTL)
    assert os.path.basename(fresh) in names           # spared (fresh mtime)
    assert "step_1" in names
    # a restart still reaps everything unconditionally (ttl=0 startup sweep)
    AutoCheckpoint(root)
    assert os.path.basename(fresh) not in os.listdir(root)


def test_overwrite_trash_restored_when_target_missing(tmp_path):
    """A crash between save_state's two overwrite renames leaves the OLD
    checkpoint as step_N.old-pt<pid>; the startup sweep must restore it,
    not delete the only copy."""
    root = str(tmp_path)
    save_state(_state(2), os.path.join(root, "step_2"))
    os.rename(os.path.join(root, "step_2"),
              os.path.join(root, "step_2.old-pt999"))  # mid-overwrite crash
    AutoCheckpoint(root)
    assert sorted(os.listdir(root)) == ["step_2"]
    assert validate_checkpoint(os.path.join(root, "step_2")) is None
    assert load_state(os.path.join(root, "step_2"))["step"] == 2


def test_gc_never_evicts_last_valid_checkpoint(tmp_path):
    """Invalid step dirs must not count toward keep_max: a newer torn save
    cannot push the only loadable fallback out of retention."""
    root = str(tmp_path)
    ac = AutoCheckpoint(root, save_interval_steps=1, keep_max=2,
                        async_save=False)
    ac.save(1, _state(1))
    ac.save(2, _state(2))
    os.remove(os.path.join(root, "step_2", "metadata.json"))  # torn
    ac.save(3, _state(3))  # gc: keeps valid {3, 1}, spares torn 2
    assert os.path.isdir(os.path.join(root, "step_1"))
    step, restored = AutoCheckpoint(root).restore()
    assert step == 3
    ac.save(4, _state(4))  # now valid {4, 3} kept; step_1 may be gc'd
    assert latest_checkpoint(root).endswith("step_4")


# --------------------------------------------------------- kill mid-save
KILL_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np
    from paddle_tpu.distributed.checkpoint import (
        AsyncSaver, latest_checkpoint, load_state)

    root = os.environ["CKPT_ROOT"]

    def state(step):
        return {"w": np.full((64, 32), float(step), np.float32),
                "b%d" % 0: np.ones(4, np.float32) * step,
                "b1": np.ones(4, np.float32) * step,
                "b2": np.ones(4, np.float32) * step,
                "step": step}

    prev = latest_checkpoint(root)
    resumed = load_state(prev)["step"] if prev else 0
    print(f"RESUMED {resumed}", flush=True)

    saver = AsyncSaver()
    if resumed < 1:
        saver.save(state(1), os.path.join(root, "step_1"))
        saver.wait()
        print("SAVED 1", flush=True)
    # step_2: under the parent's fault plan each shard write stalls, so a
    # SIGKILL arrives while the staging dir is mid-write; without the plan
    # (the restarted run) it completes instantly
    saver.save(state(2), os.path.join(root, "step_2"))
    saver.wait()
    print("SAVED 2", flush=True)
""")


def _run_child(tmp_path, root, extra_env=None, wait=True):
    script = tmp_path / "worker.py"
    script.write_text(KILL_SCRIPT)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               CKPT_ROOT=root, **(extra_env or {}))
    proc = subprocess.Popen([sys.executable, "-u", str(script)], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    if wait:
        out, _ = proc.communicate(timeout=120)
        return proc, out
    return proc, None


def _poll_until(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"{what} not reached within {timeout}s")


def test_sigkill_mid_async_save_falls_back_and_resumes(tmp_path):
    """The acceptance scenario: a worker SIGKILLed mid-``AsyncSaver.save``
    leaves ``latest_checkpoint`` on the previous complete checkpoint, and a
    restarted run resumes from it and completes."""
    root = str(tmp_path / "ckpt")
    os.makedirs(root)
    # every step_2 shard write stalls 0.5s (step_1 writes the first 4
    # matching calls) -> the save is provably in flight when the staging
    # dir appears and the SIGKILL lands
    plan = FaultPlan([{"site": "ckpt.shard_write", "kind": "delay",
                       "delay": 0.5, "times": None, "after": 4}], seed=0)
    with plan:  # exports PT_FAULT_PLAN -> the child inherits it
        proc, _ = _run_child(tmp_path, root, wait=False)
        try:
            _poll_until(lambda: glob.glob(os.path.join(root, "step_2.tmp-pt*")),
                        timeout=60.0, what="step_2 staging dir")
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
    assert proc.returncode == -signal.SIGKILL
    # the torn save is invisible: only the staging orphan exists
    assert not os.path.exists(os.path.join(root, "step_2"))
    best = latest_checkpoint(root)
    assert best is not None and best.endswith("step_1")
    assert validate_checkpoint(best) is None
    np.testing.assert_array_equal(
        load_state(best)["w"], np.full((64, 32), 1.0, np.float32))

    # restarted run (no fault plan): resumes from step_1, finishes step_2
    proc2, out2 = _run_child(tmp_path, root)
    assert proc2.returncode == 0, out2[-3000:]
    assert "RESUMED 1" in out2 and "SAVED 2" in out2
    assert latest_checkpoint(root).endswith("step_2")
    assert load_state(latest_checkpoint(root))["step"] == 2
    # the restart's AutoCheckpoint-equivalent sweep isn't in play here, but
    # the orphan must still never shadow a published checkpoint
    assert validate_checkpoint(os.path.join(root, "step_2")) is None


def test_injected_crash_mid_save_falls_back(tmp_path):
    """One-shot crash fault inside the shard-write loop: the process dies
    with CRASH_EXIT mid-save and the checkpoint root stays on the previous
    complete snapshot — deterministic, no signals involved."""
    root = str(tmp_path / "ckpt")
    os.makedirs(root)
    plan = FaultPlan([{"site": "ckpt.shard_write", "kind": "crash",
                       "after": 7}], seed=1)  # step_1 writes 5 shards; the
    # crash lands on the 3rd shard of step_2's save
    with plan:
        proc, out = _run_child(tmp_path, root)
    assert proc.returncode == CRASH_EXIT, out[-2000:]
    assert "SAVED 1" in out and "SAVED 2" not in out
    assert not os.path.exists(os.path.join(root, "step_2"))
    assert latest_checkpoint(root).endswith("step_1")

    # restart without the plan: resumes and completes
    proc2, out2 = _run_child(tmp_path, root)
    assert proc2.returncode == 0, out2[-3000:]
    assert "RESUMED 1" in out2 and "SAVED 2" in out2
    assert latest_checkpoint(root).endswith("step_2")
