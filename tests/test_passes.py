"""Composable pass registry tests (reference distributed/passes pass_base
+ concrete passes; VERDICT r2 'not a composable pass registry')."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.passes import (PassContext, PassManager,
                                           PassBase, list_passes, new_pass,
                                           register_pass)
from paddle_tpu.optimizer import Momentum, SGD


def make_ctx():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    return PassContext(model, Momentum(learning_rate=0.1, momentum=0.9),
                       loss_fn=lambda out, b: F.cross_entropy(out, b[1]))


def test_registry_basics():
    names = list_passes()
    for expected in ("amp", "recompute", "gradient_merge", "fp16_allreduce",
                     "dgc", "lars"):
        assert expected in names
    with pytest.raises(ValueError, match="unknown pass"):
        new_pass("does_not_exist")


def test_pass_composition_builds_working_step():
    """amp O2 + gradient_merge + fp16_allreduce + dgc compose into one
    functioning TrainStep that trains."""
    ctx = make_ctx()
    mgr = PassManager([
        new_pass("amp", {"level": "O2", "dtype": "bfloat16"}),
        new_pass("gradient_merge", {"k_steps": 2, "avg": True}),
        "fp16_allreduce",
        new_pass("dgc", {"rampup_begin_step": 100}),
    ])
    ctx = mgr.apply(ctx)
    assert ctx.applied == ["amp", "gradient_merge", "fp16_allreduce", "dgc"]
    from paddle_tpu.optimizer import DGCMomentum

    assert isinstance(ctx.optimizer, DGCMomentum)
    assert ctx.step_kwargs["grad_accum_steps"] == 2
    step = ctx.build_step(distributed=False)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8)).astype(np.float32)
    y = rng.integers(0, 4, 8)
    losses = [float(np.asarray(step((x, y)))) for _ in range(30)]
    assert losses[-1] < losses[0]


def test_pass_conflicts_refused():
    ctx = make_ctx()
    with pytest.raises(ValueError, match="incompatible"):
        PassManager(["dgc", "lars"]).apply(ctx)


def test_recompute_pass_flips_model_knobs():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=32, use_recompute=False)
    ctx = PassContext(GPTForCausalLM(cfg), SGD(learning_rate=0.1))
    new_pass("recompute").apply(ctx)
    assert ctx.model.cfg.use_recompute is True
    # a model with no recompute surface is rejected loudly
    ctx2 = PassContext(nn.Linear(4, 4), SGD(learning_rate=0.1))
    with pytest.raises(ValueError, match="recompute"):
        new_pass("recompute").apply(ctx2)


def test_custom_pass_registration():
    @register_pass("double_lr_test_pass")
    class DoubleLr(PassBase):
        def _apply_single_impl(self, ctx):
            ctx.optimizer.set_lr(ctx.optimizer.get_lr() * 2)

    ctx = make_ctx()
    PassManager(["double_lr_test_pass"]).apply(ctx)
    assert abs(ctx.optimizer.get_lr() - 0.2) < 1e-9


def test_amp_o1_actually_casts():
    """O1 is real, not decorative: white-listed ops (linear/conv) compute
    in the autocast dtype inside the scope, f32 outside."""
    import jax.numpy as jnp

    from paddle_tpu import amp

    pt.seed(0)
    fc = nn.Linear(8, 8)
    x = np.ones((2, 8), np.float32)
    assert fc(jnp.asarray(x)).dtype == jnp.float32
    with amp.auto_cast(True, level="O1", dtype="bfloat16"):
        assert fc(jnp.asarray(x)).dtype == jnp.bfloat16
    conv = nn.Conv2D(3, 4, 3)
    xi = np.ones((1, 3, 8, 8), np.float32)
    with amp.auto_cast(True, level="O1", dtype="bfloat16"):
        assert conv(jnp.asarray(xi)).dtype == jnp.bfloat16
    assert conv(jnp.asarray(xi)).dtype == jnp.float32

    # the O1 pass wraps the model so the TRACED step computes in bf16
    ctx = make_ctx()
    PassManager([new_pass("amp", {"level": "O1"})]).apply(ctx)
    step = ctx.build_step(distributed=False)
    rng = np.random.default_rng(0)
    xb = rng.standard_normal((8, 8)).astype(np.float32)
    yb = rng.integers(0, 4, 8)
    losses = [float(np.asarray(step((xb, yb)))) for _ in range(20)]
    assert losses[-1] < losses[0]
    with pytest.raises(ValueError, match="amp level"):
        new_pass("amp", {"level": "o2"})


def test_build_step_composes_user_grad_transform():
    """A user grad_transform in step kwargs composes with pass transforms
    instead of being clobbered."""
    calls = []

    def user_clip(grads):
        calls.append(1)
        return grads

    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 4))
    ctx = PassContext(model, Momentum(learning_rate=0.1, momentum=0.9),
                      loss_fn=lambda out, b: F.cross_entropy(out, b[1]),
                      grad_transform=user_clip)
    PassManager(["fp16_allreduce"]).apply(ctx)
    step = ctx.build_step(distributed=False)
    rng = np.random.default_rng(0)
    float(np.asarray(step((rng.standard_normal((4, 8)).astype(np.float32),
                           rng.integers(0, 4, 4)))))
    assert calls  # user transform executed (at trace time)
