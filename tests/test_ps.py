"""Parameter-server tests: native tables, optimizer rules vs numpy
references, save/load, SSD pass lifecycle, and jit-fused SparseEmbedding.

Pattern follows the reference's PS tests (table unit tests +
``PsLocalClient`` in-proc stack, SURVEY.md §4 mechanism 3).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.ps import (MemorySparseTable, PSContext, StagedPull,
                                       SSDSparseTable, SparseAccessorConfig,
                                       SparseEmbedding)


def make_table(optimizer="sgd", dim=4, lr=0.1, **kw):
    return MemorySparseTable(SparseAccessorConfig(
        embed_dim=dim, optimizer=optimizer, learning_rate=lr,
        initial_range=0.01, seed=7, **kw))


def test_pull_deterministic_init():
    t = make_table()
    a = t.pull([3, 5, 3])
    assert a.shape == (3, 4)
    np.testing.assert_array_equal(a[0], a[2])
    assert np.abs(a).max() <= 0.01
    # same seed -> same init in a fresh table
    b = make_table().pull([3])
    np.testing.assert_array_equal(a[0], b[0])
    assert len(t) == 2


def test_sgd_rule():
    t = make_table("sgd", lr=0.5)
    w0 = t.pull([11])
    g = np.full((1, 4), 2.0, np.float32)
    t.push([11], g)
    np.testing.assert_allclose(t.pull([11]), w0 - 0.5 * g, rtol=1e-6)


def test_adagrad_rule():
    t = make_table("adagrad", lr=0.1)
    w0 = t.pull([1]).astype(np.float64)
    g1 = np.array([[1.0, -2.0, 0.5, 3.0]], np.float32)
    g2 = np.array([[0.5, 1.0, -1.0, 2.0]], np.float32)
    t.push([1], g1)
    t.push([1], g2)
    g2sum = g1.astype(np.float64) ** 2
    w = w0 - 0.1 * g1 / (np.sqrt(g2sum) + 1e-8)
    g2sum += g2.astype(np.float64) ** 2
    w = w - 0.1 * g2 / (np.sqrt(g2sum) + 1e-8)
    np.testing.assert_allclose(t.pull([1]), w, rtol=1e-5)


def test_adam_rule():
    t = make_table("adam", lr=0.01)
    w = t.pull([42]).astype(np.float64)
    m = np.zeros(4)
    v = np.zeros(4)
    b1, b2, eps = 0.9, 0.999, 1e-8
    rng = np.random.default_rng(0)
    for step in range(1, 4):
        g = rng.normal(size=(1, 4)).astype(np.float32)
        t.push([42], g)
        g64 = g.astype(np.float64)[0]
        m = b1 * m + (1 - b1) * g64
        v = b2 * v + (1 - b2) * g64 ** 2
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        w = w - 0.01 * mhat / (np.sqrt(vhat) + eps)
    np.testing.assert_allclose(t.pull([42]), w, rtol=1e-4)


def test_duplicate_keys_in_batch_apply_serially():
    t = make_table("sgd", lr=1.0)
    w0 = t.pull([9])
    g = np.ones((3, 4), np.float32)
    t.push([9, 9, 9], g)
    np.testing.assert_allclose(t.pull([9]), w0 - 3.0, rtol=1e-6)


def test_save_load_roundtrip(tmp_path):
    t = make_table("adagrad")
    t.push(np.arange(100), np.random.default_rng(1).normal(
        size=(100, 4)).astype(np.float32))
    want = t.pull(np.arange(100))
    path = str(tmp_path / "t.bin")
    t.save(path)
    t2 = make_table("adagrad")
    t2.load(path)
    np.testing.assert_array_equal(t2.pull(np.arange(100)), want)
    assert len(t2) == 100


def test_shrink_evicts_cold_keys():
    t = make_table()
    t.pull([1, 2, 3])       # usage 1 each
    t.pull([1])             # key 1 usage 2
    dropped = t.shrink(2.0)
    assert dropped == 2
    assert set(t.keys().tolist()) == {1}


def test_ssd_pass_lifecycle(tmp_path):
    spill = str(tmp_path / "spill")
    t = SSDSparseTable(spill, SparseAccessorConfig(
        embed_dim=4, optimizer="sgd", learning_rate=1.0, seed=3))
    t.begin_pass()
    w0 = t.pull([5])
    t.push([5], np.ones((1, 4), np.float32))
    trained = t.pull([5])
    t.pull([6, 7])  # cold keys
    t.end_pass()    # snapshot + evict (key 5 usage 2, cold usage 1 < thresh? all >=1)
    # evict everything below 3 uses
    t.shrink(3.0)
    assert len(t) == 0
    t.begin_pass()  # reload from snapshot
    np.testing.assert_allclose(t.pull([5]), trained, rtol=1e-6)
    assert not np.allclose(t.pull([5]), w0)


def test_sparse_embedding_jit_train_step():
    """End-to-end: SparseEmbedding inside a jitted loss/grad step; grads
    flow into the table via the custom_vjp push and the loss decreases."""
    emb = SparseEmbedding(8, optimizer="adagrad", learning_rate=0.5, seed=0)
    target = jnp.asarray(np.random.default_rng(2).normal(size=(4, 8)),
                         jnp.float32)

    ids = jnp.asarray([100, 2000, 100, 31337], jnp.int32)

    # The table is not a jax parameter: the grads reach it through the
    # lookup's custom_vjp push, which runs whenever the model's (anchor)
    # params are differentiated — the normal functional train-step path.
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    params = param_state(emb)
    buffers = buffer_state(emb)

    @jax.jit
    def train_step(params):
        def loss_fn(p):
            e, _ = functional_call(emb, p, buffers, ids)
            return jnp.mean((e - target) ** 2)
        return jax.value_and_grad(loss_fn)(params)

    losses = []
    for _ in range(20):
        val, g = train_step(params)
        losses.append(float(val))
    assert losses[-1] < losses[0] * 0.2, losses
    assert len(emb.table) == 3
    # the anchor param itself gets zero grad
    (anchor_g,) = jax.tree_util.tree_leaves(g)
    assert float(jnp.abs(anchor_g).max()) == 0.0


def test_sparse_embedding_only_anchor_param():
    emb = SparseEmbedding(4, optimizer="sgd", seed=1)
    from paddle_tpu.nn.layer import param_state

    leaves = jax.tree_util.tree_leaves(param_state(emb))
    assert len(leaves) == 1 and leaves[0].shape == ()


def test_sparse_embedding_push_dce_guard():
    """A user-composed step that forgets the embedding's params must fail
    loudly — the silent alternative is AD pruning the push-vjp and the
    embedding never training (VERDICT r3 item 7)."""
    emb = SparseEmbedding(4, optimizer="sgd", seed=3)
    ids = jnp.asarray([1, 2], jnp.int32)

    @jax.jit
    def user_step(w):
        # emb's grad_anchor is a closed-over concrete array here, not a
        # differentiated input — the push could never fire
        e = emb(ids)
        return jnp.sum(w * jnp.sum(e))

    with pytest.raises(RuntimeError, match="grad_anchor"):
        jax.grad(user_step)(jnp.ones(4, jnp.float32))

    # same composition is legitimate for inference after .eval()
    emb.eval()
    out = jax.jit(lambda: jnp.sum(emb(ids)))()
    assert np.isfinite(float(out))
    emb.train()

    # and the supported path (params threaded functionally) still pushes:
    # the table rows must actually change after a grad step
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    params, buffers = param_state(emb), buffer_state(emb)
    before = emb.table.pull(np.asarray([1, 2])).copy()

    def loss_fn(p):
        e, _ = functional_call(emb, p, buffers, ids)
        return jnp.sum(e ** 2)

    jax.grad(loss_fn)(params)
    after = emb.table.pull(np.asarray([1, 2]))
    assert not np.allclose(before, after), "push was dead-code-eliminated"


def test_sparse_embedding_eval_no_callback_backend(monkeypatch):
    """Eval-mode composition on a backend without host callbacks (the axon
    tunnel): rows are baked at trace time instead of routed through
    io_callback (which would fail there)."""
    from paddle_tpu.distributed.ps import embedding as emb_mod

    emb = SparseEmbedding(4, optimizer="sgd", seed=5)
    ids = np.asarray([3, 9], np.int64)
    want = emb.table.pull(ids)
    emb.eval()
    monkeypatch.setattr(emb_mod, "_callbacks_supported", False)
    out = jax.jit(lambda: emb(jnp.asarray(ids)))()
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


def test_ps_context_persistables(tmp_path):
    ctx = PSContext()
    t1 = ctx.create_table("emb_a", embed_dim=4, optimizer="sgd", seed=1)
    ctx.create_table("emb_b", embed_dim=4, optimizer="sgd", seed=2)
    with pytest.raises(ValueError):
        ctx.create_table("emb_a", embed_dim=4)
    t1.push([1, 2], np.ones((2, 4), np.float32))
    want = t1.pull([1, 2])
    ctx.init_server()
    ctx.save_persistables(str(tmp_path / "ps"))
    ctx2 = PSContext()
    ctx2.create_table("emb_a", embed_dim=4, optimizer="sgd", seed=1)
    ctx2.load_persistables(str(tmp_path / "ps"))
    np.testing.assert_array_equal(ctx2.get_table("emb_a").pull([1, 2]), want)


def test_staged_pull_train_dedup():
    """StagedPull: pull-before/push-after staging (the PSGPUWorker
    PullSparse/PushSparseGrad structure) — works on backends without
    host-callback support; duplicate ids arrive merged."""
    from paddle_tpu.distributed.ps import StagedPull

    t = make_table("sgd", lr=1.0)
    staged = StagedPull(t)
    ids = np.asarray([7, 9, 7, 7])
    rows, inv, uniq = staged.pull(ids)
    assert rows.shape == (2, 4) and uniq.tolist() == [7, 9]
    np.testing.assert_array_equal(np.asarray(inv), [0, 1, 0, 0])
    w7 = np.asarray(rows[0])

    @jax.jit
    def step(rows, inv):
        def loss_fn(rows):
            return jnp.sum(StagedPull.lookup(rows, inv))
        return jax.value_and_grad(loss_fn)(rows)

    _, g = step(rows, inv)
    # id 7 appears 3x -> merged grad 3.0 per element
    np.testing.assert_allclose(np.asarray(g), [[3.0] * 4, [1.0] * 4])
    staged.push(uniq, g)
    np.testing.assert_allclose(t.pull([7])[0], w7 - 3.0, rtol=1e-6)


def test_load_merge_keeps_live_rows(tmp_path):
    """merge=True load inserts only missing keys — live rows win."""
    t = make_table("sgd")
    t.push([1, 2], np.ones((2, 4), np.float32))
    path = str(tmp_path / "t.bin")
    t.save(path)
    # train key 1 further, drop key 2
    t.push([1], np.ones((1, 4), np.float32))
    live = t.pull([1])
    t2 = make_table("sgd")
    t2.push([1], np.ones((1, 4), np.float32) * 5)  # divergent live row
    mine = t2.pull([1])
    t2.load(path, merge=True)
    np.testing.assert_array_equal(t2.pull([1]), mine)  # not rolled back
    assert 2 in set(t2.keys().tolist())               # missing key inserted
    # plain load overwrites
    t.load(path)
    assert not np.allclose(t.pull([1]), live)


def test_begin_pass_no_rollback(tmp_path):
    """begin_pass after extra training must not restore snapshot values."""
    spill = str(tmp_path / "spill")
    t = SSDSparseTable(spill, SparseAccessorConfig(
        embed_dim=4, optimizer="sgd", learning_rate=1.0, seed=3))
    t.pull([5])
    t.end_pass()
    t.push([5], np.ones((1, 4), np.float32))  # post-snapshot training
    trained = t.pull([5])
    t.begin_pass()  # unpaired begin_pass
    np.testing.assert_array_equal(t.pull([5]), trained)


def test_int64_ids_beyond_int32_contract():
    """Pin the int64-ids contract (VERDICT round-1 weak #8): feature signs
    above 2^31 must flow losslessly through the HOST path — the slot feed,
    the C++ table, and StagedPull's dedup/remap — because jax's global x64
    disable would truncate them on device. The device only ever sees the
    int32 `inv` remap indices, never the raw ids."""
    big_a, big_b = 2 ** 40 + 3, 2 ** 40 + (2 ** 32) + 3  # equal mod 2^32
    t = make_table("sgd")
    ra = t.pull(np.asarray([big_a]))
    rb = t.pull(np.asarray([big_b]))
    assert not np.allclose(ra, rb), \
        "keys differing only above bit 32 must hit distinct rows"
    # StagedPull end to end: int64 dedup on host, int32 remap on device
    staged = StagedPull(t)
    ids = np.asarray([[big_a, big_b], [big_b, big_a]], np.int64)
    rows, inv, uniq = staged.pull(ids)
    assert uniq.dtype == np.int64 and set(uniq) == {big_a, big_b}
    assert np.asarray(inv).dtype in (np.int32, np.int64)
    emb = np.asarray(StagedPull.lookup(rows, inv))
    np.testing.assert_array_equal(emb[0, 0], emb[1, 1])
    np.testing.assert_array_equal(emb[0, 1], emb[1, 0])
    assert not np.array_equal(emb[0, 0], emb[0, 1])
    # grads push back to the right int64 keys
    g = np.zeros((2, 4), np.float32)
    g[list(uniq).index(big_a)] = 1.0
    before_b = t.pull(np.asarray([big_b]))
    staged.push(uniq, g)
    lr = t.accessor.learning_rate
    np.testing.assert_allclose(t.pull(np.asarray([big_a]))[0],
                               np.asarray(ra)[0] - lr * 1.0, rtol=1e-5)
    np.testing.assert_array_equal(t.pull(np.asarray([big_b])), before_b)


def test_int64_signs_through_slot_feed(tmp_path):
    big = 2 ** 40 + 7
    f = tmp_path / "part"
    f.write_text(f"1\t101:{big},{big + 2 ** 32}\n")
    from paddle_tpu.io.slot_dataset import InMemoryDataset

    ds = InMemoryDataset(slots=[101], batch_size=1, max_per_slot=2,
                         drop_last=False)
    ds.load_into_memory([str(f)])
    signs, counts, labels = next(iter(ds))
    assert signs[101].dtype == np.int64
    np.testing.assert_array_equal(signs[101][0], [big, big + 2 ** 32])


def test_pipelined_pass_builder_overlap_and_parity():
    """PipelinedPassBuilder (PSGPUWrapper pre_build_thread analogue): the
    prefetched pass equals a direct StagedPull, pushes land on the right
    keys, and the build genuinely overlaps foreground work."""
    import threading
    import time

    from paddle_tpu.distributed.ps import PipelinedPassBuilder

    t = make_table("sgd")
    rng = np.random.default_rng(0)
    passes = [rng.integers(0, 500, (16, 3)) for _ in range(3)]

    builder = PipelinedPassBuilder(t)
    builder.prefetch(0, passes[0])
    ref = MemorySparseTable(SparseAccessorConfig(
        embed_dim=4, optimizer="sgd", learning_rate=0.1,
        initial_range=0.01, seed=7))
    ref_staged = StagedPull(ref)
    ref_results = {0: ref_staged.pull(passes[0])}
    for p in range(3):
        if p + 1 < 3:
            builder.prefetch(p + 1, passes[p + 1])
            # builds are as-of build time (pre-update values, same
            # staleness as the reference's pre_build_thread); join before
            # pushing so the parity comparison is deterministic, and pull
            # the mirror table at the matching point
            builder._threads[p + 1].join()
            ref_results[p + 1] = ref_staged.pull(passes[p + 1])
        rows, inv, uniq = builder.get(p)
        r_rows, r_inv, r_uniq = ref_results[p]
        np.testing.assert_array_equal(uniq, r_uniq)
        np.testing.assert_allclose(rows, r_rows, rtol=1e-6)
        g = np.ones((uniq.size, 4), np.float32)
        builder.push(p, g)
        ref.push(r_uniq, g)
        builder.end_pass(p)
    np.testing.assert_allclose(t.pull(np.arange(500)),
                               ref.pull(np.arange(500)), rtol=1e-6)

    # overlap: a slow pull must not block the foreground between prefetch
    # and get
    class SlowTable(MemorySparseTable):
        def pull(self, keys):
            time.sleep(0.3)
            return super().pull(keys)

    slow = SlowTable(SparseAccessorConfig(embed_dim=4, optimizer="sgd"))
    b2 = PipelinedPassBuilder(slow)
    t0 = time.perf_counter()
    b2.prefetch(0, np.arange(8))
    foreground = time.perf_counter() - t0
    assert foreground < 0.1, f"prefetch blocked {foreground:.2f}s"
    rows, _, _ = b2.get(0)
    assert rows.shape == (8, 4)


def test_pass_builder_errors():
    from paddle_tpu.distributed.ps import PipelinedPassBuilder

    b = PipelinedPassBuilder(make_table())
    with pytest.raises(KeyError, match="never prefetched"):
        b.get(9)
    with pytest.raises(KeyError, match="no pulled key set"):
        b.push(9, np.zeros((1, 4), np.float32))


def test_ssd_beyond_ram_working_set(tmp_path):
    """Weak #5 (round 1): cycle a working set LARGER than what stays in RAM
    through pass-based spill — every key's trained value must survive
    eviction via the snapshot, across several passes."""
    spill = str(tmp_path / "spill")
    t = SSDSparseTable(spill, SparseAccessorConfig(
        embed_dim=8, optimizer="sgd", learning_rate=1.0, seed=5),
        cache_threshold=1e9)  # evict EVERYTHING at end_pass (tiny "RAM")
    n, chunk = 5000, 1000
    expected = {}
    for p in range(5):  # each pass touches a different 1k-key chunk
        t.begin_pass()
        keys = np.arange(p * chunk, (p + 1) * chunk, dtype=np.int64)
        t.pull(keys)
        t.push(keys, np.full((chunk, 8), float(p + 1), np.float32))
        vals = t.pull(keys)
        t.end_pass()
        assert len(t) == 0, "cache_threshold must evict all of RAM"
        expected.update({int(k): vals[i] for i, k in enumerate(keys)})
    # all 5k keys reload correctly from the spill file
    t.begin_pass()
    all_keys = np.arange(n, dtype=np.int64)
    got = t.pull(all_keys)
    for i, k in enumerate(all_keys):
        np.testing.assert_allclose(got[i], expected[int(k)], rtol=1e-6,
                                   err_msg=f"key {k}")
    assert len(t) == n


def test_pass_builder_ssd_no_data_loss(tmp_path):
    """With an SSD table that evicts everything at end_pass, the builder
    must warm-reload evicted keys (begin_pass inside the build) so trained
    values survive across passes."""
    from paddle_tpu.distributed.ps import PipelinedPassBuilder

    t = SSDSparseTable(str(tmp_path / "spill"), SparseAccessorConfig(
        embed_dim=4, optimizer="sgd", learning_rate=1.0, seed=3),
        cache_threshold=1e9)
    b = PipelinedPassBuilder(t)
    ids = np.arange(10, dtype=np.int64)
    b.prefetch(0, ids)
    rows0, inv, uniq = b.get(0)
    # PIPELINED order: the next pass's build starts (and may finish)
    # before the current pass ends
    b.prefetch(1, ids)
    b._threads[1].join()
    b.push(0, np.ones((uniq.size, 4), np.float32))
    trained = t.pull(ids)
    b.end_pass(0)  # spill + evict ALL — including pass 1's pulled keys
    assert len(t) == 0
    rows1, _, uniq1 = b.get(1)
    # pass 1 pushes AFTER the eviction: must warm-reload, not re-init
    b.push(1, np.ones((uniq1.size, 4), np.float32))
    np.testing.assert_allclose(t.pull(ids), trained - 1.0, rtol=1e-6)


# ---------------------------------------------- FL coordinator (round 3)
def test_fl_coordinator_round_loop():
    """Reference ps/coordinator.py flow: clients push ClientInfoAttr, the
    coordinator's selector publishes per-client FLStrategy, clients pull
    their decision; final round FINISHes everyone."""
    import threading

    from paddle_tpu.distributed.ps import (ClientInfoAttr, Coordinator,
                                           FLClient, FLStrategy)
    from paddle_tpu.distributed.ps.coordinator import ClientSelector

    coord = Coordinator(selector=ClientSelector(max_rounds=2))
    try:
        clients = [FLClient(f"c{i}", coord.endpoint) for i in range(3)]
        results = {}

        def client_loop(c):
            for r in range(2):
                c.push_client_info(r, ClientInfoAttr(
                    loss=1.0 / (r + 1), num_samples=64))
                results[(c.client_id, r)] = c.pull_fl_strategy(r, timeout=30)

        ts = [threading.Thread(target=client_loop, args=(c,)) for c in clients]
        for t in ts:
            t.start()
        rounds = coord.run(num_clients=3, timeout=30)
        for t in ts:
            t.join(timeout=30)
        assert rounds == 2
        assert all(results[(f"c{i}", 0)].action == FLStrategy.JOIN
                   for i in range(3))
        assert all(results[(f"c{i}", 1)].action == FLStrategy.FINISH
                   for i in range(3))
    finally:
        coord.stop()


def test_fl_coordinator_custom_selector():
    """Loss-aware selection: only the worst-loss half JOINs."""
    from paddle_tpu.distributed.ps import ClientInfoAttr, Coordinator, FLClient
    from paddle_tpu.distributed.ps.coordinator import (ClientSelector,
                                                       FLStrategy)

    def pick_worst(round_idx, states):
        ranked = sorted(states, key=lambda c: -(states[c].loss or 0))
        join = set(ranked[:len(ranked) // 2])
        return {c: FLStrategy(FLStrategy.JOIN if c in join
                              else FLStrategy.WAIT) for c in states}

    coord = Coordinator(selector=ClientSelector(select_fn=pick_worst))
    try:
        cs = [FLClient(f"c{i}", coord.endpoint) for i in range(4)]
        for i, c in enumerate(cs):
            c.push_client_info(0, ClientInfoAttr(loss=float(i)))
        coord.run_round(0, num_clients=4, timeout=30)
        acts = {c.client_id: c.pull_fl_strategy(0, timeout=30).action
                for c in cs}
        assert acts["c3"] == "JOIN" and acts["c2"] == "JOIN"
        assert acts["c0"] == "WAIT" and acts["c1"] == "WAIT"
    finally:
        coord.stop()
