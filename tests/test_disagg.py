"""Disaggregated prefill/decode serving (PR 19), device-free.

The acceptance contract on top of the PR 8 block pool + PR 13 fabric:

1. **The wire is exact and self-identifying** — an exported payload
   carries versioned geometry + the covered TOKEN IDS; the importer
   re-derives the digest chain itself, so a corrupt or cross-version
   payload can only miss (ValueError / shorter match), never alias
   another prompt's K/V;
2. **Import is idempotent by digest** — a duplicated or raced migration
   is a no-op, and a failed import returns its rows to the free list;
3. **Export never leaks pins** (tpu_lint R9 for the migration path) and
   chunks the device->host staging under a byte ceiling;
4. **The coordinator degrades, never loses** — any failed migration leg
   falls back to decode-local recompute and counts itself;
5. **The per-pool control surfaces exist** — fleet prefix index with
   consecutive-chain matching, per-signal (TTFT/ITL) SLO burn tracks,
   an autoscaler that scales one pool on one signal, and router scoring
   that prices fleet-remote prefixes below local ones.

Everything here runs on tiny `_SpecModel` pools and stub replicas (no
model build, no rpc) — the real two-process fleet is `fleet_chaos.py
--disagg` / `serve_bench.py --disagg`, wired as `robustness_gate.py
--disagg`. The one real-engine test (warmup compile budget) is slow.
"""
import numpy as np
import pytest

from paddle_tpu.observability.slo import (FLEET_TENANT, SloPolicy,
                                          SloTracker)
from paddle_tpu.serving import (Autoscaler, BlockPool, DisaggClient,
                                PrefixIndex, ReplicaRouter,
                                warm_boot_env)
from paddle_tpu.serving.prefix_cache import (KV_WIRE_VERSION,
                                             _reset_migrate_stats,
                                             chain_digests,
                                             last_migrate_stats)

BS = 4


class _SpecModel:
    def cache_spec(self):
        return {"num_layers": 2, "num_kv_heads": 2, "head_dim": 4,
                "max_length": 64, "dtype": "float32"}


def _pool(**kw):
    kw.setdefault("block_tokens", BS)
    kw.setdefault("max_bytes", 1 << 20)
    return BlockPool(_SpecModel(), **kw)


def _commit_tokens(pool, toks):
    """Host-side store of a prompt's full blocks (the engine does this
    around its fused dispatch)."""
    hit = pool.lookup(toks)
    plan = pool.plan_store(toks, hit.tokens)
    pool.commit(hit, plan, pool.tensors)


def _paint(pool, value):
    """Overwrite every pool leaf with ``value`` so a roundtrip can
    assert actual K/V content moved, not just metadata."""
    import jax.numpy as jnp

    def fill(t):
        if isinstance(t, tuple):
            return tuple(jnp.full(x.shape, value, x.dtype) for x in t)
        return jnp.full(t.shape, value, t.dtype)

    pool.tensors = tuple((fill(k), fill(v)) for k, v in pool.tensors)


def _no_pins(pool):
    return all(e.refs == 0 for e in pool._entries.values())


# ------------------------------------------------------------- wire format
def test_export_import_roundtrip_moves_kv_content():
    src, dst = _pool(), _pool()
    toks = np.arange(2 * BS + 3, dtype=np.int32)     # 2 full blocks
    _commit_tokens(src, toks)
    _paint(src, 7.0)
    payload = src.export_payload(toks)
    assert payload["version"] == KV_WIRE_VERSION
    assert payload["n_blocks"] == 2
    assert payload["payload_bytes"] > 0
    np.testing.assert_array_equal(payload["tokens"], toks[:2 * BS])
    assert dst.match(toks) == 0
    added = dst.inject_payload(payload)
    assert added == 2 * BS
    assert dst.match(toks) == 2 * BS
    # the K/V content landed, block-aligned, on the importer's own rows
    hit = dst.lookup(toks)
    try:
        rows = hit.read_idx[:2]
        for k, v in dst.tensors:
            got = np.asarray(k)[rows]
            np.testing.assert_array_equal(got, np.full_like(got, 7.0))
    finally:
        dst.abort(hit)
    assert _no_pins(src) and _no_pins(dst)


def test_export_import_roundtrip_int8_value_scale_pairs():
    src, dst = _pool(kv_dtype="int8"), _pool(kv_dtype="int8")
    toks = np.arange(3 * BS + 1, dtype=np.int32)
    _commit_tokens(src, toks)
    payload = src.export_payload(toks)
    assert payload["kv_dtype"] == "int8"
    for k, v in payload["leaves"]:
        for leaf in (k, v):
            vals, scales = leaf                  # (int8 values, f32 scales)
            assert vals.dtype == np.int8
            assert scales.dtype == np.float32
    assert dst.inject_payload(payload) == 3 * BS
    assert dst.match(toks) == 3 * BS


def test_inject_rejects_cross_version_and_geometry():
    src = _pool()
    toks = np.arange(BS + 1, dtype=np.int32)
    _commit_tokens(src, toks)
    payload = src.export_payload(toks)
    bad = dict(payload, version=KV_WIRE_VERSION + 1)
    with pytest.raises(ValueError, match="version"):
        _pool().inject_payload(bad)
    with pytest.raises(ValueError, match="block_tokens"):
        _pool(block_tokens=8).inject_payload(payload)
    with pytest.raises(ValueError, match="kv_dtype"):
        _pool(kv_dtype="int8").inject_payload(payload)
    # a mixed-version fleet degrades to recompute, never corrupt K/V
    assert _pool().inject_payload(payload) == BS


def test_import_is_idempotent_by_digest():
    src, dst = _pool(), _pool()
    toks = np.arange(2 * BS + 1, dtype=np.int32)
    _commit_tokens(src, toks)
    payload = src.export_payload(toks)
    assert dst.inject_payload(payload) == 2 * BS
    before = last_migrate_stats()
    assert dst.inject_payload(payload) == 0      # duplicate: no-op
    after = last_migrate_stats()
    assert after["blocks_skipped"] - before["blocks_skipped"] == 2
    assert dst.stats()["blocks_in_use"] == 2     # never double-stored


def test_tampered_tokens_cannot_alias_the_original_prompt():
    """The payload's identity IS its tokens: corrupting them re-derives
    a different chain on import, so the original prompt still misses —
    the failure mode is a wasted migration, never wrong K/V."""
    src, dst = _pool(), _pool()
    toks = np.arange(2 * BS + 1, dtype=np.int32)
    _commit_tokens(src, toks)
    payload = src.export_payload(toks)
    forged = dict(payload, tokens=payload["tokens"].copy())
    forged["tokens"][0] = 999
    dst.inject_payload(forged)
    assert dst.match(toks) == 0


def test_export_miss_returns_none_and_releases_pins():
    pool = _pool()
    toks = np.arange(2 * BS, dtype=np.int32)
    assert pool.export_payload(toks) is None     # nothing committed
    _commit_tokens(pool, np.arange(BS + 1, dtype=np.int32))
    pool.export_payload(np.arange(BS + 1, dtype=np.int32))
    assert _no_pins(pool)                        # R9: finally released


def test_export_chunks_bound_host_staging():
    pool = _pool()
    n_blocks = 4
    toks = np.arange(n_blocks * BS + 1, dtype=np.int32)
    _commit_tokens(pool, toks)
    _reset_migrate_stats()     # peak_chunk_bytes is a process-wide max
    payload = pool.export_payload(toks, max_chunk_bytes=pool.block_bytes)
    after = last_migrate_stats()
    assert payload["n_blocks"] == n_blocks
    # one row per chunk: the staging working set never exceeds a block
    assert after["chunks"] == n_blocks
    assert after["peak_chunk_bytes"] <= 2 * pool.block_bytes


def test_saturated_importer_lands_the_chain_prefix():
    src = _pool()
    dst = _pool(max_bytes=2 * _pool().block_bytes)   # tiny destination
    toks = np.arange(6 * BS + 1, dtype=np.int32)
    _commit_tokens(src, toks)
    payload = src.export_payload(toks)
    added = dst.inject_payload(payload)
    assert 0 < added < 6 * BS
    assert added % BS == 0
    assert dst.match(toks) == added              # a CONSECUTIVE prefix


# ------------------------------------------------------------ prefix index
def test_prefix_index_consecutive_chain_match():
    idx = PrefixIndex()
    toks = np.arange(4 * BS + 1, dtype=np.int32)
    digests = chain_digests(toks, BS)
    idx.publish("pre0", [d.hex() for d in digests[:3]])
    # holds blocks 0..2 plus an unrelated block — chain stops at 3
    idx.publish("pre1", [digests[0].hex(), digests[2].hex()])
    blocks, who = idx.match(digests)
    assert (blocks, who) == (3, "pre0")
    blocks, who = idx.match(digests, exclude="pre0")
    assert (blocks, who) == (1, "pre1")          # gap at block 1
    idx.remove("pre0")
    assert idx.replicas() == ["pre1"]
    assert idx.match(digests)[1] == "pre1"
    st = idx.statusz()
    assert st["replicas"]["pre1"]["blocks"] == 2
    assert st["distinct_blocks"] == 2


def test_prefix_index_fleet_miss_is_zero_none():
    idx = PrefixIndex()
    assert idx.match(chain_digests(np.arange(9), BS)) == (0, None)
    assert idx.statusz() == {"replicas": {}, "distinct_blocks": 0}


# ------------------------------------------------------------- coordinator
class _StubReplica:
    """The RemoteReplica duck type: submit + the migration surface."""

    def __init__(self, name, payload=None, fail=None,
                 digests=(), import_tokens=2 * BS):
        self.name = name
        self.payload = payload
        self.fail = fail                 # exception raised by any kv leg
        self._digests = list(digests)
        self.import_tokens = import_tokens
        self.calls = []

    def submit(self, **kw):
        self.calls.append(("submit", kw))
        return "handle"

    def kv_prefill(self, prompt, timeout_s=None, correlation_id=None):
        self.calls.append(("kv_prefill", len(prompt)))
        if self.fail is not None:
            raise self.fail

    def kv_export(self, prompt, corr=None, max_chunk_bytes=None):
        self.calls.append(("kv_export", len(prompt)))
        if self.fail is not None:
            raise self.fail
        return self.payload

    def kv_import(self, payload, corr=None):
        self.calls.append(("kv_import", payload["payload_bytes"]))
        if self.fail is not None:
            raise self.fail
        return self.import_tokens

    def prefix_digests(self):
        if self.fail is not None:
            raise self.fail
        return {"block_tokens": BS, "digests": list(self._digests),
                "time": 0.0}

    def called(self, kind):
        return [c for c in self.calls if c[0] == kind]


def _payload(n_blocks=2):
    return {"payload_bytes": 4096 * n_blocks, "n_blocks": n_blocks}


def test_disagg_client_migrates_then_submits_to_decode():
    pre = _StubReplica("pre0", payload=_payload())
    dec = _StubReplica("dec0")
    c = DisaggClient([pre], [dec], block_tokens=BS)
    h = c.submit(np.arange(3 * BS, dtype=np.int32), max_new_tokens=4)
    assert h == "handle"
    assert pre.called("kv_prefill") and pre.called("kv_export")
    assert dec.called("kv_import") and dec.called("submit")
    st = c.statusz()
    assert st["migrations"] == 1 and st["fallbacks"] == 0
    assert st["migrated_bytes"] == 8192
    assert st["migrated_tokens"] == 2 * BS
    assert st["migrate_s"] >= 0
    # the decode submit carries a correlation id (the cross-host lane)
    assert dec.called("submit")[0][1]["correlation_id"]


def test_disagg_client_falls_back_on_any_failed_leg():
    pre = _StubReplica("pre0", fail=ConnectionError("replica gone"))
    dec = _StubReplica("dec0")
    c = DisaggClient([pre], [dec], block_tokens=BS)
    assert c.submit(np.arange(3 * BS, dtype=np.int32),
                    max_new_tokens=4) == "handle"
    assert dec.called("submit")          # the request is never lost
    assert not dec.called("kv_import")   # the migration leg was dropped
    assert c.statusz() == {**c.statusz(), "fallbacks": 1, "migrations": 0}


def test_disagg_client_skips_migration_below_min_tokens():
    pre = _StubReplica("pre0", payload=_payload())
    dec = _StubReplica("dec0")
    c = DisaggClient([pre], [dec], block_tokens=BS)
    assert c.min_migrate_tokens == BS + 1    # < one full block: recompute
    c.submit(np.arange(BS, dtype=np.int32), max_new_tokens=4)
    assert not pre.calls and dec.called("submit")
    assert c.statusz()["migrations"] == 0 == c.statusz()["fallbacks"]


def test_disagg_client_skips_adapter_salted_requests():
    """Per-tenant chains are salted with a replica-private adapter salt
    — they cannot be addressed fleet-wide, so migration must not try."""
    pre = _StubReplica("pre0", payload=_payload())
    dec = _StubReplica("dec0")
    c = DisaggClient([pre], [dec], block_tokens=BS)
    c.submit(np.arange(3 * BS, dtype=np.int32), max_new_tokens=4,
             adapter_id="tenant-a")
    assert not pre.calls
    assert dec.called("submit")[0][1]["adapter_id"] == "tenant-a"


def test_disagg_client_prefers_warm_indexed_source():
    toks = np.arange(3 * BS, dtype=np.int32)
    digests = chain_digests(toks, BS)
    warm = _StubReplica("warm", payload=_payload())
    cold = _StubReplica("cold", payload=_payload())
    idx = PrefixIndex()
    idx.publish("warm", [d.hex() for d in digests])
    c = DisaggClient([cold, warm], [_StubReplica("dec0")],
                     block_tokens=BS, index=idx)
    c.submit(toks, max_new_tokens=4)
    assert warm.called("kv_export") and not warm.called("kv_prefill")
    assert not cold.calls                    # round-robin was bypassed
    assert c.statusz()["remote_hits"] == 1


def test_disagg_client_stale_index_reprefills_then_exports():
    """The index is a scraped VIEW: when it names a source whose blocks
    were since evicted (export -> None), the client runs the prefill
    after all instead of failing the migration."""
    toks = np.arange(3 * BS, dtype=np.int32)
    warm = _StubReplica("warm", payload=None)    # stale: nothing matches

    def prefill(prompt, timeout_s=None, correlation_id=None):
        warm.calls.append(("kv_prefill", len(prompt)))
        warm.payload = _payload()                # now it really holds it

    warm.kv_prefill = prefill
    idx = PrefixIndex()
    idx.publish("warm", [d.hex() for d in chain_digests(toks, BS)])
    dec = _StubReplica("dec0")
    c = DisaggClient([warm], [dec], block_tokens=BS, index=idx)
    c.submit(toks, max_new_tokens=4)
    assert warm.called("kv_prefill") and len(warm.called("kv_export")) == 2
    assert dec.called("kv_import")
    assert c.statusz()["migrations"] == 1


def test_scrape_index_publishes_and_drops_unreachable():
    toks = np.arange(2 * BS + 1, dtype=np.int32)
    digests = [d.hex() for d in chain_digests(toks, BS)]
    up = _StubReplica("up", digests=digests)
    down = _StubReplica("down", digests=digests)
    idx = PrefixIndex()
    c = DisaggClient([up, down], [_StubReplica("dec0")],
                     block_tokens=BS, index=idx)
    assert c.scrape_index() == 2
    assert idx.replicas() == ["down", "up"]
    down.fail = ConnectionError("partitioned")
    assert c.scrape_index() == 1
    assert idx.replicas() == ["up"]          # absent beats stale


def test_disagg_client_needs_both_pools():
    with pytest.raises(ValueError, match="prefill"):
        DisaggClient([], [_StubReplica("d")])
    with pytest.raises(ValueError, match="decode"):
        DisaggClient([_StubReplica("p")], [])


# ----------------------------------------------------- router remote hits
def test_router_scores_fleet_remote_prefix_below_local():
    """A prefix resident on another host is reachable via migration:
    the router's score must count it (discounted), so shared-prefix
    traffic is not scattered as if the fleet were cold."""
    from test_fleet_serving import _StubServer

    toks = np.arange(3 * BS, dtype=np.int32)
    idx = PrefixIndex()
    idx.publish("pre0", [d.hex()
                         for d in chain_digests(toks, _StubServer()
                                                .engine.pool.block_tokens)])
    router = ReplicaRouter(prefix_index=idx, remote_hit_weight=0.5)
    router.add_replica(_StubServer(), "a")
    router.submit(toks, max_new_tokens=2)
    assert router.prefix_remote_hits >= 1
    block = router.fleet_statusz()["prefix_index"]
    assert block["remote_hit_weight"] == 0.5
    assert block["score_remote_hits"] >= 1
    assert "pre0" in block["replicas"]


def test_router_without_index_has_no_prefix_index_block():
    from test_fleet_serving import _StubServer

    router = ReplicaRouter()
    router.add_replica(_StubServer(), "a")
    router.submit(np.arange(8, dtype=np.int32), max_new_tokens=2)
    assert "prefix_index" not in router.fleet_statusz()
    assert router.prefix_remote_hits == 0


# ------------------------------------------------------- per-signal burns
def _snap(total, ttft_ms=1.0, itl_ms=1.0, ttft_n=None, itl_n=None):
    return {"requests_submitted": total, "requests_failed": 0,
            "requests_expired": 0, "requests_shed": 0,
            "ttft": {"count": ttft_n if ttft_n is not None else total,
                     "mean_ms": ttft_ms},
            "inter_token": {"count": itl_n if itl_n is not None
                            else 10 * total, "mean_ms": itl_ms}}


def test_slo_itl_burn_is_a_separate_signal():
    """An ITL breach books burn on the ITL track ONLY — the combined
    verdict (and with it every PR 16 behavior) is unchanged."""
    clock = [0.0]
    tr = SloTracker(SloPolicy(target_ttft_s=0.5, target_itl_s=0.02),
                    registry=False, dump_on_burn=False,
                    clock=lambda: clock[0])
    tr.ingest(_snap(0))
    clock[0] = 10.0
    rep = tr.ingest(_snap(20, ttft_ms=1.0, itl_ms=50.0))
    ten = rep["tenants"][FLEET_TENANT]
    assert ten["burn_slow_itl"] > 0
    assert ten["burn_slow_ttft"] == 0.0
    assert ten["burn_slow"] == 0.0           # combined: no failed requests
    assert not ten["slow_breached"]


def test_slo_ttft_burn_tracks_its_own_signal():
    clock = [0.0]
    tr = SloTracker(SloPolicy(target_ttft_s=0.05, target_itl_s=0.02),
                    registry=False, dump_on_burn=False,
                    clock=lambda: clock[0])
    tr.ingest(_snap(0))
    clock[0] = 10.0
    rep = tr.ingest(_snap(20, ttft_ms=500.0, itl_ms=1.0))
    ten = rep["tenants"][FLEET_TENANT]
    assert ten["burn_slow_ttft"] > 0
    assert ten["burn_slow_itl"] == 0.0
    assert ten["burn_slow"] > 0              # TTFT feeds the combined burn


def test_slo_policy_rejects_bad_itl_target():
    with pytest.raises(ValueError, match="target_itl_s"):
        SloPolicy(target_itl_s=0.0)


# -------------------------------------------------- per-pool autoscaling
def _signal_report(**burns):
    ten = {"burn_slow": 0.0, "burn_fast": 0.0,
           "burn_slow_ttft": 0.0, "burn_fast_ttft": 0.0,
           "burn_slow_itl": 0.0, "burn_fast_itl": 0.0,
           "slow_breached": False, "fast_breached": False,
           "alerting": False, "window_slow": {"total": 10},
           "window_fast": {"total": 10}}
    ten.update(burns)
    return {"policy": {"slow_burn_threshold": 2.0},
            "tenants": {"spike": ten}}


def _auto_fleet(**kw):
    from test_slo_control_loop import _StubServer

    router = ReplicaRouter([_StubServer()])
    clock = [0.0]
    auto = Autoscaler(router, lambda name: _StubServer(),
                      sustain_ticks=1, cooldown_s=0.0, max_replicas=3,
                      clock=lambda: clock[0], **kw)
    return router, auto


def test_autoscaler_scales_decode_pool_on_itl_burn_only():
    """The disagg split: a decode-pool autoscaler on burn_signal='itl'
    fires on ITL burn that the combined verdict never saw — and a
    TTFT-signal (prefill-pool) autoscaler ignores the same report."""
    router, auto = _auto_fleet(burn_signal="itl")
    router.slo_report = lambda: _signal_report(burn_slow_itl=5.0,
                                               burn_fast_itl=6.0)
    d = auto.tick()
    assert d["action"] == "scale_out"
    assert d["signal"] == "itl" and d["burn_slow"] == pytest.approx(5.0)

    router2, auto2 = _auto_fleet(burn_signal="ttft")
    router2.slo_report = lambda: _signal_report(burn_slow_itl=5.0,
                                                burn_fast_itl=6.0)
    assert auto2.tick() is None

    router3, auto3 = _auto_fleet()           # combined signal: PR 16
    router3.slo_report = lambda: _signal_report(burn_slow_itl=5.0,
                                                burn_fast_itl=6.0)
    assert auto3.tick() is None


def test_autoscaler_rejects_unknown_burn_signal():
    from test_slo_control_loop import _StubServer

    router = ReplicaRouter([_StubServer()])
    with pytest.raises(ValueError, match="burn_signal"):
        Autoscaler(router, lambda name: _StubServer(),
                   burn_signal="goodput")
    assert "autoscaler" not in router.statusz()


def test_autoscaler_statusz_names_its_signal():
    router, auto = _auto_fleet(burn_signal="ttft")
    router.slo_report = _signal_report
    auto.tick()
    assert router.statusz()["autoscaler"]["config"]["burn_signal"] \
        == "ttft"


# --------------------------------------------------------------- warm boot
def test_warm_boot_env_points_the_persistent_cache(tmp_path):
    env = warm_boot_env(tmp_path / "cc")
    assert env == {"FLAGS_persistent_compile_cache": "1",
                   "FLAGS_compile_cache_dir": str(tmp_path / "cc")}


@pytest.mark.slow
def test_prefill_warmup_traces_no_decode_program():
    """A prefill replica serves nothing but max_new_tokens=1 requests:
    warmup(max_new_tokens=1) must compile the #buckets prefill programs
    and NEVER trace decode — the disagg compile-budget contract."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
    from paddle_tpu.serving import ContinuousBatchingEngine

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = ContinuousBatchingEngine(model, slots=2, max_length=64,
                                   prefill_buckets=(32,))
    eng.warmup(max_new_tokens=1)
    cc = eng.cache_stats()
    assert cc["prefill"]["compiles"] == 1
    assert cc["decode"]["compiles"] == 0
