"""String ops + FasterTokenizer + top-level API compat (VERDICT r3 item 8:
tensor-API long tail + strings basics; reference
strings_lower_upper_kernel.h, faster_tokenizer_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import strings
from paddle_tpu.text import FasterTokenizer


VOCAB = {t: i for i, t in enumerate([
    "[PAD]", "[UNK]", "[CLS]", "[SEP]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "dog",
    "!", "un", "##affable",
])}


def test_strings_lower_upper():
    x = strings.to_string_tensor(["HeLLo", "WÖRLD"])
    np.testing.assert_array_equal(strings.lower(x),
                                  np.asarray(["hello", "wörld"], object))
    np.testing.assert_array_equal(strings.upper(x),
                                  np.asarray(["HELLO", "WÖRLD"], object))
    # ascii-only mode leaves non-ascii chars untouched
    np.testing.assert_array_equal(
        strings.lower(["AÖB"], use_utf8_encoding=False),
        np.asarray(["aÖb"], object))


def test_wordpiece_continuation_and_unk():
    tok = FasterTokenizer(VOCAB)
    ids, seg = tok(["The quick fox jumped!"])
    toks = [k for i in ids[0] for k, v in VOCAB.items() if v == i]
    assert toks == ["[CLS]", "the", "quick", "fox", "jump", "##ed", "!",
                    "[SEP]"]
    assert seg.tolist() == [[0] * len(toks)]
    # unknown word -> [UNK]
    ids2, _ = tok(["zzz unaffable"])
    toks2 = [k for i in ids2[0] for k, v in VOCAB.items() if v == i]
    assert toks2 == ["[CLS]", "[UNK]", "un", "##affable", "[SEP]"]


def test_tokenizer_pairs_truncation_padding():
    tok = FasterTokenizer(VOCAB)
    ids, seg = tok(["the fox", "the"], text_pair=["over the dog", "dog"])
    # batch padded to longest; segment 1 marks the pair half
    assert ids.shape == seg.shape
    row = seg[0][:int((ids[0] != VOCAB["[PAD]"]).sum())]
    assert row[0] == 0 and row[-1] == 1
    ids3, _ = tok(["the quick brown fox jump over the dog"],
                  max_seq_len=6, pad_to_max_seq_len=True)
    assert ids3.shape == (1, 6)
    assert ids3[0][-1] != VOCAB["[PAD]"]  # truncated, not padded


def test_tokenizer_edge_cases():
    tok = FasterTokenizer(VOCAB)
    # max_seq_len too small for any content: degenerates, never crashes
    ids, _ = tok(["the fox"], text_pair=["the dog"], max_seq_len=2)
    assert ids.shape[1] <= 3
    ids2, _ = tok(["the quick fox"], max_seq_len=1, pad_to_max_seq_len=True)
    assert ids2.shape == (1, 1)
    # CJK chars split one-per-word (reference tokenize_chinese_chars)
    vocab = dict(VOCAB)
    vocab.update({"你": 100, "好": 101})
    tok2 = FasterTokenizer(vocab)
    ids3, _ = tok2(["你好"])
    assert ids3[0].tolist() == [VOCAB["[CLS]"], 100, 101, VOCAB["[SEP]"]]


def test_tokenizer_lowercase_accent_strip():
    tok = FasterTokenizer(VOCAB)
    ids, _ = tok(["Thé Fôx"])  # lowercase + NFD accent strip
    toks = [k for i in ids[0] for k, v in VOCAB.items() if v == i]
    assert toks == ["[CLS]", "the", "fox", "[SEP]"]


def test_text_serving_pipeline(tmp_path):
    """Serving parity: raw strings -> tokenizer (host stage) -> compiled
    program, the faster_tokenizer_op single-pipeline contract."""
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.model import InputSpec
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit import save as jit_save

    pt.seed(0)
    model = nn.Sequential(nn.Embedding(len(VOCAB), 8))
    jit_save(model, str(tmp_path / "m"),
             input_spec=[InputSpec([None, None], dtype="int32")])
    pred = create_predictor(Config(str(tmp_path / "m")))
    tok = FasterTokenizer(VOCAB)
    ids, _ = tok(["the quick fox", "over the dog !"])
    (out,) = pred.run([ids])
    assert out.shape == (2, ids.shape[1], 8)
    assert np.isfinite(out).all()


def test_top_level_api_compat():
    # places
    assert pt.CUDAPlace(0) == pt.CUDAPlace(0)
    assert pt.CPUPlace().jax_device().platform == "cpu"
    # grad mode
    assert pt.is_grad_enabled()
    with pt.set_grad_enabled(False):
        assert not pt.is_grad_enabled()
    assert pt.is_grad_enabled()
    # static flag
    assert pt.in_dynamic_mode()
    with pytest.warns(UserWarning):
        pt.enable_static()
    assert not pt.in_dynamic_mode()
    pt.disable_static()
    # tensor array ops
    arr = pt.create_array()
    pt.array_write(pt.ones([2]), 0, arr)
    pt.array_write(pt.zeros([2]), 1, arr)
    assert int(pt.array_length(arr)) == 2
    assert float(np.asarray(pt.array_read(arr, 1)).sum()) == 0.0
    # batch reader
    assert [len(b) for b in pt.batch(lambda: iter(range(7)), 3)()] == [3, 3, 1]
    assert [len(b) for b in
            pt.batch(lambda: iter(range(7)), 3, drop_last=True)()] == [3, 3]
    # places are hashable (ported scripts key dicts on them)
    assert len({pt.CUDAPlace(0), pt.CUDAPlace(0), pt.CPUPlace()}) == 2
    # create_parameter / index_add_
    p = pt.create_parameter([4, 3])
    assert tuple(p.shape) == (4, 3) and not p.stop_gradient
    # in-place op on a grad-requiring tensor violates the tape invariant
    with pytest.raises(RuntimeError, match="index_add_"):
        pt.index_add_(p, np.asarray([0]), 0, np.ones((1, 3), np.float32))
    t = pt.eager.to_tensor(np.zeros((5, 3), np.float32))
    pt.index_add_(t, np.asarray([0, 2]), 0, np.ones((2, 3), np.float32))
    assert float(np.asarray(t.numpy()).sum()) == 6.0  # mutated in place
    a = pt.index_add_(np.zeros((5, 3), np.float32), np.asarray([1]), 0,
                      np.ones((1, 3), np.float32))
    assert float(np.asarray(a).sum()) == 3.0  # plain arrays: returns update
    # check_shape
    pt.check_shape([2, -1, 3])
    with pytest.raises(TypeError):
        pt.check_shape([2, "x"])
    # dtype callable + bool alias
    assert pt.dtype("float32") == np.float32
    assert pt.bool is pt.bool_
    # DataParallel wrapper
    import paddle_tpu.nn as nn

    dp = pt.DataParallel(nn.Linear(3, 2))
    out = dp(np.zeros((1, 3), np.float32))
    assert out.shape == (1, 2)
    with dp.no_sync():
        pass
    assert dp.scale_loss(1.5) == 1.5
    # LazyGuard / misc no-ops
    with pt.LazyGuard():
        pass
    pt.disable_signal_handler()
    assert pt.Tensor is pt.eager.Tensor


def test_static_facade(tmp_path):
    """paddle.static collapsed surface: data->InputSpec,
    save/load_inference_model over jit artifacts, honest migration errors
    on op-append machinery."""
    import paddle_tpu.nn as nn
    from paddle_tpu import static

    spec = static.data("x", [None, 4], "float32")
    assert spec.name == "x" and spec.shape[1] == 4
    with static.program_guard(static.default_main_program()):
        with static.name_scope("block"):
            pass
    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "sim")
    static.save_inference_model(prefix, [spec], net)
    prog = static.load_inference_model(prefix)
    out = prog(np.ones((3, 4), np.float32))
    assert np.asarray(out).shape == (3, 2)
    with pytest.raises(NotImplementedError, match="to_static"):
        static.Executor().run()
    with pytest.raises(NotImplementedError, match="to_static"):
        static.default_main_program().global_block()
