"""Continuous-batching serving subsystem (paddle_tpu/serving/).

The acceptance contract:

1. **Correctness under interleaving** — requests submitted at staggered
   times, admitted into slots while other requests are mid-decode, all
   complete with EXACTLY the tokens a solo batch-1 ``generate()`` with
   the same seed produces (slot placement and batch companions must not
   leak into results);
2. **Compile discipline** — after warmup the serving loop holds at
   ``#prefill_buckets + 1`` compiled programs (``cache_stats()``), no
   matter how many requests flow through;
3. **Admission control** — a full queue rejects with retryable
   backpressure; queue-expired deadlines fail with ``TimeoutError``;
4. **Crash safety** — an injected worker fault requeues in-flight
   requests and the recovered run returns identical tokens, without
   recompiling.

Tier-1 budget discipline: ONE module-scoped server (ONE bucket, so two
serving programs total) is shared by every integration test; scheduler/
metrics tests are device-free. The open-loop load bench runs under the
``slow`` marker only. NOTE: the drain-shutdown test must run LAST in
this file — it retires the shared server.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.resilience import (Deadline, FaultPlan,
                                               RetryPolicy)
from paddle_tpu.serving import (FifoScheduler, InferenceServer, QueueFull,
                                Request, SchedulerClosed)
from paddle_tpu.serving.metrics import LatencyHistogram, ServingMetrics

GEO = dict(max_length=64, prefill_buckets=(16,))


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


@pytest.fixture(scope="module")
def server(lm):
    model, _ = lm
    srv = InferenceServer(model, slots=2, max_queue_depth=8,
                          max_request_retries=1, **GEO)
    yield srv
    try:
        srv.shutdown(drain=False, timeout=30)
    except Exception:
        pass


def _prompt(cfg, n, seed=0):
    return np.random.default_rng(seed).integers(
        0, cfg.vocab_size, (n,)).astype(np.int32)


# ---------------------------------------------------------------- tentpole
def test_continuous_batching_matches_solo_generate(lm, server):
    """THE acceptance test: three staggered requests (greedy + seeded
    sampling, different lengths/budgets) admitted into a 2-slot live
    batch — every result equals its solo batch-1 generate()."""
    model, cfg = lm
    p0, p1, p2 = (_prompt(cfg, 9, 1), _prompt(cfg, 12, 2),
                  _prompt(cfg, 6, 3))
    solo0 = model.generate(p0[None], max_new_tokens=10, **GEO)[0]
    solo1 = model.generate(p1[None], max_new_tokens=7, do_sample=True,
                           temperature=0.8, seed=5, **GEO)[0]
    solo2 = model.generate(p2[None], max_new_tokens=5, **GEO)[0]

    h0 = server.submit(p0, max_new_tokens=10)
    time.sleep(0.15)  # h1/h2 arrive while h0 is mid-decode
    h1 = server.submit(p1, max_new_tokens=7, do_sample=True,
                       temperature=0.8, seed=5)
    time.sleep(0.1)
    h2 = server.submit(p2, max_new_tokens=5)
    np.testing.assert_array_equal(h0.result(timeout=300), solo0)
    np.testing.assert_array_equal(h1.result(timeout=300), solo1)
    np.testing.assert_array_equal(h2.result(timeout=300), solo2)
    assert h0.ttft_s is not None and h0.ttft_s > 0


def test_steady_state_holds_at_buckets_plus_one(lm, server):
    """After warmup (previous test), more traffic — mixed sampling knobs,
    every free-slot reuse pattern — adds ZERO compiled programs: exactly
    #prefill_buckets prefill + 1 decode."""
    from paddle_tpu.framework import compile_cache

    model, cfg = lm
    cc = server.engine.cache_stats()
    assert cc["prefill"]["compiles"] == len(server.engine.prefill_buckets)
    assert cc["decode"]["compiles"] == 1
    with compile_cache.retrace_guard(max_compiles=0, label="serving"):
        hs = [server.submit(_prompt(cfg, 4 + i, seed=10 + i),
                            max_new_tokens=3 + i, do_sample=bool(i % 2),
                            temperature=0.5 + 0.1 * i, top_p=0.9,
                            seed=i) for i in range(5)]
        for h in hs:
            assert h.result(timeout=300).shape[0] == h.request.max_new_tokens
    cc2 = server.engine.cache_stats()
    assert cc2["prefill"]["compiles"] == cc["prefill"]["compiles"]
    assert cc2["decode"]["compiles"] == 1
    total = cc2["prefill"]["compiles"] + cc2["decode"]["compiles"]
    assert total == len(server.engine.prefill_buckets) + 1


def test_streaming_iterator_and_eos(lm, server):
    """stream() yields tokens incrementally; eos finishes the request
    early and the stream ends cleanly."""
    model, cfg = lm
    p = _prompt(cfg, 8, 4)
    probe = model.generate(p[None], max_new_tokens=2, **GEO)[0]
    eos = int(probe[1])  # greedy token at step 2 -> finishes there
    solo = model.generate(p[None], max_new_tokens=16, eos_token_id=eos,
                          **GEO)[0]
    h = server.submit(p, max_new_tokens=16, eos_token_id=eos)
    got = list(h.stream())
    np.testing.assert_array_equal(np.asarray(got, np.int32), solo)
    assert got[-1] == eos and len(got) < 16


def test_worker_fault_requeues_and_result_is_identical(lm, server):
    """An injected fault mid-serve (FaultPlan at the serve.step site)
    resets the engine, requeues the in-flight request, and the retried
    run — same seed — returns the same tokens, with NO recompile."""
    model, cfg = lm
    p = _prompt(cfg, 10, 6)
    solo = model.generate(p[None], max_new_tokens=6, do_sample=True,
                          temperature=0.9, seed=11, **GEO)[0]
    before = server.engine.cache_stats()
    requeued0 = server.metrics.requests_requeued
    plan = FaultPlan([{"site": "serve.step", "kind": "drop", "times": 1}],
                     seed=3)
    with plan, pytest.warns(RuntimeWarning, match="serve loop fault"):
        h = server.submit(p, max_new_tokens=6, do_sample=True,
                          temperature=0.9, seed=11)
        out = h.result(timeout=300)
    assert plan.fired[0] == 1  # the fault actually hit the serve loop
    np.testing.assert_array_equal(out, solo)
    assert server.metrics.requests_requeued == requeued0 + 1
    after = server.engine.cache_stats()
    assert after["prefill"]["compiles"] == before["prefill"]["compiles"]
    assert after["decode"]["compiles"] == before["decode"]["compiles"]


def test_admit_fault_requeues_whole_admission_batch(lm, server):
    """A fault during ADMISSION must not drop the other requests popped
    in the same admission batch — every client completes (the handles
    would otherwise hang forever)."""
    model, cfg = lm
    solos = [model.generate(_prompt(cfg, 5 + i, 30 + i)[None],
                            max_new_tokens=4, **GEO)[0] for i in range(3)]
    plan = FaultPlan([{"site": "serve.admit", "kind": "drop", "times": 1}],
                     seed=5)
    with plan, pytest.warns(RuntimeWarning, match="serve loop fault"):
        hs = [server.submit(_prompt(cfg, 5 + i, 30 + i), max_new_tokens=4)
              for i in range(3)]
        outs = [h.result(timeout=300) for h in hs]
    assert plan.fired[0] == 1
    for out, solo in zip(outs, solos):
        np.testing.assert_array_equal(out, solo)


def test_request_deadline_expires_in_queue(lm, server):
    model, cfg = lm
    h = server.submit(_prompt(cfg, 5, 7), max_new_tokens=4, deadline=0.0)
    with pytest.raises(TimeoutError, match="expired in queue"):
        h.result(timeout=60)
    assert server.metrics.requests_expired >= 1


def test_result_timeout_and_overlong_reject(lm, server):
    model, cfg = lm
    with pytest.raises(ValueError, match="max_length"):
        server.submit(_prompt(cfg, 8), max_new_tokens=1000)
    h = server.submit(_prompt(cfg, 5, 8), max_new_tokens=4)
    with pytest.raises(TimeoutError):
        h.result(timeout=0.0)
    h.result(timeout=300)  # then completes fine


def test_unseeded_sampled_requests_draw_fresh_randomness(lm, server):
    """Two unseeded sampled requests with the SAME prompt must not
    return identical streams (solo generate(seed=None) semantics — the
    serving layer must not pin a default seed)."""
    model, cfg = lm
    p = _prompt(cfg, 7, 40)
    kw = dict(max_new_tokens=8, do_sample=True, temperature=8.0)
    a = server.submit(p, **kw).result(timeout=300)
    b = server.submit(p, **kw).result(timeout=300)
    assert not np.array_equal(a, b)


def test_top_p_rejected_on_server_without_nucleus_graph(lm):
    """allow_top_p=False compiles sampling without the nucleus filter;
    a top_p request on such a server must fail loudly at submit, never
    be silently ignored. (No dispatch — construction compiles nothing.)"""
    model, _ = lm
    srv = InferenceServer(model, slots=1, allow_top_p=False, **GEO)
    with pytest.raises(ValueError, match="allow_top_p"):
        srv.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                   do_sample=True, top_p=0.5)
    srv.shutdown(drain=False, timeout=10)


def test_metrics_snapshot_shape(server):
    snap = server.snapshot()
    for k in ("slot_occupancy", "tokens_per_sec", "requests_per_sec",
              "queue_depth", "active_slots", "compile_stats"):
        assert k in snap
    for h in ("ttft", "inter_token", "queue_wait"):
        assert {"count", "p50_ms", "p99_ms"} <= set(snap[h])
    assert snap["requests_completed"] >= 9
    assert 0.0 <= snap["slot_occupancy"] <= 1.0


@pytest.mark.slow
def test_llama_gqa_continuous_batching():
    """The GQA+RoPE path under per-slot positions: two staggered llama
    requests in a 2-slot batch both equal their solo runs (rotary tables
    and the grouped-KV cache index per ROW, not per batch). Slow: pays a
    second model family's serving compiles; the tier-1 vector-position
    coverage for llama is the eager equivalence test in
    test_generation.py."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(7)
    cfg = llama_tiny(use_flash_attention=False)
    assert cfg.num_kv_heads < cfg.num_heads  # GQA, not MHA
    model = LlamaForCausalLM(cfg)
    model.eval()
    p0, p1 = _prompt(cfg, 9, 20), _prompt(cfg, 6, 21)
    solo0 = model.generate(p0[None], max_new_tokens=6, **GEO)[0]
    solo1 = model.generate(p1[None], max_new_tokens=4, do_sample=True,
                           temperature=0.8, seed=3, **GEO)[0]
    srv = InferenceServer(model, slots=2, **GEO)
    try:
        h0 = srv.submit(p0, max_new_tokens=6)
        time.sleep(0.1)  # h1 lands while h0 decodes
        h1 = srv.submit(p1, max_new_tokens=4, do_sample=True,
                        temperature=0.8, seed=3)
        np.testing.assert_array_equal(h0.result(timeout=300), solo0)
        np.testing.assert_array_equal(h1.result(timeout=300), solo1)
    finally:
        srv.shutdown(drain=True, timeout=60)


def test_hapi_model_serve(lm):
    """Model.serve() surface: tiny 1-slot server, result == generate."""
    from paddle_tpu.hapi import Model
    import paddle_tpu.nn as nn

    model, cfg = lm
    m = Model(model)
    p = _prompt(cfg, 7, 9)
    solo = model.generate(p[None], max_new_tokens=3, **GEO)[0]
    srv = m.serve(slots=1, **GEO)
    try:
        np.testing.assert_array_equal(
            srv.submit(p, max_new_tokens=3).result(timeout=300), solo)
    finally:
        srv.shutdown(drain=True, timeout=60)
    with pytest.raises(TypeError, match="cache_spec"):
        Model(nn.Linear(4, 4)).serve()


# NOTE: keep this LAST among the tests using the shared server — it
# retires it (graceful drain, then closed-for-business semantics).
def test_shutdown_drains_inflight_then_refuses(lm, server):
    model, cfg = lm
    solo = model.generate(_prompt(cfg, 8, 12)[None], max_new_tokens=8,
                          **GEO)[0]
    h = server.submit(_prompt(cfg, 8, 12), max_new_tokens=8)
    server.shutdown(drain=True, timeout=120)
    np.testing.assert_array_equal(h.result(timeout=1), solo)
    with pytest.raises(SchedulerClosed):
        server.submit(_prompt(cfg, 4), max_new_tokens=2)


# ------------------------------------------------------- device-free units
def test_scheduler_fifo_order_and_admission_rate():
    s = FifoScheduler(max_queue_depth=8, max_prefills_per_step=2)
    reqs = [Request(prompt=[1], id=i) for i in range(5)]
    for r in reqs:
        s.submit(r)
    admit, expired = s.take(free_slots=4)
    assert [r.id for r in admit] == [0, 1]  # K=2 caps the admission rate
    assert not expired
    admit2, _ = s.take(free_slots=1)        # free slots cap it too
    assert [r.id for r in admit2] == [2]
    s.requeue(admit[0])                     # crash recovery: head, not tail
    admit3, _ = s.take(free_slots=4)
    assert [r.id for r in admit3] == [0, 3]


def test_scheduler_backpressure_is_retryable():
    """QueueFull rides the stack's RetryPolicy like any transport
    failure: a client retrying with backoff gets in once depth frees."""
    s = FifoScheduler(max_queue_depth=1)
    s.submit(Request(prompt=[1]))
    with pytest.raises(QueueFull):
        s.submit(Request(prompt=[2]))
    calls = {"n": 0}

    def drain_then_submit():
        calls["n"] += 1
        if calls["n"] == 2:  # depth freed between attempts
            s.take(free_slots=1)
        s.submit(Request(prompt=[3]))
        return True

    assert RetryPolicy(max_attempts=4, base_delay=0.01).call(
        drain_then_submit)
    assert calls["n"] >= 2


def test_scheduler_deadline_sweep_and_seal():
    s = FifoScheduler(max_queue_depth=8)
    alive = Request(prompt=[1], deadline=Deadline(60))
    dead = Request(prompt=[2], deadline=Deadline(0.0))
    s.submit(alive)
    s.submit(dead)
    expired = s.pop_expired()
    assert [r is dead for r in expired] == [True]
    s.seal()
    with pytest.raises(SchedulerClosed):
        s.submit(Request(prompt=[3]))
    admit, _ = s.take(free_slots=2)  # sealed still drains
    assert admit == [alive]
    assert s.close() == []


def test_scatter_slice_cache_rows_roundtrip():
    """The slot-scatter primitives (generation.py): write a single-slot
    cache into the live batch at a traced index, slice it back out —
    bit-identical, other rows untouched. Eager: no compile cost."""
    import jax.numpy as jnp
    from paddle_tpu.models.generation import (scatter_cache_rows,
                                              slice_cache_rows)

    rng = np.random.default_rng(0)
    live = tuple((jnp.asarray(rng.normal(size=(3, 5, 2, 4)), jnp.float32),
                  jnp.asarray(rng.normal(size=(3, 5, 2, 4)), jnp.float32))
                 for _ in range(2))
    row = tuple((jnp.asarray(rng.normal(size=(1, 5, 2, 4)), jnp.float32),
                 jnp.asarray(rng.normal(size=(1, 5, 2, 4)), jnp.float32))
                for _ in range(2))
    out = scatter_cache_rows(live, row, jnp.int32(1))
    back = slice_cache_rows(out, jnp.int32(1))
    for (bk, bv), (rk, rv) in zip(back, row):
        np.testing.assert_array_equal(np.asarray(bk), np.asarray(rk))
        np.testing.assert_array_equal(np.asarray(bv), np.asarray(rv))
    for li, (lk, _) in enumerate(live):  # rows 0/2 untouched
        np.testing.assert_array_equal(np.asarray(out[li][0])[0],
                                      np.asarray(lk)[0])
        np.testing.assert_array_equal(np.asarray(out[li][0])[2],
                                      np.asarray(lk)[2])


def test_latency_histogram_reservoir_percentiles():
    h = LatencyHistogram(max_samples=64, seed=0)
    for v in range(1, 101):
        h.observe(v / 1000.0)
    s = h.summary()
    assert s["count"] == 100
    assert 0.020 <= s["p50_ms"] / 1000.0 <= 0.080  # sampled median ~0.05
    assert s["p99_ms"] >= s["p50_ms"]
    assert s["max_ms"] == pytest.approx(100.0)


def test_serving_metrics_occupancy_integral():
    m = ServingMetrics(slots=4)
    m.set_active_slots(4)
    time.sleep(0.05)
    m.set_active_slots(0)
    snap = m.snapshot()
    assert snap["slot_occupancy"] > 0.0
    m.inc("tokens_emitted", 10)
    assert m.snapshot()["tokens_per_sec"] > 0


def test_concurrent_submitters_thread_safety():
    """Many client threads submitting at once: scheduler stays
    consistent (device-free — a standalone scheduler, not the shared
    server, so this can run after shutdown)."""
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=64)
    errs = []

    def client(i):
        try:
            s.submit(Request(prompt=[i], id=i))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=client, args=(i,)) for i in range(32)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs and s.depth == 32
    seen = []
    while True:
        got, _ = s.take(free_slots=8)
        if not got:
            break
        seen.extend(r.id for r in got)
    assert sorted(seen) == list(range(32))


# ------------------------------------------------------------------- slow
@pytest.mark.slow
def test_serve_bench_cli_emits_percentile_json():
    """tools/serve_bench.py --check end-to-end on CPU: p50/p99 TTFT and
    inter-token latency, goodput, occupancy — and exit 0 (zero
    steady-state recompiles)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--check"],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(
        [l for l in proc.stdout.splitlines() if l.startswith('{"')][-1])
    assert rec["metric"] == "gpt_serve_requests_per_sec"
    assert rec["value"] > 0
    ex = rec["extra"]
    assert ex["goodput"] > 0
    assert ex["ttft_p99_ms"] >= ex["ttft_p50_ms"] > 0
    assert ex["inter_token_p99_ms"] >= ex["inter_token_p50_ms"] > 0
    assert 0.0 <= ex["slot_occupancy"] <= 1.0
    assert ex["decode_compiles"] == 1
    assert ex["steady_state_recompiles"] == 0
