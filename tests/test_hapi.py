"""hapi Model / callbacks / metric tests (reference test pattern:
``python/paddle/tests/test_model.py``, ``test_metrics.py``)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.hapi import EarlyStopping, History, Model, ScalarLogger
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall, accuracy
from paddle_tpu.optimizer import Adam


class MLP(nn.Layer):
    def __init__(self, in_dim=8, n_classes=4):
        super().__init__()
        self.fc1 = nn.Linear(in_dim, 16)
        self.fc2 = nn.Linear(16, n_classes)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def make_data(n=64, in_dim=8, n_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, in_dim)).astype(np.float32)
    y = rng.integers(0, n_classes, size=(n, 1)).astype(np.int64)
    return TensorDataset([x, y])


def test_model_fit_evaluate_predict(tmp_path):
    pt.seed(0)
    model = Model(MLP())
    model.prepare(optimizer=Adam(learning_rate=0.01),
                  loss=lambda logits, label: F.cross_entropy(logits, label),
                  metrics=Accuracy())
    train = make_data(64)
    val = make_data(32, seed=1)
    history = model.fit(train, val, batch_size=16, epochs=2, verbose=0)
    assert "loss" in history and len(history["loss"]) == 2

    res = model.evaluate(val, batch_size=16, verbose=0)
    assert "acc" in res and 0.0 <= res["acc"] <= 1.0
    assert "loss" in res

    test_x = np.random.default_rng(2).normal(size=(8, 8)).astype(np.float32)
    out = model.predict(TensorDataset([test_x]), batch_size=4, stack_outputs=True)
    assert out.shape == (8, 4)

    # save / load round trip
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams") and os.path.exists(path + ".pdopt")
    model2 = Model(MLP())
    model2.prepare(optimizer=Adam(learning_rate=0.01),
                   loss=lambda logits, label: F.cross_entropy(logits, label))
    model2.load(path)
    p1 = model.predict_batch(np.ones((2, 8), np.float32))
    p2 = model2.predict_batch(np.ones((2, 8), np.float32))
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_model_fit_decreases_loss():
    pt.seed(0)
    model = Model(MLP())
    model.prepare(optimizer=Adam(learning_rate=0.05),
                  loss=lambda logits, label: F.cross_entropy(logits, label))
    data = make_data(128)
    hist = model.fit(data, batch_size=32, epochs=5, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_early_stopping_and_scalar_logger(tmp_path):
    pt.seed(0)
    model = Model(MLP())
    model.prepare(optimizer=Adam(learning_rate=0.0),  # frozen -> no improvement
                  loss=lambda logits, label: F.cross_entropy(logits, label),
                  metrics=Accuracy())
    data = make_data(32)
    es = EarlyStopping(monitor="eval_loss", patience=0, verbose=0,
                       save_best_model=False)
    # EarlyStopping monitors eval logs; hapi fit merges eval logs with
    # an eval_ prefix into epoch logs, the callback reads on_eval_end logs
    es.monitor = "loss"
    logger = ScalarLogger(log_dir=str(tmp_path / "runs"), log_freq=1)
    model.fit(data, data, batch_size=16, epochs=5, verbose=0,
              callbacks=[es, logger])
    assert model.stop_training
    assert (tmp_path / "runs" / "scalars.jsonl").exists()


def test_summary_and_flops():
    net = MLP()
    info = pt.summary(net, (2, 8))
    # fc1: 8*16+16, fc2: 16*4+4
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    n_flops = pt.flops(net, (2, 8))
    assert n_flops >= 2 * 2 * (8 * 16 + 16 * 4)  # at least the matmul flops


def test_accuracy_metric():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32)
    label = np.array([[1], [2]])
    correct = m.compute(pred, label)
    m.update(np.asarray(correct))
    top1, top2 = m.accumulate()
    assert top1 == pytest.approx(0.5)
    assert top2 == pytest.approx(1.0)  # row1's label 2 is in its top-2
    m.reset()
    assert m.accumulate() == [0.0, 0.0]
    # functional
    acc = accuracy(pred, label, k=1)
    assert float(acc) == pytest.approx(0.5)


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    # predicted positive: 0.9,0.8,0.6 -> tp=2 fp=1; fn=1 (the 0.2)
    assert p.accumulate() == pytest.approx(2 / 3)
    assert r.accumulate() == pytest.approx(2 / 3)


def test_auc_metric():
    m = Auc(num_thresholds=255)
    rng = np.random.default_rng(0)
    # perfectly separable -> auc ~ 1
    pos = rng.uniform(0.8, 1.0, 100)
    neg = rng.uniform(0.0, 0.2, 100)
    m.update(np.concatenate([pos, neg]),
             np.concatenate([np.ones(100), np.zeros(100)]))
    assert m.accumulate() > 0.99
    # random -> auc ~ 0.5
    m.reset()
    m.update(rng.uniform(0, 1, 4000), rng.integers(0, 2, 4000))
    assert 0.4 < m.accumulate() < 0.6
