"""Profiler, flags, and NaN/Inf debugging tests (SURVEY.md §5 aux
subsystems)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import profiler
from paddle_tpu.framework import debugging, flags
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent, Timer,
                                 make_scheduler)


# ------------------------------------------------------------------ flags
def test_flags_roundtrip_and_unknown():
    assert flags.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    pt.set_flags({"FLAGS_check_nan_inf": 1})
    assert flags.flag("FLAGS_check_nan_inf") is True
    pt.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError):
        pt.set_flags({"FLAGS_nope": 1})
    with pytest.raises(ValueError):
        pt.get_flags("FLAGS_nope")
    assert "FLAGS_v" in pt.get_flags()


# ------------------------------------------------------------- debugging
def test_tree_all_finite_in_jit():
    good = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
    bad = {"a": jnp.asarray([1.0, np.nan]), "b": {"c": jnp.zeros(2)}}
    f = jax.jit(debugging.tree_all_finite)
    assert bool(f(good)) and not bool(f(bad))
    # int leaves are ignored
    assert bool(debugging.tree_all_finite({"i": jnp.arange(3)}))


def test_check_numerics_names_offender():
    bad = {"w": jnp.asarray([np.inf, 1.0]), "ok": jnp.ones(2)}
    with pytest.raises(FloatingPointError, match="w.*inf=1"):
        debugging.check_numerics(bad, "params")


def test_train_step_nan_check_flag():
    model = nn.Linear(4, 2)
    from paddle_tpu.optimizer import SGD

    step = pt.TrainStep(model, SGD(learning_rate=1e30),
                        loss_fn=lambda out, b: (out ** 2).mean())
    x = jnp.ones((2, 4), jnp.float32)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError):
            for _ in range(40):  # lr=1e30 overflows within a few steps
                step((x,))
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_train_step_nan_check_passes_when_clean():
    model = nn.Linear(4, 2)
    from paddle_tpu.optimizer import SGD

    step = pt.TrainStep(model, SGD(learning_rate=0.1),
                        loss_fn=lambda out, b: (out ** 2).mean())
    x = jnp.ones((2, 4), jnp.float32)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        l0 = float(step((x,)))
        l1 = float(step((x,)))
        assert np.isfinite(l0) and l1 < l0
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


# -------------------------------------------------------------- scheduler
def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states == [
        ProfilerState.CLOSED,          # skip_first
        ProfilerState.CLOSED,
        ProfilerState.READY,
        ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED,          # repeat exhausted
    ]


# ------------------------------------------------------------- host events
def test_record_event_summary():
    profiler._recorder.clear()
    profiler._recorder.enabled = True
    try:
        with RecordEvent("phase_a"):
            pass
        with RecordEvent("phase_a"):
            pass
        with RecordEvent("phase_b"):
            pass
    finally:
        profiler._recorder.enabled = False
    rows = profiler.host_event_summary()
    assert rows["phase_a"][0] == 2 and rows["phase_b"][0] == 1


def test_record_event_decorator():
    profiler._recorder.clear()
    profiler._recorder.enabled = True

    @RecordEvent("fn_span")
    def fn(x):
        return x + 1

    try:
        assert fn(1) == 2
    finally:
        profiler._recorder.enabled = False
    assert profiler.host_event_summary()["fn_span"][0] == 1


# ---------------------------------------------------------------- profiler
def test_profiler_trace_capture(tmp_path):
    tdir = str(tmp_path / "prof")
    p = Profiler(scheduler=make_scheduler(closed=0, ready=1, record=2,
                                          repeat=1),
                 on_trace_ready=profiler.export_chrome_tracing(tdir),
                 trace_dir=tdir)
    p.start()
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((64, 64))
    for _ in range(4):
        f(x).block_until_ready()
        p.step(num_samples=64)
    p.stop()
    text = p.summary()
    assert "steps/s" in text
    import os

    assert os.path.isdir(tdir) and any(os.scandir(tdir)), "no trace written"
    assert p.benchmark().ips() > 0


def test_timer_reports():
    t = Timer()
    t.begin()
    for _ in range(3):
        t.step(num_samples=10)
    t.end()
    assert t.steps_per_second() > 0
    assert t.ips() > 0
    assert "steps: 3" in t.report()


def test_nan_check_preserves_state():
    """On a bad step the update must be skipped in-graph: params stay at
    their pre-step values even with donated buffers."""
    from paddle_tpu.optimizer import SGD

    model = nn.Linear(4, 2)
    step = pt.TrainStep(model, SGD(learning_rate=0.1),
                        loss_fn=lambda out, b: (out * b[1]).mean())
    x = jnp.ones((2, 4), jnp.float32)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        step((x, jnp.ones((2, 2))))  # good step
        good_params = jax.tree.map(np.asarray, step.params)
        with pytest.raises(FloatingPointError, match="state preserved"):
            step((x, jnp.full((2, 2), np.nan)))  # poisoned batch
        for k in good_params:
            np.testing.assert_array_equal(np.asarray(step.params[k]),
                                          good_params[k])
        # recovery: a clean batch continues training from intact state
        loss = step((x, jnp.ones((2, 2))))
        assert np.isfinite(float(loss))
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})


def test_nan_check_distributed_step():
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.optimizer import SGD

    mesh = init_mesh(dp=8)
    model = nn.Linear(4, 2)
    step = DistributedTrainStep(
        model, SGD(learning_rate=0.1),
        loss_fn=lambda out, b: (out * b[1]).mean(), mesh=mesh,
        batch_axes=("dp",))
    x = jnp.ones((8, 4), jnp.float32)
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        step((x, jnp.ones((8, 2))))
        with pytest.raises(FloatingPointError):
            step((x, jnp.full((8, 2), np.nan)))
        assert all(np.isfinite(np.asarray(v)).all()
                   for v in step.params.values())
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})
