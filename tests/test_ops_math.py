"""Math/creation/manipulation op tests (reference pattern:
``test_*_op.py`` files under ``python/paddle/fluid/tests/unittests/``)."""
import numpy as np
import pytest

import paddle_tpu as pt
from op_test import OpTest, check_grad, check_output


class TestAdd(OpTest):
    def setup(self):
        self.fn = pt.add
        self.inputs = (np.random.rand(3, 4), np.random.rand(3, 4))
        self.ref = np.add

    def test_output(self):
        self.run_output_checks()

    def test_grad(self):
        self.run_grad_checks()


class TestMatmul(OpTest):
    def setup(self):
        self.fn = pt.matmul
        self.inputs = (np.random.rand(4, 5), np.random.rand(5, 3))
        self.ref = np.matmul
        self.grad_args = (0, 1)

    def test_output(self):
        self.run_output_checks()

    def test_grad(self):
        self.run_grad_checks()


def test_matmul_transpose_flags():
    x = np.random.rand(5, 4).astype(np.float32)
    y = np.random.rand(5, 3).astype(np.float32)
    check_output(lambda a, b: pt.matmul(a, b, transpose_x=True), (x, y), x.T @ y)
    x2 = np.random.rand(4, 5).astype(np.float32)
    y2 = np.random.rand(3, 5).astype(np.float32)
    check_output(lambda a, b: pt.matmul(a, b, transpose_y=True), (x2, y2), x2 @ y2.T)


@pytest.mark.parametrize("op,npop", [
    ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt), ("tanh", np.tanh),
    ("sin", np.sin), ("cos", np.cos), ("abs", np.abs), ("floor", np.floor),
    ("ceil", np.ceil), ("square", np.square), ("sign", np.sign),
])
def test_unary_ops(op, npop):
    x = np.random.rand(3, 5) + 0.5
    check_output(getattr(pt, op), (x.astype(np.float32),), npop(x))


@pytest.mark.parametrize("op,npop", [
    ("subtract", np.subtract), ("multiply", np.multiply), ("divide", np.divide),
    ("maximum", np.maximum), ("minimum", np.minimum), ("pow", np.power),
])
def test_binary_ops(op, npop):
    x = np.random.rand(3, 5) + 0.5
    y = np.random.rand(3, 5) + 0.5
    check_output(getattr(pt, op), (x.astype(np.float32), y.astype(np.float32)), npop(x, y))


@pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False), (1, True), ((0, 1), False)])
def test_reductions(axis, keepdim):
    x = np.random.rand(3, 4, 5)
    check_output(lambda a: pt.sum(a, axis=axis, keepdim=keepdim), (x.astype(np.float32),),
                 np.sum(x, axis=axis, keepdims=keepdim))
    check_output(lambda a: pt.mean(a, axis=axis, keepdim=keepdim), (x.astype(np.float32),),
                 np.mean(x, axis=axis, keepdims=keepdim))
    check_output(lambda a: pt.max(a, axis=axis, keepdim=keepdim), (x.astype(np.float32),),
                 np.max(x, axis=axis, keepdims=keepdim))


def test_cumsum_cumprod():
    x = np.random.rand(3, 4).astype(np.float32)
    check_output(lambda a: pt.cumsum(a, axis=1), (x,), np.cumsum(x, axis=1))
    check_output(lambda a: pt.cumsum(a), (x,), np.cumsum(x))
    check_output(lambda a: pt.cumprod(a, dim=0), (x,), np.cumprod(x, axis=0))


def test_cummax():
    x = np.random.rand(3, 6).astype(np.float32)
    vals, idx = pt.cummax(x, axis=1)
    np.testing.assert_allclose(np.asarray(vals), np.maximum.accumulate(x, axis=1), rtol=1e-6)


def test_clip_lerp():
    x = np.random.randn(3, 4).astype(np.float32)
    check_output(lambda a: pt.clip(a, -0.5, 0.5), (x,), np.clip(x, -0.5, 0.5))
    y = np.random.randn(3, 4).astype(np.float32)
    check_output(lambda a, b: pt.lerp(a, b, 0.3), (x, y), x + 0.3 * (y - x))


def test_creation():
    np.testing.assert_array_equal(np.asarray(pt.zeros([2, 3])), np.zeros((2, 3), np.float32))
    np.testing.assert_array_equal(np.asarray(pt.ones([2])), np.ones(2, np.float32))
    np.testing.assert_array_equal(np.asarray(pt.full([2, 2], 7.0)), np.full((2, 2), 7.0, np.float32))
    np.testing.assert_array_equal(np.asarray(pt.arange(1, 7, 2)), np.arange(1, 7, 2))
    assert pt.eye(3).shape == (3, 3)
    t = pt.tril(np.ones((3, 3)))
    np.testing.assert_array_equal(np.asarray(t), np.tril(np.ones((3, 3))))


def test_manipulation():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    assert pt.reshape(x, [6, 4]).shape == (6, 4)
    assert pt.flatten(x, 1, 2).shape == (2, 12)
    assert pt.transpose(x, [2, 0, 1]).shape == (4, 2, 3)
    assert pt.unsqueeze(x, [0, 2]).shape == (1, 2, 1, 3, 4)
    assert pt.squeeze(pt.unsqueeze(x, 0), 0).shape == (2, 3, 4)
    parts = pt.split(x, [1, 2], axis=1)
    assert parts[0].shape == (2, 1, 4) and parts[1].shape == (2, 2, 4)
    parts = pt.split(x, [1, -1], axis=1)
    assert parts[1].shape == (2, 2, 4)
    c = pt.concat([x, x], axis=0)
    assert c.shape == (4, 3, 4)
    s = pt.stack([x, x], axis=1)
    assert s.shape == (2, 2, 3, 4)
    assert pt.tile(x, [1, 2, 1]).shape == (2, 6, 4)
    assert pt.expand(np.ones((1, 3, 1)), [2, -1, 4]).shape == (2, 3, 4)


def test_gather_scatter():
    x = np.arange(20).reshape(4, 5).astype(np.float32)
    idx = np.array([0, 2])
    np.testing.assert_array_equal(np.asarray(pt.gather(x, idx, axis=0)), x[[0, 2]])
    upd = np.ones((2, 5), np.float32) * 100
    out = pt.scatter(x, idx, upd, overwrite=True)
    assert np.asarray(out)[0, 0] == 100
    out2 = pt.scatter(x, idx, upd, overwrite=False)
    assert np.asarray(out2)[0, 0] == 100  # zeroed then accumulated
    nd_idx = np.array([[0, 1], [2, 3]])
    np.testing.assert_array_equal(np.asarray(pt.gather_nd(x, nd_idx)), x[[0, 2], [1, 3]])


def test_where_topk_sort():
    x = np.random.rand(4, 6).astype(np.float32)
    y = np.zeros_like(x)
    cond = x > 0.5
    np.testing.assert_array_equal(np.asarray(pt.where(cond, x, y)), np.where(cond, x, y))
    vals, idx = pt.topk(x, k=3, axis=1)
    ref = np.sort(x, axis=1)[:, ::-1][:, :3]
    np.testing.assert_allclose(np.asarray(vals), ref, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(pt.sort(x, axis=1)), np.sort(x, axis=1))
    np.testing.assert_array_equal(np.asarray(pt.argsort(x, axis=1)), np.argsort(x, axis=1, kind="stable"))


def test_argmax_argmin():
    x = np.random.rand(3, 7).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(pt.argmax(x, axis=1)), np.argmax(x, axis=1))
    np.testing.assert_array_equal(np.asarray(pt.argmin(x)), np.argmin(x))


def test_linalg():
    a = np.random.rand(4, 4).astype(np.float32) + np.eye(4, dtype=np.float32) * 4
    check_output(pt.inverse, (a,), np.linalg.inv(a.astype(np.float64)), rtol=1e-3, atol=1e-4)
    check_output(pt.det, (a,), np.linalg.det(a.astype(np.float64)), rtol=1e-3, atol=1e-3)
    sym = a @ a.T
    w = pt.eigvalsh(sym)
    np.testing.assert_allclose(np.sort(np.asarray(w)), np.sort(np.linalg.eigvalsh(sym.astype(np.float64))),
                               rtol=1e-3)
    x = np.random.rand(2, 3, 4).astype(np.float32)
    y = np.random.rand(2, 4, 5).astype(np.float32)
    check_output(pt.bmm, (x, y), np.matmul(x, y), rtol=1e-4, atol=1e-5)
    check_output(lambda u, v: pt.einsum("bij,bjk->bik", u, v), (x, y), np.matmul(x, y),
                 rtol=1e-4, atol=1e-5)


def test_logic():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    y = np.array([1.0, 5.0, 2.0], np.float32)
    np.testing.assert_array_equal(np.asarray(pt.equal(x, y)), x == y)
    np.testing.assert_array_equal(np.asarray(pt.greater_than(x, y)), x > y)
    assert bool(pt.allclose(x, x))
    assert not bool(pt.allclose(x, y))


def test_grad_through_ops():
    x = np.random.rand(3, 4).astype(np.float32) + 0.5
    check_grad(lambda a: pt.log(pt.exp(a) + 1.0), [x])
    check_grad(lambda a: pt.mean(pt.square(a)), [x])


def test_random_ops_shapes():
    pt.seed(7)
    a = pt.randn([3, 4])
    assert a.shape == (3, 4)
    b = pt.uniform([10], min=2.0, max=3.0)
    arr = np.asarray(b)
    assert (arr >= 2.0).all() and (arr < 3.0).all()
    c = pt.randint(0, 10, [100])
    assert (np.asarray(c) < 10).all()
    p = pt.randperm(16)
    assert sorted(np.asarray(p).tolist()) == list(range(16))
    # determinism under same seed
    pt.seed(42)
    r1 = np.asarray(pt.randn([4]))
    pt.seed(42)
    r2 = np.asarray(pt.randn([4]))
    np.testing.assert_array_equal(r1, r2)


def test_stat():
    x = np.random.rand(3, 5)
    check_output(lambda a: pt.var(a, axis=1), (x.astype(np.float32),), np.var(x, axis=1, ddof=1))
    check_output(lambda a: pt.std(a), (x.astype(np.float32),), np.std(x, ddof=1))
    check_output(lambda a: pt.median(a, axis=0), (x.astype(np.float32),), np.median(x, axis=0))
