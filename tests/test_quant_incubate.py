"""Quantization (QAT/PTQ) and incubate (autograd, asp, LookAhead/
ModelAverage) tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import incubate, quantization as Q
from paddle_tpu.optimizer import SGD, Adam


# ------------------------------------------------------------ quantization
def test_quant_dequant_values_and_ste():
    x = jnp.asarray([-1.5, -0.4, 0.0, 0.3, 0.9, 2.0])
    scale = jnp.asarray(1.0)
    out = Q.quant_dequant(x, scale, 8)
    # in-range values round to the 127-level grid
    np.testing.assert_allclose(out[1], np.round(-0.4 * 127) / 127, rtol=1e-6)
    assert float(out[-1]) <= 1.0 + 1e-6  # clipped
    # STE: grad 1 inside [-scale, scale], 0 outside
    g = jax.grad(lambda x: Q.quant_dequant(x, scale, 8).sum())(x)
    np.testing.assert_array_equal(np.asarray(g), [0, 1, 1, 1, 1, 0])


def test_observers():
    obs = Q.AbsmaxObserver()
    s = obs.init_state()
    s = obs.update(s, jnp.asarray([1.0, -3.0]))
    s = obs.update(s, jnp.asarray([2.0]))
    assert float(obs.scale(s)) == 3.0
    ema = Q.MovingAverageAbsmaxObserver(momentum=0.5)
    s = ema.init_state()
    s = ema.update(s, jnp.asarray([4.0]))     # first adopts
    assert float(s) == 4.0
    s = ema.update(s, jnp.asarray([2.0]))
    assert float(s) == 3.0


def test_qat_swaps_and_trains():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Q.QAT().quantize(model)
    assert isinstance(model[0], Q.QuantedLinear)
    assert isinstance(model[2], Q.QuantedLinear)

    step = pt.TrainStep(model, Adam(learning_rate=1e-2),
                        loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 4, 32).astype(np.int32)
    losses = [float(step((x, y))) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # observer buffers moved off zero
    assert float(step.buffers["0.act_scale_state"]) > 0


def test_qat_eval_close_to_float():
    pt.seed(1)
    model = nn.Linear(8, 4)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8)), jnp.float32)
    want = np.asarray(model(x))
    qmodel = Q.QAT().quantize(model)
    qmodel.train()
    qmodel(x)  # one observer pass
    qmodel.eval()
    got = np.asarray(qmodel(x))
    assert np.abs(got - want).max() < 0.1  # int8 sim error is small


def test_ptq_calibrate_convert():
    pt.seed(2)
    base = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    ptq = Q.PTQ()
    model = ptq.quantize(base)
    rng = np.random.default_rng(2)
    for _ in range(4):
        model(jnp.asarray(rng.normal(size=(16, 8)), jnp.float32))
    model = ptq.convert(model)
    s_before = float(model[0].act_scale_state)
    model(jnp.asarray(rng.normal(size=(16, 8)) * 100, jnp.float32))
    assert float(model[0].act_scale_state) == s_before  # frozen
    # freeze survives a train() flip (convert is permanent)
    model.train()
    model(jnp.asarray(rng.normal(size=(16, 8)) * 100, jnp.float32))
    assert float(model[0].act_scale_state) == s_before


def test_uncalibrated_qat_passes_through():
    pt.seed(6)
    model = nn.Linear(8, 4)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(4, 8)), jnp.float32)
    want = np.asarray(model(x))
    q = Q.QAT().quantize(model)
    q.eval()  # never calibrated: activations pass through, weights quantize
    got = np.asarray(q(x))
    assert np.abs(got - want).max() < 0.1
    assert np.abs(got).max() > 1e-3  # not collapsed to zero


def test_qat_weight_scale_tracks_current_weights():
    model = nn.Linear(4, 4)
    q = Q.QAT().quantize(model)
    q.train()
    x = jnp.ones((2, 4), jnp.float32)
    q(x)
    # shrink weights 10x: quantized output must shrink accordingly (fresh
    # abs-max each forward, not a sticky running max)
    small = model.weight * 0.1
    model.weight = small
    out = q(x)
    direct = F.linear(x, np.asarray(small), model.bias)
    assert np.abs(np.asarray(out) - np.asarray(direct)).max() < 0.01


# ---------------------------------------------------------------- autograd
def test_jvp_vjp():
    f = lambda x: (x ** 2).sum()
    x = jnp.asarray([1.0, 2.0])
    out, tangent = incubate.autograd.jvp(f, x, jnp.asarray([1.0, 0.0]))
    assert float(out) == 5.0 and float(tangent) == 2.0
    out, grad = incubate.autograd.vjp(f, x)
    np.testing.assert_allclose(grad, [2.0, 4.0])


def test_jacobian_hessian():
    f = lambda x: jnp.asarray([x[0] * x[1], x[0] + x[1]])
    x = jnp.asarray([2.0, 3.0])
    J = incubate.autograd.Jacobian(f, x)
    np.testing.assert_allclose(J[:], [[3.0, 2.0], [1.0, 1.0]])
    assert J.shape == (2, 2)
    g = lambda x: (x[0] ** 2) * x[1]
    H = incubate.autograd.Hessian(g, x)
    np.testing.assert_allclose(H[:], [[6.0, 4.0], [4.0, 0.0]])
    np.testing.assert_allclose(incubate.autograd.hessian(g, x),
                               [[6.0, 4.0], [4.0, 0.0]])


def test_batched_jacobian():
    f = lambda x: x ** 2
    xs = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    J = incubate.autograd.Jacobian(f, xs, is_batched=True)
    assert J.shape == (2, 2, 2)
    np.testing.assert_allclose(J[0], [[2.0, 0], [0, 4.0]])


# --------------------------------------------------------------------- asp
def test_create_mask_2_4():
    w = np.asarray([[0.1, -0.5, 0.3, 0.05], [1.0, 0.2, -0.8, 0.6]])
    mask = incubate.asp.create_mask(w)
    np.testing.assert_array_equal(mask, [[0, 1, 1, 0], [1, 0, 1, 0]])
    assert incubate.asp.check_mask_2_4(w * mask)
    assert not incubate.asp.check_mask_2_4(np.ones((2, 4)))
    assert incubate.asp.calculate_density(w * mask) == 0.5
    # axis=0 pruning (the Linear reduction axis)
    mask0 = incubate.asp.create_mask(w.T, axis=0)
    np.testing.assert_array_equal(mask0, mask.T)
    assert incubate.asp.check_mask_2_4(w.T * mask0, axis=0)


def test_prune_model_and_training_keeps_sparsity():
    pt.seed(3)
    model = nn.Linear(8, 8)
    helper = incubate.asp.ASPHelper(model)
    # Linear weight is [in, out]: 2:4 along the reduction (input) axis,
    # matching reference ASP semantics
    assert incubate.asp.check_mask_2_4(np.asarray(model.weight), axis=0)
    step = pt.TrainStep(model, SGD(learning_rate=0.1),
                        loss_fn=lambda out, b: (out ** 2).mean(),
                        grad_transform=helper.mask_grads)
    x = np.random.default_rng(3).normal(size=(4, 8)).astype(np.float32)
    for _ in range(5):
        step((x,))
    w = np.asarray(step.params["weight"])
    assert incubate.asp.check_mask_2_4(w, axis=0)
    assert incubate.asp.calculate_density(w) <= 0.5


def test_prune_conv_weight():
    pt.seed(5)
    conv = nn.Conv2D(8, 4, 3)  # weight [4, 8, 3, 3]; in*kh*kw = 72 % 4 == 0
    masks = incubate.asp.prune_model(conv)
    assert "weight" in masks
    w = np.asarray(conv.weight).reshape(4, -1)
    assert incubate.asp.check_mask_2_4(w)


# --------------------------------------------------------- wrap optimizers
def test_lookahead_syncs_every_k():
    opt = incubate.LookAhead(SGD(learning_rate=1.0), alpha=0.5, k=2)
    params = {"w": jnp.asarray([0.0])}
    state = opt.init(params)
    g = {"w": jnp.asarray([1.0])}
    # step1: fast=-1, no sync; step2: fast=-2 -> slow=0.5*(-2)= -1, fast=-1
    params, state = opt.update(g, state, params)
    np.testing.assert_allclose(params["w"], [-1.0])
    params, state = opt.update(g, state, params)
    np.testing.assert_allclose(params["w"], [-1.0])
    np.testing.assert_allclose(state["slow"]["w"], [-1.0])


def test_model_average_apply():
    opt = incubate.ModelAverage(SGD(learning_rate=1.0),
                                max_average_window=100)
    params = {"w": jnp.asarray([0.0])}
    state = opt.init(params)
    g = {"w": jnp.asarray([1.0])}
    for _ in range(3):  # params go -1, -2, -3; sum = -6
        params, state = opt.update(g, state, params)
    avg = opt.apply(state)
    np.testing.assert_allclose(avg["w"], [-2.0])
    np.testing.assert_allclose(params["w"], [-3.0])


def test_lookahead_composes_with_train_step():
    pt.seed(4)
    model = nn.Linear(4, 2)
    opt = incubate.LookAhead(Adam(learning_rate=1e-2), k=3)
    step = pt.TrainStep(model, opt,
                        loss_fn=lambda out, b: (out ** 2).mean())
    x = np.random.default_rng(4).normal(size=(8, 4)).astype(np.float32)
    losses = [float(step((x,))) for _ in range(12)]
    assert losses[-1] < losses[0]


def test_prune_model_skips_embedding():
    emb = nn.Embedding(16, 8)
    model = nn.Sequential(emb, nn.Linear(8, 8))
    masks = incubate.asp.prune_model(model)
    assert list(masks) == ["1.weight"]  # only the Linear
    assert incubate.asp.calculate_density(np.asarray(emb.weight)) > 0.9


def test_ptq_calibrates_in_eval_mode():
    """Dropout must be inert during PTQ calibration: scales reflect
    inference ranges."""
    pt.seed(7)
    base = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.9), nn.Linear(8, 2))
    ptq = Q.PTQ()
    model = ptq.quantize(base)
    assert not model.training  # eval-mode calibration
    x = jnp.asarray(np.random.default_rng(7).normal(size=(64, 8)), jnp.float32)
    model(x)
    s = float(model[2].act_scale_state)
    assert s > 0
    # with dropout inert, repeated calibration is deterministic
    model2 = ptq.quantize(nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.9),
                                        nn.Linear(8, 2)))
    assert not model2.training
