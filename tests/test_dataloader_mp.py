"""Multiprocess DataLoader tests (reference ``_DataLoaderIterMultiProcess``
semantics: worker procs, order preservation, worker_init_fn, persistent
workers, iterable sharding via get_worker_info, error propagation)."""
import os
import time

import numpy as np
import pytest

from paddle_tpu.io import (DataLoader, Dataset, IterableDataset,
                           get_worker_info)


class RangeSquares(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i, i * i], np.int64)


class SlowDataset(Dataset):
    """Slow per-sample transform. ``time.sleep`` (not a busy loop) so the
    speedup test measures the loader's parallel pipeline rather than the
    machine's core count — CI may pin us to a single core, where a
    CPU-bound busy loop cannot speed up no matter what the loader does."""

    def __init__(self, n=48, ms=8.0):
        self.n = n
        self.ms = ms

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        time.sleep(self.ms / 1000.0)
        return np.asarray([i], np.int64)


class PidDataset(Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.asarray([os.getpid()], np.int64)


class ShardedCounter(IterableDataset):
    """Yields [start, stop) sharded across workers via get_worker_info."""

    def __init__(self, stop=40):
        self.stop = stop

    def __iter__(self):
        info = get_worker_info()
        if info is None:
            lo, step = 0, 1
        else:
            lo, step = info.id, info.num_workers
        for i in range(lo, self.stop, step):
            yield np.asarray([i], np.int64)


class BoomDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.asarray([i], np.int64)


def test_order_matches_single_process():
    ds = RangeSquares(64)
    single = [b for b in DataLoader(ds, batch_size=8, num_workers=0)]
    multi = [b for b in DataLoader(ds, batch_size=8, num_workers=3)]
    assert len(single) == len(multi) == 8
    for a, b in zip(single, multi):
        np.testing.assert_array_equal(a, b)


def test_work_really_runs_in_other_processes():
    pids = np.concatenate(
        [b.ravel() for b in DataLoader(PidDataset(), batch_size=2,
                                       num_workers=2)])
    assert os.getpid() not in set(pids.tolist())
    assert len(set(pids.tolist())) == 2


def test_slow_transform_overlaps_across_workers():
    """The workers must OVERLAP the per-sample transforms. Asserted
    against the serial lower bound — the sum of the blocking sleeps every
    sample performs — not against a measured single-thread run: the old
    ratio-of-two-timings version raced the CI scheduler on 2-core boxes
    (both measurements are noisy; their ratio doubly so). The sleeps
    release the GIL and need no CPU, so even a fully loaded single-core
    box can overlap them; finishing under the serial bound is impossible
    without concurrency in the loader."""
    n, ms, bs = 32, 30.0, 4
    ds = SlowDataset(n=n, ms=ms)
    # timed from the FIRST batch, so worker-pool startup (process spawn +
    # interpreter init, ~0.5s+ on a small box) is excluded; the serial
    # bound covers only the remaining samples
    serial_sleep_s = (n - bs) * ms / 1000.0

    best = None
    for _ in range(3):  # retries absorb scheduler noise
        it = iter(DataLoader(ds, batch_size=bs, num_workers=4))
        next(it)
        t0 = time.perf_counter()
        for _ in it:
            pass
        t_multi = time.perf_counter() - t0
        best = t_multi if best is None else min(best, t_multi)
        if best < 0.5 * serial_sleep_s:
            return
    assert best < 0.75 * serial_sleep_s, \
        f"draining a 4-worker epoch took {best:.2f}s vs a " \
        f"{serial_sleep_s:.2f}s serial sleep bound — the transforms " \
        f"did not overlap"


@pytest.mark.slow
def test_throughput_speedup_on_slow_transform():
    """The original >=2x-over-single-thread acceptance. Wall-clock ratio
    of two measured runs, so inherently racy on starved CI boxes —
    slow-marked; the tier-1 overlap property lives in
    ``test_slow_transform_overlaps_across_workers``."""
    ds = SlowDataset(n=64, ms=12.0)

    def measure():
        t0 = time.perf_counter()
        for _ in DataLoader(ds, batch_size=4, num_workers=0):
            pass
        t_single = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in DataLoader(ds, batch_size=4, num_workers=4):
            pass
        return t_single, time.perf_counter() - t0

    # three attempts with a decaying bar: a fully loaded CI box can
    # starve the worker pool of cores, which is scheduler noise rather
    # than a loader regression
    attempts = []
    for bar in (2.0, 2.0, 1.5):
        t_single, t_multi = measure()
        attempts.append((t_single / t_multi, t_single, t_multi))
        if max(a[0] for a in attempts) >= bar:
            return
    best = max(a[0] for a in attempts)
    assert best >= 1.5, \
        f"best speedup {best:.2f}x < 1.5x across attempts: " \
        f"{[(round(r, 2), round(a, 2), round(b, 2)) for r, a, b in attempts]}"


class EchoInitDataset(Dataset):
    """Echoes the env var a worker_init_fn sets — observable proof the init
    fn ran inside the worker process."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.asarray([int(os.environ.get("PT_TEST_WINIT", "-1"))],
                          np.int64)


def _set_winit(worker_id):
    os.environ["PT_TEST_WINIT"] = str(worker_id)


def test_worker_init_fn_runs_in_workers_only():
    seen = []

    def init(worker_id):
        seen.append(worker_id)

    dl = DataLoader(RangeSquares(8), batch_size=2, num_workers=2,
                    worker_init_fn=init)
    list(dl)
    assert seen == []  # did NOT run in the parent
    os.environ.pop("PT_TEST_WINIT", None)
    vals = np.concatenate([
        b.ravel() for b in DataLoader(EchoInitDataset(), batch_size=2,
                                      num_workers=2,
                                      worker_init_fn=_set_winit)])
    assert set(vals.tolist()) == {0, 1}  # DID run in each worker
    assert "PT_TEST_WINIT" not in os.environ


class RandomDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        import random

        return np.asarray([np.random.randint(0, 2 ** 31),
                           random.getrandbits(31)], np.int64)


def test_rng_differs_across_workers_and_epochs():
    dl = DataLoader(RandomDataset(), batch_size=8, num_workers=2)
    e1 = np.concatenate([b for b in dl])
    e2 = np.concatenate([b for b in dl])
    # both np.random and stdlib random must differ between epochs (fresh
    # base seed per pool) and produce diverse values within an epoch
    assert not np.array_equal(e1, e2)
    assert len(set(e1[:, 0].tolist())) > 1
    assert len(set(e1[:, 1].tolist())) > 1


def test_concurrent_iterators_non_persistent():
    ds = RangeSquares(32)
    dl = DataLoader(ds, batch_size=4, num_workers=2)
    a, b = iter(dl), iter(dl)
    out_a = [next(a) for _ in range(8)]
    out_b = [next(b) for _ in range(8)]
    expected = [x for x in DataLoader(ds, batch_size=4, num_workers=0)]
    for got in (out_a, out_b):
        for x, y in zip(expected, got):
            np.testing.assert_array_equal(x, y)


def test_persistent_second_iterator_invalidates_first():
    dl = DataLoader(RangeSquares(32), batch_size=4, num_workers=2,
                    persistent_workers=True)
    it1 = iter(dl)
    next(it1)
    it2 = iter(dl)
    with pytest.raises(RuntimeError, match="invalidated"):
        next(it1)
    assert len(list(it2)) == 8
    dl._shutdown_workers()


def test_persistent_workers_reuse_processes():
    ds = PidDataset()
    dl = DataLoader(ds, batch_size=2, num_workers=2, persistent_workers=True)
    pids1 = set(np.concatenate([b.ravel() for b in dl]).tolist())
    pids2 = set(np.concatenate([b.ravel() for b in dl]).tolist())
    assert pids1 == pids2
    dl._shutdown_workers()
    pids3 = set(np.concatenate([b.ravel() for b in dl]).tolist())
    assert pids3 != pids1


def test_fresh_workers_per_epoch_without_persistence():
    dl = DataLoader(PidDataset(), batch_size=2, num_workers=2)
    pids1 = set(np.concatenate([b.ravel() for b in dl]).tolist())
    pids2 = set(np.concatenate([b.ravel() for b in dl]).tolist())
    assert pids1 != pids2


def test_abandoned_iterator_then_new_epoch():
    """Breaking mid-epoch must not leak stale batches into the next epoch."""
    ds = RangeSquares(64)
    dl = DataLoader(ds, batch_size=8, num_workers=2, persistent_workers=True)
    it = iter(dl)
    next(it)
    next(it)  # abandon with outstanding credits
    batches = [b for b in dl]
    expected = [b for b in DataLoader(ds, batch_size=8, num_workers=0)]
    assert len(batches) == len(expected)
    for a, b in zip(expected, batches):
        np.testing.assert_array_equal(a, b)
    dl._shutdown_workers()


def test_iterable_dataset_sharded():
    dl = DataLoader(ShardedCounter(40), batch_size=4, num_workers=3)
    got = np.sort(np.concatenate([b.ravel() for b in dl]))
    np.testing.assert_array_equal(got, np.arange(40))


def test_iterable_dataset_single_process_parity():
    vals = np.concatenate([
        b.ravel() for b in DataLoader(ShardedCounter(20), batch_size=3,
                                      num_workers=0)])
    np.testing.assert_array_equal(np.sort(vals), np.arange(20))


def test_worker_exception_propagates():
    dl = DataLoader(BoomDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


class LocalBoomDataset(Dataset):
    """Raises an exception type that is NOT picklable (defined in a local
    scope) — the wrapper must still carry it to the parent."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        class LocalError(Exception):
            pass

        if i == 2:
            raise LocalError("unpicklable boom")
        return np.asarray([i], np.int64)


def test_unpicklable_worker_exception_still_propagates():
    dl = DataLoader(LocalBoomDataset(), batch_size=1, num_workers=2)
    with pytest.raises(RuntimeError, match="unpicklable boom"):
        list(dl)


class UnpicklableBatchDataset(Dataset):
    """collate output contains a lambda — unpicklable; must error loudly,
    not hang the parent on a reply lost in the queue feeder thread."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        return np.asarray([i], np.int64)


def test_unpicklable_batch_errors_instead_of_hanging():
    dl = DataLoader(UnpicklableBatchDataset(), batch_size=2, num_workers=1,
                    collate_fn=lambda batch: (np.stack(batch), lambda: 1))
    with pytest.raises(RuntimeError, match="pickle|Pickling"):
        list(dl)
