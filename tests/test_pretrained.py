"""Pretrained-weight path (VERDICT r4 missing #3): download + cache + md5
check + paddle-checkpoint loading, driven end-to-end against a fixture
checkpoint served over a real local HTTP URL.

Reference: ``python/paddle/utils/download.py`` and
``python/paddle/vision/models/resnet.py:356-363``.
"""
import functools
import hashlib
import http.server
import os
import pickle
import threading

import numpy as np
import pytest

import paddle_tpu.utils.download as dl
from paddle_tpu.hapi import weights as W
from paddle_tpu.models.resnet import resnet18


@pytest.fixture(scope="module")
def fixture_ckpt(tmp_path_factory):
    """A real resnet18 state_dict pickled the way paddle.save writes
    .pdparams (flat {name: ndarray}), served over local HTTP."""
    root = tmp_path_factory.mktemp("weights_srv")
    import paddle_tpu as pt

    pt.seed(123)
    src_model = resnet18()
    sd = {k: np.asarray(v) for k, v in src_model.state_dict().items()}
    path = root / "resnet18.pdparams"
    with open(path, "wb") as f:
        pickle.dump(sd, f, protocol=2)
    md5 = hashlib.md5(path.read_bytes()).hexdigest()

    handler = functools.partial(http.server.SimpleHTTPRequestHandler,
                                directory=str(root))
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/resnet18.pdparams"
    yield {"url": url, "md5": md5, "state": sd}
    srv.shutdown()


@pytest.fixture()
def weights_home(tmp_path, monkeypatch):
    home = tmp_path / "weights_home"
    monkeypatch.setattr(dl, "WEIGHTS_HOME", str(home))
    return home


def test_resnet18_pretrained_true_loads_real_weights(fixture_ckpt,
                                                     weights_home,
                                                     monkeypatch):
    monkeypatch.setitem(W.PRETRAINED_URLS, "resnet18",
                        (fixture_ckpt["url"], fixture_ckpt["md5"]))
    model = resnet18(pretrained=True)
    got = model.state_dict()
    for key, want in fixture_ckpt["state"].items():
        np.testing.assert_array_equal(np.asarray(got[key]), want,
                                      err_msg=key)
    # cached: the file landed in WEIGHTS_HOME and a second load reuses it
    assert (weights_home / "resnet18.pdparams").exists()
    resnet18(pretrained=True)


def test_custom_head_skips_fc_but_fills_backbone(fixture_ckpt, weights_home,
                                                 monkeypatch):
    monkeypatch.setitem(W.PRETRAINED_URLS, "resnet18",
                        (fixture_ckpt["url"], fixture_ckpt["md5"]))
    model = resnet18(pretrained=True, num_classes=7)
    got = model.state_dict()
    np.testing.assert_array_equal(np.asarray(got["conv1.weight"]),
                                  fixture_ckpt["state"]["conv1.weight"])
    assert got["fc.weight"].shape[-1] == 7  # head kept at its custom shape


def test_md5_mismatch_raises(fixture_ckpt, weights_home, monkeypatch):
    monkeypatch.setitem(W.PRETRAINED_URLS, "resnet18",
                        (fixture_ckpt["url"], "0" * 32))
    with pytest.raises(RuntimeError, match="md5|failed"):
        resnet18(pretrained=True)


def test_unknown_arch_raises(weights_home):
    from paddle_tpu.vision.models import vgg11

    with pytest.raises(ValueError, match="no pretrained weights"):
        vgg11(pretrained=True)


def test_structure_mismatch_raises(fixture_ckpt, weights_home, monkeypatch,
                                   tmp_path):
    # a checkpoint missing most of the backbone must raise, not silently
    # leave random weights
    partial = {"conv1.weight": fixture_ckpt["state"]["conv1.weight"]}
    p = tmp_path / "partial.pdparams"
    with open(p, "wb") as f:
        pickle.dump(partial, f, protocol=2)
    monkeypatch.setitem(W.PRETRAINED_URLS, "resnet18",
                        (f"file://{p}", None))
    with pytest.raises(ValueError, match="missing"):
        resnet18(pretrained=True)


def test_utils_helpers():
    """reference paddle.utils __all__: deprecated/run_check/
    require_version/try_import (python/paddle/utils/__init__.py:31)."""
    import warnings

    import paddle_tpu.utils as U

    @U.deprecated(since="0.1", update_to="paddle_tpu.new_api")
    def old_api(x):
        return x + 1

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert old_api(1) == 2
    assert any(issubclass(w.category, DeprecationWarning) for w in rec)
    assert "Deprecated" in old_api.__doc__

    U.run_check()  # prints success on the virtual mesh; must not raise
    U.require_version("0.0.1")
    with pytest.raises(Exception, match="<|minimum"):
        U.require_version("999.0")
    assert U.try_import("math").sqrt(4) == 2.0
    with pytest.raises(ImportError, match="no_such_module_xyz"):
        U.try_import("no_such_module_xyz")
