"""Self-healing training: numerics watchdog (in-graph skip + batched host
sync), auto-rollback with deterministic data replay, hang/preemption
supervision, GradScaler skip accounting, and the recovery-equivalence
guarantees (SIGTERM mid-fit resumes to bit-identical weights; rollback
after injected NaN batches converges).

Tier-1-lean by design (the suite nearly fills its 870 s budget): the
equivalence tests run IN-PROCESS on tiny models — the real SIGTERM handler
is exercised by signalling ourselves — and the full subprocess
kill/stall/NaN soak is delegated to ``tools/chaos_soak.py`` (smoke-run
here under the ``slow`` marker).
"""
import os
import signal
import subprocess
import sys
import tempfile
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, profiler
from paddle_tpu.amp import GradScaler
from paddle_tpu.distributed.resilience import (CRASH_EXIT, EXIT_PREEMPTED,
                                               FaultPlan)
from paddle_tpu.framework.supervisor import (HangWatchdog, RecoveryPolicy,
                                             TrainingPreempted)
from paddle_tpu.hapi import Callback, Model
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.optimizer import AdamW

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Lin(nn.Layer):
    # dropout ON: resume equivalence must reproduce the per-step RNG
    # streams (restored base_key + count), not just the weights
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 8)
        self.drop = nn.Dropout(0.2)
        self.out = nn.Linear(8, 1)

    def forward(self, x):
        return self.out(self.drop(self.fc(x)))


def _mse(out, y):
    return ((out - y) ** 2).mean()


def _lin_data(n=24):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    w = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    return pt.io.TensorDataset([x, (x @ w).astype(np.float32)])


def _lin_model():
    m = Model(_Lin())
    m.prepare(AdamW(learning_rate=1e-2), loss=_mse)
    return m


def _policy(d, **kw):
    base = dict(checkpoint_dir=d, save_interval_steps=4, check_interval=2,
                max_consecutive=2, async_save=False, grace_seconds=10.0)
    base.update(kw)
    return RecoveryPolicy(**base)


# ------------------------------------------------------- numerics watchdog
def test_single_nan_batch_skipped_not_rolled_back(tmp_path):
    """One poisoned batch: the in-graph guard skips the update, the
    watchdog counts the anomaly, training continues — no rollback."""
    pt.seed(7)
    m = _lin_model()
    profiler.reset_counters()
    anomalies = []

    class Rec(Callback):
        def on_train_anomaly(self, logs=None):
            anomalies.append(logs)

    plan = FaultPlan([{"site": "train.data", "kind": "drop", "times": 1,
                       "after": 3}], seed=5)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with plan:
            hist = m.fit(_lin_data(), batch_size=4, epochs=1, shuffle=False,
                         verbose=0, callbacks=[Rec()],
                         recovery=_policy(str(tmp_path), max_consecutive=3))
    assert plan.fired[0] == 1
    c = profiler.counter_values()
    assert c.get("train.anomaly") == 1
    assert "train.rollback" not in c
    assert anomalies and anomalies[0]["batch_index"] == 3
    assert np.isfinite(hist["loss"][-1])
    for v in m._train_step.params.values():
        assert np.isfinite(np.asarray(v)).all()


def test_consecutive_anomalies_rollback_replay_and_converge(tmp_path):
    """K consecutive NaN batches escalate to rollback: state is restored
    from the verified checkpoint, the data cursor rewinds, skip_window
    jumps the offending batches, and training converges to (near) the
    fault-free answer."""
    ds = _lin_data(32)

    def run(d, plan=None):
        pt.seed(7)
        m = _lin_model()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            if plan is None:
                hist = m.fit(ds, batch_size=4, epochs=2, shuffle=False,
                             verbose=0, recovery=_policy(d, skip_window=2))
            else:
                with plan:
                    hist = m.fit(ds, batch_size=4, epochs=2, shuffle=False,
                                 verbose=0,
                                 recovery=_policy(d, skip_window=2))
        return m, hist

    with tempfile.TemporaryDirectory() as d:
        _, clean_hist = run(d)
    profiler.reset_counters()
    rollbacks = []

    class Rec(Callback):
        def on_rollback(self, logs=None):
            rollbacks.append(logs)

    plan = FaultPlan([{"site": "train.data", "kind": "drop", "times": 2,
                       "after": 5}], seed=5)
    pt.seed(7)
    m = _lin_model()
    with tempfile.TemporaryDirectory() as d:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with plan:
                hist = m.fit(ds, batch_size=4, epochs=2, shuffle=False,
                             verbose=0, callbacks=[Rec()],
                             recovery=_policy(d, skip_window=2))
    c = profiler.counter_values()
    assert c.get("train.rollback") == 1
    assert c.get("train.anomaly", 0) >= 2
    assert c.get("train.batch_skip") == 2      # skip_window honored
    assert rollbacks and rollbacks[0]["rollbacks"] == 1
    # converged: the faulted run lands in the fault-free run's ballpark —
    # it legitimately skipped 2 batches of a 16-step dropout run, so a
    # tight bound would test luck, not recovery (the 1%-after-plateau
    # guarantee is chaos_soak's job, with enough steps to mean something)
    clean, faulted = clean_hist["loss"][-1], hist["loss"][-1]
    assert np.isfinite(faulted)
    assert abs(faulted - clean) / abs(clean) < 0.25


def test_scaler_inf_skip_distinct_from_watchdog_anomaly(tmp_path):
    """An inf-grad overflow under GradScaler skips the update and is
    accounted on the scaler (skipped_step_count/last_overflow_step), NOT
    as a watchdog anomaly — end-to-end under Model.fit."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    # batch 2 (samples 8..11) overflows the SCALED grads while the raw
    # loss stays finite: |loss| ~ 1e35 < f32 max, grads*2^15 -> inf
    x[8:12] = 1e35
    y = np.ones((16, 1), np.float32)
    ds = pt.io.TensorDataset([x, y])

    pt.seed(3)
    scaler = GradScaler(init_loss_scaling=2.0 ** 15,
                        decr_every_n_nan_or_inf=1)
    m = Model(_Lin())
    m.prepare(AdamW(learning_rate=1e-3),
              loss=lambda out, y: (out * y).mean(),
              amp_configs={"scaler": scaler})
    profiler.reset_counters()
    m.fit(ds, batch_size=4, epochs=1, shuffle=False, verbose=0,
          recovery=_policy(str(tmp_path)))
    assert scaler.skipped_step_count == 1
    assert scaler.last_overflow_step == 3      # 1-based update index
    assert scaler.get_loss_scaling() == 2.0 ** 14   # backed off once
    c = profiler.counter_values()
    assert c.get("train.scaler_skip") == 1
    assert "train.anomaly" not in c            # NOT an anomaly
    for v in m._train_step.params.values():
        assert np.isfinite(np.asarray(v)).all()


def test_gradscaler_counters_without_recovery():
    """The fused scaler path counts skips in a plain fit too (no watchdog
    required) — the lazy flags force only when the counters are read."""
    x = np.ones((8, 4), np.float32)
    x[4:] = 1e35
    y = np.ones((8, 1), np.float32)
    pt.seed(3)
    scaler = GradScaler(init_loss_scaling=2.0 ** 15,
                        decr_every_n_nan_or_inf=1)
    m = Model(_Lin())
    m.prepare(AdamW(learning_rate=1e-3),
              loss=lambda out, y: (out * y).mean(),
              amp_configs={"scaler": scaler})
    m.fit(pt.io.TensorDataset([x, y]), batch_size=4, epochs=1,
          shuffle=False, verbose=0)
    assert scaler.skipped_step_count == 1
    assert scaler.last_overflow_step == 2


def test_scaler_guard_escalates_nonfinite_grads_at_scale_one():
    """Nonfinite grads under a FINITE loss are benign overflow only while
    scale > 1; at scale 1 there is no scaling left to blame, so the guard
    classifies them as an anomaly (else persistent NaN grads would skip
    every update forever without ever alarming the watchdog)."""
    import jax.numpy as jnp

    from paddle_tpu.amp.grad_scaler import init_scale_state
    from paddle_tpu.framework.jit import scaler_guard

    new = ({"w": jnp.ones(2)},)
    old = ({"w": jnp.zeros(2)},)
    loss, found = jnp.float32(1.0), jnp.asarray(True)
    (sel,), _, ok, found_inf = scaler_guard(
        loss, found, init_scale_state(2.0 ** 4), new, old)
    assert bool(ok) and bool(found_inf)          # overflow: benign skip
    np.testing.assert_array_equal(np.asarray(sel["w"]), 0.0)
    (sel,), _, ok, found_inf = scaler_guard(
        loss, found, init_scale_state(1.0), new, old)
    assert not bool(ok) and not bool(found_inf)  # scale 1: anomaly
    np.testing.assert_array_equal(np.asarray(sel["w"]), 0.0)
    # finite everything passes the update through
    (sel,), _, ok, found_inf = scaler_guard(
        loss, jnp.asarray(False), init_scale_state(1.0), new, old)
    assert bool(ok) and not bool(found_inf)
    np.testing.assert_array_equal(np.asarray(sel["w"]), 1.0)


# ------------------------------------------------ preemption + equivalence
def _gpt_model():
    # dropout ON: proves the restored base_key + count reproduce the
    # per-step RNG streams bit-exactly across the preemption boundary
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
                    max_position_embeddings=16, hidden_dropout_prob=0.1,
                    attention_dropout_prob=0.1, use_flash_attention=False)
    m = Model(GPTForCausalLM(cfg), labels=[])   # forward(ids, labels) -> loss
    m.prepare(AdamW(learning_rate=1e-3))
    return m


def _gpt_data(n=16):
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 32, (n, 16)).astype(np.int32)
    return pt.io.TensorDataset([ids, ids])


class _KillAt(Callback):
    """Deliver a real SIGTERM to ourselves after the N-th batch GLOBALLY
    (the actual handler and checkpoint-and-exit path run, not a
    simulation)."""

    def __init__(self, at):
        super().__init__()
        self.at = at
        self.seen = 0
        self.fired = False

    def on_train_batch_end(self, step, logs=None):
        self.seen += 1
        if not self.fired and self.seen == self.at:
            self.fired = True
            os.kill(os.getpid(), signal.SIGTERM)


def _sigterm_equivalence(tmp_path, make_model, data, kill_at):
    """SIGTERM mid-fit checkpoints under the grace deadline and raises
    TrainingPreempted; a fresh model resuming from the same recovery dir
    finishes with weights BIT-IDENTICAL to an uninterrupted run (same
    optimizer trajectory, same dropout streams via the restored
    base_key/count, same data via the cursor)."""
    def run(d, kill=None):
        pt.seed(11)
        m = make_model()
        cbs = [_KillAt(kill)] if kill is not None else None
        try:
            m.fit(data, batch_size=4, epochs=2, shuffle=False, verbose=0,
                  callbacks=cbs,
                  recovery=_policy(d, save_interval_steps=3))
        except TrainingPreempted as e:
            assert e.saved
            return m, False
        return m, True

    d_ref = str(tmp_path / "ref")
    d_kill = str(tmp_path / "kill")
    m_ref, done = run(d_ref)
    assert done
    preempt_seen = []

    class Rec(_KillAt):
        def on_preemption(self, logs=None):
            preempt_seen.append(logs)

    pt.seed(11)
    m1 = make_model()
    with pytest.raises(TrainingPreempted):
        m1.fit(data, batch_size=4, epochs=2, shuffle=False, verbose=0,
               callbacks=[Rec(kill_at)],
               recovery=_policy(d_kill, save_interval_steps=3))
    assert preempt_seen and preempt_seen[0]["saved"]
    # resume in a fresh model: restores weights/opt/count/base_key + cursor
    m2, done = run(d_kill)
    assert done
    w_ref = {k: np.asarray(v) for k, v in m_ref._train_step.params.items()}
    w_res = {k: np.asarray(v) for k, v in m2._train_step.params.items()}
    assert w_ref.keys() == w_res.keys()
    for k in w_ref:
        np.testing.assert_array_equal(w_ref[k], w_res[k], err_msg=k)


def test_sigterm_mid_fit_resumes_bit_identical(tmp_path):
    """Tier-1 fast variant: dropout MLP, kill mid-epoch-1 (5th batch)."""
    _sigterm_equivalence(tmp_path, _lin_model, _lin_data(16), kill_at=5)


@pytest.mark.slow
def test_sigterm_resume_bit_identical_gpt(tmp_path):
    """Soak variant on the small GPT (attention + tied embeddings +
    dropout): same bit-identity guarantee, heavier compiles."""
    _sigterm_equivalence(tmp_path, _gpt_model, _gpt_data(), kill_at=5)


def test_old_checkpoint_without_cursor_still_loads(tmp_path):
    """Pre-cursor checkpoints (PR 1-5 era) restore fine: the cursor is
    treated as unknown and the data stream restarts at epoch 0."""
    from paddle_tpu.distributed.checkpoint import save_state
    from paddle_tpu.framework.supervisor import TrainingSupervisor

    pt.seed(5)
    m = _lin_model()
    step = m._ensure_train_step()
    l0 = float(step((np.ones((4, 4), np.float32),
                     np.ones((4, 1), np.float32)))[0])
    old_style = dict(step.state_dict())
    old_style.pop("base_key")          # old checkpoints had neither
    save_state(old_style, str(tmp_path / "step_7"))

    pt.seed(5)
    m2 = _lin_model()
    sup = TrainingSupervisor(m2._ensure_train_step(),
                             _policy(str(tmp_path)))
    cursor = sup.restore()
    assert cursor is None              # unknown cursor -> epoch restart
    assert m2._train_step._count == step._count
    for k, v in step.params.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(m2._train_step.params[k]))


# ----------------------------------------------------------- hang watchdog
def test_hang_watchdog_detects_stall_and_rearms():
    profiler.reset_counters()
    seen = []
    wd = HangWatchdog(step_timeout=0.15, action="warn",
                      on_hang=lambda el: seen.append(el)).start()
    try:
        wd.beat()
        deadline = time.monotonic() + 5.0
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            while not seen and time.monotonic() < deadline:
                time.sleep(0.05)      # no beats: a "hung" step
        assert seen and seen[0] >= 0.15
        assert wd.hangs_detected == 1
        assert profiler.counter_values().get("train.hang") == 1
        # fires once per incident; a beat re-arms it
        time.sleep(0.3)
        assert wd.hangs_detected == 1
        wd.beat()
        wd.pause()                    # paused: no false positive either
        time.sleep(0.3)
        assert wd.hangs_detected == 1
    finally:
        wd.stop()


def test_fit_counts_injected_stall_as_hang(tmp_path):
    """A FaultPlan delay at train.step past step_timeout is detected."""
    pt.seed(7)
    m = _lin_model()
    profiler.reset_counters()
    plan = FaultPlan([{"site": "train.step", "kind": "delay", "delay": 0.6,
                       "times": 1, "after": 3}], seed=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with plan:
            m.fit(_lin_data(16), batch_size=4, epochs=1, shuffle=False,
                  verbose=0,
                  recovery=_policy(str(tmp_path), step_timeout=0.2))
    assert plan.fired[0] == 1
    assert profiler.counter_values().get("train.hang", 0) >= 1


# ------------------------------------------------------- distributed parity
def test_distributed_watchdog_poison_preserves_sharded_state():
    from paddle_tpu.distributed import DistributedTrainStep, init_mesh

    pt.seed(9)
    init_mesh({"dp": 4, "mp": 2})
    step = DistributedTrainStep(
        _Lin(), AdamW(learning_rate=1e-2),
        loss_fn=lambda out, batch: ((out - batch[1]) ** 2).mean())
    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    y = np.ones((8, 1), np.float32)
    loss, ok, found = step.watchdog_call((x, y))
    assert bool(ok) and not bool(found) and np.isfinite(float(loss))
    before = {k: np.asarray(v) for k, v in step.params.items()}
    step.inject_anomaly()
    loss, ok, found = step.watchdog_call((x, y))
    assert not bool(ok) and np.isnan(float(np.asarray(loss)))
    for k, v in step.params.items():   # sharded state kept consistent
        np.testing.assert_array_equal(before[k], np.asarray(v))
    sd = step.state_dict()
    assert "base_key" in sd and "base_key" in step.state_shardings()


# ------------------------------------------------------------ data cursor
def test_data_cursor_roundtrip_and_resume():
    from paddle_tpu.io.cursor import DataCursor, resume_batches

    c = DataCursor(epoch=2, batch_index=5, epoch_seed=3, global_step=37)
    assert DataCursor.from_state(c.as_state()) == c
    assert DataCursor.from_state(None) is None

    loader = pt.io.DataLoader(_lin_data(20), batch_size=4, shuffle=False)
    full = [np.asarray(b[0]) for b in loader]
    resumed = [np.asarray(b[0]) for b in resume_batches(loader, 2)]
    assert len(resumed) == len(full) - 2
    for a, b in zip(full[2:], resumed):
        np.testing.assert_array_equal(a, b)
    # past-the-end cursor -> empty epoch, not an error
    assert list(resume_batches(loader, 99)) == []


# ------------------------------------------------------------ launch + soak
def test_launcher_recognizes_preemption_exits():
    from argparse import Namespace

    from paddle_tpu.distributed.launch.main import (_MAX_PREEMPT_RESTARTS,
                                                    _note_preemption)

    args = Namespace()
    assert not _note_preemption(args, 1)          # plain failure: charged
    assert not _note_preemption(args, CRASH_EXIT)
    for i in range(_MAX_PREEMPT_RESTARTS):
        assert _note_preemption(args, EXIT_PREEMPTED)
    assert not _note_preemption(args, EXIT_PREEMPTED)  # cap reached


@pytest.mark.slow
def test_chaos_soak_quick_passes():
    """The full kill/stall/NaN soak (3 subprocesses, ~60 s): final loss
    within 1% of the fault-free run, all faults observed, no steady-state
    recompiles."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_soak.py"),
         "--quick"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=800)
    assert p.returncode == 0, p.stdout[-3000:]
    assert "PASS" in p.stdout
