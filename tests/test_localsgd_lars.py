"""LARS + LocalSGD meta-optimizer tests (reference
``fleet/meta_optimizers/lars_optimizer.py`` / ``localsgd_optimizer.py``)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.distributed.parallel.localsgd import LocalSGDStep
from paddle_tpu.optimizer import LarsMomentum, Momentum, SGD

RNG = np.random.default_rng(3)


def test_lars_trust_ratio_math():
    p0 = RNG.normal(size=(4, 4)).astype(np.float32)
    g = RNG.normal(size=(4, 4)).astype(np.float32)
    lr, mu, coeff, wd, eps = 0.1, 0.9, 0.001, 0.0005, 1e-8
    opt = LarsMomentum(learning_rate=lr, momentum=mu, lars_coeff=coeff,
                       lars_weight_decay=wd, epsilon=eps)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    new_params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    # manual reference
    p_norm = np.linalg.norm(p0)
    g_norm = np.linalg.norm(g)
    local_lr = coeff * p_norm / (g_norm + wd * p_norm + eps)
    v = lr * local_lr * (g + wd * p0)
    np.testing.assert_allclose(np.asarray(new_params["w"]), p0 - v,
                               rtol=1e-5)
    # second step uses momentum
    new2, _ = opt.update({"w": jnp.asarray(g)}, state, new_params)
    p1 = np.asarray(new_params["w"])
    local_lr2 = (coeff * np.linalg.norm(p1)
                 / (g_norm + wd * np.linalg.norm(p1) + eps))
    v2 = mu * v + lr * local_lr2 * (g + wd * p1)
    np.testing.assert_allclose(np.asarray(new2["w"]), p1 - v2, rtol=1e-4)


def test_fleet_lars_wraps_momentum():
    s = DistributedStrategy()
    s.lars = True
    s.lars_configs = {"lars_coeff": 0.002}
    fleet.init(strategy=s)
    opt = fleet.distributed_optimizer(Momentum(learning_rate=0.1))
    assert isinstance(opt, LarsMomentum)
    assert opt.lars_coeff == 0.002
    # non-momentum optimizers pass through
    sgd = fleet.distributed_optimizer(SGD(learning_rate=0.1))
    assert type(sgd) is SGD


class TinyNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 1, bias_attr=False)

    def forward(self, x):
        return self.fc(x)


def _mse(out, batch):
    return jnp.mean((out - batch[1]) ** 2)


def test_localsgd_matches_manual_simulation():
    """4 replicas, k_steps=2, SGD: replicas must diverge between syncs and
    equal the average of independently-simulated locals at a sync."""
    mesh = init_mesh(dp=4)
    net = TinyNet()
    w0 = np.asarray(net.fc.weight).copy()  # [4, 1]
    step = LocalSGDStep(net, SGD(learning_rate=0.1), loss_fn=_mse,
                        mesh=mesh, k_steps=2)
    xs = RNG.normal(size=(4, 8, 4)).astype(np.float32)  # 4 steps, B=8
    ys = RNG.normal(size=(4, 8, 1)).astype(np.float32)

    # manual numpy simulation: replica r sees batch shard r
    w_rep = np.repeat(w0[None], 4, axis=0)  # [4, 4, 1]

    def manual_step(w, x, y):
        pred = x @ w
        grad = 2 * x.T @ (pred - y) / x.shape[0]
        return w - 0.1 * grad

    for t in range(4):
        loss = step((jnp.asarray(xs[t]), jnp.asarray(ys[t])))
        for r in range(4):
            sl = slice(r * 2, (r + 1) * 2)
            w_rep[r] = manual_step(w_rep[r], xs[t][sl], ys[t][sl])
        if (t + 1) % 2 == 0:
            w_rep[:] = w_rep.mean(axis=0)
        got = np.asarray(step.replica_params()["fc.weight"])  # [4, 4, 1]
        np.testing.assert_allclose(got, w_rep, rtol=1e-4, atol=1e-5,
                                   err_msg=f"step {t}")
        if (t + 1) % 2 == 1:
            # between syncs replicas genuinely diverge
            assert not np.allclose(got[0], got[1])
        else:
            np.testing.assert_allclose(got[0], got[3], rtol=1e-5)
    # averaged params + sync_to_model
    step.sync_to_model()
    np.testing.assert_allclose(np.asarray(net.fc.weight),
                               w_rep.mean(axis=0), rtol=1e-4, atol=1e-6)


def test_fleet_localsgd_dispatch():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 3}
    fleet.init(strategy=s)
    step = fleet.distributed_model(TinyNet(), SGD(learning_rate=0.1),
                                   loss_fn=_mse)
    assert isinstance(step, LocalSGDStep) and step.k_steps == 3
    x = jnp.asarray(RNG.normal(size=(8, 4)).astype(np.float32))
    y = jnp.asarray(RNG.normal(size=(8, 1)).astype(np.float32))
    losses = [float(step((x, y))) for _ in range(9)]
    assert losses[-1] < losses[0]
