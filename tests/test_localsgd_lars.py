"""LARS + LocalSGD meta-optimizer tests (reference
``fleet/meta_optimizers/lars_optimizer.py`` / ``localsgd_optimizer.py``)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.distributed.parallel.localsgd import LocalSGDStep
from paddle_tpu.optimizer import LarsMomentum, Momentum, SGD

RNG = np.random.default_rng(3)


def test_lars_trust_ratio_math():
    p0 = RNG.normal(size=(4, 4)).astype(np.float32)
    g = RNG.normal(size=(4, 4)).astype(np.float32)
    lr, mu, coeff, wd, eps = 0.1, 0.9, 0.001, 0.0005, 1e-8
    opt = LarsMomentum(learning_rate=lr, momentum=mu, lars_coeff=coeff,
                       lars_weight_decay=wd, epsilon=eps)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    new_params, state = opt.update({"w": jnp.asarray(g)}, state, params)
    # manual reference
    p_norm = np.linalg.norm(p0)
    g_norm = np.linalg.norm(g)
    local_lr = coeff * p_norm / (g_norm + wd * p_norm + eps)
    v = lr * local_lr * (g + wd * p0)
    np.testing.assert_allclose(np.asarray(new_params["w"]), p0 - v,
                               rtol=1e-5)
    # second step uses momentum
    new2, _ = opt.update({"w": jnp.asarray(g)}, state, new_params)
    p1 = np.asarray(new_params["w"])
    local_lr2 = (coeff * np.linalg.norm(p1)
                 / (g_norm + wd * np.linalg.norm(p1) + eps))
    v2 = mu * v + lr * local_lr2 * (g + wd * p1)
    np.testing.assert_allclose(np.asarray(new2["w"]), p1 - v2, rtol=1e-4)


def test_fleet_lars_wraps_momentum():
    s = DistributedStrategy()
    s.lars = True
    s.lars_configs = {"lars_coeff": 0.002}
    fleet.init(strategy=s)
    opt = fleet.distributed_optimizer(Momentum(learning_rate=0.1))
    assert isinstance(opt, LarsMomentum)
    assert opt.lars_coeff == 0.002
    # non-momentum optimizers pass through
    sgd = fleet.distributed_optimizer(SGD(learning_rate=0.1))
    assert type(sgd) is SGD


class TinyNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 1, bias_attr=False)

    def forward(self, x):
        return self.fc(x)


def _mse(out, batch):
    return jnp.mean((out - batch[1]) ** 2)


def test_localsgd_matches_manual_simulation():
    """4 replicas, k_steps=2, SGD: replicas must diverge between syncs and
    equal the average of independently-simulated locals at a sync."""
    mesh = init_mesh(dp=4)
    net = TinyNet()
    w0 = np.asarray(net.fc.weight).copy()  # [4, 1]
    step = LocalSGDStep(net, SGD(learning_rate=0.1), loss_fn=_mse,
                        mesh=mesh, k_steps=2)
    xs = RNG.normal(size=(4, 8, 4)).astype(np.float32)  # 4 steps, B=8
    ys = RNG.normal(size=(4, 8, 1)).astype(np.float32)

    # manual numpy simulation: replica r sees batch shard r
    w_rep = np.repeat(w0[None], 4, axis=0)  # [4, 4, 1]

    def manual_step(w, x, y):
        pred = x @ w
        grad = 2 * x.T @ (pred - y) / x.shape[0]
        return w - 0.1 * grad

    for t in range(4):
        loss = step((jnp.asarray(xs[t]), jnp.asarray(ys[t])))
        for r in range(4):
            sl = slice(r * 2, (r + 1) * 2)
            w_rep[r] = manual_step(w_rep[r], xs[t][sl], ys[t][sl])
        if (t + 1) % 2 == 0:
            w_rep[:] = w_rep.mean(axis=0)
        got = np.asarray(step.replica_params()["fc.weight"])  # [4, 4, 1]
        np.testing.assert_allclose(got, w_rep, rtol=1e-4, atol=1e-5,
                                   err_msg=f"step {t}")
        if (t + 1) % 2 == 1:
            # between syncs replicas genuinely diverge
            assert not np.allclose(got[0], got[1])
        else:
            np.testing.assert_allclose(got[0], got[3], rtol=1e-5)
    # averaged params + sync_to_model
    step.sync_to_model()
    np.testing.assert_allclose(np.asarray(net.fc.weight),
                               w_rep.mean(axis=0), rtol=1e-4, atol=1e-6)


def test_fleet_localsgd_dispatch():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 3}
    fleet.init(strategy=s)
    step = fleet.distributed_model(TinyNet(), SGD(learning_rate=0.1),
                                   loss_fn=_mse)
    assert isinstance(step, LocalSGDStep) and step.k_steps == 3
    x = jnp.asarray(RNG.normal(size=(8, 4)).astype(np.float32))
    y = jnp.asarray(RNG.normal(size=(8, 1)).astype(np.float32))
    losses = [float(step((x, y))) for _ in range(9)]
    assert losses[-1] < losses[0]


# -------------------------------------------- round-3 strategy surface
def test_strategy_rejects_unknown_fields():
    """Unknown knobs raise instead of passing silently (VERDICT r2 weak 6);
    collapsed reference knobs are accepted with a recorded reason."""
    import pytest

    from paddle_tpu.distributed.fleet import DistributedStrategy

    s = DistributedStrategy()
    with pytest.raises(AttributeError, match="no field"):
        s.fuze_all_reduce_ops = True  # typo'd knob can't slip through
    # collapsed-by-design reference knobs still assign (ported configs)
    s.nccl_comm_num = 3
    s.use_hierarchical_allreduce = True
    s.cudnn_exhaustive_search = False
    assert "XLA" in DistributedStrategy.explain("fuse_all_reduce_ops")
    table = DistributedStrategy.explain()
    assert len(table) >= 20 and "build_strategy" in table


def test_strategy_dgc_wraps_momentum():
    import numpy as np

    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.optimizer import DGCMomentum, Momentum

    s = DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 2, "rampup_step": 4,
                     "sparsity": [0.75, 0.9375]}
    opt = fleet.distributed_optimizer(
        Momentum(learning_rate=0.1, momentum=0.9), strategy=s)
    assert isinstance(opt, DGCMomentum)
    assert opt.rampup_begin_step == 2 and opt.sparsity == (0.75, 0.9375)


def test_dgc_momentum_semantics():
    """Warmup = exact momentum; after rampup only ~top-(1-s) of the
    residual reaches the weights per step, the rest accumulates and lands
    later (no gradient is ever lost)."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.optimizer import DGCMomentum, Momentum

    params = {"w": jnp.zeros(64)}
    rng = np.random.default_rng(0)
    g = {"w": jnp.asarray(rng.normal(size=64).astype(np.float32))}

    dgc = DGCMomentum(learning_rate=0.1, momentum=0.9,
                      rampup_begin_step=2, rampup_step=1, sparsity=[0.75])
    ref = Momentum(learning_rate=0.1, momentum=0.9)
    sd, sr = dgc.init(params), ref.init(params)
    p_d, p_r = params, params
    for _ in range(2):  # warmup: exact momentum parity
        p_d, sd = dgc.update(g, sd, p_d)
        p_r, sr = ref.update(g, sr, p_r)
    np.testing.assert_allclose(np.asarray(p_d["w"]), np.asarray(p_r["w"]),
                               rtol=1e-6)
    # post-rampup: one step moves only ~25% of coords
    before = np.asarray(p_d["w"]).copy()
    p_d, sd = dgc.update(g, sd, p_d)
    moved = np.abs(np.asarray(p_d["w"]) - before) > 1e-9
    assert 0.1 < moved.mean() < 0.5
    # residual holds the untransmitted mass
    assert float(jnp.abs(sd["residual"]["w"]).sum()) > 0
    # the untransmitted coordinates land in later steps
    for _ in range(30):
        p_d, sd = dgc.update(g, sd, p_d)
    assert (np.abs(np.asarray(p_d["w"])) > 1e-9).mean() > 0.9


def test_strategy_fp16_allreduce_grad_cast():
    import numpy as np

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.mesh import set_mesh
    from paddle_tpu.optimizer import SGD

    set_mesh(None)
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2}
    s.fp16_allreduce = True
    fleet.init(strategy=s)
    pt.seed(0)
    model = nn.Linear(8, 4)
    step = fleet.distributed_model(
        model, SGD(learning_rate=0.1),
        loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    assert step.grad_transform is not None
    rng = np.random.default_rng(0)
    loss = step((rng.standard_normal((4, 8)).astype(np.float32),
                 rng.integers(0, 4, 4)))
    assert np.isfinite(float(np.asarray(loss)))
    set_mesh(None)
