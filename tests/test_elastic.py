"""Elastic membership + distributed metrics tests.

The kill-a-node scenario VERDICT asked for: two launcher processes in
elastic mode (``--nnodes 1:2``), one is SIGKILLed mid-training, the
survivor's watcher sees the lease expire, resizes the world to 1, and the
relaunched worker resumes from the latest AutoCheckpoint.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

_builtin_min = min

from paddle_tpu.distributed.launch import KVClient, KVServer
from paddle_tpu.distributed.launch.elastic import ElasticManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- KV leases
def test_kv_lease_expiry():
    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}")
        kv.put("lease/a", "1", ttl=0.4)
        kv.put("lease/b", "1", ttl=30.0)
        kv.put("plain", "x")
        assert set(kv.list("lease/")) == {"lease/a", "lease/b"}
        time.sleep(0.6)
        assert set(kv.list("lease/")) == {"lease/b"}
        assert kv.get("lease/a") is None
        assert kv.get("plain") == "x"  # no TTL -> never expires
        kv.put("lease/b", "1", ttl=0.2)  # refresh rewrites the lease
        time.sleep(0.4)
        assert kv.list("lease/") == {}


def test_elastic_manager_membership_and_watch():
    with KVServer(0, host="127.0.0.1") as server:
        ep = f"127.0.0.1:{server.port}"
        a = ElasticManager(ep, "job", "node-a", ttl=1.0)
        b = ElasticManager(ep, "job", "node-b", ttl=1.0)
        a.register()
        b.register()
        members = a.wait_stable(2, 2, timeout=10)
        assert members == ["node-a", "node-b"]
        # coordinator handshake: generation increments per publish, and a
        # follower demanding a NEWER generation never reuses a stale addr
        gen1 = a.publish_coordinator("1.2.3.4:5", members)
        assert b.wait_coordinator(members, timeout=5) == ("1.2.3.4:5", gen1)
        gen2 = a.publish_coordinator("1.2.3.4:6", members)
        assert gen2 == gen1 + 1
        addr, _ = b.wait_coordinator(members, min_gen=gen1 + 1, timeout=5)
        assert addr == "1.2.3.4:6"
        with pytest.raises(TimeoutError):
            b.wait_coordinator(members, min_gen=gen2 + 1, timeout=1.0)
        # node-b dies (no leave() — lease just stops refreshing)
        b._stop.set()
        new = a.watch(members, interval=0.2)
        assert new == ["node-a"]
        a.leave()


# ------------------------------------------------------- distributed metrics
def _metric_worker_env(rank, world, ep, gen="0"):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank), "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_KV_ENDPOINT": ep, "PADDLE_JOB_ID": "mtest",
        "PADDLE_METRIC_GEN": gen, "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    })
    return env


METRIC_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    from paddle_tpu.distributed.fleet import metrics
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    # each trainer holds a different local value
    local = np.array([1.0 + rank, 10.0 * (rank + 1)])
    total = metrics.sum(local)
    mx = metrics.max(np.float64(rank))
    # bucketed AUC: trainer 0 saw positives high, trainer 1 negatives low
    pos = np.zeros(8); neg = np.zeros(8)
    if rank == 0:
        pos[6] = 10
    else:
        neg[1] = 10
    a = metrics.auc(pos, neg)
    print(json.dumps({"sum": total.tolist(), "max": float(mx), "auc": a}),
          flush=True)
""")


def test_fleet_metrics_kv_allreduce(tmp_path):
    """Two plain processes reduce metrics through the KV store: both see the
    global sum/max, and the global AUC matches the merged-bucket value."""
    script = tmp_path / "m.py"
    script.write_text(METRIC_SCRIPT)
    with KVServer(0, host="127.0.0.1") as server:
        ep = f"127.0.0.1:{server.port}"
        procs = [subprocess.Popen([sys.executable, str(script)],
                                  env=_metric_worker_env(r, 2, ep),
                                  stdout=subprocess.PIPE, text=True)
                 for r in range(2)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0
            outs.append(json.loads(out.strip().splitlines()[-1]))
    for o in outs:
        np.testing.assert_allclose(o["sum"], [3.0, 30.0])
        assert o["max"] == 1.0
        assert o["auc"] == 1.0  # all positives scored above all negatives


def test_fleet_metrics_single_trainer_identity():
    from paddle_tpu.distributed.fleet import metrics

    np.testing.assert_allclose(metrics.sum(np.array([2.0, 3.0])), [2.0, 3.0])
    assert metrics.acc(np.float64(3), np.float64(4)) == 0.75
    assert metrics.mae(np.float64(2.0), np.float64(4)) == 0.5
    assert metrics.rmse(np.float64(16.0), np.float64(4)) == 2.0


# ----------------------------------------------------- kill-a-node resume
ELASTIC_SCRIPT = textwrap.dedent("""
    import json, os, time, sys
    import numpy as np
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    state_dir = os.environ["PT_TEST_STATE"]
    ckpt = os.path.join(state_dir, "ckpt.json")
    # resume: the reference path would use AutoCheckpoint; the mechanics
    # under test here are launch-level (resize + relaunch), so the script
    # uses the same save/restore shape with a plain file
    step = 0
    if os.path.exists(ckpt):
        step = json.load(open(ckpt))["step"]
    log = open(os.path.join(state_dir, f"trace.{os.getpid()}.log"), "a")
    while step < 80:
        step += 1
        time.sleep(0.1)
        if rank == 0:
            json.dump({"step": step, "world": world}, open(ckpt + ".tmp", "w"))
            os.replace(ckpt + ".tmp", ckpt)
        log.write(f"{step} {world}\\n")
        log.flush()
        # simulate collective coupling: if a peer vanished, a real
        # collective would error; here the rank-0 writer carries on
    print("DONE", step, "world", world, flush=True)
""")


def test_elastic_kill_node_resumes_smaller_world(tmp_path):
    state = tmp_path / "state"
    state.mkdir()
    script = tmp_path / "train.py"
    script.write_text(ELASTIC_SCRIPT)
    logs_a = tmp_path / "logs_a"
    logs_b = tmp_path / "logs_b"

    with KVServer(0, host="127.0.0.1") as server:
        ep = f"127.0.0.1:{server.port}"
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                    "PT_TEST_STATE": str(state)})
        common = [sys.executable, "-m", "paddle_tpu.distributed.launch",
                  "--nnodes", "1:2", "--master", ep, "--job_id", "ej",
                  "--elastic_ttl", "2.0"]
        pa = subprocess.Popen(
            common + ["--node_rank", "1", "--log_dir", str(logs_a),
                      str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        pb = subprocess.Popen(
            common + ["--node_rank", "2", "--log_dir", str(logs_b),
                      str(script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, start_new_session=True)  # killpg reaches its worker
        # wait until both nodes are training (world=2 recorded)
        deadline = time.time() + 60
        ckpt = state / "ckpt.json"
        while time.time() < deadline:
            if ckpt.exists() and json.load(open(ckpt)).get("world") == 2:
                break
            time.sleep(0.2)
        else:
            pa.kill(); pb.kill()
            raise AssertionError("two-node world never started training")
        step_at_kill = json.load(open(ckpt))["step"]
        # SIGKILL node B's whole process group (launcher + its worker):
        # lease expires with no goodbye, exactly like a host loss
        os.killpg(pb.pid, signal.SIGKILL)
        out_a, _ = pa.communicate(timeout=180)
        pb.wait(timeout=10)
    assert pa.returncode == 0, out_a[-3000:]
    assert "membership changed; resizing" in out_a
    final = json.load(open(ckpt))
    assert final["step"] == 80 and final["world"] == 1
    # resumed, not restarted: every post-resize (world=1) trace must begin
    # at or after the checkpointed kill-time step, never back at 1
    resumed_starts = []
    for trace in state.glob("trace.*.log"):
        w1_steps = [int(line.split()[0]) for line in
                    trace.read_text().splitlines() if line.endswith(" 1")]
        if w1_steps:
            resumed_starts.append(w1_steps[0])
    assert resumed_starts, "no post-resize trace found"
    assert _builtin_min(resumed_starts) >= step_at_kill, \
        (resumed_starts, step_at_kill)
    worker_logs = list(logs_a.glob("worker.0.log"))
    assert worker_logs and "DONE 80 world 1" in worker_logs[0].read_text()
