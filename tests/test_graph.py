"""Graph engine tests: native CSR store sampling/walks, GraphDataGenerator
batch stream, and the geometric message-passing/sampling API.

Pattern follows the reference's HeterPS graph tests (test_graph.cu /
test_sample_rate.cu: build a small CSR graph, sample, assert neighbor
sets — SURVEY.md §4).
"""
import numpy as np
import jax.numpy as jnp

from paddle_tpu.distributed.ps.graph import GraphDataGenerator, GraphTable
from paddle_tpu import geometric as G


def toy_graph(symmetric=False):
    g = GraphTable()
    # 0-1, 0-2, 1-2, 2-3 directed
    g.add_edges([0, 0, 1, 2], [1, 2, 2, 3])
    g.build(symmetric=symmetric)
    return g


def test_graph_build_counts():
    g = toy_graph()
    assert g.num_nodes == 4
    assert g.num_edges == 4
    assert g.degree(0) == 2 and g.degree(3) == 0
    gs = toy_graph(symmetric=True)
    assert gs.num_edges == 8
    assert gs.degree(3) == 1


def test_sample_neighbors_exact_sets():
    g = toy_graph()
    nb, cnt = g.sample_neighbors([0, 3, 777], sample_size=4)
    assert nb.shape == (3, 4)
    assert set(nb[0][nb[0] >= 0].tolist()) == {1, 2} and cnt[0] == 2
    assert cnt[1] == 0 and cnt[2] == 0
    assert (nb[1] == -1).all()


def test_sample_neighbors_without_replacement_subset():
    g = GraphTable()
    g.add_edges(np.zeros(50, np.int64), np.arange(1, 51))
    g.build()
    nb, cnt = g.sample_neighbors([0], sample_size=10, seed=3)
    vals = nb[0]
    assert cnt[0] == 10
    assert len(set(vals.tolist())) == 10  # no duplicates
    assert all(1 <= v <= 50 for v in vals)
    # different seed -> different sample (overwhelmingly likely)
    nb2, _ = g.sample_neighbors([0], sample_size=10, seed=4)
    assert not np.array_equal(nb, nb2)


def test_random_walk_follows_edges():
    g = toy_graph()
    edges = {(0, 1), (0, 2), (1, 2), (2, 3)}
    walks = g.random_walk([0, 1], walk_len=5, seed=11)
    for start, walk in zip([0, 1], walks):
        prev = start
        for v in walk:
            if v < 0:
                break
            assert (prev, int(v)) in edges
            prev = int(v)
    # node 3 is a sink: walk from 3 is all padding
    assert (g.random_walk([3], 4) == -1).all()


def test_graph_data_generator_static_shapes():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 200, 2000)
    dst = rng.integers(0, 200, 2000)
    g = GraphTable()
    g.add_edges(src, dst)
    g.build(symmetric=True)
    gen = GraphDataGenerator(g, batch_size=64, walk_len=6, window=2,
                             num_neg=3, seed=1)
    batches = list(gen)
    assert len(batches) >= 10
    for c, x, neg in batches:
        assert c.shape == (64,) and x.shape == (64,) and neg.shape == (64, 3)
        assert (c >= 0).all() and (x >= 0).all()
    # epochs reshuffle
    b2 = list(gen)
    assert not np.array_equal(batches[0][0], b2[0][0])


# ------------------------------------------------------------- geometric
def test_send_u_recv_sum_mean():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    src = jnp.asarray([0, 1, 2, 0])
    dst = jnp.asarray([1, 2, 1, 0])
    out = G.send_u_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out, [[1, 2], [6, 8], [3, 4]])
    out = G.send_u_recv(x, src, dst, "mean")
    np.testing.assert_allclose(out, [[1, 2], [3, 4], [3, 4]])


def test_send_u_recv_max_empty_segment_zero():
    x = jnp.asarray([[1.0], [2.0]])
    out = G.send_u_recv(x, jnp.asarray([0]), jnp.asarray([0]), "max",
                        out_size=3)
    np.testing.assert_allclose(out, [[1.0], [0.0], [0.0]])


def test_send_ue_recv_and_send_uv():
    x = jnp.asarray([[1.0], [2.0]])
    e = jnp.asarray([[10.0], [20.0]])
    out = G.send_ue_recv(x, e, jnp.asarray([0, 1]), jnp.asarray([0, 0]),
                         "mul", "sum")
    np.testing.assert_allclose(out, [[50.0], [0.0]])
    uv = G.send_uv(x, x, jnp.asarray([0, 1]), jnp.asarray([1, 0]), "add")
    np.testing.assert_allclose(uv, [[3.0], [3.0]])


def test_send_u_recv_differentiable():
    import jax

    x = jnp.ones((3, 2))
    src = jnp.asarray([0, 1, 2])
    dst = jnp.asarray([0, 0, 1])

    def f(x):
        return G.send_u_recv(x, src, dst, "sum").sum()

    g = jax.grad(f)(x)
    np.testing.assert_allclose(g, np.ones((3, 2)))


def test_sample_neighbors_csc():
    # CSC: node 0 has neighbors [1,2], node 1 has [2], node 2 none
    row = np.asarray([1, 2, 2], np.int64)
    colptr = np.asarray([0, 2, 3, 3], np.int64)
    out, cnt = G.sample_neighbors(row, colptr, [0, 1, 2], sample_size=5)
    assert cnt.tolist() == [2, 1, 0]
    assert set(out[:2].tolist()) == {1, 2} and out[2] == 2


def test_reindex_graph():
    src, dst, nodes = G.reindex_graph(
        x=[10, 20], neighbors=[30, 20, 10, 40], count=[2, 2])
    assert nodes.tolist() == [10, 20, 30, 40]
    assert src.tolist() == [2, 1, 0, 3]
    assert dst.tolist() == [0, 0, 1, 1]


def test_khop_sampler():
    # chain 0->1->2->3 in CSC form: neighbors(i) = {i+1}
    row = np.asarray([1, 2, 3], np.int64)
    colptr = np.asarray([0, 1, 2, 3, 3], np.int64)
    src, dst, table = G.khop_sampler(row, colptr, [0], [1, 1])
    assert table.tolist() == [0, 1, 2]
    # hop edges: 1->0 (local 1->0), 2->1 (local 2->1)
    assert src.tolist() == [1, 2]
    assert dst.tolist() == [0, 1]


def test_segment_pool():
    x = jnp.asarray([[1.0], [2.0], [3.0]])
    out = G.segment_pool(x, jnp.asarray([0, 0, 1]), "mean")
    np.testing.assert_allclose(out, [[1.5], [3.0]])
