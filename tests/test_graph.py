"""Graph engine tests: native CSR store sampling/walks, GraphDataGenerator
batch stream, and the geometric message-passing/sampling API.

Pattern follows the reference's HeterPS graph tests (test_graph.cu /
test_sample_rate.cu: build a small CSR graph, sample, assert neighbor
sets — SURVEY.md §4).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.distributed.ps.graph import (DistGraphClient,
                                             GraphDataGenerator, GraphServer,
                                             GraphTable, launch_graph_servers)
from paddle_tpu import geometric as G


def toy_graph(symmetric=False):
    g = GraphTable()
    # 0-1, 0-2, 1-2, 2-3 directed
    g.add_edges([0, 0, 1, 2], [1, 2, 2, 3])
    g.build(symmetric=symmetric)
    return g


def test_graph_build_counts():
    g = toy_graph()
    assert g.num_nodes == 4
    assert g.num_edges == 4
    assert g.degree(0) == 2 and g.degree(3) == 0
    gs = toy_graph(symmetric=True)
    assert gs.num_edges == 8
    assert gs.degree(3) == 1


def test_sample_neighbors_exact_sets():
    g = toy_graph()
    nb, cnt = g.sample_neighbors([0, 3, 777], sample_size=4)
    assert nb.shape == (3, 4)
    assert set(nb[0][nb[0] >= 0].tolist()) == {1, 2} and cnt[0] == 2
    assert cnt[1] == 0 and cnt[2] == 0
    assert (nb[1] == -1).all()


def test_sample_neighbors_without_replacement_subset():
    g = GraphTable()
    g.add_edges(np.zeros(50, np.int64), np.arange(1, 51))
    g.build()
    nb, cnt = g.sample_neighbors([0], sample_size=10, seed=3)
    vals = nb[0]
    assert cnt[0] == 10
    assert len(set(vals.tolist())) == 10  # no duplicates
    assert all(1 <= v <= 50 for v in vals)
    # different seed -> different sample (overwhelmingly likely)
    nb2, _ = g.sample_neighbors([0], sample_size=10, seed=4)
    assert not np.array_equal(nb, nb2)


def test_random_walk_follows_edges():
    g = toy_graph()
    edges = {(0, 1), (0, 2), (1, 2), (2, 3)}
    walks = g.random_walk([0, 1], walk_len=5, seed=11)
    for start, walk in zip([0, 1], walks):
        prev = start
        for v in walk:
            if v < 0:
                break
            assert (prev, int(v)) in edges
            prev = int(v)
    # node 3 is a sink: walk from 3 is all padding
    assert (g.random_walk([3], 4) == -1).all()


def test_graph_data_generator_static_shapes():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 200, 2000)
    dst = rng.integers(0, 200, 2000)
    g = GraphTable()
    g.add_edges(src, dst)
    g.build(symmetric=True)
    gen = GraphDataGenerator(g, batch_size=64, walk_len=6, window=2,
                             num_neg=3, seed=1)
    batches = list(gen)
    assert len(batches) >= 10
    for c, x, neg in batches:
        assert c.shape == (64,) and x.shape == (64,) and neg.shape == (64, 3)
        assert (c >= 0).all() and (x >= 0).all()
    # epochs reshuffle
    b2 = list(gen)
    assert not np.array_equal(batches[0][0], b2[0][0])


# ------------------------------------------------- node features (local)
def test_node_features_roundtrip():
    g = toy_graph()
    g.set_features([0, 2], [[1.0, 2.0], [3.0, 4.0]])
    assert g.feature_dim == 2
    out = g.get_features([2, 0, 99])
    np.testing.assert_allclose(out, [[3, 4], [1, 2], [0, 0]])  # missing -> 0
    with pytest.raises(ValueError):
        g.set_features([1], [[1.0, 2.0, 3.0]])  # dim mismatch


def test_walk_step_composes_to_random_walk():
    """random_walk == repeated walk_step (the distributed-walk invariant)."""
    g = toy_graph(symmetric=True)
    starts = np.asarray([0, 1, 2, 3], np.int64)
    walks = g.random_walk(starts, walk_len=5, seed=9)
    cur = starts.copy()
    rows = np.arange(starts.size)
    for step in range(5):
        cur = g.walk_step(cur, rows, step, seed=9)
        np.testing.assert_array_equal(cur, walks[:, step])


# ------------------------------------- sharded multi-host graph engine
def random_coo(n_nodes=120, n_edges=1500, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_nodes, n_edges).astype(np.int64),
            rng.integers(0, n_nodes, n_edges).astype(np.int64))


@pytest.fixture(scope="module")
def graph_cluster():
    """Two graph-shard server subprocesses + a connected client, BUILT
    with the canonical random_coo graph so every dependent test is
    self-sufficient (the reference's TestDistBase subprocess-cluster
    pattern, SURVEY §4)."""
    procs, endpoints = launch_graph_servers(2)
    client = DistGraphClient(endpoints)
    src, dst = random_coo()
    client.add_edges(src, dst)
    client.build(symmetric=True)
    yield client
    client.stop_servers()
    client.close()
    for p in procs:
        p.wait(timeout=10)


def test_dist_graph_parity_with_single_host(graph_cluster):
    """The sharded store is observationally identical to the single-host
    store: same node set, per-node degrees, bit-identical neighbor samples
    and hop-by-hop random walks (each node's adjacency lives wholly on its
    owner shard, and sampling/hopping is deterministic per node)."""
    src, dst = random_coo()
    local = GraphTable()
    local.add_edges(src, dst)
    local.build(symmetric=True)

    assert graph_cluster.num_nodes == local.num_nodes
    assert graph_cluster.num_edges == local.num_edges
    np.testing.assert_array_equal(graph_cluster.node_ids(),
                                  np.sort(local.node_ids()))
    for k in [0, 5, 77, 119]:
        assert graph_cluster.degree(k) == local.degree(k)

    nodes = np.asarray([0, 3, 50, 111, 999], np.int64)  # 999 unknown
    nb_d, ct_d = graph_cluster.sample_neighbors(nodes, 8, seed=5)
    nb_l, ct_l = local.sample_neighbors(nodes, 8, seed=5)
    np.testing.assert_array_equal(nb_d, nb_l)
    np.testing.assert_array_equal(ct_d, ct_l)

    starts = np.arange(40, dtype=np.int64)
    np.testing.assert_array_equal(graph_cluster.random_walk(starts, 6, seed=3),
                                  local.random_walk(starts, 6, seed=3))


def test_dist_graph_features(graph_cluster):
    """Features route to each node's owner shard and come back verbatim;
    missing nodes zero-fill — GpuPsCommGraphFea payload semantics."""
    rng = np.random.default_rng(7)
    keys = np.arange(0, 120, dtype=np.int64)
    feats = rng.normal(size=(120, 16)).astype(np.float32)
    graph_cluster.set_features(keys, feats)
    assert graph_cluster.feature_dim == 16
    got = graph_cluster.get_features(keys[::-1])
    np.testing.assert_array_equal(got, feats[::-1])
    # a miss zero-fills, hits around it unaffected
    got = graph_cluster.get_features([5, 100000, 6])
    np.testing.assert_array_equal(got[0], feats[5])
    np.testing.assert_array_equal(got[1], np.zeros(16, np.float32))
    np.testing.assert_array_equal(got[2], feats[6])


def test_sample_with_features_local_and_dist(graph_cluster):
    """graph_neighbor_sample_v3 analogue: samples arrive with feature
    payloads; padding rows carry zero features. Dist == local."""
    src, dst = random_coo()
    local = GraphTable()
    local.add_edges(src, dst)
    local.build(symmetric=True)
    rng = np.random.default_rng(7)
    keys = np.arange(0, 120, dtype=np.int64)
    feats = rng.normal(size=(120, 16)).astype(np.float32)
    local.set_features(keys, feats)  # cluster already has these (same rng)

    nodes = np.asarray([0, 7, 999], np.int64)
    nb_l, ct_l, f_l = local.sample_with_features(nodes, 4, seed=2)
    nb_d, ct_d, f_d = graph_cluster.sample_with_features(nodes, 4, seed=2)
    np.testing.assert_array_equal(nb_l, nb_d)
    np.testing.assert_array_equal(f_l, f_d)
    assert f_l.shape == (3, 4, 16)
    np.testing.assert_array_equal(f_l[2], np.zeros((4, 16)))  # unknown node
    for i in range(2):
        for j in range(4):
            if nb_l[i, j] >= 0:
                np.testing.assert_array_equal(f_l[i, j], feats[nb_l[i, j]])


def test_dist_graph_feeds_deepwalk_generator(graph_cluster):
    """GraphDataGenerator runs unchanged over the sharded client (the
    PGLBox walk-based feed over the distributed engine)."""
    if not getattr(graph_cluster, "_built", False):
        # self-sufficient under -k subset runs: earlier tests normally
        # populate the module-scoped cluster, but must not be required
        src, dst = random_coo()
        graph_cluster.add_edges(src, dst)
        graph_cluster.build()
    gen = GraphDataGenerator(graph_cluster, batch_size=32, walk_len=4,
                             window=2, num_neg=3, seed=1)
    batches = list(gen)
    assert len(batches) >= 5
    ids = set(graph_cluster.node_ids().tolist())
    for c, x, neg in batches[:3]:
        assert c.shape == (32,) and x.shape == (32,) and neg.shape == (32, 3)
        assert set(c.tolist()) <= ids and set(x.tolist()) <= ids


def test_inproc_graph_server_roundtrip():
    """GraphServer can host in-process (single-host multi-shard tests)."""
    srv = GraphServer()
    client = DistGraphClient([("127.0.0.1", srv.port)])
    client.add_edges([0, 0, 1], [1, 2, 2])
    client.build()
    assert client.num_nodes == 3 and client.num_edges == 3
    nb, ct = client.sample_neighbors([0], 4)
    assert set(nb[0][nb[0] >= 0].tolist()) == {1, 2} and ct[0] == 2
    client.close()
    srv.stop()


# ------------------------------------------------------------- geometric
def test_send_u_recv_sum_mean():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    src = jnp.asarray([0, 1, 2, 0])
    dst = jnp.asarray([1, 2, 1, 0])
    out = G.send_u_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out, [[1, 2], [6, 8], [3, 4]])
    out = G.send_u_recv(x, src, dst, "mean")
    np.testing.assert_allclose(out, [[1, 2], [3, 4], [3, 4]])


def test_send_u_recv_max_empty_segment_zero():
    x = jnp.asarray([[1.0], [2.0]])
    out = G.send_u_recv(x, jnp.asarray([0]), jnp.asarray([0]), "max",
                        out_size=3)
    np.testing.assert_allclose(out, [[1.0], [0.0], [0.0]])


def test_send_ue_recv_and_send_uv():
    x = jnp.asarray([[1.0], [2.0]])
    e = jnp.asarray([[10.0], [20.0]])
    out = G.send_ue_recv(x, e, jnp.asarray([0, 1]), jnp.asarray([0, 0]),
                         "mul", "sum")
    np.testing.assert_allclose(out, [[50.0], [0.0]])
    uv = G.send_uv(x, x, jnp.asarray([0, 1]), jnp.asarray([1, 0]), "add")
    np.testing.assert_allclose(uv, [[3.0], [3.0]])


def test_send_u_recv_differentiable():
    import jax

    x = jnp.ones((3, 2))
    src = jnp.asarray([0, 1, 2])
    dst = jnp.asarray([0, 0, 1])

    def f(x):
        return G.send_u_recv(x, src, dst, "sum").sum()

    g = jax.grad(f)(x)
    np.testing.assert_allclose(g, np.ones((3, 2)))


def test_sample_neighbors_csc():
    # CSC: node 0 has neighbors [1,2], node 1 has [2], node 2 none
    row = np.asarray([1, 2, 2], np.int64)
    colptr = np.asarray([0, 2, 3, 3], np.int64)
    out, cnt = G.sample_neighbors(row, colptr, [0, 1, 2], sample_size=5)
    assert cnt.tolist() == [2, 1, 0]
    assert set(out[:2].tolist()) == {1, 2} and out[2] == 2


def test_reindex_graph():
    src, dst, nodes = G.reindex_graph(
        x=[10, 20], neighbors=[30, 20, 10, 40], count=[2, 2])
    assert nodes.tolist() == [10, 20, 30, 40]
    assert src.tolist() == [2, 1, 0, 3]
    assert dst.tolist() == [0, 0, 1, 1]


def test_khop_sampler():
    # chain 0->1->2->3 in CSC form: neighbors(i) = {i+1}
    row = np.asarray([1, 2, 3], np.int64)
    colptr = np.asarray([0, 1, 2, 3, 3], np.int64)
    src, dst, table = G.khop_sampler(row, colptr, [0], [1, 1])
    assert table.tolist() == [0, 1, 2]
    # hop edges: 1->0 (local 1->0), 2->1 (local 2->1)
    assert src.tolist() == [1, 2]
    assert dst.tolist() == [0, 1]


def test_segment_pool():
    x = jnp.asarray([[1.0], [2.0], [3.0]])
    out = G.segment_pool(x, jnp.asarray([0, 0, 1]), "mean")
    np.testing.assert_allclose(out, [[1.5], [3.0]])


# ----------------------------------------------- weighted graphs (r3)
def test_weighted_sampling_bias():
    """Edge weights bias replace-sampling and walks toward heavy edges
    (the reference CSR's weight payloads)."""
    g = GraphTable()
    g.add_edges([0, 0], [1, 2], weights=[9.0, 1.0])
    g.build()
    nb, cnt = g.sample_neighbors([0], sample_size=400, replace=True, seed=5)
    frac1 = (np.asarray(nb[0]) == 1).mean()
    assert 0.8 < frac1 < 0.98, frac1  # ~0.9 expected
    # weighted hops: most walks step to node 1
    walks = g.random_walk(np.zeros(500, np.int64), walk_len=1, seed=3)
    frac1 = (np.asarray(walks[:, 0]) == 1).mean()
    assert 0.8 < frac1 < 0.98, frac1
    # weighted without replacement (A-Res) heavily prefers heavy edges
    g2 = GraphTable()
    g2.add_edges(np.zeros(20, np.int64), np.arange(1, 21),
                 weights=[100.0] * 2 + [0.01] * 18)
    g2.build()
    nb2, _ = g2.sample_neighbors([0], sample_size=2, seed=7)
    assert set(np.asarray(nb2[0]).tolist()) == {1, 2}


def test_weighted_dist_graph_parity(graph_cluster):
    """Sharded weighted store matches single-host: deterministic weighted
    hops are bit-identical; weighted sampling draws the same rows."""
    rng = np.random.default_rng(3)
    src = rng.integers(0, 60, 600).astype(np.int64)
    dst = rng.integers(0, 60, 600).astype(np.int64)
    w = rng.uniform(0.1, 5.0, 600).astype(np.float32)
    local = GraphTable()
    local.add_edges(src, dst, weights=w)
    local.build(symmetric=True)
    graph_cluster.clear_edges()  # module fixture carries earlier graphs
    graph_cluster.add_edges(src, dst, weights=w)
    graph_cluster.build(symmetric=True)
    starts = np.arange(40, dtype=np.int64)
    np.testing.assert_array_equal(
        graph_cluster.random_walk(starts, 5, seed=11),
        local.random_walk(starts, 5, seed=11))
    nb_d, ct_d = graph_cluster.sample_neighbors(starts, 6, replace=True,
                                                seed=2)
    nb_l, ct_l = local.sample_neighbors(starts, 6, replace=True, seed=2)
    np.testing.assert_array_equal(nb_d, nb_l)
    np.testing.assert_array_equal(ct_d, ct_l)


def test_khop_sampler_from_store_local_vs_sharded(graph_cluster):
    """Multi-hop GNN minibatch over the graph STORE: the sampled subgraph
    (edges + node table + features) is identical on the single-host table
    and the 2-server sharded client — the GpuPs khop path restated."""
    from paddle_tpu import geometric as G

    src, dst = random_coo(n_nodes=80, n_edges=800, seed=9)
    local = GraphTable()
    local.add_edges(src, dst)
    local.build(symmetric=True)
    rngf = np.random.default_rng(1)
    # dim 16: the module-scoped cluster's feature table fixed its dim in
    # an earlier test (first set_features wins)
    feats = rngf.normal(size=(80, 16)).astype(np.float32)
    local.set_features(np.arange(80), feats)

    graph_cluster.clear_edges()
    graph_cluster.add_edges(src, dst)
    graph_cluster.build(symmetric=True)
    graph_cluster.set_features(np.arange(80), feats)

    seeds = np.asarray([0, 3, 11], np.int64)
    es_l, ed_l, idx_l, f_l = G.khop_sampler_from_store(
        local, seeds, [4, 3], seed=5, with_features=True)
    es_d, ed_d, idx_d, f_d = G.khop_sampler_from_store(
        graph_cluster, seeds, [4, 3], seed=5, with_features=True)
    np.testing.assert_array_equal(es_l, es_d)
    np.testing.assert_array_equal(ed_l, ed_d)
    np.testing.assert_array_equal(idx_l, idx_d)
    np.testing.assert_array_equal(f_l, f_d)
    # structure sanity: every edge endpoint indexes the node table, seeds
    # occupy the first rows
    assert idx_l[:3].tolist() == seeds.tolist()
    assert es_l.max(initial=-1) < idx_l.size
    assert f_l.shape == (idx_l.size, 16)

    # and the minibatch feeds message passing end-to-end
    import jax.numpy as jnp

    h = G.send_u_recv(jnp.asarray(f_l), jnp.asarray(es_l), jnp.asarray(ed_l),
                      "mean", out_size=idx_l.size)
    assert np.asarray(h).shape == (idx_l.size, 16)


def test_graph_bench_tool_smoke():
    """tools/graph_bench.py (the scale-proof harness) stays runnable: tiny
    graph, all sections produce positive numbers."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "graph_bench.py"),
         "--edges", "20000", "--iters", "3"],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr[-800:]
    data = json.loads(out.stdout.strip().splitlines()[-1])
    for section in ("single_host", "two_shard"):
        for metric, v in data[section].items():
            assert v > 0, (section, metric, data)
    assert data["feed_train_overlap"]["overlapped_s"] > 0


def test_multi_hop_walk_uses_fewer_rpc_rounds(graph_cluster):
    """The server-side multi-hop walk (VERDICT r4 item 4) must pay one
    scatter-gather round per shard-CROSSING, not one per hop: for 2
    uniform shards a walker crosses with p~=0.5 per hop, so a
    walk_len=20 walk should need ~11 rounds, and must stay well under
    the old per-hop protocol's 20. (Wall-clock parity on this 1-core
    host is bounded by total work; the round count is the mechanism.)"""
    src, dst = random_coo(seed=3)
    graph_cluster.clear_edges()  # module fixture: drop prior tests' edges
    graph_cluster.add_edges(src, dst)
    graph_cluster.build(symmetric=True)
    starts = graph_cluster.node_ids()[:64]

    rounds = []
    orig = graph_cluster._request_multi

    def counting(reqs):
        rounds.append(len(reqs))
        return orig(reqs)

    graph_cluster._request_multi = counting
    try:
        walks = graph_cluster.random_walk(starts, walk_len=20, seed=5)
    finally:
        graph_cluster._request_multi = orig
    assert walks.shape == (64, 20)
    # every round advances every active walker >= 1 hop; crossings gate
    # the count. 16 leaves slack over the ~11 expectation without ever
    # tolerating per-hop behavior (20).
    assert 1 <= len(rounds) <= 16, rounds

    # and the result still matches the single-host walk bit-for-bit
    local = GraphTable()
    local.add_edges(src, dst)
    local.build(symmetric=True)
    np.testing.assert_array_equal(local.random_walk(starts, 20, seed=5),
                                  walks)
