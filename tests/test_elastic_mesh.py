"""Elastic mesh: topology-agnostic checkpoint resharding and
shrink/grow-on-preemption (PR 6).

Cheap tier-1 coverage of the resharding math on fake CPU devices (the
conftest forces 8): mesh-shape planning, 8->4->8 round trips including
dp<->mp re-layouts and shard boundaries that don't align, the bounded
host-memory guarantee of the streaming restore, rank-attributed
completeness reporting, and fallback to the newest complete checkpoint.
The full 8-devices -> kill -> 4-devices -> regrow parity proof is
``tools/chaos_soak.py --elastic`` (smoke-run here under ``slow``).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed import elastic_mesh
from paddle_tpu.distributed.elastic_mesh import (plan_mesh_shape,
                                                 rescale_batch,
                                                 reshaped_mesh)
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.io.cursor import DataCursor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_global_mesh():
    """These tests install shrunken (3/4-device) meshes; don't leak them
    into later test modules."""
    from paddle_tpu.distributed import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    yield
    mesh_mod.set_mesh(prev)


def _mesh(shape, n=None):
    devs = jax.devices()[:n] if n is not None else jax.devices()
    return init_mesh(dict(shape), devices=np.asarray(devs))


def _put(arr, mesh, spec):
    return jax.device_put(np.asarray(arr), NamedSharding(mesh, P(*spec)))


# ----------------------------------------------------------- shape planning
def test_plan_shrink_data_axis():
    assert plan_mesh_shape({"dp": 8}, 4) == {"dp": 4}
    assert plan_mesh_shape({"dp": 4, "mp": 2}, 4) == {"dp": 2, "mp": 2}


def test_plan_grow_data_axis():
    assert plan_mesh_shape({"dp": 2, "mp": 2}, 8) == {"dp": 4, "mp": 2}
    assert plan_mesh_shape({"dp": 4}, 16) == {"dp": 16}


def test_plan_uneven_divisor():
    # non-power-of-two survivor counts still plan (dp absorbs them)
    assert plan_mesh_shape({"dp": 4}, 6) == {"dp": 6}
    assert plan_mesh_shape({"dp": 4, "mp": 2}, 6) == {"dp": 3, "mp": 2}


def test_plan_secondary_data_axes_gcd():
    assert plan_mesh_shape({"dp": 2, "sdp": 2, "mp": 2}, 4) == \
        {"dp": 1, "sdp": 2, "mp": 2}
    assert plan_mesh_shape({"dp": 2, "sdp": 4, "mp": 1}, 4) == \
        {"dp": 1, "sdp": 4, "mp": 1}


def test_plan_frozen_axes_preserved_or_refused():
    # mp/pp partition the PROGRAM: their sizes survive every resize...
    out = plan_mesh_shape({"dp": 2, "mp": 4}, 8)
    assert out["mp"] == 4 and out["dp"] == 2
    # ...and capacity that cannot host them is an explicit error
    with pytest.raises(ValueError, match="frozen axes"):
        plan_mesh_shape({"dp": 4, "mp": 4}, 2)
    with pytest.raises(ValueError):
        plan_mesh_shape({"dp": 4}, 0)


def test_plan_fully_model_parallel_grow_adds_dp():
    assert plan_mesh_shape({"mp": 4}, 8) == {"dp": 2, "mp": 4}


# --------------------------------------------------------- batch accounting
def test_rescale_batch_keeps_global_constant():
    assert rescale_batch(32, {"dp": 4, "mp": 2}) == 8
    assert rescale_batch(32, {"dp": 2, "mp": 2}) == 16
    assert rescale_batch(32, {"mp": 2}) == 32


def test_rescale_batch_indivisible_raises():
    with pytest.raises(ValueError, match="does not divide"):
        rescale_batch(10, {"dp": 4})


def test_cursor_rescale_preserves_samples_consumed():
    c = DataCursor(epoch=2, batch_index=10, epoch_seed=7, global_step=50)
    r = c.rescale(old_global_batch=32, new_global_batch=16)
    assert (r.epoch, r.batch_index, r.global_step) == (2, 20, 50)
    # rounds DOWN to a batch boundary (replays, never skips)
    r2 = c.rescale(32, 24)   # 320 samples -> 13.33 batches -> 13
    assert r2.batch_index == 13
    assert c.rescale(32, 32).batch_index == 10
    with pytest.raises(ValueError):
        c.rescale(0, 16)


# ------------------------------------------------------ reshard round trips
def _save_tree(tmp_path, mesh, name="ck"):
    rng = np.random.default_rng(0)
    tree = {
        "w_dp": rng.standard_normal((16, 8)).astype(np.float32),
        "w_mp": rng.standard_normal((8, 16)).astype(np.float32),
        "w_2d": rng.standard_normal((8, 8)).astype(np.float32),
        "scalar": np.float32(3.5),
    }
    state = {
        "w_dp": _put(tree["w_dp"], mesh, ("dp", None)),
        "w_mp": _put(tree["w_mp"], mesh, (None, "mp")),
        "w_2d": _put(tree["w_2d"], mesh, ("dp", "mp")),
        "scalar": tree["scalar"],
    }
    d = str(tmp_path / name)
    ckpt.save_state(state, d)
    return d, tree


def _shardings(mesh):
    return {"w_dp": NamedSharding(mesh, P("dp", None)),
            "w_mp": NamedSharding(mesh, P(None, "mp")),
            "w_2d": NamedSharding(mesh, P("dp", "mp"))}


def _assert_tree(loaded, tree, mesh):
    for k, want in tree.items():
        got = np.asarray(loaded[k])
        np.testing.assert_array_equal(got, want, err_msg=k)
    for k in ("w_dp", "w_mp", "w_2d"):
        assert loaded[k].sharding.mesh is mesh or \
            loaded[k].sharding.mesh == mesh


def test_reshard_8_to_4_to_8_round_trip(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d8, tree = _save_tree(tmp_path, mesh8, "ck8")
    assert ckpt.validate_checkpoint(d8) is None

    mesh4 = _mesh({"dp": 2, "mp": 2}, n=4)
    loaded4 = ckpt.load_state(d8, shardings=_shardings(mesh4))
    _assert_tree(loaded4, tree, mesh4)

    # continue from the shrunk state: save on 4, restore back onto 8
    d4 = str(tmp_path / "ck4")
    ckpt.save_state({**loaded4, "scalar": np.float32(3.5)}, d4)
    mesh8b = _mesh({"dp": 4, "mp": 2})
    loaded8 = ckpt.load_state(d4, shardings=_shardings(mesh8b))
    _assert_tree(loaded8, tree, mesh8b)


def test_reshard_dp_to_mp_relayout(tmp_path):
    """The same bytes land on a TRANSPOSED layout: saved row-sharded over
    dp, restored column-sharded over mp — every target shard spans
    multiple source shards."""
    mesh8 = _mesh({"dp": 4, "mp": 2})
    rng = np.random.default_rng(1)
    w = rng.standard_normal((16, 16)).astype(np.float32)
    d = str(tmp_path / "ck")
    ckpt.save_state({"w": _put(w, mesh8, ("dp", None))}, d)

    mesh4 = _mesh({"dp": 1, "mp": 4}, n=4)
    out = ckpt.load_state(
        d, shardings={"w": NamedSharding(mesh4, P(None, "mp"))})
    np.testing.assert_array_equal(np.asarray(out["w"]), w)


def test_reshard_unaligned_shard_boundaries(tmp_path):
    """Saved shards of 3 rows (dp4 over 12), restored as shards of 4 rows
    (dp3): every new shard straddles an old shard boundary."""
    mesh4 = _mesh({"dp": 4}, n=4)
    rng = np.random.default_rng(2)
    w = rng.standard_normal((12, 5)).astype(np.float32)
    d = str(tmp_path / "ck")
    ckpt.save_state({"w": _put(w, mesh4, ("dp",))}, d)

    mesh3 = _mesh({"dp": 3}, n=3)
    out = ckpt.load_state(
        d, shardings={"w": NamedSharding(mesh3, P("dp"))})
    np.testing.assert_array_equal(np.asarray(out["w"]), w)


def test_reshard_peak_host_memory_bounded(tmp_path):
    """The elastic restore must never materialise a full param tree (or
    even one full leaf) on the host: decoded source shards are LRU-bounded
    by ``max_shard_cache_bytes`` and re-read on miss."""
    mesh8 = _mesh({"dp": 8})
    rng = np.random.default_rng(3)
    leaves = {f"w{i}": rng.standard_normal((64, 256)).astype(np.float32)
              for i in range(4)}   # 64 KiB each, 8 KiB per saved shard
    state = {k: _put(v, mesh8, ("dp", None)) for k, v in leaves.items()}
    d = str(tmp_path / "ck")
    ckpt.save_state(state, d)

    shard_bytes = leaves["w0"].nbytes // 8
    bound = 2 * shard_bytes
    mesh4 = _mesh({"dp": 4}, n=4)
    out = ckpt.load_state(
        d, shardings={k: NamedSharding(mesh4, P("dp", None))
                      for k in leaves},
        max_shard_cache_bytes=bound)
    for k, want in leaves.items():
        np.testing.assert_array_equal(np.asarray(out[k]), want)

    stats = ckpt.last_load_stats()
    total = sum(v.nbytes for v in leaves.values())
    # never held more than the bound + the shard being served...
    assert stats["peak_resident_bytes"] <= bound + shard_bytes, stats
    # ...which is far below one leaf, let alone the full tree
    assert stats["peak_resident_bytes"] < leaves["w0"].nbytes
    assert stats["peak_resident_bytes"] < total / 4
    assert stats["leaves"] == 4


def test_reshard_unbounded_cache_reads_each_shard_once(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, tree = _save_tree(tmp_path, mesh8, "ck")
    mesh4 = _mesh({"dp": 2, "mp": 2}, n=4)
    ckpt.load_state(d, shardings=_shardings(mesh4),
                    max_shard_cache_bytes=None)
    stats = ckpt.last_load_stats()
    assert stats["evictions"] == 0
    # every unique shard file decoded exactly once
    n_shards = len([f for f in os.listdir(d) if f.endswith(".npy")])
    assert stats["shard_reads"] == n_shards


# ------------------------------------------------- mesh metadata + planning
def test_checkpoint_records_written_mesh(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    info = ckpt.mesh_info(d)
    assert info["axes"] == {"dp": 4, "mp": 2}
    assert info["devices"] == 8
    assert info["process_count"] == 1


def test_mesh_info_absent_for_old_checkpoints(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    meta_path = os.path.join(d, "metadata.json")
    meta = json.load(open(meta_path))
    meta.pop("mesh")   # a pre-elastic checkpoint
    json.dump(meta, open(meta_path, "w"))
    assert ckpt.mesh_info(d) is None
    assert ckpt.mesh_info(str(tmp_path / "nope")) is None
    # unknown layout => caller-supplied axes (the same-topology path)
    mesh = reshaped_mesh(d, default_axes={"dp": -1, "mp": 2})
    assert dict(mesh.shape) == {"dp": 4, "mp": 2}


def test_reshaped_mesh_from_checkpoint_topology(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    # surviving capacity: 4 devices -> dp shrinks, mp frozen
    mesh = reshaped_mesh(d, devices=jax.devices()[:4])
    assert dict(mesh.shape) == {"dp": 2, "mp": 2}
    # capacity back: regrow through the SAME call
    mesh = reshaped_mesh(d, devices=jax.devices())
    assert dict(mesh.shape) == {"dp": 4, "mp": 2}


def test_reshaped_mesh_accepts_autocheckpoint_root(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    root = tmp_path / "auto"
    root.mkdir()
    _save_tree(root, mesh8, "step_10")
    mesh = reshaped_mesh(str(root), devices=jax.devices()[:4])
    assert dict(mesh.shape) == {"dp": 2, "mp": 2}
    # no checkpoint yet -> default axes planned onto the live devices
    mesh = reshaped_mesh(str(tmp_path / "empty"),
                         default_axes={"dp": -1, "mp": 2},
                         devices=jax.devices())
    assert dict(mesh.shape) == {"dp": 4, "mp": 2}


# ------------------------------------- completeness reporting and fallback
def test_validate_names_missing_ranks_and_leaves(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    # a lost host's shards: delete two of w_dp's shard files
    victims = [f for f in sorted(os.listdir(d))
               if "_w_dp__" in f and f.endswith(".npy")][:2]
    for v in victims:
        os.remove(os.path.join(d, v))
    msg = ckpt.validate_checkpoint(d)
    assert msg is not None
    assert "2 shard file(s) missing" in msg
    assert "rank(s) [0]" in msg
    assert "'w_dp'" in msg


def test_validate_names_uncommitted_ranks(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    meta_path = os.path.join(d, "metadata.json")
    meta = json.load(open(meta_path))
    meta["process_count"] = 3   # ranks 1..2 never wrote their markers
    json.dump(meta, open(meta_path, "w"))
    msg = ckpt.validate_checkpoint(d)
    assert "rank(s) [1, 2]" in msg
    assert "never committed" in msg


def test_load_missing_shard_names_writer_rank(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    d, _ = _save_tree(tmp_path, mesh8)
    victim = next(f for f in sorted(os.listdir(d))
                  if "_w_mp__" in f and f.endswith(".npy"))
    os.remove(os.path.join(d, victim))
    with pytest.raises(ckpt.CheckpointCorruptError,
                       match=r"written by rank 0.*lost\s+host"):
        ckpt.load_state(d)


def test_latest_checkpoint_skips_incomplete_and_excluded(tmp_path):
    mesh8 = _mesh({"dp": 4, "mp": 2})
    root = tmp_path / "auto"
    root.mkdir()
    d1, _ = _save_tree(root, mesh8, "step_1")
    d2, _ = _save_tree(root, mesh8, "step_2")
    # newest loses a shard (host died): fallback picks the complete one
    victim = next(f for f in sorted(os.listdir(d2)) if f.endswith(".npy"))
    os.remove(os.path.join(d2, victim))
    assert ckpt.latest_checkpoint(str(root)) == d1
    # exclude: the restore loop's "this one failed to LOAD" hook
    assert ckpt.latest_checkpoint(str(root), verify=False) == d2
    assert ckpt.latest_checkpoint(str(root), verify=False,
                                  exclude=[d2]) == d1


def test_latest_checkpoint_on_invalid_avoids_revalidation(
        tmp_path, monkeypatch):
    """Validation failures are reported via ``on_invalid`` so a retry
    loop can exclude them — the next call must not re-crc the rejected
    candidate's shards."""
    mesh8 = _mesh({"dp": 4, "mp": 2})
    root = tmp_path / "auto"
    root.mkdir()
    d1, _ = _save_tree(root, mesh8, "step_1")
    d2, _ = _save_tree(root, mesh8, "step_2")
    victim = next(f for f in sorted(os.listdir(d2)) if f.endswith(".npy"))
    os.remove(os.path.join(d2, victim))

    validated = []
    real = ckpt.validate_checkpoint
    monkeypatch.setattr(ckpt, "validate_checkpoint",
                        lambda d, **kw: validated.append(d) or real(d, **kw))
    tried = []
    assert ckpt.latest_checkpoint(str(root), exclude=tried,
                                  on_invalid=tried.append) == d1
    assert tried == [d2]
    validated.clear()
    # the restore-loop retry: the rejected newer candidate is excluded
    # outright, not validated (= re-read) a second time
    assert ckpt.latest_checkpoint(str(root), exclude=tried,
                                  on_invalid=tried.append) == d1
    assert validated == [d1]


def test_sweep_reaps_leaked_tmp_shard_files(tmp_path):
    """A multi-process writer SIGKILLed between staging a shard and its
    publish rename leaves ``<shard>.npy.tmp<pid>`` inside the committed
    step dir; the orphan sweep reaps it (stale under TTL, always at
    startup) without touching published shards or a fresh in-flight one."""
    mesh8 = _mesh({"dp": 4, "mp": 2})
    root = tmp_path / "auto"
    root.mkdir()
    d1, tree = _save_tree(root, mesh8, "step_1")
    leak = os.path.join(d1, "L0000_w_dp__0_0.npy.tmp99999")
    with open(leak, "wb") as f:
        f.write(b"torn")
    os.utime(leak, (1.0, 1.0))  # stale: crashed incarnation long gone
    fresh_leak = os.path.join(d1, "L0001_w_mp__0_0.npy.tmp88888")
    with open(fresh_leak, "wb") as f:
        f.write(b"in-flight")

    ac = ckpt.AutoCheckpoint(root=str(root), keep_max=3)  # startup: ttl=0
    assert not os.path.exists(leak)
    assert not os.path.exists(fresh_leak)  # startup sweep owns the root
    assert ckpt.validate_checkpoint(d1) is None  # published shards intact
    np.testing.assert_array_equal(
        np.asarray(ckpt.load_state(d1)["w_dp"]), tree["w_dp"])

    # periodic path: a LIVE sibling's fresh staging file survives the TTL
    # sweep, a stale one does not
    with open(leak, "wb") as f:
        f.write(b"torn")
    os.utime(leak, (1.0, 1.0))
    with open(fresh_leak, "wb") as f:
        f.write(b"in-flight")
    ac._sweep_orphans(ttl=3600.0)
    assert not os.path.exists(leak)
    assert os.path.exists(fresh_leak)
    os.remove(fresh_leak)


class _FakeStep:
    """The minimal surface TrainingSupervisor needs of a train step."""

    def __init__(self, value):
        self._count = 0
        self.mesh = None
        self.restored = None
        self._value = value

    def state_dict(self):
        return {"params": {"w": np.full((4,), self._value, np.float32)},
                "count": self._count}

    def set_state_dict(self, state):
        self.restored = state
        self._count = int(state.get("count", 0))


def test_supervisor_falls_back_to_newest_complete(tmp_path):
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)

    root = str(tmp_path / "sup")
    policy = RecoveryPolicy(checkpoint_dir=root, save_interval_steps=1,
                            async_save=False, preemption=False)
    step = _FakeStep(value=1.0)
    sup = TrainingSupervisor(step, policy)
    step._count = 1
    sup.save_now()
    step._value, step._count = 2.0, 2
    sup.save_now()
    # the newest snapshot loses a shard post-save
    d2 = os.path.join(root, "step_2")
    victim = next(f for f in sorted(os.listdir(d2)) if f.endswith(".npy"))
    os.remove(os.path.join(d2, victim))

    fresh = _FakeStep(value=0.0)
    sup2 = TrainingSupervisor(fresh, policy)
    sup2.restore()
    np.testing.assert_array_equal(fresh.restored["params"]["w"],
                                  np.full((4,), 1.0, np.float32))
    assert fresh._count == 1


# ------------------------------------------------------------ the full proof
@pytest.mark.slow
def test_chaos_soak_elastic_quick_passes():
    """Train on 8 devices -> kill -> resume resharded on 4 -> kill ->
    regrow to 8 -> final loss parity with an uninterrupted run (4
    subprocesses, ~1-2 min)."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_soak.py"),
         "--elastic", "--quick"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=800)
    assert p.returncode == 0, p.stdout[-3000:]
    assert "PASS (elastic)" in p.stdout
