"""Optimizer + LR scheduler + TrainStep tests."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.nn import param_state
from paddle_tpu.nn.clip import ClipGradByGlobalNorm
from paddle_tpu.optimizer import SGD, Adam, AdamW, Lamb, Momentum, RMSProp
from paddle_tpu.optimizer.lr import (
    CosineAnnealingDecay, LinearWarmup, NoamDecay, PiecewiseDecay, StepDecay)


def _quadratic_params():
    return {"w": pt.to_tensor(np.array([5.0, -3.0], np.float32))}


def _quadratic_grads(params):
    # d/dw of 0.5*||w||^2 = w
    return {"w": params["w"]}


@pytest.mark.parametrize("opt_cls,kwargs", [
    (SGD, {}),
    (Momentum, {"momentum": 0.9}),
    (Adam, {}),
    (AdamW, {"weight_decay": 0.01}),
    (RMSProp, {}),
    (Lamb, {}),
])
def test_optimizers_descend(opt_cls, kwargs):
    opt = opt_cls(learning_rate=0.1, **kwargs)
    params = _quadratic_params()
    state = opt.init(params)
    loss0 = float(np.sum(np.asarray(params["w"]) ** 2))
    for _ in range(50):
        grads = _quadratic_grads(params)
        params, state = opt.update(grads, state, params)
    loss1 = float(np.sum(np.asarray(params["w"]) ** 2))
    assert loss1 < loss0 * 0.5


def test_sgd_exact_step():
    opt = SGD(learning_rate=0.5)
    params = {"w": pt.to_tensor([2.0, 4.0])}
    state = opt.init(params)
    new_params, _ = opt.update({"w": pt.to_tensor([1.0, 1.0])}, state, params)
    np.testing.assert_allclose(np.asarray(new_params["w"]), [1.5, 3.5], rtol=1e-6)


def test_adam_matches_reference_impl():
    # one step of Adam against hand-computed update
    opt = Adam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=1e-8)
    params = {"w": pt.to_tensor([1.0])}
    state = opt.init(params)
    g = np.array([0.5], np.float32)
    new_params, _ = opt.update({"w": pt.to_tensor(g)}, state, params)
    m = 0.1 * g
    v = 0.001 * g**2
    m_hat = m / (1 - 0.9)
    v_hat = v / (1 - 0.999)
    expect = 1.0 - 0.1 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(np.asarray(new_params["w"]), expect, rtol=1e-5)


def test_adamw_decoupled_decay():
    opt = AdamW(learning_rate=0.1, weight_decay=0.1)
    params = {"w": pt.to_tensor([1.0])}
    state = opt.init(params)
    # zero grad: AdamW still decays the weight
    new_params, _ = opt.update({"w": pt.to_tensor([0.0])}, state, params)
    assert float(new_params["w"][0]) < 1.0


def test_global_norm_clip():
    clip = ClipGradByGlobalNorm(1.0)
    grads = {"a": pt.to_tensor([3.0, 4.0])}  # norm 5
    clipped = clip(grads)
    np.testing.assert_allclose(np.asarray(clipped["a"]), [0.6, 0.8], rtol=1e-5)
    # below threshold: untouched
    small = {"a": pt.to_tensor([0.3, 0.4])}
    np.testing.assert_allclose(np.asarray(clip(small)["a"]), [0.3, 0.4], rtol=1e-5)


def test_lr_schedulers():
    s = StepDecay(0.1, step_size=10, gamma=0.5)
    assert abs(float(s.value_at(0)) - 0.1) < 1e-7
    assert abs(float(s.value_at(10)) - 0.05) < 1e-7
    assert abs(float(s.value_at(25)) - 0.025) < 1e-7

    c = CosineAnnealingDecay(0.1, T_max=100)
    assert abs(float(c.value_at(0)) - 0.1) < 1e-7
    assert abs(float(c.value_at(100))) < 1e-7

    w = LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    assert abs(float(w.value_at(5)) - 0.05) < 1e-7
    assert abs(float(w.value_at(20)) - 0.1) < 1e-7

    p = PiecewiseDecay([10, 20], [0.1, 0.01, 0.001])
    assert abs(float(p.value_at(5)) - 0.1) < 1e-8
    assert abs(float(p.value_at(15)) - 0.01) < 1e-8
    assert abs(float(p.value_at(25)) - 0.001) < 1e-8

    n = NoamDecay(512, 4000)
    assert float(n.value_at(1)) < float(n.value_at(4000))

    # stateful API
    s2 = StepDecay(0.1, step_size=2, gamma=0.1)
    assert abs(s2.get_lr() - 0.1) < 1e-7
    s2.step()
    s2.step()
    assert abs(s2.get_lr() - 0.01) < 1e-7


def test_scheduler_inside_optimizer():
    sched = StepDecay(0.5, step_size=1000, gamma=0.1)
    opt = SGD(learning_rate=sched)
    params = {"w": pt.to_tensor([1.0])}
    state = opt.init(params)
    new_params, state = opt.update({"w": pt.to_tensor([1.0])}, state, params)
    np.testing.assert_allclose(np.asarray(new_params["w"]), [0.5], rtol=1e-6)


def test_train_step_end_to_end():
    """The minimum end-to-end slice: model -> loss -> grad -> update, jitted."""

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 1)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    model = MLP()
    opt = Adam(learning_rate=0.01)
    x = np.random.randn(32, 8).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)

    step = pt.TrainStep(model, opt,
                        loss_fn=lambda out, batch: F.mse_loss(out, batch[1]))
    losses = [float(step((x, y))) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.5


def test_train_step_with_batchnorm_and_dropout():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 8)
            self.bn = nn.BatchNorm1D(8, data_format="NLC")
            self.drop = nn.Dropout(0.2)
            self.out = nn.Linear(8, 1)

        def forward(self, x):
            h = self.bn(F.relu(self.fc(x)))
            return self.out(self.drop(h))

    model = Net()
    model.train()
    opt = SGD(learning_rate=0.05)
    x = np.random.randn(16, 3, 4).astype(np.float32)
    y = np.random.randn(16, 3, 1).astype(np.float32)
    step = pt.TrainStep(model, opt, loss_fn=lambda out, b: F.mse_loss(out, b[1]))
    l0 = float(step((x, y)))
    for _ in range(30):
        l1 = float(step((x, y)))
    assert l1 < l0
    # buffers updated inside the compiled step
    assert step._count == 31
    assert not np.allclose(np.asarray(step.buffers["bn._mean"]), 0.0)


def test_train_step_checkpoint_resume(tmp_path):
    model = nn.Linear(4, 1)
    opt = Adam(learning_rate=0.01)
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 1).astype(np.float32)
    step = pt.TrainStep(model, opt, loss_fn=lambda o, b: F.mse_loss(o, b[1]))
    for _ in range(5):
        step((x, y))
    path = str(tmp_path / "ckpt.pd")
    pt.save(step.state_dict(), path)
    ref_next = float(step((x, y)))

    model2 = nn.Linear(4, 1)
    opt2 = Adam(learning_rate=0.01)
    step2 = pt.TrainStep(model2, opt2, loss_fn=lambda o, b: F.mse_loss(o, b[1]))
    step2.set_state_dict(pt.load(path))
    resumed_next = float(step2((x, y)))
    np.testing.assert_allclose(resumed_next, ref_next, rtol=1e-5)


def test_adamw_bf16_moment_dtype_descends():
    """moment_dtype='bfloat16' stores moment1 in bf16 (2 bytes/param off
    optimizer-state HBM — part of fitting GPT-1.3B on a 16 GB v5e,
    bench.py:bench_gpt_1p3b); the update math stays f32 and must still
    descend close to the f32-slot path."""
    import jax.numpy as jnp

    def run(moment_dtype):
        pt.seed(0)
        model = nn.Linear(8, 1)
        opt = AdamW(learning_rate=0.05, moment_dtype=moment_dtype)
        step = pt.TrainStep(model, opt,
                            loss_fn=lambda o, b: F.mse_loss(o, b[1]))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = (x @ rng.standard_normal((8, 1))).astype(np.float32)
        losses = [float(step((x, y))) for _ in range(60)]
        return losses, step.opt_state

    losses_bf16, state = run("bfloat16")
    assert state["moment1"]["weight"].dtype == jnp.bfloat16
    # moment2 must stay f32 regardless: its 0.999-EMA moves ~0.1%/step,
    # below bf16 half-ULP (~0.39%), so a bf16 slot would freeze forever
    assert state["moment2"]["weight"].dtype == jnp.float32
    assert losses_bf16[-1] < 0.25 * losses_bf16[0]
    losses_f32, state_f32 = run(None)
    assert state_f32["moment1"]["weight"].dtype == jnp.float32
    # bf16 slot rounding perturbs but must not derail the trajectory
    assert abs(losses_bf16[-1] - losses_f32[-1]) < 0.15 * losses_f32[0] + 1e-3
