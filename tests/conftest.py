"""Test config: force CPU backend with 8 virtual devices.

This is the reference's "distributed tests without a cluster" mechanism
rebuilt for XLA (SURVEY §4: fake_cpu_device / subprocess clusters ->
host-platform simulated mesh).

Note: the TPU-tunnel site customization pins ``jax_platforms`` via config (not
just env), so we override the config value and reset backends before any
device query.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# fault-injection tests trigger flight-recorder crash dumps (engine
# resets, rollbacks, hangs); keep their artifacts out of the repo tree
import tempfile  # noqa: E402

os.environ.setdefault("PT_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="pt_flight_tests_"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge  # noqa: E402

if xla_bridge.backends_are_initialized():
    xla_bridge._clear_backends()

assert jax.default_backend() == "cpu", "tests must run on the CPU backend"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP): long decode/bench subprocess
    # tests opt out of the 870 s budget with this marker
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 time budget")


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Tier-1 time-budget report: the slowest tests of this run, so the
    next offender to move behind the ``slow`` marker is visible in every
    CI log instead of requiring a separate ``--durations`` run. Call +
    setup + teardown are summed per test (a fixture-heavy test is just
    as much over budget as a slow body)."""
    durations: dict = {}
    for reports in terminalreporter.stats.values():
        for rep in reports:
            dur = getattr(rep, "duration", None)
            nodeid = getattr(rep, "nodeid", None)
            if dur is None or not nodeid:
                continue
            durations[nodeid] = durations.get(nodeid, 0.0) + dur
    if not durations:
        return
    top = sorted(durations.items(), key=lambda kv: -kv[1])[:10]
    total = sum(durations.values())
    tr = terminalreporter
    tr.write_sep("=", "slowest tests (tier-1 time budget)")
    for nodeid, dur in top:
        tr.write_line(f"{dur:8.2f}s  {nodeid}")
    tr.write_line(f"{total:8.2f}s  total across {len(durations)} tests")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
    # tests that build a global mesh (init_mesh/fleet.init) must not leak it
    # into mesh-free tests: pjit'd single-device steps would suddenly see a
    # distributed mesh and fail on sharding mismatches
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)
    # likewise the process-wide PS context: restore sync mode and drop any
    # cached communicators (they may wrap clients a fixture already closed)
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.ps import get_ps_context

    try:
        get_ps_context().configure_mode(DistributedStrategy())
    except Exception:
        pass  # a dead communicator flush must not fail the NEXT test


def pytest_sessionfinish(session, exitstatus):
    """Reap orphaned shard-server subprocesses (VERDICT r4 weak #7: eight
    graph_server orphans observed 16h after an aborted run). PDEATHSIG +
    the servers' ppid watchdog prevent new leaks; this sweeps anything
    that predates them or slipped both nets. Only processes reparented to
    init (ppid 1) are touched — live sessions still own their servers."""
    import re

    try:
        pid_dirs = os.listdir("/proc")
        with open("/proc/1/cmdline", "rb") as f:
            init_cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return  # no procfs (macOS): nothing to sweep
    if "python" in init_cmd:
        # PID 1 is itself a python process (container entrypoint) — its
        # ppid==1 children may be LIVE servers it legitimately owns, not
        # orphans (see procutil.start_ppid_watchdog's warning)
        return
    for pid_dir in pid_dirs:
        if not pid_dir.isdigit():
            continue
        try:
            with open(f"/proc/{pid_dir}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
            with open(f"/proc/{pid_dir}/stat") as f:
                stat = f.read()
            # field 4 (ppid) comes after the parenthesised comm, which may
            # itself contain spaces — split after the LAST ')'
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            continue  # raced with exit / unparseable
        if ppid == 1 and re.search(
                r"paddle_tpu\.distributed\.ps\.(graph_server|server)", cmd):
            try:
                os.kill(int(pid_dir), 9)
            except OSError:
                pass
