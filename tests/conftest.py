"""Test config: force CPU backend with 8 virtual devices.

This is the reference's "distributed tests without a cluster" mechanism
rebuilt for XLA (SURVEY §4: fake_cpu_device / subprocess clusters ->
host-platform simulated mesh).

Note: the TPU-tunnel site customization pins ``jax_platforms`` via config (not
just env), so we override the config value and reset backends before any
device query.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge  # noqa: E402

if xla_bridge.backends_are_initialized():
    xla_bridge._clear_backends()

assert jax.default_backend() == "cpu", "tests must run on the CPU backend"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
    # tests that build a global mesh (init_mesh/fleet.init) must not leak it
    # into mesh-free tests: pjit'd single-device steps would suddenly see a
    # distributed mesh and fail on sharding mismatches
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)
    # likewise the process-wide PS context: restore sync mode and drop any
    # cached communicators (they may wrap clients a fixture already closed)
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.ps import get_ps_context

    try:
        get_ps_context().configure_mode(DistributedStrategy())
    except Exception:
        pass  # a dead communicator flush must not fail the NEXT test
