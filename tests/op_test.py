"""OpTest harness — the analogue of the reference's
``python/paddle/fluid/tests/unittests/op_test.py:333`` (numpy-reference
output checking + numeric-vs-analytic gradient checking with per-dtype
tolerances), rebuilt for a functional framework: an "op" here is any pure
function of jax arrays.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_TOLS = {
    np.dtype(np.float32): dict(rtol=1e-5, atol=1e-6),
    np.dtype(np.float64): dict(rtol=1e-7, atol=1e-8),
    np.dtype(np.float16): dict(rtol=1e-2, atol=1e-3),
    np.dtype("bfloat16") if "bfloat16" in np.sctypeDict else None: None,
}


def _tols(dtype, rtol=None, atol=None):
    d = jnp.dtype(dtype)
    if d == jnp.bfloat16:
        base = dict(rtol=2e-2, atol=2e-2)
    elif d == jnp.float16:
        base = dict(rtol=1e-2, atol=1e-3)
    elif d == jnp.float64:
        base = dict(rtol=1e-7, atol=1e-8)
    else:
        base = dict(rtol=1e-5, atol=1e-6)
    if rtol is not None:
        base["rtol"] = rtol
    if atol is not None:
        base["atol"] = atol
    return base


def check_output(fn: Callable, args: Sequence, expect, rtol=None, atol=None, jit_check=True):
    """Run ``fn`` eagerly and (optionally) under jit; compare to numpy ref."""
    out = fn(*args)
    _assert_close(out, expect, rtol, atol, "eager")
    if jit_check:
        out_jit = jax.jit(fn)(*args)
        _assert_close(out_jit, expect, rtol, atol, "jit")


def _assert_close(got, expect, rtol, atol, tag):
    got_leaves = jax.tree.leaves(got)
    exp_leaves = jax.tree.leaves(expect)
    assert len(got_leaves) == len(exp_leaves), f"[{tag}] structure mismatch"
    for g, e in zip(got_leaves, exp_leaves):
        g = np.asarray(g, dtype=np.float64) if jnp.issubdtype(jnp.asarray(g).dtype, np.floating) else np.asarray(g)
        e = np.asarray(e)
        tols = _tols(jnp.asarray(got_leaves[0]).dtype, rtol, atol)
        np.testing.assert_allclose(g, e.astype(g.dtype) if g.dtype != e.dtype else e,
                                   rtol=tols["rtol"], atol=tols["atol"], err_msg=f"[{tag}]")


def check_grad(fn: Callable, args: Sequence, arg_idx: int = 0, eps: float = 1e-3,
               rtol: float = 5e-2, atol: float = 1e-3, reduce_fn=None):
    """Compare analytic grad (jax.grad) vs central finite differences for
    float32/float64 inputs — the reference's ``check_grad`` contract."""
    args = [jnp.asarray(a) for a in args]

    if reduce_fn is None:
        reduce_fn = lambda out: jnp.sum(jnp.asarray(out))  # noqa: E731

    def scalar_fn(x):
        new_args = list(args)
        new_args[arg_idx] = x
        return reduce_fn(fn(*new_args))

    x0 = args[arg_idx].astype(jnp.float64) if args[arg_idx].dtype == jnp.float64 else args[arg_idx]
    analytic = np.asarray(jax.grad(scalar_fn)(x0), dtype=np.float64)

    x_np = np.asarray(x0, dtype=np.float64)
    numeric = np.zeros_like(x_np)
    flat = x_np.reshape(-1)
    num_flat = numeric.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = float(scalar_fn(jnp.asarray(x_np.reshape(x_np.shape), x0.dtype)))
        flat[i] = orig - eps
        fm = float(scalar_fn(jnp.asarray(x_np.reshape(x_np.shape), x0.dtype)))
        flat[i] = orig
        num_flat[i] = (fp - fm) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)


class OpTest:
    """Subclass-style harness:

    class TestAdd(OpTest):
        def setup(self):
            self.fn = paddle_tpu.add
            self.inputs = (np.random.rand(3, 4), np.random.rand(3, 4))
            self.ref = lambda x, y: x + y

    gives output checks across dtypes + grad checks for free via
    ``run_output_checks`` / ``run_grad_checks``.
    """

    fn: Callable
    inputs: tuple
    ref: Callable
    dtypes = ("float32",)
    grad_args: Optional[Sequence[int]] = (0,)

    def setup(self):
        raise NotImplementedError

    def run_output_checks(self, rtol=None, atol=None):
        self.setup()
        for dt in self.dtypes:
            args = [jnp.asarray(np.asarray(a), dtype=jnp.dtype(dt))
                    if np.issubdtype(np.asarray(a).dtype, np.floating) else jnp.asarray(a)
                    for a in self.inputs]
            np_args = [np.asarray(a, dtype=np.float64)
                       if np.issubdtype(np.asarray(a).dtype, np.floating) else np.asarray(a)
                       for a in self.inputs]
            expect = self.ref(*np_args)
            check_output(self.fn, args, expect, rtol=rtol, atol=atol)

    def run_grad_checks(self, **kw):
        self.setup()
        if not self.grad_args:
            return
        args = [jnp.asarray(np.asarray(a), dtype=jnp.float32) if np.issubdtype(np.asarray(a).dtype, np.floating)
                else jnp.asarray(a) for a in self.inputs]
        for idx in self.grad_args:
            check_grad(self.fn, args, arg_idx=idx, **kw)
