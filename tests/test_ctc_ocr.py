"""CTC loss + CRNN recognition (PP-OCR-class coverage; reference
nn/functional/loss.py:1736 warpctc, PaddleOCR recognition branch)."""
import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def brute_force_ctc(log_probs, label, blank=0):
    """-log P(label) by enumerating every alignment path."""
    T, C = log_probs.shape
    p = np.exp(np.asarray(log_probs, np.float64))
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        # collapse repeats then drop blanks
        collapsed = [k for k, _ in itertools.groupby(path) if k != blank]
        if collapsed == list(label):
            prob = 1.0
            for t, k in enumerate(path):
                prob *= p[t, k]
            total += prob
    return -np.log(total)


@pytest.mark.parametrize("label", [[1], [1, 2], [1, 1], [2, 1, 2]])
def test_ctc_loss_matches_brute_force(label):
    rng = np.random.default_rng(hash(tuple(label)) % 2**31)
    T, C = 5, 3
    logits = rng.normal(size=(T, 1, C)).astype(np.float32)
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))[:, 0]
    want = brute_force_ctc(logp, label)
    S = len(label)
    got = F.ctc_loss(jnp.asarray(logits),
                     jnp.asarray([label], jnp.int32),
                     jnp.asarray([T], jnp.int32),
                     jnp.asarray([S], jnp.int32), reduction="none")
    np.testing.assert_allclose(float(got[0]), want, rtol=1e-4)


def test_ctc_loss_batched_lengths_and_grad():
    rng = np.random.default_rng(0)
    T, B, C = 6, 3, 4
    logits = jnp.asarray(rng.normal(size=(T, B, C)), jnp.float32)
    labels = jnp.asarray([[1, 2, 0], [3, 0, 0], [2, 2, 1]], jnp.int32)
    in_len = jnp.asarray([6, 4, 5], jnp.int32)
    lab_len = jnp.asarray([2, 1, 3], jnp.int32)
    loss = F.ctc_loss(logits, labels, in_len, lab_len, reduction="none")
    assert loss.shape == (3,)
    assert np.isfinite(np.asarray(loss)).all()
    # per-sample parity with the single-sample path
    for b in range(B):
        single = F.ctc_loss(logits[:int(in_len[b]), b:b + 1],
                            labels[b:b + 1, :int(lab_len[b])],
                            in_len[b:b + 1], lab_len[b:b + 1],
                            reduction="none")
        np.testing.assert_allclose(float(loss[b]), float(single[0]),
                                   rtol=1e-5)
    g = jax.grad(lambda lg: F.ctc_loss(lg, labels, in_len, lab_len))(logits)
    assert np.isfinite(np.asarray(g)).all()
    # frames past input_length must get zero gradient
    assert float(jnp.abs(g[4:, 1]).sum()) == 0.0
    # mean/sum reductions + CTCLoss layer + norm_by_times
    layer = nn.CTCLoss(reduction="sum")
    s = float(layer(logits, labels, in_len, lab_len))
    np.testing.assert_allclose(s, float(jnp.sum(loss)), rtol=1e-6)
    # norm_by_times: value unchanged (warpctc normalizes only the grad)
    nt = F.ctc_loss(logits, labels, in_len, lab_len, reduction="none",
                    norm_by_times=True)
    np.testing.assert_allclose(np.asarray(nt), np.asarray(loss), rtol=1e-6)
    gn = jax.grad(lambda lg: jnp.sum(F.ctc_loss(
        lg, labels, in_len, lab_len, reduction="none",
        norm_by_times=True)))(logits)
    gp = jax.grad(lambda lg: jnp.sum(F.ctc_loss(
        lg, labels, in_len, lab_len, reduction="none")))(logits)
    np.testing.assert_allclose(
        np.asarray(gn[:, 0]), np.asarray(gp[:, 0]) / 6.0, rtol=1e-5)
    # mean reduction is per-token: mean(loss_i / label_len_i)
    mm = F.ctc_loss(logits, labels, in_len, lab_len, reduction="mean")
    np.testing.assert_allclose(
        float(mm), float(jnp.mean(loss / jnp.asarray([2, 1, 3]))),
        rtol=1e-6)


def test_crnn_trains_and_decodes():
    from paddle_tpu.models.ocr import crnn_tiny
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    pt.seed(0)
    m = crnn_tiny(num_classes=5)
    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.normal(size=(2, 3, 32, 32)), jnp.float32)
    labels = jnp.asarray([[1, 2, 3], [4, 2, 0]], jnp.int32)
    lab_len = jnp.asarray([3, 2], jnp.int32)
    logits = m(imgs)
    assert logits.shape == (8, 2, 5)  # W/4 frames, time-major

    params, buffers = param_state(m), buffer_state(m)

    class Shim:
        def __init__(self, mdl):
            self._m = mdl

        def __call__(self, *a):
            return self._m.loss(*a)

        def __getattr__(self, n):
            return getattr(self._m, n)

    from paddle_tpu.optimizer import Adam

    opt = Adam(learning_rate=5e-3)
    opt_state = opt.init(params)

    def loss_fn(p):
        out, _ = functional_call(Shim(m), p, buffers, imgs, labels, lab_len)
        return out

    @jax.jit
    def step(params, opt_state):
        l, g = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(g, opt_state, params)
        return l, params, opt_state

    losses = []
    for _ in range(120):
        l, params, opt_state = step(params, opt_state)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.05, losses[-5:]
    # after fitting, greedy decode reproduces the target sequences
    m.set_state_dict({**params, **buffers})
    m.eval()
    decoded = m.decode(imgs)
    assert decoded[0] == [1, 2, 3], decoded
    assert decoded[1] == [4, 2], decoded
