"""Heterogeneous CPU<->TPU stage pipeline tests (VERDICT r2 missing #7):
in-process section-queue overlap, loss parity with the unpipelined loop,
and the multi-process RPC-backed heter-worker split
(HeterPipelineTrainer / HeterClient-HeterServer, trainer.h:345)."""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import HeterPipelineTrainer
from paddle_tpu.framework.jit import TrainStep
from paddle_tpu.optimizer import SGD

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_heter_pipeline_overlaps_stages():
    """CPU stage for batch N+1 overlaps compute for batch N: wall time of
    the pipelined loop is well under the sequential sum."""
    def cpu_stage(b):
        time.sleep(0.05)
        return b * 2

    def step(staged):
        time.sleep(0.05)
        return staged + 1

    batches = list(range(8))
    t0 = time.perf_counter()
    seq = [step(cpu_stage(b)) for b in batches]
    t_seq = time.perf_counter() - t0

    trainer = HeterPipelineTrainer(cpu_stage, step, prefetch_depth=3)
    t0 = time.perf_counter()
    out = trainer.run(batches)
    t_pipe = time.perf_counter() - t0
    trainer.stop()
    assert out == seq  # order + values preserved
    assert t_pipe < t_seq * 0.8, (t_pipe, t_seq)


def test_heter_pipeline_training_parity():
    """Sparse-pull CPU stage + compiled dense TPU step: losses are
    bit-identical to the unpipelined loop (ordering preserved)."""
    from paddle_tpu.distributed.ps import MemorySparseTable

    pt.seed(0)
    table = MemorySparseTable(embed_dim=8, optimizer="sgd",
                              learning_rate=0.5, seed=3)
    rng = np.random.default_rng(0)
    one = (rng.integers(0, 100, 16).astype(np.int64),
           rng.integers(0, 4, 16))
    batches = [one] * 6  # fixed batch: loss must fall monotonically

    def cpu_stage(batch):
        ids, labels = batch
        return table.pull(ids), labels  # host-side sparse stage

    pt.seed(1)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    step = TrainStep(model, SGD(learning_rate=0.1),
                     loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    trainer = HeterPipelineTrainer(cpu_stage, step, prefetch_depth=2)
    pipe_losses = [float(np.asarray(l)) for l in trainer.run(batches)]
    trainer.stop()

    pt.seed(1)
    model2 = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    step2 = TrainStep(model2, SGD(learning_rate=0.1),
                      loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    ref_losses = [float(np.asarray(step2(cpu_stage(b)))) for b in batches]
    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-6)
    assert pipe_losses[-1] < pipe_losses[0]


def test_heter_pipeline_cpu_stage_error_propagates():
    def cpu_stage(b):
        if b == 2:
            raise ValueError("bad batch")
        return b

    trainer = HeterPipelineTrainer(cpu_stage, lambda s: s, prefetch_depth=2)
    with pytest.raises(ValueError, match="bad batch"):
        trainer.run(range(4))
    trainer.stop()


HETER_WORKER = textwrap.dedent("""
    import sys
    from paddle_tpu.distributed import rpc

    rank = int(sys.argv[1])
    rpc.init_rpc(name=f"worker{rank}", rank=rank, world_size=3,
                 master_endpoint=sys.argv[2])
    # heter workers just serve RPCs until shutdown's barrier releases
    rpc.shutdown()
""")

TRAINER = textwrap.dedent("""
    import sys
    import numpy as np
    from paddle_tpu.distributed import HeterPipelineTrainer, rpc
    from tests.heter_stage import cpu_stage

    rpc.init_rpc(name="worker0", rank=0, world_size=3,
                 master_endpoint=sys.argv[1])
    trainer = HeterPipelineTrainer(cpu_stage, lambda s: float(s.sum()),
                                   prefetch_depth=2,
                                   heter_workers=["worker1", "worker2"])
    out = trainer.run([np.full((4,), i, np.float32) for i in range(6)])
    assert out == [i * 4.0 * 3 for i in range(6)], out
    print("HETER_RPC_OK", flush=True)
    trainer.stop()
    rpc.shutdown()
""")


def test_heter_pipeline_rpc_workers(tmp_path):
    """The multi-host split: CPU stages execute on remote heter workers by
    name over RPC; the trainer only sees dense staged tensors."""
    stage_mod = os.path.join(REPO, "tests", "heter_stage.py")
    with open(stage_mod, "w") as f:
        f.write("import numpy as np\n\n\n"
                "def cpu_stage(batch):\n"
                "    return np.asarray(batch) * 3.0\n")
    try:
        import socket as socket_mod

        probe = socket_mod.socket()
        probe.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{probe.getsockname()[1]}"
        probe.close()
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        w_script = tmp_path / "w.py"
        w_script.write_text(HETER_WORKER)
        t_script = tmp_path / "t.py"
        t_script.write_text(TRAINER)
        workers = [subprocess.Popen(
            [sys.executable, str(w_script), str(r), master], env=env,
            cwd=REPO) for r in (1, 2)]
        trainer = subprocess.run(
            [sys.executable, str(t_script), master], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=360)
        assert trainer.returncode == 0, trainer.stderr
        assert "HETER_RPC_OK" in trainer.stdout
        for w in workers:
            assert w.wait(timeout=60) == 0
    finally:
        os.unlink(stage_mod)


def test_rpc_executor_bounds_stage_calls(monkeypatch):
    """tpu_lint R11 regression: the heter RPC executor passes its
    rpc_timeout into every stage call (a dead heter worker must fail
    the micro-batch at the trainer's deadline, not hang 120s)."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.heter import _RpcExecutor

    seen = []

    def fake_rpc_async(to, fn, args=None, kwargs=None, timeout=None, **kw):
        seen.append((to, timeout))
        return "fut"

    monkeypatch.setattr(rpc, "rpc_async", fake_rpc_async)
    ex = _RpcExecutor(lambda b: b, ["w1", "w2"], rpc_timeout=7.0)
    assert ex.submit([1]) == "fut"
    assert ex.submit([2]) == "fut"
    assert seen == [("w1", 7.0), ("w2", 7.0)]
