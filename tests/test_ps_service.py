"""Multi-host PS service tests: subprocess server cluster, client-side key
partitioning, communicator modes, barrier — the reference's
``test_dist_base.py`` subprocess-cluster pattern (SURVEY §4) applied to the
TCP PS service."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt  # noqa: F401  (native build side effect)
from paddle_tpu.distributed.ps import (Communicator, MemorySparseTable,
                                       PsClient, PsServer, SparseAccessorConfig,
                                       SparseEmbedding, launch_servers,
                                       shard_of)

DIM = 4


def make_local(optimizer="sgd", lr=1.0, seed=11):
    return MemorySparseTable(SparseAccessorConfig(
        embed_dim=DIM, optimizer=optimizer, learning_rate=lr, seed=seed))


@pytest.fixture(scope="module")
def cluster():
    """Two PS server subprocesses + a connected client."""
    procs, endpoints = launch_servers(
        2, embed_dim=DIM, optimizer="sgd", learning_rate=1.0, seed=11)
    client = PsClient(endpoints, embed_dim=DIM)
    yield client
    client.stop_servers()
    client.close()
    for p in procs:
        p.wait(timeout=10)


def test_shard_of_matches_cpp_router():
    """Python splitmix64 must agree with the C++ shard router bit-for-bit:
    keys pulled through a 16-shard table land where shard_of says (we can't
    observe C++ shards directly, so check the known vector instead)."""
    # splitmix64(0) == 0xe220a8397b1dcdaf (published test vector)
    from paddle_tpu.distributed.ps.service import _splitmix64
    assert _splitmix64(np.array([0], np.uint64))[0] == np.uint64(
        0xE220A8397B1DCDAF)


@pytest.mark.parametrize("num_servers", [2, 4, 16])
def test_server_routing_decorrelated_from_table_shards(num_servers):
    """Keys routed to ONE server must still spread over the table's 16
    internal splitmix64-mod-16 shards: server routing uses the hash's upper
    bits precisely so power-of-two server counts don't funnel each server's
    keys into hash ≡ s (mod 16) residues (which at 16 servers would pile
    every key onto a single internal shard mutex)."""
    from paddle_tpu.distributed.ps.service import _splitmix64
    keys = np.arange(200_000, dtype=np.int64)
    sid = shard_of(keys, num_servers)
    mine = keys[sid == 0]
    internal = _splitmix64(mine.view(np.uint64)) % np.uint64(16)
    counts = np.bincount(internal.astype(np.int64), minlength=16)
    # every internal shard populated, none dominating
    assert (counts > 0).all()
    assert counts.max() < 4 * counts.mean()


def test_pull_parity_with_local_table(cluster):
    """Deterministic per-(seed, key) init means the distributed pull matches
    a local table with the same accessor, regardless of which server owns
    each key."""
    local = make_local()
    keys = np.arange(100, dtype=np.int64)
    np.testing.assert_array_equal(cluster.pull(keys), local.pull(keys))


def test_push_parity_and_routing(cluster):
    local = make_local()
    rng = np.random.default_rng(0)
    keys = rng.integers(1000, 2000, 64).astype(np.int64)
    grads = rng.normal(size=(64, DIM)).astype(np.float32)
    # warm both (init), then push identical grads
    cluster.pull(keys)
    local.pull(keys)
    cluster.push(keys, grads)
    local.push(keys, grads)
    np.testing.assert_allclose(cluster.pull(keys), local.pull(keys), rtol=1e-6)
    # keys really are spread over both servers
    sid = shard_of(np.unique(keys), 2)
    assert 0 < sid.sum() < sid.size


def test_size_keys_save_load(cluster, tmp_path):
    before = len(cluster)
    cluster.pull(np.arange(5000, 5010))
    assert len(cluster) >= before + 10
    ks = set(cluster.keys().tolist())
    assert set(range(5000, 5010)) <= ks
    path = str(tmp_path / "snap")
    cluster.save(path)
    rows = cluster.pull(np.arange(5000, 5010))
    cluster.push(np.arange(5000, 5010), np.ones((10, DIM), np.float32))
    cluster.load(path)  # overwrite restores snapshot
    np.testing.assert_array_equal(cluster.pull(np.arange(5000, 5010)), rows)


def test_barrier_releases_world(cluster):
    order = []

    def worker(i):
        cluster.barrier(world=3)
        order.append(i)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    time.sleep(0.3)
    assert order == []  # 2 of 3 arrived: nobody released
    cluster.barrier(world=3)
    for t in ts:
        t.join(timeout=5)
    assert sorted(order) == [0, 1]


def test_async_communicator_parity(cluster):
    """Async-merged pushes equal the same merged grads applied locally (SGD
    is order/merge-invariant, so parity is exact)."""
    local = make_local()
    rng = np.random.default_rng(3)
    keys = np.arange(9000, 9032, dtype=np.int64)
    cluster.pull(keys)
    local.pull(keys)
    comm = Communicator(cluster, mode="async")
    total = np.zeros((keys.size, DIM), np.float32)
    for _ in range(10):
        g = rng.normal(size=(keys.size, DIM)).astype(np.float32)
        comm.push(keys, g)
        total += g
    comm.stop()
    local.push(keys, total)
    # the drain thread coalesces a nondeterministic number of batches, so
    # summation order differs from the single local push by float epsilon
    np.testing.assert_allclose(cluster.pull(keys), local.pull(keys),
                               rtol=1e-4, atol=1e-6)


def test_geo_communicator_buffers_k_steps(cluster):
    keys = np.arange(9500, 9504, dtype=np.int64)
    base = cluster.pull(keys)
    comm = Communicator(cluster, mode="geo", k_steps=4)
    for _ in range(3):
        comm.push(keys, np.ones((keys.size, DIM), np.float32))
    np.testing.assert_array_equal(cluster.pull(keys), base)  # buffered
    comm.push(keys, np.ones((keys.size, DIM), np.float32))  # 4th triggers
    np.testing.assert_allclose(cluster.pull(keys), base - 4.0, rtol=1e-6)
    comm.stop()


def test_sparse_embedding_over_network(cluster):
    """SparseEmbedding trains through the PsClient transparently: grads flow
    through the jit callback -> TCP -> C++ optimizer rule."""
    import jax
    import jax.numpy as jnp

    emb = SparseEmbedding(DIM, table=cluster)
    target = jnp.asarray(np.random.default_rng(5).normal(size=(6, DIM)),
                         jnp.float32)
    ids = jnp.asarray(np.arange(7000, 7006))

    def loss_fn(anchor):
        e = emb._lookup(ids, anchor)
        return jnp.mean((e - target) ** 2)

    step = jax.jit(jax.value_and_grad(loss_fn))
    # mean-MSE grads carry a 1/(6*DIM) factor; lr 5 keeps the SGD contraction
    # per step at ~0.58 so 15 steps shrink the loss by >10x
    cluster.set_learning_rate(5.0)
    losses = [float(step(emb.grad_anchor)[0]) for _ in range(15)]
    cluster.set_learning_rate(1.0)
    assert losses[-1] < losses[0] * 0.1


def test_client_retries_across_server_restart(tmp_path):
    """Kill the PS server mid-run and bring it back on the same port: the
    client reconnects with backoff and resumes, state restored from the
    snapshot (brpc_ps_client.cc retry semantics)."""
    import socket as socket_mod
    import subprocess
    import sys

    # reserve a port for the restart
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    argv = [sys.executable, "-m", "paddle_tpu.distributed.ps.server",
            "--port", str(port), "--embed-dim", str(DIM),
            "--optimizer", "sgd", "--lr", "1.0", "--seed", "11"]
    from paddle_tpu.distributed.ps.service import launch_port_subprocesses

    procs, eps = launch_port_subprocesses([argv])
    client = PsClient(eps, embed_dim=DIM, retries=8, retry_delay=0.25)
    keys = np.arange(100, dtype=np.int64)
    client.pull(keys)
    client.push(keys, np.ones((100, DIM), np.float32))
    before = client.pull(keys)
    snap = str(tmp_path / "restart-snap")
    client.save(snap)

    procs[0].kill()
    procs[0].wait(timeout=10)
    # client request now fails over dead endpoint... bring the server back
    procs2, eps2 = launch_port_subprocesses(
        [argv + ["--load", f"{snap}.shard0"]])
    assert eps2[0][1] == port
    after = client.pull(keys)  # reconnects transparently
    # snapshot row values survive (pull increments show, values unchanged)
    np.testing.assert_array_equal(after, before)
    client.push(keys, np.ones((100, DIM), np.float32))  # training continues
    np.testing.assert_allclose(client.pull(keys), before - 1.0)
    client.stop_servers()
    client.close()
    procs2[0].wait(timeout=10)


def test_dense_survives_server_restart(tmp_path):
    """The dense sidecar is restored on server restart with --load: dense
    weights resume alongside sparse ones instead of silently zeroing."""
    import socket as socket_mod
    import sys

    from paddle_tpu.distributed.ps.service import launch_port_subprocesses

    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    argv = [sys.executable, "-m", "paddle_tpu.distributed.ps.server",
            "--port", str(port), "--embed-dim", str(DIM),
            "--optimizer", "sgd", "--lr", "1.0", "--seed", "11"]
    procs, eps = launch_port_subprocesses([argv])
    client = PsClient(eps, embed_dim=DIM, retries=8, retry_delay=0.25)
    client.dense_init(17, optimizer="sgd", learning_rate=1.0)
    vals = np.arange(17, dtype=np.float32)
    client.dense_set(vals)
    client.dense_push(np.ones(17, np.float32))  # vals - 1
    snap = str(tmp_path / "dense-snap")
    client.save(snap)
    procs[0].kill()
    procs[0].wait(timeout=10)
    procs2, _ = launch_port_subprocesses(
        [argv + ["--load", f"{snap}.shard0"]])
    client.dense_init(17, optimizer="sgd", learning_rate=1.0)  # idempotent
    np.testing.assert_allclose(client.dense_pull(), vals - 1.0)
    client.dense_push(np.ones(17, np.float32))  # training continues
    np.testing.assert_allclose(client.dense_pull(), vals - 2.0)
    client.stop_servers()
    client.close()
    procs2[0].wait(timeout=10)


def test_dense_parameter_path(cluster):
    """Dense params shard block-wise across servers; pull/push/set match a
    local MemoryDenseTable (MemoryDenseTable over the wire)."""
    from paddle_tpu.distributed.ps import MemoryDenseTable

    L = 101  # odd length: uneven blocks
    local = MemoryDenseTable(L, optimizer="sgd", learning_rate=1.0)
    cluster.dense_init(L, optimizer="sgd", learning_rate=1.0)
    rng = np.random.default_rng(1)
    init = rng.normal(size=L).astype(np.float32)
    local.set(init)
    cluster.dense_set(init)
    np.testing.assert_array_equal(cluster.dense_pull(), local.pull())
    for _ in range(3):
        g = rng.normal(size=L).astype(np.float32)
        local.push(g)
        cluster.dense_push(g)
    np.testing.assert_allclose(cluster.dense_pull(), local.pull(), rtol=1e-6)
    # idempotent re-init keeps values (reconnecting worker)
    cluster.dense_init(L, optimizer="sgd", learning_rate=1.0)
    np.testing.assert_allclose(cluster.dense_pull(), local.pull(), rtol=1e-6)


def test_show_click_accessor_shrink():
    """CTR usage stats: shrink evicts on decayed show+click score, so
    clicked keys survive eviction that drops cold ones."""
    t = make_local()
    keys = np.arange(20, dtype=np.int64)
    t.pull(keys)  # all keys now have show=1
    hot = keys[:5]
    t.push_show_click(hot, shows=np.full(5, 10.0), clicks=np.full(5, 3.0))
    dropped = t.shrink(threshold=5.0)  # score: hot=14, cold=1
    assert dropped == 15
    assert set(t.keys().tolist()) == set(hot.tolist())


def test_geo_communicator_delta_train(cluster):
    """Geo mode ships parameter DELTAS from a locally-trained shadow, not
    raw grads: local training is visible immediately through comm.pull
    (zero lag locally), the server only moves every k steps, and the
    merged server value equals base - lr * sum(grads) for SGD."""
    keys = np.arange(9700, 9704, dtype=np.int64)
    base = cluster.pull(keys)
    comm = Communicator(cluster, mode="geo", k_steps=3, geo_lr=1.0)
    g = np.ones((keys.size, DIM), np.float32)
    comm.push(keys, g)
    # local shadow already trained; server untouched
    np.testing.assert_allclose(comm.pull(keys), base - 1.0, rtol=1e-6)
    np.testing.assert_array_equal(cluster.pull(keys), base)
    comm.push(keys, g)
    comm.push(keys, g)  # 3rd push triggers the delta ship
    np.testing.assert_allclose(cluster.pull(keys), base - 3.0, rtol=1e-6)
    # after re-base, another cycle composes additively
    comm.push(keys, 2 * g)
    comm.stop()  # flush ships the remaining delta
    np.testing.assert_allclose(cluster.pull(keys), base - 5.0, rtol=1e-6)


def test_inproc_server_roundtrip():
    """PsServer can also host in-process (single-host multi-shard tests)."""
    srv = PsServer(SparseAccessorConfig(embed_dim=DIM, optimizer="sgd",
                                        learning_rate=1.0, seed=7))
    client = PsClient([("127.0.0.1", srv.port)], embed_dim=DIM)
    local = make_local(seed=7)
    keys = np.arange(10, dtype=np.int64)
    np.testing.assert_array_equal(client.pull(keys), local.pull(keys))
    client.push(keys, np.ones((10, DIM), np.float32))
    local.push(keys, np.ones((10, DIM), np.float32))
    np.testing.assert_array_equal(client.pull(keys), local.pull(keys))
    client.close()
    srv.stop()


def test_strategy_a_sync_selects_communicator_mode(cluster):
    """strategy.a_sync / a_sync_configs drive the PS communicator mode
    (reference the_one_ps.py mode selection)."""
    import numpy as np

    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.ps import get_ps_context

    ctx = get_ps_context()
    s = DistributedStrategy()
    assert ctx.configure_mode(s) == "sync"
    s.a_sync = True
    s.a_sync_configs = {"k_steps": 0}
    assert ctx.configure_mode(s) == "async"
    s.a_sync_configs = {"k_steps": 8}
    assert ctx.configure_mode(s) == "geo"
    comm = ctx.communicator_for(cluster)
    assert comm.mode == "geo" and comm.k_steps == 8
    assert ctx.communicator_for(cluster) is comm  # cached
    # pushes buffer for k steps then land
    keys = np.arange(9900, 9904, dtype=np.int64)
    base = cluster.pull(keys)
    for _ in range(8):
        comm.push(keys, np.ones((keys.size, DIM), np.float32))
    np.testing.assert_allclose(cluster.pull(keys), base - 8.0, rtol=1e-6)
    ctx.stop_server()  # flush + drop communicators
    assert ctx.communicator_for(cluster) is not comm
    # fleet.init wires it (mesh side effects reset by conftest)
    s2 = DistributedStrategy()
    s2.a_sync = True
    fleet.init(strategy=s2)
    assert ctx.mode == "async"
