"""jit.save/load (StableHLO export) + inference Predictor tests.

Reference test model: dygraph-to-static save/load parity tests
(``python/paddle/fluid/tests/unittests/dygraph_to_static/``, SURVEY.md §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit import InputSpec, TranslatedLayer


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)
        self.bn = nn.BatchNorm1D(16)
        self.drop = nn.Dropout(0.5)

    def forward(self, x):
        h = nn.functional.relu(self.bn(self.fc1(x)))
        return self.fc2(self.drop(h))


def test_save_load_value_parity(tmp_path):
    net = SmallNet()
    net.eval()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
    want = np.asarray(net(x))
    path = str(tmp_path / "model" / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((4, 8), "float32")])
    loaded = pt.jit.load(path)
    assert isinstance(loaded, TranslatedLayer)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_save_captures_eval_mode(tmp_path):
    """Dropout must be inert in the exported program even if the layer was
    in train mode when saved (save() flips to eval, like the reference)."""
    net = SmallNet()
    net.train()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((2, 8), "float32")])
    assert net.training  # restored
    loaded = pt.jit.load(path)
    x = jnp.ones((2, 8), jnp.float32)
    net.eval()
    want = np.asarray(net(x))  # eval-mode reference
    np.testing.assert_allclose(np.asarray(loaded(x)), want, rtol=1e-5,
                               atol=1e-6)


def test_multi_dynamic_inputs_share_scope(tmp_path):
    """Two inputs with dynamic batch dims must export together (single
    symbolic scope)."""

    class TwoDyn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, a, b):
            return self.fc(a) + self.fc(b)

    net = TwoDyn()
    net.eval()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((None, 8), "float32"),
                                       InputSpec((None, 8), "float32")])
    loaded = pt.jit.load(path)
    out = loaded(jnp.ones((5, 8), jnp.float32), jnp.ones((5, 8), jnp.float32))
    assert out.shape == (5, 4)


def test_predictor_unset_input_clear_error(tmp_path):
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((2, 8), "float32")])
    predictor = create_predictor(Config(path))
    # output handles are addressable before the first run
    assert predictor.get_output_names() == ["out0"]
    assert predictor.get_output_handle("out0").shape is None
    with pytest.raises(RuntimeError, match="inputs not set"):
        predictor.run()


def test_predictor_cpu_device_selection(tmp_path):
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((2, 8), "float32")])
    config = Config(path)
    config.disable_gpu()
    predictor = create_predictor(config)
    x = np.ones((2, 8), np.float32)
    want = np.asarray(net(jnp.asarray(x)))
    np.testing.assert_allclose(predictor.run([x])[0], want, rtol=1e-5,
                               atol=1e-6)


def test_dynamic_batch_export(tmp_path):
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((None, 8), "float32")])
    loaded = pt.jit.load(path)
    for bs in (1, 3, 17):
        out = loaded(jnp.ones((bs, 8), jnp.float32))
        assert out.shape == (bs, 4)


def test_translated_layer_state_dict_roundtrip(tmp_path):
    net = SmallNet()
    net.eval()
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((2, 8), "float32")])
    loaded = pt.jit.load(path)
    sd = loaded.state_dict()
    assert len(sd) > 0
    # zero every param -> output changes; restore -> parity again
    x = jnp.ones((2, 8), jnp.float32)
    want = np.asarray(loaded(x))
    zeroed = {k: jnp.zeros_like(v) for k, v in sd.items()}
    loaded.set_state_dict(zeroed)
    assert not np.allclose(np.asarray(loaded(x)), want)
    loaded.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(loaded(x)), want, rtol=1e-6)


def test_predictor_handle_api(tmp_path):
    net = SmallNet()
    net.eval()
    x = np.random.default_rng(1).normal(size=(4, 8)).astype(np.float32)
    want = np.asarray(net(jnp.asarray(x)))
    path = str(tmp_path / "net")
    pt.jit.save(net, path, input_spec=[InputSpec((4, 8), "float32")])

    config = Config(path + ".pdmodel")
    predictor = create_predictor(config)
    names = predictor.get_input_names()
    assert len(names) == 1
    predictor.get_input_handle(names[0]).copy_from_cpu(x)
    outs = predictor.run()
    np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
    h = predictor.get_output_handle(predictor.get_output_names()[0])
    np.testing.assert_array_equal(h.copy_to_cpu(), outs[0])


def test_save_requires_input_spec(tmp_path):
    with pytest.raises(ValueError):
        pt.jit.save(SmallNet(), str(tmp_path / "x"))


def test_save_multi_input_and_example_arrays(tmp_path):
    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, a, b):
            return self.fc(a) + self.fc(b)

    net = TwoIn()
    net.eval()
    a = jnp.ones((3, 4), jnp.float32)
    b = jnp.full((3, 4), 2.0, jnp.float32)
    want = np.asarray(net(a, b))
    path = str(tmp_path / "two")
    pt.jit.save(net, path, input_spec=[a, b])  # concrete example arrays
    out = np.asarray(pt.jit.load(path)(a, b))
    np.testing.assert_allclose(out, want, rtol=1e-6)


# --------------------------------------- non-Python serving consumer (r3)
def test_c_api_consumer_matches_python_predictor(tmp_path):
    """The plain-C demo (tools/infer_demo.c, dlopen'ing the C inference
    API) reproduces the Python Predictor's outputs on a jit.save artifact —
    the capi_exp-style non-Python serving path, demonstrated end to end."""
    import os
    import subprocess
    import sys

    from paddle_tpu.inference import build_capi, build_demo
    from paddle_tpu.jit import save as jit_save

    pt.seed(4)
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "cmodel")
    jit_save(net, prefix, input_spec=[InputSpec([2, 8], "float32")])

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8)).astype(np.float32)
    ref = create_predictor(Config(prefix)).run([x])[0]

    lib = build_capi()
    demo = build_demo()
    inp = tmp_path / "input.bin"
    inp.write_bytes(x.tobytes())

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.getcwd()] + [p for p in sys.path if "site-packages" in p])
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [demo, lib, prefix, str(inp), "2", "8"],
        capture_output=True, text=True, env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    assert lines[0].split() == ["shape", "2", "4"]
    got = np.asarray([float(v) for v in lines[1:]], np.float32).reshape(2, 4)
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5, atol=1e-6)
