"""Distributed tests on the 8-virtual-CPU-device mesh (SURVEY §4's
"distributed without a cluster" pattern: loss parity between sharded and
single-device runs, per-API collective checks)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed.mesh import init_mesh, mesh_scope, set_mesh
from paddle_tpu.distributed.parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)


@pytest.fixture
def mesh8():
    m = init_mesh(dp=8)
    yield m
    set_mesh(None)


@pytest.fixture
def mesh24():
    m = init_mesh(dp=2, mp=4)
    yield m
    set_mesh(None)


# ------------------------------------------------------------- collectives
def test_collective_allreduce(mesh8):
    x = jnp.arange(8.0)

    f = shard_map(lambda v: C.all_reduce(v, group="dp"), mesh=mesh8,
                  in_specs=P("dp"), out_specs=P("dp"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()), rtol=1e-6)


def test_collective_allgather_alltoall(mesh8):
    x = jnp.arange(16.0).reshape(8, 2)
    g = shard_map(lambda v: C.all_gather(v, group="dp", axis=0), mesh=mesh8,
                  in_specs=P("dp", None), out_specs=P("dp", None))
    out = g(x)
    assert out.shape == (64, 2)  # each shard gathered the full 8x2

    # local shard is [1, 8]; exchange column blocks -> global transpose
    a2a = shard_map(lambda v: C.alltoall(v, group="dp", split_axis=1, concat_axis=1),
                    mesh=mesh8, in_specs=P("dp", None), out_specs=P("dp", None))
    out2 = a2a(jnp.arange(64.0).reshape(8, 8))
    np.testing.assert_allclose(np.asarray(out2), np.arange(64.0).reshape(8, 8).T)


def test_collective_broadcast_ppermute(mesh8):
    x = jnp.arange(8.0)
    b = shard_map(lambda v: C.broadcast(v, src=3, group="dp"), mesh=mesh8,
                  in_specs=P("dp"), out_specs=P("dp"))
    np.testing.assert_allclose(np.asarray(b(x)), np.full(8, 3.0))

    s = shard_map(lambda v: C.shift_right(v, group="dp"), mesh=mesh8,
                  in_specs=P("dp"), out_specs=P("dp"))
    np.testing.assert_allclose(np.asarray(s(x)), np.roll(np.arange(8.0), 1))


def test_reduce_scatter(mesh8):
    # replicated input; each rank ends up owning the psum of its row block
    x = jnp.ones((8, 8))
    f = shard_map(lambda v: C.reduce_scatter(v, group="dp"), mesh=mesh8,
                  in_specs=P(None, None), out_specs=P("dp", None))
    out = f(x)
    assert out.shape == (8, 8)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 8.0))


# ------------------------------------------------------------ DP parity
def test_data_parallel_loss_parity(mesh8):
    """The TestDistBase pattern: distributed loss == single-device loss."""

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(16, 32)
            self.fc2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    pt.seed(0)
    model = MLP()
    x = np.random.randn(32, 16).astype(np.float32)
    y = np.random.randint(0, 4, (32,))

    loss_fn = lambda out, b: F.cross_entropy(out, b[1])  # noqa: E731

    from paddle_tpu.optimizer import SGD

    # single-device reference
    ref_model = MLP()
    ref_model.set_state_dict(model.state_dict())
    ref_step = pt.TrainStep(ref_model, SGD(learning_rate=0.1), loss_fn=loss_fn)
    ref_losses = [float(ref_step((x, y))) for _ in range(5)]

    dstep = dist.DistributedTrainStep(model, SGD(learning_rate=0.1),
                                      loss_fn=loss_fn, mesh=mesh8)
    dist_losses = [float(dstep((x, y))) for _ in range(5)]
    np.testing.assert_allclose(dist_losses, ref_losses, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------ TP layers
def test_tensor_parallel_layers(mesh24):
    with mesh_scope(mesh24):
        col = ColumnParallelLinear(16, 32, gather_output=False)
        row = RowParallelLinear(32, 16, input_is_parallel=True)
        x = pt.randn([4, 8, 16])

        @jax.jit
        def run(params_col, params_row, xx):
            from paddle_tpu.nn import functional_call

            h, _ = functional_call(col, params_col, {}, xx)
            out, _ = functional_call(row, params_row, {}, h)
            return out

        from paddle_tpu.nn import param_state

        out = run(param_state(col), param_state(row), x)
        assert out.shape == (4, 8, 16)
        # numeric parity with plain matmuls
        ref = np.asarray(x) @ np.asarray(col.weight) + np.asarray(col.bias)
        ref = ref @ np.asarray(row.weight) + np.asarray(row.bias)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)

        # sharding declarations collected tree-wide
        specs = dist.param_specs(col, mesh24)
        assert specs["weight"] == P(None, "mp")


def test_vocab_parallel_embedding(mesh24):
    with mesh_scope(mesh24):
        emb = VocabParallelEmbedding(64, 16)
        idx = pt.randint(0, 64, [4, 8])
        out = emb(idx)
        ref = np.asarray(emb.weight)[np.asarray(idx)]
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_distributed_step_with_tp(mesh24):
    """DP x MP hybrid: mp-annotated layers inside a DistributedTrainStep."""

    class TPNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = ColumnParallelLinear(16, 64, gather_output=False)
            self.row = RowParallelLinear(64, 16, input_is_parallel=True)

        def forward(self, x):
            return self.row(F.relu(self.col(x)))

    from paddle_tpu.optimizer import Adam

    with mesh_scope(mesh24):
        model = TPNet()
        x = np.random.randn(8, 16).astype(np.float32)
        y = np.random.randn(8, 16).astype(np.float32)
        step = dist.DistributedTrainStep(model, Adam(learning_rate=1e-2),
                                         loss_fn=lambda o, b: F.mse_loss(o, b[1]),
                                         mesh=mesh24)
        l0 = float(step((x, y)))
        for _ in range(10):
            l1 = float(step((x, y)))
        assert l1 < l0
        # weight is actually sharded over mp
        w = step.params["col.weight"]
        assert w.sharding.spec == P(None, "mp")


# ------------------------------------------------------------ ZeRO stages
@pytest.mark.parametrize("stage", [1, 2, 3])
def test_sharding_stages(mesh8, stage):
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(64, 1024)
            self.fc2 = nn.Linear(1024, 64)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    # rename mesh axis to sdp for sharding
    m = init_mesh(sdp=8)
    from paddle_tpu.optimizer import Adam

    model = Net()
    x = np.random.randn(16, 64).astype(np.float32)
    y = np.random.randn(16, 64).astype(np.float32)
    step = dist.DistributedTrainStep(model, Adam(learning_rate=1e-3),
                                     loss_fn=lambda o, b: F.mse_loss(o, b[1]),
                                     mesh=m, batch_axes=("sdp",),
                                     sharding_stage=stage)
    l0 = float(step((x, y)))
    l1 = float(step((x, y)))
    assert np.isfinite(l1) and l1 < l0 * 1.5
    if stage >= 1:
        # optimizer moments sharded over sdp
        m1 = step.opt_state["moment1"]["fc1.weight"]
        assert "sdp" in [a for s in m1.sharding.spec if s is not None
                        for a in (s if isinstance(s, tuple) else (s,))]
    if stage >= 3:
        p = step.params["fc1.weight"]
        assert any(s == "sdp" for s in p.sharding.spec)
    set_mesh(None)


# ------------------------------------------------------------ recompute
def test_recompute_matches(mesh8):
    from paddle_tpu.distributed import recompute

    def f(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    x = jnp.asarray(np.random.randn(64).astype(np.float32))
    g1 = jax.grad(f)(x)
    g2 = jax.grad(lambda v: recompute(f, v))(x)
    # rtol 1e-5: the rematerialised tanh may fuse differently from the
    # cached one, giving ~2ulp drift on some XLA versions
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


# ------------------------------------------------------------ MoE
def test_moe_layer_forward_backward():
    from paddle_tpu.distributed.parallel.moe import MoELayer

    moe = MoELayer(d_model=16, d_hidden=32, num_experts=4, capacity_factor=2.0)
    moe.eval()
    x = pt.randn([2, 12, 16])
    out = moe(x)
    assert out.shape == (2, 12, 16)
    assert float(moe.aux_loss) >= 0

    # gradient flows to experts and gate
    from paddle_tpu.nn import functional_call, param_state

    params = param_state(moe)

    def loss(p):
        o, _ = functional_call(moe, p, {}, x)
        return jnp.sum(o ** 2)

    grads = jax.grad(loss)(params)
    assert float(jnp.abs(grads["gate_weight"]).sum()) > 0
    assert float(jnp.abs(grads["experts.w1"]).sum()) > 0


def test_moe_expert_parallel(mesh8):
    m = init_mesh(ep=4, dp=2)
    from paddle_tpu.distributed.parallel.moe import MoELayer

    with mesh_scope(m):
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=8)
        moe.eval()
        x = pt.randn([2, 16, 16])
        out = moe(x)
        assert out.shape == (2, 16, 16)
    set_mesh(None)


def test_moe_alltoall_parity_dense():
    """Sparse all2all dispatch vs the dense GShard einsums: identical
    weights + generous capacity (no drops) must give matching outputs
    (VERDICT r3 item 4; reference global_scatter_op.cu.cc)."""
    from paddle_tpu.distributed.parallel.moe import MoELayer

    for gate in ("gshard", "switch"):
        pt.seed(3)
        dense = MoELayer(d_model=16, d_hidden=32, num_experts=4, gate=gate,
                         eval_capacity_factor=8.0)
        sparse = MoELayer(d_model=16, d_hidden=32, num_experts=4, gate=gate,
                          eval_capacity_factor=8.0,
                          dispatch_mode="alltoall")
        sparse.set_state_dict(dense.state_dict())
        dense.eval()
        sparse.eval()
        x = pt.randn([2, 12, 16])
        np.testing.assert_allclose(np.asarray(dense(x)),
                                   np.asarray(sparse(x)),
                                   rtol=2e-5, atol=2e-5, err_msg=gate)
        np.testing.assert_allclose(float(dense.aux_loss),
                                   float(sparse.aux_loss), rtol=1e-5)


def test_moe_alltoall_parity_under_drops():
    """Capacity pressure: the sparse path's choice-major slot order must
    reproduce the dense gate's drop priority (every top-1 seats before any
    top-2), so outputs match even when tokens are dropped."""
    from paddle_tpu.distributed.parallel.moe import MoELayer

    pt.seed(9)
    dense = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                     eval_capacity_factor=1.0)
    sparse = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                      eval_capacity_factor=1.0, dispatch_mode="alltoall")
    sparse.set_state_dict(dense.state_dict())
    dense.eval()
    sparse.eval()
    x = pt.randn([2, 32, 16])
    np.testing.assert_allclose(np.asarray(dense(x)), np.asarray(sparse(x)),
                               rtol=2e-5, atol=2e-5)


def test_moe_alltoall_ep2_parity(mesh8):
    """2-way expert parallelism: the shard_map all2all path matches the
    dense path on the same weights."""
    from paddle_tpu.distributed.parallel.moe import MoELayer

    m = init_mesh(ep=2, dp=4)
    with mesh_scope(m):
        pt.seed(4)
        dense = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                         eval_capacity_factor=8.0)
        sparse = MoELayer(d_model=16, d_hidden=32, num_experts=4,
                          eval_capacity_factor=8.0,
                          dispatch_mode="alltoall")
        sparse.set_state_dict(dense.state_dict())
        dense.eval()
        sparse.eval()
        x = pt.randn([4, 8, 16])
        np.testing.assert_allclose(np.asarray(dense(x)),
                                   np.asarray(sparse(x)),
                                   rtol=2e-5, atol=2e-5)
        # aux loss is the GLOBAL statistic even under ep sharding
        np.testing.assert_allclose(float(dense.aux_loss),
                                   float(sparse.aux_loss), rtol=1e-5)
    set_mesh(None)


@pytest.mark.slow  # heaviest tier-1 test (~14s); ep2 parity coverage stays fast
def test_moe_alltoall_ep8_trains(mesh8):
    """Large-E regime on the full virtual mesh: ep=8, E=16 — forward,
    grads, and capacity-drop path all exercised."""
    from paddle_tpu.distributed.parallel.moe import MoELayer
    from paddle_tpu.nn import functional_call, param_state

    m = init_mesh(ep=8)
    with mesh_scope(m):
        pt.seed(5)
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=16,
                       capacity_factor=1.0, dispatch_mode="alltoall")
        x = pt.randn([8, 8, 16])
        out = moe(x)
        assert out.shape == (8, 8, 16)
        assert np.isfinite(np.asarray(out)).all()
        assert float(moe.aux_loss) > 0

        params = param_state(moe)

        def loss(p):
            o, _ = functional_call(moe, p, {}, x)
            return jnp.sum(o ** 2)

        grads = jax.grad(loss)(params)
        assert float(jnp.abs(grads["gate_weight"]).sum()) > 0
        assert float(jnp.abs(grads["experts.w1"]).sum()) > 0
    set_mesh(None)


# ------------------------------------------------------------ ring attention
def test_ring_attention_matches_full():
    from paddle_tpu.distributed.parallel.sequence_parallel import (
        ring_attention, ulysses_attention)
    from paddle_tpu.kernels.flash_attention import reference_attention_bhld

    m = init_mesh(sp=8)
    B, L, H, D = 2, 64, 8, 16
    q = pt.randn([B, L, H, D])
    k = pt.randn([B, L, H, D])
    v = pt.randn([B, L, H, D])
    ref = reference_attention_bhld(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                                   jnp.swapaxes(v, 1, 2), causal=True)
    ref = jnp.swapaxes(ref, 1, 2)

    with mesh_scope(m):
        out = ring_attention(q, k, v, mesh=m, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)

        out_u = ulysses_attention(q, k, v, mesh=m, causal=True)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(ref), rtol=2e-3, atol=2e-3)
    set_mesh(None)


def test_ring_attention_grad():
    from paddle_tpu.distributed.parallel.sequence_parallel import ring_attention

    m = init_mesh(sp=4)
    B, L, H, D = 1, 32, 2, 8
    q = pt.randn([B, L, H, D])
    k = pt.randn([B, L, H, D])
    v = pt.randn([B, L, H, D])

    with mesh_scope(m):
        def f(qq):
            return jnp.sum(ring_attention(qq, k, v, mesh=m, causal=True) ** 2)

        g = jax.grad(f)(q)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0
    set_mesh(None)


# ------------------------------------------------------------ pipeline
def test_pipeline_staged_module_parity():
    """pp=4 pipeline output == single-device sequential output."""
    from paddle_tpu.distributed.parallel.pipeline import PipelineStagedModule

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 16)

        def forward(self, x):
            return x + 0.1 * F.tanh(self.fc(x))

    pt.seed(3)
    set_mesh(None)
    pipe = PipelineStagedModule(Block(), num_layers=8, num_micro=4, remat=False)
    x = pt.randn([8, 16])
    ref = pipe(x)  # no mesh -> sequential scan

    m = init_mesh(pp=4)
    with mesh_scope(m):
        out = pipe(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)
    set_mesh(None)


def test_pipeline_grad_flows():
    from paddle_tpu.distributed.parallel.pipeline import PipelineStagedModule
    from paddle_tpu.nn import functional_call, param_state

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x):
            return x + F.tanh(self.fc(x))

    set_mesh(None)
    pipe = PipelineStagedModule(Block(), num_layers=4, num_micro=2, remat=True)
    x = pt.randn([4, 8])
    m = init_mesh(pp=4)
    with mesh_scope(m):
        params = param_state(pipe)

        def loss(p):
            out, _ = functional_call(pipe, p, {}, x)
            return jnp.sum(out ** 2)

        g = jax.grad(loss)(params)
        for k, v in g.items():
            assert np.isfinite(np.asarray(v)).all(), k
            assert float(jnp.abs(v).sum()) > 0, k
    set_mesh(None)


# ------------------------------------------------------------ fleet facade
def test_fleet_init_and_hcg():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    mesh = fleet.init(is_collective=True, strategy=s)
    assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 4
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.get_data_parallel_world_size() == 2
    assert fleet.worker_num() == 1  # single host
    set_mesh(None)


# ------------------------------------------------------------ gradient merge
class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def test_gradient_merge_matches_big_batch(mesh8):
    """k_steps=4 on batch B == k_steps=1 on batch 4B (SGD, avg=True).

    Reference: fleet/meta_optimizers/gradient_merge_optimizer.py — VERDICT r1
    item 6 (the config was declared but never consumed)."""
    from paddle_tpu.optimizer import SGD

    pt.seed(11)
    model_a = MLP()
    model_b = MLP()
    model_b.set_state_dict(model_a.state_dict())

    loss_fn = lambda out, b: F.cross_entropy(out, b[1])  # noqa: E731
    x = np.random.randn(32, 16).astype(np.float32)
    y = np.random.randint(0, 4, (32,))

    # accumulating step sees the 4 quarters, then applies one update
    astep = dist.DistributedTrainStep(model_a, SGD(learning_rate=0.1),
                                      loss_fn=loss_fn, mesh=mesh8,
                                      grad_accum_steps=4)
    for i in range(4):
        astep((x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]))

    # big-batch step applies the same update in one call
    bstep = dist.DistributedTrainStep(model_b, SGD(learning_rate=0.1),
                                      loss_fn=loss_fn, mesh=mesh8)
    bstep((x, y))

    for k in astep.params:
        np.testing.assert_allclose(np.asarray(astep.params[k]),
                                   np.asarray(bstep.params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_gradient_merge_trainstep_single_device():
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.optimizer import SGD

    pt.seed(12)
    model_a = MLP()
    model_b = MLP()
    model_b.set_state_dict(model_a.state_dict())
    loss_fn = lambda out, b: F.cross_entropy(out, b[1])  # noqa: E731
    x = np.random.randn(16, 16).astype(np.float32)
    y = np.random.randint(0, 4, (16,))

    astep = TrainStep(model_a, SGD(learning_rate=0.1), loss_fn=loss_fn,
                      grad_accum_steps=2)
    astep((x[:8], y[:8]))
    astep((x[8:], y[8:]))
    bstep = TrainStep(model_b, SGD(learning_rate=0.1), loss_fn=loss_fn)
    bstep((x, y))
    for k in astep.params:
        np.testing.assert_allclose(np.asarray(astep.params[k]),
                                   np.asarray(bstep.params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_fleet_gradient_merge_wiring(mesh8):
    """strategy.gradient_merge reaches DistributedTrainStep."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.optimizer import SGD

    s = DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 3, "avg": False}
    fleet._fleet_state.update(strategy=s)
    with mesh_scope(mesh8):
        step = fleet.distributed_model(MLP(), SGD(learning_rate=0.1),
                                       loss_fn=lambda o, b: jnp.mean(o ** 2))
    assert step.grad_accum_steps == 3 and step.grad_accum_avg is False
    fleet._fleet_state.update(strategy=None)
