"""Fleet observability plane: cross-host metrics aggregation
(``observability.fleet``), per-tenant SLO burn-rate tracking
(``observability.slo``), clock-skew-aligned trace stitching, and the
router/remote wiring (``fleet_scrape_now`` / ``fleet_metrics_text`` /
``collect_fleet_trace`` / detector statusz).

The clock-skew acceptance lives here: synthetic two-host span sets with
±50ms injected skew must merge into one monotonic lane, and skew beyond
the correction bound must be REPORTED, never silently corrected.

Everything in this file runs on stubs — no model build, no rpc world —
so the suite stays cheap; the real 2-process drill is
``tools/fleet_obs_drill.py`` (robustness_gate --observability).
"""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu.observability import flight
from paddle_tpu.observability.fleet import (FleetAggregator, align_spans,
                                            estimate_clock_offset,
                                            stitch_traces)
from paddle_tpu.observability.registry import (MetricsRegistry,
                                               parse_qualified)
from paddle_tpu.observability.slo import (FLEET_TENANT, SloPolicy,
                                          SloTracker)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


@pytest.fixture(autouse=True)
def _restore_flight_dir():
    """Tests repoint the GLOBAL flight recorder at their tmp dirs;
    later test files must get the session default back."""
    rec = flight.flight_recorder()
    saved = rec.dump_dir
    yield
    flight.configure(dump_dir=saved)


# ----------------------------------------------------- registry roll-up
def test_parse_qualified_roundtrip():
    assert parse_qualified("plain") == ("plain", {})
    name, labels = parse_qualified(
        'serving.queue_depth{replica="r1",server="s0"}')
    assert name == "serving.queue_depth"
    assert labels == {"replica": "r1", "server": "s0"}


def test_absorb_snapshot_relabels_counters_gauges_hists():
    r = MetricsRegistry()
    r.absorb_snapshot(
        {"counters": {'req{server="s0"}': 7, "plain": 2},
         "gauges": {"depth": 3},
         "histograms": {"ttft": {"count": 4, "p50": 0.1, "note": "x"}}},
        labels={"replica": "r1"})
    snap = r.snapshot()
    assert snap["counters"]['req{replica="r1",server="s0"}'] == 7
    assert snap["counters"]['plain{replica="r1"}'] == 2
    assert snap["gauges"]['depth{replica="r1"}'] == 3
    hist = snap["histograms"]['ttft{replica="r1"}']
    assert hist["count"] == 4 and "note" not in hist  # numbers only
    text = r.prometheus_text()
    assert 'req{replica="r1",server="s0"} 7.0' in text
    assert 'ttft_count{replica="r1"} 4' in text


def test_set_counter_is_absolute_not_additive():
    r = MetricsRegistry()
    r.set_counter("c", 5)
    r.set_counter("c", 5)
    assert r.snapshot()["counters"]["c"] == 5


# ------------------------------------------------------ clock alignment
def test_estimate_clock_offset_midpoint():
    # remote stamped 10.07 halfway through a [10.0, 10.02] round trip
    # whose midpoint is 10.01 -> the remote clock runs 60ms ahead
    assert estimate_clock_offset(10.0, 10.02, 10.07) == pytest.approx(
        0.06)
    assert estimate_clock_offset(10.0, 10.02, 9.97) == pytest.approx(
        -0.04)


def test_align_spans_shifts_within_bound():
    spans = [{"name": "a", "corr": "c", "t0": 1.05, "t1": 1.10}]
    out, rep = align_spans(spans, 0.05, max_correction_s=0.25,
                           host="hA")
    assert out[0]["t0"] == pytest.approx(1.0)
    assert out[0]["t1"] == pytest.approx(1.05)
    assert out[0]["host"] == "hA"
    assert rep["applied_s"] == pytest.approx(0.05)
    assert rep["clamped"] is False
    assert spans[0]["t0"] == 1.05    # input not mutated


def test_align_spans_beyond_bound_reported_not_hidden():
    """The satellite contract: skew past the correction bound is
    REPORTED (clamped flag + measured offset) and the spans come back
    untouched — never silently corrected."""
    spans = [{"name": "a", "corr": "c", "t0": 5.0, "t1": 5.1}]
    out, rep = align_spans(spans, 0.4, max_correction_s=0.25)
    assert out[0]["t0"] == 5.0 and out[0]["t1"] == 5.1
    assert rep["clamped"] is True
    assert rep["offset_s"] == pytest.approx(0.4)
    assert rep["applied_s"] == 0.0


def test_two_host_skew_merges_into_monotonic_lane():
    """±50ms injected skew across two hosts: after alignment the merged
    lane reads in true causal order with no overlaps — raw timestamps
    would interleave it wrongly."""
    corr = "req-x"
    local = [{"name": "router:submit", "corr": corr,
              "t0": 0.00, "t1": 0.01}]
    # true prefill [0.02, 0.05] on a host running +50ms ahead
    host_a = {"spans": [{"name": "prefill", "corr": corr,
                         "t0": 0.07, "t1": 0.10}],
              "offset_s": 0.05, "host": "hostA"}
    # true decode [0.06, 0.08] on a host running -50ms behind
    host_b = {"spans": [{"name": "decode", "corr": corr,
                         "t0": 0.01, "t1": 0.03}],
              "offset_s": -0.05, "host": "hostB"}
    merged, reports = stitch_traces(local, {"a": host_a, "b": host_b})
    assert [s["name"] for s in merged] == [
        "router:submit", "prefill", "decode"]
    for prev, nxt in zip(merged, merged[1:]):
        assert nxt["t0"] >= prev["t1"] - 1e-9   # monotonic, no overlap
    assert all(not r["clamped"] for r in reports)
    # raw (unaligned) order would have been wrong: hostB's decode
    # timestamp ties with the router submit's end instead of following
    # hostA's prefill
    assert host_b["spans"][0]["t0"] <= local[0]["t1"]
    assert host_b["spans"][0]["t0"] < host_a["spans"][0]["t0"]


def test_stitch_traces_flags_broken_clock():
    corr = "req-y"
    local = [{"name": "submit", "corr": corr, "t0": 0.0, "t1": 0.01}]
    bad = {"spans": [{"name": "prefill", "corr": corr,
                      "t0": 100.0, "t1": 100.1}],
           "offset_s": 99.0, "host": "hostZ"}
    merged, reports = stitch_traces(local, {"z": bad})
    rep = next(r for r in reports if r["replica"] == "z")
    assert rep["clamped"] is True and rep["offset_s"] == 99.0
    # the broken host's spans survive, unshifted
    assert any(s["name"] == "prefill" and s["t0"] == 100.0
               for s in merged)


# ------------------------------------------------------ fleet aggregator
def _snap(completed=1):
    return {"counters": {'serving.requests_completed{server="s0"}':
                         completed},
            "gauges": {}, "histograms": {}}


def test_fleet_aggregator_partial_stale_rollup():
    agg = FleetAggregator(stale_after_s=10.0)
    agg.observe_scrape("r1", snapshot=_snap(5), clock_offset_s=0.002)
    agg.observe_scrape("r2", snapshot=_snap(3))
    # r2's next scrape fails: last-known numbers stay, stale-marked
    agg.observe_scrape("r2", error=ConnectionError("partitioned"))
    st = agg.statusz()
    assert st["replicas"]["r1"]["stale"] is False
    assert st["replicas"]["r2"]["stale"] is True
    assert "partitioned" in st["replicas"]["r2"]["error"]
    assert st["replicas"]["r2"]["has_snapshot"] is True
    text = agg.metrics_text()
    assert ('serving_requests_completed{replica="r1",server="s0"} 5.0'
            in text)
    assert ('serving_requests_completed{replica="r2",server="s0"} 3.0'
            in text)   # partial: last-known, not dropped
    assert 'fleet_replica_stale{replica="r2"} 1.0' in text
    assert 'fleet_replica_stale{replica="r1"} 0.0' in text
    assert 'fleet_clock_offset_s{replica="r1"} 0.002' in text


def test_fleet_aggregator_staleness_by_age():
    agg = FleetAggregator(stale_after_s=5.0)
    agg.observe_scrape("r1", snapshot=_snap(), now=0.0)
    import time as _time

    now = _time.monotonic()
    # scraped_at=0.0 is far older than stale_after vs the real clock
    assert now > 5.0
    assert agg.statusz()["replicas"]["r1"]["stale"] is True
    agg.forget("r1")
    assert agg.statusz()["replicas"] == {}


# --------------------------------------------------------------- SLO
def test_slo_policy_validation():
    with pytest.raises(ValueError):
        SloPolicy(target_availability=1.0)
    with pytest.raises(ValueError):
        SloPolicy(target_ttft_s=0.0)
    with pytest.raises(ValueError):
        SloPolicy(fast_window_s=100.0, slow_window_s=10.0)
    assert SloPolicy(target_availability=0.99).error_budget == \
        pytest.approx(0.01)


def _server_snap(submitted=0, failed=0, expired=0, ttft_count=0,
                 ttft_mean_ms=0.0, per_adapter=None):
    return {"requests_submitted": submitted, "requests_failed": failed,
            "requests_expired": expired,
            "ttft": {"count": ttft_count, "mean_ms": ttft_mean_ms},
            **({"per_adapter": per_adapter} if per_adapter else {})}


def test_slo_fast_burn_dumps_with_tenant_label(tmp_path):
    flight.configure(dump_dir=str(tmp_path))
    clk = [0.0]
    tr = SloTracker(
        SloPolicy(target_ttft_s=0.1, target_availability=0.9,
                  fast_window_s=60.0, slow_window_s=600.0,
                  fast_burn_threshold=2.0),
        registry=False, clock=lambda: clk[0])
    base = _server_snap(per_adapter={"tenantA": {
        "requests": 0, "failures": 0, "ttft_count": 0,
        "ttft_sum_ms": 0.0}})
    assert tr.ingest(base) is None       # baseline produces no buckets
    clk[0] = 10.0
    hot = _server_snap(submitted=6, ttft_count=6, ttft_mean_ms=50.0,
                       per_adapter={"tenantA": {
                           "requests": 6, "failures": 0,
                           "ttft_count": 6, "ttft_sum_ms": 1200.0}})
    rep = tr.ingest(hot)
    # tenantA's interval mean TTFT (200ms) broke the 100ms target: all
    # six requests burn the 10% budget at 10x
    ten = rep["tenants"]["tenantA"]
    assert ten["burn_fast"] == pytest.approx(10.0)
    assert ten["alerting"] is True
    # the fleet tenant stayed healthy (mean 50ms under target)
    assert rep["tenants"][FLEET_TENANT]["burn_fast"] == 0.0
    dumps = [f for f in os.listdir(tmp_path) if "slo_burn" in f]
    assert len(dumps) == 1
    with open(tmp_path / dumps[0]) as f:
        dump = json.load(f)
    assert dump["extra"]["tenant"] == "tenantA"
    assert dump["extra"]["policy"]["target_ttft_s"] == 0.1
    # edge-triggered: a still-burning next window does NOT re-dump
    clk[0] = 20.0
    hotter = _server_snap(submitted=12, ttft_count=12, ttft_mean_ms=50.0,
                          per_adapter={"tenantA": {
                              "requests": 12, "failures": 0,
                              "ttft_count": 12, "ttft_sum_ms": 2400.0}})
    tr.ingest(hotter)
    assert len([f for f in os.listdir(tmp_path)
                if "slo_burn" in f]) == 1
    assert tr.burn_alerts == 1


def test_slo_below_threshold_no_dump(tmp_path):
    flight.configure(dump_dir=str(tmp_path))
    clk = [0.0]
    tr = SloTracker(SloPolicy(target_ttft_s=0.5,
                              target_availability=0.9,
                              fast_burn_threshold=10.0),
                    registry=False, clock=lambda: clk[0])
    tr.ingest(_server_snap())
    clk[0] = 5.0
    rep = tr.ingest(_server_snap(submitted=10, ttft_count=10,
                                 ttft_mean_ms=100.0))
    assert rep["tenants"][FLEET_TENANT]["burn_fast"] == 0.0
    assert not [f for f in os.listdir(tmp_path) if "slo_burn" in f]


def test_slo_availability_burn_and_window_expiry():
    clk = [0.0]
    pol = SloPolicy(target_ttft_s=10.0, target_availability=0.9,
                    fast_window_s=10.0, slow_window_s=100.0,
                    fast_burn_threshold=2.0)
    tr = SloTracker(pol, registry=False, dump_on_burn=False,
                    clock=lambda: clk[0])
    tr.ingest(_server_snap())
    clk[0] = 5.0
    rep = tr.ingest(_server_snap(submitted=10, failed=5))
    fleet = rep["tenants"][FLEET_TENANT]
    # 5 bad / 10 total against a 10% budget = burn 5x
    assert fleet["burn_fast"] == pytest.approx(5.0)
    assert fleet["window_fast"]["availability"] == pytest.approx(0.5)
    # a quiet later window: the bad bucket ages out of the fast window
    # but stays in the slow one
    clk[0] = 30.0
    rep = tr.ingest(_server_snap(submitted=10, failed=5))
    fleet = rep["tenants"][FLEET_TENANT]
    assert fleet["window_fast"]["total"] == 0.0
    assert fleet["burn_fast"] == 0.0
    assert fleet["window_slow"]["bad"] == 5.0


def test_slo_counter_regression_clamps_to_zero():
    """A replica death shrinks the fleet roll-up's cumulative counters;
    the delta must clamp at zero, not book negative traffic — and the
    baseline keeps the HIGH-water marks, so the replica's revival does
    NOT re-book its whole history as one interval's burn burst."""
    clk = [0.0]
    tr = SloTracker(SloPolicy(target_availability=0.9), registry=False,
                    dump_on_burn=False, clock=lambda: clk[0])
    roll = {"replicas": {"a": _server_snap(submitted=10),
                         "b": _server_snap(submitted=8, failed=4)}}
    tr.ingest(roll)
    clk[0] = 5.0
    shrunk = {"replicas": {"a": _server_snap(submitted=12),
                           "b": {"state": "dead"}}}
    rep = tr.ingest(shrunk)
    fleet = rep["tenants"][FLEET_TENANT]
    assert fleet["window_fast"]["total"] == 0.0   # 12 < 18: clamped
    assert fleet["burn_fast"] == 0.0
    # b revives with its old cumulative history: only traffic beyond
    # the pre-death HIGH-water mark (18 total / 4 bad) may book — a's
    # 3 new requests, and crucially NOT b's re-appearing 4 failures
    clk[0] = 10.0
    revived = {"replicas": {"a": _server_snap(submitted=13),
                            "b": _server_snap(submitted=8, failed=4)}}
    rep = tr.ingest(revived)
    fleet = rep["tenants"][FLEET_TENANT]
    assert fleet["window_fast"]["total"] == pytest.approx(3.0)
    assert fleet["window_fast"]["bad"] == 0.0
    assert fleet["burn_fast"] == 0.0


def test_slo_sheds_burn_the_fleet_budget():
    """Overload sheds are unavailability: a shed storm must burn the
    __fleet__ budget even though door sheds never reach
    requests_submitted."""
    clk = [0.0]
    tr = SloTracker(SloPolicy(target_availability=0.9,
                              fast_burn_threshold=2.0),
                    registry=False, dump_on_burn=False,
                    clock=lambda: clk[0])
    tr.ingest(_server_snap())
    clk[0] = 5.0
    snap = _server_snap(submitted=2, ttft_count=2, ttft_mean_ms=1.0)
    snap["requests_shed"] = 18
    rep = tr.ingest(snap)
    fleet = rep["tenants"][FLEET_TENANT]
    assert fleet["window_fast"]["bad"] == 18.0
    assert fleet["window_fast"]["total"] == 20.0
    assert fleet["burn_fast"] == pytest.approx(9.0)
    assert fleet["alerting"] is True


def test_slo_ingest_accepts_router_rollup_per_adapter():
    clk = [0.0]
    tr = SloTracker(SloPolicy(target_ttft_s=0.1,
                              target_availability=0.9),
                    registry=False, dump_on_burn=False,
                    clock=lambda: clk[0])
    r0 = {"replicas": {"a": _server_snap(per_adapter={
        "t1": {"requests": 0, "failures": 0, "ttft_count": 0,
               "ttft_sum_ms": 0.0}})}}
    tr.ingest(r0)
    clk[0] = 5.0
    r1 = {"replicas": {
        "a": _server_snap(submitted=4, per_adapter={
            "t1": {"requests": 2, "failures": 1, "ttft_count": 2,
                   "ttft_sum_ms": 20.0}}),
        "b": _server_snap(submitted=2, per_adapter={
            "t1": {"requests": 2, "failures": 1, "ttft_count": 2,
                   "ttft_sum_ms": 30.0}})}}
    rep = tr.ingest(r1)
    t1 = rep["tenants"]["t1"]
    # failures aggregate across replicas: 2 bad of 4 on a 10% budget
    assert t1["window_fast"]["total"] == 4.0
    assert t1["window_fast"]["bad"] == 2.0
    assert t1["burn_fast"] == pytest.approx(5.0)


# ------------------------------------------- serving metrics per-tenant
def test_serving_metrics_per_adapter_failure_and_ttft_sums():
    from paddle_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics(slots=2)
    m.adapter_request("t1")
    m.observe_adapter_ttft("t1", 0.2)
    m.adapter_failure("t1")
    m.adapter_failure(None)            # base tenant
    snap = m.snapshot()
    e = snap["per_adapter"]["t1"]
    assert e["requests"] == 1 and e["failures"] == 1
    assert e["ttft_count"] == 1
    assert e["ttft_sum_ms"] == pytest.approx(200.0)
    assert e["ttft_p50_ms"] == pytest.approx(200.0)   # key preserved
    assert snap["per_adapter"]["base"]["failures"] == 1


# ------------------------------------------------ router wiring (stubs)
class _StubEngine:
    active_count = 0
    slots = 4
    pool = None
    store = None


class _StubScheduler:
    depth = 0
    max_queue_depth = 8


class _StubRemote:
    """RemoteReplica-shaped stub: load views + the observability-plane
    duck type (metrics_snapshot / trace_export / clock attrs)."""

    clock_offset_s = 0.01
    rtt_ewma_s = 0.002

    def __init__(self, fail=False):
        self.engine = _StubEngine()
        self.scheduler = _StubScheduler()
        self.fail = fail
        self.per_adapter = None
        self.submitted = 0

    def start(self):
        return self

    def clock_stats(self):
        return {"clock_offset_ms": 10.0, "rtt_ewma_ms": 2.0,
                "clock_samples": 3}

    def metrics_snapshot(self):
        if self.fail:
            raise ConnectionError("partitioned")
        # _host_metrics shape: registry sections + the serving snapshot
        # piggybacked so the router's SLO ingest needs no second rpc
        return {"counters": {'serving.requests_completed{server="s0"}':
                             self.submitted},
                "gauges": {}, "histograms": {}, "host": "hostB",
                "time": 0.0, "serving_snapshot": self.snapshot()}

    def trace_export(self, corr=None):
        if self.fail:
            raise ConnectionError("partitioned")
        return {"spans": [{"name": "prefill", "corr": "c1",
                           "t0": 10.05, "t1": 10.06, "tags": {}}],
                "offset_s": 0.05, "host": "hostB"}

    def snapshot(self):
        return {"requests_submitted": self.submitted,
                "requests_completed": self.submitted,
                "tokens_emitted": 0, "prefix_hit_tokens": 0,
                "prefix_miss_tokens": 0,
                "ttft": {"count": self.submitted, "mean_ms": 1.0},
                **({"per_adapter": self.per_adapter}
                   if self.per_adapter else {})}

    def shutdown(self, drain=True, timeout=None):
        pass


def test_router_fleet_scrape_labels_and_partial_stale():
    from paddle_tpu.serving import ReplicaRouter

    good, bad = _StubRemote(), _StubRemote(fail=True)
    r = ReplicaRouter()
    r.add_replica(good, "good")
    r.add_replica(bad, "bad")
    st = r.fleet_scrape_now()         # must not raise on the failure
    assert st["replicas"]["good"]["stale"] is False
    assert st["replicas"]["bad"]["stale"] is True
    text = r.fleet_metrics_text()
    assert 'replica="good"' in text
    assert 'fleet_replica_stale{replica="bad"} 1.0' in text
    assert 'fleet_clock_offset_s{replica="good"} 0.01' in text


def test_router_collect_fleet_trace_aligns_and_reports():
    from paddle_tpu.serving import ReplicaRouter

    r = ReplicaRouter()
    r.add_replica(_StubRemote(), "good")
    r.add_replica(_StubRemote(fail=True), "bad")
    spans, reports = r.collect_fleet_trace()
    remote = [s for s in spans if s.get("src") == "good"]
    assert remote and remote[0]["t0"] == pytest.approx(10.0)  # -50ms
    by_name = {rep["replica"]: rep for rep in reports}
    assert by_name["good"]["applied_s"] == pytest.approx(0.05)
    assert "error" in by_name["bad"]


def test_router_statusz_detector_block():
    from paddle_tpu.serving import ReplicaRouter

    r = ReplicaRouter()
    r.add_replica(_StubRemote(), "g")
    dz = r.statusz()["detector"]
    rep = dz["replicas"]["g"]
    assert rep["state"] == "active" and rep["misses"] == 0
    assert rep["remote_client"]["clock_offset_ms"] == 10.0
    assert "requests_hedged" in dz["counters"]
    assert "hedge_multiplier" in dz["config"]
    # fleet_statusz composes detector + scrape (+ slo when configured)
    fz = r.fleet_statusz()
    assert "detector" in fz and "scrape" in fz and "slo" not in fz


def test_router_scrape_feeds_slo_tracker(tmp_path):
    from paddle_tpu.serving import ReplicaRouter

    flight.configure(dump_dir=str(tmp_path))
    stub = _StubRemote()
    stub.per_adapter = {"tenantZ": {"requests": 0, "failures": 0,
                                    "ttft_count": 0, "ttft_sum_ms": 0.0}}
    r = ReplicaRouter(slo_policy=SloPolicy(
        target_ttft_s=0.1, target_availability=0.9,
        fast_burn_threshold=2.0))
    r.add_replica(stub, "s")
    r.fleet_scrape_now()              # baseline
    stub.submitted = 4
    stub.per_adapter = {"tenantZ": {"requests": 4, "failures": 4,
                                    "ttft_count": 0, "ttft_sum_ms": 0.0}}
    r.fleet_scrape_now()
    rep = r.slo_report()
    assert rep["tenants"]["tenantZ"]["alerting"] is True
    dumped = [f for f in os.listdir(tmp_path) if "slo_burn" in f]
    assert dumped
    tenants = set()
    for fname in dumped:
        with open(tmp_path / fname) as f:
            tenants.add(json.load(f)["extra"]["tenant"])
    assert "tenantZ" in tenants
    assert "slo" in r.fleet_statusz()


def test_remote_replica_clock_ewma_without_rpc():
    from paddle_tpu.serving.remote import RemoteReplica

    rep = RemoteReplica("peer-x")
    assert rep.clock_offset_s is None
    rep._note_clock(10.0, 10.02, 10.07)      # +60ms
    assert rep.clock_offset_s == pytest.approx(0.06)
    assert rep.rtt_ewma_s == pytest.approx(0.02)
    rep._note_clock(20.0, 20.02, 20.11)      # +100ms sample -> EWMA
    assert rep.clock_offset_s == pytest.approx(0.8 * 0.06 + 0.2 * 0.10)
    stats = rep.clock_stats()
    assert stats["clock_samples"] == 2
    assert stats["clock_offset_ms"] == pytest.approx(68.0)
    rep._note_clock(30.0, 30.02, None)       # no timestamp: ignored
    assert rep.clock_stats()["clock_samples"] == 2


# -------------------------------------------------- flight + trace_view
def test_flight_dump_filename_hostname_prefixed(tmp_path):
    import socket

    from paddle_tpu.observability.flight import (FlightRecorder,
                                                 _host_token)

    rec = FlightRecorder(dump_dir=str(tmp_path))
    path = rec.dump("unit")
    fname = os.path.basename(path)
    assert fname.startswith(f"flight_{_host_token()}_{os.getpid()}_")
    # sanity: the token really derives from this host's name
    assert _host_token()[:8] in "".join(
        c if (c.isalnum() or c in "_-") else "_"
        for c in socket.gethostname())


def test_trace_view_list_groups_by_host(tmp_path, capsys):
    from trace_view import group_by_host, list_correlations, load_spans
    from trace_view import main as tv_main

    corr = "req-fleet-000001"
    files = []
    for host, pid in (("hostA", 11), ("hostB", 22)):
        dump = {"format": "flight_recorder", "version": 1,
                "reason": "t", "time": 0.0, "pid": pid, "host": host,
                "correlation_id": corr,
                "events": [],
                "spans": [{"name": f"{host}:phase", "corr": corr,
                           "t0": 1.0, "t1": 1.5, "tags": {}}],
                "counters": {}, "metrics": None}
        p = tmp_path / f"{host}.json"
        with open(p, "w") as f:
            json.dump(dump, f)
        files.append(str(p))
    spans = []
    for p in files:
        got, kind = load_spans(p)
        assert kind == "flight"
        assert got[0]["host"] in ("hostA", "hostB")
        spans.extend(got)
    groups = group_by_host(spans)
    assert set(groups) == {"hostA", "hostB"}
    rows = list_correlations(spans)
    assert rows[0]["hosts"] == ["hostA", "hostB"]
    assert tv_main(files + ["--list"]) == 0
    out = capsys.readouterr().out
    assert "# host hostA:" in out and "# host hostB:" in out
    # per-corr lines stay line-JSON (headers are '#'-prefixed)
    data_lines = [ln for ln in out.splitlines()
                  if ln and not ln.startswith("#")]
    assert json.loads(data_lines[0])["corr"] == corr


# ------------------------------------------------ bench_profile overlap
def test_overlap_breakdown_classifies_and_splits():
    from bench_profile import classify_span, overlap_breakdown

    assert classify_span("bucketed_allreduce") == "collective"
    assert classify_span("h2d_prefetch") == "host_stall"
    assert classify_span("step") == "step"
    assert classify_span("serve:decode") == "other"
    spans = [("step", 0.0, 0.10), ("step", 0.10, 0.20),
             ("psum_dp", 0.02, 0.04),          # inside step 0
             ("h2d_prefetch", 0.12, 0.15)]     # inside step 1
    b = overlap_breakdown(spans, compute_s=0.05)
    s0, s1 = b["steps"]
    assert s0["collective_ms"] == pytest.approx(20.0)
    assert s0["compute_ms"] == pytest.approx(50.0)
    assert s0["non_compute_ms"] == pytest.approx(30.0)
    assert s1["host_stall_ms"] == pytest.approx(30.0)
    assert b["mean"]["wall_ms"] == pytest.approx(100.0)
    assert 0.0 < b["mean"]["non_compute_frac"] <= 1.0
