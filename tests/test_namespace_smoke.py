"""Behavior smoke over every name the zero-missing audits assert
(VERDICT r4 weak #5: presence-only audits could be satisfied by a shallow
alias). For each public name in the audited reference ``__all__``s:

- functions are AUTO-INVOKED against a small battery of canonical inputs
  (plus per-name candidates where shapes are picky); returning a real
  value passes, raising ``NotImplementedError`` fails loudly (stub), and
  raising any other error still proves real code ran past the signature;
- classes are instantiated from the same battery; enums must have
  members; constructors needing rich arguments (a Layer, an optimizer)
  fall back to a structural check: the exported name must BE the class's
  own name (``LSTM = Linear``-style shallow aliasing fails) and the class
  must be defined in this package;
- names that legitimately cannot be invoked here are whitelisted with the
  test that DOES exercise them.

Cites: tests/test_namespace_longtail.py:44 (the presence audits),
reference unittest discipline
``python/paddle/fluid/tests/unittests/test_*_op.py``.
"""
import contextlib
import enum
import io
import importlib
import re

import numpy as np
import pytest

import jax.numpy as jnp

A = jnp.asarray([[0.5, -0.25], [0.125, 1.0]], jnp.float32)
I8 = jnp.asarray([1, 0], jnp.int64)
X3 = jnp.ones((1, 2, 8), jnp.float32)
X4 = jnp.ones((1, 2, 8, 8), jnp.float32)
X5 = jnp.ones((1, 2, 4, 8, 8), jnp.float32)
W3 = jnp.ones((3, 2, 3), jnp.float32)
W4 = jnp.ones((3, 2, 3, 3), jnp.float32)
W5 = jnp.ones((3, 2, 3, 3, 3), jnp.float32)

# generic candidates tried in order for every function/class
BATTERY = [(), (A,), (A, A), (A, A, A), (I8,), (A, I8), (2,), (A, 2),
           ("smoke",)]


def _dists():
    from paddle_tpu.distribution import AffineTransform, Normal

    return {
        "kl_divergence": [((Normal(A, A + 1.0), Normal(A, A + 2.0)), {})],
        "Beta": [((A + 0.5, A + 1.0), {})],
        "Dirichlet": [((A + 1.0,), {})],
        "Gumbel": [((A, A + 1.0), {})],
        "Independent": [((Normal(A, A + 1.0), 1), {})],
        "Laplace": [((A, A + 1.0), {})],
        "LogNormal": [((A, A + 1.0), {})],
        "Multinomial": [((4, jnp.asarray([0.25, 0.75])), {})],
        "TransformedDistribution": [
            ((Normal(A, A + 1.0), [AffineTransform(jnp.zeros(()),
                                                   jnp.ones(()))]), {})],
        "Uniform": [((A, A + 2.0), {})],
    }


# per-name (args, kwargs) candidates where the battery's shapes won't do
EXTRA = {
    "paddle_tpu.sparse": lambda: {
        "sparse_csr_tensor": [((jnp.asarray([0, 1, 2], jnp.int64),
                                jnp.asarray([0, 1], jnp.int64),
                                jnp.asarray([1.0, 2.0], jnp.float32),
                                (2, 2)), {})],
    },
    "paddle_tpu.incubate": lambda: {
        "graph_khop_sampler": [((jnp.asarray([1, 2, 0, 2, 0, 1], jnp.int64),
                                 jnp.asarray([0, 2, 4, 6], jnp.int64),
                                 jnp.asarray([0, 1], jnp.int64), [2]), {})],
        "graph_send_recv": [((A, I8, I8), {})],
    },
    "paddle_tpu.profiler": lambda: {
        "make_scheduler": [((), {"closed": 1, "ready": 1, "record": 2})],
    },
    "paddle_tpu.distribution": _dists,
    "paddle_tpu.nn.functional": lambda: {
        "avg_pool1d": [((X3, 2), {})], "avg_pool2d": [((X4, 2), {})],
        "avg_pool3d": [((X5, 2), {})], "max_pool1d": [((X3, 2), {})],
        "max_pool2d": [((X4, 2), {})], "max_pool3d": [((X5, 2), {})],
        "conv1d": [((X3, W3), {})], "conv2d": [((X4, W4), {})],
        "conv3d": [((X5, W5), {})],
        "batch_norm": [((X4, jnp.zeros(2), jnp.ones(2)), {})],
        "ctc_loss": [((jnp.zeros((6, 1, 5)), jnp.ones((1, 2), jnp.int32),
                       jnp.asarray([6], jnp.int64),
                       jnp.asarray([2], jnp.int64)), {})],
        "fold": [((jnp.ones((1, 4, 4)), [3, 3], [2, 2]), {})],
        "hsigmoid_loss": [((A, I8, 4, jnp.ones((3, 2))), {})],
        "npair_loss": [((A, A, jnp.asarray([[0], [1]], jnp.int64)), {})],
    },
}

# names whose real exercise lives elsewhere (infra: files, servers,
# models); each entry names the covering test so the mapping stays honest
INVOKE_ELSEWHERE = {
    "paddle_tpu.jit": {
        "load": "tests/test_jit_export.py (save->load roundtrips)",
        "save": "tests/test_jit_export.py",
    },
    "paddle_tpu.nn.functional": {
        "sparse_attention": "gated: reference op is CUDA-only; the TPU "
                            "path is kernels/flash_attention "
                            "(tests/test_flash_attention.py)",
    },
}

# functions that legitimately return None (setters/config)
NONE_OK = {"run_check", "require_version",
           "set_code_level", "set_verbosity", "seed", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "reset_profiler",
           "start_profiler", "stop_profiler", "disable_signal_handler",
           "set_flags", "set_device", "set_default_dtype",
           "set_grad_enabled", "set_printoptions"}

TARGETS = [
    ("/root/reference/python/paddle/sparse/__init__.py", "paddle_tpu.sparse"),
    ("/root/reference/python/paddle/fft.py", "paddle_tpu.fft"),
    ("/root/reference/python/paddle/incubate/__init__.py",
     "paddle_tpu.incubate"),
    ("/root/reference/python/paddle/jit/__init__.py", "paddle_tpu.jit"),
    ("/root/reference/python/paddle/profiler/__init__.py",
     "paddle_tpu.profiler"),
    ("/root/reference/python/paddle/distribution/__init__.py",
     "paddle_tpu.distribution"),
    ("/root/reference/python/paddle/text/__init__.py", "paddle_tpu.text"),
    ("/root/reference/python/paddle/nn/__init__.py", "paddle_tpu.nn"),
    ("/root/reference/python/paddle/nn/functional/__init__.py",
     "paddle_tpu.nn.functional"),
    ("/root/reference/python/paddle/vision/models/__init__.py",
     "paddle_tpu.vision.models"),
    ("/root/reference/python/paddle/utils/__init__.py", "paddle_tpu.utils"),
]


def _ref_all(path):
    try:
        src = open(path).read()
    except OSError:
        pytest.skip("reference tree not mounted")
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    return sorted(set(re.findall(r"['\"](\w+)['\"]", m.group(1)))) if m \
        else []


def _try_call(obj, candidates):
    """Returns (invoked, outcome): outcome is the value, 'raised' (real
    code ran and rejected values), or 'stub' (NotImplementedError)."""
    for args, kwargs in candidates:
        try:
            with contextlib.redirect_stdout(io.StringIO()):
                return True, obj(*args, **kwargs)
        except NotImplementedError:
            return True, "stub"
        except TypeError:
            continue  # signature mismatch: try the next candidate
        except Exception:
            return True, "raised"
    return False, None


@pytest.mark.parametrize("refpath,modname",
                         TARGETS, ids=[t[1] for t in TARGETS])
def test_audited_names_behave(refpath, modname):
    mod = importlib.import_module(modname)
    extra = EXTRA.get(modname, dict)()
    elsewhere = INVOKE_ELSEWHERE.get(modname, {})
    stubs, shallow, unhandled = [], [], []
    for name in _ref_all(refpath):
        if name.startswith("_"):
            continue
        obj = getattr(mod, name, None)
        if obj is None:
            shallow.append(f"{name}: missing/None")
            continue
        if name in elsewhere:
            assert callable(obj), f"{name} whitelisted but not callable"
            continue
        candidates = extra.get(name, []) + [(a, {}) for a in BATTERY]
        if isinstance(obj, type):
            if issubclass(obj, enum.Enum):
                if not len(list(obj)):
                    shallow.append(f"{name}: empty enum")
                continue
            invoked, out = _try_call(obj, candidates)
            if out == "stub":
                stubs.append(name)
            elif not invoked:
                # constructor needs rich args: structural alias check —
                # the exported name must be the class's own name and the
                # class must live in this package (or jax for re-exports)
                if obj.__name__ != name:
                    shallow.append(
                        f"{name}: aliases class {obj.__name__}")
                elif not obj.__module__.startswith(("paddle_tpu", "jax")):
                    shallow.append(f"{name}: defined in {obj.__module__}")
            continue
        if not callable(obj):
            continue  # constants: presence is all there is
        invoked, out = _try_call(obj, candidates)
        if out == "stub":
            stubs.append(name)
        elif not invoked:
            unhandled.append(name)
        elif out is None and name not in NONE_OK:
            shallow.append(f"{name}: returned None for real inputs")
    assert stubs == [], f"NotImplementedError stubs: {stubs}"
    assert shallow == [], f"shallow aliases: {shallow}"
    assert unhandled == [], (
        f"uninvokable with current candidates (add EXTRA entries or "
        f"INVOKE_ELSEWHERE mappings): {unhandled}")
