"""Behavior smoke over every name the zero-missing audits assert
(VERDICT r4 weak #5: presence-only audits could be satisfied by a shallow
alias). For each public name in the audited reference ``__all__``s:

- functions are AUTO-INVOKED against a small battery of canonical inputs
  (plus per-name candidates where shapes are picky); returning a real
  value passes, raising ``NotImplementedError`` fails loudly (stub), and
  raising any other error still proves real code ran past the signature;
- classes are instantiated from the same battery; enums must have
  members; constructors needing rich arguments (a Layer, an optimizer)
  fall back to a structural check: the exported name must BE the class's
  own name (``LSTM = Linear``-style shallow aliasing fails) and the class
  must be defined in this package;
- names that legitimately cannot be invoked here are whitelisted with the
  test that DOES exercise them.

Cites: tests/test_namespace_longtail.py:44 (the presence audits),
reference unittest discipline
``python/paddle/fluid/tests/unittests/test_*_op.py``.
"""
import contextlib
import enum
import io
import importlib
import re

import numpy as np
import pytest

import jax.numpy as jnp

A = jnp.asarray([[0.5, -0.25], [0.125, 1.0]], jnp.float32)
I8 = jnp.asarray([1, 0], jnp.int64)
X3 = jnp.ones((1, 2, 8), jnp.float32)
X4 = jnp.ones((1, 2, 8, 8), jnp.float32)
X5 = jnp.ones((1, 2, 4, 8, 8), jnp.float32)
W3 = jnp.ones((3, 2, 3), jnp.float32)
W4 = jnp.ones((3, 2, 3, 3), jnp.float32)
W5 = jnp.ones((3, 2, 3, 3, 3), jnp.float32)
A2x3_GEO = jnp.ones((3, 4), jnp.float32)   # node features [num_nodes, d]
E3_GEO = jnp.ones((3, 4), jnp.float32)     # edge features [num_edges, d]

# generic candidates tried in order for every function/class
BATTERY = [(), (A,), (A, A), (A, A, A), (I8,), (A, I8), (2,), (A, 2),
           ("smoke",)]


def _dists():
    from paddle_tpu.distribution import AffineTransform, Normal

    return {
        "kl_divergence": [((Normal(A, A + 1.0), Normal(A, A + 2.0)), {})],
        "Beta": [((A + 0.5, A + 1.0), {})],
        "Dirichlet": [((A + 1.0,), {})],
        "Gumbel": [((A, A + 1.0), {})],
        "Independent": [((Normal(A, A + 1.0), 1), {})],
        "Laplace": [((A, A + 1.0), {})],
        "LogNormal": [((A, A + 1.0), {})],
        "Multinomial": [((4, jnp.asarray([0.25, 0.75])), {})],
        "TransformedDistribution": [
            ((Normal(A, A + 1.0), [AffineTransform(jnp.zeros(()),
                                                   jnp.ones(()))]), {})],
        "Uniform": [((A, A + 2.0), {})],
    }


def _toplevel():
    import paddle_tpu as pt

    IDX2 = jnp.zeros((2, 1), jnp.int64)

    def _grad_pair():
        x = pt.to_tensor([[1.0, 2.0]], stop_gradient=False)
        return x, (x * x).sum()

    x_g, y_g = _grad_pair()
    return {
        # a valid dtype FIRST: the battery's generic ints would otherwise
        # set a bogus global default dtype (paddle dtype enum ints are
        # accepted) and poison every later creation op in the sweep
        "set_default_dtype": [(("float32",), {})],
        "set_cuda_rng_state": [((pt.get_cuda_rng_state(),), {})],
        "bitwise_and": [((I8, I8), {})],
        "bitwise_or": [((I8, I8), {})],
        "bitwise_xor": [((I8, I8), {})],
        "broadcast_shape": [(([2, 2], [2]), {})],
        "full": [(([2, 2], 1.0), {})],
        "grad": [(([y_g], [x_g]), {})],
        "index_add": [((A, I8, 0, A), {})],
        "index_add_": [((A, I8, 0, A), {})],
        "linspace": [((0.0, 1.0, 5), {})],
        "logspace": [((0.0, 1.0, 5), {})],
        "moveaxis": [((A, 0, 1), {})],
        "put_along_axis": [((A, IDX2, 1.0, 1), {})],
        "renorm": [((A, 2.0, 0, 1.0), {})],
        "reshape": [((A, [4]), {})],
        "reshape_": [((A, [4]), {})],
        "save": [((A, "/tmp/_smoke_save.pdparams"), {})],
        "scatter": [((A, I8, A), {})],
        "scatter_": [((A, I8, A), {})],
        "scatter_nd": [((IDX2, jnp.asarray([1.0, 2.0]), [2]), {})],
        "scatter_nd_add": [((jnp.zeros(2), IDX2,
                             jnp.asarray([1.0, 2.0])), {})],
        "shard_index": [((I8, 4, 2, 0), {})],
        "slice": [((A, [0], [0], [1]), {})],
        "standard_normal": [(([2, 2],), {})],
        "strided_slice": [((A, [0], [0], [2], [1]), {})],
        "tril_indices": [((2, 2, 0), {})],
        "uniform": [(([2, 2],), {})],
    }


def _autograd():
    import paddle_tpu as pt

    x = pt.to_tensor([[1.0, 2.0]], stop_gradient=False)
    return {"backward": [(([(x * x).sum()],), {})]}


def _vision_ops():
    B1 = jnp.asarray([[0.0, 0.0, 4.0, 4.0]], jnp.float32)
    N1 = jnp.asarray([1], jnp.int32)
    return {
        "box_coder": [((jnp.ones((2, 4)), jnp.ones((2, 4)),
                        jnp.ones((2, 4))), {})],
        "distribute_fpn_proposals": [
            ((jnp.asarray([[0, 0, 10, 10], [0, 0, 100, 100]], jnp.float32),
              2, 5, 4, 224), {})],
        "generate_proposals": [
            ((jnp.ones((1, 2, 4, 4)) * 0.5, jnp.zeros((1, 8, 4, 4)),
              jnp.asarray([[32.0, 32.0]]), jnp.ones((4, 4, 2, 4)),
              jnp.ones((4, 4, 2, 4)) * 0.1), {})],
        "matrix_nms": [((jnp.ones((1, 3, 4)), jnp.ones((1, 2, 3)) * 0.5,
                         0.1, 0.1, 5, 5), {})],
        "psroi_pool": [((jnp.ones((1, 4, 8, 8)), B1, N1, 2), {})],
        "roi_align": [((jnp.ones((1, 2, 8, 8)), B1, N1, 2), {})],
        "roi_pool": [((jnp.ones((1, 2, 8, 8)), B1, N1, 2), {})],
        "yolo_box": [((jnp.ones((1, 14, 4, 4)),
                       jnp.asarray([[32, 32]], jnp.int32),
                       [10, 13, 16, 30], 2, 0.01, 8), {})],
        "yolo_loss": [((jnp.ones((1, 14, 4, 4)), jnp.ones((1, 3, 4)) * 0.3,
                        jnp.zeros((1, 3), jnp.int32), [10, 13, 16, 30],
                        [0, 1], 2, 0.5, 8), {})],
    }


def _transforms():
    img = jnp.ones((8, 8, 3), jnp.float32)
    return {
        "affine": [((img, 10.0, [1, 1], 1.0, [0.0, 0.0]), {})],
        "crop": [((img, 1, 1, 4, 4), {})],
        "erase": [((img, 1, 1, 2, 2, 0.0), {})],
    }


def _geometric():
    SRC = jnp.asarray([0, 1, 2], jnp.int64)
    DST = jnp.asarray([1, 2, 0], jnp.int64)
    return {
        "send_u_recv": [((A2x3_GEO, SRC, DST), {})],
        "send_ue_recv": [((A2x3_GEO, E3_GEO, SRC, DST), {})],
        "send_uv": [((A2x3_GEO, A2x3_GEO, SRC, DST), {})],
        "reindex_heter_graph": [(([0, 1, 2],
                                  [[8, 9, 0], [0, 2]],
                                  [[2, 1], [1, 1]]), {})],
        "sample_neighbors": [((jnp.asarray([1, 2, 0, 2, 0, 1], jnp.int64),
                               jnp.asarray([0, 2, 4, 6], jnp.int64),
                               jnp.asarray([0, 1], jnp.int64)), {})],
        "reindex_graph": [(([0, 1], [2, 0, 1], [2, 1]), {})],
        "khop_sampler": [((jnp.asarray([1, 2, 0, 2, 0, 1], jnp.int64),
                           jnp.asarray([0, 2, 4, 6], jnp.int64),
                           jnp.asarray([0, 1], jnp.int64), [2]), {})],
    }


def _initializer():
    import paddle_tpu.nn.initializer as I

    return {
        "set_global_initializer": [((I.Normal(0.0, 0.02),), {})],
        "calculate_gain": [(("relu",), {})],
    }


# per-name (args, kwargs) candidates where the battery's shapes won't do
EXTRA = {
    "paddle_tpu": _toplevel,
    "paddle_tpu.geometric": _geometric,
    "paddle_tpu.nn.initializer": _initializer,
    "paddle_tpu.vision.transforms": _transforms,
    "paddle_tpu.autograd": _autograd,
    "paddle_tpu.vision.ops": _vision_ops,
    "paddle_tpu.sparse": lambda: {
        "sparse_csr_tensor": [((jnp.asarray([0, 1, 2], jnp.int64),
                                jnp.asarray([0, 1], jnp.int64),
                                jnp.asarray([1.0, 2.0], jnp.float32),
                                (2, 2)), {})],
    },
    "paddle_tpu.incubate": lambda: {
        "graph_khop_sampler": [((jnp.asarray([1, 2, 0, 2, 0, 1], jnp.int64),
                                 jnp.asarray([0, 2, 4, 6], jnp.int64),
                                 jnp.asarray([0, 1], jnp.int64), [2]), {})],
        "graph_send_recv": [((A, I8, I8), {})],
    },
    "paddle_tpu.profiler": lambda: {
        "make_scheduler": [((), {"closed": 1, "ready": 1, "record": 2})],
    },
    "paddle_tpu.distribution": _dists,
    "paddle_tpu.nn.functional": lambda: {
        "avg_pool1d": [((X3, 2), {})], "avg_pool2d": [((X4, 2), {})],
        "avg_pool3d": [((X5, 2), {})], "max_pool1d": [((X3, 2), {})],
        "max_pool2d": [((X4, 2), {})], "max_pool3d": [((X5, 2), {})],
        "conv1d": [((X3, W3), {})], "conv2d": [((X4, W4), {})],
        "conv3d": [((X5, W5), {})],
        "batch_norm": [((X4, jnp.zeros(2), jnp.ones(2)), {})],
        "ctc_loss": [((jnp.zeros((6, 1, 5)), jnp.ones((1, 2), jnp.int32),
                       jnp.asarray([6], jnp.int64),
                       jnp.asarray([2], jnp.int64)), {})],
        "fold": [((jnp.ones((1, 4, 4)), [3, 3], [2, 2]), {})],
        "hsigmoid_loss": [((A, I8, 4, jnp.ones((3, 2))), {})],
        "npair_loss": [((A, A, jnp.asarray([[0], [1]], jnp.int64)), {})],
    },
}

# names whose real exercise lives elsewhere (infra: files, servers,
# models); each entry names the covering test so the mapping stays honest
INVOKE_ELSEWHERE = {
    "paddle_tpu.jit": {
        "load": "tests/test_jit_export.py (save->load roundtrips)",
        "save": "tests/test_jit_export.py",
    },
    "paddle_tpu.nn.functional": {
        "sparse_attention": "gated: reference op is CUDA-only; the TPU "
                            "path is kernels/flash_attention "
                            "(tests/test_flash_attention.py)",
    },
}

# functions that legitimately return None (setters/config; get_worker_info
# outside a DataLoader worker; backward writes .grad in place; save
# writes its file)
NONE_OK = {"run_check", "require_version", "set_global_initializer",
           "set_code_level", "set_verbosity", "seed", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "reset_profiler",
           "start_profiler", "stop_profiler", "disable_signal_handler",
           "set_flags", "set_device", "set_default_dtype",
           "set_grad_enabled", "set_printoptions",
           "disable_static", "enable_static", "set_cuda_rng_state",
           "get_worker_info", "backward", "save"}

TARGETS = [
    ("/root/reference/python/paddle/__init__.py", "paddle_tpu"),
    ("/root/reference/python/paddle/optimizer/__init__.py",
     "paddle_tpu.optimizer"),
    ("/root/reference/python/paddle/io/__init__.py", "paddle_tpu.io"),
    ("/root/reference/python/paddle/metric/__init__.py", "paddle_tpu.metric"),
    ("/root/reference/python/paddle/amp/__init__.py", "paddle_tpu.amp"),
    ("/root/reference/python/paddle/autograd/__init__.py",
     "paddle_tpu.autograd"),
    ("/root/reference/python/paddle/signal.py", "paddle_tpu.signal"),
    ("/root/reference/python/paddle/linalg.py", "paddle_tpu.linalg"),
    ("/root/reference/python/paddle/nn/initializer/__init__.py",
     "paddle_tpu.nn.initializer"),
    ("/root/reference/python/paddle/geometric/__init__.py",
     "paddle_tpu.geometric"),
    ("/root/reference/python/paddle/vision/ops.py", "paddle_tpu.vision.ops"),
    ("/root/reference/python/paddle/vision/transforms/__init__.py",
     "paddle_tpu.vision.transforms"),
    ("/root/reference/python/paddle/sparse/__init__.py", "paddle_tpu.sparse"),
    ("/root/reference/python/paddle/fft.py", "paddle_tpu.fft"),
    ("/root/reference/python/paddle/incubate/__init__.py",
     "paddle_tpu.incubate"),
    ("/root/reference/python/paddle/jit/__init__.py", "paddle_tpu.jit"),
    ("/root/reference/python/paddle/profiler/__init__.py",
     "paddle_tpu.profiler"),
    ("/root/reference/python/paddle/distribution/__init__.py",
     "paddle_tpu.distribution"),
    ("/root/reference/python/paddle/text/__init__.py", "paddle_tpu.text"),
    ("/root/reference/python/paddle/nn/__init__.py", "paddle_tpu.nn"),
    ("/root/reference/python/paddle/nn/functional/__init__.py",
     "paddle_tpu.nn.functional"),
    ("/root/reference/python/paddle/vision/models/__init__.py",
     "paddle_tpu.vision.models"),
    ("/root/reference/python/paddle/utils/__init__.py", "paddle_tpu.utils"),
]


def _ref_all(path):
    try:
        src = open(path).read()
    except OSError:
        pytest.skip("reference tree not mounted")
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    return sorted(set(re.findall(r"['\"](\w+)['\"]", m.group(1)))) if m \
        else []


STUB = object()    # NotImplementedError: a stub pretending to exist
RAISED = object()  # real code ran and rejected the canonical values


def _try_call(obj, candidates):
    """Returns (invoked, outcome): outcome is the value, RAISED (real
    code ran and rejected values), or STUB (NotImplementedError).
    Sentinel objects, not strings: a returned ndarray must never be
    `==`-compared against a sentinel (elementwise ambiguity)."""
    for args, kwargs in candidates:
        try:
            with contextlib.redirect_stdout(io.StringIO()):
                return True, obj(*args, **kwargs)
        except NotImplementedError:
            return True, STUB
        except TypeError:
            continue  # signature mismatch: try the next candidate
        except Exception:
            return True, RAISED
    return False, None


@pytest.fixture(autouse=True)
def _restore_global_defaults():
    """The battery invokes setters with arbitrary values; whatever they
    flip (default dtype, static mode, global seed) must not leak into
    later tests — the reference ``__all__`` order ends with
    ``enable_static`` after ``disable_static``, so without this the rest
    of the suite would run in static mode."""
    yield
    import paddle_tpu as pt

    pt.set_default_dtype("float32")
    pt.disable_static()
    pt.seed(0)
    from paddle_tpu.nn.initializer import set_global_initializer

    set_global_initializer(None)


def _resolve_module(modname: str):
    """import the target, falling back to attribute traversal for
    namespaces exposed as attributes rather than import paths
    (``paddle_tpu.linalg`` mirrors ``paddle.linalg``)."""
    try:
        return importlib.import_module(modname)
    except ModuleNotFoundError:
        parts = modname.split(".")
        obj = importlib.import_module(parts[0])
        for p in parts[1:]:
            obj = getattr(obj, p)
        return obj


@pytest.mark.parametrize("refpath,modname",
                         TARGETS, ids=[t[1] for t in TARGETS])
def test_audited_names_behave(refpath, modname):
    mod = _resolve_module(modname)
    extra = EXTRA.get(modname, dict)()
    elsewhere = INVOKE_ELSEWHERE.get(modname, {})
    stubs, shallow, unhandled = [], [], []
    for name in _ref_all(refpath):
        if name.startswith("_"):
            continue
        obj = getattr(mod, name, None)
        if obj is None:
            shallow.append(f"{name}: missing/None")
            continue
        if name in elsewhere:
            assert callable(obj), f"{name} whitelisted but not callable"
            continue
        candidates = extra.get(name, []) + [(a, {}) for a in BATTERY]
        if isinstance(obj, type):
            if issubclass(obj, enum.Enum):
                if not len(list(obj)):
                    shallow.append(f"{name}: empty enum")
                continue
            invoked, out = _try_call(obj, candidates)
            if out is STUB:
                stubs.append(name)
            elif not invoked:
                # constructor needs rich args: structural alias check —
                # the exported name must be the class's own name and the
                # class must live in this package (or jax for re-exports)
                if obj.__name__ != name:
                    shallow.append(
                        f"{name}: aliases class {obj.__name__}")
                elif not obj.__module__.startswith(("paddle_tpu", "jax")):
                    shallow.append(f"{name}: defined in {obj.__module__}")
            continue
        if not callable(obj):
            continue  # constants: presence is all there is
        invoked, out = _try_call(obj, candidates)
        if out is STUB:
            stubs.append(name)
        elif not invoked:
            unhandled.append(name)
        elif out is None and name not in NONE_OK:
            shallow.append(f"{name}: returned None for real inputs")
    assert stubs == [], f"NotImplementedError stubs: {stubs}"
    assert shallow == [], f"shallow aliases: {shallow}"
    assert unhandled == [], (
        f"uninvokable with current candidates (add EXTRA entries or "
        f"INVOKE_ELSEWHERE mappings): {unhandled}")
