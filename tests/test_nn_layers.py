"""Layer system + nn layers tests."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.nn import buffer_state, functional_call, param_state


def test_layer_registration():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    m = M()
    names = dict(m.named_parameters())
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    assert names["fc1.weight"].shape == (4, 8)
    assert len(m.parameters()) == 4
    assert len(m.sublayers()) == 2
    out = m(pt.randn([3, 4]))
    assert out.shape == (3, 2)


def test_state_dict_roundtrip():
    m = nn.Linear(3, 5)
    sd = m.state_dict()
    m2 = nn.Linear(3, 5)
    m2.set_state_dict(sd)
    x = pt.randn([2, 3])
    np.testing.assert_allclose(np.asarray(m(x)), np.asarray(m2(x)), rtol=1e-6)


def test_save_load(tmp_path):
    m = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    path = str(tmp_path / "model.pdparams")
    pt.save(m.state_dict(), path)
    loaded = pt.load(path)
    m2 = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    m2.set_state_dict(loaded)
    x = pt.randn([2, 3])
    np.testing.assert_allclose(np.asarray(m(x)), np.asarray(m2(x)), rtol=1e-6)


def test_functional_call_capture_buffers():
    bn = nn.BatchNorm2D(3)
    x = pt.randn([4, 3, 8, 8])
    params = param_state(bn)
    buffers = buffer_state(bn)
    out, new_buffers = functional_call(bn, params, buffers, x)
    assert out.shape == x.shape
    # running stats changed
    assert not np.allclose(np.asarray(new_buffers["_mean"]), np.asarray(buffers["_mean"]))
    # original layer state untouched
    np.testing.assert_array_equal(np.asarray(bn._mean), np.asarray(buffers["_mean"]))


def test_batchnorm_train_eval():
    bn = nn.BatchNorm1D(4, data_format="NCL")
    x = pt.randn([8, 4, 6]) * 3 + 1
    bn.train()
    y = bn(x)
    assert y.shape == x.shape
    # train-mode output normalized per channel
    arr = np.asarray(y)
    assert abs(arr.mean()) < 0.1
    bn.eval()
    y2 = bn(x)
    assert y2.shape == x.shape


def test_layernorm_matches_numpy():
    ln = nn.LayerNorm(16)
    x = np.random.randn(4, 16).astype(np.float32)
    out = np.asarray(ln(x))
    ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_conv2d_shape_and_value():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = pt.randn([2, 3, 16, 16])
    out = conv(x)
    assert out.shape == (2, 8, 8, 8)
    # compare against explicit correlation for one output element
    import jax.numpy as jnp

    w = conv.weight
    b = conv.bias
    xp = np.pad(np.asarray(x), ((0, 0), (0, 0), (1, 1), (1, 1)))
    ref = (xp[0, :, 0:3, 0:3] * np.asarray(w)[0]).sum() + np.asarray(b)[0]
    np.testing.assert_allclose(np.asarray(out)[0, 0, 0, 0], ref, rtol=1e-4, atol=1e-4)


def test_conv2d_groups_depthwise():
    conv = nn.Conv2D(4, 4, 3, padding=1, groups=4)
    x = pt.randn([1, 4, 8, 8])
    assert conv(x).shape == (1, 4, 8, 8)


def test_conv_transpose():
    convt = nn.Conv2DTranspose(3, 5, 4, stride=2, padding=1)
    x = pt.randn([2, 3, 8, 8])
    out = convt(x)
    assert out.shape == (2, 5, 16, 16)


def test_pooling():
    x = pt.randn([2, 3, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.AdaptiveAvgPool2D(1)(x).shape == (2, 3, 1, 1)
    xnp = np.asarray(x)
    np.testing.assert_allclose(
        np.asarray(nn.AdaptiveAvgPool2D(1)(x))[..., 0, 0], xnp.mean((2, 3)), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nn.MaxPool2D(2, 2)(x))[0, 0, 0, 0], xnp[0, 0, :2, :2].max(), rtol=1e-6)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = pt.ones([1000])
    d.train()
    y = np.asarray(d(x))
    frac_zero = (y == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # upscale keeps expectation
    assert abs(y.mean() - 1.0) < 0.2
    d.eval()
    np.testing.assert_array_equal(np.asarray(d(x)), np.asarray(x))


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = pt.to_tensor([[1, 2], [0, 3]], dtype="int32")
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    np.testing.assert_array_equal(np.asarray(out)[1, 0], np.zeros(4, np.float32))


def test_activations():
    x = np.linspace(-3, 3, 13).astype(np.float32)
    np.testing.assert_allclose(np.asarray(F.relu(x)), np.maximum(x, 0))
    np.testing.assert_allclose(np.asarray(F.hardswish(x)),
                               x * np.clip(x + 3, 0, 6) / 6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(F.sigmoid(x)), 1 / (1 + np.exp(-x)), rtol=1e-5)
    sm = np.asarray(F.softmax(x))
    np.testing.assert_allclose(sm, np.exp(x) / np.exp(x).sum(), rtol=1e-5)


def test_losses():
    logits = np.random.randn(8, 5).astype(np.float32)
    labels = np.random.randint(0, 5, (8,))
    loss = F.cross_entropy(logits, labels)
    # numpy reference
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(8), labels]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    # soft label
    soft = p
    loss2 = F.cross_entropy(logits, soft, soft_label=True)
    ref2 = -(soft * np.log(p)).sum(-1).mean()
    np.testing.assert_allclose(float(loss2), ref2, rtol=1e-4)

    # ignore index
    labels2 = labels.copy()
    labels2[:4] = -100
    loss3 = F.cross_entropy(logits, labels2, ignore_index=-100)
    ref3 = -np.log(p[np.arange(4, 8), labels[4:]]).mean()
    np.testing.assert_allclose(float(loss3), ref3, rtol=1e-5)

    x = np.random.randn(6).astype(np.float32)
    y = np.random.randn(6).astype(np.float32)
    np.testing.assert_allclose(float(F.mse_loss(x, y)), ((x - y) ** 2).mean(), rtol=1e-6)
    np.testing.assert_allclose(float(F.l1_loss(x, y)), np.abs(x - y).mean(), rtol=1e-6)


def test_bce_with_logits():
    z = np.random.randn(10).astype(np.float32)
    y = (np.random.rand(10) > 0.5).astype(np.float32)
    loss = F.binary_cross_entropy_with_logits(z, y)
    p = 1 / (1 + np.exp(-z))
    ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


def test_attention_matches_reference():
    B, L, H, D = 2, 16, 4, 8
    q = pt.randn([B, L, H, D])
    k = pt.randn([B, L, H, D])
    v = pt.randn([B, L, H, D])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True, training=False)
    assert out.shape == (B, L, H, D)
    # causal: first position attends only to itself
    import jax.numpy as jnp
    from paddle_tpu.kernels.flash_attention import reference_attention_bhld

    ref = reference_attention_bhld(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                                   jnp.swapaxes(v, 1, 2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.swapaxes(ref, 1, 2)),
                               rtol=1e-4, atol=1e-5)


def test_multihead_attention():
    mha = nn.MultiHeadAttention(32, 4)
    x = pt.randn([2, 10, 32])
    out = mha(x)
    assert out.shape == (2, 10, 32)


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=32, nhead=4, dim_feedforward=64)
    enc = nn.TransformerEncoder(layer, 2)
    enc.eval()
    x = pt.randn([2, 6, 32])
    assert enc(x).shape == (2, 6, 32)


def test_rnn_lstm_gru():
    x = pt.randn([4, 7, 6])
    lstm = nn.LSTM(6, 12, num_layers=2)
    out, (h, c) = lstm(x)
    assert out.shape == (4, 7, 12)
    assert h.shape == (2, 4, 12) and c.shape == (2, 4, 12)
    gru = nn.GRU(6, 12, direction="bidirect")
    out2, _ = gru(x)
    assert out2.shape == (4, 7, 24)
    rnn = nn.SimpleRNN(6, 12)
    out3, _ = rnn(x)
    assert out3.shape == (4, 7, 12)


def test_sequential_containers():
    seq = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    assert len(seq) == 3
    x = pt.randn([2, 3])
    assert seq(x).shape == (2, 2)
    ll = nn.LayerList([nn.Linear(3, 3) for _ in range(3)])
    ll.append(nn.Linear(3, 3))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_hooks():
    m = nn.Linear(3, 3)
    calls = []
    h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(pt.randn([1, 3]))
    assert calls == [1]
    h.remove()
    m(pt.randn([1, 3]))
    assert calls == [1]


def test_initializers():
    from paddle_tpu.nn.initializer import (
        Constant, KaimingNormal, Normal, TruncatedNormal, Uniform, XavierUniform)
    import jax

    key = jax.random.key(0)
    assert float(np.asarray(Constant(3.0)(key, (2, 2), np.float32)).sum()) == 12.0
    w = np.asarray(Normal(0, 0.02)(key, (1000,), np.float32))
    assert abs(w.std() - 0.02) < 0.005
    w = np.asarray(Uniform(-1, 1)(key, (1000,), np.float32))
    assert w.min() >= -1 and w.max() <= 1
    w = np.asarray(TruncatedNormal(0, 1.0)(key, (1000,), np.float32))
    assert np.abs(w).max() <= 2.0 + 1e-5
    w = np.asarray(XavierUniform()(key, (100, 100), np.float32))
    limit = np.sqrt(6 / 200)
    assert np.abs(w).max() <= limit + 1e-6


def test_set_global_initializer_priority():
    """Reference contract (fluid/initializer.py:1346): the global default
    applies to params created without an explicit attr initializer,
    REPLACING the layer's built-in default; an attr-carried initializer
    keeps priority; None cancels."""
    import numpy as np

    import paddle_tpu.nn as nn
    from paddle_tpu.nn.initializer import (Constant,
                                           set_global_initializer)

    try:
        set_global_initializer(Constant(3.0), Constant(0.5))
        lin = nn.Linear(4, 4)
        np.testing.assert_allclose(np.asarray(lin.weight), 3.0)
        np.testing.assert_allclose(np.asarray(lin.bias), 0.5)
        # attr-carried initializer outranks the global
        lin2 = nn.Linear(4, 4, weight_attr=Constant(7.0))
        np.testing.assert_allclose(np.asarray(lin2.weight), 7.0)
        np.testing.assert_allclose(np.asarray(lin2.bias), 0.5)
        # wrong type rejected loudly
        import pytest as _pytest

        with _pytest.raises(TypeError):
            set_global_initializer("xavier")
    finally:
        set_global_initializer(None)
    lin3 = nn.Linear(4, 4)
    assert float(np.abs(np.asarray(lin3.weight)).sum()) > 0  # xavier again
    np.testing.assert_allclose(np.asarray(lin3.bias), 0.0)


def test_bilinear_initializer_upsamples_exactly():
    """A conv_transpose with Bilinear-initialized weights upsamples by the
    factor exactly on a constant input (the initializer's whole contract)."""
    import numpy as np

    import paddle_tpu.nn as nn
    from paddle_tpu.nn.initializer import Bilinear

    factor = 2
    k = 2 * factor - factor % 2
    conv = nn.Conv2DTranspose(1, 1, k, stride=factor, padding=1,
                              weight_attr=Bilinear(), bias_attr=False)
    x = np.ones((1, 1, 4, 4), np.float32)
    out = np.asarray(conv(x))
    assert out.shape == (1, 1, 8, 8)
    # interior of a constant map upsamples to the same constant
    np.testing.assert_allclose(out[0, 0, 2:-2, 2:-2], 1.0, rtol=1e-6)
