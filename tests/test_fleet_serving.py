"""Fleet serving: paged prefix/KV block pool + multi-replica router.

The acceptance contract on top of PR 4's continuous batching:

1. **Prefix reuse is invisible in the tokens** — a request whose prompt
   prefix is warm in the block pool admits by copying matched blocks
   in-program and prefilling only the novel suffix, and its stream is
   token-identical to a cold solo ``generate()`` with the same seed;
2. **Compile discipline survives pooling** — hit admits, miss admits and
   block stores all ride ONE program family per suffix bucket, so a
   pooled replica still holds at ``#prefill_buckets + 1`` programs;
3. **The router is load- and affinity-aware** — shared-prefix traffic
   lands where its blocks are warm, occupancy/queue skew pushes traffic
   away, ``QueueFull`` fails over before propagating, drains re-route;
4. **A replica crash loses nothing** — in-flight requests reroute to
   survivors and replay identical tokens (router-assigned seeds).

Tier-1 budget discipline: ONE module-scoped two-replica fleet (ONE
bucket each) is shared by every integration test; router/pool logic is
otherwise exercised on device-free stubs. NOTE: the crash test kills
replica "b" and must stay LAST among the fleet-fixture tests.
"""
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.resilience import RetryPolicy
from paddle_tpu.serving import (BlockPool, InferenceServer,
                                NoReplicasAvailable, QueueFull,
                                ReplicaRouter, Request, SchedulerClosed,
                                ServingMetrics)
from paddle_tpu.serving.server import RequestHandle

GEO = dict(max_length=64, prefill_buckets=(32,))
POOL = dict(block_tokens=8, max_bytes=1 << 20)


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


@pytest.fixture(scope="module")
def fleet(lm):
    model, _ = lm
    a = InferenceServer(model, slots=2, prefix_cache=dict(POOL), **GEO)
    b = InferenceServer(model, slots=2, prefix_cache=dict(POOL), **GEO)
    router = ReplicaRouter()
    router.add_replica(a, "a")
    router.add_replica(b, "b")
    yield router, a, b
    for srv in (a, b):
        try:
            srv.shutdown(drain=False, timeout=30)
        except Exception:
            pass


def _prompt(cfg, n, seed=0):
    return np.random.default_rng(seed).integers(
        0, cfg.vocab_size, (n,)).astype(np.int32)


# ---------------------------------------------------------------- tentpole
def test_prefix_hit_stream_matches_cold_solo(lm, fleet):
    """THE acceptance test: a cold admit populates the pool; two
    follow-ups sharing its 16-token prefix admit as hits (blocks copied
    in-program, only the suffix prefilled) and both equal their cold
    solo generate() — greedy and seeded-sampled."""
    model, cfg = lm
    router, a, b = fleet
    prefix = _prompt(cfg, 16, 100)
    p1 = np.concatenate([prefix, _prompt(cfg, 5, 101)])
    p2 = np.concatenate([prefix, _prompt(cfg, 6, 102)])
    p3 = np.concatenate([prefix, _prompt(cfg, 4, 103)])
    solo1 = model.generate(p1[None], max_new_tokens=6, **GEO)[0]
    solo2 = model.generate(p2[None], max_new_tokens=5, **GEO)[0]
    solo3 = model.generate(p3[None], max_new_tokens=6, do_sample=True,
                           temperature=0.8, seed=9, **GEO)[0]

    h1 = router.submit(p1, max_new_tokens=6, prefer="a")
    np.testing.assert_array_equal(h1.result(timeout=300), solo1)
    assert h1.cache_hit_tokens == 0          # cold: the pool was empty

    h2 = router.submit(p2, max_new_tokens=5, prefer="a")
    h3 = router.submit(p3, max_new_tokens=6, do_sample=True,
                       temperature=0.8, seed=9, prefer="a")
    np.testing.assert_array_equal(h2.result(timeout=300), solo2)
    np.testing.assert_array_equal(h3.result(timeout=300), solo3)
    assert h2.cache_hit_tokens == 16         # both full prefix blocks
    assert h3.cache_hit_tokens == 16
    snap = a.snapshot()
    assert snap["prefix_hit_tokens"] >= 32
    assert snap["prefix_cache"]["blocks_in_use"] >= 2
    assert snap["prefix_cache"]["hit_rate"] > 0


def test_pooled_engine_holds_compile_budget(lm, fleet):
    """Hits, misses and block stores all rode ONE prefill program: the
    pooled replica sits exactly at #buckets + 1 compiled programs after
    the traffic above."""
    router, a, b = fleet
    cc = a.engine.cache_stats()
    assert cc["prefill"]["compiles"] == len(a.engine.prefill_buckets) == 1
    assert cc["decode"]["compiles"] == 1
    assert len(cc["prefill"]["signatures"]) == 1   # one shape, reused


def test_router_affinity_places_warm_replica(lm, fleet):
    """Equal load, warm blocks on "a": the shared-prefix request must
    land on "a" (prefix-affinity scoring), and a disjoint prompt on the
    emptier scorer without error."""
    model, cfg = lm
    router, a, b = fleet
    prefix = _prompt(cfg, 16, 100)           # warm on a from the test above
    p = np.concatenate([prefix, _prompt(cfg, 5, 104)])
    assert a.engine.pool.match(p) == 16 and b.engine.pool.match(p) == 0
    h = router.submit(p, max_new_tokens=2)
    h.result(timeout=300)
    assert h.replica == "a"
    assert h.cache_hit_tokens == 16


def test_fleet_crash_reroutes_and_tokens_identical(lm, fleet):
    """LAST fleet test (kills "b"): a seeded in-flight request whose
    replica dies mid-stream reroutes to the survivor and produces the
    EXACT solo tokens; the survivor does not recompile."""
    model, cfg = lm
    router, a, b = fleet
    p = _prompt(cfg, 12, 110)
    solo = model.generate(p[None], max_new_tokens=20, do_sample=True,
                          temperature=0.9, seed=77, **GEO)[0]
    before = a.engine.cache_stats()
    h = router.submit(p, max_new_tokens=20, do_sample=True,
                      temperature=0.9, seed=77, prefer="b")
    # hard kill, no drain: whatever b held must reroute, not drop
    b.shutdown(drain=False, timeout=60)
    out = h.result(timeout=300)
    np.testing.assert_array_equal(out, solo)
    assert h.reroutes >= 1 and h.replica == "a"
    assert router.replicas()["b"] == "dead"
    assert router.snapshot()["requests_rerouted"] >= 1
    after = a.engine.cache_stats()
    assert after["prefill"]["compiles"] == before["prefill"]["compiles"]
    assert after["decode"]["compiles"] == before["decode"]["compiles"]
    # dead replica out of rotation: placement still works
    out2 = router.submit(p, max_new_tokens=3).result(timeout=300)
    assert out2.shape[0] == 3


# ------------------------------------------------------- device-free units
class _StubPool:
    block_tokens = 4

    def __init__(self, matched=0):
        self.matched = matched

    def match(self, prompt):
        return min(self.matched, len(prompt))

    def match_digests(self, digests):
        return min(self.matched, len(digests) * self.block_tokens)


class _StubEngine:
    def __init__(self, active, slots, pool):
        self.active_count = active
        self.slots = slots
        self.pool = pool


class _StubScheduler:
    def __init__(self, depth, cap):
        self.depth = depth
        self.max_queue_depth = cap


class _StubHandle:
    def __init__(self, outcome):
        self.outcome = outcome  # np array to return, or exception to raise
        self.cache_hit_tokens = 0
        self.ttft_s = 0.001

    def result(self, timeout=None):
        if isinstance(self.outcome, BaseException):
            raise self.outcome
        return self.outcome

    def stream(self):
        for t in self.result():
            yield int(t)


class _StubServer:
    """Just enough surface for ReplicaRouter: live load fields +
    submit()/start()/shutdown()."""

    def __init__(self, active=0, depth=0, slots=4, cap=8, matched=0,
                 submit_error=None, outcomes=None):
        self.engine = _StubEngine(active, slots, _StubPool(matched))
        self.scheduler = _StubScheduler(depth, cap)
        self.submit_error = submit_error
        self.outcomes = list(outcomes or [])
        self.submitted = []
        self.shutdowns = []

    def start(self):
        return self

    def submit(self, **kw):
        if self.submit_error is not None:
            raise self.submit_error
        self.submitted.append(kw)
        out = (self.outcomes.pop(0) if self.outcomes
               else np.zeros(1, np.int32))
        return _StubHandle(out)

    def shutdown(self, drain=True, timeout=None):
        self.shutdowns.append(drain)

    def snapshot(self):
        return {"requests_completed": len(self.submitted),
                "tokens_emitted": 0, "prefix_hit_tokens": 0,
                "prefix_miss_tokens": 0}


def test_router_places_on_least_loaded():
    busy = _StubServer(active=4, slots=4, depth=6)
    idle = _StubServer(active=0, slots=4, depth=0)
    r = ReplicaRouter()
    r.add_replica(busy, "busy")
    r.add_replica(idle, "idle")
    h = r.submit(np.arange(4), max_new_tokens=2)
    assert h.replica == "idle" and len(idle.submitted) == 1


def test_router_affinity_outweighs_mild_load_skew():
    warm = _StubServer(active=1, slots=4, matched=8)
    cold = _StubServer(active=0, slots=4, matched=0)
    r = ReplicaRouter(affinity_weight=0.75)
    r.add_replica(warm, "warm")
    r.add_replica(cold, "cold")
    h = r.submit(np.arange(8), max_new_tokens=2)   # fully warm prompt
    assert h.replica == "warm"
    # ...but a hot replica's queue eventually outweighs its warm cache
    warm.engine.active_count = 4
    warm.scheduler.depth = 8
    h2 = r.submit(np.arange(8), max_new_tokens=2)
    assert h2.replica == "cold"


def test_router_queuefull_fails_over_then_propagates():
    full_a = _StubServer(submit_error=QueueFull("a full"))
    ok_b = _StubServer()
    r = ReplicaRouter()
    r.add_replica(full_a, "a")
    r.add_replica(ok_b, "b")
    assert r.submit(np.arange(4), max_new_tokens=2).replica == "b"
    ok_b.submit_error = QueueFull("b full")
    with pytest.raises(QueueFull):           # every replica at depth
        r.submit(np.arange(4), max_new_tokens=2)
    # ...and QueueFull stays a ConnectionError: RetryPolicy retries it
    calls = {"n": 0}

    def submit_retry():
        calls["n"] += 1
        if calls["n"] == 2:
            ok_b.submit_error = None
        return r.submit(np.arange(4), max_new_tokens=2)

    h = RetryPolicy(max_attempts=4, base_delay=0.01).call(submit_retry)
    assert h.replica == "b" and calls["n"] >= 2


def test_router_drain_reroutes_new_traffic():
    a = _StubServer()
    b = _StubServer()
    r = ReplicaRouter()
    r.add_replica(a, "a")
    r.add_replica(b, "b")
    assert r.submit(np.arange(4), max_new_tokens=2).replica == "a"
    r.drain("a", timeout=10)
    assert a.shutdowns == [True]             # graceful: backlog finishes
    assert r.replicas()["a"] == "dead"
    for _ in range(3):                       # placement never returns to a
        assert r.submit(np.arange(4), max_new_tokens=2).replica == "b"
    r.drain("b", timeout=10)
    with pytest.raises(NoReplicasAvailable):
        r.submit(np.arange(4), max_new_tokens=2)


def test_router_dead_replica_resubmits_to_survivor():
    tokens = np.asarray([5, 6, 7], np.int32)
    dying = _StubServer(outcomes=[SchedulerClosed("crashed")])
    healthy = _StubServer(outcomes=[tokens])
    r = ReplicaRouter()
    r.add_replica(dying, "dying")
    r.add_replica(healthy, "healthy")
    h = r.submit(np.arange(4), max_new_tokens=3, prefer="dying")
    np.testing.assert_array_equal(h.result(timeout=5), tokens)
    assert h.reroutes == 1 and h.replica == "healthy"
    assert r.replicas()["dying"] == "dead"
    # reroute budget bounds the loop: a fleet of corpses raises
    r2 = ReplicaRouter(max_reroutes=1)
    r2.add_replica(_StubServer(
        outcomes=[SchedulerClosed("x"), SchedulerClosed("x")]), "only")
    h2 = r2.submit(np.arange(4), max_new_tokens=3)
    with pytest.raises(SchedulerClosed):
        h2.result(timeout=5)


def test_router_reroute_is_single_flight_across_consumers():
    """Two threads blocked on one RouterHandle observing the same dead
    inner handle must trigger exactly ONE resubmission (the loser waits
    for the winner's placement and picks up its handle)."""
    import threading

    tokens = np.asarray([3, 4], np.int32)
    dying = _StubServer(outcomes=[SchedulerClosed("crashed")])
    healthy = _StubServer(outcomes=[tokens, tokens])
    r = ReplicaRouter()
    r.add_replica(dying, "dying")
    r.add_replica(healthy, "healthy")
    h = r.submit(np.arange(4), max_new_tokens=2, prefer="dying")
    got, errs = [], []

    def consume():
        try:
            got.append(h.result(timeout=10))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=consume) for _ in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=30) for t in ts]
    assert not errs and len(got) == 2
    np.testing.assert_array_equal(got[0], tokens)
    np.testing.assert_array_equal(got[1], tokens)
    assert len(healthy.submitted) == 1       # ONE resubmission, not two
    assert h.reroutes == 1


def test_router_all_replicas_closed_raises_retryable():
    """Every candidate rejecting with SchedulerClosed (a fleet-wide
    shutdown race) must surface as retryable NoReplicasAvailable — not
    the non-retryable SchedulerClosed — and mark the corpses DEAD."""
    r = ReplicaRouter()
    r.add_replica(_StubServer(submit_error=SchedulerClosed("gone")), "x")
    r.add_replica(_StubServer(submit_error=SchedulerClosed("gone")), "y")
    with pytest.raises(NoReplicasAvailable):
        r.submit(np.arange(4), max_new_tokens=2)
    assert set(r.replicas().values()) == {"dead"}


def test_prefix_cache_zero_budget_means_off(lm):
    """A 0-byte budget spells "disabled" (config convention), never a
    one-block pool on the slower pooled program."""
    model, _ = lm
    srv = InferenceServer(model, slots=1, prefix_cache=0, **GEO)
    assert srv.engine.pool is None
    srv2 = InferenceServer(model, slots=1, prefix_cache=0.0, **GEO)
    assert srv2.engine.pool is None


def test_router_assigns_seed_to_unseeded_sampled():
    """The reroute-replay guarantee: an unseeded sampled request gets a
    concrete seed at the front door, so a resubmission reuses it."""
    a = _StubServer()
    r = ReplicaRouter()
    r.add_replica(a, "a")
    r.submit(np.arange(4), max_new_tokens=2, do_sample=True)
    assert a.submitted[0]["seed"] is not None
    r.submit(np.arange(4), max_new_tokens=2)          # greedy: no seed
    assert a.submitted[1]["seed"] is None


# ----------------------------------------------------------- block pool
class _SpecModel:
    def cache_spec(self):
        return {"num_layers": 2, "num_kv_heads": 2, "head_dim": 4,
                "max_length": 64, "dtype": "float32"}


def _commit_tokens(pool, toks, matched=None):
    """Host-side store of a prompt's full blocks (the engine does this
    around its fused dispatch)."""
    hit = pool.lookup(toks)
    m = hit.tokens if matched is None else matched
    if m != hit.tokens:
        hit = pool.trim(hit, m)
    plan = pool.plan_store(toks, m)
    pool.commit(hit, plan, pool.tensors)
    return hit, plan


def test_block_pool_hash_chain_match():
    pool = BlockPool(_SpecModel(), block_tokens=4, max_bytes=1 << 20)
    toks = np.arange(14, dtype=np.int32)     # 3 full blocks + tail of 2
    assert pool.match(toks) == 0
    _commit_tokens(pool, toks)
    assert pool.match(toks) == 12
    # same prefix, divergent third block: chain stops at 2 blocks
    other = toks.copy()
    other[9] = 99
    assert pool.match(other) == 8
    # the WHOLE prompt never matches: the last token must be recomputed
    exact = np.arange(12, dtype=np.int32)
    assert pool.match(exact) == 8
    # a matched read plan points the padded tail at the dump row 0
    hit = pool.lookup(toks)
    assert hit.tokens == 12
    assert (hit.read_idx[:3] > 0).all() and (hit.read_idx[3:] == 0).all()
    plan = pool.plan_store(toks, hit.tokens)
    assert not plan.pending                  # nothing new to store
    pool.commit(hit, plan, pool.tensors)
    s = pool.stats()
    assert s["blocks_in_use"] == 3 and s["hit_tokens"] >= 12
    assert 0 < s["occupancy"] <= 1 and s["hit_rate"] > 0


def test_block_pool_lru_eviction_and_pinning():
    spec = _SpecModel()
    probe = BlockPool(spec, block_tokens=4, max_bytes=1 << 20)
    pool = BlockPool(spec, block_tokens=4,
                     max_bytes=4 * probe.block_bytes)   # 4 usable rows
    assert pool.num_blocks == 5              # + reserved dump row
    a = np.arange(0, 9, dtype=np.int32)      # 2 full blocks
    b = np.arange(100, 109, dtype=np.int32)  # 2 full blocks
    _commit_tokens(pool, a)
    _commit_tokens(pool, b)
    assert pool.stats()["blocks_in_use"] == 4            # pool full
    c = np.arange(200, 209, dtype=np.int32)
    _commit_tokens(pool, c)                  # forces eviction, LRU = a
    s = pool.stats()
    assert s["blocks_evicted"] == 2 and s["blocks_in_use"] == 4
    assert pool.match(a) == 0 and pool.match(b) == 8 and pool.match(c) == 8
    # pinned entries survive eviction pressure: hold b, push d through
    hit_b = pool.lookup(b)
    d = np.arange(300, 309, dtype=np.int32)
    hit_d = pool.lookup(d)                   # miss (0 matched), no pins
    plan_d = pool.plan_store(d, 0)
    assert len(plan_d.pending) <= 2          # c's rows (LRU, unpinned)...
    pool.commit(hit_d, plan_d, pool.tensors)
    assert pool.match(b) == 8                # ...never b's (pinned)
    pool.commit(hit_b, pool.plan_store(b, hit_b.tokens), pool.tensors)


def test_block_pool_child_blocks_protect_parents():
    """A chain's middle link never evicts from under its descendants:
    eviction takes leaves first (children == 0)."""
    spec = _SpecModel()
    probe = BlockPool(spec, block_tokens=4, max_bytes=1 << 20)
    pool = BlockPool(spec, block_tokens=4,
                     max_bytes=3 * probe.block_bytes)
    chain = np.arange(13, dtype=np.int32)    # 3 full blocks, one chain
    _commit_tokens(pool, chain)
    assert pool.stats()["blocks_in_use"] == 3
    x = np.arange(500, 505, dtype=np.int32)  # 1 block, needs 1 eviction
    _commit_tokens(pool, x)
    # the leaf (block 3 of the chain) went; the chain still matches 8
    assert pool.match(chain) == 8
    assert pool.match(x) == 4


def test_block_pool_reset_and_abort():
    pool = BlockPool(_SpecModel(), block_tokens=4, max_bytes=1 << 20)
    toks = np.arange(9, dtype=np.int32)
    hit = pool.lookup(toks)
    plan = pool.plan_store(toks, 0)
    assert len(plan.pending) == 2
    free_before = len(pool._free)
    pool.abort(hit, plan)                    # dispatch failed: rows back
    assert len(pool._free) == free_before + 2
    assert pool.match(toks) == 0
    _commit_tokens(pool, toks)
    assert pool.match(toks) == 8
    pool.reset()                             # crash recovery wipes blocks
    assert pool.match(toks) == 0
    assert pool.stats()["blocks_in_use"] == 0
    assert pool.stats()["blocks_stored"] == 2   # cumulative survives


def test_block_pool_abort_without_plan_releases_pins():
    """tpu_lint R9 regression: a failure between lookup and plan_store
    has pins but no plan yet — abort(hit) alone must release them so
    the blocks stay evictable."""
    pool = BlockPool(_SpecModel(), block_tokens=4, max_bytes=1 << 20)
    toks = np.arange(14, dtype=np.int32)
    _commit_tokens(pool, toks)
    hit = pool.lookup(toks)
    assert hit.tokens == 12
    assert pool.stats()["blocks_pinned"] == 3
    pool.abort(hit)                          # no plan: pins only
    assert pool.stats()["blocks_pinned"] == 0


def test_plan_hit_failure_path_releases_pins(monkeypatch):
    """tpu_lint R9 regression (the self-application fix): a raise out
    of plan_store inside `_plan_hit` must abort the lookup's pins —
    pre-fix they leaked forever, making the pool unevictable."""
    from types import SimpleNamespace

    from paddle_tpu.serving.engine import ContinuousBatchingEngine

    pool = BlockPool(_SpecModel(), block_tokens=4, max_bytes=1 << 20)
    toks = np.arange(14, dtype=np.int32)
    _commit_tokens(pool, toks)

    def boom(*a, **k):
        raise RuntimeError("planner down")

    monkeypatch.setattr(pool, "plan_store", boom)
    fake = SimpleNamespace(pool=pool, max_length=64,
                           bucket_for_prompt=lambda n: 32)
    with pytest.raises(RuntimeError, match="planner down"):
        ContinuousBatchingEngine._plan_hit(fake, toks,
                                           int(toks.shape[0]))
    assert pool.stats()["blocks_pinned"] == 0


def test_gather_scatter_cache_blocks_roundtrip():
    """The paged-pool primitives (generation.py): scatter a cache row
    into pool blocks, gather it back at the same indices — identical;
    dump-row writes never corrupt real blocks. Eager: no compile."""
    import jax.numpy as jnp
    from paddle_tpu.models.generation import (gather_cache_blocks,
                                              scatter_cache_blocks)

    rng = np.random.default_rng(0)
    pool = tuple((jnp.asarray(rng.normal(size=(6, 4, 2, 3)), jnp.float32),
                  jnp.asarray(rng.normal(size=(6, 4, 2, 3)), jnp.float32))
                 for _ in range(2))
    row = tuple((jnp.asarray(rng.normal(size=(1, 16, 2, 3)), jnp.float32),
                 jnp.asarray(rng.normal(size=(1, 16, 2, 3)), jnp.float32))
                for _ in range(2))
    idx = jnp.asarray([2, 5, 0, 0], jnp.int32)   # blocks 3/4 -> dump row
    stored = scatter_cache_blocks(pool, row, idx)
    back = gather_cache_blocks(stored, idx, 16)
    for (bk, bv), (rk, rv) in zip(back, row):
        np.testing.assert_array_equal(np.asarray(bk)[0, :8],
                                      np.asarray(rk)[0, :8])
    for li in (0, 1):                        # untouched rows keep values
        for j in (1, 3, 4):
            np.testing.assert_array_equal(np.asarray(stored[li][0])[j],
                                          np.asarray(pool[li][0])[j])
    short = gather_cache_blocks(stored, idx, 20)  # padded past n*bs
    assert np.asarray(short[0][0]).shape == (1, 20, 2, 3)
    assert (np.asarray(short[0][0])[0, 16:] == 0).all()


def test_metrics_snapshot_prefix_fields():
    m = ServingMetrics(slots=2)
    m.inc("prefix_hit_tokens", 30)
    m.inc("prefix_miss_tokens", 10)
    snap = m.snapshot(prefix_cache={"blocks_in_use": 3, "occupancy": 0.5})
    assert snap["prefix_hit_tokens"] == 30
    assert snap["prefix_miss_tokens"] == 10
    assert snap["prefix_hit_rate"] == 0.75
    assert snap["prefix_cache"]["blocks_in_use"] == 3
    assert "prefix_cache" not in ServingMetrics(slots=1).snapshot()


# ------------------------------------------- scheduler expiry regression
def test_shutdown_tail_counts_queued_expiry_as_expired(lm):
    """Regression (satellite): a request whose deadline lapsed while
    QUEUED, caught by a non-drain shutdown racing the expiry sweep, must
    expire (TimeoutError + requests_expired) — not vanish into
    requests_failed as a generic SchedulerClosed."""
    from paddle_tpu.distributed.resilience import Deadline

    model, _ = lm
    srv = InferenceServer(model, slots=1, **GEO)   # worker never started
    expired_req = Request(prompt=np.arange(4), deadline=Deadline(0.0))
    expired_req.handle = RequestHandle(expired_req)
    live_req = Request(prompt=np.arange(4), deadline=None)
    live_req.handle = RequestHandle(live_req)
    srv.scheduler.submit(expired_req)
    srv.scheduler.submit(live_req)
    time.sleep(0.005)
    srv._fail_backlog()
    assert srv.metrics.requests_expired == 1
    assert srv.metrics.requests_failed == 1
    with pytest.raises(TimeoutError, match="expired in queue"):
        expired_req.handle.result(timeout=1)
    with pytest.raises(SchedulerClosed):
        live_req.handle.result(timeout=1)


def test_queued_expiry_still_counted_in_live_loop(lm):
    """The pre-existing live path keeps working: deadline expiry during
    normal service produces TimeoutError + the expired counter."""
    model, cfg = lm
    srv = InferenceServer(model, slots=1, **GEO)
    # pretend every slot is busy so nothing admits and the queued
    # request can only expire (device-free: no dispatch, no compile)
    srv.engine.free_slots = lambda: []
    h = srv.submit(_prompt(cfg, 4), max_new_tokens=2, deadline=0.01)
    with pytest.raises(TimeoutError, match="expired in queue"):
        h.result(timeout=30)
    assert srv.metrics.requests_expired == 1
    srv.shutdown(drain=False, timeout=30)


# ------------------------------------------------------------------- slow
@pytest.mark.slow
def test_serve_bench_fleet_crash_cli():
    """The robustness_gate --fleet command end-to-end: 2 replicas,
    prefix-heavy trace, one hard-killed mid-window — exit 0 (all
    requests recovered, token parity held, zero steady recompiles)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--check", "--replicas", "2", "--prefix-cache-mb", "4",
         "--prefix-tokens", "24", "--crash-replica", "--verify", "3"],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(
        [l for l in proc.stdout.splitlines() if l.startswith('{"')][-1])
    ex = rec["extra"]
    assert ex["failed"] == 0
    assert ex["verify_failures"] == 0
    assert ex["cache_hit_rate"] > 0
    assert ex["steady_state_recompiles"] == 0
    assert ex["crashed_replica"] == "r1" and ex["live_replicas"] == 1
