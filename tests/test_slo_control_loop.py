"""The SLO control loop (PR 16): burn-rate-driven autoscaler,
per-tenant token-bucket admission + deficit-round-robin fair queueing,
and the abuse-proofing contract (rate-limit rejects book ZERO tenant
failures, so an abusive tenant cannot buy fleet capacity).

Everything here runs on stubs — no model build, no rpc world, injected
clocks throughout — so the suite stays inside the tier-1 time budget;
the real 2-process adversarial trace is ``tools/serve_bench.py
--fairness`` (robustness_gate --fairness).
"""
import json
import os
import sys
import threading

import numpy as np
import pytest

from paddle_tpu.observability import flight
from paddle_tpu.serving import (Autoscaler, Backpressure, FifoScheduler,
                                InferenceServer, Overloaded, QueueFull,
                                RateLimited, ReplicaRouter, Request,
                                TokenBucket)
from paddle_tpu.serving.scheduler import BASE_TENANT

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


@pytest.fixture(autouse=True)
def _restore_flight_dir():
    rec = flight.flight_recorder()
    saved = rec.dump_dir
    yield
    flight.configure(dump_dir=saved)


def _req(tenant=None, deadline=None, n=4):
    return Request(prompt=np.zeros(n, np.int32), max_new_tokens=4,
                   adapter_id=tenant, deadline=deadline)


# ------------------------------------------------------------ TokenBucket
def test_token_bucket_burst_then_rate():
    b = TokenBucket(rate=2.0, burst=3.0)
    now = 100.0
    assert all(b.try_take(now) for _ in range(3))   # burst capacity
    assert not b.try_take(now)                      # empty
    assert b.retry_after() == pytest.approx(0.5)    # 1 token at 2/s
    assert b.try_take(now + 0.5)                    # refilled exactly 1
    assert not b.try_take(now + 0.5)
    # refill caps at burst: a long quiet period doesn't bank credit
    assert b.level(now + 1000.0) == pytest.approx(3.0)


def test_rate_limited_is_retryable_backpressure():
    e = RateLimited("over", tenant="t1", retry_after=0.25)
    assert isinstance(e, Backpressure)
    assert isinstance(e, ConnectionError)   # RetryPolicy-visible
    assert e.tenant == "t1" and e.retry_after == pytest.approx(0.25)


# ----------------------------------------------- scheduler rate limiting
def test_scheduler_defaults_off_no_buckets():
    s = FifoScheduler(max_queue_depth=4)
    for _ in range(4):
        s.submit(_req(tenant="loud"))   # unlimited without knobs
    assert s.bucket_levels() == {}
    with pytest.raises(QueueFull):      # depth cap still the only gate
        s.submit(_req(tenant="loud"))


def test_scheduler_per_tenant_bucket_rejects_and_refills():
    clock = [0.0]
    s = FifoScheduler(max_queue_depth=64, tenant_rate=1.0,
                      tenant_burst=2.0, clock=lambda: clock[0])
    s.submit(_req(tenant="t"))
    s.submit(_req(tenant="t"))
    with pytest.raises(RateLimited) as ei:
        s.submit(_req(tenant="t"))
    assert ei.value.tenant == "t"
    assert ei.value.retry_after == pytest.approx(1.0)
    s.submit(_req(tenant="other"))      # other tenants: own buckets
    clock[0] = 1.0
    s.submit(_req(tenant="t"))          # refilled
    levels = s.bucket_levels()
    assert levels["t"]["rate"] == 1.0 and levels["t"]["burst"] == 2.0
    assert levels["t"]["tokens"] == pytest.approx(0.0)


def test_scheduler_tenant_limits_override_and_base_tenant():
    clock = [0.0]
    s = FifoScheduler(max_queue_depth=64,
                      tenant_limits={"abuser": (1.0, 1.0)},
                      clock=lambda: clock[0])
    s.submit(_req(tenant="abuser"))
    with pytest.raises(RateLimited):
        s.submit(_req(tenant="abuser"))
    for _ in range(8):
        s.submit(_req())                # base/unlisted: unlimited
    assert BASE_TENANT not in s.bucket_levels()


def test_requeue_bypasses_the_bucket():
    """A crash-recovery requeue re-admits work the tenant ALREADY paid
    admission for — charging the bucket again would double-bill."""
    clock = [0.0]
    s = FifoScheduler(max_queue_depth=64,
                      tenant_limits={"t": (1.0, 1.0)},
                      clock=lambda: clock[0])
    r = _req(tenant="t")
    s.submit(r)
    taken, _ = s.take(1)
    assert taken == [r]
    s.requeue(r)                        # no RateLimited despite empty
    assert s.take(1)[0] == [r]          # bucket


# --------------------------------------------------- DRR fair queueing
def test_fair_take_round_robins_under_10x_tenant():
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=8,
                      fair_queueing=True)
    flood = [_req(tenant="abuser") for _ in range(20)]
    quiet = [_req(tenant="alice"), _req(tenant="bob")]
    for r in flood[:10]:
        s.submit(r)
    for r in quiet:
        s.submit(r)
    for r in flood[10:]:
        s.submit(r)
    got, _ = s.take(4)
    # one service quantum per tenant per round: both quiet tenants are
    # served in the FIRST budget despite 20 queued abuser requests
    # (identity checks: Request.__eq__ compares numpy prompt fields)
    assert any(r is quiet[0] for r in got)
    assert any(r is quiet[1] for r in got)
    assert [r for r in got if r.adapter_id == "abuser"] == flood[:2]


def test_fair_take_fifo_within_tenant_and_drains():
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=4,
                      fair_queueing=True)
    a = [_req(tenant="a") for _ in range(3)]
    b = [_req(tenant="b") for _ in range(1)]
    for r in a[:2]:
        s.submit(r)
    for r in b:
        s.submit(r)
    s.submit(a[2])
    assert s.take(4)[0] == [a[0], b[0], a[1], a[2]]
    assert s.depth == 0


def test_fair_weights_bias_the_quantum():
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=6,
                      fair_queueing=True,
                      fair_weights={"gold": 2.0, "bronze": 1.0})
    gold = [_req(tenant="gold") for _ in range(4)]
    bronze = [_req(tenant="bronze") for _ in range(4)]
    for g, b in zip(gold, bronze):
        s.submit(g)
        s.submit(b)
    got, _ = s.take(6)
    assert len([r for r in got if r.adapter_id == "gold"]) == 4
    assert len([r for r in got if r.adapter_id == "bronze"]) == 2


def test_fair_take_skips_expired_without_spending_deficit():
    clock = [0.0]
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=4,
                      fair_queueing=True)
    from paddle_tpu.distributed.resilience import Deadline

    dead = _req(tenant="a", deadline=Deadline(0.0))
    live = _req(tenant="a")
    other = _req(tenant="b")
    s.submit(dead)
    s.submit(live)
    s.submit(other)
    got, exp = s.take(3)
    assert all(r is not dead for r in got)
    assert any(r is live for r in got) and any(r is other for r in got)
    assert len(exp) == 1 and exp[0] is dead     # handed back to fail


def test_fair_off_is_strict_fifo():
    """Defaults-off bit-identical: without fair_queueing the take order
    is EXACTLY the PR 15 FIFO regardless of tenant mix."""
    s = FifoScheduler(max_queue_depth=64, max_prefills_per_step=8)
    reqs = [_req(tenant=t) for t in
            ("a", "a", "a", "b", "a", None, "a", "b")]
    for r in reqs:
        s.submit(r)
    assert s.take(8)[0] == reqs


# -------------------------------------- server path (stubbed, no model)
class _KnownStore:
    """Submit-path validation stub: every adapter name is registered."""

    def known(self, name):
        return True

    def resident(self, name):
        return False    # no adapter-affinity bonus in scoring


class _StubEngine:
    active_count = 0
    slots = 4
    pool = None
    store = None

    def validate(self, n, m):
        pass

    allow_top_p = True


def _stub_server(**sched_kw):
    """A real InferenceServer instance driving a REAL FifoScheduler
    through the real ``submit()`` path — engine and start() stubbed so
    no model is built and no loop thread spawns."""
    srv = object.__new__(InferenceServer)
    from paddle_tpu.serving.metrics import ServingMetrics

    srv.engine = _StubEngine()
    srv.engine.store = _KnownStore()
    srv.scheduler = FifoScheduler(**sched_kw)
    srv.metrics = ServingMetrics(slots=4)
    srv._cv = threading.Condition()
    srv.start = lambda: srv
    return srv


def test_server_submit_rate_limited_counts_not_tenant_failure(tmp_path):
    """The abuse-proofing contract end to end at the server door: a
    RateLimited reject increments its own counter, notes a
    tenant-labeled flight event, and books NO per-tenant failure — so
    the SLO tracker sees zero burn from throttled abuse."""
    flight.configure(dump_dir=str(tmp_path))
    clock = [0.0]
    srv = _stub_server(max_queue_depth=8,
                       tenant_limits={"abuser": (1.0, 1.0)},
                       clock=lambda: clock[0])
    srv.submit(np.zeros(4, np.int32), max_new_tokens=4,
               adapter_id="abuser")
    with pytest.raises(RateLimited):
        srv.submit(np.zeros(4, np.int32), max_new_tokens=4,
                   adapter_id="abuser")
    snap = srv.metrics.snapshot()
    assert snap["requests_rate_limited"] == 1
    assert snap["requests_shed"] == 0
    # NO failure booked against the tenant (shed/expired would book)
    assert snap.get("per_adapter", {}).get("abuser", {}) \
                                      .get("failures", 0) == 0
    ev = [e for e in flight.flight_recorder().events()
          if e.get("kind") == "rate_limited"]
    assert ev and ev[-1]["tenant"] == "abuser"
    assert ev[-1].get("corr")       # listable as a trace lane
    # the statusz token_buckets block reads straight from here
    assert srv.scheduler.bucket_levels()["abuser"]["rate"] == 1.0


def test_rate_limited_flight_event_lists_in_trace_view(tmp_path):
    flight.configure(dump_dir=str(tmp_path))
    clock = [0.0]
    srv = _stub_server(max_queue_depth=8,
                       tenant_limits={"abuser": (1.0, 1.0)},
                       clock=lambda: clock[0])
    srv.submit(np.zeros(4, np.int32), max_new_tokens=4,
               adapter_id="abuser")
    with pytest.raises(RateLimited):
        srv.submit(np.zeros(4, np.int32), max_new_tokens=4,
                   adapter_id="abuser")
    path = flight.dump("test_rate_limit_dump")
    from trace_view import list_correlations, load_spans

    spans, _ = load_spans(path)
    rl = [s for s in spans if s["name"] == "event:rate_limited"]
    assert rl and rl[0]["tags"]["tenant"] == "abuser"
    corrs = {e["corr"] for e in list_correlations(spans)}
    assert rl[0]["corr"] in corrs


# ----------------------------------------------------------- autoscaler
class _StubSched:
    depth = 0
    max_queue_depth = 8

    def __init__(self, buckets=None):
        self._buckets = buckets or {}

    def bucket_levels(self):
        return dict(self._buckets)


class _StubServer:
    def __init__(self, buckets=None):
        self.engine = _StubEngine()
        self.scheduler = _StubSched(buckets)
        self.started = False
        self.shutdowns = []

    def start(self):
        self.started = True
        return self

    def shutdown(self, drain=True, timeout=None):
        self.shutdowns.append(drain)

    def snapshot(self):
        return {"requests_completed": 0, "tokens_emitted": 0,
                "prefix_hit_tokens": 0, "prefix_miss_tokens": 0}

    def statusz(self):
        return {}


def _burning(tenant="spike", burn=5.0):
    return {"tenants": {tenant: {
        "burn_slow": burn, "burn_fast": 2 * burn, "slow_breached": True,
        "fast_breached": False, "alerting": False,
        "window_slow": {"total": 10}, "window_fast": {"total": 10}}}}


def _quiet():
    return {"tenants": {"spike": {
        "burn_slow": 0.0, "burn_fast": 0.0, "slow_breached": False,
        "fast_breached": False, "alerting": False,
        "window_slow": {"total": 10}, "window_fast": {"total": 10}}}}


def _fleet(n=1, spawn_log=None, **kw):
    router = ReplicaRouter([_StubServer() for _ in range(n)])
    clock = [0.0]

    def spawn(name):
        if spawn_log is not None:
            spawn_log.append(name)
        return _StubServer()

    kw.setdefault("sustain_ticks", 2)
    kw.setdefault("cooldown_s", 60.0)
    auto = Autoscaler(router, spawn, clock=lambda: clock[0], **kw)
    return router, auto, clock


def test_scale_out_is_edge_triggered_on_sustained_burn():
    spawned = []
    router, auto, clock = _fleet(1, spawn_log=spawned, max_replicas=3)
    router.slo_report = _burning
    assert auto.tick() is None          # 1 hot tick: sustaining, no act
    d = auto.tick()
    assert d["action"] == "scale_out" and d["tenant"] == "spike"
    assert d["burn_slow"] == pytest.approx(5.0)
    assert spawned == ["auto-1"]
    assert router.replicas()["auto-1"] == "active"
    assert auto.scale_outs == 1


def test_one_window_spike_does_not_scale():
    """Hysteresis: burn must SUSTAIN for sustain_ticks consecutive
    evaluations — a single hot window resets on the next quiet one."""
    router, auto, clock = _fleet(1, max_replicas=3)
    reports = [_burning(), _quiet(), _burning(), _quiet()]
    router.slo_report = lambda: reports.pop(0)
    for _ in range(4):
        assert auto.tick() is None
    assert auto.scale_outs == 0


def test_cooldown_suppresses_flap():
    router, auto, clock = _fleet(1, max_replicas=4, cooldown_s=60.0)
    router.slo_report = _burning
    auto.tick()
    assert auto.tick()["action"] == "scale_out"
    clock[0] = 59.0                     # still cooling: burn keeps
    for _ in range(5):                  # sustaining but nothing fires
        assert auto.tick() is None
    assert auto.scale_outs == 1
    clock[0] = 121.0                    # cooldown over: the sustain
    d = auto.tick()                     # banked while cooling fires at
    assert d["action"] == "scale_out"   # once
    assert auto.scale_outs == 2


def test_max_replicas_bounds_scale_out():
    router, auto, clock = _fleet(2, max_replicas=2)
    router.slo_report = _burning
    for _ in range(6):
        assert auto.tick() is None
    assert auto.scale_outs == 0


def test_scale_in_drains_never_kills():
    spawned = []
    router, auto, clock = _fleet(1, spawn_log=spawned, max_replicas=2,
                                 scale_in_load=0.5)
    router.slo_report = _burning
    auto.tick()
    auto.tick()
    grown = router._replicas["auto-1"].server
    router.slo_report = _quiet
    clock[0] = 100.0
    assert auto.tick() is None          # sustained headroom required
    d = auto.tick()
    assert d["action"] == "scale_in" and d["replica"] == "auto-1"
    # the LIFO victim is the autoscaler's own spawn, and it was
    # DRAINED (drain=True), never killed
    assert grown.shutdowns == [True]
    assert router.replicas()["auto-1"] == "dead"
    assert auto.scale_ins == 1


def test_min_replicas_bounds_scale_in():
    router, auto, clock = _fleet(1, min_replicas=1, max_replicas=2,
                                 scale_in_load=0.5)
    router.slo_report = _quiet
    for _ in range(6):
        assert auto.tick() is None
    assert auto.scale_ins == 0


def test_spawn_failure_is_counted_not_fatal():
    router = ReplicaRouter([_StubServer()])

    def bad_spawn(name):
        raise RuntimeError("boom")

    clock = [0.0]
    auto = Autoscaler(router, bad_spawn, sustain_ticks=1,
                      cooldown_s=0.0, max_replicas=2,
                      clock=lambda: clock[0])
    router.slo_report = _burning
    d = auto.tick()
    assert d["action"] == "scale_out_failed" and "boom" in d["error"]
    assert auto.spawn_failures == 1 and auto.scale_outs == 0
    assert list(router.replicas()) == ["replica-%d" % (
        int(list(router.replicas())[0].split("-")[1]))]  # no new member


def test_statusz_autoscaler_block_and_token_buckets():
    router = ReplicaRouter(
        [_StubServer(buckets={"abuser": {"tokens": 0.5, "rate": 1.0,
                                         "burst": 2.0}})])
    clock = [0.0]
    auto = Autoscaler(router, lambda name: _StubServer(),
                      sustain_ticks=1, cooldown_s=60.0, max_replicas=2,
                      clock=lambda: clock[0])
    router.slo_report = _burning
    auto.tick()
    block = router.statusz()["autoscaler"]
    assert block["state"] == "manual"       # no interval -> no thread
    assert block["scale_outs"] == 1
    assert block["last_decision"]["tenant"] == "spike"
    assert block["cooldown_remaining_s"] == pytest.approx(60.0)
    assert block["config"]["max_replicas"] == 2
    name = next(iter(router.replicas()))
    assert block["token_buckets"][name]["abuser"]["tokens"] == 0.5


def test_statusz_has_no_autoscaler_block_by_default():
    router = ReplicaRouter([_StubServer()])
    assert "autoscaler" not in router.statusz()


def test_router_shutdown_stops_autoscaler_thread():
    router = ReplicaRouter([_StubServer()])
    auto = Autoscaler(router, lambda name: _StubServer(),
                      interval=30.0)
    auto.start()
    assert auto._thread is not None and auto._thread.is_alive()
    router.shutdown()
    assert not auto._thread.is_alive()


def test_scale_out_dump_lists_in_trace_view(tmp_path):
    flight.configure(dump_dir=str(tmp_path))
    router, auto, clock = _fleet(1, max_replicas=2, sustain_ticks=1)
    router.slo_report = _burning
    d = auto.tick()
    assert d["action"] == "scale_out"
    dumps = [f for f in os.listdir(tmp_path) if "scale_out" in f]
    assert dumps
    from trace_view import list_correlations, load_spans

    spans, _ = load_spans(os.path.join(str(tmp_path), dumps[0]))
    lanes = {e["corr"]: e for e in list_correlations(spans)}
    assert d["corr"] in lanes           # visible in --list
    ev = [s for s in spans if s["name"] == "event:scale_out"
          and s["corr"] == d["corr"]]
    assert ev and ev[0]["tags"]["tenant"] == "spike"
    with open(os.path.join(str(tmp_path), dumps[0])) as f:
        extra = json.load(f)["extra"]
    assert extra["tenant"] == "spike"   # burn evidence rides the dump
    assert extra["burn_slow"] == pytest.approx(5.0)


# ----------------------------------------- RateLimited through the router
class _RateLimitingServer(_StubServer):
    def __init__(self, exc):
        super().__init__()
        self.engine.store = _KnownStore()   # passes the adapter filter
        self.exc = exc

    def submit(self, **kw):
        raise self.exc


def test_router_propagates_rate_limited_when_all_replicas_throttle():
    router = ReplicaRouter([
        _RateLimitingServer(RateLimited("over", tenant="t",
                                        retry_after=0.5)),
        _RateLimitingServer(RateLimited("over", tenant="t",
                                        retry_after=0.7))])
    with pytest.raises(RateLimited) as ei:
        router.submit(np.zeros(4, np.int32), max_new_tokens=4,
                      adapter_id="t")
    assert ei.value.tenant == "t"       # tenant + retry_after intact


def test_router_mixed_rate_limit_and_full_raises_queue_full():
    router = ReplicaRouter([
        _RateLimitingServer(RateLimited("over", tenant="t")),
        _RateLimitingServer(QueueFull("full"))])
    with pytest.raises(QueueFull):
        router.submit(np.zeros(4, np.int32), max_new_tokens=4)


# ----------------------------------------------------- adapter hot-swap
class _SwapStore:
    def __init__(self, fail=False):
        self.fail = fail
        self.versions = {}

    def register(self, name, state):
        if self.fail:
            raise RuntimeError("load failed")
        self.versions[name] = self.versions.get(name, 0) + 1

    def known(self, name):
        return name in self.versions


def test_register_adapter_broadcasts_to_live_replicas():
    good, bad, storeless = _StubServer(), _StubServer(), _StubServer()
    good.engine.store = _SwapStore()
    bad.engine.store = _SwapStore(fail=True)
    router = ReplicaRouter()
    router.add_replica(good, "good")
    router.add_replica(bad, "bad")
    router.add_replica(storeless, "none")
    dead = _StubServer()
    dead.engine.store = _SwapStore()
    router.add_replica(dead, "dead")
    router._mark_dead("dead", cause="test")
    out = router.register_adapter("tenantA", {"w": 1})
    assert out == {"good": True, "bad": False, "none": False}
    assert good.engine.store.versions["tenantA"] == 1
    assert dead.engine.store.versions == {}     # dead replica skipped
    # re-register = hot swap: version bumps again on the live store
    router.register_adapter("tenantA", {"w": 2})
    assert good.engine.store.versions["tenantA"] == 2


def test_hot_swap_pins_old_rows_until_stream_end():
    """The PR 9 contract the router broadcast rides end to end: a
    re-register over a PINNED row orphans it — the live stream keeps
    its rows/salt to the end, new acquires get the new version and a
    DIFFERENT salt (so no cache can serve stale weights)."""
    from paddle_tpu import lora
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    cfg = gpt_tiny(hidden_size=32, num_layers=1, num_heads=2,
                   vocab_size=64, max_position_embeddings=32,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    lora.apply_lora(model, lora.LoraConfig(rank=1, alpha=2.0))
    zero = lora.lora_state(model)
    v1 = {k: np.full(np.shape(v), 0.01, np.float32)
          for k, v in zero.items()}
    v2 = {k: np.full(np.shape(v), 0.02, np.float32)
          for k, v in zero.items()}
    store = lora.AdapterStore(model, max_loaded=3)
    store.register("t", v1)
    slot_old, salt_old = store.acquire("t", with_salt=True)
    store.register("t", v2)             # hot swap mid-stream
    slot_new, salt_new = store.acquire("t", with_salt=True)
    assert salt_new != salt_old         # version salt split the caches
    assert slot_new != slot_old         # old row still pinned, intact
    store.release(slot_old)             # stream ends -> old row frees
    store.release(slot_new)
