"""Remaining-namespace parity batch (r4): sparse unary/util family,
hfft2/hfftn pair, incubate graph/segment/fused-softmax ops, jit
translator controls + TracedLayer, profiler protobuf roundtrip,
distribution Independent/ExponentialFamily, WMT datasets."""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt


def _ref_all(path):
    try:
        s = open(path).read()
    except OSError:
        pytest.skip("reference tree not mounted")
    m = re.search(r"__all__ = \[(.*?)\]", s, re.S)
    return set(re.findall(r"'(\w+)'", m.group(1))) if m else set()


def test_remaining_namespaces_zero_missing():
    import paddle_tpu.distribution as distr
    import paddle_tpu.fft as fft
    import paddle_tpu.incubate as inc
    import paddle_tpu.jit as jit
    import paddle_tpu.profiler as prof
    import paddle_tpu.sparse as sparse
    import paddle_tpu.text as text

    for p, mod in [
            ('/root/reference/python/paddle/jit/__init__.py', jit),
            ('/root/reference/python/paddle/profiler/__init__.py', prof),
            ('/root/reference/python/paddle/sparse/__init__.py', sparse),
            ('/root/reference/python/paddle/fft.py', fft),
            ('/root/reference/python/paddle/incubate/__init__.py', inc),
            ('/root/reference/python/paddle/distribution/__init__.py',
             distr),
            ('/root/reference/python/paddle/text/__init__.py', text)]:
        ref = _ref_all(p)
        missing = sorted(x for x in ref
                         if x not in set(dir(mod)) and not x.startswith('_'))
        assert missing == [], (p, missing)


def test_sparse_family():
    import paddle_tpu.sparse as S

    t = S.sparse_coo_tensor([[0, 1, 1], [1, 0, 2]], [0.5, -2.0, 3.0], (2, 3))
    dense = np.asarray(t.to_dense())
    np.testing.assert_allclose(np.asarray(S.sin(t).to_dense()),
                               np.sin(dense) * (dense != 0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(S.abs(t).to_dense()),
                               np.abs(dense), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(S.pow(t, 2).to_dense()),
                               dense ** 2 * (dense != 0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(S.transpose(t, [1, 0]).to_dense()), dense.T)
    np.testing.assert_allclose(
        np.asarray(S.subtract(t, t).to_dense()), np.zeros_like(dense))
    np.testing.assert_allclose(np.asarray(S.divide(t, t).to_dense()),
                               (dense != 0).astype(np.float32))
    assert S.is_same_shape(t, t)
    assert S.reshape(t, (3, 2)).shape == (3, 2)
    assert S.cast(t, value_dtype=jnp.float16).dtype == jnp.float16
    v = S.mv(t, jnp.ones(3))
    np.testing.assert_allclose(np.asarray(v), dense @ np.ones(3), rtol=1e-6)
    am = S.addmm(jnp.ones((2, 2)), t, jnp.ones((3, 2)), beta=2.0, alpha=1.0)
    np.testing.assert_allclose(np.asarray(am), 2.0 + dense @ np.ones((3, 2)),
                               rtol=1e-6)


def test_hfft_family_inverse_pair():
    import paddle_tpu.fft as fft

    y = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)))
    np.testing.assert_allclose(
        np.asarray(fft.hfft2(fft.ihfft2(y), s=(4, 8))), np.asarray(y),
        atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(fft.hfftn(fft.ihfftn(y), s=(4, 8))), np.asarray(y),
        atol=1e-6)
    # degenerate single axis == jnp's hfft
    x = jnp.asarray(np.random.default_rng(1).normal(size=(3, 5))
                    + 1j * np.random.default_rng(2).normal(size=(3, 5)))
    np.testing.assert_allclose(np.asarray(fft.hfftn(x, axes=(-1,))),
                               np.asarray(jnp.fft.hfft(x)), rtol=1e-5)


def test_incubate_ops():
    import paddle_tpu.incubate as inc

    data = jnp.asarray([[1.0, 2], [3, 4], [5, 6]])
    seg = jnp.asarray([0, 0, 1])
    np.testing.assert_allclose(np.asarray(inc.segment_sum(data, seg)),
                               [[4, 6], [5, 6]])
    np.testing.assert_allclose(np.asarray(inc.segment_mean(data, seg)),
                               [[2, 3], [5, 6]])
    np.testing.assert_allclose(np.asarray(inc.segment_max(data, seg)),
                               [[3, 4], [5, 6]])
    np.testing.assert_allclose(np.asarray(inc.segment_min(data, seg)),
                               [[1, 2], [5, 6]])
    assert float(inc.identity_loss(data, "mean")) == float(jnp.mean(data))
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 2, 4, 4)),
                    jnp.float32)
    m = jnp.where(jnp.arange(4) < 3, 0.0, -1e9)[None, None, None, :]
    np.testing.assert_allclose(
        np.asarray(inc.softmax_mask_fuse(x, m)),
        np.asarray(jax.nn.softmax(x + m, -1)), rtol=1e-6)
    tri = inc.softmax_mask_fuse_upper_triangle(x)
    assert np.allclose(np.asarray(tri)[..., 0, 1:], 0)  # causal row
    # graph wrappers ride the geometric engine
    row = np.asarray([1, 2, 0, 2, 0, 1], np.int64)
    colptr = np.asarray([0, 2, 4, 6], np.int64)
    nbr, cnt = inc.graph_sample_neighbors(row, colptr,
                                          np.asarray([0, 1], np.int64),
                                          sample_size=2)
    assert np.asarray(cnt).tolist() == [2, 2]


def test_jit_translator_controls(tmp_path):
    from paddle_tpu import jit as pjit
    import paddle_tpu.nn as nn

    inst = pjit.ProgramTranslator.get_instance()
    assert inst is pjit.ProgramTranslator.get_instance()
    calls = []

    def f(x):
        calls.append(1)  # side effect: traced ONCE under jit, every call eagerly
        return x + 1

    g = pjit.to_static(f)
    assert float(g(jnp.zeros(()))) == 1.0
    float(g(jnp.zeros(())))
    compiled_calls = len(calls)  # trace-time only
    inst.enable(False)
    try:
        # the switch is consulted at CALL time: the SAME wrapper now runs
        # the original python eagerly (side effect fires per call)
        float(g(jnp.zeros(())))
        float(g(jnp.zeros(())))
        assert len(calls) == compiled_calls + 2, calls
    finally:
        inst.enable(True)
    float(g(jnp.zeros(())))
    assert len(calls) == compiled_calls + 2  # back to the compiled path
    pjit.set_code_level(1)
    pjit.set_code_level(0)
    pjit.set_verbosity(0)

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    x = jnp.ones((2, 4), jnp.float32)
    out, traced = pjit.TracedLayer.trace(net, [x])
    assert out.shape == (2, 2)
    np.testing.assert_allclose(np.asarray(traced(x)), np.asarray(out),
                               rtol=1e-6)
    traced.save_inference_model(str(tmp_path / "tl"))
    loaded = pjit.load(str(tmp_path / "tl"))
    np.testing.assert_allclose(np.asarray(loaded(x)), np.asarray(out),
                               rtol=1e-5)


def test_profiler_protobuf_roundtrip(tmp_path):
    import time

    import paddle_tpu.profiler as profiler

    prof = profiler.Profiler(
        on_trace_ready=profiler.export_protobuf(str(tmp_path)))
    prof.start()
    with profiler.RecordEvent("unit_span"):
        time.sleep(0.01)
    prof.stop()
    spans = profiler.load_profiler_result(prof.last_protobuf_path)
    names = [s["name"] for s in spans]
    assert "unit_span" in names
    assert profiler.SortedKeys.CPUTotal.value == 0
    assert profiler.SummaryView.KernelView.name == "KernelView"


def test_distribution_independent_entropy():
    from paddle_tpu.distribution import Independent, Normal

    base = Normal(jnp.zeros((3, 4)), jnp.ones((3, 4)))
    ind = Independent(base, 1)
    assert ind.batch_shape == (3,) and ind.event_shape == (4,)
    np.testing.assert_allclose(
        np.asarray(ind.log_prob(jnp.zeros((3, 4)))),
        np.asarray(base.log_prob(jnp.zeros((3, 4))).sum(-1)), rtol=1e-6)
    with pytest.raises(ValueError):
        Independent(base, 5)


def test_wmt_dataset(tmp_path):
    from paddle_tpu.text import WMT14, Conll05st

    p = tmp_path / "pairs.tsv"
    p.write_text("1 2 3\t4 5\nhello world\tbonjour monde\n")
    ds = WMT14(data_file=str(p))
    assert len(ds) == 2
    src, trg = ds[0]
    assert src.tolist() == [1, 2, 3] and trg.tolist() == [4, 5]
    src2, trg2 = ds[1]
    assert src2.shape == (2,) and trg2.shape == (2,)
    assert Conll05st is not None
