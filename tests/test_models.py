"""Model zoo tests: GPT forward/loss/train-step, ResNet forward/train,
and the hybrid-parallel dryrun on the 8-device CPU mesh."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu
import paddle_tpu as pt
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_loss_fn, gpt_tiny
from paddle_tpu.models.resnet import resnet18, resnet50
from paddle_tpu.framework.jit import TrainStep
from paddle_tpu.optimizer import AdamW, Momentum


def _ids(shape, vocab):
    return np.asarray(np.random.default_rng(0).integers(0, vocab, shape), np.int32)


def test_gpt_forward_shapes():
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = _ids((2, 16), cfg.vocab_size)
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = model.loss(logits, ids)
    assert np.isfinite(float(loss))


def test_gpt_untied_head():
    cfg = gpt_tiny(tie_word_embeddings=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    logits = model(_ids((1, 8), cfg.vocab_size))
    assert logits.shape == (1, 8, cfg.vocab_size)


def test_gpt_train_loss_decreases():
    cfg = gpt_tiny(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                   max_position_embeddings=32,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    step = TrainStep(model, AdamW(learning_rate=1e-3),
                     loss_fn=gpt_loss_fn(model))
    ids = _ids((4, 16), cfg.vocab_size)
    losses = [float(step((ids, ids))) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_gpt_recompute_matches():
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle_tpu.seed(7)
    m1 = GPTForCausalLM(cfg)
    ids = _ids((2, 16), cfg.vocab_size)
    m1.eval()
    base = np.asarray(m1(ids))
    m1.cfg.use_recompute = True
    m1.gpt.h.cfg.use_recompute = True
    rec = np.asarray(m1(ids))
    np.testing.assert_allclose(base, rec, rtol=1e-5, atol=1e-5)


def test_resnet18_forward():
    model = resnet18(num_classes=10)
    model.eval()
    x = np.random.default_rng(0).standard_normal((2, 3, 32, 32)).astype(np.float32)
    out = model(x)
    assert out.shape == (2, 10)


@pytest.mark.slow   # ~19s compile on the CI box; resnet18 covers tier-1
def test_resnet50_train_step():
    model = resnet50(num_classes=4)
    import paddle_tpu.nn.functional as F

    def loss_fn(out, batch):
        return F.cross_entropy(out, batch[1])

    step = TrainStep(model, Momentum(learning_rate=0.01), loss_fn=loss_fn)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)
    y = np.asarray(rng.integers(0, 4, (2,)), np.int64)
    l0 = float(step((x, y)))
    l1 = float(step((x, y)))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_graft_entry_single_chip():
    import importlib.util
    import jax

    spec = importlib.util.spec_from_file_location("__graft_entry__",
                                                  "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.slow   # ~15s 8-device entry compile (tier-1 report)
def test_graft_entry_multichip():
    import importlib.util

    spec = importlib.util.spec_from_file_location("__graft_entry__",
                                                  "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


# ------------------------------------------------------- BERT (round 3)
import jax
import jax.numpy as jnp


def test_bert_model_shapes_and_padding_mask():
    from paddle_tpu.models.bert import BertModel, bert_tiny

    paddle_tpu.seed(0)
    cfg = bert_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = BertModel(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    ids = rng.integers(1, cfg.vocab_size, (2, 16))
    ids[1, 8:] = 0  # pad tail of row 1
    seq, pooled = model(jnp.asarray(ids))
    assert seq.shape == (2, 16, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)
    # padding must not influence non-pad positions: changing pad content
    # leaves row-1 valid outputs identical
    ids2 = ids.copy()
    ids2[1, 8:] = 7
    mask = (ids != 0).astype(np.float32)
    seq_a, _ = model(jnp.asarray(ids), attention_mask=jnp.asarray(mask))
    seq_b, _ = model(jnp.asarray(ids2), attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(seq_a[1, :8]),
                               np.asarray(seq_b[1, :8]), rtol=1e-5,
                               atol=1e-5)


def test_bert_finetune_trains():
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.bert import (BertForSequenceClassification,
                                        bert_tiny)
    from paddle_tpu.optimizer import AdamW

    paddle_tpu.seed(1)
    cfg = bert_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = BertForSequenceClassification(cfg, num_classes=2)
    rng = np.random.default_rng(0)
    ids = rng.integers(1, cfg.vocab_size, (8, 12))
    labels = (ids.sum(1) % 2).astype(np.int64)
    step = TrainStep(model, AdamW(learning_rate=5e-4), loss_fn=None,
                     inputs_fn=lambda b: (b[0], None, None, b[1]))
    losses = [float(np.asarray(step((ids, labels)))) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_bert_pretraining_masked_lm():
    """MLM gathers only masked positions (no [B, L, vocab] logits) and the
    loss ignores -1 padded positions; tied decoder follows the embedding."""
    from paddle_tpu.models.bert import BertForPretraining, bert_tiny

    paddle_tpu.seed(2)
    cfg = bert_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = BertForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(3)
    ids = rng.integers(1, cfg.vocab_size, (2, 16))
    pos = np.asarray([[1, 5, -1], [2, 7, 9]], np.int64)
    lbl = np.asarray([[11, 22, -1], [33, 44, 55]], np.int64)
    nsp = np.asarray([0, 1], np.int64)
    loss = model(jnp.asarray(ids), jnp.asarray(pos), jnp.asarray(lbl),
                 jnp.asarray(nsp))
    assert np.isfinite(float(loss))
    # padded mask slot is ignored: altering its label changes nothing
    lbl2 = lbl.copy(); lbl2[0, 2] = 99
    loss2 = model(jnp.asarray(ids), jnp.asarray(pos), jnp.asarray(lbl2),
                  jnp.asarray(nsp))
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)
    # grads flow into the tied word embedding through the decoder
    from paddle_tpu.nn import functional_call, param_state

    params = param_state(model)

    def f(p):
        out, _ = functional_call(model, p, {}, jnp.asarray(ids),
                                 jnp.asarray(pos), jnp.asarray(lbl),
                                 jnp.asarray(nsp))
        return out

    g = jax.grad(f)(params)
    key = [k for k in g if "word_embeddings" in k][0]
    assert float(jnp.abs(g[key]).sum()) > 0


@pytest.mark.slow   # ~15s backbone+loss+nms compile (tier-1 report)
def test_yolov3_detector_end_to_end():
    """The PP-YOLOE-class pipeline: conv backbone -> 3-scale heads ->
    vectorized yolo_loss training signal -> yolo_box + matrix_nms
    inference."""
    from paddle_tpu.models.yolo import YOLOv3

    paddle_tpu.seed(0)
    model = YOLOv3(num_classes=4, width=8)
    model.eval()
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.normal(size=(2, 3, 64, 64)).astype(np.float32))
    heads = model(imgs)
    assert [h.shape[2] for h in heads] == [2, 4, 8]  # strides 32/16/8
    assert heads[0].shape[1] == 3 * (5 + 4)

    gt = np.zeros((2, 3, 4), np.float32)
    gt[:, 0] = [0.5, 0.5, 0.4, 0.4]
    lbl = np.zeros((2, 3), np.int64)
    loss0 = float(model.loss(imgs, jnp.asarray(gt), jnp.asarray(lbl)))
    assert np.isfinite(loss0)

    # a few grad steps on the loss reduce it (jit-compiled whole pipeline)
    from paddle_tpu.nn import functional_call, param_state, buffer_state
    from paddle_tpu.nn.layer import Layer

    class _Wrap(Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, imgs, gt, lbl):
            return self.m.loss(imgs, gt, lbl)

    wrap = _Wrap(model)
    wparams = param_state(wrap)
    wbufs = buffer_state(wrap)

    @jax.jit
    def wstep(p, b):
        def f(p):
            l, nb = functional_call(wrap, p, b, imgs, jnp.asarray(gt),
                                    jnp.asarray(lbl))
            return l, nb
        (l, nb), g = jax.value_and_grad(f, has_aux=True)(p)
        return l, jax.tree.map(lambda w, gg: w - 1e-3 * gg, p, g), nb

    losses = []
    for _ in range(8):
        l, wparams, wbufs = wstep(wparams, wbufs)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses

    # inference path: decode + matrix NMS produce [R, 6] rows
    dets, num = model.predict(imgs, [[64, 64], [64, 64]],
                              conf_thresh=0.05, keep_top_k=10)
    dets = np.asarray(dets)
    assert dets.ndim == 2 and dets.shape[1] == 6
    assert len(np.asarray(num)) == 2


# ------------------------------------------------------------ llama
def test_llama_forward_shapes_and_gqa():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(0)
    cfg = llama_tiny()  # num_heads=4, num_kv_heads=2 -> GQA path
    assert cfg.num_kv_heads == 2
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    logits = model(jnp.asarray(ids, jnp.int32))
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_rope_properties():
    from paddle_tpu.models.llama import _rope_tables, apply_rotary

    cos, sin = _rope_tables(16, 64, 10000.0)
    q = jnp.asarray(np.random.default_rng(1).normal(size=(1, 8, 2, 16)),
                    jnp.float32)
    k = q + 0.0
    qr, kr = apply_rotary(q, k, cos, sin)
    # rotation preserves per-head norms
    np.testing.assert_allclose(np.linalg.norm(np.asarray(q), axis=-1),
                               np.linalg.norm(np.asarray(qr), axis=-1),
                               rtol=1e-5)
    # relative-position property: dot(q_i, k_j) depends only on i - j
    qr2, kr2 = apply_rotary(q, k, cos, sin, position_offset=7)
    d1 = np.einsum("blhd,bmhd->bhlm", np.asarray(qr), np.asarray(kr))
    d2 = np.einsum("blhd,bmhd->bhlm", np.asarray(qr2), np.asarray(kr2))
    np.testing.assert_allclose(d1, d2, rtol=1e-4, atol=1e-4)


def test_llama_train_loss_decreases():
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.optimizer import AdamW

    pt.seed(1)
    cfg = llama_tiny(vocab_size=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    step = TrainStep(model, AdamW(learning_rate=1e-3), loss_fn=None)
    ids = np.random.default_rng(1).integers(0, 128, (4, 32)).astype(np.int32)
    losses = [float(np.asarray(step((ids, ids)))) for _ in range(12)]
    assert losses[-1] < losses[0], losses


def test_llama_chunked_loss_matches_full():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny

    pt.seed(2)
    cfg = llama_tiny(vocab_size=128, use_flash_attention=False)
    full = LlamaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, 128, (2, 24)), jnp.int32)
    ref = float(full(ids, labels=ids))
    cfg2 = llama_tiny(vocab_size=128, use_flash_attention=False,
                      loss_chunk=8)
    chunked = LlamaForCausalLM(cfg2)
    chunked.set_state_dict(full.state_dict())
    np.testing.assert_allclose(float(chunked(ids, labels=ids)), ref,
                               rtol=2e-5)


def test_llama_zero3_sharded_step():
    """The BASELINE row: llama-family pretrain under sharding stage 3
    (ZeRO-3) on the virtual mesh."""
    from paddle_tpu.distributed.mesh import init_mesh, mesh_scope, set_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.models.llama import (LlamaForCausalLM, llama_loss_fn,
                                         llama_tiny)
    from paddle_tpu.optimizer import AdamW

    m = init_mesh(sdp=8)
    with mesh_scope(m):
        pt.seed(3)
        cfg = llama_tiny(vocab_size=128, use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        step = DistributedTrainStep(
            model, AdamW(learning_rate=1e-3), loss_fn=llama_loss_fn(model),
            mesh=m, batch_axes=("sdp",), sharding_stage=3)
        ids = np.random.default_rng(3).integers(0, 128, (8, 16)).astype(
            np.int32)
        l0 = float(np.asarray(step((ids, ids))))
        l1 = float(np.asarray(step((ids, ids))))
        assert np.isfinite(l0) and l1 < l0
    set_mesh(None)


# ------------------------------------------------------------ ernie
def test_ernie_task_embedding_changes_output():
    from paddle_tpu.models.ernie import ErnieModel, ernie_tiny

    pt.seed(4)
    model = ErnieModel(ernie_tiny())
    model.eval()
    ids = jnp.asarray(
        np.random.default_rng(4).integers(1, 1000, (2, 12)), jnp.int32)
    seq0, _ = model(ids, task_type_ids=jnp.zeros_like(ids))
    seq1, _ = model(ids, task_type_ids=jnp.ones_like(ids))
    assert not np.allclose(np.asarray(seq0), np.asarray(seq1))
    assert np.isfinite(np.asarray(seq0)).all()


def test_ernie_finetune_trains():
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.ernie import (ErnieForSequenceClassification,
                                         ernie_tiny)
    from paddle_tpu.optimizer import AdamW

    pt.seed(5)
    model = ErnieForSequenceClassification(ernie_tiny(), num_classes=2)
    rng = np.random.default_rng(5)
    ids = rng.integers(1, 1000, (8, 16)).astype(np.int32)
    labels = (ids.sum(1) % 2).astype(np.int64)  # learnable from tokens
    import paddle_tpu.nn.functional as F

    step = TrainStep(model, AdamW(learning_rate=5e-4),
                     loss_fn=lambda out, b: F.cross_entropy(out, b[1]),
                     inputs_fn=lambda b: (b[0],))
    losses = [float(np.asarray(step((ids, labels)))) for _ in range(15)]
    assert losses[-1] < losses[0], losses


def test_ernie_pretraining_loss_runs():
    from paddle_tpu.models.ernie import ErnieForPretraining, ernie_tiny

    pt.seed(6)
    model = ErnieForPretraining(ernie_tiny())
    rng = np.random.default_rng(6)
    ids = jnp.asarray(rng.integers(1, 1000, (2, 16)), jnp.int32)
    pos = jnp.asarray([[1, 5, -1], [2, 7, 9]], jnp.int32)
    lbl = jnp.asarray(rng.integers(1, 1000, (2, 3)), jnp.int32)
    nsp = jnp.asarray([0, 1], jnp.int32)
    loss = model(ids, pos, lbl, nsp,
                 task_type_ids=jnp.zeros_like(ids))
    assert np.isfinite(float(loss))
