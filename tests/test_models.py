"""Model zoo tests: GPT forward/loss/train-step, ResNet forward/train,
and the hybrid-parallel dryrun on the 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_loss_fn, gpt_tiny
from paddle_tpu.models.resnet import resnet18, resnet50
from paddle_tpu.framework.jit import TrainStep
from paddle_tpu.optimizer import AdamW, Momentum


def _ids(shape, vocab):
    return np.asarray(np.random.default_rng(0).integers(0, vocab, shape), np.int32)


def test_gpt_forward_shapes():
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = _ids((2, 16), cfg.vocab_size)
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    loss = model.loss(logits, ids)
    assert np.isfinite(float(loss))


def test_gpt_untied_head():
    cfg = gpt_tiny(tie_word_embeddings=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    logits = model(_ids((1, 8), cfg.vocab_size))
    assert logits.shape == (1, 8, cfg.vocab_size)


def test_gpt_train_loss_decreases():
    cfg = gpt_tiny(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                   max_position_embeddings=32,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    step = TrainStep(model, AdamW(learning_rate=1e-3),
                     loss_fn=gpt_loss_fn(model))
    ids = _ids((4, 16), cfg.vocab_size)
    losses = [float(step((ids, ids))) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_gpt_recompute_matches():
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle_tpu.seed(7)
    m1 = GPTForCausalLM(cfg)
    ids = _ids((2, 16), cfg.vocab_size)
    m1.eval()
    base = np.asarray(m1(ids))
    m1.cfg.use_recompute = True
    m1.gpt.h.cfg.use_recompute = True
    rec = np.asarray(m1(ids))
    np.testing.assert_allclose(base, rec, rtol=1e-5, atol=1e-5)


def test_resnet18_forward():
    model = resnet18(num_classes=10)
    model.eval()
    x = np.random.default_rng(0).standard_normal((2, 3, 32, 32)).astype(np.float32)
    out = model(x)
    assert out.shape == (2, 10)


def test_resnet50_train_step():
    model = resnet50(num_classes=4)
    import paddle_tpu.nn.functional as F

    def loss_fn(out, batch):
        return F.cross_entropy(out, batch[1])

    step = TrainStep(model, Momentum(learning_rate=0.01), loss_fn=loss_fn)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)
    y = np.asarray(rng.integers(0, 4, (2,)), np.int64)
    l0 = float(step((x, y)))
    l1 = float(step((x, y)))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_graft_entry_single_chip():
    import importlib.util
    import jax

    spec = importlib.util.spec_from_file_location("__graft_entry__",
                                                  "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(out)))


def test_graft_entry_multichip():
    import importlib.util

    spec = importlib.util.spec_from_file_location("__graft_entry__",
                                                  "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
