"""Audio + text package tests (reference ``python/paddle/audio`` and
``python/paddle/text`` coverage: functional parity vs scipy/librosa-style
references, feature layer shapes/jit, WAV IO round-trip, viterbi vs brute
force, dataset parsing from local archives)."""
import io
import itertools
import os
import tarfile
import wave

import numpy as np
import pytest
import scipy.signal

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import audio, text

RNG = np.random.default_rng(11)


# ------------------------------------------------------------- functional
def test_mel_hz_roundtrip_both_flavors():
    f = np.asarray([0.0, 440.0, 1000.0, 4000.0, 11025.0], np.float32)
    for htk in (False, True):
        mel = audio.functional.hz_to_mel(f, htk=htk)
        back = np.asarray(audio.functional.mel_to_hz(mel, htk=htk))
        np.testing.assert_allclose(back, f, rtol=1e-4, atol=1e-2)


def test_fbank_matrix_properties():
    fb = np.asarray(audio.functional.compute_fbank_matrix(
        sr=16000, n_fft=512, n_mels=40))
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    # every filter has support, triangles overlap neighbors
    assert (fb.sum(axis=1) > 0).all()


def test_get_window_matches_scipy():
    for name in ["hann", "hamming", "blackman", "nuttall", "triang",
                 "bohman", "cosine"]:
        for fftbins in (True, False):
            got = np.asarray(audio.functional.get_window(name, 64,
                                                         fftbins=fftbins))
            ref = scipy.signal.get_window(name, 64, fftbins=fftbins)
            np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7,
                                       err_msg=f"{name} fftbins={fftbins}")
    got = np.asarray(audio.functional.get_window(("gaussian", 7), 32))
    ref = scipy.signal.get_window(("gaussian", 7), 32)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)
    with pytest.raises(ValueError, match="needs a parameter"):
        audio.functional.get_window("gaussian", 32)


def test_power_to_db_matches_formula():
    s = np.abs(RNG.normal(size=(8, 8))).astype(np.float32) ** 2
    db = np.asarray(audio.functional.power_to_db(s, top_db=None))
    np.testing.assert_allclose(db, 10 * np.log10(np.maximum(s, 1e-10)),
                               rtol=1e-5)
    clamped = np.asarray(audio.functional.power_to_db(s, top_db=20.0))
    assert clamped.min() >= clamped.max() - 20.0 - 1e-5


def test_create_dct_orthonormal():
    d = np.asarray(audio.functional.create_dct(13, 40, norm="ortho"))
    assert d.shape == (40, 13)
    np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)


# ---------------------------------------------------------------- features
def test_feature_layers_shapes_and_jit():
    wav = RNG.normal(size=16000).astype(np.float32)
    spec = audio.features.Spectrogram(n_fft=512, hop_length=160)
    s = np.asarray(spec(wav))
    assert s.shape[0] == 257 and (s >= 0).all()
    mel = audio.features.MelSpectrogram(sr=16000, n_fft=512, hop_length=160,
                                        n_mels=64)
    m = np.asarray(mel(wav))
    assert m.shape[0] == 64 and m.shape[1] == s.shape[1]
    mfcc = audio.features.MFCC(sr=16000, n_mfcc=20, n_fft=512,
                               hop_length=160, n_mels=64)
    c = np.asarray(mfcc(wav))
    assert c.shape[0] == 20
    # whole pipeline jit-compiles
    jc = np.asarray(jax.jit(lambda w: mfcc(w))(wav))
    np.testing.assert_allclose(jc, c, rtol=1e-4, atol=1e-4)


def test_mel_layer_batched():
    wavs = RNG.normal(size=(3, 8000)).astype(np.float32)
    mel = audio.features.MelSpectrogram(sr=16000, n_fft=256, n_mels=32)
    out = np.asarray(mel(wavs))
    assert out.shape[0] == 3 and out.shape[1] == 32


# -------------------------------------------------------------------- IO
def test_wav_save_load_roundtrip(tmp_path):
    path = str(tmp_path / "t.wav")
    wav = (RNG.normal(size=(1, 4000)) * 0.3).astype(np.float32)
    audio.save(path, wav, sample_rate=16000)
    meta = audio.info(path)
    assert meta.sample_rate == 16000 and meta.num_samples == 4000
    assert meta.num_channels == 1 and meta.bits_per_sample == 16
    loaded, sr = audio.load(path)
    assert sr == 16000 and loaded.shape == (1, 4000)
    # save clips to [-1, 1] (16-bit PCM range); beyond that it's pure
    # quantization error
    np.testing.assert_allclose(loaded, np.clip(wav, -1.0, 1.0),
                               atol=1.0 / 32767)
    # offset/num_frames
    part, _ = audio.load(path, frame_offset=100, num_frames=50)
    np.testing.assert_allclose(part, loaded[:, 100:150], atol=1e-7)


def test_audio_dataset_from_wavs(tmp_path):
    files, labels = [], []
    for i in range(4):
        p = str(tmp_path / f"{i}.wav")
        audio.save(p, RNG.normal(size=(1, 2000)).astype(np.float32) * 0.1,
                   sample_rate=8000)
        files.append(p)
        labels.append(i % 2)
    ds = audio.datasets.AudioClassificationDataset(
        files, labels, feat_type="melspectrogram", duration=0.25,
        sr=8000, n_fft=256, n_mels=16)
    feat, label = ds[1]
    assert feat.shape[0] == 16 and label == 1
    assert len(ds) == 4
    with pytest.raises(RuntimeError, match="data_dir"):
        audio.datasets.ESC50(data_dir=str(tmp_path / "missing"))


# ---------------------------------------------------------------- viterbi
def _brute_force_viterbi(pot, trans, length, include_bos_eos):
    N = pot.shape[-1]
    best_score, best_path = -np.inf, None
    for path in itertools.product(range(N), repeat=length):
        score = pot[0, path[0]]
        if include_bos_eos:
            score += trans[-1, path[0]]
        for t in range(1, length):
            score += trans[path[t - 1], path[t]] + pot[t, path[t]]
        if include_bos_eos:
            score += trans[path[-1], -2]
        if score > best_score:
            best_score, best_path = score, path
    return best_score, list(best_path)


@pytest.mark.parametrize("include", [False, True])
def test_viterbi_matches_brute_force(include):
    B, T, N = 3, 5, 4
    pot = RNG.normal(size=(B, T, N)).astype(np.float32)
    trans = RNG.normal(size=(N, N)).astype(np.float32)
    lengths = np.asarray([5, 3, 4])
    scores, paths = text.viterbi_decode(pot, trans, lengths, include)
    for b in range(B):
        bs, bp = _brute_force_viterbi(pot[b], trans, lengths[b], include)
        assert abs(float(scores[b]) - bs) < 1e-4, b
        assert list(np.asarray(paths[b])[:lengths[b]]) == bp, b


def test_viterbi_decoder_layer_jits():
    B, T, N = 2, 6, 5
    pot = jnp.asarray(RNG.normal(size=(B, T, N)).astype(np.float32))
    trans = RNG.normal(size=(N, N)).astype(np.float32)
    dec = text.ViterbiDecoder(trans, include_bos_eos_tag=False)
    lengths = jnp.asarray([6, 6])
    s1, p1 = dec(pot, lengths)
    s2, p2 = jax.jit(lambda q, l: dec(q, l))(pot, lengths)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(p1),
                                  np.asarray(p2)[:, :p1.shape[1]])


# ---------------------------------------------------------------- datasets
def _make_imdb_tar(tmp_path):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for split in ("train", "test"):
            for sent, label in [("good great fine", "pos"),
                                ("bad awful poor", "neg")]:
                for i in range(3):
                    data = f"{sent} sample {i}".encode()
                    info = tarfile.TarInfo(f"aclImdb/{split}/{label}/{i}.txt")
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
    path = str(tmp_path / "aclImdb.tgz")
    open(path, "wb").write(buf.getvalue())
    return path


def test_imdb_dataset(tmp_path):
    path = _make_imdb_tar(tmp_path)
    ds = text.Imdb(data_file=path, mode="train", cutoff=1)
    assert len(ds) == 6
    ids, label = ds[0]
    assert ids.dtype == np.int64 and label in (0, 1)
    assert "<unk>" in ds.word_idx and "sample" in ds.word_idx


def test_uci_housing(tmp_path):
    data = RNG.normal(size=(50, 14)).astype(np.float64)
    path = str(tmp_path / "housing.data")
    np.savetxt(path, data)
    train = text.UCIHousing(data_file=path, mode="train")
    test = text.UCIHousing(data_file=path, mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    with pytest.raises(RuntimeError, match="data_file"):
        text.UCIHousing(data_file=None)


def test_imikolov_ngram(tmp_path):
    buf = io.BytesIO()
    lines = "\n".join("the quick brown fox jumps" for _ in range(60))
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name in ("ptb.train.txt", "ptb.valid.txt"):
            data = lines.encode()
            info = tarfile.TarInfo(f"simple-examples/data/{name}")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    path = str(tmp_path / "ptb.tgz")
    open(path, "wb").write(buf.getvalue())
    ds = text.Imikolov(data_file=path, data_type="NGRAM", window_size=3,
                       mode="train", min_word_freq=50)
    assert len(ds) > 0
    gram = ds[0]
    assert gram.shape == (3,) and gram.dtype == np.int64
