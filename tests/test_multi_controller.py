"""True multi-process (multi-controller) execution coverage (VERDICT r4
missing #2): two OS processes bootstrap one global 8-device mesh through
``init_parallel_env`` -> ``jax.distributed.initialize`` (the path a real
multi-host TPU job takes), discover each other through the elastic KV
store, train DP, dp x mp, and ZeRO-2 (sdp-sharded optimizer state +
grad reduce-scatter) ``DistributedTrainStep``s, write a per-process
sharded checkpoint, reload it sharded, and must match the
single-process 8-device run loss-for-loss.

Reference discipline:
``python/paddle/fluid/tests/unittests/test_dist_base.py:901`` (subprocess
cluster + loss-parity assertion) and
``paddle/fluid/distributed/collective/ProcessGroup.h:52``.
"""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import json, os, time
import numpy as np

rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.launch import KVClient
from paddle_tpu.distributed.parallel.mp_layers import (ColumnParallelLinear,
                                                       RowParallelLinear)
from paddle_tpu.distributed.shard import DistributedTrainStep
from paddle_tpu.optimizer import AdamW

if nproc > 1:
    # elastic KV rendezvous the way the launcher does it: every rank
    # leases its presence, waits for the full world, and reads the
    # coordinator address from rank 0's entry before touching
    # jax.distributed
    kv = KVClient(os.environ["TEST_KV"])
    kv.put(f"mc/{rank}", os.environ["PADDLE_MASTER"], ttl=120)
    deadline = time.time() + 90
    while len(kv.list("mc/")) < nproc:
        assert time.time() < deadline, "KV rendezvous timeout"
        time.sleep(0.05)
    assert kv.get("mc/0") == os.environ["PADDLE_MASTER"]

results = {}
for mode in ("dp", "dpmp", "zero2"):
    strategy = DistributedStrategy()
    strategy.hybrid_configs = (
        {"dp_degree": 4, "mp_degree": 2} if mode == "dpmp"
        else {"sharding_degree": 8} if mode == "zero2"
        else {"dp_degree": 8})
    fleet.init(strategy=strategy)
    assert dist_env.get_world_size() == nproc, dist_env.get_world_size()
    assert dist_env.get_rank() == rank
    assert dist_env.device_count() == 8, "global mesh must span 8 devices"

    def build():
        pt.seed(0)
        if mode == "dpmp":
            return nn.Sequential(
                ColumnParallelLinear(16, 32, gather_output=False),
                nn.ReLU(),
                RowParallelLinear(32, 8, input_is_parallel=True))
        return nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                             nn.Linear(32, 8))

    loss_fn = lambda out, b: F.mse_loss(out, b[1])
    stage = 2 if mode == "zero2" else 0
    step = DistributedTrainStep(build(), AdamW(learning_rate=5e-3),
                                loss_fn=loss_fn, sharding_stage=stage)
    rng = np.random.default_rng(0)
    # every process feeds the same GLOBAL batch; the dp sharding hands
    # each device its slice (the multi-controller data contract)
    x = rng.standard_normal((8, 16)).astype(np.float32)
    y = rng.standard_normal((8, 8)).astype(np.float32)
    losses = [float(np.asarray(step((x, y)))) for _ in range(6)]

    # per-process sharded save -> barrier -> sharded load -> resume
    d = os.environ["TEST_CKPT_DIR"] + "_" + mode
    ckpt.save_state(step.state_dict(), d)
    dist_env.barrier()
    step2 = DistributedTrainStep(build(), AdamW(learning_rate=5e-3),
                                 loss_fn=loss_fn, sharding_stage=stage)
    restored = ckpt.load_state(d, shardings=step2.state_shardings(),
                               template=step2.state_dict())
    step2.set_state_dict(restored)
    resumed = [float(np.asarray(step2((x, y)))) for _ in range(2)]
    cont = [float(np.asarray(step((x, y)))) for _ in range(2)]
    results[mode] = {"losses": losses, "resumed": resumed, "cont": cont}

out = {"rank": rank, "world": dist_env.get_world_size(), **results}
with open(os.environ["TEST_OUT"] + f".{rank}", "w") as f:
    json.dump(out, f)
print("WORKER_DONE", rank, flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(rank, nproc, coord_port, kv_addr, ckpt_dir, out_path,
                local_devices):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={local_devices}",
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nproc),
        "PADDLE_MASTER": f"127.0.0.1:{coord_port}",
        "TEST_KV": kv_addr,
        "TEST_CKPT_DIR": ckpt_dir,
        "TEST_OUT": out_path,
        "PYTHONPATH": REPO,
    })
    return env


@pytest.mark.slow   # ~13s two-subprocess mesh spin-up (tier-1 report)
def test_two_process_mesh_loss_parity_with_single_process(tmp_path):
    from paddle_tpu.distributed.launch import KVServer

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    coord_port = _free_port()

    with KVServer(0, host="127.0.0.1") as server:
        kv_addr = f"127.0.0.1:{server.port}"
        procs = [
            subprocess.Popen(
                [sys.executable, str(script)],
                env=_worker_env(r, 2, coord_port, kv_addr,
                                str(tmp_path / "ck2p"),
                                str(tmp_path / "out2p"), 4),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for r in range(2)]
        try:
            outs = [p.communicate(timeout=480)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        for p, o in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    # single-process 8-device reference run, same script/seed/data
    ref = subprocess.run(
        [sys.executable, str(script)],
        env=_worker_env(0, 1, _free_port(), "", str(tmp_path / "ck1p"),
                        str(tmp_path / "out1p"), 8),
        capture_output=True, text=True, timeout=480)
    assert ref.returncode == 0, f"reference failed:\n{ref.stdout[-3000:]}"

    r0 = json.loads((tmp_path / "out2p.0").read_text())
    r1 = json.loads((tmp_path / "out2p.1").read_text())
    r_ref = json.loads((tmp_path / "out1p.0").read_text())
    assert r0["world"] == 2 and r_ref["world"] == 1

    for mode in ("dp", "dpmp", "zero2"):
        # both controllers see the same loss stream (one SPMD program)
        np.testing.assert_allclose(r0[mode]["losses"], r1[mode]["losses"],
                                   rtol=1e-6)
        # the 2-process mesh matches the single-process 8-device mesh
        np.testing.assert_allclose(r0[mode]["losses"],
                                   r_ref[mode]["losses"], rtol=2e-4)
        # checkpoint resume continues exactly where the original left off
        np.testing.assert_allclose(r0[mode]["resumed"], r0[mode]["cont"],
                                   rtol=1e-5)
        np.testing.assert_allclose(r_ref[mode]["resumed"],
                                   r_ref[mode]["cont"], rtol=1e-5)
