"""paddle.distributed API long tail (r4): groups, P2P over RPC,
reduce/scatter in shard_map, group_sharded_parallel, stream module,
entry configs (reference python/paddle/distributed/__init__.py)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_groups_env_mode():
    g = dist.new_group([0, 1, 2])
    assert dist.get_group(g.id) is g
    assert g.nranks == 3 and g.get_group_rank(1) == 1
    dist.destroy_process_group(g)
    assert dist.get_group(g.id) is None
    env = dist.ParallelEnv()
    assert env.rank == 0 and env.world_size >= 1
    assert dist.ParallelMode.SHARDING_PARALLEL == 3
    assert float(np.asarray(dist.wait(jnp.ones(())))) == 1.0


def test_reduce_scatter_in_shard_map():
    from paddle_tpu.framework.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.mesh import init_mesh, set_mesh

    m = init_mesh(dp=8)

    g0 = dist.new_group(list(range(8)), axis="dp")

    def body(x):
        r = dist.reduce(x, dst=2, group=g0)      # Group objects map to axes
        s = dist.scatter(jnp.arange(16.0), src=0, group="dp")
        s2 = dist.scatter(None, [jnp.full((2,), float(i))
                                 for i in range(8)], src=0, group="dp")
        return r, s, s2

    f = shard_map(body, mesh=m, in_specs=(P("dp"),),
                  out_specs=(P("dp"), P("dp"), P("dp")))
    r, s, s2 = f(jnp.ones((8,)))
    r = np.asarray(r)
    assert r[2] == 8.0 and r[0] == 0.0  # kept only on dst
    np.testing.assert_allclose(np.asarray(s), np.arange(16.0))
    # tensor_list form: rank i gets chunk i
    np.testing.assert_allclose(np.asarray(s2),
                               np.repeat(np.arange(8.0), 2))
    # a Group without an axis mapping fails loudly in collectives
    import pytest as _pytest

    bad = dist.new_group([0, 1])
    with _pytest.raises(ValueError, match="mesh-axis"):
        f2 = shard_map(lambda x: dist.reduce(x, group=bad), mesh=m,
                       in_specs=(P("dp"),), out_specs=P("dp"))
        f2(jnp.ones((8,)))
    # alltoall_single delegates; uneven splits refused loudly
    with pytest.raises(NotImplementedError):
        dist.alltoall_single(jnp.ones((8,)), in_split_sizes=[1, 7])
    set_mesh(None)


def test_all_gather_object_single_process():
    out = []
    dist.all_gather_object(out, {"a": 1})
    assert out == [{"a": 1}]


def test_group_sharded_parallel_tags_and_trains():
    from paddle_tpu.distributed.mesh import init_mesh, mesh_scope, set_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.optimizer import AdamW
    import paddle_tpu.nn as nn

    with pytest.raises(ValueError):
        dist.group_sharded_parallel(None, AdamW(learning_rate=1e-3), "bogus")
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 8))
    opt = AdamW(learning_rate=1e-3)
    model, opt, _ = dist.group_sharded_parallel(model, opt, "p_g_os")
    assert opt._group_sharded_stage == 3
    m = init_mesh(sdp=8)
    with mesh_scope(m):
        step = DistributedTrainStep(
            model, opt, loss_fn=lambda out, b: jnp.mean((out - b[1]) ** 2),
            mesh=m, batch_axes=("sdp",))
        x = np.random.default_rng(0).normal(size=(8, 8)).astype(np.float32)
        l0 = float(np.asarray(step((x, np.tanh(x)))))
        l1 = float(np.asarray(step((x, np.tanh(x)))))
        assert np.isfinite(l0) and l1 < l0
    set_mesh(None)


def test_save_group_sharded_model(tmp_path):
    import paddle_tpu.nn as nn

    pt.seed(1)
    model = nn.Linear(4, 2)
    dist.save_group_sharded_model(model, str(tmp_path / "out"))
    state = pt.load(str(tmp_path / "out" / "model.pdparams"))
    assert "weight" in state


def test_stream_module_and_entries():
    from paddle_tpu.distributed import stream

    # stream variants accept the knobs and delegate
    from paddle_tpu.framework.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.mesh import init_mesh, set_mesh

    m = init_mesh(dp=8)
    f = shard_map(lambda x: stream.all_reduce(x, sync_op=False,
                                              use_calc_stream=True),
                  mesh=m, in_specs=(P("dp"),), out_specs=P("dp"))
    np.testing.assert_allclose(np.asarray(f(jnp.ones((8,)))), 8.0)
    set_mesh(None)

    assert dist.CountFilterEntry(5).accessor_kwargs() == \
        {"min_show_to_keep": 5.0}
    assert dist.ShowClickEntry("s", "c").accessor_kwargs() == \
        {"show_name": "s", "click_name": "c"}
    assert dist.ProbabilityEntry(0.5).accessor_kwargs() == \
        {"admit_probability": 0.5}
    with pytest.raises(NotImplementedError, match="ColumnParallelLinear"):
        dist.split(jnp.ones((2, 4)), (4, 8), "linear")


P2P_WORKER = textwrap.dedent("""
    import sys
    import numpy as np
    from paddle_tpu.distributed import rpc
    import paddle_tpu.distributed as dist

    rank = int(sys.argv[1])
    rpc.init_rpc(name=f"rank{rank}", rank=rank, world_size=2,
                 master_endpoint=sys.argv[2])
    if rank == 0:
        dist.send(np.arange(6, dtype=np.float32), dst=1, tag=7)
        got = dist.recv(src=1, tag=9)
        assert got.tolist() == [5.0], got
        objs = []
        dist.all_gather_object(objs, {"rank": 0})
        assert sorted(o["rank"] for o in objs) == [0, 1], objs
        print("P2P_OK", flush=True)
    else:
        got = dist.recv(src=0, tag=7)
        assert got.tolist() == list(range(6)), got
        reqs = dist.batch_isend_irecv([
            dist.P2POp(dist.isend, np.asarray([5.0]), 0, tag=9)])
        for r in reqs:
            r.wait()
        objs = []
        dist.all_gather_object(objs, {"rank": 1})
    rpc.shutdown()
""")


def test_p2p_over_rpc_two_processes():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    w1 = subprocess.Popen([sys.executable, "-c", P2P_WORKER, "1", ep],
                          env=env, cwd=REPO)
    try:
        w0 = subprocess.run([sys.executable, "-c", P2P_WORKER, "0", ep],
                            env=env, cwd=REPO, capture_output=True,
                            text=True, timeout=240)
        assert w0.returncode == 0, w0.stderr
        assert "P2P_OK" in w0.stdout
        assert w1.wait(timeout=60) == 0
    finally:
        if w1.poll() is None:
            w1.kill()
            w1.communicate()


def test_p2p_rpc_calls_are_deadline_bounded(monkeypatch):
    """tpu_lint R11 regression: send/all_gather_object must thread an
    explicit timeout into rpc_sync instead of riding the transport's
    120s default — a dead peer fails the caller at ITS deadline."""
    import paddle_tpu.distributed.api_compat as ac
    from paddle_tpu.distributed import rpc

    seen = []

    def fake_rpc_sync(to, fn, args=None, kwargs=None, timeout=None, **kw):
        seen.append(timeout)
        return 0

    monkeypatch.setattr(ac, "_peer_name", lambda r: "w1")
    monkeypatch.setattr(ac, "_my_rank", lambda: 0)
    monkeypatch.setattr(rpc, "rpc_sync", fake_rpc_sync)
    dist.send(np.ones(3, np.float32), dst=1, tag=1, timeout=3.5)
    assert seen == [3.5]
    dist.send(np.ones(3, np.float32), dst=1, tag=1)   # default stays finite
    assert seen[-1] is not None and seen[-1] > 0
