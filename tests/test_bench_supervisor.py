"""The bench artifact must be outage- AND timeout-proof.

Round-4 failure: the driver's outer timeout SIGKILLed bench.py inside its
own retry window before any JSON line was printed (BENCH_r04.json rc=124,
parsed=null), losing the round's perf evidence. These tests pin the fix:
a structured-failure line is printed on SIGTERM mid-retry, on budget
exhaustion, and the supervisor never orphans probe children.

Reference discipline: /root/reference/tools/ci_model_benchmark.sh (the CI
bench wrapper always leaves a parseable log).
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update(extra)
    return env


def _metric_line(stdout: str) -> dict:
    lines = [l for l in stdout.splitlines() if l.startswith('{"metric"')]
    assert lines, f"no metric JSON line in: {stdout!r}"
    return json.loads(lines[-1])


def test_budget_exhaustion_emits_structured_failure():
    """With the probe forced down and a tiny budget, the supervisor must
    exit rc=0 with a parseable tpu_unavailable record on its own."""
    out = subprocess.run(
        [sys.executable, BENCH],
        env=_env(BENCH_FORCE_PROBE_FAIL="1", BENCH_TOTAL_BUDGET_SECONDS="2",
                 BENCH_TPU_RETRY_SECONDS="2"),
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    rec = _metric_line(out.stdout)
    assert rec["error"] == "tpu_unavailable"
    assert rec["value"] == 0.0
    assert "forced probe failure" in rec["extra"]["detail"]


def test_probe_timeout_env_override_and_retry_accounting():
    """PT_BENCH_PROBE_TIMEOUT must bound each probe attempt (round r05
    burned ~20 min at the fixed 180 s cap before tpu_unavailable), and the
    failure record must account for the wall clock burned in retries."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    old = os.environ.pop("PT_BENCH_PROBE_TIMEOUT", None)
    try:
        assert bench._probe_timeout_default() == 180.0
        os.environ["PT_BENCH_PROBE_TIMEOUT"] = "7.5"
        assert bench._probe_timeout_default() == 7.5
        os.environ["PT_BENCH_PROBE_TIMEOUT"] = "not-a-number"
        assert bench._probe_timeout_default() == 180.0
    finally:
        os.environ.pop("PT_BENCH_PROBE_TIMEOUT", None)
        if old is not None:
            os.environ["PT_BENCH_PROBE_TIMEOUT"] = old

    # end-to-end: a forced-down probe with a small budget must leave the
    # retry accounting in the artifact's extra
    out = subprocess.run(
        [sys.executable, BENCH],
        env=_env(BENCH_FORCE_PROBE_FAIL="1", BENCH_TOTAL_BUDGET_SECONDS="3",
                 BENCH_TPU_RETRY_SECONDS="3", PT_BENCH_PROBE_TIMEOUT="5"),
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    rec = _metric_line(out.stdout)
    assert rec["error"] == "tpu_unavailable"
    assert rec["extra"]["probe_retry_s"] >= 0.0
    assert rec["extra"]["probe_attempts"] >= 1


def test_probe_budget_caps_total_probe_wall_clock():
    """PT_BENCH_PROBE_BUDGET must cap the TOTAL wall clock spent probing
    (round r05 burned ~20 min of per-attempt retries before
    tpu_unavailable): with a huge retry window but a tiny probe budget,
    _wait_for_backend must give up promptly, naming the budget, with the
    attempt accounting intact — and the pot is SHARED, so the post-bench
    re-probe gets nothing once it is empty. In-module (no subprocess):
    tier-1 is tight on wall clock."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_budget_test", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._check_backend = lambda timeout=None: (None, "tunnel down (stub)")
    bench._RETRY_STATS.update(probe_retry_s=0.0, probe_attempts=0)
    bench._PROBE_BUDGET["remaining"] = None
    old = os.environ.get("PT_BENCH_PROBE_BUDGET")
    os.environ["PT_BENCH_PROBE_BUDGET"] = "1"
    try:
        t0 = time.monotonic()
        backend, err = bench._wait_for_backend(time.monotonic() + 3600)
        elapsed = time.monotonic() - t0
        assert backend is None
        assert "probe budget exhausted" in err
        assert "PT_BENCH_PROBE_BUDGET" in err
        assert bench._RETRY_STATS["probe_attempts"] >= 1
        attempts = bench._RETRY_STATS["probe_attempts"]
        assert elapsed < 30, f"budget-capped probe took {elapsed:.0f}s"
        # second call (the supervisor's post-bench-failure re-probe) finds
        # the pot empty and returns WITHOUT probing again
        backend, err = bench._wait_for_backend(time.monotonic() + 3600)
        assert backend is None and "probe budget exhausted" in err
        assert bench._RETRY_STATS["probe_attempts"] == attempts
    finally:
        if old is None:
            os.environ.pop("PT_BENCH_PROBE_BUDGET", None)
        else:
            os.environ["PT_BENCH_PROBE_BUDGET"] = old


def test_sigterm_mid_retry_still_leaves_artifact():
    """SIGTERM during the retry loop (the round-4 scenario) must flush a
    killed_by_signal record naming the phase, then exit."""
    import threading

    proc = subprocess.Popen(
        [sys.executable, BENCH],
        env=_env(BENCH_FORCE_PROBE_FAIL="1",
                 BENCH_TOTAL_BUDGET_SECONDS="600",
                 BENCH_TPU_RETRY_SECONDS="600"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # wait for the supervisor's OWN retry message before killing: a fixed
    # grace flakes on a loaded host where the interpreter hasn't even
    # installed its signal handlers yet
    parked = threading.Event()
    stderr_lines = []

    def drain():
        for line in proc.stderr:
            stderr_lines.append(line)
            if "retrying in" in line:
                parked.set()

    th = threading.Thread(target=drain, daemon=True)
    th.start()
    try:
        assert parked.wait(timeout=60.0), (
            f"supervisor never reached its retry loop: {stderr_lines!r}")
        assert proc.poll() is None, "supervisor exited before the kill"
        proc.send_signal(signal.SIGTERM)
        # the drain thread owns stderr; read only stdout here (communicate
        # would race it on the same pipe)
        stdout = proc.stdout.read()
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        th.join(timeout=10)
    rec = _metric_line(stdout)
    assert rec["error"] == "killed_by_signal"
    assert "probe" in rec["extra"]["detail"]
