"""Detection-op tests vs numpy reference implementations (reference
``python/paddle/vision/ops.py`` semantics)."""
import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.vision import ops

RNG = np.random.default_rng(9)


def _np_nms(boxes, scores, thresh):
    order = np.argsort(-scores, kind="stable")
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a_j = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / max(a_i + a_j - inter, 1e-10) > thresh:
                suppressed[j] = True
    return keep


def test_nms_matches_reference_greedy():
    boxes = RNG.uniform(0, 90, (40, 2)).astype(np.float32)
    boxes = np.concatenate([boxes, boxes + RNG.uniform(5, 30, (40, 2))],
                           axis=1).astype(np.float32)
    scores = RNG.random(40).astype(np.float32)
    got = list(np.asarray(ops.nms(boxes, 0.4, scores)))
    want = _np_nms(boxes, scores, 0.4)
    assert got == want


def test_nms_categorical_and_topk():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]],
                       np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    cats = np.asarray([0, 0, 1])
    # same-category overlap suppressed; other category survives
    kept = list(np.asarray(ops.nms(boxes, 0.5, scores, category_idxs=cats,
                                   categories=[0, 1])))
    assert kept == [0, 2]
    assert list(np.asarray(ops.nms(boxes, 0.5, scores, category_idxs=cats,
                                   categories=[0, 1], top_k=1))) == [0]


def test_nms_mask_jit():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                       np.float32)
    scores = np.asarray([0.5, 0.9, 0.1], np.float32)
    keep = jax.jit(lambda b, s: ops.nms_mask(b, s, 0.5))(boxes, scores)
    np.testing.assert_array_equal(np.asarray(keep), [False, True, True])


def test_box_coder_roundtrip():
    priors = RNG.uniform(0, 50, (6, 2)).astype(np.float32)
    priors = np.concatenate([priors, priors + 10], axis=1)
    targets = RNG.uniform(0, 50, (4, 2)).astype(np.float32)
    targets = np.concatenate([targets, targets + 8], axis=1)
    var = np.asarray([0.1, 0.1, 0.2, 0.2], np.float32)
    codes = ops.box_coder(priors, var, targets, "encode_center_size")
    assert codes.shape == (4, 6, 4)
    decoded = ops.box_coder(priors, var, codes, "decode_center_size", axis=0)
    # decoding the encoding of target t against prior p returns target t
    for t in range(4):
        np.testing.assert_allclose(np.asarray(decoded[t]),
                                   np.tile(targets[t], (6, 1)), rtol=1e-4,
                                   atol=1e-3)


def test_yolo_box_shapes_and_range():
    n, na, cls, h, w = 2, 3, 5, 4, 4
    x = RNG.normal(size=(n, na * (5 + cls), h, w)).astype(np.float32)
    img = np.asarray([[128, 128], [96, 64]], np.int32)
    boxes, scores = ops.yolo_box(x, img, anchors=[10, 13, 16, 30, 33, 23],
                                 class_num=cls, conf_thresh=0.01,
                                 downsample_ratio=32)
    assert boxes.shape == (n, na * h * w, 4)
    assert scores.shape == (n, na * h * w, cls)
    b = np.asarray(boxes)
    assert (b[0, :, [0, 2]] <= 127.0 + 1e-3).all() and (b >= -1e-3).all()


def test_prior_box():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    boxes, var = ops.prior_box(feat, img, min_sizes=[16.0],
                               aspect_ratios=[1.0, 2.0], flip=True)
    assert boxes.shape[:2] == (4, 4) and boxes.shape[-1] == 4
    assert var.shape == boxes.shape
    c = np.asarray(boxes)[2, 2]
    # centered anchors around cell (2,2) center = (40, 40)/64
    centers = (c[:, :2] + c[:, 2:]) / 2
    np.testing.assert_allclose(centers, 40.0 / 64, rtol=1e-5)


def test_roi_align_constant_and_grad():
    x = np.full((1, 2, 8, 8), 7.0, np.float32)
    boxes = np.asarray([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = ops.roi_align(x, boxes, [1], output_size=2)
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(np.asarray(out), 7.0, rtol=1e-5)
    # gradient flows to the input
    g = jax.grad(lambda xx: ops.roi_align(xx, boxes, [1], 2).sum())(
        jnp.asarray(x))
    assert float(jnp.abs(g).sum()) > 0


def test_roi_align_linear_field_exact():
    """On a bilinear field f(y, x) = x, averaged samples equal the bin
    center's x — an analytically checkable case."""
    h = w = 16
    x = np.broadcast_to(np.arange(w, dtype=np.float32), (1, 1, h, w)).copy()
    boxes = np.asarray([[2.0, 2.0, 10.0, 10.0]], np.float32)
    out = np.asarray(ops.roi_align(x, boxes, [1], output_size=4,
                                   aligned=False))
    bin_w = 8.0 / 4
    expect_x = 2.0 + (np.arange(4) + 0.5) * bin_w
    np.testing.assert_allclose(out[0, 0, 0], expect_x, rtol=1e-5)


def test_roi_pool_max():
    x = np.zeros((1, 1, 8, 8), np.float32)
    x[0, 0, 2, 2] = 5.0
    x[0, 0, 6, 6] = 9.0
    out = np.asarray(ops.roi_pool(x, np.asarray([[0., 0., 7., 7.]],
                                                np.float32), [1], 2))
    assert out[0, 0, 0, 0] == 5.0 and out[0, 0, 1, 1] == 9.0


def test_psroi_pool_channel_blocks():
    r, co, ph, pw = 1, 2, 2, 2
    c = co * ph * pw
    x = RNG.normal(size=(1, c, 8, 8)).astype(np.float32)
    out = ops.psroi_pool(x, np.asarray([[0., 0., 7., 7.]], np.float32),
                         [1], (ph, pw))
    assert out.shape == (r, co, ph, pw)
    with pytest.raises(ValueError, match="divide"):
        ops.psroi_pool(np.zeros((1, 3, 4, 4), np.float32),
                       np.zeros((1, 4), np.float32), [1], 2)


def test_deform_conv2d_zero_offsets_equals_conv():
    """Zero offsets + all-ones mask reduce deform_conv2d to a plain conv."""
    from jax import lax as jlax

    x = RNG.normal(size=(2, 3, 8, 8)).astype(np.float32)
    wgt = RNG.normal(size=(4, 3, 3, 3)).astype(np.float32)
    offset = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out = ops.deform_conv2d(x, offset, wgt)
    ref = jlax.conv_general_dilated(jnp.asarray(x), jnp.asarray(wgt),
                                    (1, 1), "VALID")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-3,
                               atol=1e-4)
    # v2 mask halves the contribution
    out_half = ops.deform_conv2d(x, offset, wgt,
                                 mask=np.full((2, 9, 6, 6), 0.5, np.float32))
    np.testing.assert_allclose(np.asarray(out_half), 0.5 * np.asarray(ref),
                               rtol=1e-3, atol=1e-4)


def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image

    arr = RNG.integers(0, 255, (16, 16, 3)).astype(np.uint8)
    p = str(tmp_path / "t.jpg")
    Image.fromarray(arr).save(p, quality=95)
    raw = ops.read_file(p)
    assert raw.dtype == jnp.uint8
    img = ops.decode_jpeg(raw, mode="rgb")
    assert img.shape == (3, 16, 16)
    assert abs(float(jnp.mean(img.astype(jnp.float32)))
               - arr.mean()) < 10.0  # lossy


def test_sequence_mask():
    m = ops.sequence_mask(np.asarray([1, 3, 0]), maxlen=4)
    np.testing.assert_array_equal(
        np.asarray(m), [[1, 0, 0, 0], [1, 1, 1, 0], [0, 0, 0, 0]])
    m2 = ops.sequence_mask(np.asarray([2, 4]), dtype="float32")
    assert m2.shape == (2, 4) and m2.dtype == jnp.float32


# ------------------------------------------- detection remainder (r3)
def test_distribute_fpn_proposals_levels_and_restore():
    rois = np.array([[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 300, 300],
                     [5, 5, 40, 40]], np.float32)
    multi, restore, num = ops.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    assert len(multi) == 4  # levels 2..5
    assert sum(int(x) for x in num) == 4
    # small boxes land on low levels, big on high
    assert np.asarray(multi[0]).shape[0] >= 1  # level 2 got the 10x10
    flat = np.concatenate([np.asarray(m) for m in multi])
    np.testing.assert_allclose(flat[np.asarray(restore)], rois)


def test_matrix_nms_decay_ordering():
    """Top box keeps its score; its overlaps decay; distinct boxes barely
    decay (SOLOv2 matrix suppression semantics)."""
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out, idx, cnt = ops.matrix_nms(boxes, scores, 0.1, 0.0, 10, 10,
                                 return_index=True)
    out = np.asarray(out)
    assert int(cnt[0]) == 3 and out.shape[1] == 6
    assert out[0, 1] == pytest.approx(0.9)  # undecayed top
    overlapped = out[np.asarray(idx) % 3 == 1][0]
    distinct = out[np.asarray(idx) % 3 == 2][0]
    assert overlapped[1] < 0.8 * 0.7  # strongly decayed
    assert distinct[1] > 0.69  # nearly untouched
    # gaussian flavor also runs + post_threshold filters
    out2 = ops.matrix_nms(boxes, scores, 0.1, 0.5, 10, 10, use_gaussian=True,
                        return_rois_num=False)
    assert np.asarray(out2).shape[0] <= 3


def test_generate_proposals_pipeline():
    rng = np.random.default_rng(0)
    H = W = 8
    A = 3
    base = rng.uniform(0, 48, (H * W * A, 2)).astype(np.float32)
    anchors = np.column_stack([base, base + rng.uniform(4, 16, base.shape)])
    var = np.full((H * W * A, 4), 1.0, np.float32)
    scores = rng.normal(size=(2, A, H, W)).astype(np.float32)
    deltas = rng.normal(size=(2, 4 * A, H, W)).astype(np.float32) * 0.1
    rois, probs, rn = ops.generate_proposals(
        scores, deltas, [[64, 64], [64, 64]], anchors, var,
        pre_nms_top_n=64, post_nms_top_n=8, return_rois_num=True)
    rois = np.asarray(rois)
    assert rois.shape[1] == 4
    assert all(int(x) <= 8 for x in rn)
    # clipped to the image and probs sorted descending per image
    assert rois.min() >= 0 and rois.max() <= 64
    p0 = np.asarray(probs)[:int(rn[0]), 0]
    assert (np.diff(p0) <= 1e-6).all()


@pytest.mark.slow   # ~19s grad compile on the CI box (tier-1 report)
def test_yolo_loss_matching_and_grads():
    """Responsible-cell construction: loss decreases when predictions move
    toward the target; grads flow; ignore band suppresses high-IoU
    negatives from the objectness loss."""
    import jax

    anchors = [10, 13, 16, 30, 33, 23]
    kw = dict(anchors=anchors, anchor_mask=[0, 1, 2], class_num=4,
              ignore_thresh=0.7, downsample_ratio=8)
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(1, 3 * 9, 8, 8)) * 0.01).astype(np.float32)
    gt = np.zeros((1, 3, 4), np.float32)
    gt[0, 0] = [0.5, 0.5, 0.25, 0.25]
    lbl = np.zeros((1, 3), np.int64)
    lbl[0, 0] = 2

    loss0 = float(ops.yolo_loss(x, gt, lbl, **kw)[0])
    assert np.isfinite(loss0)
    # gradient descent on the head input should reduce the loss
    fn = lambda xx: ops.yolo_loss(xx, gt, lbl, **kw).sum()
    g = jax.grad(fn)(x)
    x1 = x - 0.5 * np.asarray(g)
    for _ in range(20):
        x1 = x1 - 0.5 * np.asarray(jax.grad(fn)(x1))
    assert float(ops.yolo_loss(x1, gt, lbl, **kw)[0]) < loss0 * 0.8
    # gt_score weighting scales the positive terms
    half = ops.yolo_loss(x, gt, lbl, gt_score=np.full((1, 3), 0.5, np.float32),
                       **kw)
    assert float(half[0]) < loss0
