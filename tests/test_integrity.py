"""Silent-data-corruption defense: fingerprints, the cross-replica vote,
the escalation ladder, and the checkpoint integrity ledger.

Everything here is stub-based and single-device — numpy fingerprints
drive the monitor, a scripted FakeStep drives the supervisor ladder —
so the module stays far under the tier-1 time budget. The real
multi-replica vote (shard_map over a dp4 x mp2 mesh, physical-copy
corruption, eviction + reduced-topology resume) lives in
``tools/sdc_drill.py``, gated as ``robustness_gate.py --sdc``.
"""
import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed import integrity
from paddle_tpu.distributed.integrity import (
    LEDGER_FILE, HostEvictionRequested, IntegrityMonitor, build_ledger,
    build_ledger_bytes, combine_folds, coverage_split, flip_bit,
    fold_leaf, host_fold_leaf, ledger_problem, load_quarantine,
    minority_ranks, read_ledger, record_conviction, verify_ledger)
from paddle_tpu.distributed.resilience import (
    EXIT_EVICTED, FaultPlan, InjectedBitflip)


# ===================================================== fold primitives
@pytest.mark.parametrize("arr", [
    np.linspace(-3, 3, 24, dtype=np.float32).reshape(4, 6),
    np.arange(-5, 7, dtype=np.int32),
    np.array([True, False, True]),
    np.linspace(-1, 1, 10, dtype=np.float16),
    np.arange(6, dtype=np.int64).reshape(2, 3),
])
def test_host_fold_matches_device_fold(arr):
    # the ledger is written by the HOST fold and verified against leaves
    # fingerprinted by the DEVICE fold — they must agree to the bit
    assert host_fold_leaf(arr) == int(fold_leaf(jnp.asarray(arr)))


def test_fold_sees_a_single_bit():
    a = np.linspace(-2, 2, 32, dtype=np.float32)
    b = a.copy()
    b.view(np.uint32)[17] ^= np.uint32(1)   # lowest mantissa bit
    assert host_fold_leaf(a) != host_fold_leaf(b)
    assert int(fold_leaf(jnp.asarray(a))) != int(fold_leaf(jnp.asarray(b)))


def test_fold_is_position_weighted():
    # a plain modular sum would miss two swapped elements
    a = np.array([1.0, 2.0, 3.0], np.float32)
    b = np.array([2.0, 1.0, 3.0], np.float32)
    assert host_fold_leaf(a) != host_fold_leaf(b)


def test_combine_folds_key_sensitive_and_order_free():
    folds = {"w": 123, "b": 456}
    assert combine_folds(folds) == combine_folds(
        dict(reversed(list(folds.items()))))   # canonical key order
    assert combine_folds({"w": 123, "b": 456}) != combine_folds(
        {"w": 456, "b": 123})                  # fold-to-key binding


# ================================================= vote + coverage math
def test_minority_ranks_names_the_deviant():
    fps = np.array([[7, 9], [7, 9], [7, 9], [7, 9]], np.uint32)
    assert minority_ranks(fps) == []
    fps[2, 0] ^= 1
    assert minority_ranks(fps) == [2]


def test_minority_ranks_no_majority_blames_everyone():
    # a 2v2 split has no quorum: every rank is suspect, and the monitor
    # escalates with rank=None (replay, never a conviction)
    fps = np.array([[1], [1], [2], [2]], np.uint32)
    assert minority_ranks(fps) == [0, 1, 2, 3]


def test_minority_ranks_any_column_counts():
    fps = np.array([[5, 5], [5, 5], [5, 6]], np.uint32)
    assert minority_ranks(fps) == [2]


def test_coverage_split_excludes_sharded_leaves():
    specs = {"w": P(None, "mp"), "b": P(), "z": P("dp"), "n": None}
    covered, uncovered = coverage_split(specs, "dp")
    # a leaf sharded over the vote axis has no cross-replica redundancy:
    # every replica holds a DIFFERENT slice, so equality says nothing
    assert set(covered) == {"w", "b", "n"}
    assert set(uncovered) == {"z"}


# ==================================================== monitor ladder
def _fp(*rows):
    return np.asarray(rows, np.uint32)


def test_monitor_clean_window_is_silent():
    mon = IntegrityMonitor(check_interval=2)
    assert not mon.due
    mon.observe(1, _fp([3, 4], [3, 4]))
    mon.observe(2, _fp([5, 6], [5, 6]))
    assert mon.due
    assert mon.flush() is None
    assert mon.stats()["mismatches"] == 0 and mon.stats()["pending"] == 0


def test_monitor_replay_then_convict_same_rank():
    mon = IntegrityMonitor(check_interval=1)
    v = mon.flush()
    assert v is None                      # nothing pending
    mon.observe(5, _fp([3, 4], [3, 4], [9, 4]))
    v = mon.flush()
    assert v == {"action": "replay", "rank": 2, "step": 5,
                 "fingerprints": [[3, 4], [3, 4], [9, 4]]}
    assert mon.stats()["replays"] == 1
    # the SAME rank diverging again after the deterministic replay is a
    # sticky fault: escalate to conviction
    mon.observe(6, _fp([3, 4], [3, 4], [8, 4]))
    v = mon.flush()
    assert v["action"] == "convict" and v["rank"] == 2
    assert mon.stats()["convictions"] == 1


def test_monitor_forgives_a_transient_after_clean_flushes():
    mon = IntegrityMonitor(check_interval=1, forgive_after=2)
    mon.observe(5, _fp([3], [9], [3]))
    assert mon.flush()["action"] == "replay"
    assert mon.armed == (1, 5)
    for step in (6, 7):
        mon.observe(step, _fp([4], [4], [4]))
        assert mon.flush() is None
    assert mon.armed is None and mon.stats()["suspect"] is None
    # a LATER flip is a fresh transient, not a conviction
    mon.observe(8, _fp([5], [6], [5]))
    assert mon.flush()["action"] == "replay"


def test_monitor_different_rank_is_a_new_replay_not_a_conviction():
    mon = IntegrityMonitor(check_interval=1)
    mon.observe(1, _fp([9], [3], [3]))
    assert mon.flush()["rank"] == 0
    mon.observe(2, _fp([3], [9], [3]))
    v = mon.flush()
    assert v["action"] == "replay" and v["rank"] == 1
    assert mon.stats()["convictions"] == 0


def test_monitor_drop_pending_forgets_rolled_back_steps():
    mon = IntegrityMonitor(check_interval=4)
    mon.observe(1, _fp([1], [2]))
    mon.drop_pending()
    assert mon.flush() is None and mon.stats()["mismatches"] == 0


# ======================================================== injection
def test_flip_bit_changes_exactly_one_bit_deterministically():
    import random

    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    base = jnp.asarray(np.linspace(-1, 1, 12, dtype=np.float32))
    arr1, info1 = flip_bit(base, mesh, "dp", 0, rng=random.Random(7))
    arr2, info2 = flip_bit(base, mesh, "dp", 0, rng=random.Random(7))
    assert info1 == info2                       # seeded draw is replayable
    a, b = np.asarray(base), np.asarray(arr1)
    diff = a.view(np.uint32) ^ b.view(np.uint32)
    assert np.count_nonzero(diff) == 1
    assert bin(int(diff.reshape(-1)[info1["element"]])).count("1") == 1
    assert info1["bit"] < 23                    # f32 default: mantissa only
    assert np.all(np.isfinite(b))               # numerics watchdog stays blind


def test_bitflip_rule_roundtrip_and_injection():
    plan = FaultPlan([{"site": "train.bitflip", "kind": "bitflip",
                       "times": 1, "tensor": "*weight*", "rank": 2,
                       "bit": 5}], seed=99)
    again = FaultPlan.from_json(plan.to_json())
    r = again.rules[0]
    assert (r.kind, r.tensor, r.rank, r.bit) == ("bitflip", "*weight*", 2, 5)
    with pytest.raises(InjectedBitflip) as ei:
        again.check("train.bitflip")
    assert ei.value.tensor == "*weight*" and ei.value.rank == 2
    assert ei.value.bit == 5
    again.check("train.bitflip")                # times=1: spent
    assert EXIT_EVICTED == 46


def test_apply_bitflip_without_mesh_degrades_to_anomaly():
    class Bare:
        def __init__(self):
            self.poisoned = 0

        def inject_anomaly(self):
            self.poisoned += 1

    step = Bare()
    fault = InjectedBitflip("x", tensor="*", rank=0)
    integrity.apply_bitflip(step, fault)
    assert step.poisoned == 1


# ============================================= ledger + quarantine
def test_ledger_roundtrip_and_leaf_verification(tmp_path):
    state = {"w": np.arange(6, dtype=np.float32),
             "opt": {"m": np.ones(3, np.float32)}, "count": 7}
    rec = build_ledger(state, step=7)
    d = str(tmp_path)
    with open(os.path.join(d, LEDGER_FILE), "wb") as f:
        f.write(build_ledger_bytes(state, step=7))
    assert read_ledger(d)["fingerprint"] == rec["fingerprint"]
    assert ledger_problem(d) is None
    flat = {"w": state["w"], "opt/m": state["opt"]["m"], "count": 7}
    assert verify_ledger(d, flat) is None
    flat["opt/m"] = np.full(3, 2.0, np.float32)   # bit rot after the crc
    prob = verify_ledger(d, flat)
    assert prob is not None and "opt/m" in prob


def test_divergent_ledger_is_rejected_with_rank_named(tmp_path):
    mon = IntegrityMonitor(check_interval=1)
    mon.observe(3, _fp([1, 2], [1, 2], [9, 2]))
    assert mon.flush()["rank"] == 2
    with open(os.path.join(str(tmp_path), LEDGER_FILE), "wb") as f:
        f.write(build_ledger_bytes({"w": np.ones(2, np.float32)}, 3, mon))
    prob = ledger_problem(str(tmp_path))
    assert prob is not None and "rank 2" in prob


def test_missing_ledger_is_not_a_problem(tmp_path):
    # pre-PR-20 checkpoints have no ledger; they must keep restoring
    assert read_ledger(str(tmp_path)) is None
    assert ledger_problem(str(tmp_path)) is None


def test_quarantine_record_is_durable_and_appends(tmp_path):
    root = str(tmp_path)
    p = record_conviction(root, {"rank": 2, "step": 40})
    record_conviction(root, {"rank": 5, "step": 90})
    q = load_quarantine(root)
    assert [r["rank"] for r in q["convicted"]] == [2, 5]
    assert not glob.glob(p + ".tmp-*")     # staged write left no temp file


def test_quarantine_staging_cleans_up_on_failure(tmp_path):
    class Boom:
        """json.dump walks into this and explodes mid-write."""

        def __iter__(self):
            raise RuntimeError("disk on fire")

    path = str(tmp_path / "q.json")
    with pytest.raises(TypeError):
        integrity._write_json_durable(path, {"convicted": Boom()})
    assert not os.path.exists(path)
    assert not glob.glob(path + ".tmp-*")  # R9: no leak on the error path


# ================================================= supervisor wiring
class FakeStep:
    """Scripted step: hands the supervisor a queue of fingerprints and a
    restorable numpy state — no mesh, no jit."""

    def __init__(self, fps):
        self._fps = list(fps)
        self._count = 0
        self.enabled_axis = None
        self.w = np.ones(4, np.float32)

    def enable_integrity(self, vote_axis="dp"):
        self.enabled_axis = vote_axis

    def take_fingerprint(self):
        return self._fps.pop(0) if self._fps else None

    def state_dict(self):
        return {"w": self.w, "count": np.asarray(self._count)}

    def set_state_dict(self, state):
        self.w = np.asarray(state["w"])
        self._count = int(np.asarray(state["count"]))


def _supervisor(tmp_path, fps, **kw):
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)

    policy = RecoveryPolicy(
        checkpoint_dir=str(tmp_path / "ckpt"), save_interval_steps=100,
        keep_max=3, async_save=False, preemption=False,
        integrity_check_interval=1, **kw)
    step = FakeStep(fps)
    return TrainingSupervisor(step, policy), step


def test_supervisor_enables_integrity_from_policy(tmp_path):
    sup, step = _supervisor(tmp_path, [], integrity_vote_axis="sdp")
    assert step.enabled_axis == "sdp" and sup.integrity is not None


def test_supervisor_warns_when_step_cannot_fingerprint(tmp_path):
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)

    class NoIntegrity:
        _count = 0

    with pytest.warns(RuntimeWarning, match="enable_integrity"):
        sup = TrainingSupervisor(
            NoIntegrity(), RecoveryPolicy(
                checkpoint_dir=str(tmp_path / "c"),
                integrity_check_interval=2, preemption=False))
    assert sup.integrity is None


def test_supervisor_ladder_replay_then_evict(tmp_path):
    from paddle_tpu.framework.supervisor import RollbackRequested
    from paddle_tpu.observability.registry import default_registry

    clean = _fp([3], [3], [3])
    bad = _fp([3], [9], [3])
    sup, step = _supervisor(tmp_path, [clean, bad, bad])
    seen = []
    sup.on_rollback = lambda info: seen.append(info.get("integrity"))
    base_replays = default_registry().snapshot()["counters"].get(
        "integrity.replay", 0)
    with sup:
        sup.save_now()                         # the replay's restore point
        step.w[:] = 5.0                        # post-checkpoint progress
        step._count = 1
        sup.after_batch(0, 0, 0.5, True, False)     # clean -> no verdict
        step._count = 2
        with pytest.raises(RollbackRequested):      # flip detected: replay
            sup.after_batch(0, 1, 0.5, True, False)
        assert np.all(step.w == 1.0)           # state rewound bit-exactly
        assert step._count == 0
        step._count = 1
        with pytest.raises(HostEvictionRequested) as ei:  # sticky: convict
            sup.after_batch(0, 0, 0.5, True, False)
    assert ei.value.rank == 1 and os.path.exists(ei.value.record_path)
    q = load_quarantine(sup.checkpoint.root)
    assert q["convicted"][0]["rank"] == 1
    assert seen and seen[0]["action"] == "replay" and seen[0]["rank"] == 1
    snap = default_registry().snapshot()["counters"]
    assert snap.get("integrity.replay", 0) == base_replays + 1
    assert snap.get("integrity.evicted", 0) >= 1
    assert snap.get("integrity.mismatch", 0) >= 2


def test_supervisor_save_writes_ledger_and_restore_rejects_divergent(
        tmp_path):
    sup, step = _supervisor(tmp_path, [])
    with sup:
        sup.save_now()
        path = os.path.join(sup.checkpoint.root, "step_0")
        assert read_ledger(path)["divergent"] is False
        # a later save whose window had already diverged: poison the
        # ledger the way a divergent monitor would have
        step._count = 1
        sup.save_now()
        p2 = os.path.join(sup.checkpoint.root, "step_1")
        rec = read_ledger(p2)
        rec["divergent"], rec["suspect"] = True, 3
        with open(os.path.join(p2, LEDGER_FILE), "w") as f:
            json.dump(rec, f)
        with pytest.warns(RuntimeWarning, match="rank 3"):
            sup.restore()
        assert step._count == 0                # fell back to step_0


# ------------------------------------------------------------ the full proof
@pytest.mark.slow
def test_sdc_drill_quick_passes():
    """The real multi-replica ladder on a dp4 x mp2 simulated mesh: a
    seeded flip on rank 2's physical copies detected by the fingerprint
    vote within one check interval, transient replayed + forgiven (loss
    bit-identical to fault-free), sticky convicted + quarantined +
    EXIT_EVICTED, then a reduced-topology resume on the surviving 6
    devices. Integrity-ON clean run asserted BIT-identical to the
    integrity-OFF reference (5 subprocesses, ~15-30 s)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "sdc_drill.py"),
         "--quick"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=600)
    assert p.returncode == 0, p.stdout[-3000:]
    assert "[sdc_drill] PASS" in p.stdout
