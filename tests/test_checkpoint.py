"""Distributed checkpoint tests (reference pattern: auto-parallel
``dist_saver`` re-slicing + ``auto_checkpoint`` resume tests)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.distributed.checkpoint import (
    AutoCheckpoint, latest_checkpoint, load_state, save_state)
from paddle_tpu.distributed.mesh import init_mesh, mesh_scope


def test_save_load_roundtrip_plain(tmp_path):
    state = {
        "w": np.arange(24, dtype=np.float32).reshape(4, 6),
        "nested": {"b": np.ones(3, np.float32), "step": 7},
        "scalar": jnp.asarray(2.5),
    }
    d = str(tmp_path / "ckpt")
    save_state(state, d)
    out = load_state(d)
    np.testing.assert_array_equal(out["w"], state["w"])
    np.testing.assert_array_equal(out["nested/b"], state["nested"]["b"])
    assert out["nested/step"] == 7
    assert float(out["scalar"]) == 2.5
    # template restores the tree structure
    tree = load_state(d, template=state)
    assert set(tree.keys()) == {"w", "nested", "scalar"}
    np.testing.assert_array_equal(tree["nested"]["b"], state["nested"]["b"])


def test_save_load_bfloat16(tmp_path):
    state = {"w": jnp.asarray(np.random.randn(8, 4), jnp.bfloat16)}
    d = str(tmp_path / "bf16")
    save_state(state, d)
    out = load_state(d)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["w"], np.float32),
                                  np.asarray(state["w"], np.float32))


def test_sharded_save_and_reslice(tmp_path):
    mesh = init_mesh(dp=2, mp=4)
    big = jnp.asarray(np.arange(64 * 8, dtype=np.float32).reshape(64, 8))
    sharded = jax.device_put(big, NamedSharding(mesh, P("mp", None)))
    d = str(tmp_path / "sh")
    save_state({"w": sharded}, d)
    # shard files: one per distinct mp slice (4), not 8 replicas
    files = [f for f in os.listdir(d) if f.endswith(".npy")]
    assert len(files) == 4

    # load re-sliced onto a different axis layout
    out = load_state(d, shardings={"w": NamedSharding(mesh, P(None, "dp"))})
    assert out["w"].shape == (64, 8)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(big))
    spec = out["w"].sharding.spec
    assert tuple(spec) == (None, "dp")

    # plain load (full gather on host)
    full = load_state(d)["w"]
    np.testing.assert_array_equal(full, np.asarray(big))


def test_async_save(tmp_path):
    d = str(tmp_path / "async")
    state = {"w": np.random.randn(32, 32).astype(np.float32)}
    pending = save_state(state, d, async_=True)
    assert pending.wait(30)
    out = load_state(d)
    np.testing.assert_array_equal(out["w"], state["w"])


def test_auto_checkpoint_resume(tmp_path):
    root = str(tmp_path / "auto")
    ac = AutoCheckpoint(root, save_interval_steps=5, keep_max=2,
                        async_save=True)
    state = {"w": np.zeros(4, np.float32), "step": 0}
    for step in range(1, 21):
        state = {"w": state["w"] + 1, "step": step}
        ac.maybe_save(step, state)
    ac.wait()
    # keep_max=2 -> only steps 15 and 20 remain
    kept = sorted(n for n in os.listdir(root) if n.startswith("step_"))
    assert kept == ["step_15", "step_20"]
    step, restored = ac.restore()
    assert step == 20
    np.testing.assert_array_equal(restored["w"], np.full(4, 20, np.float32))
    assert restored["step"] == 20

    # fresh manager over same root resumes too
    ac2 = AutoCheckpoint(root, save_interval_steps=5)
    step2, restored2 = ac2.restore()
    assert step2 == 20 and restored2["step"] == 20


def test_colliding_sanitized_keys(tmp_path):
    """'a/b' and 'a_b' sanitize identically — files must not collide."""
    w1 = np.full((2, 2), 1.0, np.float32)
    w2 = np.full((2, 2), 2.0, np.float32)
    d = str(tmp_path / "coll")
    save_state({"a": {"b": w1}, "a_b": w2}, d)
    out = load_state(d)
    np.testing.assert_array_equal(out["a/b"], w1)
    np.testing.assert_array_equal(out["a_b"], w2)


def test_async_save_error_propagates(tmp_path):
    target = tmp_path / "not_a_dir"
    target.write_text("file in the way")
    with pytest.raises((RuntimeError, NotADirectoryError, FileExistsError)):
        pending = save_state({"w": np.ones(2, np.float32)},
                             str(target / "sub"), async_=True)
        if pending is not None:
            pending.wait(30)


def test_auto_checkpoint_empty(tmp_path):
    ac = AutoCheckpoint(str(tmp_path / "none"))
    assert ac.restore() == (0, None)
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_trainstep_checkpoint_roundtrip(tmp_path):
    """save_state/load_state carries a whole TrainStep state (params +
    opt_state) — the fleet.save_persistables analogue."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.optimizer import Adam

    pt.seed(0)
    model = nn.Linear(8, 4)
    step = pt.TrainStep(model, Adam(learning_rate=0.01),
                        loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    x = np.random.default_rng(0).normal(size=(16, 8)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 4, (16, 1))
    for _ in range(3):
        step((x, y))
    d = str(tmp_path / "ts")
    save_state(step.state_dict(), d)

    pt.seed(0)
    model2 = nn.Linear(8, 4)
    step2 = pt.TrainStep(model2, Adam(learning_rate=0.01),
                         loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    restored = load_state(d, template=step2.state_dict())
    step2.set_state_dict(restored)
    l1 = float(step((x, y)))
    l2 = float(step2((x, y)))
    assert l1 == pytest.approx(l2, rel=1e-5)
