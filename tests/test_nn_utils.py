"""nn.utils (weight/spectral norm, param transforms) + incubate.nn fused
wrapper tests (reference ``python/paddle/nn/utils`` and
``python/paddle/incubate/nn``)."""
import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.nn.layer import buffer_state, functional_call, param_state
from paddle_tpu.nn.utils import (parameters_to_vector, remove_weight_norm,
                                 spectral_norm, vector_to_parameters,
                                 weight_norm)

RNG = np.random.default_rng(5)


def test_weight_norm_preserves_function_and_reparametrizes():
    lin = nn.Linear(6, 4)
    x = jnp.asarray(RNG.normal(size=(3, 6)).astype(np.float32))
    before = np.asarray(lin(x))
    weight_norm(lin, "weight", dim=0)
    ps = param_state(lin)
    assert "weight_g" in ps and "weight_v" in ps and "weight" not in ps
    np.testing.assert_allclose(np.asarray(lin(x)), before, rtol=1e-5,
                               atol=1e-6)
    # the reparameterization is differentiable through functional_call
    def loss(p):
        out, _ = functional_call(lin, p, {}, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(ps)
    assert float(jnp.abs(g["weight_g"]).sum()) > 0
    assert float(jnp.abs(g["weight_v"]).sum()) > 0
    # scaling g scales the effective weight rows
    lin2 = nn.Linear(6, 4)
    weight_norm(lin2, "weight", dim=0)
    ps2 = param_state(lin2)
    ps2["weight_g"] = ps2["weight_g"] * 2.0
    out_scaled, _ = functional_call(lin2, ps2, {}, x)
    out_base = lin2(x)
    np.testing.assert_allclose(np.asarray(out_scaled) -
                               np.asarray(lin2.bias),
                               2 * (np.asarray(out_base) -
                                    np.asarray(lin2.bias)), rtol=1e-4,
                               atol=1e-5)


def test_remove_weight_norm_restores_plain_param():
    lin = nn.Linear(5, 3)
    x = jnp.asarray(RNG.normal(size=(2, 5)).astype(np.float32))
    weight_norm(lin)
    y = np.asarray(lin(x))
    remove_weight_norm(lin)
    ps = param_state(lin)
    assert "weight" in ps and "weight_g" not in ps
    np.testing.assert_allclose(np.asarray(lin(x)), y, rtol=1e-5, atol=1e-6)


def test_spectral_norm_unit_sigma():
    lin = nn.Linear(8, 8)
    spectral_norm(lin, "weight", n_power_iterations=3)
    x = jnp.asarray(RNG.normal(size=(2, 8)).astype(np.float32))
    for _ in range(10):  # power iteration converges through forwards
        lin(x)
    w = np.asarray(lin.weight)
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    assert abs(sigma - 1.0) < 0.05, sigma
    ps = param_state(lin)
    assert "weight_orig" in ps and "weight" not in ps


def test_spectral_norm_buffer_updates_through_functional_call():
    lin = nn.Linear(6, 6)
    spectral_norm(lin)
    ps, bs = param_state(lin), buffer_state(lin)
    assert "weight_u" in bs
    x = jnp.asarray(RNG.normal(size=(2, 6)).astype(np.float32))
    _, new_bs = functional_call(lin, ps, bs, x)
    assert not np.allclose(np.asarray(new_bs["weight_u"]),
                           np.asarray(bs["weight_u"]))


def test_parameters_to_vector_roundtrip():
    params = [RNG.normal(size=(3, 4)).astype(np.float32),
              RNG.normal(size=(7,)).astype(np.float32)]
    vec = parameters_to_vector(params)
    assert vec.shape == (19,)
    back = vector_to_parameters(vec, params)
    for a, b in zip(params, back):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_fused_wrappers_run():
    from paddle_tpu.incubate.nn import (FusedFeedForward,
                                        FusedMultiHeadAttention,
                                        FusedTransformerEncoderLayer)

    x = jnp.asarray(RNG.normal(size=(2, 5, 16)).astype(np.float32))
    mha = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                  attn_dropout_rate=0.0)
    mha.eval()
    assert mha(x, x, x).shape == (2, 5, 16)
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0, act_dropout_rate=0.0)
    ffn.eval()
    assert ffn(x).shape == (2, 5, 16)
    enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0,
                                       attn_dropout_rate=0.0,
                                       act_dropout_rate=0.0)
    enc.eval()
    assert enc(x).shape == (2, 5, 16)


def test_weight_norm_two_params_independent():
    lin = nn.Linear(4, 3)
    weight_norm(lin, "weight", dim=0)
    weight_norm(lin, "bias", dim=None)
    ps = param_state(lin)
    assert {"weight_g", "weight_v", "bias_g", "bias_v"} <= set(ps)
    remove_weight_norm(lin, "weight")  # must not clobber bias's hook
    ps = param_state(lin)
    assert "weight" in ps and "bias_g" in ps
    remove_weight_norm(lin, "bias")
    assert "bias" in param_state(lin)
