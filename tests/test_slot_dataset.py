"""InMemoryDataset (industrial slot feed) tests + CTR end-to-end with the
PS sparse embedding — the reference's train_from_dataset path (SURVEY.md
§3.5) on TPU-native machinery."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.io.slot_dataset import InMemoryDataset


def write_ctr_file(path, n=100, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        label = int(rng.integers(2))
        s1 = ",".join(str(int(x)) for x in rng.integers(0, 1000, 3))
        s2 = ",".join(str(int(x)) for x in rng.integers(1000, 2000,
                                                        rng.integers(1, 5)))
        lines.append(f"{label}\t101:{s1}\t102:{s2}")
    path.write_text("\n".join(lines) + "\n")
    return lines


def test_load_and_batch(tmp_path):
    f = tmp_path / "part-0"
    write_ctr_file(f, n=100)
    ds = InMemoryDataset(slots=[101, 102], batch_size=32, max_per_slot=4)
    assert ds.load_into_memory([str(f)]) == 100
    assert len(ds) == 100
    batches = list(ds)
    assert len(batches) == 3  # drop_last
    signs, counts, labels = batches[0]
    assert signs[101].shape == (32, 4) and signs[102].shape == (32, 4)
    assert labels.shape == (32,)
    assert set(np.unique(labels)) <= {0.0, 1.0}
    # slot 101 always has 3 signs
    assert (counts[101] == 3).all()
    assert (signs[101][:, 3] == -1).all()  # padded
    # slot 102 has 1..4 signs
    assert counts[102].min() >= 1 and counts[102].max() <= 4


def test_first_record_content(tmp_path):
    f = tmp_path / "part-0"
    f.write_text("1\t101:5,7\t102:42\n0\t101:9\n")
    ds = InMemoryDataset(slots=[101, 102], batch_size=2, max_per_slot=3,
                         drop_last=False)
    ds.load_into_memory([str(f)])
    signs, counts, labels = next(iter(ds))
    np.testing.assert_array_equal(labels, [1.0, 0.0])
    np.testing.assert_array_equal(signs[101], [[5, 7, -1], [9, -1, -1]])
    np.testing.assert_array_equal(signs[102], [[42, -1, -1], [-1, -1, -1]])
    np.testing.assert_array_equal(counts[102], [1, 0])


def test_unknown_slots_ignored_and_errors(tmp_path):
    f = tmp_path / "part-0"
    f.write_text("1\t999:1,2\t101:3\n")
    ds = InMemoryDataset(slots=[101], batch_size=1, drop_last=False)
    ds.load_into_memory([str(f)])
    signs, _, _ = next(iter(ds))
    np.testing.assert_array_equal(signs[101][0][:1], [3])
    with pytest.raises(IOError):
        ds.load_into_memory([str(tmp_path / "missing")])
    bad = tmp_path / "bad"
    bad.write_text("not_a_label\t101:1\n")
    with pytest.raises(ValueError, match="malformed"):
        ds.load_into_memory([str(bad)])


def test_shuffle_is_permutation(tmp_path):
    f = tmp_path / "part-0"
    write_ctr_file(f, n=64)
    ds = InMemoryDataset(slots=[101], batch_size=64, max_per_slot=3)
    ds.load_into_memory([str(f)])
    before = next(iter(ds))[0][101].copy()
    ds.local_shuffle(seed=7)
    after = next(iter(ds))[0][101]
    assert not np.array_equal(before, after)
    # same multiset of rows
    assert sorted(map(tuple, before.tolist())) == \
        sorted(map(tuple, after.tolist()))
    ds.release_memory()
    assert len(ds) == 0


def test_ctr_train_e2e(tmp_path):
    """The train_from_dataset slice: slot file -> InMemoryDataset ->
    SparseEmbedding (PS table) via staged pull/push -> logistic loss ->
    AUC improves. Labels are made learnable: clicky signs occur in clicked
    records."""
    from paddle_tpu.distributed.ps import (MemorySparseTable,
                                           SparseAccessorConfig, StagedPull)
    from paddle_tpu.metric import Auc

    rng = np.random.default_rng(5)
    lines = []
    for i in range(512):
        label = int(rng.integers(2))
        base = 0 if label else 500
        signs = rng.integers(base, base + 200, 3)
        lines.append(f"{label}\t101:" + ",".join(map(str, signs)))
    f = tmp_path / "train"
    f.write_text("\n".join(lines))

    ds = InMemoryDataset(slots=[101], batch_size=128, max_per_slot=3)
    ds.load_into_memory([str(f)])
    table = MemorySparseTable(SparseAccessorConfig(
        embed_dim=8, optimizer="adagrad", learning_rate=0.2, seed=0))
    staged = StagedPull(table)

    @jax.jit
    def step(rows, inv, mask, labels):
        def loss_fn(rows):
            emb = StagedPull.lookup(rows, inv)          # [B, K, D]
            emb = emb * mask[:, :, None]                # zero the padding
            logit = emb.sum((1, 2))
            return -jnp.mean(labels * jax.nn.log_sigmoid(logit)
                             + (1 - labels) * jax.nn.log_sigmoid(-logit))
        return jax.value_and_grad(loss_fn)(rows)

    auc = Auc()
    first = last = None
    for epoch in range(6):
        ds.local_shuffle(seed=epoch)
        for signs, counts, labels in ds:
            ids = signs[101].clip(min=0)  # pad -1 -> id 0, masked anyway
            mask = (signs[101] >= 0).astype(np.float32)
            rows, inv, uniq = staged.pull(ids)
            loss, g = step(rows, inv, jnp.asarray(mask), jnp.asarray(labels))
            staged.push(uniq, g)
            if first is None:
                first = float(loss)
            last = float(loss)
    assert last < first * 0.5, (first, last)

    # eval AUC on the training set (memorization check)
    for signs, counts, labels in ds:
        ids = signs[101].clip(min=0)
        mask = (signs[101] >= 0).astype(np.float32)
        rows, inv, _ = staged.pull(ids)
        emb = np.asarray(StagedPull.lookup(rows, inv)) * mask[:, :, None]
        logit = emb.sum((1, 2))
        prob = 1 / (1 + np.exp(-logit))
        preds = np.stack([1 - prob, prob], axis=1)
        auc.update(preds, labels[:, None])
    assert auc.accumulate() > 0.9


def test_truncated_line_rejected_not_merged(tmp_path):
    """A line ending in 'slot:' must error, not silently consume the next
    line's label as a sign (strtoll skips '\\n' in the shared buffer)."""
    bad = tmp_path / "bad"
    bad.write_text("1\t101:\n0\t101:7\n")
    ds = InMemoryDataset(slots=[101], batch_size=1, drop_last=False)
    with pytest.raises(ValueError, match="malformed"):
        ds.load_into_memory([str(bad)])
    # whitespace-only line is skipped by the line splitter; trailing junk
    # after the last sign is tolerated only when numeric parsing stops at it
    ok = tmp_path / "ok"
    ok.write_text("1\t101:3\n\n0\t101:7\n")
    ds2 = InMemoryDataset(slots=[101], batch_size=2, drop_last=False)
    assert ds2.load_into_memory([str(ok)]) == 2
