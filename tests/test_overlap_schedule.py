"""Overlap-scheduled gradient reduction (``distributed.overlap``) and the
ZeRO sharded-update path of ``DistributedTrainStep``.

The contract under test: ``overlap_grad_reduce=True`` changes the step's
SCHEDULE (bucketed reverse-backward collective placement + sharded
weight update at ``sharding_stage >= 1``) but never its VALUES — every
parity assertion here is bitwise, not allclose, because the bucket
seams are ``optimization_barrier`` chains and sharding constraints
that pass values through untouched.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed.mesh import init_mesh, set_mesh
from paddle_tpu.distributed.overlap import (
    GradBucket, bucket_order, build_buckets, shard_first_free_dim,
    weight_update_specs)
from paddle_tpu.framework.jax_compat import shard_map
from paddle_tpu.optimizer import AdamW
from paddle_tpu.observability.registry import default_registry


@pytest.fixture
def mesh8():
    m = init_mesh(sdp=8)
    yield m
    set_mesh(None)


class MLP(nn.Layer):
    """fc3.bias has shape (4,) — indivisible by sdp=8, so it exercises
    the ZeRO fallback (replicated update for that one param)."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 8)
        self.fc3 = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc3(F.relu(self.fc2(F.relu(self.fc1(x)))))


def _mse(out, batch):
    return ((out - batch[1]) ** 2).mean()


def _data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 16)).astype(np.float32)
    y = rng.standard_normal((16, 4)).astype(np.float32)
    return x, y


def _make_step(stage, overlap, **kw):
    pt.seed(0)
    return dist.DistributedTrainStep(
        MLP(), AdamW(learning_rate=1e-2), loss_fn=_mse,
        sharding_stage=stage, overlap_grad_reduce=overlap,
        bucket_size_mb=0.001, **kw)   # tiny target -> several buckets


def _flat(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flat(v, f"{prefix}{k}/"))
        elif hasattr(v, "shape"):
            out[f"{prefix}{k}"] = np.asarray(v)
    return out


def _assert_bitident(a, b):
    fa, fb = _flat(a), _flat(b)
    assert fa.keys() == fb.keys()
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


# ------------------------------------------------------------ bucket logic
def test_bucket_order_is_reverse_backward():
    # grads materialise in reverse declaration order during backward —
    # the first-ready grads must land in the first bucket
    assert bucket_order(["fc1.w", "fc1.b", "fc2.w"]) == \
        ["fc2.w", "fc1.b", "fc1.w"]


def test_build_buckets_deterministic_and_covering():
    sizes = {f"p{i}": 100 * (i + 1) for i in range(7)}
    a = build_buckets(sizes, bucket_bytes=500)
    b = build_buckets(sizes, bucket_bytes=500)
    assert a == b                                   # deterministic
    names = [n for bk in a for n in bk.names]
    assert names == bucket_order(list(sizes))       # covering, in order
    assert all(isinstance(bk, GradBucket) for bk in a)
    assert [bk.index for bk in a] == list(range(len(a)))
    for bk in a:
        assert bk.bytes == sum(sizes[n] for n in bk.names)


def test_build_buckets_count_override():
    sizes = {f"p{i}": 128 for i in range(12)}
    assert len(build_buckets(sizes, bucket_bytes=128, bucket_count=3)) == 3
    assert len(build_buckets(sizes, bucket_bytes=10 ** 9,
                             bucket_count=1)) == 1
    # without the override the byte target rules: 12 singleton buckets
    assert len(build_buckets(sizes, bucket_bytes=128)) == 12


def test_shard_first_free_dim(mesh8):
    # first divisible free dim picked
    spec, ok = shard_first_free_dim(P(), (32, 4), "sdp", mesh8)
    assert ok and spec == P("sdp", None)
    # dim 0 indivisible -> falls through to dim 1
    spec, ok = shard_first_free_dim(P(), (4, 32), "sdp", mesh8)
    assert ok and spec == P(None, "sdp")
    # nothing divisible -> unchanged, not ok
    spec, ok = shard_first_free_dim(P(), (4,), "sdp", mesh8)
    assert not ok and spec == P(None)
    # axis already used by the param's own spec -> kept as-is
    spec, ok = shard_first_free_dim(P("sdp"), (32,), "sdp", mesh8)
    assert ok and spec == P("sdp")


def test_weight_update_specs_reports_fallbacks(mesh8):
    fell = []
    specs = weight_update_specs(
        {"a": P(), "b": P()}, {"a": (32, 8), "b": (3,)}, "sdp", mesh8,
        on_fallback=fell.append)
    assert specs["a"] == P("sdp", None)
    assert specs["b"] == P(None)
    assert fell == ["b"]


# --------------------------------------------------------- schedule surface
def test_collective_schedule_and_statusz(mesh8):
    step = _make_step(1, True)
    sched = step.collective_schedule()
    assert sched, "overlap step must expose its bucket schedule"
    names = [n for b in sched for n in b["params"]]
    assert names == bucket_order(list(step.params))
    sz = step.statusz()
    assert sz["overlap_grad_reduce"] and sz["sharding_stage"] == 1
    assert len(sz["buckets"]) == len(sched)
    # fc3.bias (4,) is indivisible by sdp=8 -> counted, surfaced, metered
    assert "fc3.bias" in sz["zero_fallback_params"]
    counters = default_registry().snapshot()["counters"]
    assert any(k.startswith("distributed.zero_fallback_params_total")
               and v >= 1 for k, v in counters.items())

    serial = _make_step(1, False)
    assert serial.collective_schedule() == []
    assert not serial.statusz()["overlap_grad_reduce"]


def test_bucket_count_knob_reaches_step(mesh8):
    step = _make_step(1, True, bucket_count=2)
    assert len(step.collective_schedule()) == 2


# ------------------------------------------------------------ step parity
@pytest.mark.parametrize("stage", [
    0, 1,
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow),
])
def test_overlap_bitwise_parity(mesh8, stage):
    """The bucketed schedule at every sharding stage is a RESCHEDULE of
    the serial program: losses, params, and opt state stay bit-identical
    over multiple steps."""
    x, y = _data()
    serial = _make_step(stage, False)
    bucketed = _make_step(stage, True)
    for _ in range(3):
        ls = serial((x, y))
        lb = bucketed((x, y))
        np.testing.assert_array_equal(np.asarray(ls), np.asarray(lb))
    _assert_bitident(serial.params, bucketed.params)
    _assert_bitident(serial.opt_state, bucketed.opt_state)


def test_overlap_grad_accum_parity(mesh8):
    """Gradient merge composes with the bucketed schedule: the sharded
    accumulator feeds the same update as the serial one."""
    x, y = _data()
    serial = _make_step(1, False, grad_accum_steps=2)
    bucketed = _make_step(1, True, grad_accum_steps=2)
    for _ in range(4):                        # two full accumulation cycles
        ls = serial((x, y))
        lb = bucketed((x, y))
        np.testing.assert_array_equal(np.asarray(ls), np.asarray(lb))
    _assert_bitident(serial.params, bucketed.params)
    _assert_bitident(serial.opt_state, bucketed.opt_state)


def test_scaler_rollback_restores_sharded_opt_state(mesh8):
    """A watchdog-poisoned step under the bucketed+ZeRO schedule must
    roll back to EXACTLY the pre-step sharded state (params, moments,
    and scale all bit-identical)."""
    from paddle_tpu.amp import GradScaler

    x, y = _data()
    step = _make_step(1, True,
                      scaler=GradScaler(init_loss_scaling=2.0 ** 10,
                                        use_dynamic_loss_scaling=True))
    loss, ok, found = step.watchdog_call((x, y))
    assert bool(ok) and np.isfinite(float(loss))
    before_p = {k: np.asarray(v) for k, v in step.params.items()}
    before_o = _flat(step.opt_state)
    step.inject_anomaly()
    loss, ok, found = step.watchdog_call((x, y))
    assert not bool(ok)
    _assert_bitident(step.params, before_p)
    _assert_bitident(step.opt_state, before_o)


@pytest.mark.slow
def test_overlap_state_reshards_across_dp_resize(mesh8):
    """PR 6 elastic path: a checkpoint written by the bucketed+ZeRO step
    on sdp=8 resumes on sdp=4 (set_state_dict re-places every leaf onto
    the new mesh's declared shardings) and keeps training parity."""
    x, y = _data()
    big = _make_step(1, True)
    ref = _make_step(1, True)
    for _ in range(2):
        big((x, y))
        ref((x, y))
    sd = jax.tree.map(np.asarray, big.state_dict())
    set_mesh(None)
    init_mesh(sdp=4)
    small = _make_step(1, True)
    small.set_state_dict(sd)
    for k, v in small.params.items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(ref.params[k]))
    l_small = float(small((x, y)))
    l_ref = float(ref((x, y)))
    assert np.isfinite(l_small)
    # across topologies the reduction tree changes: parity is numeric
    np.testing.assert_allclose(l_small, l_ref, rtol=1e-5, atol=1e-6)


# ------------------------------------------------- explicit-SPMD analogue
def test_all_reduce_buckets_matches_mapped_all_reduce(mesh8):
    xs = [jnp.arange(8.0) + i for i in range(3)]

    def bucketed(*vs):
        return tuple(C.all_reduce_buckets(vs, group="sdp"))

    def mapped(*vs):
        return tuple(C.all_reduce(v, group="sdp") for v in vs)

    specs = (P("sdp"),) * 3
    fb = shard_map(bucketed, mesh=mesh8, in_specs=specs, out_specs=specs)
    fm = shard_map(mapped, mesh=mesh8, in_specs=specs, out_specs=specs)
    for got, want in zip(fb(*xs), fm(*xs)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
