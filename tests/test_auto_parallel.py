"""Auto-parallel tests: annotations, cost-model planner, Engine on the
8-device CPU mesh (reference auto_parallel/ engine + tuner unittests,
SURVEY.md §4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import auto_parallel as ap
from paddle_tpu.distributed.auto_parallel.planner import (ClusterSpec,
                                                          CostModel,
                                                          ModelSpec, Planner)
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.optimizer import AdamW


def gpt_1p3b_spec(batch_tokens=0.5e6):
    """GPT-3 1.3B-ish workload (BASELINE.md north star config)."""
    n_params = 1.3e9
    return ModelSpec(
        n_params=n_params, flops_per_token=6 * n_params,
        hidden_size=2048, n_layers=24, seq_len=2048,
        global_batch_tokens=batch_tokens)


# ----------------------------------------------------------- annotations
def test_shard_tensor_eager_and_jit():
    mesh = init_mesh(dp=4, mp=2)
    x = np.ones((8, 16), np.float32)
    sx = ap.shard_tensor(x, shard_spec=["dp", None])
    assert sx.sharding.spec == PartitionSpec("dp", None)

    @jax.jit
    def f(x):
        h = ap.shard_tensor(x * 2, shard_spec=["dp", "mp"])
        return h.sum()

    with mesh:
        out = f(jnp.ones((8, 16)))
    assert float(out) == 256.0


def test_process_mesh_wrapper():
    pm = ap.ProcessMesh(shape=(4, 2), dim_names=["x", "y"])
    assert pm.shape == {"x": 4, "y": 2}
    with pm:
        s = ap.shard_tensor(np.ones((4, 4), np.float32),
                            shard_spec=["x", None])
        assert s.sharding.spec == PartitionSpec("x", None)


def test_shard_op_wrapper():
    init_mesh(dp=8)

    def matmul(a, b):
        return a @ b

    op = ap.shard_op(matmul, in_shard_specs=[["dp", None], None],
                     out_shard_specs=[["dp", None]])
    out = op(np.ones((8, 4), np.float32), np.ones((4, 2), np.float32))
    np.testing.assert_allclose(out, 4.0)
    assert out.sharding.spec == PartitionSpec("dp", None)


# ---------------------------------------------------------------- planner
def test_cost_model_scaling_laws():
    spec = gpt_1p3b_spec()
    cm = CostModel(spec)
    pure_dp8 = cm.evaluate(dp=8, mp=1)
    pure_dp4 = cm.evaluate(dp=4, mp=1)
    # more chips -> less compute time
    assert pure_dp8.compute_time < pure_dp4.compute_time
    # TP adds activation comm: mp=8 costs more comm than dp=8
    mp8 = cm.evaluate(dp=1, mp=8)
    assert mp8.comm_time > pure_dp8.comm_time
    # ZeRO shards memory
    z = cm.evaluate(dp=1, mp=1, sdp=8)
    assert z.mem_per_chip < pure_dp8.mem_per_chip


def test_planner_picks_feasible_minimum():
    spec = gpt_1p3b_spec()
    planner = Planner(spec, n_devices=16)
    cands = planner.candidates()
    assert len(cands) > 3
    best = planner.best()
    assert best.feasible
    # best is the fastest feasible candidate
    feas = [c for c in cands if c.feasible]
    assert best.step_time == min(c.step_time for c in feas)
    assert best.dp * best.mp * best.sdp == 16
    # on small-HBM chips (v5e-like 16GB), 1.3B + adam state (~18GB) does
    # not fit pure-dp; the planner must shard (sdp/mp)
    small = Planner(spec, n_devices=16,
                    cluster=ClusterSpec(hbm_per_chip=16e9))
    scands = small.candidates()
    assert all(not c.feasible for c in scands if c.dp == 16)
    sbest = small.best()
    assert sbest.feasible and (sbest.sdp > 1 or sbest.mp > 1)


def test_planner_infeasible_raises():
    # 100B params on 1 chip: nothing fits
    spec = ModelSpec(n_params=1e11, flops_per_token=6e11, hidden_size=8192,
                     n_layers=80, seq_len=2048, global_batch_tokens=1e6)
    with pytest.raises(ValueError, match="feasible"):
        Planner(spec, n_devices=1).best()


def test_plan_mesh_returns_usable_mesh():
    spec = gpt_1p3b_spec(batch_tokens=8 * 128)
    mesh, plan = ap.plan_mesh(spec, n_devices=8)
    assert int(np.prod(list(mesh.shape.values()))) == 8
    assert plan.feasible


# ----------------------------------------------------------------- engine
def test_engine_fit_evaluate_predict():
    pt.seed(0)
    mesh = init_mesh(dp=4, mp=2)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    eng = ap.Engine(model,
                    loss_fn=lambda out, b: F.cross_entropy(out, b[1]),
                    optimizer=AdamW(learning_rate=1e-2), mesh=mesh,
                    batch_axes=("dp",))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)
    data = [(x, y)] * 8
    hist = eng.fit(data, epochs=3)
    assert hist["loss"][-1] < hist["loss"][0]
    ev = eng.evaluate([(x, y)])
    assert np.isfinite(ev["loss"])
    preds = eng.predict([(x, y)])
    assert preds[0].shape == (16, 4)


def test_engine_save_load_roundtrip(tmp_path):
    pt.seed(1)
    init_mesh(dp=8)
    model = nn.Linear(8, 4)
    eng = ap.Engine(model, loss_fn=lambda out, b: (out ** 2).mean(),
                    optimizer=AdamW(learning_rate=1e-2),
                    batch_axes=("dp",))
    x = np.ones((8, 8), np.float32)
    eng.fit([(x,)] * 4)
    path = str(tmp_path / "eng.pdparams")
    eng.save(path)
    pred1 = eng.predict([(x,)])[0]

    model2 = nn.Linear(8, 4)
    eng2 = ap.Engine(model2, batch_axes=("dp",))
    eng2.load(path)
    pred2 = eng2.predict([(x,)])[0]
    np.testing.assert_allclose(pred1, pred2, rtol=1e-5)


def test_engine_with_planner_spec():
    """Engine + model_spec: planner chooses the mesh, training runs."""
    pt.seed(2)
    spec = ModelSpec(n_params=1e4, flops_per_token=6e4, hidden_size=16,
                     n_layers=2, seq_len=8, global_batch_tokens=64,
                     optim_state_mult=6.0)
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 2))
    eng = ap.Engine(model, loss_fn=lambda out, b: (out ** 2).mean(),
                    optimizer=AdamW(learning_rate=1e-2), model_spec=spec,
                    batch_axes=("dp",))
    assert eng.plan is not None and eng.plan.feasible
    x = np.ones((8, 16), np.float32)
    hist = eng.fit([(x,)] * 6)
    assert hist["loss"][-1] < hist["loss"][0]


def test_engine_metrics():
    from paddle_tpu.metric import Accuracy

    pt.seed(3)
    init_mesh(dp=8)
    model = nn.Linear(8, 4)
    eng = ap.Engine(model, loss_fn=lambda out, b: F.cross_entropy(out, b[1]),
                    optimizer=AdamW(learning_rate=1e-2),
                    metrics=[Accuracy()], batch_axes=("dp",))
    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.int32)
    res = eng.evaluate([(x, y)])
    assert "acc" in res or any(k != "loss" for k in res), res
    non_loss = [v for k, v in res.items() if k != "loss"]
    assert 0.0 <= float(np.asarray(non_loss[0]).reshape(-1)[0]) <= 1.0


def test_shard_op_spec_mismatch_raises():
    init_mesh(dp=8)
    op = ap.shard_op(lambda a, b: a + b, in_shard_specs=[["dp", None]])
    with pytest.raises(ValueError, match="in_shard_specs"):
        op(np.ones((8, 2), np.float32), np.ones((8, 2), np.float32))


# ----------------------------------------------- ParallelTuner (round 3)
def bench_gpt_spec(n_params=1.3e9, seq=1024, batch=512):
    """The BASELINE.md GPT-1.3B pretrain config as a ModelSpec."""
    from paddle_tpu.distributed.auto_parallel.planner import ModelSpec

    hidden = 2048
    return ModelSpec(n_params=n_params, flops_per_token=6 * n_params,
                     hidden_size=hidden, n_layers=24, seq_len=seq,
                     global_batch_tokens=batch * seq)


def test_tuner_picks_known_best_among_candidates():
    """GPT-1.3B on 32 v5e-class chips (16 GB HBM): params+Adam state are
    ~10.4 GB, so pure dp-32 replication fits but leaves nothing for
    activations at this batch — the physics-known best is a ZeRO/dp mix
    with NO model parallel (the model fits once sharded; mp would add
    per-layer collectives for nothing). The tuner must search >= 8
    candidates and land in that family."""
    from paddle_tpu.distributed.auto_parallel import ParallelTuner
    from paddle_tpu.distributed.auto_parallel.planner import ClusterSpec

    v5e = ClusterSpec(peak_flops=197e12, ici_bandwidth=45e9,
                      hbm_per_chip=16e9, mfu=0.4)
    tuner = ParallelTuner(bench_gpt_spec(), 32, cluster=v5e, num_heads=16)
    cands = tuner.tune()
    assert len(cands) >= 8
    best = tuner.best()
    assert best.feasible
    assert best.mp == 1 and best.pp == 1  # dp/ZeRO family wins
    assert best.sdp > 1  # replicated opt state would not fit activations
    # modeled ordering sanity: heavy mp is strictly worse here
    by_axes = {(c.dp, c.sdp, c.mp, c.pp, c.sp): c for c in cands}
    heavy_mp = [c for c in cands if c.mp >= 16]
    assert heavy_mp and all(c.step_time > best.step_time for c in heavy_mp)


def test_tuner_forces_sharding_when_model_does_not_fit():
    """7B on 8 x 16 GB chips: 56 GB of params+state can NOT replicate;
    every feasible plan must shard (sdp/mp/pp product covering it), and
    infeasible plans sort last."""
    from paddle_tpu.distributed.auto_parallel import ParallelTuner
    from paddle_tpu.distributed.auto_parallel.planner import (ClusterSpec,
                                                              ModelSpec)

    spec = ModelSpec(n_params=7e9, flops_per_token=42e9, hidden_size=4096,
                     n_layers=32, seq_len=2048,
                     global_batch_tokens=64 * 2048)
    v5e = ClusterSpec(peak_flops=197e12, ici_bandwidth=45e9,
                      hbm_per_chip=16e9, mfu=0.4)
    tuner = ParallelTuner(spec, 8, cluster=v5e, num_heads=32)
    best = tuner.best()
    assert best.feasible
    shard_product = best.sdp * best.mp * best.pp
    assert shard_product >= 4  # 56 GB / 16 GB -> at least 4-way state shard
    # pure dp-8 is modeled infeasible
    dp8 = tuner.evaluate(8, 1, 1, 1, 1)
    assert not dp8.feasible


def test_tuner_long_context_prefers_sequence_parallel():
    """At seq=65536 even batch-of-one activations blow a chip; sp must
    appear in the winning plan (the long-context capability the reference
    lacks, SURVEY §5)."""
    from paddle_tpu.distributed.auto_parallel import ParallelTuner
    from paddle_tpu.distributed.auto_parallel.planner import (ClusterSpec,
                                                              ModelSpec)

    spec = ModelSpec(n_params=1.3e9, flops_per_token=6 * 1.3e9,
                     hidden_size=2048, n_layers=24, seq_len=65536,
                     global_batch_tokens=8 * 65536, remat=False)
    v5e = ClusterSpec(peak_flops=197e12, ici_bandwidth=45e9,
                      hbm_per_chip=16e9, mfu=0.4)
    tuner = ParallelTuner(spec, 32, cluster=v5e, num_heads=16)
    best = tuner.best()
    assert best.sp > 1


def test_tuner_calibration_from_bench_json(tmp_path):
    from paddle_tpu.distributed.auto_parallel import calibrate_cluster

    bench = {"metric": "gpt", "value": 1.0, "extra": {"mfu": 0.37}}
    path = tmp_path / "bench.json"
    path.write_text(__import__("json").dumps(bench))
    spec = calibrate_cluster(str(path))
    assert spec.mfu == 0.37
    # driver BENCH_r{N} wrapper shape also accepted
    spec2 = calibrate_cluster({"parsed": bench})
    assert spec2.mfu == 0.37


def test_tuner_measured_validation_on_mesh():
    """The profiler.py-style measured pass: compile + time real
    DistributedTrainStep programs for the top plans on the 8-device host
    mesh and re-rank by wall time."""
    import jax

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.auto_parallel import ParallelTuner
    from paddle_tpu.distributed.auto_parallel.planner import (ClusterSpec,
                                                              ModelSpec)
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.optimizer import SGD

    spec = ModelSpec(n_params=1e6, flops_per_token=6e6, hidden_size=64,
                     n_layers=2, seq_len=64, global_batch_tokens=16 * 64)
    tuner = ParallelTuner(spec, 8, cluster=ClusterSpec(), num_heads=4)
    top = [c for c in tuner.tune() if c.pp == 1 and c.sp == 1][:2]
    assert len(top) == 2

    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 64)).astype(np.float32)
    y = rng.integers(0, 8, 16)

    def build(plan):
        pt.seed(0)
        mesh = init_mesh(plan.axes)
        model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                              nn.Linear(128, 8))
        step = DistributedTrainStep(
            model, SGD(learning_rate=0.1),
            loss_fn=lambda out, b: F.cross_entropy(out, b[1]), mesh=mesh)
        return lambda: step((x, y))

    ranked = tuner.validate(top, build, steps=2)
    assert all(c.measured_time and c.measured_time > 0 for c in ranked)
    assert ranked[0].measured_time <= ranked[1].measured_time


def test_engine_auto_tune_adopts_tuner_plan():
    """Engine(auto_tune=True) escalates from the 3-axis planner to the
    full ParallelTuner and builds its mesh from the winning plan."""
    import paddle_tpu.nn as nn_mod
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.auto_parallel.planner import (ClusterSpec,
                                                              ModelSpec)
    from paddle_tpu.distributed.mesh import set_mesh
    from paddle_tpu.optimizer import SGD

    set_mesh(None)
    spec = ModelSpec(n_params=1e6, flops_per_token=6e6, hidden_size=64,
                     n_layers=2, seq_len=64, global_batch_tokens=16 * 64)
    eng = Engine(nn_mod.Linear(64, 64), optimizer=SGD(learning_rate=0.1),
                 loss_fn=lambda o, b: None, model_spec=spec, auto_tune=True,
                 cluster=ClusterSpec(), num_heads=4)
    assert eng.plan is not None and hasattr(eng.plan, "sp")  # TunedPlan
    assert eng.plan.n_devices == 8
    assert int(np.prod(list(eng.mesh.shape.values()))) == 8
    set_mesh(None)
