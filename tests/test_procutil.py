"""Server subprocesses must die with their parent (VERDICT r4 weak #7:
orphaned graph_server processes survived an aborted run by 16 hours).
PDEATHSIG at spawn + a ppid watchdog inside the server are both tested by
SIGKILLing the spawning client mid-serve."""
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CLIENT = r"""
import os, sys, time
from paddle_tpu.distributed.ps.graph import launch_graph_servers

procs, endpoints = launch_graph_servers(2)
print("SERVER_PIDS " + " ".join(str(p.pid) for p in procs), flush=True)
time.sleep(120)  # parked: the test SIGKILLs us mid-serve
"""


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def test_servers_die_with_killed_parent(tmp_path):
    script = tmp_path / "client.py"
    script.write_text(_CLIENT)
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        line = ""
        deadline = time.time() + 60
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SERVER_PIDS"):
                break
        assert line.startswith("SERVER_PIDS"), "client never started servers"
        pids = [int(p) for p in line.split()[1:]]
        assert pids and all(_alive(p) for p in pids)

        os.kill(proc.pid, signal.SIGKILL)  # the abnormal-abort scenario
        proc.wait(timeout=10)

        # PDEATHSIG fires immediately; allow slack for scheduler jitter
        deadline = time.time() + 10
        while time.time() < deadline and any(_alive(p) for p in pids):
            time.sleep(0.2)
        leaked = [p for p in pids if _alive(p)]
        for p in leaked:  # clean up before failing loudly
            os.kill(p, signal.SIGKILL)
        assert not leaked, f"servers survived parent death: {leaked}"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
