"""Systematic numeric-vs-analytic gradient sweep over the differentiable
op surface (the reference's ``check_grad`` discipline applied wide:
``python/paddle/fluid/tests/unittests/op_test.py:333`` — every op test
there carries a finite-difference gradient check; this file gives the
same guarantee to the hot op families here in one parametrized sweep).

Inputs are tiny (<= 12 elements keeps central differences cheap) and
nudged away from non-differentiable kinks (|x| >= 0.05 for relu-likes,
distinct values for max/min subgradients).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from op_test import check_grad

rng = np.random.default_rng(7)


def _x(*shape):
    """Values in +-[0.3, 1.3): away from kinks of relu/abs/clip/sqrt."""
    v = rng.random(shape).astype(np.float32) + 0.3
    sign = np.where(rng.random(shape) < 0.5, -1.0, 1.0).astype(np.float32)
    return v * sign


def _pos(*shape):
    return rng.random(shape).astype(np.float32) + 0.5


A23 = _x(2, 3)
B23 = _x(2, 3)
P23 = _pos(2, 3)
M22 = _x(2, 2)
N22 = _x(2, 2)
V4 = _x(4)
LOGITS = _x(3, 4)
LABELS = np.asarray([1, 3, 0])
IMG = _x(1, 2, 4, 4)
KER = _x(3, 2, 2, 2)
# targets/labels are constants: regenerating them inside the op lambda
# would corrupt the finite-difference baseline
BCE_TARGET = jnp.asarray((_pos(2, 3) > 0.9).astype(np.float32))
HINGE_LABELS = jnp.asarray(np.where(_x(2, 3) > 0, 1.0, -1.0)
                           .astype(np.float32))

# (id, fn, args, arg_idx) — grad checked w.r.t. args[arg_idx]
CASES = [
    # activations
    ("relu", F.relu, (A23,), 0),
    ("sigmoid", F.sigmoid, (A23,), 0),
    ("tanh", pt.tanh, (A23,), 0),
    ("gelu", F.gelu, (A23,), 0),
    ("softplus", F.softplus, (A23,), 0),
    ("elu", F.elu, (A23,), 0),
    ("selu", F.selu, (A23,), 0),
    ("silu", F.silu, (A23,), 0),
    ("leaky_relu", F.leaky_relu, (A23,), 0),
    ("hardswish", F.hardswish, (A23,), 0),
    ("mish", F.mish, (A23,), 0),
    ("softsign", F.softsign, (A23,), 0),
    ("tanhshrink", F.tanhshrink, (A23,), 0),
    # pointwise math
    ("exp", pt.exp, (A23,), 0),
    ("log", pt.log, (P23,), 0),
    ("sqrt", pt.sqrt, (P23,), 0),
    ("rsqrt", pt.rsqrt, (P23,), 0),
    ("sin", pt.sin, (A23,), 0),
    ("cos", pt.cos, (A23,), 0),
    ("atan", pt.atan, (A23,), 0),
    ("sinh", pt.sinh, (A23,), 0),
    ("cosh", pt.cosh, (A23,), 0),
    ("expm1", pt.expm1, (A23,), 0),
    ("log1p", pt.log1p, (P23,), 0),
    ("reciprocal", pt.reciprocal, (P23,), 0),
    ("square", pt.square, (A23,), 0),
    ("pow", lambda x: pt.pow(x, 3.0), (P23,), 0),
    # binary
    ("multiply_wrt_rhs", pt.multiply, (A23, B23), 1),
    ("add", pt.add, (A23, B23), 0),
    ("subtract", pt.subtract, (A23, B23), 1),
    ("multiply", pt.multiply, (A23, B23), 0),
    ("divide", pt.divide, (A23, P23), 0),
    ("divide_wrt_denom", pt.divide, (A23, P23), 1),
    ("maximum", pt.maximum, (A23, B23), 0),
    ("minimum", pt.minimum, (A23, B23), 0),
    # matmul / linalg
    ("matmul", pt.matmul, (M22, N22), 0),
    ("matmul_rhs", pt.matmul, (M22, N22), 1),
    ("einsum", lambda a, b: pt.einsum("ij,jk->ik", a, b), (M22, N22), 0),
    ("dot", pt.dot, (V4, _x(4)), 0),
    # reductions
    ("sum", pt.sum, (A23,), 0),
    ("mean", pt.mean, (A23,), 0),
    ("max_red", pt.max, (A23,), 0),
    ("min_red", pt.min, (A23,), 0),
    ("logsumexp", pt.logsumexp, (A23,), 0),
    ("prod", pt.prod, (P23,), 0),
    ("norm", lambda x: pt.linalg.norm(x), (A23,), 0),
    # softmax family
    ("softmax", lambda x: F.softmax(x, axis=-1), (LOGITS,), 0),
    ("log_softmax", lambda x: F.log_softmax(x, axis=-1), (LOGITS,), 0),
    # losses (w.r.t. predictions)
    ("mse_loss", F.mse_loss, (A23, B23), 0),
    ("l1_loss", lambda p, t: F.l1_loss(p, t),
     (A23, A23 + 0.37), 0),  # offset keeps p-t away from 0
    ("smooth_l1", F.smooth_l1_loss, (A23, B23), 0),
    ("cross_entropy", lambda lg: F.cross_entropy(lg, jnp.asarray(LABELS)),
     (LOGITS,), 0),
    ("nll_loss", lambda lp: F.nll_loss(lp, jnp.asarray(LABELS)),
     (np.log(np.abs(LOGITS) + 0.5).astype(np.float32),), 0),
    ("kl_div", lambda lp, t: F.kl_div(lp, t),
     (np.log(_pos(2, 3)).astype(np.float32), _pos(2, 3)), 0),
    ("bce_with_logits", lambda lg: F.binary_cross_entropy_with_logits(
        lg, BCE_TARGET), (A23,), 0),
    ("hinge_embedding", lambda p: F.hinge_embedding_loss(
        p, HINGE_LABELS), (P23 + 0.2,), 0),
    # manipulation
    ("transpose", lambda x: pt.transpose(x, [1, 0]), (A23,), 0),
    ("reshape", lambda x: pt.reshape(x, [6]), (A23,), 0),
    ("concat", lambda a, b: pt.concat([a, b], axis=0), (A23, B23), 0),
    ("split", lambda x: pt.split(x, 3, axis=1)[1], (A23,), 0),
    ("pad", lambda x: F.pad(x, [1, 1, 1, 1]), (M22,), 0),
    ("gather", lambda x: pt.gather(x, jnp.asarray([0, 1, 0])), (A23,), 0),
    ("clip", lambda x: pt.clip(x, -5.0, 5.0), (A23,), 0),  # interior
    ("tile", lambda x: pt.tile(x, [2, 1]), (A23,), 0),
    ("flip", lambda x: pt.flip(x, axis=0), (A23,), 0),
    ("roll", lambda x: pt.roll(x, 1, axis=1), (A23,), 0),
    ("squeeze_unsqueeze", lambda x: pt.squeeze(pt.unsqueeze(x, 0), 0),
     (A23,), 0),
    ("cumsum", lambda x: pt.cumsum(x, axis=1), (A23,), 0),
    ("stack", lambda a, b: pt.stack([a, b], axis=0), (A23, B23), 1),
    # conv / pooling / norm (functional)
    ("conv2d_wrt_x", lambda x: F.conv2d(x, jnp.asarray(KER)), (IMG,), 0),
    ("conv2d_wrt_w", lambda w: F.conv2d(jnp.asarray(IMG), w), (KER,), 0),
    ("avg_pool2d", lambda x: F.avg_pool2d(x, 2), (IMG,), 0),
    ("max_pool2d", lambda x: F.max_pool2d(x, 2), (IMG,), 0),
    ("layer_norm", lambda x: F.layer_norm(x, (3,), jnp.ones(3),
                                          jnp.zeros(3)), (A23,), 0),
    ("interp_bilinear", lambda x: F.interpolate(
        x, size=[6, 6], mode="bilinear", align_corners=True), (IMG,), 0),
    ("grid_sample_like", lambda x: F.interpolate(
        x, scale_factor=2.0, mode="nearest"), (IMG,), 0),
]


@pytest.mark.parametrize("name,fn,args,idx", CASES,
                         ids=[c[0] for c in CASES])
def test_numeric_grad(name, fn, args, idx):
    check_grad(fn, args, arg_idx=idx)
