"""int8 KV-cache quantization (paddle_tpu/quantization + the cache
pytree plumbing in models/generation.py and serving/engine.py).

What must hold:

1. **Round-trip bound** — per-head abs-max int8 quantization's error is
   at most half a quantization step (``scale / 2``), and all-zero heads
   dequantize to exact zero;
2. **Byte accounting** — a quantized cache pytree is at most half the
   full-precision cache's bytes (the HBM-per-slot halving claim);
3. **Checkpoint/reshard** — the scales leaf lives alongside the int8
   values in the cache pytree, so ``save_state``/``load_state(
   shardings=...)`` reshards both together with dtypes preserved;
4. **Adapter compatibility** — a zero-initialized LoRA adapter on a
   QUANTIZED base projection is a bitwise no-op (B = 0), so serving a
   quantized base with idle adapters changes nothing;
5. **Bounded drift** — teacher-forced decode logits through an int8
   cache stay within a small relative error of the full-precision path.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.quantization import (is_quantized_kv, kv_dequantize,
                                     kv_quantize)


@pytest.fixture(scope="module")
def gpt_model():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


def test_roundtrip_error_within_half_step():
    x = np.random.default_rng(0).normal(
        0, 3.0, (2, 5, 3, 8)).astype(np.float32)
    q, scale = kv_quantize(jnp.asarray(x))
    assert q.dtype == jnp.int8 and q.shape == x.shape
    assert scale.dtype == jnp.float32 and scale.shape == (2, 5, 3, 1)
    deq = np.asarray(kv_dequantize(q, scale))
    # symmetric round-to-nearest: |err| <= scale / 2 per element
    bound = np.broadcast_to(np.asarray(scale) / 2 + 1e-7, x.shape)
    assert (np.abs(deq - x) <= bound).all()
    # relative error of the worst element stays small
    rel = np.abs(deq - x).max() / np.abs(x).max()
    assert rel < 0.01


def test_zero_head_dequantizes_to_exact_zero():
    x = jnp.zeros((1, 2, 2, 8), jnp.float32)
    q, scale = kv_quantize(x)
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(kv_dequantize(q, scale)) == 0.0).all()


def test_is_quantized_kv_predicate():
    x = jnp.ones((1, 2, 2, 4), jnp.float32)
    assert is_quantized_kv(kv_quantize(x))
    assert not is_quantized_kv(x)
    assert not is_quantized_kv((x, x))   # fp pair is not a quant entry


def test_cache_pytree_bytes_halved(gpt_model):
    from paddle_tpu.models.generation import cache_nbytes, init_cache

    model, _ = gpt_model
    full = cache_nbytes(init_cache(model, 4, 64))
    quant = cache_nbytes(init_cache(model, 4, 64, kv_dtype="int8"))
    assert quant <= full / 2, (
        f"int8 cache is {quant} bytes vs {full} full-precision — the "
        f"halving claim fails")


def test_serving_slot_bytes_halved(gpt_model):
    from paddle_tpu.serving.engine import ContinuousBatchingEngine

    model, _ = gpt_model
    full = ContinuousBatchingEngine(
        model, slots=2, max_length=64).cache_bytes_per_slot()
    quant = ContinuousBatchingEngine(
        model, slots=2, max_length=64,
        kv_dtype="int8").cache_bytes_per_slot()
    assert quant <= full / 2


def test_scales_reshard_alongside_cache(tmp_path):
    from paddle_tpu.distributed.checkpoint import load_state, save_state
    from paddle_tpu.distributed.mesh import init_mesh

    mesh = init_mesh(dp=2, mp=4)
    x = np.random.default_rng(1).normal(
        0, 1.0, (8, 16, 2, 8)).astype(np.float32)
    q, scale = kv_quantize(jnp.asarray(x))
    # the quantized pair shards over batch exactly like a fp cache leaf
    # (the trailing keepdim axis is why scales need no special casing)
    shard = NamedSharding(mesh, P("dp", None, None, None))
    state = {"k": jax.device_put(q, shard),
             "k_scale": jax.device_put(scale, shard)}
    d = str(tmp_path / "kv")
    save_state(state, d)
    # reload re-sliced onto a different axis layout: both leaves move
    # together, dtypes preserved
    target = NamedSharding(mesh, P("mp", None, None, None))
    out = load_state(d, shardings={"k": target, "k_scale": target})
    assert out["k"].dtype == jnp.int8
    assert out["k_scale"].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out["k"]), np.asarray(q))
    np.testing.assert_array_equal(np.asarray(out["k_scale"]),
                                  np.asarray(scale))
    assert tuple(out["k"].sharding.spec) == ("mp", None, None, None)
    assert tuple(out["k_scale"].sharding.spec) == ("mp", None, None, None)
    # dequant after the round trip reproduces the pre-save values
    np.testing.assert_array_equal(
        np.asarray(kv_dequantize(out["k"], out["k_scale"])),
        np.asarray(kv_dequantize(q, scale)))


def test_zero_adapter_noop_on_quantized_base():
    from paddle_tpu.lora import LoraConfig, apply_lora
    from paddle_tpu.quantization import QAT
    import paddle_tpu.nn as nn

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(16, 8)

        def forward(self, x):
            return self.proj(x)

    pt.seed(0)
    model = QAT().quantize(Head())   # proj becomes QuantedLinear
    model.eval()
    x = jnp.asarray(np.random.default_rng(2).normal(
        0, 1.0, (3, 16)).astype(np.float32))
    base = np.asarray(model(x))
    apply_lora(model, LoraConfig(rank=4, target_modules=("proj",)))
    with_adapter = np.asarray(model(x))
    # lora_B starts at zero: injection must be BITWISE invisible even
    # through the fake-quant forward
    np.testing.assert_array_equal(base, with_adapter)


def test_quantized_cache_logit_drift_bounded(gpt_model):
    from paddle_tpu.models.generation import init_cache
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)

    model, cfg = gpt_model
    params = param_state(model)
    buffers = buffer_state(model)
    ids = np.random.default_rng(3).integers(
        1, cfg.vocab_size, (2, 8)).astype(np.int32)
    full = init_cache(model, 2, 32)
    quant = init_cache(model, 2, 32, kv_dtype="int8")
    (lf, full), _ = functional_call(model, params, buffers,
                                    jnp.asarray(ids), cache=full,
                                    position_offset=0)
    (lq, quant), _ = functional_call(model, params, buffers,
                                     jnp.asarray(ids), cache=quant,
                                     position_offset=0)
    # prefill logits attend the un-quantized fresh block: bit-identical
    np.testing.assert_array_equal(np.asarray(lf[:, -1]),
                                  np.asarray(lq[:, -1]))
    # teacher-forced decode: replay the full-precision argmax chain
    # through both caches and bound the relative logit drift
    worst = 0.0
    tok = jnp.argmax(lf[:, -1], axis=-1).astype(jnp.int32)
    for step in range(4):
        (lf, full), _ = functional_call(
            model, params, buffers, tok[:, None], cache=full,
            position_offset=jnp.full((2,), 8 + step, jnp.int32))
        (lq, quant), _ = functional_call(
            model, params, buffers, tok[:, None], cache=quant,
            position_offset=jnp.full((2,), 8 + step, jnp.int32))
        a, b = np.asarray(lf[:, -1]), np.asarray(lq[:, -1])
        worst = max(worst, np.abs(a - b).max() / max(np.abs(a).max(),
                                                     1e-9))
        tok = jnp.argmax(lf[:, -1], axis=-1).astype(jnp.int32)
    assert worst < 0.05, f"int8 KV logit drift {worst} exceeds 5%"
