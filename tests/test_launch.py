"""Launcher tests: KV store wait/barrier, Pod supervision, CLI spawn with
worker env, elastic restart — the reference's launch-CLI shell tests
(``test_fleet_launch_*.sh``, SURVEY.md §4) in-process.
"""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import KVClient, KVServer, launch
from paddle_tpu.distributed.launch.job import Container, Pod


def test_kv_put_get_wait_barrier():
    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}")
        assert kv.get("missing") is None
        kv.put("a/b", "hello")
        assert kv.get("a/b") == "hello"
        assert kv.wait("a/b", timeout=1) == "hello"
        with pytest.raises(TimeoutError):
            kv.wait("never", timeout=0.5)
        kv.barrier("sync", rank=0, world=1, timeout=2)


def test_pod_success_and_failure(tmp_path):
    pod = Pod()
    pod.add(Container([sys.executable, "-c", "print('w0')"], {},
                      str(tmp_path / "w0.log")))
    pod.add(Container([sys.executable, "-c", "print('w1')"], {},
                      str(tmp_path / "w1.log")))
    pod.deploy()
    assert pod.join() == 0
    assert "w0" in (tmp_path / "w0.log").read_text()

    bad = Pod()
    bad.add(Container([sys.executable, "-c", "import sys; sys.exit(3)"], {}))
    bad.add(Container([sys.executable, "-c", "import time; time.sleep(60)"], {}))
    bad.deploy()
    assert bad.join() == 3  # failure propagates, peer terminated
    assert all(not c.alive for c in bad.containers)


def test_launch_sets_worker_env(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os
        print("RANK", os.environ["PADDLE_TRAINER_ID"],
              "WORLD", os.environ["PADDLE_TRAINERS_NUM"],
              "LOCAL", os.environ["PADDLE_LOCAL_RANK"], flush=True)
    """))
    log_dir = str(tmp_path / "logs")
    rc = launch(["--nproc_per_node", "2", "--log_dir", log_dir, str(script)])
    assert rc == 0
    logs = sorted(os.listdir(log_dir))
    assert logs == ["worker.0.log", "worker.1.log"]
    t0 = open(os.path.join(log_dir, "worker.0.log")).read()
    t1 = open(os.path.join(log_dir, "worker.1.log")).read()
    assert "RANK 0 WORLD 2 LOCAL 0" in t0
    assert "RANK 1 WORLD 2 LOCAL 1" in t1


def test_launch_elastic_restart(tmp_path):
    """Worker fails on first attempt, succeeds after restart (state via a
    sentinel file) — the ElasticManager relaunch path."""
    sentinel = tmp_path / "tried"
    script = tmp_path / "flaky.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        s = {str(repr(str(sentinel)))}
        if not os.path.exists(s):
            open(s, "w").close()
            sys.exit(7)
        print("recovered", flush=True)
    """))
    log_dir = str(tmp_path / "logs")
    rc = launch(["--max_restarts", "2", "--log_dir", log_dir, str(script)])
    assert rc == 0
    assert "recovered" in open(os.path.join(log_dir, "worker.0.log")).read()


def test_launch_failure_exit_code(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(9)")
    rc = launch([str(script)])
    assert rc == 9


def test_cli_module_entry(tmp_path):
    script = tmp_path / "ok.py"
    script.write_text("print('cli ok')")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch", str(script)],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
