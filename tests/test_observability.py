"""Unified telemetry: metrics registry, request-scoped tracing, flight
recorder (paddle_tpu/observability/ + the wiring through serving,
profiler, supervisor and tools/trace_view.py).

The tentpole acceptance lives here: one served request yields a single
merged chrome-trace lane spanning router submit → queue wait → prefill
(bucket/prefix tags) → per-token decode → stream end, keyed by its
correlation id; and a crash drill (FaultPlan engine reset) emits a
flight-recorder dump carrying that id.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.observability import (MetricsRegistry, default_registry,
                                      flight, tracing)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

GEO = dict(max_length=64, prefill_buckets=(16,))


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


@pytest.fixture(scope="module")
def fleet(lm):
    from paddle_tpu.serving import InferenceServer, ReplicaRouter

    model, _ = lm
    srv = InferenceServer(model, slots=2, max_queue_depth=8,
                          max_request_retries=1, **GEO)
    router = ReplicaRouter()
    router.add_replica(srv, "r0")
    yield router, srv
    try:
        router.shutdown(drain=False, timeout=30)
    except Exception:
        pass


def _prompt(cfg, n, seed=0):
    return np.random.default_rng(seed).integers(
        0, cfg.vocab_size, (n,)).astype(np.int32)


@pytest.fixture(autouse=True)
def _restore_flight_dir():
    """Tests repoint the GLOBAL flight recorder at their tmp dirs;
    later test files must get the session default back."""
    rec = flight.flight_recorder()
    saved = rec.dump_dir
    yield
    flight.configure(dump_dir=saved)


# ------------------------------------------------------------- registry
def test_registry_counters_gauges_labels():
    r = MetricsRegistry()
    assert r.inc("req", 2) == 2
    assert r.inc("req", 3) == 5
    r.inc("req", 1, replica="a")
    r.set_gauge("depth", 7, replica="a")
    snap = r.snapshot()
    assert snap["counters"]["req"] == 5
    assert snap["counters"]['req{replica="a"}'] == 1
    assert snap["gauges"]['depth{replica="a"}'] == 7


def test_registry_histogram_percentiles():
    r = MetricsRegistry()
    for v in range(100):
        r.observe("lat", v / 1000.0)
    s = r.snapshot()["histograms"]["lat"]
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(0.0495, abs=0.002)
    assert s["p99"] == pytest.approx(0.099, abs=0.002)
    assert s["max"] == pytest.approx(0.099)


def test_registry_collector_absorbs_and_flattens():
    r = MetricsRegistry()
    r.register_collector(
        lambda: {"gauges": {"pool": {"occupancy": 0.5, "name": "x"}},
                 "counters": {"hits": 3}},
        labels={"server": "s0"}, name="c")
    snap = r.snapshot()
    assert snap["gauges"]['pool.occupancy{server="s0"}'] == 0.5
    assert snap["counters"]['hits{server="s0"}'] == 3
    # non-numeric leaves are dropped from the scrape
    assert not any("pool.name" in k for k in snap["gauges"])
    assert r.unregister_collector("c") == 1
    assert 'hits{server="s0"}' not in r.snapshot()["counters"]


def test_registry_weak_collector_prunes_dead_owner():
    r = MetricsRegistry()

    class Owner:
        def collect(self):
            return {"gauges": {"alive": 1}}

    o = Owner()
    r.register_collector(o.collect, name="owner")
    assert r.snapshot()["gauges"].get("alive") == 1
    del o
    import gc

    gc.collect()
    assert "alive" not in r.snapshot()["gauges"]


def test_registry_prometheus_text_format():
    r = MetricsRegistry()
    r.inc("serving.requests_completed", 4, server="s0")
    r.set_gauge("queue-depth", 2)
    for v in (0.01, 0.02, 0.03):
        r.observe("ttft", v)
    text = r.prometheus_text()
    assert "# TYPE serving_requests_completed counter" in text
    assert 'serving_requests_completed{server="s0"} 4' in text
    assert "# TYPE queue_depth gauge" in text
    assert 'ttft{quantile="0.5"}' in text
    assert "ttft_count 3" in text
    # collector errors don't break the scrape
    r.register_collector(lambda: 1 / 0, name="boom")
    assert "queue_depth 2" in r.prometheus_text()
    assert r.collector_errors >= 1


def test_default_registry_absorbs_profiler_counters():
    from paddle_tpu import profiler

    profiler.bump_counter("obs.test_counter", 5)
    snap = default_registry().snapshot()
    assert snap["counters"]["obs.test_counter"] >= 5
    assert "compile_cache.compiles" in snap["gauges"]
    json.dumps(snap)   # the whole snapshot must be JSON-able


# -------------------------------------------------------------- tracing
def test_correlation_ids_unique_and_scoped():
    a, b = tracing.new_correlation_id(), tracing.new_correlation_id()
    assert a != b and a.startswith("req-")
    assert tracing.current() is None or isinstance(tracing.current(), str)
    with tracing.correlate("corr-x"):
        assert tracing.current() == "corr-x"
        with tracing.span("inner", tag=1):
            pass
    spans = tracing.spans(corr="corr-x", name="inner")
    assert len(spans) == 1 and spans[0]["tags"] == {"tag": 1}


def test_trace_buffer_bounded_counts_drops():
    from paddle_tpu.observability.tracing import _TraceBuffer

    buf = _TraceBuffer(capacity=4)
    # swap in a tiny buffer so the bound is testable without 65k appends
    saved = tracing._buf
    tracing._buf = buf
    try:
        for i in range(10):
            tracing.record_event(f"e{i}")
        st = tracing.stats()
        assert st["buffered"] == 4 and st["dropped"] == 6
        assert st["recorded"] == 10
        assert [s["name"] for s in tracing.spans()] == [
            "e6", "e7", "e8", "e9"]
    finally:
        tracing._buf = saved


def test_tracing_disabled_records_nothing():
    tracing.enable(False)
    try:
        before = tracing.stats()["recorded"]
        tracing.record_event("nope")
        with tracing.span("nope2"):
            pass
        assert tracing.stats()["recorded"] == before
    finally:
        tracing.enable(True)


def test_chrome_trace_one_lane_per_correlation():
    recs = [
        {"name": "a", "corr": "c1", "t0": 1.0, "t1": 2.0, "tags": {}},
        {"name": "b", "corr": "c1", "t0": 2.0, "t1": 2.0, "tags": {}},
        {"name": "c", "corr": "c2", "t0": 1.5, "t1": 1.8, "tags": {}},
        {"name": "d", "corr": None, "t0": 0.0, "t1": 0.5, "tags": {}},
    ]
    ct = tracing.chrome_trace(span_records=recs)
    data = [e for e in ct["traceEvents"] if e["ph"] in ("X", "i")]
    lanes = {e["args"].get("correlation_id", "untraced"): e["tid"]
             for e in data}
    assert lanes["c1"] != lanes["c2"] != lanes["untraced"]
    assert lanes["untraced"] == 0
    names = {e["args"]["name"] for e in ct["traceEvents"]
             if e.get("name") == "thread_name"}
    assert {"c1", "c2", "untraced"} <= names
    # durations in microseconds; instants use ph "i"
    a = next(e for e in data if e["name"] == "a")
    assert a["ph"] == "X" and a["dur"] == pytest.approx(1e6)
    b = next(e for e in data if e["name"] == "b")
    assert b["ph"] == "i"


def test_export_chrome_trace_writes_file(tmp_path):
    with tracing.correlate(tracing.new_correlation_id("exp")) as corr:
        with tracing.span("phase"):
            pass
    path = tracing.export_chrome_trace(
        str(tmp_path / "trace.json"), corr=corr)
    with open(path) as f:
        obj = json.load(f)
    assert any(e.get("name") == "phase" for e in obj["traceEvents"])


# ------------------------------------------------------------- profiler
def test_profiler_counts_dropped_spans_and_surfaces_them():
    from paddle_tpu import profiler
    from paddle_tpu.profiler import _HostEventRecorder

    saved = profiler._recorder
    rec = _HostEventRecorder(capacity=4)
    rec.enabled = True
    profiler._recorder = rec
    try:
        base = profiler.counter_values().get("profiler.spans_dropped", 0)
        for i in range(10):
            with profiler.RecordEvent("spin"):
                pass
        assert rec.dropped == 6
        got = profiler.counter_values()["profiler.spans_dropped"]
        assert got == base + 6
        rows = profiler.host_event_summary()
        assert rows["(dropped spans)"][0] == 6
    finally:
        profiler._recorder = saved


def test_host_event_summary_percentile_columns():
    from paddle_tpu import profiler
    from paddle_tpu.profiler import _HostEventRecorder

    saved = profiler._recorder
    rec = _HostEventRecorder()
    profiler._recorder = rec
    try:
        for i in range(1, 11):
            rec.record("op", 0.0, i / 100.0)   # 10ms..100ms
        rows = profiler.host_event_summary(percentiles=(50, 99))
        calls, total, avg, mx, p50, p99 = rows["op"]
        assert calls == 10 and mx == pytest.approx(0.10)
        assert p50 == pytest.approx(0.06, abs=0.011)
        assert p99 == pytest.approx(0.10, abs=0.011)
        # default stays the 4-tuple shape existing consumers unpack
        assert len(profiler.host_event_summary()["op"]) == 4
    finally:
        profiler._recorder = saved


# ------------------------------------------------------ flight recorder
def test_flight_recorder_ring_and_dump(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder

    rec = FlightRecorder(capacity=3, dump_dir=str(tmp_path))
    for i in range(5):
        rec.note("ev", corr=f"c{i}", detail=i)
    evs = rec.events()
    assert len(evs) == 3 and evs[0]["detail"] == 2  # oldest rolled off
    path = rec.dump("unit_test", corr="c4", extra={"k": "v"})
    with open(path) as f:
        dump = json.load(f)
    assert dump["format"] == "flight_recorder"
    assert dump["reason"] == "unit_test"
    assert dump["correlation_id"] == "c4"
    assert dump["extra"] == {"k": "v"}
    assert [e["corr"] for e in dump["events"]] == ["c2", "c3", "c4"]
    assert isinstance(dump["spans"], list)
    assert isinstance(dump["counters"], dict)
    assert rec.stats()["dumps_written"] == 1


def test_flight_recorder_dump_budget(tmp_path):
    from paddle_tpu.observability.flight import FlightRecorder

    rec = FlightRecorder(dump_dir=str(tmp_path), max_dumps=2)
    assert rec.dump("a") and rec.dump("b")
    assert rec.dump("c") is None
    st = rec.stats()
    assert st["dumps_written"] == 2 and st["dumps_skipped"] == 1


def test_hang_watchdog_dumps_flight_artifact(tmp_path):
    from paddle_tpu.framework.supervisor import HangWatchdog

    import warnings

    flight.configure(dump_dir=str(tmp_path))
    before = flight.flight_recorder().stats()["dumps_written"]
    wd = HangWatchdog(step_timeout=0.05, action="warn")
    with warnings.catch_warnings():
        # the watcher thread warns through the (global) filter state
        warnings.simplefilter("ignore", RuntimeWarning)
        wd.start()
        wd.beat()
        deadline = time.monotonic() + 5.0
        while wd.hangs_detected == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        wd.stop()
    assert wd.hangs_detected == 1
    rec = flight.flight_recorder()
    assert rec.stats()["dumps_written"] == before + 1
    with open(rec.stats()["last_dump_path"]) as f:
        dump = json.load(f)
    assert dump["reason"] == "hang"
    assert dump["extra"]["step_timeout_s"] == pytest.approx(0.05)


def test_supervisor_before_batch_stamps_train_corr(tmp_path):
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)

    class FakeStep:
        _count = 41

        def state_dict(self):
            return {}

    sup = TrainingSupervisor(
        FakeStep(), RecoveryPolicy(checkpoint_dir=str(tmp_path),
                                   preemption=False))
    prev = tracing.current()
    try:
        sup.before_batch()
        assert tracing.current() == f"train-{os.getpid():x}-s41"
    finally:
        tracing.set_current(prev)
        sup.stop()


# ------------------------------------------------- serving end-to-end
def test_served_request_yields_one_trace_lane(lm, fleet):
    """THE acceptance test: router submit → queue wait → prefill (with
    bucket tag) → per-token decode → stream end, one lane, one corr."""
    model, cfg = lm
    router, srv = fleet
    p = _prompt(cfg, 9, seed=1)
    h = router.submit(p, max_new_tokens=5)
    out = h.result(timeout=300)
    assert out.shape[0] == 5
    corr = h.correlation_id
    assert corr and corr == h._current().correlation_id
    spans = tracing.spans(corr=corr)
    names = [s["name"] for s in spans]
    for expected in ("submit", "router:submit", "queue_wait", "prefill",
                     "decode", "stream_end"):
        assert expected in names, f"missing {expected} in {names}"
    assert names.count("decode") == 4   # 5 tokens = prefill + 4 decode
    prefill = next(s for s in spans if s["name"] == "prefill")
    assert prefill["tags"]["bucket"] == 16
    assert prefill["tags"]["prompt_len"] == 9
    ct = tracing.chrome_trace(corr=corr)
    lanes = {e["tid"] for e in ct["traceEvents"] if e["ph"] in ("X", "i")}
    assert len(lanes) == 1          # ONE merged lane for the request
    # a second request gets its own id and its own lane
    h2 = router.submit(_prompt(cfg, 6, seed=2), max_new_tokens=3)
    h2.result(timeout=300)
    assert h2.correlation_id != corr
    assert tracing.spans(corr=h2.correlation_id, name="stream_end")


def test_registry_scrape_carries_serving_and_introspection(lm, fleet):
    model, cfg = lm
    router, srv = fleet
    snap = default_registry().snapshot()
    completed = [v for k, v in snap["counters"].items()
                 if k.startswith("serving.requests_completed")]
    assert completed and max(completed) >= 1
    label = srv._obs_label
    assert snap["gauges"][f'serving.slots{{server="{label}"}}'] == 2
    text = srv.metrics_text()
    assert "# TYPE serving_requests_completed counter" in text
    assert f'server="{label}"' in text
    sz = srv.statusz()
    assert sz["queue_depth"] == 0
    assert sz["snapshot"]["requests_completed"] >= 1
    assert sz["trace"]["enabled"] is True
    rz = router.statusz()
    assert rz["replicas"] == {"r0": "active"}
    assert "requests_routed" in rz["snapshot"]


def test_crash_drill_dump_carries_failing_corr(lm, tmp_path):
    """Engine-reset drill (FaultPlan at serve.step): the flight dump
    must exist, be well formed, and carry the failing request's
    correlation id in its inflight list AND its span tail."""
    from flight_drill import run_drill

    model, _ = lm
    result = run_drill(str(tmp_path), new_tokens=5, model=model)
    assert result["fault_fired"], result
    assert result["ok"], result
    with open(result["dump_path"]) as f:
        dump = json.load(f)
    assert result["correlation_id"] in dump["extra"]["inflight"]
    kinds = [e["kind"] for e in dump["events"]]
    assert "engine_reset" in kinds


def test_trace_view_merges_replica_dumps_by_corr(tmp_path):
    """Two replica dumps sharing a correlation id merge into ONE lane."""
    from trace_view import list_correlations, load_spans, main

    corr = "req-merge-000042"
    for i, name in enumerate(("router", "replica")):
        dump = {"format": "flight_recorder", "version": 1,
                "reason": "test", "time": 0.0, "pid": 100 + i,
                "host": "h", "correlation_id": corr,
                "events": [{"t": 1.0 + i, "kind": "compile"}],
                "spans": [{"name": f"{name}:phase", "corr": corr,
                           "t0": 1.0 + i, "t1": 1.5 + i, "tags": {}},
                          {"name": "other", "corr": f"req-other-{i}",
                           "t0": 0.5, "t1": 0.6, "tags": {}}],
                "counters": {}, "metrics": None}
        with open(tmp_path / f"{name}.json", "w") as f:
            json.dump(dump, f)
    files = [str(tmp_path / "router.json"), str(tmp_path / "replica.json")]
    spans = []
    for p in files:
        got, kind = load_spans(p)
        assert kind == "flight"
        spans.extend(got)
    rows = {e["corr"]: e for e in list_correlations(spans)}
    assert rows[corr]["spans"] == 2
    assert sorted(rows[corr]["names"]) == ["replica:phase", "router:phase"]
    out = str(tmp_path / "merged.json")
    assert main(files + ["-o", out, "--corr", corr]) == 0
    with open(out) as f:
        merged = json.load(f)
    data = [e for e in merged["traceEvents"] if e["ph"] in ("X", "i")]
    # both replicas' spans, one lane; the other corrs filtered out
    assert {e["name"] for e in data} == {"router:phase", "replica:phase"}
    assert len({e["tid"] for e in data}) == 1


def test_compile_events_reach_flight_ring(lm, fleet):
    """compile_cache.record_trace lands compile events in the flight
    ring — the first thing a postmortem wants to rule out."""
    kinds = [e["kind"] for e in flight.flight_recorder().events()]
    assert "compile" in kinds     # the fleet fixture compiled programs


def test_serving_metrics_snapshot_keys_preserved(lm, fleet):
    """MIGRATION guarantee: the registry absorption did not change the
    ServingMetrics.snapshot() shape serve_bench/router roll-ups parse."""
    _, srv = fleet
    snap = srv.snapshot()
    for key in ("requests_submitted", "requests_completed",
                "tokens_emitted", "slot_occupancy", "ttft",
                "inter_token", "queue_wait", "prefix_hit_rate",
                "compile_stats"):
        assert key in snap, key
