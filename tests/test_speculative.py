"""Draft-model speculative decoding (models/speculative.py).

The load-bearing guarantees:

1. **Exactness** — greedy speculative decode is token-identical to the
   solo :class:`GenerationEngine` (the Leviathan accept rule degenerates
   to ``d_i == argmax``), and eos handling matches the solo done-mask;
2. **Determinism** — a fixed seed replays the same tokens AND the same
   per-round acceptance trace (the per-(stream, position, row) key
   discipline: restructuring the round must not move a single draw);
3. **Compile discipline** — a generate() across both prefill buckets
   compiles exactly ``2 * #buckets + 1`` programs (target prefill +
   draft prefill per bucket, ONE fused decode round) and the steady
   state compiles nothing.

Tier-1 budget: one module-scoped gpt_tiny target + 1-layer draft; the
greedy tests share one engine's compiled programs.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.framework import compile_cache

GEO = dict(max_length=64, prefill_buckets=(16, 32))


@pytest.fixture(scope="module")
def target_model():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


@pytest.fixture(scope="module")
def draft_model(target_model):
    from paddle_tpu.models.speculative import build_draft_model

    model, _ = target_model
    return build_draft_model(model, num_layers=1)


@pytest.fixture(scope="module")
def engine(target_model, draft_model):
    from paddle_tpu.models.speculative import SpeculativeEngine

    model, _ = target_model
    return SpeculativeEngine(model, draft_model, k=4, **GEO)


@pytest.fixture(scope="module")
def solo(target_model):
    from paddle_tpu.models.generation import GenerationEngine

    model, _ = target_model
    return GenerationEngine(model, **GEO)


def _prompt(rows=3, length=12, seed=7):
    return np.random.default_rng(seed).integers(
        1, 64, (rows, length)).astype(np.int32)


def test_greedy_parity_with_solo(engine, solo):
    ids = _prompt()
    ref = solo.generate(ids, max_new_tokens=20)
    out = engine.generate(ids, max_new_tokens=20)
    np.testing.assert_array_equal(ref, out)


def test_greedy_parity_second_bucket(engine, solo):
    ids = _prompt(rows=2, length=24, seed=3)   # falls in the 32 bucket
    ref = solo.generate(ids, max_new_tokens=16)
    out = engine.generate(ids, max_new_tokens=16)
    np.testing.assert_array_equal(ref, out)


def test_eos_parity_with_solo(engine, solo):
    ids = _prompt()
    ref_free = solo.generate(ids, max_new_tokens=20)
    eos = int(ref_free[0, 5])   # a token the free run actually emits
    ref = solo.generate(ids, max_new_tokens=20, eos_token_id=eos)
    out = engine.generate(ids, max_new_tokens=20, eos_token_id=eos)
    np.testing.assert_array_equal(ref, out)


def test_fixed_seed_replay_deterministic(engine):
    ids = _prompt()
    kw = dict(max_new_tokens=20, do_sample=True, temperature=0.9,
              top_k=20, seed=123, return_stats=True)
    o1, s1 = engine.generate(ids, **kw)
    o2, s2 = engine.generate(ids, **kw)
    np.testing.assert_array_equal(o1, o2)
    t1, t2 = s1["acceptance_trace"], s2["acceptance_trace"]
    assert len(t1) == len(t2) == s1["rounds"]
    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(a, b)
    # trace rows are per-round emit counts in 0..K+1, B wide
    assert all(r.shape == (ids.shape[0],) for r in t1)
    assert all(0 <= int(v) <= engine.k + 1 for r in t1 for v in r)


def test_self_draft_accepts_everything(target_model):
    from paddle_tpu.models.speculative import SpeculativeEngine

    model, _ = target_model
    eng = SpeculativeEngine(model, model, k=4, **GEO)
    _, stats = eng.generate(_prompt(), max_new_tokens=16,
                            return_stats=True)
    assert stats["acceptance_rate"] == pytest.approx(1.0)
    # every round emits the full window: K accepted + the bonus token
    assert stats["tokens_per_target_dispatch"] > eng.k


def test_compile_budget(target_model, draft_model):
    from paddle_tpu.models.speculative import SpeculativeEngine

    model, _ = target_model
    eng = SpeculativeEngine(model, draft_model, k=4, **GEO)
    before = compile_cache.cache_stats()["compiles"]
    for plen in (12, 24):                       # spans both buckets
        eng.generate(_prompt(rows=2, length=plen), max_new_tokens=8)
    compiled = compile_cache.cache_stats()["compiles"] - before
    budget = 2 * len(GEO["prefill_buckets"]) + 1
    assert compiled == budget, (
        f"{compiled} programs for 2 buckets (budget {budget} = "
        f"2 prefill families + one fused decode round)")
    # steady state: same shapes compile nothing
    for plen in (12, 24):
        eng.generate(_prompt(rows=2, length=plen), max_new_tokens=8)
    assert compile_cache.cache_stats()["compiles"] - before == budget
    per_family = eng.cache_stats()
    assert per_family["decode_round"]["compiles"] == 1


def test_int8_kv_replay_and_greedy(target_model, draft_model):
    from paddle_tpu.models.speculative import SpeculativeEngine

    model, _ = target_model
    eng = SpeculativeEngine(model, draft_model, k=4, kv_dtype="int8",
                            **GEO)
    ids = _prompt()
    a = eng.generate(ids, max_new_tokens=16, do_sample=True, seed=5)
    b = eng.generate(ids, max_new_tokens=16, do_sample=True, seed=5)
    np.testing.assert_array_equal(a, b)


def test_validation():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
    from paddle_tpu.models.speculative import SpeculativeEngine

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny(use_flash_attention=False))
    with pytest.raises(ValueError, match="k must be >= 1"):
        SpeculativeEngine(model, model, k=0, **GEO)
    eng = SpeculativeEngine(model, model, k=8, **GEO)
    # the last verify window must fit in max_length
    with pytest.raises(ValueError, match="exceeds max_length"):
        eng.generate(_prompt(length=12), max_new_tokens=60)
