"""Multi-tenant LoRA (paddle_tpu/lora + serving integration).

The acceptance contract:

1. **Per-tenant exactness** — a live batch mixing three adapters plus
   the base model produces, for EVERY stream, exactly the tokens a solo
   single-adapter ``generate()`` with the same seed produces (greedy and
   seeded sampling);
2. **Compile discipline** — with adapters enabled the serving loop still
   holds at ``#prefill_buckets + 1`` programs, and adapter load/evict
   churn (an ``AdapterStore`` buffer update) triggers ZERO compiles;
3. **Registry safety** — LRU eviction is deterministic and reload is
   bit-exact; pinned rows (live requests) never evict; a full-model
   checkpoint is refused as an adapter and vice versa; an adapter
   refuses to load onto a mismatched base (fingerprint);
4. **Frozen-base training** — ``Model.fit(lora=...)`` moves only the
   adapter pytree; base params stay bitwise identical and optimizer
   state scales with the rank.

Tier-1 budget discipline: ONE module-scoped injected model + store +
server (ONE prefill bucket => two serving programs) shared by all
integration tests; registry/metrics/router tests are device-free or
device-light.
"""
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.lora import (AdapterError, AdapterFormatError, AdapterStore,
                             LoraConfig, apply_lora, applied_config,
                             base_fingerprint, clear_adapter, is_lora_param,
                             load_adapter, lora_state, save_adapter,
                             set_adapter)
from paddle_tpu.serving import InferenceServer
from paddle_tpu.serving.metrics import ServingMetrics

GEO = dict(max_length=48, prefill_buckets=(12,))
LCFG = LoraConfig(rank=4, alpha=8.0)


def _tiny_cfg(**over):
    from paddle_tpu.models.gpt import gpt_tiny

    base = dict(hidden_size=64, num_layers=2, num_heads=2, vocab_size=256,
                max_position_embeddings=64, hidden_dropout_prob=0.0,
                attention_dropout_prob=0.0, use_flash_attention=False)
    base.update(over)
    return gpt_tiny(**base)


@pytest.fixture(scope="module")
def lm():
    from paddle_tpu.models.gpt import GPTForCausalLM

    pt.seed(7)
    model = GPTForCausalLM(_tiny_cfg())
    model.eval()
    base_out_params = {k: np.asarray(v) for k, v in model.named_parameters()}
    apply_lora(model, LCFG)
    return model, base_out_params


@pytest.fixture(scope="module")
def tenants(lm):
    model, _ = lm
    rng = np.random.default_rng(42)
    zero = lora_state(model)
    return {t: {k: rng.normal(0, 0.04, v.shape).astype(np.float32)
                for k, v in zero.items()}
            for t in ("t0", "t1", "t2")}


@pytest.fixture(scope="module")
def store(lm, tenants):
    model, _ = lm
    st = AdapterStore(model, max_loaded=4)
    for name, tree in tenants.items():
        st.register(name, tree)
    return st


@pytest.fixture(scope="module")
def server(lm, store):
    model, _ = lm
    srv = InferenceServer(model, slots=3, adapter_store=store,
                          max_queue_depth=16, **GEO)
    yield srv
    try:
        srv.shutdown(drain=False, timeout=30)
    except Exception:
        pass


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(0, 256, (n,)).astype(
        np.int32)


# ------------------------------------------------------------ unit: config
def test_lora_config_validation():
    with pytest.raises(ValueError):
        LoraConfig(rank=0)
    with pytest.raises(ValueError):
        LoraConfig(dropout=1.0)
    cfg = LoraConfig(rank=8, alpha=16.0, target_modules=["q_proj"])
    assert cfg.scaling == 2.0
    assert cfg.target_modules == ("q_proj",)
    assert is_lora_param("gpt.h.0.attn.qkv_proj.lora_A")
    assert not is_lora_param("gpt.h.0.attn.qkv_proj.weight")


def test_apply_lora_idempotent_and_conflicts(lm):
    model, _ = lm
    assert applied_config(model) == LCFG
    apply_lora(model, LCFG)  # same config: no-op
    with pytest.raises(ValueError, match="refusing to stack"):
        apply_lora(model, LoraConfig(rank=2))
    # a model with neither lora_spec nor explicit targets is rejected
    from paddle_tpu.nn.layer import Layer

    class Bare(Layer):
        pass

    with pytest.raises(ValueError, match="target_modules"):
        apply_lora(Bare(), LoraConfig())
    with pytest.raises(ValueError, match="matched"):
        apply_lora(Bare(), LoraConfig(target_modules=("nope",)))


def test_injection_is_base_identical_until_trained(lm):
    """B = 0 at injection: bitwise no-op; set_adapter changes outputs;
    clear_adapter restores base bitwise."""
    from paddle_tpu.models.gpt import GPTForCausalLM

    pt.seed(7)
    fresh = GPTForCausalLM(_tiny_cfg())
    fresh.eval()
    x = _prompt(8, 0)[None]
    base = np.asarray(fresh(x))
    apply_lora(fresh, LCFG)
    assert np.array_equal(base, np.asarray(fresh(x)))
    rng = np.random.default_rng(5)
    set_adapter(fresh, {k: rng.normal(0, 0.05, v.shape).astype(np.float32)
                        for k, v in lora_state(fresh).items()})
    assert not np.array_equal(base, np.asarray(fresh(x)))
    clear_adapter(fresh)
    assert np.array_equal(base, np.asarray(fresh(x)))


def test_set_adapter_rejects_mismatch(lm, tenants):
    model, _ = lm
    good = tenants["t0"]
    with pytest.raises(ValueError, match="missing"):
        set_adapter(model, dict(list(good.items())[:-1]))
    k0 = next(iter(good))
    with pytest.raises(ValueError, match="shape"):
        set_adapter(model, {**good, k0: np.zeros((3, 3), np.float32)})
    clear_adapter(model)


# -------------------------------------------------------- training (fit)
@pytest.mark.slow   # ~13s fit() train-step compile (tier-1 report)
def test_fit_trains_only_adapter_pytree():
    from paddle_tpu import hapi
    from paddle_tpu.models.gpt import GPTForCausalLM
    from paddle_tpu.optimizer import Adam

    pt.seed(3)
    net = GPTForCausalLM(_tiny_cfg())
    base_before = {k: np.asarray(v) for k, v in net.named_parameters()}
    m = hapi.Model(net)
    m.prepare(optimizer=Adam(learning_rate=1e-2, parameters=[]),
              loss=lambda out, labels: net.loss(out, labels))
    data = [(_prompt(10, i).reshape(2, 5),) * 2 for i in range(3)]
    m.fit(data, epochs=2, verbose=0, lora=LoraConfig(rank=2, alpha=4.0))
    step = m._train_step
    # only adapter leaves are optimized...
    assert all(is_lora_param(k) for k in step.params)
    # ...the frozen base rides the buffers bitwise unchanged...
    for k, v in base_before.items():
        assert np.array_equal(v, np.asarray(step.buffers[k])), k
    # ...the adapter actually moved...
    assert any(not np.allclose(np.asarray(v), 0.0)
               for k, v in step.params.items() if k.endswith("lora_B"))
    # ...and optimizer state is rank-sized, not model-sized
    import jax

    opt_floats = sum(int(np.prod(l.shape)) for l in
                     jax.tree_util.tree_leaves(step.opt_state)
                     if hasattr(l, "shape"))
    model_floats = sum(int(np.prod(v.shape)) for v in base_before.values())
    assert opt_floats < model_floats / 10
    # a later PLAIN fit must not silently keep the base frozen
    m.fit(data, epochs=1, verbose=0)
    step2 = m._train_step
    assert step2 is not step and step2._trainable is None
    assert any(not np.array_equal(base_before[k], np.asarray(v))
               for k, v in step2.params.items() if k in base_before)


# -------------------------------------------------- registry: disk format
def test_adapter_save_load_roundtrip(lm, tenants, tmp_path):
    model, _ = lm
    set_adapter(model, tenants["t0"])
    d = str(tmp_path / "t0")
    save_adapter(d, model)
    clear_adapter(model)
    state, meta = load_adapter(d, model)
    assert meta["rank"] == LCFG.rank
    assert meta["base_fingerprint"] == base_fingerprint(model)
    for k, v in state.items():
        assert np.allclose(np.asarray(v), tenants["t0"][k]), k


def test_format_guards_both_directions(lm, tmp_path):
    """An adapter checkpoint refuses to restore a full model; a full
    checkpoint refuses to load as an adapter."""
    from paddle_tpu.distributed.checkpoint import load_state, save_state

    model, _ = lm
    adir = str(tmp_path / "adapter")
    save_adapter(adir, model)
    # adapter -> full-model restore: named ValueError, not missing-leaves
    with pytest.raises(ValueError, match="LoRA ADAPTER checkpoint"):
        load_state(adir, template=dict(model.state_dict()))
    # full -> adapter loader: AdapterFormatError
    fdir = str(tmp_path / "full")
    save_state(dict(model.state_dict()), fdir)
    with pytest.raises(AdapterFormatError, match="not a LoRA adapter"):
        load_adapter(fdir, model)
    with pytest.raises(AdapterFormatError):
        AdapterStore(model, max_loaded=2).load("x", fdir)


def test_fingerprint_mismatch_rejected(lm, tmp_path):
    """An adapter saved against one base hard-fails onto another
    architecture."""
    from paddle_tpu.models.gpt import GPTForCausalLM

    model, _ = lm
    adir = str(tmp_path / "t")
    save_adapter(adir, model)
    pt.seed(9)
    other = GPTForCausalLM(_tiny_cfg(hidden_size=32, num_heads=2))
    apply_lora(other, LCFG)
    with pytest.raises(AdapterFormatError, match="fingerprint"):
        load_adapter(adir, other)
    # geometry mismatch is equally fatal even on the right base
    pt.seed(7)
    same_arch = GPTForCausalLM(_tiny_cfg())
    apply_lora(same_arch, LoraConfig(rank=2, alpha=8.0))
    with pytest.raises(AdapterFormatError, match="rank"):
        load_adapter(adir, same_arch)


# --------------------------------------------------- registry: residency
def test_store_lru_eviction_and_reload_determinism(lm, tenants):
    model, _ = lm
    st = AdapterStore(model, max_loaded=2)
    for name, tree in tenants.items():
        st.register(name, tree)

    def pages_of(name):
        row = st.loaded()[name]
        return {p: (np.asarray(a[row]), np.asarray(b[row]))
                for p, (a, b) in st.tensors.items()}

    s0 = st.acquire("t0"); st.release(s0)
    first_pages = pages_of("t0")
    s1 = st.acquire("t1"); st.release(s1)
    assert set(st.loaded()) == {"t0", "t1"}
    # t2 must evict the LRU resident (t0)
    s2 = st.acquire("t2"); st.release(s2)
    assert set(st.loaded()) == {"t1", "t2"}
    assert st.stats()["evictions"] == 1
    # reload of the evicted adapter is bit-exact and deterministic
    s0b = st.acquire("t0"); st.release(s0b)
    again = pages_of("t0")
    for p in first_pages:
        assert np.array_equal(first_pages[p][0], again[p][0])
        assert np.array_equal(first_pages[p][1], again[p][1])
    # unknown adapters fail host-side with the named error
    with pytest.raises(AdapterError, match="unknown adapter"):
        st.acquire("nope")


def test_store_pinned_rows_never_evict(lm, tenants):
    model, _ = lm
    st = AdapterStore(model, max_loaded=2)
    for name, tree in tenants.items():
        st.register(name, tree)
    a = st.acquire("t0")
    b = st.acquire("t1")
    # both rows pinned: a third tenant cannot stage
    with pytest.raises(AdapterError, match="pinned"):
        st.acquire("t2")
    st.release(b)
    # now t2 evicts the UNPINNED t1, never the pinned t0
    st.acquire("t2")
    assert set(st.loaded()) == {"t0", "t2"}
    st.release_all()
    # base rows acquire/release without touching residency
    assert st.acquire(None) == 0 and st.acquire("base") == 0
    st.release_all()


def test_reregister_bumps_cache_namespace(lm, tenants):
    """Pushing a NEW version of an adapter must orphan prefix-cache
    blocks its old weights computed: the digest salt embeds the
    registration version."""
    model, _ = lm
    st = AdapterStore(model, max_loaded=2)
    st.register("t0", tenants["t0"])
    s1 = st.salt("t0")
    assert s1.startswith(b"lora:t0@")
    st.register("t0", tenants["t1"])   # adapter update
    s2 = st.salt("t0")
    assert s1 != s2
    assert st.salt(None) == st.salt("base") == b""


def test_reregister_never_swaps_pages_under_a_pin(lm, tenants):
    """Updating a RESIDENT adapter while streams decode against it must
    not rewrite the pinned row: old streams keep the old pages (the row
    is orphaned and frees when they finish); new acquires stage the new
    pages into a fresh row."""
    model, _ = lm
    st = AdapterStore(model, max_loaded=3)
    st.register("t0", tenants["t0"])
    row = st.acquire("t0")              # a live stream pins the row
    before = np.asarray(st.tensors[st.paths[0]][0][row])
    st.register("t0", tenants["t1"])    # push v2 mid-stream
    after = np.asarray(st.tensors[st.paths[0]][0][row])
    assert np.array_equal(before, after)        # pinned pages untouched
    assert "t0" not in st.loaded()              # name unmapped
    row2 = st.acquire("t0")                     # v2 stages into a FRESH row
    assert row2 != row
    # the orphaned-but-pinned row is not handed out as free
    st.register("t2", tenants["t2"])
    assert st.acquire("t2") not in (row, row2)
    st.release_all()


def test_store_register_validation(lm, tenants):
    model, _ = lm
    st = AdapterStore(model, max_loaded=2)
    with pytest.raises(ValueError):
        st.register("base", tenants["t0"])
    bad = dict(tenants["t0"])
    bad.popitem()
    with pytest.raises(AdapterFormatError, match="lacks"):
        st.register("x", bad)


# ------------------------------------------------- serving: THE acceptance
@pytest.fixture(scope="module")
def mixed_run(lm, tenants, server):
    """Submit a staggered batch mixing 3 adapters + base (greedy and
    seeded sampling) and capture solo references for every stream."""
    model, _ = lm
    reqs = [("t0", _prompt(7, 1), dict(max_new_tokens=6)),
            (None, _prompt(9, 2), dict(max_new_tokens=5)),
            ("t1", _prompt(5, 3), dict(max_new_tokens=7, do_sample=True,
                                       temperature=0.8, seed=11)),
            ("t2", _prompt(8, 4), dict(max_new_tokens=6, do_sample=True,
                                       temperature=0.7, top_p=0.9,
                                       seed=12)),
            ("t0", _prompt(6, 5), dict(max_new_tokens=4, do_sample=True,
                                       seed=13))]
    solos = []
    for tid, p, kw in reqs:
        if tid is None:
            clear_adapter(model)
        else:
            set_adapter(model, tenants[tid])
        solos.append(model.generate(p[None], **kw, **GEO)[0])
    clear_adapter(model)
    handles = []
    for tid, p, kw in reqs:
        handles.append(server.submit(p, adapter_id=tid, **kw))
        time.sleep(0.05)   # arrive while earlier requests are mid-decode
    results = [h.result(timeout=300) for h in handles]
    return reqs, solos, results


def test_mixed_adapter_batch_matches_solo(mixed_run):
    """THE acceptance: every stream of a batch mixing >=3 adapters plus
    base is token-identical to the solo single-adapter generate with the
    same seed — greedy and seeded sampling."""
    reqs, solos, results = mixed_run
    for (tid, _, _), solo, got in zip(reqs, solos, results):
        np.testing.assert_array_equal(got, solo, err_msg=f"adapter={tid}")


def test_compile_budget_holds_with_adapters(lm, store, server, tenants,
                                            mixed_run):
    """Steady state stays at #prefill_buckets + 1 programs with adapters
    enabled, and LRU load/evict churn adds ZERO compiles."""
    from paddle_tpu.framework import compile_cache

    cc = server.engine.cache_stats()
    assert cc["prefill"]["compiles"] == len(server.engine.prefill_buckets)
    assert cc["decode"]["compiles"] == 1
    with compile_cache.retrace_guard(max_compiles=0, label="lora-serving"):
        hs = [server.submit(_prompt(4 + i, 20 + i),
                            adapter_id=("t0", "t1", "t2", None)[i % 4],
                            max_new_tokens=3, do_sample=bool(i % 2),
                            seed=i) for i in range(6)]
        for h in hs:
            assert h.result(timeout=300).shape[0] == 3
    cc2 = server.engine.cache_stats()
    assert cc2["prefill"]["compiles"] == cc["prefill"]["compiles"]
    assert cc2["decode"]["compiles"] == 1


def test_adapter_submit_validation(lm, server):
    model, _ = lm
    with pytest.raises(ValueError, match="unknown adapter"):
        server.submit(_prompt(5, 0), adapter_id="nobody")
    bare = InferenceServer(model, slots=1, **GEO)
    with pytest.raises(ValueError, match="no adapter_store"):
        bare.submit(_prompt(5, 0), adapter_id="t0")


def test_store_is_owned_by_one_engine(lm, store, server):
    """Pins are engine-lifecycle state: attaching one store to a second
    replica would let either engine's crash reset void the other's live
    pins (same sharing hazard BlockPool guards)."""
    model, _ = lm
    with pytest.raises(ValueError, match="one store per replica"):
        InferenceServer(model, slots=1, adapter_store=store, **GEO)


def test_acquire_with_salt_is_atomic(lm, tenants):
    """The admission path pins pages and captures the digest salt in one
    lock hold, so a concurrent adapter update cannot stamp old-weight
    K/V into the new version's namespace."""
    model, _ = lm
    st = AdapterStore(model, max_loaded=2)
    st.register("t0", tenants["t0"])
    row, salt = st.acquire("t0", with_salt=True)
    assert salt == st.salt("t0")
    st.register("t0", tenants["t1"])    # version bump mid-flight
    assert st.salt("t0") != salt        # new namespace for new pages
    assert st.acquire(None, with_salt=True) == (0, b"")
    st.release_all()


def test_base_alias_is_one_namespace(server, mixed_run):
    """adapter_id="base" is the zero adapter: same stream, same metrics
    key, no split cache namespace."""
    p = _prompt(6, 77)
    a = server.submit(p, max_new_tokens=4).result(timeout=300)
    b = server.submit(p, adapter_id="base",
                      max_new_tokens=4).result(timeout=300)
    np.testing.assert_array_equal(a, b)
    per = server.snapshot()["per_adapter"]
    assert "base" in per and None not in per


def test_snapshot_surfaces_per_adapter(server, mixed_run):
    snap = server.snapshot()
    per = snap["per_adapter"]
    assert {"base", "t0", "t1", "t2"} <= set(per)
    for e in per.values():
        assert e["requests"] >= 1 and e["tokens"] >= 1
        assert "ttft_p50_ms" in e
    assert snap["adapter_store"]["resident"] >= 1
    assert snap["adapter_store"]["rank"] == LCFG.rank


# ------------------------------------------------- device-free satellites
def test_metrics_per_adapter_block():
    m = ServingMetrics(slots=2)
    m.adapter_request("a")
    m.adapter_tokens("a", 5)
    m.observe_adapter_ttft("a", 0.1)
    m.adapter_request(None)
    m.adapter_tokens(None, 2)
    snap = m.snapshot()
    assert snap["per_adapter"]["a"] == {
        "requests": 1, "tokens": 5, "ttft_p50_ms": 100.0,
        # PR 15: SLO-countable cumulative fields (failures per tenant,
        # exact TTFT count/sum for window-mean deltas)
        "failures": 0, "ttft_count": 1, "ttft_sum_ms": 100.0}
    assert snap["per_adapter"]["base"]["tokens"] == 2
    m.reset()
    assert "per_adapter" not in m.snapshot()


def test_prefix_digest_salt_isolates_tenants():
    from paddle_tpu.serving.prefix_cache import chain_digests

    toks = np.arange(33, dtype=np.int32)
    base = chain_digests(toks, 8)
    t0 = chain_digests(toks, 8, salt=b"lora:t0")
    t1 = chain_digests(toks, 8, salt=b"lora:t1")
    assert len(base) == len(t0) == 4
    assert all(a != b for a, b in zip(base, t0))
    assert all(a != b for a, b in zip(t0, t1))
    assert t0 == chain_digests(toks, 8, salt=b"lora:t0")


class _StubStore:
    def __init__(self, resident, known=None):
        self._resident = set(resident)
        self._known = set(known) if known is not None else set(resident)

    def resident(self, name):
        return name in self._resident

    def known(self, name):
        return name in (None, "base") or name in self._known

    def salt(self, name):
        return (b"" if name in (None, "base")
                else b"lora:%s@1" % str(name).encode())


def test_router_skips_replicas_without_the_adapter():
    """A replica whose registry does not know the tenant is excluded
    from placement (instead of aborting it with its submit-time
    ValueError); a fleet with no knowing replica names the problem."""
    from paddle_tpu.serving import ReplicaRouter
    from tests.test_fleet_serving import _StubServer

    knows = _StubServer(active=3, slots=4)     # busy but able
    ignorant = _StubServer(active=0, slots=4)  # idle but unable
    knows.engine.store = _StubStore({"tenant-a"})
    ignorant.engine.store = _StubStore(())
    r = ReplicaRouter()
    r.add_replica(knows, "knows")
    r.add_replica(ignorant, "ignorant")
    r.submit(np.arange(8, dtype=np.int32), max_new_tokens=2,
             adapter_id="tenant-a").result(timeout=30)
    assert knows.submitted and not ignorant.submitted
    with pytest.raises(ValueError, match="knows adapter"):
        r.submit(np.arange(8, dtype=np.int32), adapter_id="tenant-b")


def test_router_adapter_affinity_prefers_warm_replica():
    """Device-free: the router places a tenant where its pages are
    resident, but load still outweighs warmth."""
    from paddle_tpu.serving import ReplicaRouter
    from tests.test_fleet_serving import _StubServer

    warm = _StubServer(active=1, slots=4)
    cold = _StubServer(active=0, slots=4)
    warm.engine.store = _StubStore({"tenant-a"})
    cold.engine.store = _StubStore((), known={"tenant-a"})
    r = ReplicaRouter(adapter_affinity_weight=0.5)
    r.add_replica(warm, "warm")
    r.add_replica(cold, "cold")
    h = r.submit(np.arange(8, dtype=np.int32), max_new_tokens=2,
                 adapter_id="tenant-a")
    h.result(timeout=30)
    assert warm.submitted and not cold.submitted
    # without the adapter the same skew places on the idle replica
    h2 = r.submit(np.arange(8, dtype=np.int32), max_new_tokens=2)
    h2.result(timeout=30)
    assert cold.submitted
    # a heavily loaded warm replica loses to the idle cold one
    warm.engine.active_count = 4
    warm.scheduler.depth = 6
    h3 = r.submit(np.arange(8, dtype=np.int32), max_new_tokens=2,
                  adapter_id="tenant-a")
    h3.result(timeout=30)
    assert len(cold.submitted) == 2
