"""The examples/ directory must stay runnable: each demo is executed as a
subprocess (fresh interpreter, the way a user runs it) and its printed
proof-of-work is asserted. Mirrors the reference's demo-scripts-as-tests
discipline (``python/paddle/fluid/tests/demo/``). Each script runs ONCE
per session; every assertion reads the cached output."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    # Heaviest demo (~13s): tier-1 time budget pushed it behind `slow`.
    pytest.param("gpt_pretrain.py", ["loss", "tokens/s", "saved"],
                 marks=pytest.mark.slow, id="gpt_pretrain"),
    pytest.param("hybrid_parallel.py", ["loss", "PartitionSpec"],
                 id="hybrid_parallel"),
    pytest.param("ps_ctr_train.py", ["table rows 500"], id="ps_ctr_train"),
    pytest.param("graph_deepwalk.py", ["cosine same-clique"],
                 id="graph_deepwalk"),
    pytest.param("export_serving.py",
                 ["matches the eager model", "decode engine: "],
                 id="export_serving"),
]

_outputs = {}


def _run_once(script: str) -> str:
    if script not in _outputs:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "examples", script)],
            capture_output=True, text=True, timeout=420, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        _outputs[script] = proc.stdout
    return _outputs[script]


@pytest.mark.parametrize("script,expect", CASES)
def test_example_runs(script, expect):
    out = _run_once(script)
    for needle in expect:
        assert needle in out, (needle, out[-2000:])


def test_deepwalk_separates_cliques():
    """The deepwalk demo's learning signal is real: same-clique cosine
    must exceed cross-clique by a wide margin."""
    out = _run_once("graph_deepwalk.py")
    line = [l for l in out.splitlines() if "cosine" in l][0]
    same = float(line.split("same-clique ")[1].split(" ")[0])
    cross = float(line.split("cross-clique ")[1])
    assert same > cross + 0.3, line
