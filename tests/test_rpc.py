"""paddle.distributed.rpc parity tests: multi-process agents, sync/async
calls by worker name, exception transport, worker-info registry, barriered
shutdown (reference ``python/paddle/distributed/rpc``)."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys, time
    from paddle_tpu.distributed import rpc

    def add(a, b):
        return a + b

    def whoami():
        return rpc.get_current_worker_info().name

    def boom():
        raise ValueError("rpc boom")

    rank = int(sys.argv[1])
    rpc.init_rpc(name=f"worker{rank}", rank=rank, world_size=2,
                 master_endpoint=sys.argv[2])
    infos = rpc.get_all_worker_infos()
    assert [w.name for w in infos] == ["worker0", "worker1"], infos
    if rank == 0:
        out = rpc.rpc_sync("worker1", add, args=(2, 3))
        assert out == 5, out
        fut = rpc.rpc_async("worker1", add, args=(10, 30))
        assert fut.wait() == 40
        assert rpc.rpc_sync("worker1", whoami) == "worker1"
        assert rpc.rpc_sync("worker0", whoami) == "worker0"  # self-call
        try:
            rpc.rpc_sync("worker1", boom)
            raise SystemExit("expected remote ValueError")
        except ValueError as e:
            assert "rpc boom" in str(e)
        print("RPC_OK", flush=True)
    rpc.shutdown()
""")


def test_rpc_two_process_cluster(tmp_path):
    script = tmp_path / "w.py"
    script.write_text(WORKER)
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen([sys.executable, str(script), str(r), ep],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    assert "RPC_OK" in outs[0]


def test_rpc_requires_init():
    from paddle_tpu.distributed import rpc

    with pytest.raises(RuntimeError, match="init_rpc"):
        rpc.rpc_sync("nobody", print)


def test_rpc_reinit_cycles_single_process(tmp_path):
    """init -> shutdown -> init -> shutdown on the same store must not see
    the previous cycle's rendezvous/barrier keys."""
    import socket

    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    for cycle in range(2):
        rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
        assert rpc.rpc_sync("solo", int, args=(41 + cycle,)) == 41 + cycle
        rpc.shutdown()
