"""Eager dygraph ergonomics tests (VERDICT r1 item 5).

Reference semantics being matched: ``varbase_patch_methods.py:224``
(``Tensor.backward``) + ``egr::Backward`` reverse accumulation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import eager
from paddle_tpu.optimizer import SGD, AdamW


@pytest.fixture(autouse=True)
def _enable():
    eager.enable()
    yield


def test_tensor_basics():
    t = eager.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert t.shape == [2, 3]
    assert t.stop_gradient
    assert float(t.sum()) == 15.0
    np.testing.assert_allclose((t + 1).numpy(), t.numpy() + 1)
    np.testing.assert_allclose((t * 2 - t).numpy(), t.numpy())


def test_backward_simple():
    x = eager.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [2.0, 4.0, 6.0])


def test_backward_chain_and_accumulation():
    x = eager.to_tensor([2.0], stop_gradient=False)
    (x * 3).backward()
    (x * 5).backward()
    np.testing.assert_allclose(np.asarray(x.grad), [8.0])  # 3 + 5
    x.clear_grad()
    assert x.grad is None


def test_no_grad():
    x = eager.to_tensor([1.0], stop_gradient=False)
    with eager.no_grad():
        y = x * 2
    assert y._node is None


def test_branching_graph():
    """Diamond graph: z = x*y + x."""
    x = eager.to_tensor([3.0], stop_gradient=False)
    y = eager.to_tensor([4.0], stop_gradient=False)
    z = x * y + x
    z.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [5.0])  # y + 1
    np.testing.assert_allclose(np.asarray(y.grad), [3.0])  # x


def test_layer_backward_and_grads():
    pt.seed(0)
    fc = nn.Linear(4, 2)
    x = eager.to_tensor(np.random.randn(3, 4).astype(np.float32))
    out = fc(x)
    assert isinstance(out, eager.Tensor)
    loss = (out * out).mean()
    loss.backward()
    g = eager.grads_of(fc)
    assert set(g) == {"weight", "bias"}
    assert float(jnp.abs(g["weight"]).sum()) > 0

    # parity with jax.grad over functional_call
    from paddle_tpu.nn import functional_call, param_state

    params = param_state(fc)

    def ref_loss(p):
        o, _ = functional_call(fc, p, {}, jnp.asarray(x.numpy()))
        return jnp.mean(o * o)

    ref = jax.grad(ref_loss)(params)
    np.testing.assert_allclose(np.asarray(g["weight"]), np.asarray(ref["weight"]),
                               rtol=1e-5, atol=1e-6)


def test_functional_dispatch():
    x = eager.to_tensor(np.random.randn(2, 5).astype(np.float32),
                        stop_gradient=False)
    out = F.relu(x)
    assert isinstance(out, eager.Tensor)
    out.sum().backward()
    assert x.grad is not None


def test_reference_style_training_loop_matches_trainstep():
    """model -> loss.backward() -> opt.step() matches TrainStep losses."""

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((4, 8, 8)).astype(np.float32)
    ys = rng.integers(0, 4, (4, 8))

    pt.seed(7)
    model_a = Net()
    model_b = Net()
    model_b.set_state_dict(model_a.state_dict())

    # eager reference-style loop
    opt = SGD(learning_rate=0.1, parameters=model_a)
    eager_losses = []
    for x, y in zip(xs, ys):
        out = model_a(eager.to_tensor(x))
        loss = F.cross_entropy(out, eager.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        eager_losses.append(float(loss))

    # compiled TrainStep
    from paddle_tpu.framework.jit import TrainStep

    step = TrainStep(model_b, SGD(learning_rate=0.1),
                     loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    jit_losses = [float(step((x, y))) for x, y in zip(xs, ys)]

    np.testing.assert_allclose(eager_losses, jit_losses, rtol=1e-5, atol=1e-6)


def test_buffers_update_eagerly():
    bn = nn.BatchNorm1D(4)
    x = eager.to_tensor(np.random.randn(8, 4).astype(np.float32))
    before = np.asarray(bn._buffers["_mean"]).copy()
    bn(x)
    after = np.asarray(bn._buffers["_mean"])
    assert not np.allclose(before, after)


def test_ops_method_delegation():
    x = eager.to_tensor(np.random.randn(3, 4).astype(np.float32),
                        stop_gradient=False)
    out = x.exp()
    assert isinstance(out, eager.Tensor)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad), np.exp(x.numpy()), rtol=1e-5)


# ---------------------------------------------------------------- PyLayer
def test_pylayer_custom_backward():
    """Reference py_layer.py shape: forward saves activations, backward
    computes the custom grad (tanh' = 1 - tanh^2 written by hand)."""

    class CusTanh(eager.PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = x.tanh()
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor()
            return dy * (1 - y * y)

    x = eager.to_tensor([0.3, -1.2, 2.0], stop_gradient=False)
    out = CusTanh.apply(x)
    assert isinstance(out, eager.Tensor)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad),
                               1 - np.tanh(x.numpy()) ** 2, rtol=1e-5)


def test_pylayer_scaled_backward_and_ctx_attrs():
    """A deliberately WRONG custom grad proves the user's backward really
    replaces the traced one; ctx carries arbitrary attributes + kwargs."""

    class ScaleGrad(eager.PyLayer):
        @staticmethod
        def forward(ctx, x, factor=10.0):
            ctx.factor = factor
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            return dy * ctx.factor

    x = eager.to_tensor([1.0, 2.0], stop_gradient=False)
    ScaleGrad.apply(x, factor=7.0).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad), [7.0, 7.0])  # not 2.0


def test_pylayer_multi_input_output():
    """Multi-output PyLayer: backward is invoked exactly ONCE with ALL
    output grads (the reference single-GradNode contract), not once per
    consumed output with zero-filled siblings."""
    calls = []

    class Swap(eager.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return b * 2, a * 3

        @staticmethod
        def backward(ctx, da, db):
            calls.append((da.numpy().copy(), db.numpy().copy()))
            # forward: out0 = 2b, out1 = 3a -> d_a = 3*db, d_b = 2*da
            return db * 3, da * 2

    a = eager.to_tensor([1.0], stop_gradient=False)
    b = eager.to_tensor([1.0], stop_gradient=False)
    o0, o1 = Swap.apply(a, b)
    (o0 * 5 + o1 * 7).backward()
    assert len(calls) == 1  # one joint call, da=5, db=7 together
    np.testing.assert_allclose(calls[0][0], [5.0])
    np.testing.assert_allclose(calls[0][1], [7.0])
    np.testing.assert_allclose(np.asarray(a.grad), [21.0])  # 3*7
    np.testing.assert_allclose(np.asarray(b.grad), [10.0])  # 2*5

    # a partially-consumed output still yields one call; the unconsumed
    # output's grad materializes as zeros (default materialize_grads)
    calls.clear()
    o0, o1 = Swap.apply(a, b)
    o0.sum().backward()
    assert len(calls) == 1
    np.testing.assert_allclose(calls[0][1], [0.0])


def test_pylayer_training_loop():
    """PyLayer composes with layers/optimizer in a paddle-shaped loop."""

    class Square(eager.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * 2 * x

    pt.seed(3)
    fc = nn.Linear(4, 4)
    opt = SGD(learning_rate=0.05, parameters=fc)
    xs = np.random.default_rng(0).standard_normal((5, 2, 4)).astype(np.float32)
    losses = []
    for x in xs:
        out = Square.apply(fc(eager.to_tensor(x)))
        loss = out.mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pylayer_backward_arity_check():
    class Bad(eager.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return a + b

        @staticmethod
        def backward(ctx, dy):
            return dy  # should be 2 grads

    a = eager.to_tensor([1.0], stop_gradient=False)
    b = eager.to_tensor([1.0], stop_gradient=False)
    with pytest.raises(RuntimeError, match="grad"):
        Bad.apply(a, b).backward()


def test_saved_tensors_hooks_pack_unpack():
    packed, unpacked = [], []

    def pack(t):
        packed.append(t)
        return ("wrapped", t)

    def unpack(p):
        unpacked.append(p)
        assert p[0] == "wrapped"
        return p[1]

    class Identity(eager.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 1

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return dy * jnp.ones_like(x.numpy())

    x = eager.to_tensor([5.0], stop_gradient=False)
    with eager.saved_tensors_hooks(pack, unpack):
        out = Identity.apply(x)
    out.backward()
    assert len(packed) == 1 and len(unpacked) == 1


# ------------------------------------------------------------------ hooks
def test_register_hook_observes_and_modifies():
    x = eager.to_tensor([1.0, 2.0], stop_gradient=False)
    seen = []
    h = x.register_hook(lambda g: (seen.append(g.numpy().copy()), g * 2)[1])
    (x * 3).sum().backward()
    np.testing.assert_allclose(seen[0], [3.0, 3.0])  # raw grad observed
    np.testing.assert_allclose(np.asarray(x.grad), [6.0, 6.0])  # doubled
    h.remove()
    x.clear_grad()
    (x * 3).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad), [3.0, 3.0])  # back to raw
    assert len(seen) == 1  # removed hook did not fire again


def test_register_hook_fires_once_with_accumulated_grad():
    """Diamond: hook on an interior tensor sees the FULL accumulated grad
    exactly once (reference hook timing)."""
    x = eager.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    calls = []
    y.register_hook(lambda g: calls.append(g.numpy().copy()))
    z = y * 3 + y  # two consumers of y
    z.backward()
    assert len(calls) == 1
    np.testing.assert_allclose(calls[0], [4.0])  # 3 + 1
    np.testing.assert_allclose(np.asarray(x.grad), [8.0])


def test_register_hook_modified_grad_flows_upstream():
    x = eager.to_tensor([1.0], stop_gradient=False)
    y = x * 5
    y.register_hook(lambda g: g * 0)  # kill the gradient mid-flow
    (y * 2).backward()
    np.testing.assert_allclose(np.asarray(x.grad), [0.0])


def test_register_hook_requires_grad():
    t = eager.to_tensor([1.0])  # stop_gradient=True
    with pytest.raises(RuntimeError, match="stop"):
        t.register_hook(lambda g: g)


# ------------------------------------------------------------ strict mode
def test_strict_mode_blocks_silent_detach():
    x = eager.to_tensor([1.0], stop_gradient=False)
    y = x * 2  # grad-requiring, on tape
    with pytest.raises(RuntimeError, match="detach"):
        np.asarray(y)
    with pytest.raises(RuntimeError, match="detach"):
        jnp.asarray(y)
    # explicit escapes work
    assert float(y.detach().numpy()[0]) == 2.0
    assert float(y.numpy()[0]) == 2.0
    with eager.no_grad():
        assert float(np.asarray(y)[0]) == 2.0  # deliberate, non-recording
    # plain data tensors convert freely
    t = eager.to_tensor([3.0])
    assert float(np.asarray(t)[0]) == 3.0
    # and the guard is toggleable
    prev = eager.set_strict(False)
    try:
        assert float(np.asarray(y)[0]) == 2.0
    finally:
        eager.set_strict(prev)


def test_autograd_facade_backward():
    from paddle_tpu import autograd

    assert autograd.PyLayer is eager.PyLayer
    x = eager.to_tensor([1.0], stop_gradient=False)
    y1 = x * 2
    y2 = x * 3
    autograd.backward([y1, y2])
    np.testing.assert_allclose(np.asarray(x.grad), [5.0])


def test_autograd_backward_joint_hooks():
    """Multi-root backward is ONE joint pass: a hook on a tensor shared by
    both roots fires once with the accumulated grad (3+5), not per root
    with partials."""
    from paddle_tpu import autograd

    x = eager.to_tensor([1.0], stop_gradient=False)
    z = x * 2
    calls = []
    z.register_hook(lambda g: calls.append(g.numpy().copy()))
    y1 = z * 3
    y2 = z * 5
    autograd.backward([y1, y2], grad_tensors=[None, jnp.asarray([2.0])])
    assert len(calls) == 1
    np.testing.assert_allclose(calls[0], [13.0])  # 3*1 + 5*2 at once
    np.testing.assert_allclose(np.asarray(x.grad), [26.0])


def test_front_door_to_tensor_tape():
    """paddle.to_tensor(d, stop_gradient=False) from the TOP-LEVEL
    namespace must return a tape Tensor so the canonical dygraph snippet
    works end to end (reference: paddle.to_tensor + Tensor.backward)."""
    import paddle_tpu as pt

    x = pt.to_tensor([[1.0, 2.0]], stop_gradient=False)
    assert isinstance(x, eager.Tensor)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [[2.0, 4.0]])
    # default stays the functional fast path: a plain array
    import jax

    assert isinstance(pt.to_tensor([[1.0, 2.0]]), jax.Array)


def test_partial_grad_api():
    """paddle.grad(outputs, inputs): partial grads without touching .grad
    (reference python/paddle/fluid/dygraph/base.py:468)."""
    import paddle_tpu as pt

    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    w = pt.to_tensor([3.0, 4.0], stop_gradient=False)
    y = (x * w).sum()
    gx, gw = pt.grad([y], [x, w])
    np.testing.assert_allclose(np.asarray(gx), [3.0, 4.0])
    np.testing.assert_allclose(np.asarray(gw), [1.0, 2.0])
    assert x.grad is None and w.grad is None  # .grad untouched

    # grad_outputs seeding
    x2 = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y2 = x2 * 2.0
    (g2,) = pt.grad([y2], [x2], grad_outputs=[pt.to_tensor([10.0, 100.0])])
    np.testing.assert_allclose(np.asarray(g2), [20.0, 200.0])

    # unreachable input: error by default, None under allow_unused
    z = pt.to_tensor([5.0], stop_gradient=False)
    with pytest.raises(RuntimeError, match="allow_unused"):
        pt.grad([y2], [z])
    x3 = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y3 = (x3 * x3).sum()
    got = pt.grad([y3], [x3, z], allow_unused=True)
    assert got[1] is None
    np.testing.assert_allclose(np.asarray(got[0]), [2.0, 4.0])

    # intermediate (non-leaf) input collects its full cotangent
    x4 = pt.to_tensor([2.0], stop_gradient=False)
    mid = x4 * 3.0
    out = (mid * mid).sum()
    (gmid,) = pt.grad([out], [mid], retain_graph=True)
    np.testing.assert_allclose(np.asarray(gmid), [12.0])  # 2*mid

    # higher-order points to the functional transforms
    with pytest.raises(NotImplementedError, match="incubate.autograd"):
        pt.grad([out], [x4], create_graph=True)

    # callable first arg keeps the jax.grad functional form
    import jax.numpy as jnp

    f = pt.grad(lambda v: (v * v).sum())
    np.testing.assert_allclose(np.asarray(f(jnp.asarray([3.0]))), [6.0])


def test_partial_grad_identity_and_mode_restore():
    """grad([x], [x]) returns the seed (reference: an output
    differentiated w.r.t. itself is ones); and the smoke battery's
    static-mode flip must not leak (fixture restores dynamic mode)."""
    import paddle_tpu as pt

    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    (g,) = pt.grad([x], [x])
    np.testing.assert_allclose(np.asarray(g), [1.0, 1.0])
    assert pt.in_dynamic_mode()


def test_partial_grad_identity_runs_hooks():
    import paddle_tpu as pt

    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    x.register_hook(lambda g: g * 2)
    (g,) = pt.grad([x], [x])
    np.testing.assert_allclose(np.asarray(g), [2.0, 2.0])
