"""Eager dygraph ergonomics tests (VERDICT r1 item 5).

Reference semantics being matched: ``varbase_patch_methods.py:224``
(``Tensor.backward``) + ``egr::Backward`` reverse accumulation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import eager
from paddle_tpu.optimizer import SGD, AdamW


@pytest.fixture(autouse=True)
def _enable():
    eager.enable()
    yield


def test_tensor_basics():
    t = eager.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert t.shape == [2, 3]
    assert t.stop_gradient
    assert float(t.sum()) == 15.0
    np.testing.assert_allclose((t + 1).numpy(), t.numpy() + 1)
    np.testing.assert_allclose((t * 2 - t).numpy(), t.numpy())


def test_backward_simple():
    x = eager.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [2.0, 4.0, 6.0])


def test_backward_chain_and_accumulation():
    x = eager.to_tensor([2.0], stop_gradient=False)
    (x * 3).backward()
    (x * 5).backward()
    np.testing.assert_allclose(np.asarray(x.grad), [8.0])  # 3 + 5
    x.clear_grad()
    assert x.grad is None


def test_no_grad():
    x = eager.to_tensor([1.0], stop_gradient=False)
    with eager.no_grad():
        y = x * 2
    assert y._node is None


def test_branching_graph():
    """Diamond graph: z = x*y + x."""
    x = eager.to_tensor([3.0], stop_gradient=False)
    y = eager.to_tensor([4.0], stop_gradient=False)
    z = x * y + x
    z.backward()
    np.testing.assert_allclose(np.asarray(x.grad), [5.0])  # y + 1
    np.testing.assert_allclose(np.asarray(y.grad), [3.0])  # x


def test_layer_backward_and_grads():
    pt.seed(0)
    fc = nn.Linear(4, 2)
    x = eager.to_tensor(np.random.randn(3, 4).astype(np.float32))
    out = fc(x)
    assert isinstance(out, eager.Tensor)
    loss = (out * out).mean()
    loss.backward()
    g = eager.grads_of(fc)
    assert set(g) == {"weight", "bias"}
    assert float(jnp.abs(g["weight"]).sum()) > 0

    # parity with jax.grad over functional_call
    from paddle_tpu.nn import functional_call, param_state

    params = param_state(fc)

    def ref_loss(p):
        o, _ = functional_call(fc, p, {}, jnp.asarray(x.numpy()))
        return jnp.mean(o * o)

    ref = jax.grad(ref_loss)(params)
    np.testing.assert_allclose(np.asarray(g["weight"]), np.asarray(ref["weight"]),
                               rtol=1e-5, atol=1e-6)


def test_functional_dispatch():
    x = eager.to_tensor(np.random.randn(2, 5).astype(np.float32),
                        stop_gradient=False)
    out = F.relu(x)
    assert isinstance(out, eager.Tensor)
    out.sum().backward()
    assert x.grad is not None


def test_reference_style_training_loop_matches_trainstep():
    """model -> loss.backward() -> opt.step() matches TrainStep losses."""

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((4, 8, 8)).astype(np.float32)
    ys = rng.integers(0, 4, (4, 8))

    pt.seed(7)
    model_a = Net()
    model_b = Net()
    model_b.set_state_dict(model_a.state_dict())

    # eager reference-style loop
    opt = SGD(learning_rate=0.1, parameters=model_a)
    eager_losses = []
    for x, y in zip(xs, ys):
        out = model_a(eager.to_tensor(x))
        loss = F.cross_entropy(out, eager.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        eager_losses.append(float(loss))

    # compiled TrainStep
    from paddle_tpu.framework.jit import TrainStep

    step = TrainStep(model_b, SGD(learning_rate=0.1),
                     loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    jit_losses = [float(step((x, y))) for x, y in zip(xs, ys)]

    np.testing.assert_allclose(eager_losses, jit_losses, rtol=1e-5, atol=1e-6)


def test_buffers_update_eagerly():
    bn = nn.BatchNorm1D(4)
    x = eager.to_tensor(np.random.randn(8, 4).astype(np.float32))
    before = np.asarray(bn._buffers["_mean"]).copy()
    bn(x)
    after = np.asarray(bn._buffers["_mean"])
    assert not np.allclose(before, after)


def test_ops_method_delegation():
    x = eager.to_tensor(np.random.randn(3, 4).astype(np.float32),
                        stop_gradient=False)
    out = x.exp()
    assert isinstance(out, eager.Tensor)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad), np.exp(x.numpy()), rtol=1e-5)
