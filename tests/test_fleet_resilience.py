"""Cross-host serving resilience: rpc remote replicas, heartbeat failure
detection, hedged retries, and overload-shedding admission.

The acceptance contract on top of PR 8's in-process fleet:

1. **Remote replicas speak the router's duck type** — a
   ``RemoteReplica`` over real rpc sockets submits/streams/probes like a
   local ``InferenceServer``, remote application errors (``QueueFull``)
   cross the wire unwrapped so failover logic is placement-invariant,
   and transport failures classify as retryable ``ReplicaUnreachable``;
2. **The heartbeat detector quarantines before it condemns** — a probe
   miss (or a probe far slower than the replica's latency EWMA) moves
   ACTIVE -> SUSPECT (placement stops, in-flight continues), repeated
   misses declare DEAD with a flight-recorder dump carrying the affected
   correlation ids, and remote replicas abandon their live handles so
   streams reroute immediately;
3. **Hedged retries win without diverging** — a stalled stream fires one
   hedge to a second replica reusing the router-assigned seed, and the
   winner's tokens are identical; the slow replica is NOT marked dead;
4. **Overload sheds fast, never at the head** — predicted-SLO-miss
   requests fail with retryable ``Overloaded`` (counted as
   ``requests_shed``, never as expired/failed), at submit when the
   cadence EWMA already says so, from the queue body when service
   degrades later.

Tier-1 budget discipline: everything here runs on device-free stubs or a
world-of-1 rpc loopback (module fixture); the only model-backed tests
patch ``free_slots`` to [] so nothing ever compiles. The two-process
soak (``tools/fleet_chaos.py``) is marked slow.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.resilience import (Deadline, FaultPlan,
                                               RetryPolicy)
from paddle_tpu.observability import flight as _flight
from paddle_tpu.serving import (FifoScheduler, InferenceServer,
                                Overloaded, QueueFull, RemoteReplica,
                                ReplicaRouter, ReplicaUnreachable,
                                Request, SchedulerClosed)
from paddle_tpu.serving import remote as remote_mod
from paddle_tpu.serving.server import RequestHandle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEO = dict(max_length=64, prefill_buckets=(32,))


# --------------------------------------------------------- stub plumbing
class _FakeEngine:
    pool = None
    store = None

    def __init__(self, slots=2):
        self.active_count = 0
        self.slots = slots


class _FakeSched:
    def __init__(self, depth=0, cap=8):
        self.depth = depth
        self.max_queue_depth = cap


class _FakeServer:
    """Duck-typed InferenceServer built on REAL RequestHandles: a worker
    thread pushes ``tokens`` (optionally stalling forever after
    ``stall_after`` of them, or pausing ``pause`` seconds mid-stream),
    so router hedging/reroute logic sees genuine handle mechanics."""

    def __init__(self, tokens=(1, 2, 3), delay=0.005, stall_after=None,
                 pause=None, submit_error=None, fail_with=None,
                 probe_exc=None, probe_sleep=0.0):
        self.engine = _FakeEngine()
        self.scheduler = _FakeSched()
        self.tokens = list(tokens)
        self.delay = delay
        self.stall_after = stall_after
        self.pause = pause
        self.submit_error = submit_error
        self.fail_with = fail_with
        self.probe_exc = probe_exc
        self.probe_sleep = probe_sleep
        self.submitted = []

    def start(self):
        return self

    def submit(self, **kw):
        if self.submit_error is not None:
            raise self.submit_error
        self.submitted.append(kw)
        req = Request(prompt=kw["prompt"],
                      corr_id=kw.get("correlation_id"))
        h = RequestHandle(req)
        req.handle = h

        def run():
            if self.fail_with is not None:
                time.sleep(self.delay)
                h._fail(self.fail_with)
                return
            for i, t in enumerate(self.tokens):
                if self.stall_after is not None and i >= self.stall_after:
                    return               # stalls forever, never finishes
                if self.pause is not None and i == 1:
                    time.sleep(self.pause)
                time.sleep(self.delay)
                h._push(t)
            h.ttft_s = self.delay
            h._finish()

        threading.Thread(target=run, daemon=True).start()
        return h

    def probe(self):
        if self.probe_exc is not None:
            raise self.probe_exc
        if self.probe_sleep:
            time.sleep(self.probe_sleep)
        return {"active": self.engine.active_count,
                "slots": self.engine.slots,
                "queue_depth": self.scheduler.depth,
                "max_queue_depth": self.scheduler.max_queue_depth}

    def snapshot(self):
        return {"requests_completed": len(self.submitted)}

    def shutdown(self, drain=True, timeout=None):
        pass


def _warm_hedge(router, n=None):
    for _ in range(n or router.hedge_warmup_tokens):
        router._note_inter_token(0.01)


def _hedge_router(**kw):
    kw.setdefault("hedge_multiplier", 2.0)
    kw.setdefault("hedge_min_s", 0.05)
    kw.setdefault("hedge_warmup_tokens", 4)
    kw.setdefault("hedge_poll_interval", 0.01)
    return ReplicaRouter(**kw)


def _mkreq(deadline=None):
    req = Request(prompt=np.arange(2),
                  deadline=Deadline(deadline) if deadline is not None
                  else None)
    req.handle = RequestHandle(req)
    return req


# ------------------------------------------------------ scheduler sheds
def test_scheduler_sheds_predicted_miss_at_submit():
    s = FifoScheduler(shed_on_overload=True)
    assert s.predicted_wait(5) is None       # zero evidence: no shedding
    with s._lock:
        s._svc_ewma = 1.0                    # 1s per admission
    s.submit(_mkreq())                       # position 0
    s.submit(_mkreq(deadline=10.0))          # predicted 1.0s < 10s: in
    with pytest.raises(Overloaded):
        s.submit(_mkreq(deadline=0.5))       # predicted 2.0s > 0.5s: shed
    assert s.depth == 2                      # the shed never queued
    # no-deadline requests are never shed (no SLO to miss)
    s.submit(_mkreq())
    assert s.depth == 3


def test_scheduler_shed_default_off_is_inert():
    s = FifoScheduler()                      # shed_on_overload=False
    with s._lock:
        s._svc_ewma = 100.0
    s.submit(_mkreq())
    s.submit(_mkreq(deadline=0.01))          # hopeless, but NOT shed
    assert s.depth == 2
    assert s.pop_predicted_misses() == []


def test_scheduler_queue_shed_spares_head():
    s = FifoScheduler(shed_on_overload=True)
    head = _mkreq(deadline=0.2)
    mid = _mkreq(deadline=0.3)
    tail = _mkreq()                          # no deadline: untouchable
    for r in (head, mid, tail):
        s.submit(r)
    with s._lock:
        s._svc_ewma = 1.0                    # service collapsed
    shed = s.pop_predicted_misses()
    assert shed == [mid]                     # position 1: predicted 1.0s
    assert s.depth == 2                      # head survives at position 0
    admit, _ = s.take(4)
    assert admit[0] is head


def test_scheduler_cadence_ewma_ignores_idle_gaps():
    s = FifoScheduler(shed_on_overload=True)
    s.submit(_mkreq())
    s.take(1)                                # first admit: clock starts
    s.submit(_mkreq())
    time.sleep(0.05)
    s.take(1)                                # genuine ~50ms sample
    w = s.predicted_wait(2)
    assert w is not None and 0.02 <= w <= 1.0
    s.take(1)                                # empty queue: clock reset
    time.sleep(0.25)                         # idle gap
    s.submit(_mkreq())                       # arrival restarts the clock
    s.take(1)
    assert s.predicted_wait(1) < 0.15        # the 0.25s lull never counted


# ------------------------------------------------- server-side accounting
@pytest.fixture(scope="module")
def lm():
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(7)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


def test_server_shed_accounting_separate_and_retryable(lm):
    """requests_shed counts separately from expired/failed; both shed
    flavors (door + queue sweep) surface as the retryable Overloaded.
    Device-free: free_slots is pinned empty so nothing ever admits."""
    model, _ = lm
    srv = InferenceServer(model, slots=1, shed_on_overload=True, **GEO)
    srv.engine.free_slots = lambda: []
    with srv.scheduler._lock:
        srv.scheduler._svc_ewma = 5.0
    h1 = srv.submit(np.arange(4), max_new_tokens=2)   # deadline-free
    with pytest.raises(Overloaded):                   # door shed (pos 1)
        srv.submit(np.arange(4), max_new_tokens=2, deadline=1.0)
    assert srv.metrics.requests_shed == 1
    h2 = srv.submit(np.arange(4), max_new_tokens=2, deadline=60.0)
    with srv.scheduler._lock:                         # service collapses
        srv.scheduler._svc_ewma = 1000.0
    with pytest.raises(Overloaded) as ei:             # queue-sweep shed
        h2.result(timeout=30)
    assert isinstance(ei.value, ConnectionError)      # retryable class
    assert srv.metrics.requests_shed == 2
    assert srv.metrics.requests_expired == 0
    assert srv.metrics.requests_failed == 0
    assert not h1.done                                # head never shed
    snap = srv.snapshot()
    assert snap["requests_shed"] == 2
    # ...and a RetryPolicy really does classify a shed as retryable
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise Overloaded("shed")
        return "ok"

    assert RetryPolicy(max_attempts=3, base_delay=0.01).call(flaky) == "ok"
    srv.shutdown(drain=False, timeout=30)


def test_server_probe_shape_and_fault_site(lm):
    model, _ = lm
    srv = InferenceServer(model, slots=2, **GEO)
    p = srv.probe()
    assert p["slots"] == 2 and p["queue_depth"] == 0
    assert p["max_queue_depth"] == srv.scheduler.max_queue_depth
    with FaultPlan([{"site": "serve.probe", "kind": "drop"}], seed=0):
        with pytest.raises(ConnectionError):
            srv.probe()
    srv.shutdown(drain=False, timeout=30)


# -------------------------------------------------------- router detector
def test_detector_miss_suspects_then_kills_and_dumps():
    bad = _FakeServer(stall_after=0)         # its handle never finishes
    ok = _FakeServer(tokens=(1, 2, 3))
    router = ReplicaRouter(suspect_misses=1, dead_misses=3)
    router.add_replica(bad, "bad")
    router.add_replica(ok, "ok")
    h = router.submit(np.arange(4), max_new_tokens=3, prefer="bad")
    corr = h.correlation_id
    dumps_before = _flight.flight_recorder().stats()["dumps_written"]
    bad.probe_exc = ConnectionError("probe refused")
    router.check_health()
    assert router.replicas()["bad"] == "suspect"      # quarantined
    router.check_health()
    assert router.replicas()["bad"] == "suspect"      # not yet condemned
    router.check_health()                             # 3rd miss: dead
    assert router.replicas()["bad"] == "dead"
    snap = router.snapshot()
    assert snap["replicas_suspected"] == 1
    assert snap["replicas_failed"] == 1
    rec = _flight.flight_recorder()
    assert rec.stats()["dumps_written"] == dumps_before + 1
    path = rec.last_dump_path
    assert path is not None and "replica_dead" in path
    with open(path) as f:
        dump = json.load(f)
    assert dump["extra"]["replica"] == "bad"
    assert corr in dump["extra"]["inflight"]          # affected corr rides
    # the in-flight request is NOT lost: reroute still drives it home
    # (local stubs have no abandon(); the handle's own wait does it)
    h._current()._fail(SchedulerClosed("server gone"))
    assert list(h.result(timeout=10)) == [1, 2, 3]
    assert h.replica == "ok"


def test_detector_latency_ewma_suspects_gray_then_revives():
    gray = _FakeServer()
    router = ReplicaRouter(suspect_latency_factor=3.0,
                           min_suspect_latency=0.01)
    router.add_replica(gray, "gray")
    for _ in range(5):                       # healthy baseline EWMA
        router.check_health()
    assert router.replicas()["gray"] == "active"
    gray.probe_sleep = 0.08                  # alive but 10x slower
    router.check_health()
    assert router.replicas()["gray"] == "suspect"
    gray.probe_sleep = 0.0
    for _ in range(3):
        router.check_health()                # healthy probes revive it
    assert router.replicas()["gray"] == "active"
    snap = router.snapshot()
    assert snap["replicas_suspected"] >= 1
    assert snap["replicas_revived"] >= 1
    assert snap["replicas_failed"] == 0


def test_suspect_excluded_from_placement_until_no_active_left():
    a = _FakeServer(tokens=(1,))
    b = _FakeServer(tokens=(2,))
    router = ReplicaRouter()
    router.add_replica(a, "a")
    router.add_replica(b, "b")
    a.probe_exc = ConnectionError("gray")
    router.check_health()                    # a -> suspect
    assert router.replicas()["a"] == "suspect"
    for _ in range(3):                       # placement avoids the suspect
        h = router.submit(np.arange(4), max_new_tokens=1)
        assert h.replica == "b"
    b.probe_exc = ConnectionError("gray too")
    a.probe_exc = None
    router.check_health()                    # b -> suspect, a revives
    assert router.replicas() == {"a": "active", "b": "suspect"}
    b.probe_exc = None
    a.probe_exc = ConnectionError("down again")
    router.check_health()                    # a suspect again, b revives
    # all-suspect fallback: degraded beats NoReplicasAvailable
    b.probe_exc = ConnectionError("down")
    router.check_health()
    assert set(router.replicas().values()) == {"suspect"}
    h = router.submit(np.arange(4), max_new_tokens=1)
    assert h.replica in ("a", "b")
    # registry collector carries the membership gauges + counters
    from paddle_tpu.observability import default_registry

    snap = default_registry().snapshot()
    assert any(k.startswith("router.replicas_suspected")
               for k in snap["counters"])


# ------------------------------------------------------------- hedging
def test_hedge_fires_on_stall_and_winner_is_adopted():
    slow = _FakeServer(tokens=(7, 8, 9), stall_after=1)
    fast = _FakeServer(tokens=(7, 8, 9))
    router = _hedge_router()
    router.add_replica(slow, "slow")
    router.add_replica(fast, "fast")
    _warm_hedge(router)
    dumps_before = _flight.flight_recorder().stats()["dumps_written"]
    h = router.submit(np.arange(4), max_new_tokens=3, prefer="slow")
    out = h.result(timeout=30)
    assert list(out) == [7, 8, 9]            # token-identical winner
    assert h.replica == "fast"
    assert router.requests_hedged == 1 and router.hedge_wins == 1
    assert router.replicas()["slow"] == "active"    # gray, NOT dead
    rec = _flight.flight_recorder()
    assert rec.stats()["dumps_written"] == dumps_before + 1
    assert "hedge_fire" in rec.last_dump_path
    with open(rec.last_dump_path) as f:
        assert h.correlation_id in f.read()


def test_hedge_stream_switches_and_reemits():
    slow = _FakeServer(tokens=(4, 5, 6), stall_after=1)
    router = _hedge_router()
    router.add_replica(slow, "slow")
    router.add_replica(_FakeServer(tokens=(4, 5, 6)), "fast")
    _warm_hedge(router)
    h = router.submit(np.arange(4), max_new_tokens=3, prefer="slow")
    got = list(h.stream())
    # at-least-once: the switch re-emits from the hedge's first token,
    # and the re-emitted stream is the identical token sequence
    assert got[-3:] == [4, 5, 6]
    assert router.hedge_wins == 1


def test_hedge_without_second_replica_degrades_gracefully():
    only = _FakeServer(tokens=(1, 2, 3), pause=0.3)   # mid-stream stall
    router = _hedge_router()
    router.add_replica(only, "only")
    _warm_hedge(router)
    h = router.submit(np.arange(4), max_new_tokens=3)
    assert list(h.result(timeout=30)) == [1, 2, 3]    # still completes
    assert router.requests_hedged == 0                # no one to hedge to
    assert router.replicas()["only"] == "active"


def test_hedge_disabled_by_default_and_below_warmup():
    slow = _FakeServer(tokens=(1,), pause=None, delay=0.05)
    router = ReplicaRouter()                          # hedging off
    router.add_replica(slow, "a")
    assert router._hedge_threshold() is None
    router2 = _hedge_router()                         # on, but cold EWMA
    router2.add_replica(_FakeServer(), "a")
    assert router2._hedge_threshold() is None         # warmup gate


def test_overloaded_from_handle_is_not_a_death():
    shedding = _FakeServer(fail_with=Overloaded("shed from queue"))
    router = ReplicaRouter()
    router.add_replica(shedding, "only")
    h = router.submit(np.arange(4), max_new_tokens=2)
    with pytest.raises(Overloaded):
        h.result(timeout=10)
    assert h.reroutes == 0                   # backpressure != death
    assert router.replicas()["only"] == "active"


def test_router_fails_over_on_submit_overload():
    shedding = _FakeServer(submit_error=Overloaded("at capacity"))
    healthy = _FakeServer(tokens=(9,))
    router = ReplicaRouter()
    router.add_replica(shedding, "shedding")
    router.add_replica(healthy, "healthy")
    h = router.submit(np.arange(4), max_new_tokens=1, prefer="shedding")
    assert h.replica == "healthy"            # failover, not failure
    assert router.replicas()["shedding"] == "active"
    healthy.submit_error = Overloaded("also full")
    with pytest.raises(QueueFull):           # fleet-wide: retryable
        router.submit(np.arange(4), max_new_tokens=1)


# ------------------------------------------------- remote replicas (rpc)
@pytest.fixture(scope="module")
def rpc_world():
    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
    yield rpc
    rpc.shutdown(timeout=10.0)


def _remote(hosted, **kw):
    kw.setdefault("rpc_timeout", 5.0)
    kw.setdefault("connect_deadline", 0.4)
    kw.setdefault("poll_interval", 0.01)
    return RemoteReplica("solo", hosted_name=hosted, **kw)


def test_rpc_transport_error_names_peer(rpc_world):
    from paddle_tpu.distributed.rpc import RpcTransportError

    plan = FaultPlan([{"site": "rpc.connect.solo", "kind": "partition",
                       "times": None}], seed=0)
    with plan:
        with pytest.raises(RpcTransportError) as ei:
            rpc_world.rpc_sync("solo", int, args=(1,),
                               connect_deadline=0.2)
    assert ei.value.peer == "solo"
    assert isinstance(ei.value, ConnectionError)      # retryable class
    assert rpc_world.rpc_sync("solo", int, args=(1,)) == 1  # healed


def test_remote_replica_round_trip_and_probe_view(rpc_world):
    srv = _FakeServer(tokens=(11, 12, 13))
    srv.engine.active_count = 1
    srv.scheduler.depth = 3
    remote_mod.host_server(srv, "rt")
    rep = _remote("rt")
    router = ReplicaRouter()
    router.add_replica(rep, "remote")
    h = router.submit(np.arange(4), max_new_tokens=3)
    assert list(h.result(timeout=30)) == [11, 12, 13]
    assert h.correlation_id is not None
    # the probe refreshed the load view the placement scorer reads
    assert rep.engine.active_count == 1 and rep.scheduler.depth == 3
    assert rep.snapshot()["requests_completed"] == 1
    # remote submit kwargs crossed the wire intact (incl. corr id)
    assert srv.submitted[0]["correlation_id"] == h.correlation_id


def test_remote_queuefull_crosses_wire_and_fails_over(rpc_world):
    remote_mod.host_server(_FakeServer(submit_error=QueueFull("depth")),
                           "full")
    local = _FakeServer(tokens=(5,))
    router = ReplicaRouter()
    router.add_replica(_remote("full"), "remote")
    router.add_replica(local, "local")
    h = router.submit(np.arange(4), max_new_tokens=1, prefer="remote")
    assert h.replica == "local"              # backpressure failed over
    assert router.replicas()["remote"] == "active"


def test_remote_partition_death_abandons_and_reroutes(rpc_world):
    """THE remote acceptance: a partitioned peer's in-flight stream is
    abandoned by the detector-declared death and completes on a local
    survivor; the flight dump carries its correlation id."""
    remote_mod.host_server(_FakeServer(tokens=(1, 2, 3), delay=0.3),
                           "part")
    rep = _remote("part", rpc_timeout=1.5)
    router = ReplicaRouter(suspect_misses=1, dead_misses=2)
    router.add_replica(rep, "remote")
    router.add_replica(_FakeServer(tokens=(1, 2, 3)), "local")
    h = router.submit(np.arange(4), max_new_tokens=3, prefer="remote")
    plan = FaultPlan([{"site": "rpc.connect.solo", "kind": "partition",
                       "times": None}], seed=0)
    with plan:
        router.check_health()
        assert router.replicas()["remote"] == "suspect"
        router.check_health()                # second miss: dead + abandon
        assert router.replicas()["remote"] == "dead"
        out = h.result(timeout=30)           # rerouted by the abandon
    assert list(out) == [1, 2, 3]
    assert h.replica == "local" and h.reroutes >= 1
    path = _flight.flight_recorder().last_dump_path
    assert path is not None and "replica_dead" in path
    with open(path) as f:
        assert h.correlation_id in json.load(f)["extra"]["inflight"]


def test_remote_submit_to_unreachable_marks_dead_not_fatal(rpc_world):
    remote_mod.host_server(_FakeServer(tokens=(6,)), "alive")
    router = ReplicaRouter()
    router.add_replica(_remote("alive"), "good")
    dead = RemoteReplica("solo", hosted_name="alive", rpc_timeout=1.0,
                         connect_deadline=0.2, poll_interval=0.01)
    router.add_replica(dead, "bad")
    plan = FaultPlan([{"site": "rpc.connect.solo", "kind": "partition",
                       "times": None}], seed=0)
    # the partition cuts BOTH replicas' transport (same peer), so drive
    # placement onto the unreachable one while the plan is scoped to it
    with plan:
        with pytest.raises(ReplicaUnreachable):
            dead.submit(prompt=np.arange(4), max_new_tokens=1)
    h = router.submit(np.arange(4), max_new_tokens=1)
    assert list(h.result(timeout=30)) == [6]


# ------------------------------------------------ trace_view remote merge
def test_trace_view_merges_remote_reroute_into_one_lane(tmp_path):
    """A rerouted remote request's telemetry is scattered across the
    router process and two replica processes; trace_view must merge all
    of it into ONE lane keyed by the correlation id."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_view

    corr = "req-abc123-000042"
    t0 = 1000.0
    router_dump = {
        "format": "flight_recorder", "version": 1, "pid": 111,
        "reason": "replica_dead",
        "events": [{"t": t0 + 0.30, "kind": "replica_dead",
                    "corr": corr, "replica": "r2"}],
        "spans": [{"name": "router:submit", "corr": corr,
                   "t0": t0, "t1": t0 + 0.01, "tags": {"replica": "r2"}}],
    }
    replica_a = {
        "format": "flight_recorder", "version": 1, "pid": 222,
        "reason": "snapshot",
        "events": [],
        "spans": [{"name": "queue_wait", "corr": corr, "t0": t0 + 0.01,
                   "t1": t0 + 0.05, "tags": {}},
                  {"name": "decode", "corr": corr, "t0": t0 + 0.05,
                   "t1": t0 + 0.20, "tags": {"slot": 0}}],
    }
    replica_b = {
        "format": "flight_recorder", "version": 1, "pid": 333,
        "reason": "snapshot",
        "events": [],
        "spans": [{"name": "queue_wait", "corr": corr, "t0": t0 + 0.31,
                   "t1": t0 + 0.33, "tags": {}},
                  {"name": "decode", "corr": corr, "t0": t0 + 0.33,
                   "t1": t0 + 0.50, "tags": {"slot": 1}}],
    }
    paths = []
    for i, dump in enumerate((router_dump, replica_a, replica_b)):
        p = tmp_path / f"dump{i}.json"
        p.write_text(json.dumps(dump))
        paths.append(str(p))
    spans = []
    for p in paths:
        got, kind = trace_view.load_spans(p)
        assert kind == "flight"
        spans.extend(got)
    merged = trace_view.merge_chrome(spans, corr=corr)
    data_events = [e for e in merged["traceEvents"]
                   if e["ph"] in ("X", "i")]
    assert len(data_events) == 6             # 5 spans + the death event
    lanes = {e["tid"] for e in data_events}
    assert lanes == {1}                      # ONE lane across 3 processes
    sources = {e["args"].get("source") for e in data_events}
    assert len(sources) == 3                 # ...fed by all three dumps
    listing = trace_view.list_correlations(spans)
    assert len(listing) == 1 and listing[0]["corr"] == corr
    assert len(listing[0]["sources"]) == 3


# ------------------------------------------------------------------- slow
@pytest.mark.slow
def test_fleet_chaos_cli():
    """The robustness_gate --fleet-chaos command end-to-end: three rpc
    replica processes under SIGKILL + partition + slow + overload; exit
    0 means zero lost, zero divergence, sheds fast-failed, detector
    reroutes happened, and survivors held their compile budget."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PT_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_chaos.py"),
         "--quick"],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, (proc.stdout[-3000:]
                                  + proc.stderr[-2000:])
    rec = json.loads(
        [l for l in proc.stdout.splitlines()
         if l.startswith('{"fleet_chaos"')][-1])["fleet_chaos"]
    assert rec["failures"] == []
    assert rec["sheds"] > 0
    assert rec["requests_hedged"] >= 1
    assert rec["replicas_failed"] >= 2       # partition + SIGKILL
