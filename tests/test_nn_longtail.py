"""nn / nn.functional long-tail parity batch (r4): pooling variants,
unpool, fold, shuffles, losses, warps, hsigmoid, margin softmax, beam
search. Reference: python/paddle/nn/functional/__init__.py __all__ audit
(zero missing names after this batch)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _rand(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape),
                       jnp.float32)


def test_namespace_parity_vs_reference():
    import re

    def ref_all(path):
        s = open(path).read()
        m = re.search(r"__all__ = \[(.*?)\]", s, re.S)
        return set(re.findall(r"'(\w+)'", m.group(1)))

    for refp, mod in [
            ('/root/reference/python/paddle/nn/__init__.py', nn),
            ('/root/reference/python/paddle/nn/functional/__init__.py', F)]:
        try:
            ref = ref_all(refp)
        except OSError:
            pytest.skip("reference tree not mounted")
        missing = sorted(x for x in ref
                         if x not in set(dir(mod)) and not x.startswith('_'))
        assert missing == [], missing


def test_max_pool_mask_unpool_roundtrip():
    x = _rand((2, 3, 8, 8), 1)
    pooled, mask = F.max_pool2d(x, 2, 2, return_mask=True)
    assert pooled.shape == (2, 3, 4, 4) and mask.shape == (2, 3, 4, 4)
    # mask indexes the true maxima
    flat = np.asarray(x).reshape(2, 3, -1)
    np.testing.assert_allclose(
        np.take_along_axis(flat, np.asarray(mask).reshape(2, 3, -1), -1),
        np.asarray(pooled).reshape(2, 3, -1), rtol=1e-6)
    up = F.max_unpool2d(pooled, mask, 2)
    assert up.shape == x.shape
    nz = np.asarray(up) != 0
    np.testing.assert_allclose(np.asarray(up)[nz], np.asarray(x)[nz])
    u = nn.MaxUnPool2D(2)(pooled, mask)
    np.testing.assert_allclose(np.asarray(u), np.asarray(up))


def test_fold_unfold_inverse_and_adaptive3d():
    x = _rand((1, 2, 6, 6), 3)
    cols = F.unfold(x, 2, 2)
    back = F.fold(cols, (6, 6), 2, 2)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), rtol=1e-6)
    v = _rand((1, 2, 4, 6, 8), 4)
    o = F.adaptive_avg_pool3d(v, (2, 3, 4))
    assert o.shape == (1, 2, 2, 3, 4)
    np.testing.assert_allclose(float(o[0, 0, 0, 0, 0]),
                               float(jnp.mean(v[0, 0, :2, :2, :2])),
                               rtol=1e-5)
    assert F.adaptive_max_pool3d(v, 2).shape == (1, 2, 2, 2, 2)
    assert F.adaptive_max_pool1d(_rand((1, 2, 9), 5), 3).shape == (1, 2, 3)


def test_shuffles_pads_diag():
    x = _rand((1, 4, 4, 4), 6)
    cs = F.channel_shuffle(x, 2)
    assert cs.shape == x.shape
    np.testing.assert_allclose(np.asarray(cs[0, 1]), np.asarray(x[0, 2]))
    ps = F.pixel_shuffle(x, 2)
    pu = F.pixel_unshuffle(ps, 2)
    np.testing.assert_allclose(np.asarray(pu), np.asarray(x), rtol=1e-6)
    z = F.zeropad2d(x, (1, 2, 3, 4))
    assert z.shape == (1, 4, 4 + 3 + 4, 4 + 1 + 2)
    d = F.diag_embed(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(d), [[1, 0], [0, 2]])
    d2 = F.diag_embed(jnp.asarray([1.0]), offset=1)
    assert d2.shape == (2, 2) and float(d2[0, 1]) == 1.0


def test_loss_long_tail():
    x = _rand((4, 5), 7)
    y = jnp.asarray([1, 0, 3, 2])
    sm = F.soft_margin_loss(x[:, 0], jnp.asarray([1, -1, 1, -1]),
                            reduction="none")
    np.testing.assert_allclose(
        np.asarray(sm),
        np.log1p(np.exp(-np.asarray([1, -1, 1, -1]) * np.asarray(x[:, 0]))),
        rtol=1e-5)
    assert float(F.multi_margin_loss(x, y)) >= 0
    ml = F.multi_label_soft_margin_loss(x, (x > 0).astype(jnp.float32))
    assert np.isfinite(float(ml))
    assert np.isfinite(float(F.npair_loss(x, x + 0.1, y)))
    t = F.triplet_margin_with_distance_loss(x, x + 0.01, x + 5.0)
    assert float(t) == 0.0  # negative is far: hinge inactive
    p = jax.nn.softmax(x)
    assert 0 <= float(F.dice_loss(p, y[:, None])) <= 1
    ll = F.log_loss(jnp.asarray([0.9, 0.1]), jnp.asarray([1.0, 0.0]))
    assert (np.asarray(ll) > 0).all()
    pd = F.pairwise_distance(x, x + 1.0)
    np.testing.assert_allclose(np.asarray(pd), np.sqrt(5.0) * np.ones(4),
                               rtol=1e-3)


def test_hsigmoid_trains_and_layer_form():
    pt.seed(0)
    layer = nn.HSigmoidLoss(8, 16)
    x = _rand((6, 8), 8)
    y = jnp.asarray([0, 3, 7, 11, 15, 2])
    loss = layer(x, y)
    assert loss.shape == (6, 1) and np.isfinite(np.asarray(loss)).all()
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    params, buffers = param_state(layer), buffer_state(layer)

    def loss_fn(p):
        out, _ = functional_call(layer, p, buffers, x, y)
        return jnp.mean(out)

    l0 = float(loss_fn(params))
    g = jax.grad(loss_fn)(params)
    params = jax.tree.map(lambda a, b: a - 0.5 * b, params, g)
    assert float(loss_fn(params)) < l0


def test_margin_cross_entropy_properties():
    rng = np.random.default_rng(9)
    cos = jnp.asarray(rng.uniform(-0.9, 0.9, (8, 12)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 12, 8))
    plain = F.margin_cross_entropy(cos, y, margin1=1.0, margin2=0.0,
                                   margin3=0.0, scale=10.0)
    arc = F.margin_cross_entropy(cos, y, margin1=1.0, margin2=0.5,
                                 margin3=0.0, scale=10.0)
    assert float(arc) > float(plain)  # margins make the task harder
    loss, sm = F.margin_cross_entropy(cos, y, return_softmax=True)
    np.testing.assert_allclose(np.asarray(jnp.sum(sm, -1)), np.ones(8),
                               rtol=1e-5)


def test_affine_grid_sample_roundtrip():
    x = _rand((2, 3, 6, 8), 10)
    theta = jnp.tile(jnp.asarray([[[1.0, 0, 0], [0, 1, 0]]]), (2, 1, 1))
    g = F.affine_grid(theta, (2, 3, 6, 8))
    y = F.grid_sample(x, g)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)
    flip = jnp.tile(jnp.asarray([[[-1.0, 0, 0], [0, 1, 0]]]), (2, 1, 1))
    yf = F.grid_sample(x, F.affine_grid(flip, (2, 3, 6, 8)))
    np.testing.assert_allclose(np.asarray(yf), np.asarray(x)[..., ::-1],
                               atol=1e-5)
    F.grid_sample(x, g, mode="nearest", padding_mode="border")


def test_sparse_attention_matches_dense_on_full_csr():
    B, H, L, D = 1, 2, 4, 8
    q, k, v = _rand((B, H, L, D), 11), _rand((B, H, L, D), 12), _rand(
        (B, H, L, D), 13)
    offs = np.tile(np.arange(0, L * L + 1, L), (B, H, 1)).astype(np.int32)
    cols = np.tile(np.tile(np.arange(L), L), (B, H, 1)).astype(np.int32)
    out = F.sparse_attention(q, k, v, offs, cols)
    import math

    s = jnp.einsum("bhld,bhmd->bhlm", q, k) / math.sqrt(D)
    ref = jnp.einsum("bhlm,bhmd->bhld", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_beam_search_decoder():
    """A deterministic toy LM: beam search must find the argmax chain and
    stop at end_token, with ancestry correctly backtraced."""
    V = 6
    table = np.full((V, V), -5.0, np.float32)
    for t in range(V - 1):
        table[t, t + 1] = 5.0
    table[4, 5] = 10.0

    def cell(emb_ids, states):
        return jnp.asarray(table)[emb_ids], states

    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=5,
                               beam_size=3)
    seqs, lp = nn.dynamic_decode(dec, inits={"h": jnp.zeros((2, 1))},
                                 max_step_num=10)
    best = np.asarray(seqs)[:, 0]
    for b in range(2):
        assert best[b].tolist()[:5] == [1, 2, 3, 4, 5], best[b]
    ids = jnp.asarray([[[1, 2]], [[3, 4]]])          # T=2, B=1, K=2
    par = jnp.asarray([[[0, 0]], [[1, 0]]])          # step1 beam0 from beam1
    seq = F.gather_tree(ids, par)
    assert np.asarray(seq)[:, 0, 0].tolist() == [2, 3]
