"""tpu_lint: trace-discipline static analysis.

Per rule (R1–R5): >=2 true-positive fixtures modeled on real (pre-fix)
defect shapes from this repo, plus >=1 false-positive guard proving the
idioms the codebase relies on stay clean. Then the policy layer
(mandatory suppression reasons, baseline accept/new/stale semantics), the
CLI exit codes, and a whole-repo smoke run against the checked-in
baseline asserting zero NEW findings.

Everything here is pure-AST over tmp fixture trees — no jit, no device
work — so the module stays far under the tier-1 time budget (the one
whole-repo parse is ~5 s on the 2-core box).
"""
import importlib.util
import json
import os
import textwrap

import pytest

from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                 save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(tmp_path, source, name="mod.py"):
    """Write one fixture module and run every rule over it."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze(str(tmp_path), ["."]).findings


def rules_at(findings, rule):
    return [f for f in findings if f.rule == rule]


# ================================================================== R1
def test_r1_item_in_trace_reachable(tmp_path):
    # pre-fix GradScaler shape: a per-flag .item() readback inside code
    # reachable from a jit entry point
    fs = lint(tmp_path, """
        import jax

        def check(flag):
            return flag.item()

        @jax.jit
        def step(x, flag):
            if check(flag):
                return x
            return x * 2
    """)
    r1 = rules_at(fs, "R1")
    assert any(".item()" in f.message and f.symbol == "check" for f in r1)
    # the finding names the jit entry that makes the helper reachable
    assert any("step" in " ".join(f.chain) for f in r1 if f.symbol == "check")


def test_r1_np_asarray_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def fwd(ids):
            rows = np.asarray(ids)
            return rows
    """)
    assert any("np.asarray" in f.message for f in rules_at(fs, "R1"))


def test_r1_lazy_dispatch_readback(tmp_path):
    # pre-fix ContinuousBatchingEngine.admit shape: two serialized scalar
    # reads of compiled-call results instead of one batched device_get
    fs = lint(tmp_path, """
        import jax

        def admit(x):
            step = jax.jit(lambda v: (v, v > 0))
            tok, done = step(x)
            first = int(tok)
            fin = bool(done)
            return first, fin
    """)
    msgs = [f.message for f in rules_at(fs, "R1")]
    assert any("`int()`" in m for m in msgs)
    assert any("`bool()`" in m for m in msgs)


def test_r1_method_form_block_until_ready(tmp_path):
    # `arr.block_until_ready()` must be caught like the function form
    fs = lint(tmp_path, """
        def fence(out):
            out.block_until_ready()
            return out
    """)
    assert any(".block_until_ready()" in f.message
               for f in rules_at(fs, "R1"))


def test_r1_guard_region_is_clean(tmp_path):
    # viterbi_decode shape: the host read happens only on the proven-
    # concrete side of an isinstance(..., Tracer) guard
    fs = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def decode(paths, lengths):
            if not isinstance(lengths, jax.core.Tracer):
                paths = paths[:, :int(jnp.max(lengths))]
            return paths
    """)
    assert rules_at(fs, "R1") == []


def test_r1_batched_device_get_is_explicit_not_implicit(tmp_path):
    # the repo's fixed shape — ONE batched device_get — still surfaces as
    # an explicit-sync finding (it must carry a reason), but the int()/
    # bool() on the fetched host values are clean
    fs = lint(tmp_path, """
        import jax

        def step(x):
            run = jax.jit(lambda v: (v, v > 0))
            tok, done = run(x)
            tok_h, done_h = jax.device_get((tok, done))
            return int(tok_h), bool(done_h)
    """)
    r1 = rules_at(fs, "R1")
    assert len(r1) == 1 and "device_get" in r1[0].message


def test_r1_module_level_jit_wrap_site(tmp_path):
    # `run = jax.jit(body)` at FILE scope must make body a trace root
    fs = lint(tmp_path, """
        import jax

        def body(x):
            v = float(x)
            return x * v

        run = jax.jit(body)
    """)
    assert any("`float()`" in f.message and f.symbol == "body"
               for f in rules_at(fs, "R1"))


# ================================================================== R2
def test_r2_branch_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x, n):
            if n > 3:
                return x
            while n < 0:
                n = n + 1
            return x * 2
    """)
    r2 = rules_at(fs, "R2")
    assert any("`if` branches" in f.message for f in r2)
    assert any("`while` branches" in f.message for f in r2)


def test_r2_fstring_of_tracer(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            label = f"loss={x}"
            return x
    """)
    assert any("f-string" in f.message for f in rules_at(fs, "R2"))


def test_r2_jit_inside_loop(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def train(batches):
            for b in batches:
                step = jax.jit(lambda v: v * 2)
                step(b)
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R2"))


def test_r2_shape_branch_is_static(tmp_path):
    # bucketed-prefill idiom: branching on .shape is per-shape
    # specialization (how the compile-budget design works), not a hazard
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x):
            if x.shape[0] > 8:
                return x[:8]
            return x
    """)
    assert rules_at(fs, "R2") == []


def test_r2_isinstance_guarded_branch_is_clean(tmp_path):
    # generation.py prefill-vs-decode dispatch: isinstance(pos, int)
    # proves the scalar is static on that path
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x, pos):
            if isinstance(pos, int) and pos == 0:
                return x
            return x + 1
    """)
    assert rules_at(fs, "R2") == []


# ================================================================== R3
def test_r3_donated_then_read(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def run(state, x):
            step = jax.jit(lambda s, v: s + v, donate_argnums=(0,))
            out = step(state, x)
            return out + state
    """)
    r3 = rules_at(fs, "R3")
    assert any("`state` was donated" in f.message for f in r3)


def test_r3_donated_in_loop_without_rebind(tmp_path):
    # the decode-loop bug shape the KV-cache engine is built to avoid:
    # iteration 2 would dispatch an already-donated buffer
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: c, donate_argnums=(0,))
            outs = []
            for x in xs:
                outs.append(step(cache, x))
            return outs
    """)
    assert any("never reassigned in the loop" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_decorator_jitted_callee(tmp_path):
    # @partial(jax.jit, donate_argnums=...) — calling the bare name IS a
    # dispatch of the compiled callable, so donation rules apply
    fs = lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def run(state, x):
            out = step(state, x)
            return out + state
    """)
    assert any("`state` was donated" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_rebound_from_results_is_clean(tmp_path):
    # the repo's actual decode loop: the donated cache is rebound from
    # the call's results every iteration
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: (c, v), donate_argnums=(0,))
            for x in xs:
                cache, tok = step(cache, x)
            return cache
    """)
    assert rules_at(fs, "R3") == []


# ================================================================== R4
def test_r4_key_reused_across_two_ops(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            a = jax.random.categorical(key, logits)
            b = jax.random.categorical(key, logits)
            return a, b
    """)
    r4 = rules_at(fs, "R4")
    assert any("consumed again" in f.message for f in r4)


def test_r4_key_reused_across_loop(tmp_path):
    # the PR-4 historical bug: one key for every decode step (and every
    # row) — identical prompts sampled identical continuations
    fs = lint(tmp_path, """
        import jax

        def decode(key, steps, logits):
            toks = []
            for _ in range(steps):
                toks.append(jax.random.categorical(key, logits))
            return toks
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R4"))


def test_r4_split_and_fold_in_are_clean(tmp_path):
    # the fixed shapes: split per use, fold_in per iteration (the
    # per-(step,row) key derivation), branch-exclusive single use
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            key, k1 = jax.random.split(key)
            a = jax.random.categorical(k1, logits)
            key, k2 = jax.random.split(key)
            b = jax.random.categorical(k2, logits)
            return a, b

        def decode(key, steps, logits):
            toks = []
            for i in range(steps):
                toks.append(jax.random.categorical(
                    jax.random.fold_in(key, i), logits))
            return toks

        def either(key, logits, greedy):
            if greedy:
                return jax.random.categorical(key, logits)
            return jax.random.categorical(key, logits)
    """)
    assert rules_at(fs, "R4") == []


def test_r4_from_import_forms(tmp_path):
    # `from jax import random` and `from jax.random import normal` must
    # be recognized as key consumers, not just `jax.random.*` chains
    fs = lint(tmp_path, """
        from jax import random
        from jax.random import normal

        def a(key, logits):
            x = random.categorical(key, logits)
            y = random.categorical(key, logits)
            return x, y

        def b(key):
            u = normal(key, (4,))
            v = normal(key, (4,))
            return u, v
    """)
    r4 = rules_at(fs, "R4")
    assert {f.symbol for f in r4} == {"a", "b"}


def test_r4_interprocedural_consumption(tmp_path):
    # reuse through a helper: the callee's param is (transitively) key-
    # consuming, so passing the same key twice correlates the draws
    fs = lint(tmp_path, """
        import jax

        def draw(key, logits):
            return jax.random.categorical(key, logits)

        def sample(key, logits):
            a = draw(key, logits)
            b = draw(key, logits)
            return a, b
    """)
    assert len(rules_at(fs, "R4")) >= 1


# ================================================================== R5
def test_r5_unguarded_read_in_threaded_class(tmp_path):
    # pre-fix InferenceServer.shutdown shape: _thread read outside the
    # condition variable that guards it at every other site
    fs = lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = None
                self._stop = False

            def start(self):
                with self._lock:
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

            def _run(self):
                with self._lock:
                    if self._thread is None:
                        return

            def shutdown(self):
                with self._lock:
                    self._stop = True
                t = self._thread
                return t
    """)
    r5 = rules_at(fs, "R5")
    assert any(f.symbol == "Server.shutdown" and "_thread" in f.message
               for f in r5)


def test_r5_unguarded_write_from_worker(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self._n += 1

            def read(self):
                with self._lock:
                    return self._n

            def bump(self):
                with self._lock:
                    self._n += 1
    """)
    assert any("_n" in f.message and f.symbol == "Counter._work"
               for f in rules_at(fs, "R5"))


def test_r5_lock_inherited_by_private_helper(tmp_path):
    # helper only ever called with the lock held inherits its context —
    # the scheduler/engine idiom; no finding
    fs = lint(tmp_path, """
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._t = threading.Thread(target=self.drain)

            def drain(self):
                with self._lock:
                    self._flush()

            def push(self, x):
                with self._lock:
                    self._items.append(x)
                    self._flush()

            def _flush(self):
                while self._items:
                    self._items.pop()
    """)
    assert rules_at(fs, "R5") == []


def test_r5_single_threaded_class_ignored(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self._n
    """)
    assert rules_at(fs, "R5") == []


# ===================================================== suppression policy
def test_suppression_with_reason_is_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            # tpu-lint: disable=R1(deliberate batched flush point)
            return jax.device_get(flags)
    """)
    assert rules_at(fs, "R1") == []
    assert rules_at(fs, "R0") == []


def test_bare_suppression_is_r0_and_not_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    assert any("no reason" in f.message for f in rules_at(fs, "R0"))
    assert len(rules_at(fs, "R1")) == 1  # the bare disable did nothing


def test_suppression_examples_in_docstrings_are_inert(tmp_path):
    # a suppression QUOTED in a docstring must neither install a real
    # suppression nor (bare form) raise R0 — only true comments count
    fs = lint(tmp_path, '''
        """Module doc.

            x = y.item()  # tpu-lint: disable-file=R1(docstring example)
            z = q.item()  # tpu-lint: disable=R1
        """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    ''')
    assert rules_at(fs, "R0") == []          # bare example is inert
    assert len(rules_at(fs, "R1")) == 1      # file-disable example too


def test_file_level_suppression(tmp_path):
    fs = lint(tmp_path, """
        # tpu-lint: disable-file=R1(host-side tool by contract)
        import jax

        def a(x):
            return jax.device_get(x)

        def b(x):
            return x.item()
    """)
    assert rules_at(fs, "R1") == []


# ============================================================== baseline
def test_baseline_accepts_then_fails_new(tmp_path):
    src = """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """
    findings = lint(tmp_path, src)
    assert len(findings) == 1
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    baseline = load_baseline(str(bl_path))

    new, stale = diff_baseline(findings, baseline)
    assert new == [] and stale == []

    # a second, new occurrence (different function) fails
    grown = lint(tmp_path, src + """
        def flush2(flags):
            return jax.device_get(flags)
    """)
    new, _ = diff_baseline(grown, baseline)
    assert len(new) == 1 and new[0].symbol == "flush2"

    # line drift does NOT churn the baseline (keys carry no line numbers)
    drifted = lint(tmp_path, "\n\n\n" + textwrap.dedent(src))
    new, stale = diff_baseline(drifted, baseline)
    assert new == [] and stale == []


def test_baseline_stale_keys_reported_not_failing(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """)
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    new, stale = diff_baseline([], load_baseline(str(bl_path)))
    assert new == [] and len(stale) == 1


def test_r0_findings_are_never_baselinable(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    r0 = rules_at(findings, "R0")
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)   # counts include the R0 key
    new, _ = diff_baseline(findings, load_baseline(str(bl_path)))
    assert any(f.rule == "R0" for f in new)  # still fails


# ==================================================== CLI + repo smoke
def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "tpu_lint_cli", os.path.join(REPO, "tools", "tpu_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_nonzero_on_injected_violation(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x, n):
            if n > 0:
                return x
            return x.item()
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))
    assert cli.main([str(bad), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "R1" in out and "R2" in out

    # --json carries the machine-readable findings + keys
    assert cli.main([str(bad), "--no-baseline", "--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in data["new_findings"]} == {"R1", "R2"}
    assert all("key" in f for f in data["findings"])

    assert cli.main(["nope_not_here"]) == 2

    # --update-baseline over a subtree would erase the accepted entries
    # outside it; the CLI must refuse
    assert cli.main([str(bad), "--update-baseline"]) == 2


def test_repo_is_clean_under_checked_in_baseline(capsys):
    """THE gate: the shipped tree + .tpu_lint_baseline.json => zero new
    findings. Any regression (new sync/retrace/donation/key/lock bug, or
    a reason-less suppression) fails this test before the runtime soaks
    ever see it."""
    cli = _load_cli()
    rc = cli.main([])   # defaults: paddle_tpu + tools, default baseline
    out = capsys.readouterr().out
    assert rc == 0, f"tpu_lint found NEW findings:\n{out}"
    assert "no new findings" in out
    # the analyzer really saw the tree (not an empty walk)
    assert "trace roots" in out.split("\n")[0]
