"""tpu_lint: trace-discipline static analysis.

Per rule (R1–R11): >=2 true-positive fixtures modeled on real (pre-fix)
defect shapes from this repo, plus >=1 false-positive guard proving the
idioms the codebase relies on stay clean. Then the policy layer
(mandatory suppression reasons, baseline accept/new/stale semantics), the
incremental engine (content-hash cache invalidation, ``--changed-only``,
the cache-schema bump), the SARIF round-trip, the CLI exit codes, and a
whole-repo smoke run against the checked-in baseline asserting zero NEW
findings (plus the real lock graph naming the serving/lora acquisition
edges and the real lifecycle graph naming the engine pin sites).

Everything here is pure-AST over tmp fixture trees — no jit, no device
work — so the module stays far under the tier-1 time budget (the one
whole-repo parse is ~6 s on the 2-core box; its result is cached, so the
later whole-repo assertions are millisecond cache hits).
"""
import importlib.util
import json
import os
import subprocess
import textwrap

import pytest

from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                 save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(tmp_path, source, name="mod.py"):
    """Write one fixture module and run every rule over it."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze(str(tmp_path), ["."]).findings


def rules_at(findings, rule):
    return [f for f in findings if f.rule == rule]


# ================================================================== R1
def test_r1_item_in_trace_reachable(tmp_path):
    # pre-fix GradScaler shape: a per-flag .item() readback inside code
    # reachable from a jit entry point
    fs = lint(tmp_path, """
        import jax

        def check(flag):
            return flag.item()

        @jax.jit
        def step(x, flag):
            if check(flag):
                return x
            return x * 2
    """)
    r1 = rules_at(fs, "R1")
    assert any(".item()" in f.message and f.symbol == "check" for f in r1)
    # the finding names the jit entry that makes the helper reachable
    assert any("step" in " ".join(f.chain) for f in r1 if f.symbol == "check")


def test_r1_np_asarray_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def fwd(ids):
            rows = np.asarray(ids)
            return rows
    """)
    assert any("np.asarray" in f.message for f in rules_at(fs, "R1"))


def test_r1_lazy_dispatch_readback(tmp_path):
    # pre-fix ContinuousBatchingEngine.admit shape: two serialized scalar
    # reads of compiled-call results instead of one batched device_get
    fs = lint(tmp_path, """
        import jax

        def admit(x):
            step = jax.jit(lambda v: (v, v > 0))
            tok, done = step(x)
            first = int(tok)
            fin = bool(done)
            return first, fin
    """)
    msgs = [f.message for f in rules_at(fs, "R1")]
    assert any("`int()`" in m for m in msgs)
    assert any("`bool()`" in m for m in msgs)


def test_r1_method_form_block_until_ready(tmp_path):
    # `arr.block_until_ready()` must be caught like the function form
    fs = lint(tmp_path, """
        def fence(out):
            out.block_until_ready()
            return out
    """)
    assert any(".block_until_ready()" in f.message
               for f in rules_at(fs, "R1"))


def test_r1_guard_region_is_clean(tmp_path):
    # viterbi_decode shape: the host read happens only on the proven-
    # concrete side of an isinstance(..., Tracer) guard
    fs = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def decode(paths, lengths):
            if not isinstance(lengths, jax.core.Tracer):
                paths = paths[:, :int(jnp.max(lengths))]
            return paths
    """)
    assert rules_at(fs, "R1") == []


def test_r1_batched_device_get_is_explicit_not_implicit(tmp_path):
    # the repo's fixed shape — ONE batched device_get — still surfaces as
    # an explicit-sync finding (it must carry a reason), but the int()/
    # bool() on the fetched host values are clean
    fs = lint(tmp_path, """
        import jax

        def step(x):
            run = jax.jit(lambda v: (v, v > 0))
            tok, done = run(x)
            tok_h, done_h = jax.device_get((tok, done))
            return int(tok_h), bool(done_h)
    """)
    r1 = rules_at(fs, "R1")
    assert len(r1) == 1 and "device_get" in r1[0].message


def test_r1_module_level_jit_wrap_site(tmp_path):
    # `run = jax.jit(body)` at FILE scope must make body a trace root
    fs = lint(tmp_path, """
        import jax

        def body(x):
            v = float(x)
            return x * v

        run = jax.jit(body)
    """)
    assert any("`float()`" in f.message and f.symbol == "body"
               for f in rules_at(fs, "R1"))


# ================================================================== R2
def test_r2_branch_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x, n):
            if n > 3:
                return x
            while n < 0:
                n = n + 1
            return x * 2
    """)
    r2 = rules_at(fs, "R2")
    assert any("`if` branches" in f.message for f in r2)
    assert any("`while` branches" in f.message for f in r2)


def test_r2_fstring_of_tracer(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            label = f"loss={x}"
            return x
    """)
    assert any("f-string" in f.message for f in rules_at(fs, "R2"))


def test_r2_jit_inside_loop(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def train(batches):
            for b in batches:
                step = jax.jit(lambda v: v * 2)
                step(b)
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R2"))


def test_r2_shape_branch_is_static(tmp_path):
    # bucketed-prefill idiom: branching on .shape is per-shape
    # specialization (how the compile-budget design works), not a hazard
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x):
            if x.shape[0] > 8:
                return x[:8]
            return x
    """)
    assert rules_at(fs, "R2") == []


def test_r2_isinstance_guarded_branch_is_clean(tmp_path):
    # generation.py prefill-vs-decode dispatch: isinstance(pos, int)
    # proves the scalar is static on that path
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x, pos):
            if isinstance(pos, int) and pos == 0:
                return x
            return x + 1
    """)
    assert rules_at(fs, "R2") == []


# ================================================================== R3
def test_r3_donated_then_read(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def run(state, x):
            step = jax.jit(lambda s, v: s + v, donate_argnums=(0,))
            out = step(state, x)
            return out + state
    """)
    r3 = rules_at(fs, "R3")
    assert any("`state` was donated" in f.message for f in r3)


def test_r3_donated_in_loop_without_rebind(tmp_path):
    # the decode-loop bug shape the KV-cache engine is built to avoid:
    # iteration 2 would dispatch an already-donated buffer
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: c, donate_argnums=(0,))
            outs = []
            for x in xs:
                outs.append(step(cache, x))
            return outs
    """)
    assert any("never reassigned in the loop" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_decorator_jitted_callee(tmp_path):
    # @partial(jax.jit, donate_argnums=...) — calling the bare name IS a
    # dispatch of the compiled callable, so donation rules apply
    fs = lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def run(state, x):
            out = step(state, x)
            return out + state
    """)
    assert any("`state` was donated" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_rebound_from_results_is_clean(tmp_path):
    # the repo's actual decode loop: the donated cache is rebound from
    # the call's results every iteration
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: (c, v), donate_argnums=(0,))
            for x in xs:
                cache, tok = step(cache, x)
            return cache
    """)
    assert rules_at(fs, "R3") == []


# ================================================================== R4
def test_r4_key_reused_across_two_ops(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            a = jax.random.categorical(key, logits)
            b = jax.random.categorical(key, logits)
            return a, b
    """)
    r4 = rules_at(fs, "R4")
    assert any("consumed again" in f.message for f in r4)


def test_r4_key_reused_across_loop(tmp_path):
    # the PR-4 historical bug: one key for every decode step (and every
    # row) — identical prompts sampled identical continuations
    fs = lint(tmp_path, """
        import jax

        def decode(key, steps, logits):
            toks = []
            for _ in range(steps):
                toks.append(jax.random.categorical(key, logits))
            return toks
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R4"))


def test_r4_split_and_fold_in_are_clean(tmp_path):
    # the fixed shapes: split per use, fold_in per iteration (the
    # per-(step,row) key derivation), branch-exclusive single use
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            key, k1 = jax.random.split(key)
            a = jax.random.categorical(k1, logits)
            key, k2 = jax.random.split(key)
            b = jax.random.categorical(k2, logits)
            return a, b

        def decode(key, steps, logits):
            toks = []
            for i in range(steps):
                toks.append(jax.random.categorical(
                    jax.random.fold_in(key, i), logits))
            return toks

        def either(key, logits, greedy):
            if greedy:
                return jax.random.categorical(key, logits)
            return jax.random.categorical(key, logits)
    """)
    assert rules_at(fs, "R4") == []


def test_r4_from_import_forms(tmp_path):
    # `from jax import random` and `from jax.random import normal` must
    # be recognized as key consumers, not just `jax.random.*` chains
    fs = lint(tmp_path, """
        from jax import random
        from jax.random import normal

        def a(key, logits):
            x = random.categorical(key, logits)
            y = random.categorical(key, logits)
            return x, y

        def b(key):
            u = normal(key, (4,))
            v = normal(key, (4,))
            return u, v
    """)
    r4 = rules_at(fs, "R4")
    assert {f.symbol for f in r4} == {"a", "b"}


def test_r4_interprocedural_consumption(tmp_path):
    # reuse through a helper: the callee's param is (transitively) key-
    # consuming, so passing the same key twice correlates the draws
    fs = lint(tmp_path, """
        import jax

        def draw(key, logits):
            return jax.random.categorical(key, logits)

        def sample(key, logits):
            a = draw(key, logits)
            b = draw(key, logits)
            return a, b
    """)
    assert len(rules_at(fs, "R4")) >= 1


# ================================================================== R5
def test_r5_unguarded_read_in_threaded_class(tmp_path):
    # pre-fix InferenceServer.shutdown shape: _thread read outside the
    # condition variable that guards it at every other site
    fs = lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = None
                self._stop = False

            def start(self):
                with self._lock:
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

            def _run(self):
                with self._lock:
                    if self._thread is None:
                        return

            def shutdown(self):
                with self._lock:
                    self._stop = True
                t = self._thread
                return t
    """)
    r5 = rules_at(fs, "R5")
    assert any(f.symbol == "Server.shutdown" and "_thread" in f.message
               for f in r5)


def test_r5_unguarded_write_from_worker(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self._n += 1

            def read(self):
                with self._lock:
                    return self._n

            def bump(self):
                with self._lock:
                    self._n += 1
    """)
    assert any("_n" in f.message and f.symbol == "Counter._work"
               for f in rules_at(fs, "R5"))


def test_r5_lock_inherited_by_private_helper(tmp_path):
    # helper only ever called with the lock held inherits its context —
    # the scheduler/engine idiom; no finding
    fs = lint(tmp_path, """
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._t = threading.Thread(target=self.drain)

            def drain(self):
                with self._lock:
                    self._flush()

            def push(self, x):
                with self._lock:
                    self._items.append(x)
                    self._flush()

            def _flush(self):
                while self._items:
                    self._items.pop()
    """)
    assert rules_at(fs, "R5") == []


def test_r5_single_threaded_class_ignored(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self._n
    """)
    assert rules_at(fs, "R5") == []


# ===================================================== suppression policy
def test_suppression_with_reason_is_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            # tpu-lint: disable=R1(deliberate batched flush point)
            return jax.device_get(flags)
    """)
    assert rules_at(fs, "R1") == []
    assert rules_at(fs, "R0") == []


def test_bare_suppression_is_r0_and_not_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    assert any("no reason" in f.message for f in rules_at(fs, "R0"))
    assert len(rules_at(fs, "R1")) == 1  # the bare disable did nothing


def test_suppression_examples_in_docstrings_are_inert(tmp_path):
    # a suppression QUOTED in a docstring must neither install a real
    # suppression nor (bare form) raise R0 — only true comments count
    fs = lint(tmp_path, '''
        """Module doc.

            x = y.item()  # tpu-lint: disable-file=R1(docstring example)
            z = q.item()  # tpu-lint: disable=R1
        """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    ''')
    assert rules_at(fs, "R0") == []          # bare example is inert
    assert len(rules_at(fs, "R1")) == 1      # file-disable example too


def test_file_level_suppression(tmp_path):
    fs = lint(tmp_path, """
        # tpu-lint: disable-file=R1(host-side tool by contract)
        import jax

        def a(x):
            return jax.device_get(x)

        def b(x):
            return x.item()
    """)
    assert rules_at(fs, "R1") == []


# ============================================================== baseline
def test_baseline_accepts_then_fails_new(tmp_path):
    src = """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """
    findings = lint(tmp_path, src)
    assert len(findings) == 1
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    baseline = load_baseline(str(bl_path))

    new, stale = diff_baseline(findings, baseline)
    assert new == [] and stale == []

    # a second, new occurrence (different function) fails
    grown = lint(tmp_path, src + """
        def flush2(flags):
            return jax.device_get(flags)
    """)
    new, _ = diff_baseline(grown, baseline)
    assert len(new) == 1 and new[0].symbol == "flush2"

    # line drift does NOT churn the baseline (keys carry no line numbers)
    drifted = lint(tmp_path, "\n\n\n" + textwrap.dedent(src))
    new, stale = diff_baseline(drifted, baseline)
    assert new == [] and stale == []


def test_baseline_stale_keys_reported_not_failing(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """)
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    new, stale = diff_baseline([], load_baseline(str(bl_path)))
    assert new == [] and len(stale) == 1


def test_r0_findings_are_never_baselinable(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    r0 = rules_at(findings, "R0")
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)   # counts include the R0 key
    new, _ = diff_baseline(findings, load_baseline(str(bl_path)))
    assert any(f.rule == "R0" for f in new)  # still fails


# ================================================================== R6
def test_r6_interprocedural_reentry(tmp_path):
    # acquiring a non-reentrant Lock inside a helper reached from a
    # region already holding it — the single-thread self-deadlock
    fs = lint(tmp_path, """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v
                    self._evict()

            def _evict(self):
                with self._lock:
                    self._items.clear()
    """)
    r6 = rules_at(fs, "R6")
    assert any("re-enters non-reentrant" in f.message
               and f.symbol == "Store._evict" for f in r6)
    # the evidence chain names the path that arrives with the lock held
    assert any("Store.put" in " ".join(f.chain) for f in r6)


def test_r6_cross_class_lock_order_cycle(tmp_path):
    # A->B on one path, B->A on another: two threads interleaving
    # deadlock. The second acquire is behind a cross-object method call.
    fs = lint(tmp_path, """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self.a = A()

            def bump(self):
                with self._lock:
                    self._n += 1

            def poke(self):
                with self._lock:
                    self.a.fwd()

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._lock:
                    self.b.bump()
    """)
    r6 = rules_at(fs, "R6")
    assert any("lock-order cycle" in f.message for f in r6)


def test_r6_overlapping_cycles_all_edges_named(tmp_path):
    # a<->b and b<->c share one SCC: the finding must name EVERY edge
    # of the knot (not a synthetic walk that hides the second deadlock)
    fs = lint(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def down(self):
                with self._lock:
                    self.b.noop()

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()
                self.c = C()

            def noop(self):
                with self._lock:
                    pass

            def poke(self):
                with self._lock:
                    self.a.ping()

            def up(self):
                with self._lock:
                    self.c.down()

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def ping(self):
                with self._lock:
                    pass

            def fwd(self):
                with self._lock:
                    self.b.noop()
    """)
    cyc = [f for f in rules_at(fs, "R6")
           if "lock-order cycle" in f.message]
    text = " ".join(f.message for f in cyc)
    # both deadlock pairs surface, with both directions of each
    assert "A._lock -> B._lock" in text and "B._lock -> A._lock" in text
    assert "B._lock -> C._lock" in text and "C._lock -> B._lock" in text


def test_r6_consistent_order_is_clean(tmp_path):
    # nested locks taken in ONE global order everywhere — legal
    fs = lint(tmp_path, """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._lock:
                    self.b.bump()

            def bwd(self):
                with self._lock:
                    self.b.bump()
    """)
    assert rules_at(fs, "R6") == []


def test_r6_rlock_reentry_and_cv_alias_are_clean(tmp_path):
    # RLock re-entry is legal; Condition(self._lock) is the SAME lock
    # (one node in the graph), not a second lock ordered against it
    fs = lint(tmp_path, """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.RLock()
                self._n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self._n += 1

        class Cv:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._q = []

            def put(self, x):
                with self._cv:
                    self._q.append(x)
                    self._cv.notify_all()

            def flush(self):
                with self._lock:
                    self._q.clear()
    """)
    assert rules_at(fs, "R6") == []
    # and the alias really collapsed: a cv re-entry IS caught
    fs2 = lint(tmp_path, """
        import threading

        class Cv:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._q = []

            def put(self, x):
                with self._cv:
                    self._drain()

            def _drain(self):
                with self._lock:
                    self._q.clear()
    """, name="mod2.py")
    assert any("re-enters non-reentrant" in f.message
               for f in rules_at(fs2, "R6"))


# ================================================================== R7
def test_r7_device_page_write_under_lock(tmp_path):
    # the pre-fix AdapterStore shape: .at[slot].set H2D staging while
    # holding the metadata lock every placement probe contends
    fs = lint(tmp_path, """
        import threading

        class PageStore:
            def __init__(self, stacks):
                self._lock = threading.Lock()
                self.tensors = stacks
                self._names = {}

            def acquire(self, name, slot, pages):
                with self._lock:
                    self.tensors = {
                        k: (a.at[slot].set(pages[k][0]),
                            b.at[slot].set(pages[k][1]))
                        for k, (a, b) in self.tensors.items()}
                    self._names[name] = slot

            def resident(self, name):
                with self._lock:
                    return name in self._names
    """)
    r7 = rules_at(fs, "R7")
    assert any("device buffer update" in f.message
               and f.symbol == "PageStore.acquire" for f in r7)


def test_r7_sleep_and_unbounded_wait_under_lock(tmp_path):
    fs = lint(tmp_path, """
        import threading
        import time

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()
                self._jobs = []

            def poll(self):
                with self._lock:
                    time.sleep(0.5)
                    return list(self._jobs)

            def wait_all(self):
                with self._cv:
                    self._cv.wait()
    """)
    r7 = rules_at(fs, "R7")
    assert any("`time.sleep`" in f.message for f in r7)
    assert any("unbounded `.wait()`" in f.message for f in r7)


def test_r7_io_and_sync_under_lock_interprocedural(tmp_path):
    # the blocking op hides in a helper only reached with the lock held
    fs = lint(tmp_path, """
        import threading
        import jax

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []

            def dump(self, path, flags):
                with self._lock:
                    self._write(path)
                    host = jax.device_get(flags)
                return host

            def _write(self, path):
                with open(path, "w") as f:
                    f.write(str(self._events))
    """)
    r7 = rules_at(fs, "R7")
    assert any("file I/O" in f.message and f.symbol == "Recorder._write"
               for f in r7)
    assert any("host sync" in f.message and f.symbol == "Recorder.dump"
               for f in r7)


def test_r7_bounded_wait_and_io_outside_lock_are_clean(tmp_path):
    # the repo's fixed shapes: timeout-bounded cv.wait in the serve
    # loop, and the flight recorder's snapshot-under-lock/write-outside
    fs = lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._cv = threading.Condition()
                self._stop = False
                self._events = []

            def loop(self):
                with self._cv:
                    while not self._stop:
                        self._cv.wait(0.1)

            def dump(self, path):
                with self._cv:
                    events = list(self._events)
                with open(path, "w") as f:
                    f.write(str(events))
    """)
    assert rules_at(fs, "R7") == []


# ================================================================== R8
def test_r8_undeclared_partition_spec_axis(tmp_path):
    fs = lint(tmp_path, """
        from jax.sharding import Mesh, PartitionSpec as P

        def build(devs):
            mesh = Mesh(devs, ("dp", "mp"))
            spec = P("tp", None)
            return mesh, spec
    """)
    r8 = rules_at(fs, "R8")
    assert any("names axis 'tp'" in f.message for f in r8)


def test_r8_frozen_axis_resize(tmp_path):
    # a plan_mesh_shape-style resize path recomputing mp/ep from the
    # device count — the elastic_mesh invariant violation
    fs = lint(tmp_path, """
        from paddle_tpu.distributed.mesh import init_mesh

        def shrink(saved, n_devices):
            axes = dict(saved)
            axes["mp"] = n_devices // 2
            axes["ep"] = n_devices // axes["mp"]
            return init_mesh(axes)
    """)
    r8 = rules_at(fs, "R8")
    assert any("frozen program axis 'mp'" in f.message for f in r8)
    assert any("frozen program axis 'ep'" in f.message for f in r8)


def test_r8_shard_map_arity_mismatch(tmp_path):
    fs = lint(tmp_path, """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def body(grads, scale):
            return grads

        def wrap(mesh):
            return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))
    """)
    r8 = rules_at(fs, "R8")
    assert any("in_specs has 1 spec(s) but the wrapped function takes 2"
               in f.message for f in r8)


def test_r8_donated_input_resharded(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            state = jax.lax.with_sharding_constraint(state, None)
            return state + x
    """)
    assert any("DONATED at the wrap site" in f.message
               for f in rules_at(fs, "R8"))


def test_r8_legal_shapes_are_clean(tmp_path):
    # dp/sdp resize IS the elastic contract; declared axes (including a
    # custom one) pass; matching shard_map arity passes
    fs = lint(tmp_path, """
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed.mesh import init_mesh

        def resize(saved, n_devices):
            axes = dict(saved)
            axes["dp"] = n_devices // 2
            axes["sdp"] = 2
            return init_mesh(axes)

        def metric_mesh(devs):
            mesh = Mesh(devs, ("metric",))
            return mesh, P("metric")

        def body(grads):
            return grads

        def wrap(mesh):
            return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))

        def outer(x):
            def helper(v):
                return v, v
            helper(x)

        def wrap2(mesh):
            # a CLOSURE's tuple return must not masquerade as the
            # wrapped function's arity (nested defs are pruned)
            return shard_map(outer, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=(P("dp"),))
    """)
    assert rules_at(fs, "R8") == []


# ================================================================== R9
def test_r9_risky_call_between_acquire_and_guard(tmp_path):
    # pre-fix ContinuousBatchingEngine._plan_hit shape: the lookup pins
    # blocks, then a project helper that can raise runs BEFORE any
    # try/abort — the exception path leaks the pins
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def commit(self, hit, plan, t): ...
            def abort(self, hit, plan=None): ...
            def plan_store(self, toks, m): ...

        def bucket_for(n):
            raise ValueError(n)

        def plan_hit(prompt):
            pool = BlockPool()
            hit = pool.lookup(prompt)
            m = bucket_for(len(prompt))
            plan = pool.plan_store(prompt, m)
            return hit, plan
    """)
    r9 = rules_at(fs, "R9")
    assert any("can raise while `hit`" in f.message
               and "exception path leaks" in f.message for f in r9)


def test_r9_one_hop_transfer_and_return_leak(tmp_path):
    # the helper transfers ownership to its caller (one interprocedural
    # hop, like R6): the CALLER's unguarded risky call flags, and an
    # early return that drops the resource flags too
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def commit(self, hit, plan, t): ...
            def abort(self, hit, plan=None): ...

        def plan_hit(pool, prompt):
            hit = pool.lookup(prompt)
            return hit

        def dispatch(x):
            raise RuntimeError(x)

        def admit(prompt):
            pool = BlockPool()
            hit = plan_hit(pool, prompt)
            out = dispatch(prompt)
            pool.commit(hit, None, out)

        def admit_dropping(prompt):
            pool = BlockPool()
            hit = plan_hit(pool, prompt)
            if prompt is None:
                return None
            pool.commit(hit, None, None)
    """)
    r9 = rules_at(fs, "R9")
    assert any(f.symbol == "admit" and "can raise" in f.message
               for f in r9)
    assert any(f.symbol == "admit_dropping" and "returns" in f.message
               for f in r9)


def test_r9_adapter_pin_discarded_and_staged_tmp(tmp_path):
    fs = lint(tmp_path, """
        import os

        class AdapterStore:
            def acquire(self, name): ...
            def release(self, slot): ...

        def warm(store: AdapterStore, name):
            store.acquire(name)     # pin discarded: nothing can release

        def publish(path, raw):
            tmp = path + ".tmp1"
            with open(tmp, "wb") as f:
                f.write(raw)
            if not raw:
                return False        # staged file never published here
            os.replace(tmp, path)
            return True
    """)
    r9 = rules_at(fs, "R9")
    assert any(f.symbol == "warm" and "discarded" in f.message
               for f in r9)
    assert any(f.symbol == "publish" and "staged .tmp" in f.message
               for f in r9)


def test_r9_release_in_handler_of_terminating_try_is_clean(tmp_path):
    # the body raises on every path; the handler that releases and
    # completes normally must actually CLEAR the resource (a dict.update
    # merge used to resurrect it, flagging the later return)
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def abort(self, hit, plan=None): ...

        def salvage(pool: BlockPool, prompt):
            hit = pool.lookup(prompt)
            try:
                raise ValueError(prompt)
            except ValueError:
                pool.abort(hit)
            return None
    """)
    assert rules_at(fs, "R9") == []


def test_r9_acquire_and_return_inside_retry_loop_is_clean(tmp_path):
    # acquire-and-transfer inside a poll/retry loop: the return hands
    # ownership out; the loop's second symbolic iteration must not
    # resurrect the resource as a rebind/exit leak
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def abort(self, hit, plan=None): ...

        def poll(pool: BlockPool, prompt):
            while True:
                hit = pool.lookup(prompt)
                return hit
    """)
    assert rules_at(fs, "R9") == []


def test_r9_finally_release_covers_return_inside_try(tmp_path):
    # the canonical try/finally shape: the finally runs on the return
    # too, so the release IS reachable from it
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def abort(self, hit, plan=None): ...

        def compute(p):
            raise RuntimeError(p)

        def with_finally(pool: BlockPool, prompt):
            hit = pool.lookup(prompt)
            try:
                return compute(prompt)
            finally:
                pool.abort(hit)
    """)
    assert rules_at(fs, "R9") == []


def test_r9_abort_in_except_and_trim_rebind_are_clean(tmp_path):
    # the FIXED admission discipline: abort-in-except IS a release,
    # commit on success releases, a neutral trim() rebind keeps the
    # resource alive, and a staged tmp that always publishes is clean
    fs = lint(tmp_path, """
        import os

        class BlockPool:
            def lookup(self, toks): ...
            def trim(self, hit, n): ...
            def plan_store(self, toks, m): ...
            def commit(self, hit, plan, t): ...
            def abort(self, hit, plan=None): ...

        def dispatch(x):
            raise RuntimeError(x)

        def admit(prompt):
            pool = BlockPool()
            hit = pool.lookup(prompt)
            try:
                hit = pool.trim(hit, 8)
                plan = pool.plan_store(prompt, 8)
                out = dispatch(prompt)
            except Exception:
                pool.abort(hit)
                raise
            pool.commit(hit, plan, out)

        def publish(path, raw):
            tmp = path + ".tmp1"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
    """)
    assert rules_at(fs, "R9") == []


# ================================================================= R10
def test_r10_collective_under_rank_branch(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def reduce_metrics(x):
            if jax.process_index() == 0:
                x = lax.psum(x, "dp")
            return x
    """)
    r10 = rules_at(fs, "R10")
    assert any("rank-dependent" in f.message
               and "deadlock" in f.message for f in r10)


def test_r10_asymmetric_sequences_and_tainted_loop(tmp_path):
    # if-arm issues 2 collectives, else-arm 1 => ordering mismatch; and
    # a loop whose trip count came from a rank source
    fs = lint(tmp_path, """
        import os
        import jax
        from jax import lax

        def step(x):
            r = jax.process_index()
            if r == 0:
                x = lax.psum(x, "dp")
                x = lax.all_gather(x, "dp")
            else:
                x = lax.psum(x, "dp")
            return x

        def sweep(x):
            n = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            for _ in range(n):
                x = lax.psum(x, "dp")
            return x
    """)
    r10 = rules_at(fs, "R10")
    assert any(f.symbol == "step"
               and "different collective sequences" in f.message
               for f in r10)
    assert any(f.symbol == "sweep" and "trip count" in f.message
               for f in r10)


def test_r10_early_exit_skips_later_collective(tmp_path):
    # the early-returning ranks never reach the psum below — through a
    # project WRAPPER (the distributed/ collective.py shape), so the
    # transitive collective signature must register
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def all_reduce(t):
            return lax.psum(t, "dp")

        def aggregate(x):
            if jax.process_index() != 0:
                return x
            return all_reduce(x)
    """)
    r10 = rules_at(fs, "R10")
    assert any("early exit skips" in f.message for f in r10)


def test_r10_early_return_matching_fall_through_is_clean(tmp_path):
    # every rank issues exactly one psum whichever path it takes — the
    # early-return arm must be compared against arm+suffix, not against
    # the other arm alone
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def reduce_either_way(x):
            if jax.process_index() == 0:
                return lax.psum(x, "dp")
            return lax.psum(x, "dp")
    """)
    assert rules_at(fs, "R10") == []


def test_r10_early_return_with_extra_collective_is_flagged(tmp_path):
    # the exiting arm runs ONE rendezvous, the continuing path TWO —
    # schedules diverge even though both arms "have collectives"
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def skewed(x):
            if jax.process_index() == 0:
                return lax.psum(x, "dp")
            x = lax.psum(x, "dp")
            return lax.all_gather(x, "dp")
    """)
    r10 = rules_at(fs, "R10")
    assert any("different rendezvous schedules" in f.message
               for f in r10)


def test_r10_uniform_suffix_branches_and_nested_defs_are_clean(tmp_path):
    # the suffix after a rank-gated early return is compared
    # path-sensitively: a uniform if/else downstream where EVERY path
    # issues one psum must not double-count, and a nested def's
    # collective is not the enclosing function's
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def one_psum_every_path(x, training):
            if jax.process_index() == 0:
                return lax.psum(x, "dp")
            if training:
                return lax.psum(x, "dp")
            return lax.psum(x * 2, "dp")

        def only_nested_collective(x):
            if jax.process_index() != 0:
                return x
            def helper(y):
                return lax.psum(y, "dp")
            return helper
    """)
    assert rules_at(fs, "R10") == []


def test_r10_same_collectives_both_arms_is_clean(tmp_path):
    # every rank still rendezvouses (same ops, same order): clean; a
    # rank-0 branch with NO collectives (checkpoint gating) is clean;
    # and a uniform (rank-independent) condition may differ freely
    fs = lint(tmp_path, """
        import jax
        from jax import lax

        def masked(x):
            r = jax.process_index()
            if r == 0:
                y = lax.psum(x, "dp")
            else:
                y = lax.psum(x * 0, "dp")
            return y

        def save_gate(x, path):
            if jax.process_index() == 0:
                open(path, "w").write(str(len(x)))
            return x

        def uniform(x, training):
            if training:
                x = lax.psum(x, "dp")
            return x
    """)
    assert rules_at(fs, "R10") == []


# ================================================================= R11
def test_r11_unbounded_rpc_and_deadline_threading(tmp_path):
    # the bare call rides the 120s transport default: flagged; the
    # helper that threads its caller's timeout/Deadline is clean
    fs = lint(tmp_path, """
        from paddle_tpu.distributed import rpc

        def work(x):
            return x

        def bad(x):
            return rpc.rpc_sync("w", work, args=(x,))

        def good(x, timeout):
            return rpc.rpc_sync("w", work, args=(x,), timeout=timeout)

        def good_deadline(x, budget):
            return rpc.rpc_sync("w", work, args=(x,),
                                deadline=budget)
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "bad" and "default timeout" in r11[0].message


def test_r11_non_idempotent_under_retry_policy(tmp_path):
    # the RemoteReplica invariant: submit through a multi-attempt retry
    # kwarg flags; through the single-attempt policy it is clean
    fs = lint(tmp_path, """
        from paddle_tpu.distributed.resilience import RetryPolicy

        def _host_submit(name, kwargs):
            ...

        class Replica:
            def __init__(self):
                self._retry = RetryPolicy(max_attempts=3)
                self._no_retry = RetryPolicy(max_attempts=1)

            def _call(self, fn, *args, retry=None):
                ...

            def submit_bad(self, kwargs):
                return self._call(_host_submit, kwargs,
                                  retry=self._retry)

            def submit_ok(self, kwargs):
                return self._call(_host_submit, kwargs,
                                  retry=self._no_retry)
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "Replica.submit_bad"
    assert "max_attempts=3" in r11[0].message


def test_r11_non_literal_max_attempts_is_unresolvable_not_uncapped(
        tmp_path):
    # max_attempts present but not a literal: the analyzer must stay
    # silent (unresolvable), not report "no attempt cap"; positional
    # literal 1 is single-attempt and clean too
    fs = lint(tmp_path, """
        from paddle_tpu.distributed.resilience import RetryPolicy

        def _host_submit(x):
            ...

        class Replica:
            def __init__(self, attempts=1):
                self._retry = RetryPolicy(max_attempts=attempts)
                self._one = RetryPolicy(1)

            def _call(self, fn, *args, retry=None):
                ...

            def submit_param(self, kwargs):
                return self._call(_host_submit, kwargs,
                                  retry=self._retry)

            def submit_pos_one(self, kwargs):
                return self._call(_host_submit, kwargs,
                                  retry=self._one)
    """)
    assert rules_at(fs, "R11") == []


def test_r11_submit_inside_retried_callable_and_annotation(tmp_path):
    # a submit-shaped rpc inside a policy.call() closure flags; the
    # same shape with an `rpc-idempotent` annotation on the def is the
    # documented opt-out
    fs = lint(tmp_path, """
        from paddle_tpu.distributed import rpc
        from paddle_tpu.distributed.resilience import RetryPolicy

        def _host_submit(x):
            ...

        def _host_submit_probe(x):  # tpu-lint: rpc-idempotent
            ...

        def resend(x):
            policy = RetryPolicy(deadline=5.0)
            def once():
                return rpc.rpc_sync("w", _host_submit, args=(x,),
                                    timeout=1.0)
            return policy.call(once)

        def reprobe(x):
            policy = RetryPolicy(deadline=5.0)
            def once():
                return rpc.rpc_sync("w", _host_submit_probe, args=(x,),
                                    timeout=1.0)
            return policy.call(once)
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "resend" and "double-submits" in r11[0].message


def test_r11_swallowed_transport_error(tmp_path):
    # a pass-only handler hides the dead peer; re-raising as a
    # classified error is the clean shape, and a ConnectionError
    # swallow in NON-rpc code is out of scope
    fs = lint(tmp_path, """
        from paddle_tpu.distributed import rpc

        class RpcTransportError(ConnectionError):
            ...

        def work(x):
            return x

        def bad_poll(x):
            try:
                return rpc.rpc_sync("w", work, args=(x,), timeout=1.0)
            except RpcTransportError:
                pass

        def good_poll(x):
            try:
                return rpc.rpc_sync("w", work, args=(x,), timeout=1.0)
            except RpcTransportError as e:
                raise ConnectionError(f"peer gone: {e}")

        def local_cleanup(path):
            try:
                open(path).close()
            except ConnectionError:
                pass
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "bad_poll" and "swallows" in r11[0].message


def test_r11_hand_rolled_retry_loop_around_submit(tmp_path):
    fs = lint(tmp_path, """
        from paddle_tpu.distributed import rpc

        def _host_submit(x):
            ...

        def stubborn(x):
            while True:
                try:
                    return rpc.rpc_sync("w", _host_submit, args=(x,),
                                        timeout=1.0)
                except ConnectionError:
                    continue
    """)
    r11 = rules_at(fs, "R11")
    assert any("retried by the loop" in f.message for f in r11)


# ============================================== migration rpc surface
def test_r11_kv_migration_rpc_must_be_deadline_bounded(tmp_path):
    # the PR 19 disagg shape: a kv_export leg riding the 120s transport
    # default stalls the whole migration on a dead prefill replica;
    # the Deadline-threaded variant (what DisaggClient actually does)
    # is clean
    fs = lint(tmp_path, """
        from paddle_tpu.distributed import rpc

        def _host_kv_export(name, prompt):
            ...

        def migrate_bad(prompt):
            return rpc.rpc_sync("pre0", _host_kv_export,
                                args=("default", prompt))

        def migrate_good(prompt, deadline):
            return rpc.rpc_sync("pre0", _host_kv_export,
                                args=("default", prompt),
                                timeout=deadline.remaining())
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "migrate_bad"
    assert "default timeout" in r11[0].message


def test_r11_kv_migration_idempotence_annotations_under_retry(tmp_path):
    # the migration surface's annotation contract: a migration fn
    # declared rpc-non-idempotent flags under a multi-attempt policy,
    # while `_host_kv_import` — idempotent BY DIGEST (a replayed
    # payload is a no-op), annotated exactly as serving/disagg.py does
    # — is retriable
    fs = lint(tmp_path, """
        from paddle_tpu.distributed.resilience import RetryPolicy

        def _host_kv_scatter(name, payload):  # tpu-lint: rpc-non-idempotent
            ...

        def _host_kv_import(name, payload):  # tpu-lint: rpc-idempotent
            ...

        class Replica:
            def __init__(self):
                self._retry = RetryPolicy(max_attempts=3)

            def _call(self, fn, *args, retry=None):
                ...

            def kv_scatter_bad(self, payload):
                return self._call(_host_kv_scatter, payload,
                                  retry=self._retry)

            def kv_import_ok(self, payload):
                return self._call(_host_kv_import, payload,
                                  retry=self._retry)
    """)
    r11 = rules_at(fs, "R11")
    assert len(r11) == 1
    assert r11[0].symbol == "Replica.kv_scatter_bad"
    assert "_host_kv_scatter" in r11[0].message


def test_r9_kv_export_must_abort_pins_on_failure(tmp_path):
    # the migration pin-lifecycle contract: export pins matched blocks
    # via lookup, then the device readback can raise — without a
    # try/finally abort the failed export leaks the pins and the
    # evictor can never reclaim those rows
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def abort(self, hit, plan=None): ...

        def stage_chunk(rows):
            raise RuntimeError(rows)

        def export_leaky(pool: BlockPool, toks):
            hit = pool.lookup(toks)
            leaves = stage_chunk(toks)
            pool.abort(hit)
            return leaves
    """)
    r9 = rules_at(fs, "R9")
    assert any(f.symbol == "export_leaky" and "can raise" in f.message
               and "exception path leaks" in f.message for f in r9)


def test_r9_kv_export_finally_abort_is_clean(tmp_path):
    # the FIXED export_payload discipline: pins released in a finally,
    # covering the miss early-return and the raising readback alike
    fs = lint(tmp_path, """
        class BlockPool:
            def lookup(self, toks): ...
            def abort(self, hit, plan=None): ...

        def stage_chunk(rows):
            raise RuntimeError(rows)

        def export_clean(pool: BlockPool, toks):
            hit = pool.lookup(toks)
            try:
                if not toks:
                    return None
                return stage_chunk(toks)
            finally:
                pool.abort(hit)
    """)
    assert rules_at(fs, "R9") == []


# ======================================================= incremental
def _git(cwd, *args):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *args], cwd=cwd, check=True, capture_output=True)


def test_cache_hit_and_invalidation(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent("""
        def clean(x):
            return x + 1
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))

    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    d1 = json.loads(capsys.readouterr().out)
    assert d1["schema_version"] == 3
    assert d1["cache"]["hit"] is False
    # fresh runs carry the timing block: per-file parse/lint ms + rules
    assert "pkg/mod.py" in d1["timing"]["files"]
    assert "parse_ms" in d1["timing"]["files"]["pkg/mod.py"]
    assert "R1" in d1["timing"]["rules"]

    # untouched tree => cache hit (no re-analysis)
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    d2 = json.loads(capsys.readouterr().out)
    assert d2["cache"]["hit"] is True
    assert d2["findings"] == d1["findings"]

    # edit => invalidated => re-linted, and the new finding surfaces
    (pkg / "mod.py").write_text(textwrap.dedent("""
        import jax

        def dirty(x):
            return jax.device_get(x)
    """))
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 1
    d3 = json.loads(capsys.readouterr().out)
    assert d3["cache"]["hit"] is False
    assert {f["rule"] for f in d3["new_findings"]} == {"R1"}


def test_changed_only_lints_just_the_diff(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(textwrap.dedent("""
        def helper(x):
            return x * 2
    """))
    (pkg / "b.py").write_text(textwrap.dedent("""
        from pkg.a import helper

        def use(x):
            return helper(x)
    """))
    (pkg / "c.py").write_text(textwrap.dedent("""
        def thing(x):
            return x + 1
    """))
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    monkeypatch.setattr(cli, "REPO", str(tmp_path))

    # no cache yet: --changed-only falls back to a full run (and says so)
    (pkg / "b.py").write_text(textwrap.dedent("""
        import jax
        from pkg.a import helper

        def use(x):
            return jax.device_get(helper(x))
    """))
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d0 = json.loads(capsys.readouterr().out)
    assert "fallback" in d0["cache"]["mode"]

    # the fallback full run populated the cache; now the real path —
    # and the edit ADDS an import (pkg.c) the cached graph has never
    # seen: the fresh-parse overlay must still scope it in
    (pkg / "b.py").write_text(textwrap.dedent("""
        import jax
        from pkg.a import helper
        from pkg.c import thing

        def use(x):
            return jax.device_get(thing(helper(x)))
    """))
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d1 = json.loads(capsys.readouterr().out)
    assert d1["cache"]["mode"] == "changed-only"
    assert d1["cache"]["changed"] == ["pkg/b.py"]
    # the import closure pulled BOTH context files in (a.py from the
    # cached graph, c.py from the freshly added import), but only the
    # CHANGED file's findings gate
    assert d1["cache"]["closure_files"] >= 3
    assert {f["path"] for f in d1["new_findings"]} == {"pkg/b.py"}

    # clean diff => the WHOLE-tree verdict (cache-served when fresh,
    # re-analyzed when the committed tree drifted) — committing a
    # violation and running the gate on the clean checkout must still
    # fail; "no changed files" is not "no findings"
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "wip")
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d2 = json.loads(capsys.readouterr().out)
    assert d2["cache"]["changed"] == []
    assert "empty diff" in d2["cache"]["mode"]
    assert {f["path"] for f in d2["new_findings"]} == {"pkg/b.py"}

    # but a NON-empty diff over a cache whose unchanged side drifted
    # (e.g. a pull landed commits since the last full run) must fall
    # back to a full run — the cached graph can't scope the closure
    cli.main(["pkg", "--json", "--no-baseline"])        # refresh cache
    capsys.readouterr()
    (pkg / "a.py").write_text(textwrap.dedent("""
        def helper(x):
            return x * 3
    """))
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "landed-behind-your-back")
    (pkg / "c.py").write_text(textwrap.dedent("""
        def thing(x):
            return x + 2
    """))
    # c.py is the uncommitted diff; a.py drifted vs the cache behind
    # git's back => full-run fallback (which still sees b.py's R1)
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d3 = json.loads(capsys.readouterr().out)
    assert "fallback" in d3["cache"]["mode"]
    assert "stale" in d3["cache"]["mode"]

    # once the tree is ACTUALLY clean, the empty-diff path is a
    # cache-served whole-tree OK
    (pkg / "b.py").write_text(textwrap.dedent("""
        from pkg.a import helper

        def use(x):
            return helper(x)
    """))
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "fix")
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 0
    d4 = json.loads(capsys.readouterr().out)
    assert d4["cache"]["changed"] == [] and d4["new_findings"] == []


def test_stale_baseline_versions_are_rejected_with_migration_pointer(
        tmp_path):
    # v3 re-keyed the baseline: a v2 file silently asserts "no R9–R11
    # findings were accepted" without anyone having triaged them, so
    # both old versions are hard-rejected
    for version in (1, 2):
        p = tmp_path / f"bl{version}.json"
        p.write_text('{"version": %d, "findings": {"R2|x|y|z": 1}}'
                     % version)
        with pytest.raises(ValueError, match="MIGRATION"):
            load_baseline(str(p))


def test_cache_schema_bump_invalidates_old_entries(tmp_path, monkeypatch,
                                                   capsys):
    """A cache entry written by an older cache schema must be ignored
    (full re-analysis), never mis-served — the schema_version 3 release
    bumped CACHE_SCHEMA for the lifecycle_graph block."""
    cli = _load_cli()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(x):\n    return x\n")
    monkeypatch.setattr(cli, "REPO", str(tmp_path))
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    capsys.readouterr()
    cache_dir = tmp_path / ".tpu_lint_cache"
    entries = list(cache_dir.glob("run_*.json"))
    assert entries
    data = json.loads(entries[0].read_text())
    assert data["schema"] >= 2 and "lifecycle_graph" in data
    data["schema"] = 1                      # a pre-bump entry
    entries[0].write_text(json.dumps(data))
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["cache"]["hit"] is False       # re-analyzed, not served
    # and the refreshed entry is back on the current schema
    data = json.loads(entries[0].read_text())
    assert data["schema"] >= 2


def test_sarif_round_trips_against_json(tmp_path, monkeypatch, capsys):
    """--sarif carries exactly the --json findings: same rules, paths,
    lines, and baseline keys; `properties.new` mirrors new_findings."""
    cli = _load_cli()
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x, n):
            if n > 0:
                return x
            return x.item()
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))
    assert cli.main([str(bad), "--no-baseline", "--json"]) == 1
    d = json.loads(capsys.readouterr().out)
    sarif_path = tmp_path / "out.sarif"
    assert cli.main([str(bad), "--no-baseline", "--sarif",
                     str(sarif_path)]) == 1
    capsys.readouterr()
    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"R1", "R2", "R9", "R10", "R11"} <= rule_ids
    results = run["results"]
    want = {(f["rule"], f["path"], f["line"], f["key"])
            for f in d["findings"]}
    got = {(r["ruleId"],
            r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
            r["locations"][0]["physicalLocation"]["region"]["startLine"],
            r["partialFingerprints"]["tpuLintKey"]) for r in results}
    assert got == want
    assert sum(r["properties"]["new"] for r in results) == \
        len(d["new_findings"])
    assert all(r["level"] == "error" for r in results)  # all NEW here


# ==================================================== CLI + repo smoke
def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "tpu_lint_cli", os.path.join(REPO, "tools", "tpu_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_nonzero_on_injected_violation(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x, n):
            if n > 0:
                return x
            return x.item()
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))
    assert cli.main([str(bad), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "R1" in out and "R2" in out

    # --json carries the machine-readable findings + keys
    assert cli.main([str(bad), "--no-baseline", "--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in data["new_findings"]} == {"R1", "R2"}
    assert all("key" in f for f in data["findings"])

    assert cli.main(["nope_not_here"]) == 2

    # --update-baseline over a subtree would erase the accepted entries
    # outside it; the CLI must refuse
    assert cli.main([str(bad), "--update-baseline"]) == 2

    # --update-baseline returns before findings gate, so a combined
    # --sarif would silently write nothing: refused loudly instead
    assert cli.main(["--update-baseline", "--sarif",
                     str(tmp_path / "x.sarif")]) == 2


def test_repo_is_clean_under_checked_in_baseline(capsys):
    """THE gate: the shipped tree + .tpu_lint_baseline.json => zero new
    findings. Any regression (new sync/retrace/donation/key/lock bug, or
    a reason-less suppression) fails this test before the runtime soaks
    ever see it."""
    cli = _load_cli()
    rc = cli.main([])   # defaults: paddle_tpu + tools, default baseline
    out = capsys.readouterr().out
    assert rc == 0, f"tpu_lint found NEW findings:\n{out}"
    assert "no new findings" in out
    # the analyzer really saw the tree (not an empty walk)
    assert "trace roots" in out.split("\n")[0]


def test_repo_lock_graph_names_serving_and_lora_edges(capsys):
    """The R6 acceptance shape: the --json lock graph carries the REAL
    lock nodes + acquisition edges of serving/server.py and
    lora/store.py, including the interprocedural order edge the serve
    loop fixes by reading the scheduler's depth under its condition
    variable. (Runs off the whole-repo cache the smoke test above just
    warmed — milliseconds.)"""
    cli = _load_cli()
    rc = cli.main(["--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    lg = data["lock_graph"]
    ids = {l["id"] for l in lg["locks"]}
    assert any(i.endswith("server.py::InferenceServer._cv") for i in ids)
    assert any(i.endswith("store.py::AdapterStore._lock") for i in ids)
    acq = lg["acquisitions"]
    by_file = {a["file"] for a in acq}
    assert "paddle_tpu/serving/server.py" in by_file
    assert "paddle_tpu/lora/store.py" in by_file
    # named functions, not just files: the graph is auditable
    assert any(a["function"] == "AdapterStore.acquire" for a in acq)
    assert any(a["function"] == "InferenceServer._loop" for a in acq)
    # the interprocedural held->acquired edge (cv held across the
    # scheduler-depth property read)
    assert any(e["held"].endswith("InferenceServer._cv")
               and e["acquired"].endswith("FifoScheduler._lock")
               for e in lg["edges"])
    # timing rides the same JSON (warm runs report the cached-run block)
    assert "timing" in data and data["timing"]


def test_repo_lifecycle_graph_names_engine_pin_sites(capsys):
    """The R9 acceptance shape: the --json lifecycle graph carries the
    REAL acquire/release sites of the admission pin discipline — the
    pool lookup inside `_plan_hit`, `admit`'s one-hop acquire THROUGH
    `_plan_hit`, the adapter-pin acquire, and the commit/abort/release
    pairs. (Rides the whole-repo cache the smoke test warmed.)"""
    cli = _load_cli()
    rc = cli.main(["--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    lg = data["lifecycle_graph"]
    protos = {p["name"] for p in lg["protocols"]}
    assert {"block-pin", "adapter-pin", "staged-file"} <= protos
    acq = lg["acquires"]
    assert any(a["protocol"] == "block-pin"
               and a["function"] == "ContinuousBatchingEngine._plan_hit"
               for a in acq)
    # the one-hop ownership transfer is recorded with its via chain
    assert any(a["protocol"] == "block-pin"
               and a["function"] == "ContinuousBatchingEngine.admit"
               and a["via"] == "self._plan_hit" for a in acq)
    assert any(a["protocol"] == "adapter-pin"
               and a["function"] == "ContinuousBatchingEngine.admit"
               for a in acq)
    rel = lg["releases"]
    eng = [r for r in rel
           if r["file"] == "paddle_tpu/serving/engine.py"]
    assert {"commit", "abort"} <= {r["method"] for r in eng
                                   if r["protocol"] == "block-pin"}
    assert any(r["protocol"] == "adapter-pin" and r["method"] == "release"
               for r in eng)
    # tmp-stage→publish sites are first-class protocol sites too (the
    # flight recorder's crash-safe dump is the canonical one)
    assert any(a["protocol"] == "staged-file"
               and a["file"] == "paddle_tpu/observability/flight.py"
               for a in acq)


# ===================================== PR 20: integrity-readback shapes
def test_r1_fingerprint_flush_without_reason_is_flagged(tmp_path):
    # the integrity monitor's window drain: a device_get is a host sync
    # wherever it lives — without a reasoned suppression it must surface
    fs = lint(tmp_path, """
        import threading
        import jax

        class Monitor:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []

            def flush(self):
                with self._lock:
                    todo, self._pending = self._pending, []
                fetched = jax.device_get([fp for _, fp in todo])
                return fetched
    """)
    assert any(f.symbol == "Monitor.flush" and "device_get" in f.message
               for f in rules_at(fs, "R1"))


def test_r1_batched_fingerprint_flush_suppression_holds(tmp_path):
    # the shipped shape (integrity.IntegrityMonitor.flush): ONE batched
    # readback per check window, drained outside the lock, with the
    # reasoned suppression — R1 silenced, R5/R7 genuinely clean
    fs = lint(tmp_path, """
        import threading
        import jax

        class Monitor:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []
                self.mismatches = 0

            def observe(self, step, fp):
                with self._lock:
                    self._pending.append((step, fp))

            def flush(self):
                with self._lock:
                    todo, self._pending = self._pending, []
                # tpu-lint: disable=R1(one batched readback per check window, by design)
                fetched = jax.device_get([fp for _, fp in todo])
                with self._lock:
                    self.mismatches += len(fetched)
                return fetched
    """)
    assert rules_at(fs, "R1") == []
    assert rules_at(fs, "R5") == []
    assert rules_at(fs, "R7") == []


def test_r7_fingerprint_readback_under_lock_is_flagged(tmp_path):
    # the pre-fix hazard the shipped monitor avoids: device_get while
    # holding the bookkeeping lock — a stuck device wedges every
    # stats()/observe() caller behind the flush
    fs = lint(tmp_path, """
        import threading
        import jax

        class Monitor:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []

            def stats(self):
                with self._lock:
                    return len(self._pending)

            def flush(self):
                with self._lock:
                    # tpu-lint: disable=R1(window drain)
                    return jax.device_get(self._pending)
    """)
    assert any(f.symbol == "Monitor.flush"
               for f in rules_at(fs, "R7"))


def test_r9_quarantine_staged_write_is_clean(tmp_path):
    # integrity._write_json_durable: stage to a tmp sibling, publish with
    # os.replace, and remove the tmp on ANY failure — no exception path
    # may leak a half-written record next to the checkpoints
    fs = lint(tmp_path, """
        import json
        import os

        def write_durable(path, obj):
            tmp = f"{path}.tmp-pt{os.getpid()}"
            try:
                f = open(tmp, "w")
                try:
                    json.dump(obj, f)
                    f.flush()
                    os.fsync(f.fileno())
                finally:
                    f.close()
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
    """)
    assert rules_at(fs, "R9") == []


def test_r9_quarantine_staging_leak_is_flagged(tmp_path):
    # a staged record that takes a NORMAL early return without publishing
    # is a silent lost write (raise paths are exempt by design — that is
    # the crash-safety the orphan sweep covers)
    fs = lint(tmp_path, """
        import json
        import os

        def write_leaky(path, obj):
            tmp = f"{path}.tmp-pt{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(obj, f)
            if not obj:
                return None
            os.replace(tmp, path)
    """)
    assert any(f.symbol == "write_leaky" and "staged .tmp file" in f.message
               for f in rules_at(fs, "R9"))
