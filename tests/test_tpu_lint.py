"""tpu_lint: trace-discipline static analysis.

Per rule (R1–R8): >=2 true-positive fixtures modeled on real (pre-fix)
defect shapes from this repo, plus >=1 false-positive guard proving the
idioms the codebase relies on stay clean. Then the policy layer
(mandatory suppression reasons, baseline accept/new/stale semantics), the
incremental engine (content-hash cache invalidation, ``--changed-only``),
the CLI exit codes, and a whole-repo smoke run against the checked-in
baseline asserting zero NEW findings (plus the real lock graph naming
the serving/lora acquisition edges).

Everything here is pure-AST over tmp fixture trees — no jit, no device
work — so the module stays far under the tier-1 time budget (the one
whole-repo parse is ~6 s on the 2-core box; its result is cached, so the
later whole-repo assertions are millisecond cache hits).
"""
import importlib.util
import json
import os
import subprocess
import textwrap

import pytest

from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                 save_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(tmp_path, source, name="mod.py"):
    """Write one fixture module and run every rule over it."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return analyze(str(tmp_path), ["."]).findings


def rules_at(findings, rule):
    return [f for f in findings if f.rule == rule]


# ================================================================== R1
def test_r1_item_in_trace_reachable(tmp_path):
    # pre-fix GradScaler shape: a per-flag .item() readback inside code
    # reachable from a jit entry point
    fs = lint(tmp_path, """
        import jax

        def check(flag):
            return flag.item()

        @jax.jit
        def step(x, flag):
            if check(flag):
                return x
            return x * 2
    """)
    r1 = rules_at(fs, "R1")
    assert any(".item()" in f.message and f.symbol == "check" for f in r1)
    # the finding names the jit entry that makes the helper reachable
    assert any("step" in " ".join(f.chain) for f in r1 if f.symbol == "check")


def test_r1_np_asarray_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def fwd(ids):
            rows = np.asarray(ids)
            return rows
    """)
    assert any("np.asarray" in f.message for f in rules_at(fs, "R1"))


def test_r1_lazy_dispatch_readback(tmp_path):
    # pre-fix ContinuousBatchingEngine.admit shape: two serialized scalar
    # reads of compiled-call results instead of one batched device_get
    fs = lint(tmp_path, """
        import jax

        def admit(x):
            step = jax.jit(lambda v: (v, v > 0))
            tok, done = step(x)
            first = int(tok)
            fin = bool(done)
            return first, fin
    """)
    msgs = [f.message for f in rules_at(fs, "R1")]
    assert any("`int()`" in m for m in msgs)
    assert any("`bool()`" in m for m in msgs)


def test_r1_method_form_block_until_ready(tmp_path):
    # `arr.block_until_ready()` must be caught like the function form
    fs = lint(tmp_path, """
        def fence(out):
            out.block_until_ready()
            return out
    """)
    assert any(".block_until_ready()" in f.message
               for f in rules_at(fs, "R1"))


def test_r1_guard_region_is_clean(tmp_path):
    # viterbi_decode shape: the host read happens only on the proven-
    # concrete side of an isinstance(..., Tracer) guard
    fs = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def decode(paths, lengths):
            if not isinstance(lengths, jax.core.Tracer):
                paths = paths[:, :int(jnp.max(lengths))]
            return paths
    """)
    assert rules_at(fs, "R1") == []


def test_r1_batched_device_get_is_explicit_not_implicit(tmp_path):
    # the repo's fixed shape — ONE batched device_get — still surfaces as
    # an explicit-sync finding (it must carry a reason), but the int()/
    # bool() on the fetched host values are clean
    fs = lint(tmp_path, """
        import jax

        def step(x):
            run = jax.jit(lambda v: (v, v > 0))
            tok, done = run(x)
            tok_h, done_h = jax.device_get((tok, done))
            return int(tok_h), bool(done_h)
    """)
    r1 = rules_at(fs, "R1")
    assert len(r1) == 1 and "device_get" in r1[0].message


def test_r1_module_level_jit_wrap_site(tmp_path):
    # `run = jax.jit(body)` at FILE scope must make body a trace root
    fs = lint(tmp_path, """
        import jax

        def body(x):
            v = float(x)
            return x * v

        run = jax.jit(body)
    """)
    assert any("`float()`" in f.message and f.symbol == "body"
               for f in rules_at(fs, "R1"))


# ================================================================== R2
def test_r2_branch_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x, n):
            if n > 3:
                return x
            while n < 0:
                n = n + 1
            return x * 2
    """)
    r2 = rules_at(fs, "R2")
    assert any("`if` branches" in f.message for f in r2)
    assert any("`while` branches" in f.message for f in r2)


def test_r2_fstring_of_tracer(tmp_path):
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            label = f"loss={x}"
            return x
    """)
    assert any("f-string" in f.message for f in rules_at(fs, "R2"))


def test_r2_jit_inside_loop(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def train(batches):
            for b in batches:
                step = jax.jit(lambda v: v * 2)
                step(b)
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R2"))


def test_r2_shape_branch_is_static(tmp_path):
    # bucketed-prefill idiom: branching on .shape is per-shape
    # specialization (how the compile-budget design works), not a hazard
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x):
            if x.shape[0] > 8:
                return x[:8]
            return x
    """)
    assert rules_at(fs, "R2") == []


def test_r2_isinstance_guarded_branch_is_clean(tmp_path):
    # generation.py prefill-vs-decode dispatch: isinstance(pos, int)
    # proves the scalar is static on that path
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def fwd(x, pos):
            if isinstance(pos, int) and pos == 0:
                return x
            return x + 1
    """)
    assert rules_at(fs, "R2") == []


# ================================================================== R3
def test_r3_donated_then_read(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def run(state, x):
            step = jax.jit(lambda s, v: s + v, donate_argnums=(0,))
            out = step(state, x)
            return out + state
    """)
    r3 = rules_at(fs, "R3")
    assert any("`state` was donated" in f.message for f in r3)


def test_r3_donated_in_loop_without_rebind(tmp_path):
    # the decode-loop bug shape the KV-cache engine is built to avoid:
    # iteration 2 would dispatch an already-donated buffer
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: c, donate_argnums=(0,))
            outs = []
            for x in xs:
                outs.append(step(cache, x))
            return outs
    """)
    assert any("never reassigned in the loop" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_decorator_jitted_callee(tmp_path):
    # @partial(jax.jit, donate_argnums=...) — calling the bare name IS a
    # dispatch of the compiled callable, so donation rules apply
    fs = lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def run(state, x):
            out = step(state, x)
            return out + state
    """)
    assert any("`state` was donated" in f.message
               for f in rules_at(fs, "R3"))


def test_r3_rebound_from_results_is_clean(tmp_path):
    # the repo's actual decode loop: the donated cache is rebound from
    # the call's results every iteration
    fs = lint(tmp_path, """
        import jax

        def decode(cache, xs):
            step = jax.jit(lambda c, v: (c, v), donate_argnums=(0,))
            for x in xs:
                cache, tok = step(cache, x)
            return cache
    """)
    assert rules_at(fs, "R3") == []


# ================================================================== R4
def test_r4_key_reused_across_two_ops(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            a = jax.random.categorical(key, logits)
            b = jax.random.categorical(key, logits)
            return a, b
    """)
    r4 = rules_at(fs, "R4")
    assert any("consumed again" in f.message for f in r4)


def test_r4_key_reused_across_loop(tmp_path):
    # the PR-4 historical bug: one key for every decode step (and every
    # row) — identical prompts sampled identical continuations
    fs = lint(tmp_path, """
        import jax

        def decode(key, steps, logits):
            toks = []
            for _ in range(steps):
                toks.append(jax.random.categorical(key, logits))
            return toks
    """)
    assert any("inside a loop" in f.message for f in rules_at(fs, "R4"))


def test_r4_split_and_fold_in_are_clean(tmp_path):
    # the fixed shapes: split per use, fold_in per iteration (the
    # per-(step,row) key derivation), branch-exclusive single use
    fs = lint(tmp_path, """
        import jax

        def sample(key, logits):
            key, k1 = jax.random.split(key)
            a = jax.random.categorical(k1, logits)
            key, k2 = jax.random.split(key)
            b = jax.random.categorical(k2, logits)
            return a, b

        def decode(key, steps, logits):
            toks = []
            for i in range(steps):
                toks.append(jax.random.categorical(
                    jax.random.fold_in(key, i), logits))
            return toks

        def either(key, logits, greedy):
            if greedy:
                return jax.random.categorical(key, logits)
            return jax.random.categorical(key, logits)
    """)
    assert rules_at(fs, "R4") == []


def test_r4_from_import_forms(tmp_path):
    # `from jax import random` and `from jax.random import normal` must
    # be recognized as key consumers, not just `jax.random.*` chains
    fs = lint(tmp_path, """
        from jax import random
        from jax.random import normal

        def a(key, logits):
            x = random.categorical(key, logits)
            y = random.categorical(key, logits)
            return x, y

        def b(key):
            u = normal(key, (4,))
            v = normal(key, (4,))
            return u, v
    """)
    r4 = rules_at(fs, "R4")
    assert {f.symbol for f in r4} == {"a", "b"}


def test_r4_interprocedural_consumption(tmp_path):
    # reuse through a helper: the callee's param is (transitively) key-
    # consuming, so passing the same key twice correlates the draws
    fs = lint(tmp_path, """
        import jax

        def draw(key, logits):
            return jax.random.categorical(key, logits)

        def sample(key, logits):
            a = draw(key, logits)
            b = draw(key, logits)
            return a, b
    """)
    assert len(rules_at(fs, "R4")) >= 1


# ================================================================== R5
def test_r5_unguarded_read_in_threaded_class(tmp_path):
    # pre-fix InferenceServer.shutdown shape: _thread read outside the
    # condition variable that guards it at every other site
    fs = lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = None
                self._stop = False

            def start(self):
                with self._lock:
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

            def _run(self):
                with self._lock:
                    if self._thread is None:
                        return

            def shutdown(self):
                with self._lock:
                    self._stop = True
                t = self._thread
                return t
    """)
    r5 = rules_at(fs, "R5")
    assert any(f.symbol == "Server.shutdown" and "_thread" in f.message
               for f in r5)


def test_r5_unguarded_write_from_worker(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self._n += 1

            def read(self):
                with self._lock:
                    return self._n

            def bump(self):
                with self._lock:
                    self._n += 1
    """)
    assert any("_n" in f.message and f.symbol == "Counter._work"
               for f in rules_at(fs, "R5"))


def test_r5_lock_inherited_by_private_helper(tmp_path):
    # helper only ever called with the lock held inherits its context —
    # the scheduler/engine idiom; no finding
    fs = lint(tmp_path, """
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._t = threading.Thread(target=self.drain)

            def drain(self):
                with self._lock:
                    self._flush()

            def push(self, x):
                with self._lock:
                    self._items.append(x)
                    self._flush()

            def _flush(self):
                while self._items:
                    self._items.pop()
    """)
    assert rules_at(fs, "R5") == []


def test_r5_single_threaded_class_ignored(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Plain:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self._n
    """)
    assert rules_at(fs, "R5") == []


# ===================================================== suppression policy
def test_suppression_with_reason_is_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            # tpu-lint: disable=R1(deliberate batched flush point)
            return jax.device_get(flags)
    """)
    assert rules_at(fs, "R1") == []
    assert rules_at(fs, "R0") == []


def test_bare_suppression_is_r0_and_not_honored(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    assert any("no reason" in f.message for f in rules_at(fs, "R0"))
    assert len(rules_at(fs, "R1")) == 1  # the bare disable did nothing


def test_suppression_examples_in_docstrings_are_inert(tmp_path):
    # a suppression QUOTED in a docstring must neither install a real
    # suppression nor (bare form) raise R0 — only true comments count
    fs = lint(tmp_path, '''
        """Module doc.

            x = y.item()  # tpu-lint: disable-file=R1(docstring example)
            z = q.item()  # tpu-lint: disable=R1
        """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    ''')
    assert rules_at(fs, "R0") == []          # bare example is inert
    assert len(rules_at(fs, "R1")) == 1      # file-disable example too


def test_file_level_suppression(tmp_path):
    fs = lint(tmp_path, """
        # tpu-lint: disable-file=R1(host-side tool by contract)
        import jax

        def a(x):
            return jax.device_get(x)

        def b(x):
            return x.item()
    """)
    assert rules_at(fs, "R1") == []


# ============================================================== baseline
def test_baseline_accepts_then_fails_new(tmp_path):
    src = """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """
    findings = lint(tmp_path, src)
    assert len(findings) == 1
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    baseline = load_baseline(str(bl_path))

    new, stale = diff_baseline(findings, baseline)
    assert new == [] and stale == []

    # a second, new occurrence (different function) fails
    grown = lint(tmp_path, src + """
        def flush2(flags):
            return jax.device_get(flags)
    """)
    new, _ = diff_baseline(grown, baseline)
    assert len(new) == 1 and new[0].symbol == "flush2"

    # line drift does NOT churn the baseline (keys carry no line numbers)
    drifted = lint(tmp_path, "\n\n\n" + textwrap.dedent(src))
    new, stale = diff_baseline(drifted, baseline)
    assert new == [] and stale == []


def test_baseline_stale_keys_reported_not_failing(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)
    """)
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)
    new, stale = diff_baseline([], load_baseline(str(bl_path)))
    assert new == [] and len(stale) == 1


def test_r0_findings_are_never_baselinable(tmp_path):
    findings = lint(tmp_path, """
        import jax

        def flush(flags):
            return jax.device_get(flags)  # tpu-lint: disable=R1
    """)
    r0 = rules_at(findings, "R0")
    bl_path = tmp_path / "bl.json"
    save_baseline(str(bl_path), findings)   # counts include the R0 key
    new, _ = diff_baseline(findings, load_baseline(str(bl_path)))
    assert any(f.rule == "R0" for f in new)  # still fails


# ================================================================== R6
def test_r6_interprocedural_reentry(tmp_path):
    # acquiring a non-reentrant Lock inside a helper reached from a
    # region already holding it — the single-thread self-deadlock
    fs = lint(tmp_path, """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._items[k] = v
                    self._evict()

            def _evict(self):
                with self._lock:
                    self._items.clear()
    """)
    r6 = rules_at(fs, "R6")
    assert any("re-enters non-reentrant" in f.message
               and f.symbol == "Store._evict" for f in r6)
    # the evidence chain names the path that arrives with the lock held
    assert any("Store.put" in " ".join(f.chain) for f in r6)


def test_r6_cross_class_lock_order_cycle(tmp_path):
    # A->B on one path, B->A on another: two threads interleaving
    # deadlock. The second acquire is behind a cross-object method call.
    fs = lint(tmp_path, """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self.a = A()

            def bump(self):
                with self._lock:
                    self._n += 1

            def poke(self):
                with self._lock:
                    self.a.fwd()

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._lock:
                    self.b.bump()
    """)
    r6 = rules_at(fs, "R6")
    assert any("lock-order cycle" in f.message for f in r6)


def test_r6_overlapping_cycles_all_edges_named(tmp_path):
    # a<->b and b<->c share one SCC: the finding must name EVERY edge
    # of the knot (not a synthetic walk that hides the second deadlock)
    fs = lint(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def down(self):
                with self._lock:
                    self.b.noop()

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = A()
                self.c = C()

            def noop(self):
                with self._lock:
                    pass

            def poke(self):
                with self._lock:
                    self.a.ping()

            def up(self):
                with self._lock:
                    self.c.down()

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def ping(self):
                with self._lock:
                    pass

            def fwd(self):
                with self._lock:
                    self.b.noop()
    """)
    cyc = [f for f in rules_at(fs, "R6")
           if "lock-order cycle" in f.message]
    text = " ".join(f.message for f in cyc)
    # both deadlock pairs surface, with both directions of each
    assert "A._lock -> B._lock" in text and "B._lock -> A._lock" in text
    assert "B._lock -> C._lock" in text and "C._lock -> B._lock" in text


def test_r6_consistent_order_is_clean(tmp_path):
    # nested locks taken in ONE global order everywhere — legal
    fs = lint(tmp_path, """
        import threading

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self.b = B()

            def fwd(self):
                with self._lock:
                    self.b.bump()

            def bwd(self):
                with self._lock:
                    self.b.bump()
    """)
    assert rules_at(fs, "R6") == []


def test_r6_rlock_reentry_and_cv_alias_are_clean(tmp_path):
    # RLock re-entry is legal; Condition(self._lock) is the SAME lock
    # (one node in the graph), not a second lock ordered against it
    fs = lint(tmp_path, """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.RLock()
                self._n = 0

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    self._n += 1

        class Cv:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._q = []

            def put(self, x):
                with self._cv:
                    self._q.append(x)
                    self._cv.notify_all()

            def flush(self):
                with self._lock:
                    self._q.clear()
    """)
    assert rules_at(fs, "R6") == []
    # and the alias really collapsed: a cv re-entry IS caught
    fs2 = lint(tmp_path, """
        import threading

        class Cv:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._q = []

            def put(self, x):
                with self._cv:
                    self._drain()

            def _drain(self):
                with self._lock:
                    self._q.clear()
    """, name="mod2.py")
    assert any("re-enters non-reentrant" in f.message
               for f in rules_at(fs2, "R6"))


# ================================================================== R7
def test_r7_device_page_write_under_lock(tmp_path):
    # the pre-fix AdapterStore shape: .at[slot].set H2D staging while
    # holding the metadata lock every placement probe contends
    fs = lint(tmp_path, """
        import threading

        class PageStore:
            def __init__(self, stacks):
                self._lock = threading.Lock()
                self.tensors = stacks
                self._names = {}

            def acquire(self, name, slot, pages):
                with self._lock:
                    self.tensors = {
                        k: (a.at[slot].set(pages[k][0]),
                            b.at[slot].set(pages[k][1]))
                        for k, (a, b) in self.tensors.items()}
                    self._names[name] = slot

            def resident(self, name):
                with self._lock:
                    return name in self._names
    """)
    r7 = rules_at(fs, "R7")
    assert any("device buffer update" in f.message
               and f.symbol == "PageStore.acquire" for f in r7)


def test_r7_sleep_and_unbounded_wait_under_lock(tmp_path):
    fs = lint(tmp_path, """
        import threading
        import time

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()
                self._jobs = []

            def poll(self):
                with self._lock:
                    time.sleep(0.5)
                    return list(self._jobs)

            def wait_all(self):
                with self._cv:
                    self._cv.wait()
    """)
    r7 = rules_at(fs, "R7")
    assert any("`time.sleep`" in f.message for f in r7)
    assert any("unbounded `.wait()`" in f.message for f in r7)


def test_r7_io_and_sync_under_lock_interprocedural(tmp_path):
    # the blocking op hides in a helper only reached with the lock held
    fs = lint(tmp_path, """
        import threading
        import jax

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []

            def dump(self, path, flags):
                with self._lock:
                    self._write(path)
                    host = jax.device_get(flags)
                return host

            def _write(self, path):
                with open(path, "w") as f:
                    f.write(str(self._events))
    """)
    r7 = rules_at(fs, "R7")
    assert any("file I/O" in f.message and f.symbol == "Recorder._write"
               for f in r7)
    assert any("host sync" in f.message and f.symbol == "Recorder.dump"
               for f in r7)


def test_r7_bounded_wait_and_io_outside_lock_are_clean(tmp_path):
    # the repo's fixed shapes: timeout-bounded cv.wait in the serve
    # loop, and the flight recorder's snapshot-under-lock/write-outside
    fs = lint(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._cv = threading.Condition()
                self._stop = False
                self._events = []

            def loop(self):
                with self._cv:
                    while not self._stop:
                        self._cv.wait(0.1)

            def dump(self, path):
                with self._cv:
                    events = list(self._events)
                with open(path, "w") as f:
                    f.write(str(events))
    """)
    assert rules_at(fs, "R7") == []


# ================================================================== R8
def test_r8_undeclared_partition_spec_axis(tmp_path):
    fs = lint(tmp_path, """
        from jax.sharding import Mesh, PartitionSpec as P

        def build(devs):
            mesh = Mesh(devs, ("dp", "mp"))
            spec = P("tp", None)
            return mesh, spec
    """)
    r8 = rules_at(fs, "R8")
    assert any("names axis 'tp'" in f.message for f in r8)


def test_r8_frozen_axis_resize(tmp_path):
    # a plan_mesh_shape-style resize path recomputing mp/ep from the
    # device count — the elastic_mesh invariant violation
    fs = lint(tmp_path, """
        from paddle_tpu.distributed.mesh import init_mesh

        def shrink(saved, n_devices):
            axes = dict(saved)
            axes["mp"] = n_devices // 2
            axes["ep"] = n_devices // axes["mp"]
            return init_mesh(axes)
    """)
    r8 = rules_at(fs, "R8")
    assert any("frozen program axis 'mp'" in f.message for f in r8)
    assert any("frozen program axis 'ep'" in f.message for f in r8)


def test_r8_shard_map_arity_mismatch(tmp_path):
    fs = lint(tmp_path, """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def body(grads, scale):
            return grads

        def wrap(mesh):
            return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))
    """)
    r8 = rules_at(fs, "R8")
    assert any("in_specs has 1 spec(s) but the wrapped function takes 2"
               in f.message for f in r8)


def test_r8_donated_input_resharded(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            state = jax.lax.with_sharding_constraint(state, None)
            return state + x
    """)
    assert any("DONATED at the wrap site" in f.message
               for f in rules_at(fs, "R8"))


def test_r8_legal_shapes_are_clean(tmp_path):
    # dp/sdp resize IS the elastic contract; declared axes (including a
    # custom one) pass; matching shard_map arity passes
    fs = lint(tmp_path, """
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu.distributed.mesh import init_mesh

        def resize(saved, n_devices):
            axes = dict(saved)
            axes["dp"] = n_devices // 2
            axes["sdp"] = 2
            return init_mesh(axes)

        def metric_mesh(devs):
            mesh = Mesh(devs, ("metric",))
            return mesh, P("metric")

        def body(grads):
            return grads

        def wrap(mesh):
            return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))

        def outer(x):
            def helper(v):
                return v, v
            helper(x)

        def wrap2(mesh):
            # a CLOSURE's tuple return must not masquerade as the
            # wrapped function's arity (nested defs are pruned)
            return shard_map(outer, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=(P("dp"),))
    """)
    assert rules_at(fs, "R8") == []


# ======================================================= incremental
def _git(cwd, *args):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *args], cwd=cwd, check=True, capture_output=True)


def test_cache_hit_and_invalidation(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent("""
        def clean(x):
            return x + 1
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))

    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    d1 = json.loads(capsys.readouterr().out)
    assert d1["schema_version"] == 2
    assert d1["cache"]["hit"] is False
    # fresh runs carry the timing block: per-file parse/lint ms + rules
    assert "pkg/mod.py" in d1["timing"]["files"]
    assert "parse_ms" in d1["timing"]["files"]["pkg/mod.py"]
    assert "R1" in d1["timing"]["rules"]

    # untouched tree => cache hit (no re-analysis)
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 0
    d2 = json.loads(capsys.readouterr().out)
    assert d2["cache"]["hit"] is True
    assert d2["findings"] == d1["findings"]

    # edit => invalidated => re-linted, and the new finding surfaces
    (pkg / "mod.py").write_text(textwrap.dedent("""
        import jax

        def dirty(x):
            return jax.device_get(x)
    """))
    assert cli.main(["pkg", "--json", "--no-baseline"]) == 1
    d3 = json.loads(capsys.readouterr().out)
    assert d3["cache"]["hit"] is False
    assert {f["rule"] for f in d3["new_findings"]} == {"R1"}


def test_changed_only_lints_just_the_diff(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(textwrap.dedent("""
        def helper(x):
            return x * 2
    """))
    (pkg / "b.py").write_text(textwrap.dedent("""
        from pkg.a import helper

        def use(x):
            return helper(x)
    """))
    (pkg / "c.py").write_text(textwrap.dedent("""
        def thing(x):
            return x + 1
    """))
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    monkeypatch.setattr(cli, "REPO", str(tmp_path))

    # no cache yet: --changed-only falls back to a full run (and says so)
    (pkg / "b.py").write_text(textwrap.dedent("""
        import jax
        from pkg.a import helper

        def use(x):
            return jax.device_get(helper(x))
    """))
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d0 = json.loads(capsys.readouterr().out)
    assert "fallback" in d0["cache"]["mode"]

    # the fallback full run populated the cache; now the real path —
    # and the edit ADDS an import (pkg.c) the cached graph has never
    # seen: the fresh-parse overlay must still scope it in
    (pkg / "b.py").write_text(textwrap.dedent("""
        import jax
        from pkg.a import helper
        from pkg.c import thing

        def use(x):
            return jax.device_get(thing(helper(x)))
    """))
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d1 = json.loads(capsys.readouterr().out)
    assert d1["cache"]["mode"] == "changed-only"
    assert d1["cache"]["changed"] == ["pkg/b.py"]
    # the import closure pulled BOTH context files in (a.py from the
    # cached graph, c.py from the freshly added import), but only the
    # CHANGED file's findings gate
    assert d1["cache"]["closure_files"] >= 3
    assert {f["path"] for f in d1["new_findings"]} == {"pkg/b.py"}

    # clean diff => clean exit (even over a stale cache: "nothing
    # uncommitted" is a valid pre-commit answer)
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "wip")
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 0
    d2 = json.loads(capsys.readouterr().out)
    assert d2["cache"]["changed"] == []
    assert d2["new_findings"] == []

    # but a NON-empty diff over a cache whose unchanged side drifted
    # (e.g. a pull landed commits since the last full run) must fall
    # back to a full run — the cached graph can't scope the closure
    cli.main(["pkg", "--json", "--no-baseline"])        # refresh cache
    capsys.readouterr()
    (pkg / "a.py").write_text(textwrap.dedent("""
        def helper(x):
            return x * 3
    """))
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "landed-behind-your-back")
    (pkg / "c.py").write_text(textwrap.dedent("""
        def thing(x):
            return x + 2
    """))
    # c.py is the uncommitted diff; a.py drifted vs the cache behind
    # git's back => full-run fallback (which still sees b.py's R1)
    assert cli.main(["pkg", "--json", "--no-baseline",
                     "--changed-only"]) == 1
    d3 = json.loads(capsys.readouterr().out)
    assert "fallback" in d3["cache"]["mode"]
    assert "stale" in d3["cache"]["mode"]


def test_baseline_v1_is_rejected_with_migration_pointer(tmp_path):
    p = tmp_path / "bl.json"
    p.write_text('{"version": 1, "findings": {"R2|x|y|z": 1}}')
    with pytest.raises(ValueError, match="MIGRATION"):
        load_baseline(str(p))


# ==================================================== CLI + repo smoke
def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "tpu_lint_cli", os.path.join(REPO, "tools", "tpu_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_nonzero_on_injected_violation(tmp_path, monkeypatch, capsys):
    cli = _load_cli()
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x, n):
            if n > 0:
                return x
            return x.item()
    """))
    monkeypatch.setattr(cli, "REPO", str(tmp_path))
    assert cli.main([str(bad), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "R1" in out and "R2" in out

    # --json carries the machine-readable findings + keys
    assert cli.main([str(bad), "--no-baseline", "--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in data["new_findings"]} == {"R1", "R2"}
    assert all("key" in f for f in data["findings"])

    assert cli.main(["nope_not_here"]) == 2

    # --update-baseline over a subtree would erase the accepted entries
    # outside it; the CLI must refuse
    assert cli.main([str(bad), "--update-baseline"]) == 2


def test_repo_is_clean_under_checked_in_baseline(capsys):
    """THE gate: the shipped tree + .tpu_lint_baseline.json => zero new
    findings. Any regression (new sync/retrace/donation/key/lock bug, or
    a reason-less suppression) fails this test before the runtime soaks
    ever see it."""
    cli = _load_cli()
    rc = cli.main([])   # defaults: paddle_tpu + tools, default baseline
    out = capsys.readouterr().out
    assert rc == 0, f"tpu_lint found NEW findings:\n{out}"
    assert "no new findings" in out
    # the analyzer really saw the tree (not an empty walk)
    assert "trace roots" in out.split("\n")[0]


def test_repo_lock_graph_names_serving_and_lora_edges(capsys):
    """The R6 acceptance shape: the --json lock graph carries the REAL
    lock nodes + acquisition edges of serving/server.py and
    lora/store.py, including the interprocedural order edge the serve
    loop fixes by reading the scheduler's depth under its condition
    variable. (Runs off the whole-repo cache the smoke test above just
    warmed — milliseconds.)"""
    cli = _load_cli()
    rc = cli.main(["--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    lg = data["lock_graph"]
    ids = {l["id"] for l in lg["locks"]}
    assert any(i.endswith("server.py::InferenceServer._cv") for i in ids)
    assert any(i.endswith("store.py::AdapterStore._lock") for i in ids)
    acq = lg["acquisitions"]
    by_file = {a["file"] for a in acq}
    assert "paddle_tpu/serving/server.py" in by_file
    assert "paddle_tpu/lora/store.py" in by_file
    # named functions, not just files: the graph is auditable
    assert any(a["function"] == "AdapterStore.acquire" for a in acq)
    assert any(a["function"] == "InferenceServer._loop" for a in acq)
    # the interprocedural held->acquired edge (cv held across the
    # scheduler-depth property read)
    assert any(e["held"].endswith("InferenceServer._cv")
               and e["acquired"].endswith("FifoScheduler._lock")
               for e in lg["edges"])
    # timing rides the same JSON (warm runs report the cached-run block)
    assert "timing" in data and data["timing"]
