"""dy2static control-flow conversion (VERDICT r3 missing #3): paddle-style
models with tensor-dependent if/while/for, written as plain imperative
Python, must compile under to_static — the ProgramTranslator analogue
(reference python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.jit import not_to_static, to_static
from paddle_tpu.jit.dy2static import convert_control_flow


def test_tensor_if_under_jit():
    # the canonical paddle dy2static example, assignment form
    def f(x):
        if jnp.mean(x) > 0:
            out = x * 2.0
        else:
            out = x - 1.0
        return out

    g = to_static(f)
    xp = jnp.asarray([1.0, 2.0])
    xn = jnp.asarray([-1.0, -2.0])
    np.testing.assert_allclose(np.asarray(g(xp)), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(g(xn)), [-2.0, -3.0])


def test_if_defined_only_in_branches():
    def f(x):
        if jnp.sum(x) > 0:
            y = x + 1.0
        else:
            y = x - 1.0
        return y

    g = to_static(f)
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([2.0]))), [3.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([-2.0]))), [-3.0])


def test_tensor_while_under_jit():
    def f(x):
        while jnp.sum(x) < 100.0:
            x = x * 2.0
        return x

    g = to_static(f)
    out = np.asarray(g(jnp.asarray([1.0, 1.0])))
    assert out.sum() >= 100.0
    assert out.sum() < 200.0  # doubled exactly until crossing


def test_tensor_for_range_under_jit():
    def f(n, x):
        for i in range(n):
            x = x + jnp.asarray(i, x.dtype)
        return x

    g = to_static(f)
    # n is a traced scalar: range() would explode without conversion
    out = g(jnp.asarray(4), jnp.zeros(()))
    assert float(out) == 0 + 1 + 2 + 3
    # and plain python ints still work (unrolled)
    assert float(g(3, jnp.zeros(()))) == 3.0


def test_for_over_tensor_rows_scan():
    def f(xs):
        acc = jnp.zeros(xs.shape[1:], xs.dtype)
        for row in xs:
            acc = acc + row
        return acc

    g = to_static(f)
    xs = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    np.testing.assert_allclose(np.asarray(g(xs)), np.asarray(xs).sum(0))


def test_nested_if_in_while():
    def f(x):
        steps = jnp.zeros((), jnp.int32)
        while jnp.sum(x) < 50.0:
            if jnp.max(x) > 4.0:
                x = x + 10.0
            else:
                x = x * 2.0
            steps = steps + 1
        return x, steps

    g = to_static(f)
    x, steps = g(jnp.asarray([1.0]))
    assert float(jnp.sum(x)) >= 50.0
    assert int(steps) > 0


def test_eager_semantics_preserved():
    # converted code must behave identically OUTSIDE jit (python values)
    def f(x, flag):
        if flag:
            y = x + 1
        else:
            y = x - 1
        total = 0
        for i in range(3):
            total = total + i
        while total < 10:
            total = total + 2
        return y, total

    g = convert_control_flow(f)
    assert g.__d2s_converted__
    assert g(5, True) == (6, 11)
    assert g(5, False) == (4, 11)
    assert f(5, True) == g(5, True)


def test_closure_and_globals_survive():
    scale = 3.0

    def f(x):
        if jnp.sum(x) > 0:
            y = x * scale  # closure read
        else:
            y = x / scale
        return y

    g = to_static(f)
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([2.0]))), [6.0])


def test_undef_branch_poisons_loudly():
    def f(x):
        if jnp.sum(x) > 0:
            y = x * 2.0
        # y undefined on the else path
        return y

    g = convert_control_flow(f)
    # concrete positive: fine
    np.testing.assert_allclose(g(jnp.asarray([1.0])), [2.0])
    # concrete negative: y is UNDEF -> poison error mentioning the cause
    with pytest.raises(RuntimeError, match="not defined on every path"):
        np.asarray(g(jnp.asarray([-1.0]))) * 1.0


def test_escape_statements_keep_python_semantics():
    # return inside if / break inside for: left unconverted (trace-only),
    # plain python still works
    def f(x, n):
        if n > 2:
            return x * 10
        total = x
        for i in range(10):
            if i >= n:
                break
            total = total + 1
        return total

    g = convert_control_flow(f)
    assert g(1, 5) == 10
    assert g(1, 2) == 3


def test_foreign_decorator_skips_conversion():
    import functools

    def doubler(fn):
        @functools.wraps(fn)
        def inner(*a):
            return fn(*a) * 2
        return inner

    @doubler
    def f(x):
        if x > 0:
            y = x
        else:
            y = -x
        return y

    # conversion would silently drop the doubling wrapper — must skip
    assert convert_control_flow(f) is f
    assert f(4) == 8


def test_generator_skips_conversion():
    def gen(xs, flag):
        if flag:
            yield 1
        for x in xs:
            yield x

    assert convert_control_flow(gen) is gen
    assert list(gen([10], True)) == [1, 10]


def test_def_and_import_inside_branch():
    def f(x, flag):
        if flag:
            def act(v):
                return v * 2
        else:
            def act(v):
                return v
        return act(x)

    g = convert_control_flow(f)
    assert g(3, True) == 6
    assert g(3, False) == 3

    def h(flag):
        if flag:
            import math as m
        else:
            import cmath as m
        return m.sqrt(4)

    g2 = convert_control_flow(h)
    assert g2(True) == 2.0


def test_del_inside_branch():
    def f(x, flag):
        if flag:
            tmp = x * 2
            y = tmp
            del tmp
        else:
            y = x
        return y

    g = convert_control_flow(f)
    assert g(5, True) == 10
    assert g(5, False) == 5


def test_super_method_skips_conversion():
    class Base:
        def run(self, x):
            return x + 1

    class Sub(Base):
        def run(self, x):
            if x > 0:
                y = super().run(x)
            else:
                y = x
            return y

    g = convert_control_flow(Sub.run)
    assert g is Sub.run  # conversion cannot rebuild the __class__ cell
    assert Sub().run(3) == 4


def test_walrus_in_while_test_skips_conversion():
    def f(xs):
        it = iter(xs)
        total = 0
        while (v := next(it)) > 0:
            total = total + v
        return total

    g = convert_control_flow(f)
    assert g([3, 5, -1]) == 8


def test_not_to_static_marker():
    @not_to_static
    def f(x):
        if jnp.sum(x) > 0:
            y = x
        else:
            y = -x
        return y

    assert convert_control_flow(f) is f


def test_layer_with_dynamic_forward():
    class DynNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin_a = nn.Linear(4, 4)
            self.lin_b = nn.Linear(4, 4)

        def forward(self, x):
            if jnp.mean(x) > 0:
                out = self.lin_a(x)
            else:
                out = self.lin_b(x)
            return out

    pt.seed(0)
    net = DynNet()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)),
                    jnp.float32)
    # eager references for both paths
    ref_pos = np.asarray(net.lin_a(jnp.abs(x)))
    ref_neg = np.asarray(net.lin_b(-jnp.abs(x) - 1.0))
    g = to_static(net)
    np.testing.assert_allclose(np.asarray(g(jnp.abs(x))), ref_pos,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g(-jnp.abs(x) - 1.0)), ref_neg,
                               rtol=1e-5)


def test_loop_bound_makes_while_differentiable():
    """to_static(loop_bound=N): converted while lowers to a masked scan —
    identical values, and reverse-mode grads flow (the while_grad
    analogue). The bound is baked per-wrapper, so while_loop and scan
    variants of the same fn coexist without jit-cache crosstalk."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        while jnp.sum(x) < 10.0:
            x = x * 2.0
        return jnp.sum(x)

    x0 = jnp.asarray([1.0, 0.5])
    ref = float(to_static(f)(x0))             # while_loop path
    bounded = to_static(f, loop_bound=16)     # masked-scan path
    assert float(bounded(x0)) == ref
    grad = jax.grad(convert_control_flow(f, loop_bound=16))(x0)
    # sum 1.5 doubles 3x -> 12; d out / d x = 8 everywhere
    np.testing.assert_allclose(np.asarray(grad), [8.0, 8.0], rtol=1e-6)
    # numerical check against the unbounded eager semantics
    eps = 1e-3
    num = (f(np.asarray([1.0 + eps, 0.5], np.float32)) -
           f(np.asarray([1.0 - eps, 0.5], np.float32))) / (2 * eps)
    np.testing.assert_allclose(float(num), float(grad[0]), rtol=1e-2)


def test_loop_bound_double_where_grad_is_finite():
    """The masked tail runs the body on the frozen exit state, where it
    can be numerically undefined — the double-where select must keep the
    dead branch's NaN out of the cotangent."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        # body is undefined (sqrt of negative) once x has crossed 2.0
        while jnp.sum(x) > 2.0:
            x = x * jnp.sqrt(jnp.sum(x) - 2.0) * 0.1
        return jnp.sum(x)

    g = convert_control_flow(f, loop_bound=8)
    x0 = jnp.asarray([3.0, 1.5])
    val = float(g(x0))
    assert np.isfinite(val)
    grad = jax.grad(g)(x0)
    assert np.isfinite(np.asarray(grad)).all(), grad


def test_loop_bound_trains_while_model():
    """End-to-end: a while-based model is trainable with loop_bound."""
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.optimizer import SGD

    class Halver(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            while jnp.linalg.norm(h) > 2.0:
                h = h * 0.5
            return h

    pt.seed(2)
    net = Halver()
    to_static(net, loop_bound=12)
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32) * 4
    y = np.tanh(x)
    step = TrainStep(net, SGD(learning_rate=0.05),
                     loss_fn=lambda out, b: jnp.mean((out - b[1]) ** 2))
    losses = [float(np.asarray(step((x, y)))) for _ in range(30)]
    assert losses[-1] < losses[0], losses


def test_dynamic_rnn_style_model():
    """The reference's loop_transformer flagship: a while-loop RNN whose
    step count depends on tensor data, trained end-to-end."""
    class ClipRNN(nn.Layer):
        def __init__(self):
            super().__init__()
            self.cell = nn.Linear(8, 8)

        def forward(self, x):
            h = x
            n = jnp.zeros((), jnp.int32)
            while jnp.linalg.norm(h) < 10.0:
                h = self.cell(h) + h
                n = n + 1
            return h, n

    pt.seed(1)
    net = ClipRNN()
    g = to_static(net)
    h, n = g(jnp.ones((8,), jnp.float32) * 0.1)
    assert float(jnp.linalg.norm(h)) >= 10.0
    assert int(n) >= 1


# ------------------------------------------------ return lowering (r5)
def test_return_in_both_branches_converts():
    """`if cond: return a / else: return b` with a TENSOR predicate must
    lower to lax.cond (reference ReturnTransformer,
    python/paddle/jit/dy2static/return_transformer.py) — under jit a
    trace-only fallback would raise a TracerBoolConversionError."""
    def f(x):
        if jnp.mean(x) > 0:
            return x * 2.0
        else:
            return x - 1.0

    g = to_static(f)
    compiled = jax.jit(g)
    np.testing.assert_allclose(np.asarray(compiled(jnp.asarray([1.0, 2.0]))),
                               [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(compiled(jnp.asarray([-1.0, -2.0]))),
                               [-2.0, -3.0])


def test_guard_clause_return_converts():
    """Early-return guard followed by more statements: the tail folds into
    the else path."""
    def f(x):
        if jnp.sum(x) > 10.0:
            return x * 0.0
        y = x + 1.0
        y = y * 2.0
        return y

    g = jax.jit(to_static(f))
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([20.0]))), [0.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([1.0]))), [4.0])


def test_elif_chain_returns_convert():
    def f(x):
        s = jnp.sum(x)
        if s > 10.0:
            return x * 3.0
        elif s > 0.0:
            return x * 2.0
        else:
            return -x

    g = jax.jit(to_static(f))
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([20.0]))), [60.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([2.0]))), [4.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([-2.0]))), [2.0])


def test_bare_and_implicit_none_returns_match_python():
    """Concrete predicates (outside jit) must keep exact python
    semantics, including the implicit `return None` when the guard does
    not fire. (Under jit a tensor predicate with structurally-mismatched
    branch returns still errors loudly — lax.cond demands matching
    pytrees — exactly as the unconverted trace would.)"""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x, flag):
        if flag:  # concrete bool: python dispatch at runtime
            return x + 1.0
        # implicit: returns None

    g = convert_control_flow(f)
    assert g.__d2s_converted__
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([1.0]), True)),
                               [2.0])
    assert g(jnp.asarray([1.0]), False) is None


def test_return_inside_loop_stays_python():
    """Returns inside loops are NOT lowered (documented limit): eager
    semantics must be preserved untouched."""
    def f(xs):
        for i in range(3):
            if i == 2:
                return xs + i
        return xs

    g = to_static(f)
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([1.0]))), [3.0])


def test_mixed_assignment_and_return_branch():
    """One branch returns, the other assigns and falls through."""
    def f(x):
        if jnp.sum(x) < 0:
            return jnp.zeros_like(x)
        else:
            y = x * 3.0
        return y + 1.0

    g = jax.jit(to_static(f))
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([-1.0]))), [0.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([2.0]))), [7.0])


# ------------------------- liveness soundness regressions (r5 review)
def test_augassign_keeps_branch_result_live():
    """y += 1 READS y: liveness must keep y carried out of the if."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        if x > 0:
            y = x
        else:
            y = -x
        y += 1.0
        return x * 2.0

    g = convert_control_flow(f)
    assert g(2.0) == 4.0
    assert g(-2.0) == -4.0


def test_closure_defined_before_if_keeps_name_live():
    """A nested def BEFORE the if reads its free variable at CALL time —
    backward statement-order liveness alone would prune it."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x, cond):
        def g():
            return y

        if cond:
            y = x * 2
        else:
            y = x
        return g()

    h = convert_control_flow(f)
    assert h(3.0, True) == 6.0
    assert h(3.0, False) == 3.0


def test_loop_else_reads_keep_inner_if_results():
    """for/while-else blocks run after the loop: their reads must keep
    names assigned by converted ifs inside the (python-kept) loop body."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(items, cond):
        for i in items:
            if cond:
                y = i
            else:
                y = -i
        else:
            out = y + 1
        return out

    g = convert_control_flow(f)
    assert g([1.0, 2.0], True) == 3.0
    assert g([1.0, 2.0], False) == -1.0

    def fw(n, cond):
        i = 0
        while i < n:
            if cond:
                y = i
            else:
                y = -i
            i += 1
        else:
            out = y + 10
        return out

    gw = convert_control_flow(fw)
    assert gw(3, True) == 12
    assert gw(3, False) == 8


def test_match_case_bodies_still_convert():
    """Control flow inside match-case bodies must still be reached by the
    converter (the block traversal must visit `cases`)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x, tag):
        match tag:
            case "double":
                if jnp.sum(x) > 0:
                    y = x * 2.0
                else:
                    y = x - 1.0
            case _:
                y = x
        return y

    g = convert_control_flow(f)
    assert g.__d2s_converted__
    np.testing.assert_allclose(
        np.asarray(jax.jit(g, static_argnums=1)(jnp.asarray([1.0]),
                                                "double")), [2.0])
    np.testing.assert_allclose(
        np.asarray(g(jnp.asarray([-1.0]), "double")), [-2.0])
    np.testing.assert_allclose(np.asarray(g(jnp.asarray([5.0]), "other")),
                               [5.0])


# --------------------------------- break/continue lowering (r5)
def test_tensor_break_in_while_converts():
    """`if c: break` with a tensor condition lowers to flag/guard form
    and runs under jit (reference BreakContinueTransformer,
    python/paddle/jit/dy2static/break_continue_transformer.py)."""
    def f(x):
        s = x
        i = jnp.zeros(())
        while i < 8.0:
            s = s * 1.5
            if jnp.sum(s) > 40.0:
                break
            i = i + 1.0
        return s, i

    # python reference semantics
    def ref(x):
        s = np.asarray(x, np.float32)
        i = 0.0
        while i < 8.0:
            s = s * np.float32(1.5)
            if s.sum() > 40.0:
                break
            i = i + 1.0
        return s, i

    g = jax.jit(to_static(f))
    for start in ([4.0, 4.0], [0.1, 0.1]):
        s_ref, i_ref = ref(np.asarray(start, np.float32))
        s_got, i_got = g(jnp.asarray(start))
        np.testing.assert_allclose(np.asarray(s_got), s_ref, rtol=1e-6)
        assert float(i_got) == i_ref


def test_tensor_continue_in_while_converts():
    """`if c: continue` guards the remaining statements."""
    def f(x):
        total = jnp.zeros(())
        i = jnp.zeros(())
        while i < 6.0:
            i = i + 1.0
            if jnp.sum(x) * i % 2.0 < 1.0:
                continue
            total = total + i
        return total

    def ref(xsum):
        total, i = 0.0, 0.0
        while i < 6.0:
            i += 1.0
            if xsum * i % 2.0 < 1.0:
                continue
            total += i
        return total

    g = jax.jit(to_static(f))
    assert float(g(jnp.asarray([1.0]))) == ref(1.0)
    assert float(g(jnp.asarray([0.5]))) == ref(0.5)


def test_general_escape_shapes_keep_python_semantics():
    """The r5 generalized lowering (break with neighbouring statements,
    while-else, bare escapes) must keep exact eager semantics."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x, n):
        total = x
        i = 0
        while True:
            if i >= n:
                total = total + 100
                break  # break with a statement before it, same if-body
            total = total + 1
            i += 1
        return total

    g = convert_control_flow(f)
    assert g(1, 3) == 104

    # while-else + break: the else must NOT run when the break fires
    # (lowered to a `not brk` guard on the detached epilogue)
    def fe(n):
        i = 0
        while i < 5:
            if i == 2:
                break
            i += 1
        else:
            i = 100
        return i

    ge = convert_control_flow(fe)
    assert ge(3) == 2 == fe(3)
    assert ge(1) == 2 == fe(1)

    # while-else without a break: else always runs
    def fne(n):
        i = 0
        while i < n:
            i += 1
        else:
            i = i + 1000
        return i

    gne = convert_control_flow(fne)
    assert gne(3) == 1003 == fne(3)
    assert gne(0) == 1000 == fne(0)

    # walrus in the test: lowering and conversion both bail; eager works
    def fw(vals):
        s = 0
        k = 0
        while (v := vals[k]) > 0:
            if v > 100:
                break
            s += v
            k += 1
        return s

    gw = convert_control_flow(fw)
    assert gw([1, 2, 3, -1]) == 6 == fw([1, 2, 3, -1])
    assert gw([1, 2, 500, -1]) == 3 == fw([1, 2, 500, -1])


def test_break_with_statements_converts_under_jit():
    """Break with neighbouring statements in the same if-body, plus
    statements under else, lowers and compiles with a TENSOR condition
    (the unconverted form would raise ConcretizationTypeError)."""
    def f(x):
        s = x
        i = jnp.zeros(())
        while i < 8.0:
            if jnp.sum(s) > 40.0:
                s = s - 5.0
                break
            else:
                s = s * 1.5
            i = i + 1.0
        return s, i

    def ref(x):
        s = np.asarray(x, np.float32)
        i = 0.0
        while i < 8.0:
            if s.sum() > 40.0:
                s = s - np.float32(5.0)
                break
            else:
                s = s * np.float32(1.5)
            i = i + 1.0
        return s, i

    g = jax.jit(to_static(f))
    for start in ([4.0, 4.0], [30.0, 30.0], [0.1, 0.1]):
        s_ref, i_ref = ref(np.asarray(start, np.float32))
        s_got, i_got = g(jnp.asarray(start))
        np.testing.assert_allclose(np.asarray(s_got), s_ref, rtol=1e-6)
        assert float(i_got) == i_ref


def test_break_under_else_converts_under_jit():
    def f(x):
        s = x
        i = jnp.zeros(())
        while i < 6.0:
            if jnp.sum(s) < 100.0:
                s = s * 2.0
            else:
                break
            i = i + 1.0
        return s, i

    def ref(x):
        s = np.asarray(x, np.float32)
        i = 0.0
        while i < 6.0:
            if s.sum() < 100.0:
                s = s * np.float32(2.0)
            else:
                break
            i = i + 1.0
        return s, i

    g = jax.jit(to_static(f))
    for start in ([3.0, 3.0], [60.0, 60.0]):
        s_ref, i_ref = ref(np.asarray(start, np.float32))
        s_got, i_got = g(jnp.asarray(start))
        np.testing.assert_allclose(np.asarray(s_got), s_ref, rtol=1e-6)
        assert float(i_got) == i_ref


def test_while_else_with_tensor_break_converts_under_jit():
    """while-else with a tensor break: the else must run exactly when
    the loop exits via its test — both paths, compiled."""
    def f(x):
        s = x
        i = jnp.zeros(())
        while i < 4.0:
            s = s * 2.0
            if jnp.sum(s) > 50.0:
                break
            i = i + 1.0
        else:
            s = s + 1000.0
        return s

    def ref(x):
        s = np.asarray(x, np.float32)
        i = 0.0
        while i < 4.0:
            s = s * np.float32(2.0)
            if s.sum() > 50.0:
                break
            i = i + 1.0
        else:
            s = s + np.float32(1000.0)
        return s

    g = jax.jit(to_static(f))
    for start in ([20.0, 20.0], [0.5, 0.5]):  # break taken / not taken
        np.testing.assert_allclose(
            np.asarray(g(jnp.asarray(start))),
            ref(np.asarray(start, np.float32)), rtol=1e-6)


def test_for_range_else_with_tensor_break_converts():
    """for-range-else: the search-loop idiom — else runs only when no
    break fired."""
    def f(x):
        found = jnp.zeros(())
        for i in range(5):
            if x[i] > 10.0:
                found = jnp.zeros(()) + i
                break
        else:
            found = jnp.asarray(-1.0)
        return found

    def ref(x):
        for i in range(5):
            if x[i] > 10.0:
                return float(i)
        return -1.0

    g = jax.jit(to_static(f))
    hit = np.asarray([1.0, 2.0, 50.0, 3.0, 4.0], np.float32)
    miss = np.asarray([1.0, 2.0, 3.0, 4.0, 5.0], np.float32)
    assert float(g(jnp.asarray(hit))) == ref(hit)
    assert float(g(jnp.asarray(miss))) == ref(miss)


def test_mixed_break_continue_nested_ifs_convert():
    """break and continue in one nested if/elif chain, both tensor-
    dependent, with trailing statements guarded by the escape flag."""
    def f(x):
        total = jnp.zeros(())
        i = jnp.zeros(())
        while i < 10.0:
            i = i + 1.0
            v = jnp.sum(x) * i
            if v % 3.0 < 1.0:
                continue
            elif v > 20.0:
                total = total + 100.0
                break
            total = total + v
        return total, i

    def ref(xsum):
        total, i = 0.0, 0.0
        while i < 10.0:
            i += 1.0
            v = xsum * i
            if v % 3.0 < 1.0:
                continue
            elif v > 20.0:
                total += 100.0
                break
            total += v
        return total, i

    g = jax.jit(to_static(f))
    for xv in (1.0, 2.5, 0.3):
        t_ref, i_ref = ref(xv)
        t_got, i_got = g(jnp.asarray([xv]))
        np.testing.assert_allclose(float(t_got), t_ref, rtol=1e-6)
        assert float(i_got) == i_ref


def test_escape_inside_try_stays_python():
    """An escape buried in a try block is unliftable: the loop stays a
    python loop and eager semantics hold."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(vals):
        s = 0
        i = 0
        while i < len(vals):
            try:
                if vals[i] < 0:
                    break
                s += vals[i]
            finally:
                i += 1
        return s

    g = convert_control_flow(f)
    assert g([1, 2, -1, 5]) == 3 == f([1, 2, -1, 5])
    assert g([1, 2, 3]) == 6 == f([1, 2, 3])


def test_break_mid_loop_concrete_matches_python():
    """Concrete values through the lowered form: break semantics exact,
    including NOT re-evaluating the loop test after the break fires."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    tests = []

    def f(xs):
        i = 0
        out = []
        while tests.append(i) or i < len(xs):
            if xs[i] < 0:
                break
            out.append(xs[i])
            i += 1
        return out

    g = convert_control_flow(f)
    tests.clear()
    assert g([1, 2, -1, 4]) == [1, 2]
    n_evals = len(tests)
    tests.clear()
    assert f([1, 2, -1, 4]) == [1, 2]
    assert n_evals == len(tests)  # test evaluated the same number of times


def test_for_range_with_tensor_break_converts():
    """The canonical decode loop: `for i in range(n): ... if eos: break`
    rewrites to the while form and lowers (reference transforms for-range
    the same way before BreakContinueTransformer)."""
    def f(x):
        h = x
        steps = jnp.zeros(())
        for i in range(10):
            h = h * 1.4
            if jnp.sum(h) > 30.0:
                break
            steps = steps + 1.0
        return h, steps

    def ref(x):
        h = np.asarray(x, np.float32)
        steps = 0.0
        for i in range(10):
            h = h * np.float32(1.4)
            if h.sum() > 30.0:
                break
            steps += 1.0
        return h, steps

    g = jax.jit(to_static(f))
    for start in ([2.0, 2.0], [0.01, 0.01]):
        h_ref, s_ref = ref(np.asarray(start, np.float32))
        h_got, s_got = g(jnp.asarray(start))
        np.testing.assert_allclose(np.asarray(h_got), h_ref, rtol=1e-5)
        assert float(s_got) == s_ref


def test_for_range_with_continue_and_step():
    """continue + negative step through the while rewrite, eager parity."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(vals):
        total = 0
        for i in range(8, 0, -2):
            if vals[i % len(vals)] < 0:
                continue
            total = total + i
        return total, i

    g = convert_control_flow(f)
    for vals in ([1, -1, 1], [1, 1, 1], [-1, -1, -1]):
        assert g(vals) == f(vals)


def test_for_range_break_keeps_loop_var_semantics():
    """After the loop the target holds the break-iteration value, exactly
    as python leaves it."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n, lim):
        acc = 0
        for i in range(n):
            if acc >= lim:
                break
            acc += i
        return acc, i

    g = convert_control_flow(f)
    assert g(10, 6) == f(10, 6)
    assert g(10, 1000) == f(10, 1000)


def test_for_range_arg_eval_order_and_side_effects():
    """range args must evaluate left-to-right exactly once (start, stop,
    step) — the rewrite's prelude must preserve python's order."""
    def f(it):
        total = 0
        for i in range(next(it), next(it), -1):
            if total > 1000:
                break
            total = total + i
        return total

    g = convert_control_flow(f)
    assert g(iter([10, 3, 7])) == f(iter([10, 3, 7])) == 10+9+8+7+6+5+4


def test_nested_break_does_not_rewrite_outer_for():
    """A break belonging to a NESTED loop must not trigger the outer
    for-range rewrite: the outer loop keeps the exact-count convert_for
    path (under loop_bound a while would be truncated to the bound)."""
    def f(n, x):
        total = jnp.zeros(())
        for i in range(n):
            j = 0
            while j < 5:
                total = total + x
                j += 1
                if j >= 5:  # concrete: the inner loop's OWN break
                    break
        return total

    g = to_static(f, loop_bound=3)
    # 20 outer iterations x 5 inner: a while-rewritten outer loop would be
    # truncated to loop_bound=3 outer steps (15.0) — must be 100.0
    out = g(jnp.asarray(20), jnp.asarray(1.0))
    assert float(out) == 100.0


def test_starred_range_args_stay_python_but_function_still_converts():
    """range(*bounds)+break can't rewrite; the loop stays python and the
    REST of the function must still convert (no recompile failure)."""
    def f(x, bounds):
        if jnp.sum(x) > 0:  # must still lower to lax.cond
            y = x * 2.0
        else:
            y = -x
        total = 0
        for i in range(*bounds):
            if i > 2:
                break
            total = total + i
        return y, total

    g = convert_control_flow(f)
    assert g.__d2s_converted__
    y, total = g(jnp.asarray([1.0]), (0, 10))
    np.testing.assert_allclose(np.asarray(y), [2.0])
    assert total == 0 + 1 + 2


def test_zero_trip_for_target_poisons_on_use():
    """Zero-trip rewritten for-range: the unbound loop target follows the
    documented UNDEF contract — poison on USE with a loud message (python
    raises UnboundLocalError at the read; conversion defers to use)."""
    def f(n, lim):
        acc = 0
        for i in range(n):
            if acc >= lim:
                break
            acc += i
        return acc, i

    g = convert_control_flow(f)
    acc, i = g(0, 5)
    assert acc == 0
    with pytest.raises(RuntimeError, match="not defined on every path"):
        i + 1


def test_for_range_break_not_truncated_by_loop_bound():
    """A statically-counted for-range with a tensor break must run its
    full trip count even when converted with a smaller loop_bound (the
    bound is for unbounded whiles; a break only SHORTENS a for)."""
    def f(x):
        s = jnp.zeros(())
        for i in range(10):
            s = s + x
            if jnp.sum(s) > 1e9:  # never fires
                break
        return s

    g = jax.jit(to_static(f, loop_bound=3))
    assert float(g(jnp.asarray(1.0))) == 10.0
    # and the break itself still works at that exact count
    def f2(x):
        s = jnp.zeros(())
        for i in range(10):
            s = s + x
            if jnp.sum(s) > 4.5:
                break
        return s

    g2 = jax.jit(to_static(f2, loop_bound=3))
    assert float(g2(jnp.asarray(1.0))) == 5.0


def test_for_range_break_validates_range_args():
    """The rewrite must keep python's range() argument validation."""
    def f(x, n):
        s = 0
        for i in range(n):
            if s > 100:
                break
            s += 1
        return s

    g = convert_control_flow(f)
    with pytest.raises(TypeError):
        g(1, 2.5)
    assert g(1, 3) == 3


def test_zero_step_range_raises_even_with_traced_bounds():
    """range(a, b, 0) must raise like python even when a/b are traced."""
    def f(a, b):
        s = jnp.zeros(())
        for i in range(a, b, 0):
            s = s + 1.0
            if jnp.sum(s) > 3.0:
                break
        return s

    g = to_static(f)
    with pytest.raises(ValueError, match="must not be zero"):
        g(jnp.asarray(5), jnp.asarray(0))


def test_method_decoration_trains_under_trainstep():
    """`@to_static(loop_bound=N)` directly on `forward` in the class body
    (the canonical reference idiom): `self` must not fall into a
    standalone jit, the converted control flow must lower under
    TrainStep's enclosing jit, and the bounded while must be
    differentiable end to end."""
    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.optimizer import AdamW

    class IterRefine(nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(4, 4)

        @to_static(loop_bound=6)
        def forward(self, x):
            h = self.proj(x)
            i = jnp.zeros(())
            while i < 4.0:
                if jnp.mean(h * h) > 9.0:
                    h = h * 0.5
                    break
                h = h + 0.2 * self.proj(h)
                i = i + 1.0
            else:
                h = h + 0.01
            return h

    pt.seed(0)
    model = IterRefine()
    step = pt.TrainStep(model, AdamW(learning_rate=5e-3),
                        loss_fn=lambda out, b: F.mse_loss(out, b[1]))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32)
    t = rng.standard_normal((8, 4)).astype(np.float32)
    losses = [float(step((x, t))) for _ in range(8)]
    assert losses[-1] < losses[0]

    # the dispatched forward is the converted function, not the original
    step.sync_to_model()
    out = np.asarray(model(pt.to_tensor(x)))
    # python-semantics reference on the synced weights
    h = np.asarray(model.proj(pt.to_tensor(x)))
    i = 0.0
    while i < 4.0:
        if (h * h).mean() > 9.0:
            h = h * 0.5
            break
        h = h + 0.2 * np.asarray(model.proj(pt.to_tensor(h)))
        i += 1.0
    else:
        h = h + 0.01
    np.testing.assert_allclose(out, h, rtol=1e-5)


def test_break_in_nested_loop_else_binds_outer():
    """A break in a NESTED loop's else clause belongs to the OUTER loop:
    the outer loop's else must not run when it fires (review finding:
    nested-orelse escapes were shielded with the nested body)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        out = 0
        i = 0
        while i < n:
            j = 0
            while j < 2:
                j += 1
            else:
                if i == 2:
                    break
            i += 1
        else:
            out = 999
        return out, i

    g = convert_control_flow(f)
    assert g(5) == (0, 2) == f(5)       # break fires: else skipped
    assert g(2) == (999, 2) == f(2)     # no break: else runs


def test_detached_loop_else_keeps_earlier_liveness():
    """A detached loop-else's reads must stay visible to the liveness of
    EARLIER converted statements (review finding: reads were collected
    from the mutated node, losing the detached else)."""
    def f(t):
        if t > 0:
            y = 1.0
        else:
            y = 2.0
        i = 0
        while i < 3:
            i += 1
        else:
            z = y + 10.0
        return z

    from paddle_tpu.jit.dy2static import convert_control_flow
    g = convert_control_flow(f)
    assert g(1) == 11.0 == f(1)
    assert g(-1) == 12.0 == f(-1)

    # same through the for-else detach
    def h(t):
        if t > 0:
            y = 1.0
        else:
            y = 2.0
        for i in range(3):
            pass
        else:
            z = y + 10.0
        return z

    g2 = convert_control_flow(h)
    assert g2(1) == 11.0 == h(1)
    assert g2(-1) == 12.0 == h(-1)

    # and through the break-guarded while-else detach
    def k(t, n):
        if t > 0:
            y = 1.0
        else:
            y = 2.0
        i = 0
        z = 0.0
        while i < 5:
            if i >= n:
                break
            i += 1
        else:
            z = y + 10.0
        return z

    g3 = convert_control_flow(k)
    assert g3(1, 99) == 11.0 == k(1, 99)   # no break: else runs, reads y
    assert g3(1, 2) == 0.0 == k(1, 2)      # break: else skipped


def test_method_to_static_warns_on_dropped_jit_kwargs():
    import warnings

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")

        class M(nn.Layer):
            @to_static(loop_bound=4, donate_argnums=1)
            def forward(self, x):
                return x

    assert any("ignores jit options" in str(w.message) for w in rec)


def test_for_else_reading_loop_target_stays_exact():
    """A for-else that reads the loop target must keep python-exact
    semantics (the converted loop's target is body-local, so the else is
    left attached and the loop stays a python loop)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        i = 99
        s = x
        for i in range(3):
            s = s + 1.0
        else:
            z = i * 1.0
        return s + z

    g = convert_control_flow(f)
    assert g(0.0) == 5.0 == f(0.0)

    # without a pre-binding the else still sees the loop's last value
    def h(x):
        s = x
        for i in range(3):
            s = s + 1.0
        else:
            z = i * 1.0
        return s + z

    g2 = convert_control_flow(h)
    assert g2(0.0) == 5.0 == h(0.0)

    # non-range iterables too
    def k(vals):
        for v in vals:
            pass
        else:
            t = v
        return t

    g3 = convert_control_flow(k)
    assert g3([1, 2, 7]) == 7 == k([1, 2, 7])


def test_for_range_else_reading_target_stays_python():
    """for-range + break whose else reads the loop target must keep the
    python path: a converted zero-trip loop would hand the else an UNDEF
    target where python raises UnboundLocalError."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n, x):
        z = -1
        for i in range(n):
            if x > 10:
                break
        else:
            z = i
        return z

    g = convert_control_flow(f)
    assert g(3, 5) == 2 == f(3, 5)
    assert g(3, 50) == -1 == f(3, 50)
    with pytest.raises(UnboundLocalError):
        f(0, 5)
    with pytest.raises(UnboundLocalError):
        g(0, 5)


def test_for_over_tensor_with_break_converts():
    """Escapes over a tensor iterable: the runtime indexability dispatch
    rewrites to the for-range form, so a tensor-dependent break compiles
    (scan-with-early-exit, the capability the plain scan path lacks)."""
    def f(xs):
        total = jnp.zeros(())
        for row in xs:
            s = jnp.sum(row)
            if s > 10.0:
                break
            total = total + s
        return total

    def ref(xs):
        total = 0.0
        for row in np.asarray(xs):
            s = row.sum()
            if s > 10.0:
                break
            total += s
        return total

    g = jax.jit(to_static(f))
    xs1 = np.asarray([[1, 2], [3, 4], [20, 1], [5, 5]], np.float32)
    xs2 = np.asarray([[1, 2], [3, 4]], np.float32)
    np.testing.assert_allclose(float(g(jnp.asarray(xs1))), ref(xs1))
    np.testing.assert_allclose(float(g(jnp.asarray(xs2))), ref(xs2))


def test_for_over_list_with_break_eager_parity():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(vals, cap):
        out = []
        for v in vals:
            if v > cap:
                break
            out.append(v)
        else:
            out.append(-1)
        return out

    g = convert_control_flow(f)
    assert g([1, 2, 9, 3], 5) == [1, 2] == f([1, 2, 9, 3], 5)
    assert g([1, 2, 3], 5) == [1, 2, 3, -1] == f([1, 2, 3], 5)


def test_for_over_generator_with_break_stays_python():
    """Non-indexable iterables (generators consume once, dicts iterate
    keys) take the python fallback; eager semantics exact."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        gen = (i * i for i in range(n))
        total = 0
        for v in gen:
            if v > 9:
                break
            total += v
        return total

    g = convert_control_flow(f)
    assert g(10) == f(10) == 0 + 1 + 4 + 9

    def h(d):
        keys = []
        for k in d:
            if k == "stop":
                break
            keys.append(k)
        return keys

    g2 = convert_control_flow(h)
    d = {"a": 1, "stop": 2, "b": 3}
    assert g2(d) == h(d) == ["a"]
