"""distribution / sparse / fft / signal tests (SURVEY.md §2.2 API-breadth
components), numpy/scipy references where available."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import distribution as D
from paddle_tpu import fft as pfft
from paddle_tpu import signal as psignal
from paddle_tpu import sparse as psparse


# ---------------------------------------------------------- distribution
def test_normal_moments_and_logprob():
    d = D.Normal(loc=1.0, scale=2.0)
    s = d.sample((20000,), seed=0)
    assert abs(float(s.mean()) - 1.0) < 0.05
    assert abs(float(s.std()) - 2.0) < 0.05
    from scipy import stats

    np.testing.assert_allclose(d.log_prob(jnp.asarray([0.5, 3.0])),
                               stats.norm.logpdf([0.5, 3.0], 1.0, 2.0),
                               rtol=1e-5)
    np.testing.assert_allclose(d.cdf(1.0), 0.5, atol=1e-6)
    np.testing.assert_allclose(d.icdf(d.cdf(2.5)), 2.5, rtol=1e-4)
    np.testing.assert_allclose(d.entropy(),
                               stats.norm.entropy(1.0, 2.0), rtol=1e-6)


def test_uniform_bernoulli_categorical():
    u = D.Uniform(0.0, 4.0)
    assert float(u.log_prob(jnp.asarray(5.0))) == -np.inf
    np.testing.assert_allclose(u.entropy(), np.log(4.0), rtol=1e-6)

    b = D.Bernoulli(probs=jnp.asarray([0.2, 0.8]))
    s = b.sample((5000,), seed=1)
    np.testing.assert_allclose(s.mean(0), [0.2, 0.8], atol=0.03)

    c = D.Categorical(probs=jnp.asarray([0.1, 0.2, 0.7]))
    s = c.sample((8000,), seed=2)
    counts = np.bincount(np.asarray(s), minlength=3) / 8000
    np.testing.assert_allclose(counts, [0.1, 0.2, 0.7], atol=0.03)
    from scipy import stats

    np.testing.assert_allclose(c.entropy(),
                               stats.entropy([0.1, 0.2, 0.7]), rtol=1e-5)


@pytest.mark.parametrize("dist,scipy_name,args", [
    (lambda: D.Beta(2.0, 3.0), "beta", (2.0, 3.0)),
    (lambda: D.Gamma(2.0, 3.0), "gamma", None),
    (lambda: D.Laplace(0.5, 1.5), "laplace", None),
    (lambda: D.Gumbel(0.0, 1.0), "gumbel_r", None),
])
def test_logprob_vs_scipy(dist, scipy_name, args):
    from scipy import stats

    d = dist()
    xs = np.asarray([0.3, 0.7], np.float32)
    if scipy_name == "beta":
        want = stats.beta.logpdf(xs, 2.0, 3.0)
    elif scipy_name == "gamma":
        want = stats.gamma.logpdf(xs, 2.0, scale=1 / 3.0)
    elif scipy_name == "laplace":
        want = stats.laplace.logpdf(xs, 0.5, 1.5)
    else:
        want = stats.gumbel_r.logpdf(xs)
    np.testing.assert_allclose(d.log_prob(jnp.asarray(xs)), want, rtol=1e-4)


def test_dirichlet_multinomial():
    d = D.Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
    s = d.sample((4000,), seed=3)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6], atol=0.03)

    m = D.Multinomial(10, jnp.asarray([0.3, 0.7]))
    s = m.sample((2000,), seed=4)
    assert s.shape == (2000, 2)
    np.testing.assert_array_equal(np.asarray(s.sum(-1)), 10)
    np.testing.assert_allclose(s.mean(0), [3.0, 7.0], atol=0.2)
    from scipy import stats

    np.testing.assert_allclose(
        m.log_prob(jnp.asarray([4.0, 6.0])),
        stats.multinomial.logpmf([4, 6], 10, [0.3, 0.7]), rtol=1e-4)


def test_kl_divergences():
    from scipy import stats

    p = D.Normal(0.0, 1.0)
    q = D.Normal(1.0, 2.0)
    # closed form
    want = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(D.kl_divergence(p, q), want, rtol=1e-5)
    # self-KL = 0
    np.testing.assert_allclose(
        D.kl_divergence(D.Beta(2.0, 3.0), D.Beta(2.0, 3.0)), 0.0, atol=1e-6)
    cp = D.Categorical(probs=jnp.asarray([0.5, 0.5]))
    cq = D.Categorical(probs=jnp.asarray([0.9, 0.1]))
    want = stats.entropy([0.5, 0.5], [0.9, 0.1])
    np.testing.assert_allclose(D.kl_divergence(cp, cq), want, rtol=1e-5)
    with pytest.raises(NotImplementedError):
        D.kl_divergence(p, cp)


def test_transformed_distribution_lognormal_consistency():
    base = D.Normal(0.2, 0.5)
    t = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(0.2, 0.5)
    xs = jnp.asarray([0.5, 1.0, 2.0])
    np.testing.assert_allclose(t.log_prob(xs), ln.log_prob(xs), rtol=1e-5)
    s = t.sample((2000,), seed=5)
    assert float(s.min()) > 0


def test_affine_chain_transform():
    t = D.ChainTransform([D.AffineTransform(1.0, 2.0), D.TanhTransform()])
    x = jnp.asarray([0.1, -0.3])
    np.testing.assert_allclose(t.inverse(t.forward(x)), x, rtol=1e-5)


# ---------------------------------------------------------------- sparse
def test_sparse_coo_roundtrip():
    dense = np.asarray([[0, 1.0, 0], [2.0, 0, 3.0]], np.float32)
    idx = np.nonzero(dense)
    st = psparse.sparse_coo_tensor(np.stack(idx), dense[idx], dense.shape)
    assert st.nnz() == 3 and st.shape == (2, 3)
    np.testing.assert_array_equal(st.to_dense(), dense)
    np.testing.assert_array_equal(np.asarray(st.indices()), np.stack(idx))


def test_sparse_csr_and_matmul():
    # [[1, 0], [0, 2], [3, 0]]
    st = psparse.sparse_csr_tensor([0, 1, 2, 3], [0, 1, 0], [1.0, 2.0, 3.0],
                                   (3, 2))
    dense = st.to_dense()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)), jnp.float32)
    np.testing.assert_allclose(psparse.matmul(st, x), dense @ x, rtol=1e-5)


def test_sparse_add_mul_relu():
    a = psparse.sparse_coo_tensor([[0, 1], [0, 1]], [1.0, -2.0], (2, 2))
    b = psparse.sparse_coo_tensor([[0, 1], [0, 0]], [5.0, 1.0], (2, 2))
    np.testing.assert_array_equal(psparse.add(a, b).to_dense(),
                                  [[6.0, 0], [1.0, -2.0]])
    np.testing.assert_array_equal(psparse.relu(a).to_dense(),
                                  [[1.0, 0], [0, 0.0]])
    d = jnp.full((2, 2), 3.0)
    np.testing.assert_array_equal(psparse.multiply(a, d).to_dense(),
                                  [[3.0, 0], [0, -6.0]])


def test_sparse_masked_matmul():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(3, 4)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(4, 3)), jnp.float32)
    mask = psparse.sparse_coo_tensor([[0, 2], [1, 0]], [1.0, 1.0], (3, 3))
    out = psparse.masked_matmul(x, y, mask)
    full = np.asarray(x @ y)
    np.testing.assert_allclose(np.asarray(out.values()),
                               [full[0, 1], full[2, 0]], rtol=1e-5)


def test_sparse_matmul_grad():
    st = psparse.sparse_coo_tensor([[0, 1], [1, 0]], [2.0, 4.0], (2, 2))

    def f(x):
        return psparse.matmul(st, x).sum()

    g = jax.grad(f)(jnp.ones((2, 3)))
    np.testing.assert_allclose(g, np.asarray([[4.0] * 3, [2.0] * 3]),
                               rtol=1e-6)


# ------------------------------------------------------------------- fft
def test_fft_matches_numpy():
    x = np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32)
    np.testing.assert_allclose(pfft.fft(x), np.fft.fft(x), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(pfft.rfft(x), np.fft.rfft(x), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(pfft.irfft(pfft.rfft(x)), x, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(pfft.fft2(x), np.fft.fft2(x), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(pfft.fftshift(pfft.fftfreq(8)),
                               np.fft.fftshift(np.fft.fftfreq(8)), rtol=1e-6)
    np.testing.assert_allclose(pfft.fft(x, norm="ortho"),
                               np.fft.fft(x, norm="ortho"), rtol=1e-4,
                               atol=1e-5)


# ---------------------------------------------------------------- signal
def test_frame_overlap_add_inverse():
    x = jnp.asarray(np.arange(16, dtype=np.float32))
    fr = psignal.frame(x, frame_length=4, hop_length=4)  # non-overlapping
    assert fr.shape == (4, 4)
    back = psignal.overlap_add(fr, hop_length=4)
    np.testing.assert_array_equal(back, x)


def test_stft_istft_roundtrip():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 256)), jnp.float32)
    window = jnp.asarray(np.hanning(64), jnp.float32)
    spec = psignal.stft(x, n_fft=64, hop_length=16, window=window)
    assert spec.shape[-2] == 33  # onesided bins
    back = psignal.istft(spec, n_fft=64, hop_length=16, window=window,
                         length=256)
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_stft_matches_scipy():
    from scipy import signal as ssig

    rng = np.random.default_rng(3)
    x = rng.normal(size=512).astype(np.float32)
    win = np.hanning(128).astype(np.float32)
    ours = np.asarray(psignal.stft(jnp.asarray(x), n_fft=128, hop_length=32,
                                   window=jnp.asarray(win), center=False))
    _, _, want = ssig.stft(x, window=win, nperseg=128, noverlap=96,
                           boundary=None, padded=False)
    # scipy normalizes by window.sum(); undo
    want = want * win.sum()
    np.testing.assert_allclose(ours, want, atol=1e-3)


def test_sparse_multiply_sparse():
    a = psparse.sparse_coo_tensor([[0, 1], [0, 1]], [2.0, 3.0], (2, 2))
    b = psparse.sparse_coo_tensor([[0, 1], [0, 0]], [5.0, 7.0], (2, 2))
    out = psparse.multiply(a, b)
    np.testing.assert_array_equal(out.to_dense(), [[10.0, 0], [0, 0.0]])


def test_lognormal_entropy_matches_scipy():
    from scipy import stats

    d = D.LogNormal(0.3, 0.7)
    want = stats.lognorm.entropy(0.7, scale=np.exp(0.3))
    np.testing.assert_allclose(d.entropy(), want, rtol=1e-6)


def test_fft_invalid_norm_raises():
    with pytest.raises(ValueError, match="norm"):
        pfft.fft(np.ones(4), norm="orthogonal")
