"""Multi-rank pipelined serving (VERDICT r3 missing #2): the
FleetExecutor/DistModel analogue — per-stage StableHLO served across
processes over RPC, with output parity against the single-process
Predictor (reference carrier.h:49, dist_model.cc)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_stages():
    pt.seed(7)
    stage0 = nn.Sequential(nn.Linear(8, 32), nn.ReLU())
    stage1 = nn.Sequential(nn.Linear(32, 16), nn.ReLU(), nn.Linear(16, 4))
    full = nn.Sequential(stage0, stage1)
    return stage0, stage1, full


def test_save_dist_model_artifacts(tmp_path):
    from paddle_tpu.hapi.model import InputSpec
    from paddle_tpu.inference import save_dist_model

    stage0, stage1, _ = _build_stages()
    prefix = str(tmp_path / "dm")
    save_dist_model([stage0, stage1], prefix,
                    input_spec=[InputSpec([None, 8], dtype="float32")])
    for i in (0, 1):
        assert os.path.exists(f"{prefix}.stage{i}.pdmodel")
        assert os.path.exists(f"{prefix}.stage{i}.pdiparams")
    assert os.path.exists(prefix + ".distmeta.json")


def test_dist_model_single_rank_parity(tmp_path):
    """nranks=1 degenerates to the plain Predictor (no RPC hop needed for
    the relay's correctness)."""
    from paddle_tpu.hapi.model import InputSpec
    from paddle_tpu.inference import (Config, DistModel, DistModelConfig,
                                      create_predictor, save_dist_model)
    from paddle_tpu.jit import save as jit_save

    stage0, stage1, full = _build_stages()
    prefix = str(tmp_path / "dm1")
    save_dist_model([nn.Sequential(stage0, stage1)], prefix,
                    input_spec=[InputSpec([None, 8], dtype="float32")])
    jit_save(full, str(tmp_path / "full"),
             input_spec=[InputSpec([None, 8], dtype="float32")])

    x = np.random.default_rng(0).standard_normal((6, 8)).astype(np.float32)
    ref = create_predictor(Config(str(tmp_path / "full"))).run([x])

    # self-contained single-process serving, incl. micro-batching
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    dm = DistModel(DistModelConfig(model_prefix=prefix, rank=0, nranks=1,
                                   master_endpoint=ep))
    try:
        np.testing.assert_allclose(dm.run([x])[0], ref[0], rtol=1e-5)
        np.testing.assert_allclose(dm.run([x], num_micro=3)[0], ref[0],
                                   rtol=1e-5)
        # num_micro > batch clamps instead of producing batch=0 splits
        # (which would violate the export's batch>=1 constraint)
        np.testing.assert_allclose(dm.run([x], num_micro=50)[0], ref[0],
                                   rtol=1e-5)
    finally:
        dm.shutdown()


RANK1 = textwrap.dedent("""
    import sys
    from paddle_tpu.inference import DistModel, DistModelConfig
    dm = DistModel(DistModelConfig(model_prefix=sys.argv[1], rank=1,
                                   nranks=2, master_endpoint=sys.argv[2]))
    dm.serve()
    print("RANK1_DONE", flush=True)
""")

RANK0 = textwrap.dedent("""
    import sys
    import numpy as np
    from paddle_tpu.inference import (Config, DistModel, DistModelConfig,
                                      create_predictor)
    prefix, ep, full_prefix = sys.argv[1:4]
    x = np.random.default_rng(0).standard_normal((6, 8)).astype(np.float32)
    ref = create_predictor(Config(full_prefix)).run([x])
    dm = DistModel(DistModelConfig(model_prefix=prefix, rank=0, nranks=2,
                                   master_endpoint=ep))
    out = dm.run([x])
    np.testing.assert_allclose(out[0], ref[0], rtol=1e-5)
    # micro-batch amplification: 3 pipelined micro-batches, same result
    out_mb = dm.run([x], num_micro=3)
    np.testing.assert_allclose(out_mb[0], ref[0], rtol=1e-5)
    print("DIST_MODEL_OK", flush=True)
    dm.shutdown()
""")


def test_dist_model_two_process_parity(tmp_path):
    """The real thing: 2 processes, each loading only its stage, output
    bit-compatible with the single-process Predictor on the full model."""
    from paddle_tpu.hapi.model import InputSpec
    from paddle_tpu.inference import save_dist_model
    from paddle_tpu.jit import save as jit_save

    stage0, stage1, full = _build_stages()
    prefix = str(tmp_path / "dm2")
    full_prefix = str(tmp_path / "full2")
    save_dist_model([stage0, stage1], prefix,
                    input_spec=[InputSpec([None, 8], dtype="float32")])
    jit_save(full, full_prefix,
             input_spec=[InputSpec([None, 8], dtype="float32")])

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r1 = subprocess.Popen([sys.executable, "-c", RANK1, prefix, ep],
                          env=env, cwd=REPO, stdout=subprocess.PIPE,
                          text=True)
    try:
        r0 = subprocess.run([sys.executable, "-c", RANK0, prefix, ep,
                             full_prefix], env=env, cwd=REPO,
                            capture_output=True, text=True, timeout=300)
        assert r0.returncode == 0, r0.stderr
        assert "DIST_MODEL_OK" in r0.stdout
        out1, _ = r1.communicate(timeout=60)
        assert "RANK1_DONE" in out1, out1
    finally:
        if r1.poll() is None:  # failure path: don't leak the serving rank
            r1.kill()
            r1.communicate()
