"""Recompile-proof input pipeline: shape bucketing, tail padding, async
device prefetch, compile-cache accounting, retrace guard.

Acceptance anchor (ISSUE 2): a CPU fit loop over a ragged dataset with 3
sequence lengths compiles <= (1 + #buckets) programs with stabilization on
(vs one compile per distinct shape off), asserted via ``cache_stats()``;
the prefetch iterator demonstrably overlaps and shuts down leak-free.
"""
import gc
import itertools
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import compile_cache
from paddle_tpu.framework.jit import TrainStep
from paddle_tpu.io import (DataLoader, Dataset, PaddedBatcher, bucket_for,
                           default_collate_fn, prefetch_to_device)
from paddle_tpu.io.dataloader import _PrefetchIterator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ fixtures
LENGTHS = (12, 20, 28)
BUCKETS = (16, 32)


class RaggedDataset(Dataset):
    """(ids[L], label): lengths in blocks of 8 samples (two batches of 4),
    22 samples total -> ragged tail batch of 2."""

    def __len__(self):
        return 22

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        L = LENGTHS[min(i // 8, len(LENGTHS) - 1)]
        return (np.asarray(rng.integers(1, 64, L), np.int64),
                np.int64(i % 4))


class TinyClassifier(nn.Layer):
    def __init__(self):
        super().__init__()
        self.embed = nn.Embedding(64, 16)
        self.head = nn.Linear(16, 4)

    def forward(self, ids):
        return self.head(self.embed(ids).mean(axis=1))


# ------------------------------------------------- collate fn satellites
class TestDefaultCollate:
    def test_bool_scalars_stay_bool(self):
        out = default_collate_fn([True, False, True])
        assert out.dtype == np.bool_
        np.testing.assert_array_equal(out, [True, False, True])

    def test_numpy_bool_scalars_stay_bool(self):
        out = default_collate_fn([np.bool_(True), np.bool_(False)])
        assert out.dtype == np.bool_

    def test_numpy_generic_preserves_dtype(self):
        out = default_collate_fn([np.int16(1), np.int16(2)])
        assert out.dtype == np.int16
        out = default_collate_fn([np.float16(0.5), np.float16(1.5)])
        assert out.dtype == np.float16

    def test_empty_batch_raises_value_error(self):
        with pytest.raises(ValueError, match="empty batch"):
            default_collate_fn([])

    def test_python_numbers_unchanged(self):
        assert default_collate_fn([1, 2, 3]).dtype.kind == "i"
        assert default_collate_fn([1.0, 2.0]).dtype.kind == "f"


# ------------------------------------------------------- shape bucketing
class TestBucketing:
    def test_bucket_for_smallest_fit(self):
        assert bucket_for(1, (16, 32)) == 16
        assert bucket_for(16, (16, 32)) == 16
        assert bucket_for(17, (16, 32)) == 32
        assert bucket_for(32, (16, 32)) == 32

    def test_bucket_for_overflow_ladder(self):
        # beyond the top bucket: next multiple of it (bounded shape set)
        assert bucket_for(33, (16, 32)) == 64
        assert bucket_for(65, (16, 32)) == 96

    def test_bucket_for_order_independent(self):
        for L in range(1, 70):
            assert bucket_for(L, (32, 16)) == bucket_for(L, (16, 32))

    def test_bucket_for_deterministic(self):
        sigs = {bucket_for(L, BUCKETS) for L in LENGTHS}
        assert sigs == {16, 32}
        # same length -> same bucket, every time
        assert all(bucket_for(20, BUCKETS) == 32 for _ in range(10))

    def test_bucket_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            bucket_for(5, (0, 16))

    def test_batch_level_bucket_is_max_sample(self):
        b = PaddedBatcher(batch_size=2, pad_batches=False,
                          length_buckets=BUCKETS)
        out = b([(np.zeros(12, np.int64), np.int64(0)),
                 (np.zeros(20, np.int64), np.int64(1))])
        assert out[0].shape == (2, 32)  # 20 -> bucket 32 rules the batch

    def test_length_fields_protects_fixed_size_features(self):
        # (ids[L], soft_label[10]): only field 0 carries the seq axis;
        # without length_fields the 10-vector would be padded to the bucket
        b = PaddedBatcher(batch_size=2, pad_batches=False,
                          length_buckets=(16,), length_fields=(0,))
        out = b([(np.zeros(12, np.int64), np.ones(10, np.float32)),
                 (np.zeros(9, np.int64), np.ones(10, np.float32))])
        assert out[0].shape == (2, 16)
        assert out[1].shape == (2, 10)  # untouched


# ----------------------------------------------------- tail-batch padding
class TestTailPadding:
    def test_tail_padded_and_masked(self):
        loader = DataLoader(RaggedDataset(), batch_size=4, shuffle=False,
                            pad_batches=True, length_buckets=BUCKETS)
        batches = list(loader)
        assert len(batches) == 6
        shapes = {b[0].shape for b in batches}
        assert shapes == {(4, 16), (4, 32)}  # every batch full-size
        # all non-tail masks fully valid
        for b in batches[:-1]:
            np.testing.assert_array_equal(b[-1], [True] * 4)
        ids, label, mask = batches[-1]
        np.testing.assert_array_equal(mask, [True, True, False, False])
        assert mask.dtype == np.bool_
        # filler rows repeat the last REAL sample (finite losses, no junk)
        np.testing.assert_array_equal(ids[2], ids[1])
        np.testing.assert_array_equal(ids[3], ids[1])
        assert label[2] == label[1]

    def test_mask_emitted_for_every_batch(self):
        # batch structure must be shape-stable: the mask is appended even
        # when nothing was padded
        loader = DataLoader(RaggedDataset(), batch_size=2, shuffle=False,
                            pad_batches=True, length_buckets=(32,))
        for b in loader:
            assert len(b) == 3 and b[-1].dtype == np.bool_

    def test_sequence_padding_zero_filled(self):
        b = PaddedBatcher(batch_size=4, pad_batches=True,
                          length_buckets=(16,), pad_value=0)
        out = b([(np.ones(10, np.int64), np.int64(1))])
        ids, label, mask = out
        assert ids.shape == (4, 16)
        np.testing.assert_array_equal(ids[0, 10:], np.zeros(6, np.int64))
        np.testing.assert_array_equal(mask, [True, False, False, False])

    def test_empty_batch_raises(self):
        with pytest.raises(ValueError):
            PaddedBatcher(batch_size=4)([])

    def test_padding_through_worker_processes(self):
        loader = DataLoader(RaggedDataset(), batch_size=4, shuffle=False,
                            num_workers=2, pad_batches=True,
                            length_buckets=BUCKETS)
        shapes = {b[0].shape for b in loader}
        assert shapes == {(4, 16), (4, 32)}

    def test_drop_last_needs_no_padding(self):
        loader = DataLoader(RaggedDataset(), batch_size=4, shuffle=False,
                            drop_last=True, pad_batches=True,
                            length_buckets=BUCKETS)
        batches = list(loader)
        assert len(batches) == 5
        assert all(bool(b[-1].all()) for b in batches)


# -------------------------------------------------- prefetch iterator
class TestPrefetchIterator:
    def test_values_and_order(self):
        it = _PrefetchIterator(iter(range(10)), depth=3)
        assert list(it) == list(range(10))

    def test_overlap_producer_runs_ahead(self):
        """Producer timestamps precede consumer step completion — the
        pipeline actually overlaps production with consumption."""
        produced = {}

        def stamp(x):
            produced[x] = time.perf_counter()
            return x

        it = _PrefetchIterator(iter(range(5)), depth=2, transform=stamp)
        completed = {}
        for x in it:
            time.sleep(0.03)  # simulated device step
            completed[x] = time.perf_counter()
        for n in range(1, 5):
            assert produced[n] < completed[n - 1], (
                f"batch {n} was not produced while batch {n - 1} was "
                f"still being consumed")

    def test_error_delivered_promptly(self):
        """A producer exception surfaces on the NEXT __next__, not after
        the queued batches drain."""

        def gen():
            yield 1
            yield 2
            raise RuntimeError("producer boom")

        it = _PrefetchIterator(gen(), depth=8)
        deadline = time.monotonic() + 5.0
        while it._state.err is None and time.monotonic() < deadline:
            time.sleep(0.01)  # let the producer run to its exception
        with pytest.raises(RuntimeError, match="producer boom"):
            next(it)  # queued 1, 2 must NOT be yielded first
        with pytest.raises(StopIteration):
            next(it)

    def test_error_midstream(self):
        """Items consumed before the failure flow normally; the error
        arrives on the next request after it happens. The gate makes the
        ordering deterministic (no race between consume and fail)."""
        gate = threading.Event()

        def gen():
            yield "ok"
            gate.wait(5.0)
            raise ValueError("later")

        it = _PrefetchIterator(gen(), depth=1)
        assert next(it) == "ok"
        gate.set()
        with pytest.raises(ValueError, match="later"):
            next(it)

    def test_close_unblocks_and_joins(self):
        # infinite producer parked on the bounded queue
        it = _PrefetchIterator(itertools.count(), depth=2)
        assert next(it) == 0
        th = it._thread
        it.close()
        assert not th.is_alive()
        with pytest.raises(StopIteration):
            next(it)
        it.close()  # idempotent

    def test_abandoned_iterator_does_not_leak_thread(self):
        it = _PrefetchIterator(itertools.count(), depth=2)
        next(it)
        th = it._thread
        del it
        gc.collect()
        th.join(timeout=5.0)
        assert not th.is_alive()

    def test_exhaustion_joins_thread(self):
        it = _PrefetchIterator(iter(range(3)), depth=2)
        list(it)
        it._thread.join(timeout=5.0)
        assert not it._thread.is_alive()

    def test_stats_track_stall(self):
        it = _PrefetchIterator(iter(range(4)), depth=2)
        list(it)
        s = it.stats()
        assert s["batches"] == 4
        assert s["consumer_stall_s"] >= 0.0


# -------------------------------------------------- device prefetch
class TestDevicePrefetch:
    def test_values_on_device(self):
        import jax

        batches = [(np.full((2, 3), i, np.float32), np.int64(i))
                   for i in range(4)]
        it = prefetch_to_device(iter(batches), depth=2)
        out = list(it)
        assert len(out) == 4
        for i, (x, y) in enumerate(out):
            assert isinstance(x, jax.Array)
            np.testing.assert_array_equal(np.asarray(x), batches[i][0])
            assert int(y) == i
        it.close()

    def test_sharded_landing(self):
        """With a sharding, batches land in their GSPMD layout directly
        (make_array_from_process_local_data; 8 virtual CPU devices)."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        sh = NamedSharding(mesh, PartitionSpec("dp"))
        batches = [np.arange(16, dtype=np.float32).reshape(8, 2) + i
                   for i in range(3)]
        it = prefetch_to_device(iter(batches), depth=2, sharding=sh)
        out = list(it)
        assert len(out) == 3
        for i, x in enumerate(out):
            np.testing.assert_array_equal(np.asarray(x), batches[i])
            assert x.sharding.is_equivalent_to(sh, x.ndim)

    def test_mesh_spec_spelling(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec

        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        it = prefetch_to_device(iter([np.zeros((8, 2), np.float32)]),
                                mesh=mesh, spec=PartitionSpec("dp"))
        (x,) = list(it)
        assert {d.id for d in x.sharding.device_set} == {
            d.id for d in jax.devices()}

    def test_sharded_landing_clips_spec_for_low_rank_mask(self):
        # (ids[B,S], label[B], mask[B]) under a rank-2 spec: the rank-1
        # riders take the clipped spec instead of crashing
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        sh = NamedSharding(mesh, PartitionSpec("dp", None))
        loader = DataLoader(RaggedDataset(), batch_size=8, shuffle=False,
                            pad_batches=True, length_buckets=(32,))
        it = prefetch_to_device(iter(loader), depth=2, sharding=sh)
        batches = list(it)
        assert len(batches) == 3
        ids, label, mask = batches[-1]
        assert ids.sharding.is_equivalent_to(sh, ids.ndim)
        assert len(mask.shape) == 1 and len(label.shape) == 1
        assert np.asarray(mask).sum() == 6  # 22 = 8+8+6 real rows

    def test_mesh_without_spec_rejected(self):
        # a replicated default would silently diverge on multi-host
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        with pytest.raises(ValueError, match="spec"):
            prefetch_to_device(iter([np.zeros(4)]), mesh=mesh)

    def test_through_dataloader(self):
        loader = DataLoader(RaggedDataset(), batch_size=4, shuffle=False,
                            pad_batches=True, length_buckets=BUCKETS)
        it = prefetch_to_device(iter(loader), depth=2)
        n = 0
        for ids, label, mask in it:
            assert ids.shape[1] in BUCKETS
            n += 1
        assert n == 6


# ------------------------------------------- compile cache + retrace guard
def _make_step():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc(x).mean()

    return TrainStep(M(), pt.optimizer.SGD(learning_rate=0.1))


class TestCompileCache:
    def test_cache_stats_counts_traces_and_hits(self):
        step = _make_step()
        x = np.ones((4, 8), np.float32)
        step(x)
        step(x)
        step(x)
        s = step.cache_stats()
        assert s["compiles"] == 1
        assert s["calls"] == 3
        assert s["cache_hits"] == 2
        assert "float32(4, 8)" in s["last_trace_signature"]

    def test_new_shape_is_new_compile(self):
        step = _make_step()
        step(np.ones((4, 8), np.float32))
        step(np.ones((2, 8), np.float32))
        s = step.cache_stats()
        assert s["compiles"] == 2
        assert len(s["signatures"]) == 2

    def test_retrace_guard_catches_shape_change(self):
        step = _make_step()
        step(np.ones((4, 8), np.float32))  # warmup
        with compile_cache.retrace_guard(max_compiles=0):
            step(np.ones((4, 8), np.float32))  # cached: fine
            with pytest.raises(compile_cache.RetraceError,
                               match="pad/bucket"):
                step(np.ones((3, 8), np.float32))  # injected shape change

    def test_retrace_guard_budget(self):
        step = _make_step()
        with compile_cache.retrace_guard(max_compiles=1):
            step(np.ones((4, 8), np.float32))  # the one budgeted compile

    def test_retrace_guard_warn_mode(self):
        step = _make_step()
        step(np.ones((4, 8), np.float32))
        with pytest.warns(RuntimeWarning, match="retrace_guard"):
            with compile_cache.retrace_guard(max_compiles=0, action="warn"):
                step(np.ones((5, 8), np.float32))

    def test_guard_removed_after_exit(self):
        step = _make_step()
        with compile_cache.retrace_guard(max_compiles=0):
            pass
        step(np.ones((4, 8), np.float32))  # no guard active: fine

    def test_jit_function_stats(self):
        import jax.numpy as jnp

        from paddle_tpu.framework.jit import jit

        @jit
        def f(x):
            return jnp.sum(x * 2)

        f(np.ones(4, np.float32))
        f(np.ones(4, np.float32))
        assert f.cache_stats()["compiles"] == 1
        assert f.cache_stats()["calls"] == 2

    def test_global_stats_aggregate(self):
        step = _make_step()
        step(np.ones((4, 8), np.float32))
        g = compile_cache.cache_stats()
        assert g["compiles"] >= 1
        assert step._cc_name in g["functions"]

    def test_persistent_cache_wiring(self, tmp_path):
        import jax

        old = jax.config.jax_compilation_cache_dir
        try:
            d = compile_cache.enable_persistent_cache(
                str(tmp_path / "xla_cache"))
            assert os.path.isdir(d)
            assert jax.config.jax_compilation_cache_dir == d
            assert compile_cache.persistent_cache_dir() == d
        finally:
            jax.config.update("jax_compilation_cache_dir", old)

    def test_persistent_cache_flags_exist(self):
        flags = pt.get_flags(["FLAGS_persistent_compile_cache",
                              "FLAGS_compile_cache_dir"])
        assert flags["FLAGS_persistent_compile_cache"] is False


# --------------------------------------------- the acceptance fit loop
class TestFitShapeStability:
    def _fit(self, stabilize):
        pt.seed(0)
        from paddle_tpu.hapi import Model

        model = Model(TinyClassifier())
        model.prepare(
            optimizer=pt.optimizer.SGD(learning_rate=0.1),
            loss=lambda logits, label: F.cross_entropy(logits, label))
        model.fit(RaggedDataset(), batch_size=4, epochs=2, verbose=0,
                  shuffle=False, pad_batches=stabilize,
                  length_buckets=BUCKETS if stabilize else None)
        return model._train_step.cache_stats()

    def test_stabilized_compiles_at_most_one_per_bucket(self):
        s = self._fit(stabilize=True)
        assert s["compiles"] <= 1 + len(BUCKETS), s
        assert s["calls"] == 12  # 6 batches x 2 epochs
        assert s["cache_hits"] >= s["calls"] - (1 + len(BUCKETS))

    def test_unstabilized_compiles_once_per_shape(self):
        s = self._fit(stabilize=False)
        # shapes: (4,12), (4,20), (4,28), ragged tail (2,28)
        assert s["compiles"] == 4, s

    def test_fit_with_device_prefetch(self):
        pt.seed(0)
        from paddle_tpu.hapi import Model

        model = Model(TinyClassifier())
        model.prepare(
            optimizer=pt.optimizer.SGD(learning_rate=0.1),
            loss=lambda logits, label: F.cross_entropy(logits, label))
        hist = model.fit(RaggedDataset(), batch_size=4, epochs=1, verbose=0,
                         shuffle=False, pad_batches=True,
                         length_buckets=BUCKETS, prefetch_depth=2)
        assert model._train_step.cache_stats()["compiles"] <= 1 + len(BUCKETS)
        # no leaked prefetch threads
        gc.collect()
        stragglers = [t for t in threading.enumerate()
                      if t is not threading.main_thread() and t.daemon
                      and "Thread-" in t.name and not t.is_alive()]
        assert not stragglers


# ------------------------------------------------------- tool smoke test
def _load_retrace_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "retrace_report", os.path.join(REPO, "tools", "retrace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRetraceReportTool:
    """In-process (a subprocess would spend ~15s just re-importing jax;
    main() is argv-driven either way)."""

    def test_stabilized_within_budget(self, capsys):
        tool = _load_retrace_report()
        rc = tool.main(["--epochs", "1"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "OK:" in out
        assert "trace signature" in out
        assert "train" in out  # per-row kind labels (train/prefill/decode)

    def test_unstabilized_busts_budget(self, capsys):
        tool = _load_retrace_report()
        rc = tool.main(["--epochs", "1", "--no-stabilize", "--budget", "2"])
        assert rc == 1
        assert "FAIL:" in capsys.readouterr().err
