"""Fault-tolerance layer tests: RetryPolicy semantics, deterministic fault
injection through the KV store / RPC / PS client, elastic heartbeat health,
and bounded rpc shutdown.

Everything here is tier-1-safe by construction: seeded plans (no real
randomness), deadline-bounded waits (no unbounded polls), and short
injected delays (no sleep-and-hope synchronisation).
"""
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.launch import KVClient, KVServer
from paddle_tpu.distributed.launch.elastic import ElasticManager
from paddle_tpu.distributed.resilience import (
    CRASH_EXIT, FAULT_PLAN_ENV, FaultPlan, FaultRule, InjectedFault,
    RetryPolicy, Unavailable, fault_point, with_timeout)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poll_until(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"{what} not reached within {timeout}s")


# ------------------------------------------------------------- RetryPolicy
def test_retry_policy_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.01)
    assert policy.call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_policy_exhausted_attempts_reraises_original():
    class MyError(ConnectionError):
        pass

    policy = RetryPolicy(max_attempts=3, base_delay=0.01)
    calls = []
    with pytest.raises(MyError):
        policy.call(lambda: calls.append(1) or (_ for _ in ()).throw(
            MyError("down")))
    assert len(calls) == 3


def test_retry_policy_deadline_raises_timeout_chained():
    policy = RetryPolicy(deadline=0.15, base_delay=0.05)
    with pytest.raises(TimeoutError) as ei:
        policy.call(lambda: (_ for _ in ()).throw(ConnectionError("dead")),
                    what="unit op")
    assert "unit op" in str(ei.value)
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_retry_policy_non_retryable_passes_through():
    policy = RetryPolicy(max_attempts=5, base_delay=0.01,
                         retryable=(ConnectionError,))
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("application error")

    with pytest.raises(ValueError):
        policy.call(bad)
    assert len(calls) == 1  # never retried


def test_retry_policy_requires_a_bound():
    with pytest.raises(ValueError):
        RetryPolicy()  # unbounded loops are forbidden by construction


def test_retry_policy_jitter_deterministic_given_seed():
    a = RetryPolicy(max_attempts=9, base_delay=0.1, jitter=0.5, seed=42)
    b = RetryPolicy(max_attempts=9, base_delay=0.1, jitter=0.5, seed=42)
    sched_a = [d for d, _ in zip(a.delays(), range(8))]
    sched_b = [d for d, _ in zip(b.delays(), range(8))]
    assert sched_a == sched_b
    assert max(sched_a) <= a.max_delay * 1.5  # jitter bounded
    c = RetryPolicy(max_attempts=9, base_delay=0.1, jitter=0.5, seed=43)
    assert sched_a != [d for d, _ in zip(c.delays(), range(8))]


def test_retry_policy_until_polls_none_results():
    state = {"n": 0}

    def poll():
        state["n"] += 1
        return "ready" if state["n"] >= 3 else None

    policy = RetryPolicy(deadline=5.0, base_delay=0.01, multiplier=1.0)
    assert policy.until(poll) == "ready"
    with pytest.raises(TimeoutError):
        RetryPolicy(deadline=0.1, base_delay=0.02).until(lambda: None)


def test_with_timeout():
    assert with_timeout(lambda: 7, timeout=5.0) == 7
    with pytest.raises(TimeoutError, match="slow thing"):
        with_timeout(lambda: time.sleep(10), timeout=0.2, what="slow thing")
    with pytest.raises(KeyError):
        with_timeout(lambda: {}["missing"], timeout=5.0)


# --------------------------------------------------------------- FaultPlan
def test_fault_plan_counted_drops_and_site_matching():
    plan = FaultPlan([FaultRule(site="kv.*", kind="drop", times=2)], seed=1)
    with plan:
        hits = 0
        for _ in range(5):
            try:
                fault_point("kv.get")
            except InjectedFault:
                hits += 1
        fault_point("rpc.connect.w0")  # non-matching site: never raises
    assert hits == 2 and plan.fired[0] == 2
    # outside the with-block the plan is inactive
    fault_point("kv.get")


def test_fault_plan_probabilistic_drops_replay_identically():
    def run(seed):
        plan = FaultPlan([{"site": "x", "kind": "drop", "times": None,
                           "prob": 0.5}], seed=seed)
        out = []
        with plan:
            for _ in range(32):
                try:
                    fault_point("x")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
        return out

    a, b, c = run(7), run(7), run(8)
    assert a == b          # same seed -> identical fault sequence
    assert a != c          # different seed -> different sequence
    assert 0 < sum(a) < 32  # actually probabilistic


def test_fault_plan_partition_window():
    plan = FaultPlan([{"site": "net", "kind": "partition", "after": 2,
                       "times": 3}], seed=0)
    outcomes = []
    with plan:
        for _ in range(8):
            try:
                fault_point("net")
                outcomes.append("ok")
            except InjectedFault:
                outcomes.append("cut")
    assert outcomes == ["ok", "ok", "cut", "cut", "cut", "ok", "ok", "ok"]


def test_fault_plan_slow_kind_seeded_latency(monkeypatch):
    """``slow`` draws its sleep from the rule's seeded RNG in
    [0.5, 1.5) * delay: durations VARY call to call (gray failure, not
    a fixed stall) but replay identically for the same seed. Sleeps are
    RECORDED (time.sleep patched), not wall-clock timed — scheduler
    noise stays out of the assertions."""
    import threading

    from paddle_tpu.distributed import resilience as rz

    main = threading.main_thread()

    def run(seed):
        recorded = []

        def fake_sleep(s):
            # only this test's calls: a stray daemon thread sleeping
            # through the patch window must not pollute the schedule
            if threading.current_thread() is main:
                recorded.append(round(float(s), 9))

        monkeypatch.setattr(rz.time, "sleep", fake_sleep)
        plan = FaultPlan([{"site": "net.x", "kind": "slow",
                           "times": None, "delay": 0.04}], seed=seed)
        with plan:
            for _ in range(6):
                fault_point("net.x")       # never raises, only drags
        monkeypatch.undo()
        assert plan.fired[0] == 6
        return recorded

    a, b, c = run(7), run(7), run(8)
    assert len(a) == 6
    for d in a:
        assert 0.02 <= d < 0.06            # [0.5, 1.5) * delay
    assert max(a) - min(a) > 0.001         # actually varies per call
    assert a == b                          # same seed -> same schedule
    assert a != c                          # different seed -> different


def test_fault_plan_slow_kind_counts_and_site_matching():
    plan = FaultPlan([{"site": "kv.*", "kind": "slow", "times": 2,
                       "delay": 0.03}], seed=1)
    with plan:
        t0 = time.monotonic()
        fault_point("kv.get")
        fault_point("kv.put")
        slowed = time.monotonic() - t0
        t1 = time.monotonic()
        fault_point("kv.get")              # budget spent: full speed
        fault_point("rpc.connect.w0")      # non-matching site
        fast = time.monotonic() - t1
    assert plan.fired[0] == 2
    assert slowed >= 0.03                  # two sleeps of >= 0.015 each
    assert fast < 0.01


def test_fault_plan_env_roundtrip_and_subprocess_inheritance(tmp_path):
    """A plan active in the parent is inherited by subprocesses through
    PT_FAULT_PLAN with identical deterministic behavior."""
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent("""
        from paddle_tpu.distributed.resilience import (
            InjectedFault, fault_point)
        out = []
        for _ in range(4):
            try:
                fault_point("kv.put")
                out.append("ok")
            except InjectedFault:
                out.append("drop")
        print(",".join(out), flush=True)
    """))
    plan = FaultPlan([{"site": "kv.put", "kind": "drop", "times": 2}],
                     seed=5)
    with plan:
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        assert FAULT_PLAN_ENV in env
        out = subprocess.run([sys.executable, str(script)], env=env,
                             capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip() == "drop,drop,ok,ok"


def test_fault_plan_crash_kills_subprocess(tmp_path):
    script = tmp_path / "crash.py"
    script.write_text(textwrap.dedent("""
        from paddle_tpu.distributed.resilience import fault_point
        fault_point("boom")
        print("survived", flush=True)
    """))
    plan = FaultPlan([{"site": "boom", "kind": "crash"}], seed=0)
    with plan:
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, str(script)], env=env,
                             capture_output=True, text=True, timeout=60)
    assert out.returncode == CRASH_EXIT
    assert "survived" not in out.stdout


# ----------------------------------------------------- KV client under fault
def test_kv_client_retries_injected_drops():
    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}",
                      retry=RetryPolicy(max_attempts=4, base_delay=0.02))
        plan = FaultPlan([{"site": "kv.put", "kind": "drop", "times": 2},
                          {"site": "kv.get", "kind": "drop", "times": 1}],
                         seed=3)
        with plan:
            kv.put("k", "v")          # 2 injected drops, then lands
            assert kv.get("k") == "v"  # 1 injected drop, then lands
        assert plan.fired == [2, 1]


def test_kv_client_delay_fault_is_tolerated():
    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}",
                      retry=RetryPolicy(max_attempts=3, base_delay=0.02))
        plan = FaultPlan([{"site": "kv.get", "kind": "delay",
                           "delay": 0.15, "times": 1}], seed=0)
        with plan:
            kv.put("d", "1")
            t0 = time.monotonic()
            assert kv.get("d") == "1"
            assert time.monotonic() - t0 >= 0.15  # the delay really fired
        assert plan.fired[0] == 1


def test_kv_client_drop_beyond_retry_budget_surfaces():
    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}",
                      retry=RetryPolicy(max_attempts=2, base_delay=0.02))
        with FaultPlan([{"site": "kv.get", "kind": "partition",
                         "times": None}], seed=0):
            with pytest.raises(ConnectionError):
                kv.get("anything")


# ------------------------------------------------------------ RPC under fault
def test_rpc_retries_injected_connect_drop():
    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
    try:
        plan = FaultPlan([{"site": "rpc.connect.*", "kind": "drop",
                           "times": 1}], seed=2)
        with plan:
            assert rpc.rpc_sync("solo", int, args=(99,)) == 99
        assert plan.fired[0] == 1  # the drop fired and was retried away
    finally:
        rpc.shutdown()


def test_rpc_shutdown_idempotent():
    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
    rpc.shutdown()
    rpc.shutdown()  # second call: no-op, no error
    rpc.shutdown(timeout=0.5)


DEAD_PEER_WORKER = textwrap.dedent("""
    import os, sys, time
    from paddle_tpu.distributed import rpc

    rank = int(sys.argv[1])
    rpc.init_rpc(name=f"w{rank}", rank=rank, world_size=2,
                 master_endpoint=sys.argv[2])
    if rank == 1:
        os._exit(0)  # dies without shutdown: no barrier key ever appears
    t0 = time.monotonic()
    rpc.shutdown(timeout=3.0)  # must NOT hang on the dead peer
    took = time.monotonic() - t0
    assert took < 20.0, f"shutdown took {took}s"
    print(f"SHUTDOWN_OK {took:.2f}", flush=True)
""")


def test_rpc_shutdown_bounded_with_dead_peer(tmp_path):
    """A peer that dies without reaching the exit barrier must not hang the
    survivor: shutdown abandons the barrier at its deadline and tears down
    locally."""
    script = tmp_path / "w.py"
    script.write_text(DEAD_PEER_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen([sys.executable, str(script), str(r), ep],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    out0, _ = procs[0].communicate(timeout=120)
    procs[1].wait(timeout=10)
    assert procs[0].returncode == 0, out0[-3000:]
    assert "SHUTDOWN_OK" in out0


# ------------------------------------------------------- PS client under fault
def test_ps_client_retries_injected_drop():
    from paddle_tpu.distributed.ps import (PsClient, PsServer,
                                           SparseAccessorConfig)

    server = PsServer(SparseAccessorConfig(embed_dim=4, optimizer="sgd",
                                           learning_rate=1.0, seed=11))
    client = PsClient([("127.0.0.1", server.port)], embed_dim=4,
                      retries=3, retry_delay=0.02)
    try:
        import numpy as np

        keys = np.array([1, 2, 3], np.int64)
        plan = FaultPlan([{"site": "ps.request.*", "kind": "drop",
                           "times": 2}], seed=9)
        with plan:
            rows = client.pull(keys)
        assert rows.shape == (3, 4)
        assert plan.fired[0] == 2
        # beyond the budget the original transport error surfaces
        with FaultPlan([{"site": "ps.request.*", "kind": "partition",
                         "times": None}], seed=0):
            with pytest.raises(ConnectionError):
                client.pull(keys)
    finally:
        client.close()
        server.stop()


# ------------------------------------------------------ elastic heartbeat
def test_elastic_heartbeat_health_and_recovery():
    server = KVServer(0, host="127.0.0.1")
    server.start()
    port = server.port
    mgr = ElasticManager(f"127.0.0.1:{port}", "hjob", "node-x", ttl=1.0)
    try:
        mgr.register()
        assert mgr.is_healthy() and mgr.last_error is None
        # KV store goes away: beats fail, health must flip within ~ttl
        server.stop()
        _poll_until(lambda: not mgr.is_healthy(), timeout=10.0,
                    what="unhealthy after KV loss")
        assert mgr.last_error is not None  # surfaced, not swallowed
        # store returns on the same port: health recovers
        server = KVServer(port, host="127.0.0.1")
        server.start()
        _poll_until(mgr.is_healthy, timeout=10.0,
                    what="healthy after KV recovery")
        assert mgr.last_error is None
    finally:
        mgr.leave()
        try:
            server.stop()
        except Exception:
            pass


def test_elastic_heartbeat_partition_flips_health_then_heals():
    """Satellite: a PARTITION window (contiguous outage, the network
    failure mode a drop count can't model) must flip ``is_healthy()``
    false with ``last_error`` surfaced, and the manager must heal on
    its own the moment the window closes — no restart, no re-register."""
    with KVServer(0, host="127.0.0.1") as server:
        mgr = ElasticManager(f"127.0.0.1:{server.port}", "hjob3", "node-z",
                             ttl=1.0)
        # the window opens AFTER registration (after=1 skips the
        # register put... register doesn't hit the heartbeat site) and
        # outlasts several ticks' retry budgets (2 attempts per tick)
        plan = FaultPlan([{"site": "elastic.heartbeat",
                           "kind": "partition", "times": 8}], seed=4)
        with plan:
            mgr.register()
            assert mgr.is_healthy()          # a beat landed at register
            _poll_until(lambda: not mgr.is_healthy(), timeout=15.0,
                        what="unhealthy inside the partition window")
            assert mgr.last_error is not None
            assert isinstance(mgr.last_error, ConnectionError)
            assert mgr._thread.is_alive()    # surfaced, never fatal
            # window closes after 8 matching calls: health returns
            _poll_until(mgr.is_healthy, timeout=15.0,
                        what="healthy after the partition heals")
            assert mgr.last_error is None
            assert plan.fired[0] == 8
        mgr.leave()


def test_elastic_heartbeat_survives_injected_faults():
    """Counted heartbeat drops: the first tick fails both its attempts
    (surfacing last_error — never a dead thread), the next tick absorbs
    the remaining drop through its retry budget and heals."""
    with KVServer(0, host="127.0.0.1") as server:
        mgr = ElasticManager(f"127.0.0.1:{server.port}", "hjob2", "node-y",
                             ttl=1.0)
        plan = FaultPlan([{"site": "elastic.heartbeat", "kind": "drop",
                           "times": 3}], seed=4)
        with plan:
            mgr.register()
            # tick 1: drop+drop -> tick fails, error recorded, thread lives
            _poll_until(lambda: mgr.last_error is not None, timeout=10.0,
                        what="heartbeat error surfaced")
            assert mgr._thread.is_alive()
            # tick 2: drop+success -> healed, error cleared
            _poll_until(lambda: mgr.last_error is None, timeout=10.0,
                        what="heartbeat recovered")
            assert plan.fired[0] == 3
            assert mgr.is_healthy()
        mgr.leave()
