"""OpTest coverage for the round-2 tensor-API breadth sweep (output parity
vs numpy + gradient checks for the differentiable ones), mirroring the
reference's per-op unittests under ``fluid/tests/unittests/test_*_op.py``."""
import numpy as np
import pytest
import scipy.linalg
import scipy.special

import jax.numpy as jnp

import paddle_tpu.ops as ops
from op_test import check_grad, check_output

RNG = np.random.default_rng(7)


# ----------------------------------------------------------------- math
def test_add_n():
    xs = [RNG.normal(size=(3, 4)).astype(np.float32) for _ in range(3)]
    check_output(ops.add_n, [xs], xs[0] + xs[1] + xs[2])


def test_angle_polar_roundtrip():
    mag = np.abs(RNG.normal(size=8)).astype(np.float32) + 0.1
    ang = RNG.uniform(-3, 3, 8).astype(np.float32)
    z = np.asarray(ops.polar(mag, ang))
    np.testing.assert_allclose(np.asarray(ops.angle(z)), np.angle(z), rtol=1e-5)
    np.testing.assert_allclose(np.abs(z), mag, rtol=1e-5)


def test_sgn_real_and_complex():
    x = np.asarray([-2.0, 0.0, 5.0], np.float32)
    check_output(ops.sgn, [x], np.sign(x))
    z = np.asarray([3 + 4j, 0j], np.complex64)
    got = np.asarray(ops.sgn(z))
    np.testing.assert_allclose(got, [0.6 + 0.8j, 0j], rtol=1e-5)


def test_frexp_ldexp_roundtrip():
    x = RNG.normal(size=16).astype(np.float32) * 100
    m, e = ops.frexp(x)
    np.testing.assert_allclose(np.asarray(ops.ldexp(m, e)), x, rtol=1e-6)


def test_copysign_hypot_signbit():
    x = RNG.normal(size=8).astype(np.float32)
    y = RNG.normal(size=8).astype(np.float32)
    check_output(ops.copysign, [x, y], np.copysign(x, y))
    check_output(ops.hypot, [x, y], np.hypot(x, y))
    check_output(ops.signbit, [x], np.signbit(x))


def test_special_functions():
    x = np.abs(RNG.normal(size=8)).astype(np.float32)
    check_output(ops.sinc, [x], np.sinc(x))
    check_output(ops.i0, [x], scipy.special.i0(x), rtol=1e-4)
    check_output(ops.i1, [x], scipy.special.i1(x), rtol=1e-4)
    y = np.abs(RNG.normal(size=8)).astype(np.float32) + 0.1
    check_output(ops.xlogy, [x, y], scipy.special.xlogy(x, y), rtol=1e-4)
    check_grad(ops.xlogy, [x, y], arg_idx=1)


def test_nan_to_num():
    x = np.asarray([np.nan, np.inf, -np.inf, 1.5], np.float32)
    check_output(ops.nan_to_num, [x], np.nan_to_num(x))
    got = np.asarray(ops.nan_to_num(x, nan=9.0, posinf=1.0, neginf=-1.0))
    np.testing.assert_allclose(got, [9.0, 1.0, -1.0, 1.5])


def test_increment_and_inplace_aliases():
    x = np.asarray([1.0, 2.0], np.float32)
    np.testing.assert_allclose(np.asarray(ops.increment(x, 2.5)), [3.5, 4.5])
    np.testing.assert_allclose(np.asarray(ops.add_(x, x)), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(ops.sqrt_(np.asarray([4.0], np.float32))), [2.0])
    np.testing.assert_allclose(np.asarray(ops.clip_(x, 1.5, 1.8)), [1.5, 1.8])


def test_multiplex():
    a = np.arange(8, dtype=np.float32).reshape(4, 2)
    b = a + 100
    idx = np.asarray([0, 1, 1, 0])
    got = np.asarray(ops.multiplex([a, b], idx))
    expect = np.stack([a[0], b[1], b[2], a[3]])
    np.testing.assert_allclose(got, expect)


def test_logcumsumexp():
    x = RNG.normal(size=(4, 5)).astype(np.float32)
    expect = np.logaddexp.accumulate(x, axis=1)
    check_output(lambda v: ops.logcumsumexp(v, axis=1), [x], expect, rtol=1e-4)
    check_grad(lambda v: ops.logcumsumexp(v, axis=1), [x])


def test_renorm():
    x = RNG.normal(size=(3, 4)).astype(np.float32) * 5
    out = np.asarray(ops.renorm(x, p=2.0, axis=0, max_norm=1.0))
    norms = np.linalg.norm(out.reshape(3, -1), axis=1)
    assert (norms <= 1.0 + 1e-4).all()
    # slices already under the cap are untouched
    small = (x / np.linalg.norm(x.reshape(3, -1), axis=1, keepdims=True)
             .reshape(3, 1) * 0.5)
    np.testing.assert_allclose(
        np.asarray(ops.renorm(small.astype(np.float32), 2.0, 0, 1.0)),
        small, rtol=1e-5)


def test_trapezoid_and_cumulative():
    y = RNG.normal(size=(3, 8)).astype(np.float32)
    x = np.sort(RNG.uniform(0, 10, 8)).astype(np.float32)
    check_output(lambda v: ops.trapezoid(v, dx=0.5), [y],
                 np.trapezoid(y, dx=0.5, axis=-1), rtol=1e-5)
    check_output(lambda v: ops.cumulative_trapezoid(v, x=x), [y],
                 scipy.integrate.cumulative_trapezoid(y, x=x, axis=-1),
                 rtol=1e-4)


def test_rank_shape_broadcast_shape():
    x = np.zeros((2, 3, 4))
    assert int(ops.rank(x)) == 3
    np.testing.assert_array_equal(np.asarray(ops.shape(x)), [2, 3, 4])
    assert ops.broadcast_shape([2, 1, 4], [3, 1]) == [2, 3, 4]


# ---------------------------------------------------------------- linalg
def test_lu_and_unpack_reconstruct():
    a = RNG.normal(size=(5, 5)).astype(np.float32)
    lu_mat, piv = ops.lu(a)
    P, L, U = ops.lu_unpack(lu_mat, piv)
    np.testing.assert_allclose(np.asarray(P) @ np.asarray(L) @ np.asarray(U),
                               a, rtol=1e-4, atol=1e-4)
    # get_infos flavor
    _, _, info = ops.lu(a, get_infos=True)
    assert int(info) == 0


def test_tensordot():
    a = RNG.normal(size=(3, 4, 5)).astype(np.float32)
    b = RNG.normal(size=(4, 5, 6)).astype(np.float32)
    check_output(lambda x, y: ops.tensordot(x, y, axes=2), [a, b],
                 np.tensordot(a, b, axes=2), rtol=1e-4)
    check_grad(lambda x, y: ops.tensordot(x, y, axes=2), [a, b])


def test_cov_corrcoef():
    x = RNG.normal(size=(4, 50)).astype(np.float32)
    check_output(ops.cov, [x], np.cov(x), rtol=1e-4)
    check_output(ops.corrcoef, [x], np.corrcoef(x), rtol=1e-4)


# ----------------------------------------------------------- manipulation
def test_unbind_vsplit_hsplit():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    parts = ops.unbind(x, axis=0)
    assert len(parts) == 4 and parts[2].shape == (6,)
    np.testing.assert_array_equal(np.asarray(parts[2]), x[2])
    vs = ops.vsplit(x, 2)
    np.testing.assert_array_equal(np.asarray(vs[1]), x[2:])
    hs = ops.hsplit(x, 3)
    np.testing.assert_array_equal(np.asarray(hs[0]), x[:, :2])


def test_reverse_crop_diagonal():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    np.testing.assert_array_equal(np.asarray(ops.reverse(x, 1)), x[:, ::-1])
    np.testing.assert_array_equal(
        np.asarray(ops.crop(x, shape=[2, 3], offsets=[1, 2])), x[1:3, 2:5])
    np.testing.assert_array_equal(
        np.asarray(ops.crop(x, shape=[2, -1], offsets=[1, 2])), x[1:3, 2:])
    np.testing.assert_array_equal(np.asarray(ops.diagonal(x, offset=1)),
                                  np.diagonal(x, offset=1))


def test_fill_diagonal_tensor_and_scatter():
    x = np.zeros((4, 4), np.float32)
    y = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)
    got = np.asarray(ops.fill_diagonal_tensor(x, y))
    np.testing.assert_array_equal(np.diagonal(got), y)
    assert got.sum() == y.sum()
    got2 = np.asarray(ops.diagonal_scatter(x, y[:3], offset=1))
    np.testing.assert_array_equal(np.diagonal(got2, offset=1), y[:3])

    base = np.zeros((3, 4), np.float32)
    out = np.asarray(ops.select_scatter(base, np.ones(4, np.float32), 0, 1))
    np.testing.assert_array_equal(out[1], np.ones(4))
    assert out[0].sum() == out[2].sum() == 0

    out = np.asarray(ops.index_fill(base, [0, 2], 0, 7.0))
    assert (out[0] == 7).all() and (out[2] == 7).all() and (out[1] == 0).all()


def test_take_modes():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    np.testing.assert_array_equal(np.asarray(ops.take(x, [0, 5, 11])),
                                  [0, 5, 11])
    np.testing.assert_array_equal(np.asarray(ops.take(x, [13], mode="wrap")),
                                  [1])
    np.testing.assert_array_equal(np.asarray(ops.take(x, [99], mode="raise")),
                                  [11])  # clamped under jit semantics


def test_unfold_as_strided_view():
    x = np.arange(10, dtype=np.float32)
    got = np.asarray(ops.unfold(x, 0, size=4, step=3))
    np.testing.assert_array_equal(got, [[0, 1, 2, 3], [3, 4, 5, 6],
                                        [6, 7, 8, 9]])
    st = np.asarray(ops.as_strided(x, shape=[3, 2], stride=[3, 1], offset=1))
    np.testing.assert_array_equal(st, [[1, 2], [4, 5], [7, 8]])
    v = np.asarray(ops.view(x.reshape(2, 5), [5, 2]))
    assert v.shape == (5, 2)
    bits = np.asarray(ops.view(np.asarray([1.0], np.float32), "int32"))
    assert bits.dtype == np.int32 and bits[0] == 0x3F800000
    assert np.asarray(ops.view_as(x, np.zeros((5, 2)))).shape == (5, 2)


# ------------------------------------------------------- sets / histogram
def test_set_ops():
    x = np.asarray([1, 2, 3, 4], np.int32)
    y = np.asarray([3, 4, 5], np.int32)
    np.testing.assert_array_equal(np.asarray(ops.union1d(x, y)),
                                  [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(np.asarray(ops.intersect1d(x, y)), [3, 4])
    np.testing.assert_array_equal(np.asarray(ops.setdiff1d(x, y)), [1, 2])
    np.testing.assert_array_equal(np.asarray(ops.isin(x, y)),
                                  [False, False, True, True])


def test_digitize_histogramdd_vander():
    x = RNG.uniform(0, 10, 20).astype(np.float32)
    bins = np.asarray([2.0, 5.0, 8.0], np.float32)
    check_output(lambda v: ops.digitize(v, bins), [x], np.digitize(x, bins))
    pts = RNG.normal(size=(100, 2)).astype(np.float32)
    hist, edges = ops.histogramdd(pts, bins=4)
    ref_h, ref_e = np.histogramdd(pts, bins=4)
    np.testing.assert_allclose(np.asarray(hist), ref_h)
    assert len(edges) == 2
    v = np.asarray([1.0, 2.0, 3.0], np.float32)
    check_output(lambda a: ops.vander(a, n=3), [v], np.vander(v, 3))


# ----------------------------------------------------------- predicates
def test_type_predicates():
    assert ops.is_floating_point(np.zeros(2, np.float32))
    assert not ops.is_floating_point(np.zeros(2, np.int32))
    assert ops.is_integer(np.zeros(2, np.int64))
    assert ops.is_complex(np.zeros(2, np.complex64))
    assert not ops.is_complex(np.zeros(2, np.float32))


def test_gaussian_and_printoptions():
    g = np.asarray(ops.gaussian((1000,), mean=2.0, std=0.5, seed=3))
    assert abs(g.mean() - 2.0) < 0.1 and abs(g.std() - 0.5) < 0.1
    ops.set_printoptions(precision=2)
    try:
        assert "0.33" in repr(np.asarray([1 / 3]))
    finally:
        np.set_printoptions(precision=8)


def test_floor_mod_alias():
    x = np.asarray([5.0, -5.0], np.float32)
    check_output(lambda v: ops.floor_mod(v, 3.0), [x], np.mod(x, 3.0))


def test_view_dtype_scales_last_dim():
    """paddle view-dtype semantics: last dim scales by the itemsize ratio
    (NOT jax bitcast's trailing-dim convention)."""
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    narrow = np.asarray(ops.view(x, "float16"))
    assert narrow.shape == (2, 8)
    wide = np.asarray(ops.view(narrow, "float32"))
    assert wide.shape == (2, 4)
    np.testing.assert_array_equal(wide, x)
    with pytest.raises(ValueError, match="divisible"):
        ops.view(np.zeros((2, 3), np.float32), "float64")


def test_gaussian_dtype_forwarded():
    g = ops.gaussian((4,), dtype="float16", seed=1)
    assert jnp.asarray(g).dtype == jnp.float16


def test_r3_manipulation_additions():
    """Round-3 long-tail: unflatten/masked_scatter/slice_scatter/stacks/
    tensor_split/atleast/block_diag/cartesian_prod/diag_embed/combinations."""
    import numpy as np

    import paddle_tpu.ops as ops

    assert ops.unflatten(np.zeros((2, 12)), 1, [3, -1]).shape == (2, 3, 4)
    np.testing.assert_allclose(
        ops.masked_scatter(np.zeros(5), np.array([1, 0, 1, 0, 1], bool),
                           np.array([1., 2., 3.])), [1, 0, 2, 0, 3])
    out = ops.slice_scatter(np.zeros((4, 4)), np.ones((2, 4)),
                            [0], [1], [3], [1])
    np.testing.assert_allclose(np.asarray(out)[:, 0], [0, 1, 1, 0])
    assert ops.column_stack([np.arange(3), np.arange(3)]).shape == (3, 2)
    assert ops.row_stack([np.arange(3), np.arange(3)]).shape == (2, 3)
    parts = ops.tensor_split(np.arange(10), 3)
    assert [p.shape[0] for p in parts] == [4, 3, 3]
    assert ops.atleast_1d(np.float32(3)).shape == (1,)
    assert ops.atleast_2d(np.arange(3)).shape == (1, 3)
    assert ops.atleast_3d(np.arange(3)).shape == (1, 3, 1)
    bd = ops.block_diag([np.ones((2, 2)), 2 * np.ones((1, 3))])
    assert bd.shape == (3, 5) and float(bd[2, 2]) == 2
    cp = ops.cartesian_prod([np.arange(2), np.arange(3)])
    assert cp.shape == (6, 2)
    np.testing.assert_allclose(ops.diag_embed(np.array([1., 2., 3.])),
                               np.diag([1, 2, 3]))
    assert ops.diag_embed(np.ones((2, 3)), offset=1).shape == (2, 4, 4)
    assert ops.combinations(np.arange(4), 2).shape == (6, 2)
    assert ops.combinations(np.arange(3), 2, with_replacement=True).shape \
        == (6, 2)


def test_r3_math_additions():
    import numpy as np
    from scipy import special as ss

    import paddle_tpu.ops as ops

    np.testing.assert_allclose(ops.gammaln(np.array([3.0, 5.5])),
                               ss.gammaln([3.0, 5.5]), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(ops.gammainc(np.array([2.0]), np.array([1.0])),
                               ss.gammainc(2.0, 1.0), rtol=1e-5)
    np.testing.assert_allclose(ops.gammaincc(np.array([2.0]), np.array([1.0])),
                               ss.gammaincc(2.0, 1.0), rtol=1e-5)
    np.testing.assert_allclose(ops.multigammaln(np.array([5.0]), 2),
                               ss.multigammaln(5.0, 2), rtol=1e-4)
    np.testing.assert_allclose(ops.polygamma(np.array([2.0]), 1),
                               ss.polygamma(1, 2.0), rtol=1e-4)
    assert float(ops.nextafter(np.float32(1.0), np.float32(2.0))) > 1.0
    assert bool(ops.isposinf(np.array(np.inf)))
    assert bool(ops.isneginf(np.array(-np.inf)))
    assert bool(ops.isreal(np.array(1.0)))


def test_r3_distance_ops():
    import numpy as np
    from scipy.spatial.distance import cdist as sp_cdist, pdist as sp_pdist

    import paddle_tpu.ops as ops

    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 3)).astype(np.float32)
    y = rng.normal(size=(4, 3)).astype(np.float32)
    np.testing.assert_allclose(ops.cdist(x, y), sp_cdist(x, y), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(ops.cdist(x, y, p=1.0), sp_cdist(x, y, "minkowski", p=1),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ops.pdist(x), sp_pdist(x), rtol=1e-4, atol=1e-5)


def test_r3_eager_inplace_variants():
    import numpy as np
    import pytest

    from paddle_tpu import eager

    t = eager.to_tensor(np.ones((3, 3)))
    assert float(t.fill_(2.0).numpy()[0, 0]) == 2.0
    assert float(t.zero_().numpy().sum()) == 0.0
    t.fill_diagonal_(7.0)
    np.testing.assert_allclose(np.diag(t.numpy()), [7, 7, 7])
    x = eager.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError, match="tape"):
        y.fill_(0.0)


def test_r3_sequence_op_family():
    """Sequence ops over (padded, lengths) pairs — the LoD family restated
    for static shapes (sequence_ops/, SURVEY L4 gap)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu.ops as ops

    seqs = [np.array([[1., 1], [2, 2], [3, 3]]), np.array([[4., 4]])]
    padded, lens = ops.sequence_pad(seqs, pad_value=0.0)
    assert padded.shape == (2, 3, 2)
    np.testing.assert_allclose(np.asarray(lens), [3, 1])
    np.testing.assert_allclose(np.asarray(padded)[1], [[4, 4], [0, 0], [0, 0]])
    # flat + lengths (LoD) form round-trips
    flat = np.concatenate(seqs)
    p2, l2 = ops.sequence_pad(flat, lengths=[3, 1])
    np.testing.assert_allclose(np.asarray(p2), np.asarray(padded))
    back = ops.sequence_unpad(padded, lens)
    np.testing.assert_allclose(np.asarray(back[0]), seqs[0])
    np.testing.assert_allclose(np.asarray(back[1]), seqs[1])

    # pooling flavors ignore padding; all jit-compile
    pool = jax.jit(lambda x, l: ops.sequence_pool(x, l, "mean"))
    np.testing.assert_allclose(np.asarray(pool(padded, lens)),
                               [[2, 2], [4, 4]])
    np.testing.assert_allclose(
        np.asarray(ops.sequence_pool(padded, lens, "max")), [[3, 3], [4, 4]])
    np.testing.assert_allclose(
        np.asarray(ops.sequence_pool(padded, lens, "sqrt")),
        [[6 / np.sqrt(3)] * 2, [4, 4]], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ops.sequence_last_step(padded, lens)), [[3, 3], [4, 4]])
    np.testing.assert_allclose(
        np.asarray(ops.sequence_first_step(padded)), [[1, 1], [4, 4]])

    # masked softmax: padding gets probability 0, valid rows sum to 1
    scores = jnp.asarray([[1., 2, 3], [5, 0, 0]])
    sm = ops.sequence_softmax(scores, jnp.asarray([3, 1]))
    np.testing.assert_allclose(np.asarray(sm).sum(1), [1.0, 1.0], rtol=1e-6)
    assert float(sm[1, 1]) == 0.0 and float(sm[1, 0]) == 1.0

    # reverse flips only the valid prefix
    rev = ops.sequence_reverse(padded, lens)
    np.testing.assert_allclose(np.asarray(rev)[0], [[3, 3], [2, 2], [1, 1]])
    np.testing.assert_allclose(np.asarray(rev)[1], [[4, 4], [0, 0], [0, 0]])

    # expand repeats rows per ref lengths
    ex = ops.sequence_expand(np.array([[1., 1], [2, 2]]), [2, 3])
    assert ex.shape == (5, 2) and float(ex[4, 0]) == 2

    # per-row concat of two padded pairs
    cat, clens = ops.sequence_concat([padded, padded], [lens, lens])
    np.testing.assert_allclose(np.asarray(clens), [6, 2])
    np.testing.assert_allclose(np.asarray(cat)[1][:2], [[4, 4], [4, 4]])


def test_r3_linalg_additions():
    import numpy as np

    import paddle_tpu.ops as ops

    rng = np.random.default_rng(0)
    a = rng.normal(size=(4, 4)).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(ops.inv(a)) @ a, np.eye(4),
                               atol=1e-4)
    b = rng.normal(size=(2, 3, 5)).astype(np.float32)
    assert ops.matrix_transpose(b).shape == (2, 5, 3)
    x = rng.normal(size=(3, 8)).astype(np.float32)
    y = rng.normal(size=(3, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(ops.vecdot(x, y)),
                               (x * y).sum(-1), rtol=1e-5)
    # householder_product reconstructs Q from scipy's compact QR form
    from scipy.linalg import qr as sqr

    m = rng.normal(size=(5, 3)).astype(np.float32)
    (qr_raw, tau), _r = sqr(m, mode="raw")
    q = np.asarray(ops.householder_product(np.asarray(qr_raw), tau))
    q_ref = sqr(m, mode="economic")[0]
    np.testing.assert_allclose(q[:, :3], q_ref, atol=1e-4)
