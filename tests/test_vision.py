"""Vision package tests: transforms math, datasets, model zoo forward
shapes + trainability."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.vision import datasets, models, transforms as T


# -------------------------------------------------------------- transforms
def test_resize_shapes_and_short_side():
    img = np.zeros((40, 80, 3), np.uint8)
    assert T.resize(img, (20, 30)).shape == (20, 30, 3)
    out = T.resize(img, 20)  # short side -> 20, aspect kept
    assert out.shape == (20, 40, 3)
    assert T.resize(img, 20, "nearest").shape == (20, 40, 3)


def test_resize_bilinear_values():
    img = np.asarray([[0.0, 10.0], [20.0, 30.0]], np.float32)[:, :, None]
    out = T.resize(img, (4, 4))[:, :, 0]
    # corners approach original corner values; center is the mean
    assert out[0, 0] == 0.0 and out[-1, -1] == 30.0
    np.testing.assert_allclose(out.mean(), 15.0, atol=0.5)


def test_crops_flips_pad():
    img = np.arange(24, dtype=np.uint8).reshape(4, 6, 1)
    c = T.center_crop(img, 2)
    np.testing.assert_array_equal(c[:, :, 0], [[8, 9], [14, 15]])
    np.testing.assert_array_equal(T.hflip(img)[:, :, 0], img[:, ::-1, 0])
    np.testing.assert_array_equal(T.vflip(img)[:, :, 0], img[::-1, :, 0])
    p = T.pad(img, 1, fill=7)
    assert p.shape == (6, 8, 1) and p[0, 0, 0] == 7
    rc = T.RandomCrop(3)(img)
    assert rc.shape == (3, 3, 1)


def test_to_tensor_normalize_compose():
    img = np.full((4, 4, 3), 255, np.uint8)
    pipeline = T.Compose([T.ToTensor(),
                          T.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])])
    out = pipeline(img)
    assert out.shape == (3, 4, 4)
    np.testing.assert_allclose(out, 1.0, rtol=1e-6)


# --------------------------------------------------------------- datasets
def test_fake_data_deterministic():
    ds = datasets.FakeData(num_samples=8, image_shape=(1, 8, 8), seed=3)
    img0, y0 = ds[0]
    img0b, y0b = ds[0]
    np.testing.assert_array_equal(img0, img0b)
    assert y0 == y0b and len(ds) == 8


def test_mnist_idx_reader(tmp_path):
    import gzip
    import struct

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (5, 28, 28), np.uint8)
    labels = rng.integers(0, 10, 5, np.uint8)
    ip = str(tmp_path / "img.gz")
    lp = str(tmp_path / "lab.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 5, 28, 28) + images.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 5) + labels.tobytes())
    ds = datasets.MNIST(ip, lp)
    assert len(ds) == 5
    img, y = ds[2]
    np.testing.assert_array_equal(img, images[2])
    assert y == labels[2]
    # corrupt magic -> clear error
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 1234, 5, 28, 28))
    with pytest.raises(ValueError, match="magic"):
        datasets.MNIST(ip, lp)


def test_cifar_tarball_reader(tmp_path):
    import pickle
    import tarfile

    rng = np.random.default_rng(1)
    data = {b"data": rng.integers(0, 256, (10, 3072), np.uint8),
            b"labels": list(rng.integers(0, 10, 10))}
    tar_path = str(tmp_path / "cifar.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tar:
        import io

        blob = pickle.dumps(data)
        info = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
        info.size = len(blob)
        tar.addfile(info, io.BytesIO(blob))
    ds = datasets.Cifar10(tar_path, mode="train")
    assert len(ds) == 10
    img, y = ds[0]
    assert img.shape == (32, 32, 3)
    with pytest.raises(FileNotFoundError):
        datasets.Cifar10(str(tmp_path / "nope.tar.gz"))


def test_dataset_folder_npy(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            np.save(str(d / f"{i}.npy"),
                    np.zeros((8, 8, 3), np.uint8))
    ds = datasets.DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"] and len(ds) == 4
    img, y = ds[3]
    assert img.shape == (8, 8, 3) and y == 1
    flat = datasets.ImageFolder(str(tmp_path))
    assert len(flat) == 4


# ------------------------------------------------------------------ models
@pytest.mark.parametrize("ctor,in_shape,n_out", [
    (lambda: models.LeNet(num_classes=10), (2, 1, 28, 28), 10),
    (lambda: models.vgg11(num_classes=7), (1, 3, 32, 32), 7),
    (lambda: models.mobilenet_v1(scale=0.25, num_classes=5), (1, 3, 32, 32), 5),
    # mobilenet_v2's inverted-residual stack compiles ~13s (tier-1
    # report) — slow-tier alongside v3; v1 keeps the family's tier-1
    # coverage
    pytest.param(lambda: models.mobilenet_v2(scale=0.25, num_classes=5),
                 (1, 3, 32, 32), 5, marks=pytest.mark.slow),
    # mobilenet_v3's hard-swish/SE stack compiles ~27s on the CI box —
    # slow-tier (v1 keeps the family's tier-1 coverage)
    pytest.param(lambda: models.mobilenet_v3_small(scale=0.5, num_classes=5),
                 (1, 3, 64, 64), 5, marks=pytest.mark.slow),
])
def test_model_forward_shapes(ctor, in_shape, n_out):
    pt.seed(0)
    model = ctor()
    model.eval()
    x = jnp.asarray(np.random.default_rng(0).normal(size=in_shape),
                    jnp.float32)
    out = model(x)
    assert out.shape == (in_shape[0], n_out)
    assert bool(jnp.isfinite(out).all())


def test_lenet_trains():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.optimizer import Adam

    pt.seed(0)
    model = models.LeNet(num_classes=4)
    step = pt.TrainStep(model, Adam(learning_rate=1e-3),
                        loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)
    losses = [float(step((x, y))) for _ in range(15)]
    assert losses[-1] < losses[0]


def test_random_crop_pad_if_needed_narrow_image():
    img = np.zeros((40, 20, 3), np.uint8)
    out = T.RandomCrop(32, pad_if_needed=True)(img)
    assert out.shape == (32, 32, 3)


# ------------------------------------------------------------ pp-yoloe
@pytest.mark.slow   # ~13s forward+decode compile (tier-1 report)
def test_ppyoloe_forward_and_decode():
    from paddle_tpu.models.ppyoloe import ppyoloe_tiny

    pt.seed(0)
    m = ppyoloe_tiny(num_classes=4)
    m.eval()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3, 64, 64)),
                    jnp.float32)
    cls_logits, reg_logits, pts, strs = m(x)
    A = (8 * 8) + (4 * 4) + (2 * 2)  # strides 8/16/32 on 64x64
    assert cls_logits.shape == (2, A, 4)
    assert reg_logits.shape == (2, A, 4 * (m.reg_max + 1))
    assert pts.shape == (A, 2) and strs.shape == (A,)
    boxes = m._decode(reg_logits, pts, strs)
    assert boxes.shape == (2, A, 4)
    assert np.isfinite(np.asarray(boxes)).all()
    dets, num = m.predict(x, conf_thresh=0.0, keep_top_k=5)
    assert np.asarray(dets).shape[1] == 6 and len(np.asarray(num)) == 2


def test_ppyoloe_repconv_fuse_parity():
    from paddle_tpu.models.ppyoloe import RepConv

    pt.seed(1)
    blk = RepConv(6, 6)
    blk.eval()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 6, 16, 16)),
                    jnp.float32)
    before = np.asarray(blk(x))
    blk.fuse()
    after = np.asarray(blk(x))
    np.testing.assert_allclose(before, after, rtol=1e-4, atol=1e-5)


@pytest.mark.slow   # ~16s TAL assigner compile (tier-1 report)
def test_ppyoloe_tal_assigns_inside_anchors():
    from paddle_tpu.models.ppyoloe import ppyoloe_tiny

    pt.seed(2)
    m = ppyoloe_tiny(num_classes=4)
    m.eval()
    x = jnp.zeros((1, 3, 64, 64), jnp.float32)
    cls_logits, reg_logits, pts, strs = m(x)
    cls_scores = jax.nn.sigmoid(cls_logits)
    pred_boxes = m._decode(reg_logits, pts, strs)
    gt_boxes = jnp.asarray([[[8.0, 8.0, 40.0, 40.0]]])
    gt_labels = jnp.asarray([[2]])
    fg, tgt_lbl, tgt_box, tgt_q = m._assign(cls_scores, pred_boxes, pts,
                                            gt_boxes, gt_labels)
    fg = np.asarray(fg)[0]
    assert fg.sum() >= 1
    p = np.asarray(pts)
    inside = ((p[:, 0] > 8) & (p[:, 0] < 40)
              & (p[:, 1] > 8) & (p[:, 1] < 40))
    assert (fg <= inside).all()  # only inside-gt anchors assigned
    assert set(np.asarray(tgt_lbl)[0][fg].tolist()) == {2}
    # padded gt rows assign nothing
    fg2, _, _, _ = m._assign(cls_scores, pred_boxes, pts,
                             jnp.asarray([[[-1.0, -1, -1, -1]]]),
                             jnp.asarray([[-1]]))
    assert np.asarray(fg2).sum() == 0


@pytest.mark.slow   # ~16s train-step compile; forward/decode/TAL/fuse
def test_ppyoloe_trains():    # parity keep the head covered in tier-1
    from paddle_tpu.models.ppyoloe import ppyoloe_tiny
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    pt.seed(3)
    m = ppyoloe_tiny(num_classes=4)
    rng = np.random.default_rng(3)
    imgs = jnp.asarray(rng.normal(size=(2, 3, 64, 64)), jnp.float32)
    gt_boxes = jnp.asarray([[[8, 8, 40, 40], [-1, -1, -1, -1]],
                            [[24, 16, 56, 48], [4, 4, 20, 20]]], jnp.float32)
    gt_labels = jnp.asarray([[1, -1], [0, 3]], jnp.int32)
    params = param_state(m)
    buffers = buffer_state(m)

    def loss_fn(p):
        # functional_call drives forward; loss() is the training entry, so
        # route it through the call protocol by temporary forward swap
        out, _ = functional_call(_LossShim(m), p, buffers,
                                 imgs, gt_boxes, gt_labels)
        return out

    vg = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(8):
        l, g = vg(params)
        params = jax.tree.map(lambda a, b: a - 2e-3 * b, params, g)
        losses.append(float(l))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


class _LossShim:
    """Adapter: exposes a PPYOLOE's loss() as the callable/stateful surface
    functional_call drives."""

    def __init__(self, model):
        self._m = model

    def __call__(self, *a, **k):
        return self._m.loss(*a, **k)

    def __getattr__(self, name):
        return getattr(self._m, name)


# --------------------------------------------- r4 model-zoo completion
def test_vision_models_zero_missing_vs_reference():
    import re

    import paddle_tpu.vision.models as M

    try:
        s = open('/root/reference/python/paddle/vision/models/'
                 '__init__.py').read()
    except OSError:
        pytest.skip("reference tree not mounted")
    ref = set(re.findall(r"'(\w+)'",
                         re.search(r"__all__ = \[(.*?)\]", s, re.S).group(1)))
    missing = sorted(x for x in ref if x not in set(dir(M)))
    assert missing == [], missing


@pytest.mark.parametrize("factory", [
    # the four deepest stems compile 20-45s EACH on the CI box (top of
    # the tier-1 slowest-tests report) — slow-tier; the remaining four
    # keep every code path (plain conv, fire, channel-shuffle, grouped)
    # inside the budget
    "alexnet", "squeezenet1_1", "shufflenet_v2_x0_25", "resnext50_64x4d",
    pytest.param("densenet121", marks=pytest.mark.slow),
    pytest.param("googlenet", marks=pytest.mark.slow),
    pytest.param("inception_v3", marks=pytest.mark.slow),
    pytest.param("mobilenet_v3_large", marks=pytest.mark.slow),
])
def test_new_vision_family_forward(factory):
    import paddle_tpu.vision.models as M

    pt.seed(0)
    m = getattr(M, factory)(num_classes=7)
    m.eval()
    # inception's stem downsamples ~32x; 64px inputs collapse to nothing
    size = 96 if factory == "inception_v3" else 64
    x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 3, size, size)),
                    jnp.float32)
    out = m(x)
    assert out.shape == (1, 7), (factory, out.shape)
    assert np.isfinite(np.asarray(out)).all(), factory


def test_transforms_long_tail():
    import paddle_tpu.vision.transforms as T

    rng = np.random.default_rng(0)
    img = rng.uniform(0, 255, (16, 20, 3)).astype(np.uint8)
    # identity factors are exact (within integer rounding)
    np.testing.assert_allclose(T.adjust_brightness(img, 1.0), img)
    np.testing.assert_allclose(T.adjust_contrast(img, 1.0).astype(float),
                               img.astype(float), atol=1)
    np.testing.assert_allclose(T.adjust_saturation(img, 1.0).astype(float),
                               img.astype(float), atol=1)
    np.testing.assert_allclose(T.adjust_hue(img, 0.0).astype(float),
                               img.astype(float), atol=2)
    with pytest.raises(ValueError):
        T.adjust_hue(img, 0.9)
    g = T.to_grayscale(img)
    assert g.shape == (16, 20, 1)
    # warps: zero rotation / identity perspective preserve the image
    np.testing.assert_allclose(T.rotate(img, 0.0).astype(float),
                               img.astype(float), atol=1)
    pts = [(0, 0), (19, 0), (19, 15), (0, 15)]
    np.testing.assert_allclose(
        T.perspective(img, pts, pts).astype(float), img.astype(float),
        atol=1)
    e = T.erase(img, 2, 3, 4, 5, 0)
    assert (e[2:6, 3:8] == 0).all() and (e[0, 0] == img[0, 0]).all()
    # transform classes run and keep shapes
    for cls in [T.ColorJitter(0.2, 0.2, 0.2, 0.1),
                T.RandomAffine(10, translate=(0.1, 0.1)),
                T.RandomErasing(prob=1.0), T.RandomPerspective(prob=1.0),
                T.RandomRotation(15)]:
        assert cls(img).shape == img.shape
    assert T.RandomResizedCrop(8)(img).shape[:2] == (8, 8)
    assert T.Grayscale(3)(img).shape == img.shape
    # BaseTransform keyed dispatch: non-image entries pass through
    class ImgOnly(T.BaseTransform):
        def __init__(self):
            super().__init__(keys=("image", "label"))

        def _apply_image(self, x):
            return x + 1

        def _apply_label(self, y):
            return y

    out_img, out_lbl = ImgOnly()((np.zeros((2, 2, 1), np.uint8), 7))
    assert out_img.sum() == 4 and out_lbl == 7


def test_vision_ops_layer_wrappers():
    from paddle_tpu.vision.ops import RoIAlign

    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 4, 8, 8)),
                    jnp.float32)
    boxes = jnp.asarray([[0.0, 0.0, 7.0, 7.0]])
    layer = RoIAlign(output_size=2)
    out = layer(x, boxes, jnp.asarray([1], jnp.int32))
    assert np.asarray(out).shape == (1, 4, 2, 2)


def test_fashion_mnist_and_voc(tmp_path):
    import gzip
    import struct

    from paddle_tpu.vision.datasets import FashionMNIST, VOC2012

    # synthesize a 3-image IDX pair (FashionMNIST = MNIST wire format)
    imgs = np.arange(3 * 4 * 4, dtype=np.uint8).reshape(3, 4, 4)
    ip = tmp_path / "im.gz"
    lp = tmp_path / "lb.gz"
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 3, 4, 4) + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 3) + bytes([0, 1, 2]))
    ds = FashionMNIST(str(ip), str(lp))
    assert len(ds) == 3
    img, lbl = ds[1]
    assert img.shape == (4, 4) and lbl == 1

    # VOC layout with one sample
    from PIL import Image

    root = tmp_path / "VOC2012"
    (root / "ImageSets" / "Segmentation").mkdir(parents=True)
    (root / "JPEGImages").mkdir()
    (root / "SegmentationClass").mkdir()
    (root / "ImageSets" / "Segmentation" / "train.txt").write_text("a\n")
    Image.fromarray(np.zeros((6, 6, 3), np.uint8)).save(
        root / "JPEGImages" / "a.jpg")
    Image.fromarray(np.ones((6, 6), np.uint8)).save(
        root / "SegmentationClass" / "a.png")
    voc = VOC2012(str(root), mode="train")
    img, seg = voc[0]
    assert img.shape == (6, 6, 3) and seg.shape == (6, 6)
